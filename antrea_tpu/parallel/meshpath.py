"""MeshDatapath: the full stateful datapath promoted onto the device mesh.

PR 8 left exactly one sharded component: the stateless classifier
(parallel/mesh.py).  This module is the multichip serving engine — a
`TpuflowDatapath` whose EVERY plane runs against the 2-D (data × rule)
mesh, so a pod slice serves as one fleet of switches (PAPER.md L0: the
datapath OVS implements in C, scaled the way the reference scales by
adding nodes):

  stateful fast path   conntrack/affinity tables carry the leading (D,)
                       axis `parallel/mesh.py` always anticipated; each
                       data shard owns a PRIVATE slots slice.  A
                       deterministic, direction-symmetric 5-tuple hash
                       (`mesh.shard_of_tuples`) routes every packet to
                       its home shard on the traffic path, so a flow's
                       entries live in exactly one shard's table and
                       direct-mapped-cache semantics stay sound per
                       shard.  Hash-skew overflow lanes "spill" to other
                       shards with `no_commit` set (never caching
                       foreign) and then take a bounded HOME-ROUTED
                       retry dispatch (`_spill_retry`), so skew never
                       strands an established flow on provisional
                       verdicts.
  sharded slow path    one bounded miss queue PER data replica
                       (`MeshSlowPath`); a drain pops one block per
                       replica, classifies all of them in ONE sharded
                       dispatch (each replica's chunk in its own batch
                       slice), and publishes via a MESH-WIDE epoch swap:
                       a single epoch counter plus the state pytree
                       published by the one dispatch means every replica
                       flips generation atomically.  Re-missed flows
                       re-enqueue idempotently (the PR 6 lost-update
                       guard, now spanning shards: the deterministic
                       endpoint hash makes the re-classification commit
                       the identical entry in the identical home shard).
  replica-gated commit the canary classifies its probe set on EVERY data
                       replica (probes tiled over the data axis inside
                       shard_map, so each replica's own devices walk
                       their own table copies) and datapath/commit.py
                       diffs each replica against the scalar Oracle —
                       ONE replica's mismatch vetoes the bundle and the
                       rollback restores the (D,)-sharded snapshot, i.e.
                       ALL replicas, keeping the PR 4/5 self-healing
                       ladder provable under sharding.
  striped audit        the PR 5 audit cursor runs over the GLOBAL slot
                       space D*S, striped g -> (replica g % D, local
                       slot g // D), so every scheduler-budgeted window
                       advances coverage on all replicas simultaneously;
                       the tensor scrub folds the sharded tensors
                       logically (one digest covers every shard).
  rule-axis capacity   `_place_rules` pads + shards the incidence words
                       over ``rule`` (ops/match.to_device word_multiple)
                       for the whole pipeline — fast path, drains,
                       canary and audit fresh-walks all combine hits via
                       `lax.pmin` over the rule axis, so capacity scales
                       past 100k rules exactly as the HBM math in
                       parallel/mesh.py promises.

Everything else — commit/audit/maintenance plane state machines, the
membership delta bookkeeping, persistence, metrics counting — is
INHERITED from TpuflowDatapath: the planes were built plane-owner-
agnostic (PR 7's one-scheduler refactor was precisely for this port).

Round-8 additions (the PR 9 follow-ups + the elastic plane):
  * the engine now serves the FULL per-packet walk — SpoofGuard -> policy/
    service pipeline -> L2/L3 forward -> Output — through one sharded
    dispatch (`_mesh_step_full_fn`): forwarding is stateless per-packet
    and shards trivially over data with replicated topology tables, so
    `install_topology` works exactly like single-chip;
  * incremental group deltas take the O(delta) slot path on the mesh:
    the per-slot rule masks upload sharded on the same word axis as the
    incidence they patch (`_place_delta`), so pod churn never forces a
    recompile here either — overflow and named-port folds still recompile
    (canary-gated), as on single-chip;
  * the data axis RESIZES under live traffic (parallel/reshard.py):
    `reshard_begin(D')` builds the target mesh and serves dual-topology
    (in-flight batches resolve against the old affinity generation while
    a budgeted maintenance task migrates flow-cache rows to their target
    ring homes); a replica-resolved canary + a migrated-row audit certify
    the target before `shard_of_tuples` flips generation in one
    mesh-wide epoch swap, and a veto aborts back to the old mesh.
    TENANT worlds ride every resize (grow, shrink, failover evacuation):
    each world migrates under its own `_world_ctx` with per-world dirty
    tracking and a per-world certified cutover — one world's canary veto
    latches only that world on its old topology (`_TENANT_WORLD_FIELDS`
    carries `_mesh`/`_n_data`/`_topo_gen`, so a latched world SERVES on
    its own mesh; `tenant_reshard_resync` re-homes it later), while the
    fleet and every certified sibling flip.

Known mesh limits (documented, test-pinned):
  * v4-only (like the async slow path); dual_stack raises ConfigError.
  * overlap_commits/autotune_drain are single-chip knobs (the mesh drain
    is already one fused sharded dispatch per replica set).
  * DNAT'd service reply legs can land off-shard and re-classify — the
    ECMP-asymmetry analog; see the README multichip failure-model row.
"""

from __future__ import annotations

import time
from functools import lru_cache, partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..compiler.topology import FWD_TUNNEL
from ..config import ConfigError
from ..datapath.interface import StepResult
from ..datapath.maintenance import MaintenanceTask
from ..datapath.slowpath import ADMIT_DROP, MissQueue, SlowPathEngine
from ..datapath.tpuflow import TpuflowDatapath, _rid
from ..observability.telemetry import classify_regime
from ..models import forwarding as fw
from ..models import pipeline as pl
from ..ops import hashing
from ..ops import match as m
from ..ops.match import to_host
from ..packet import PacketBatch
from ..utils import ip as iputil
from .mesh import (
    DATA,
    RULE,
    _drs_specs,
    _fwd_specs,
    _pmin_rule,
    _shard_map,
    _state_specs,
    _svc_specs,
    make_mesh,
    shard_of_tuples,
    shard_state,
)
from .failover import FailoverPlane
from .reshard import ReshardPlane, resync_world


# --------------------------------------------------------------------------
# Cached compiled kernels.  Keyed by (Mesh, PipelineMeta/StaticMeta) — both
# hashable — so every MeshDatapath on the same mesh with the same shapes
# shares ONE jitted program per variant (the jit-identity discipline the
# single-chip engine gets from module-level pipeline_step): installs that
# keep rule shapes re-use the compiled step, and the drain has one program
# per chunk rung, never a recompile storm.  The caches are BOUNDED: rule
# shapes change across bundle churn (each distinct meta.match retains its
# compiled executables), so an unbounded cache would grow host+device
# memory for the agent's whole lifetime; eviction just re-traces on the
# next use of a long-unseen shape.
# --------------------------------------------------------------------------

@lru_cache(maxsize=32)
def _mesh_step_fn(mesh, meta: pl.PipelineMeta):
    """The sharded stateful step: fast path, drains and sync slow path
    are all this one builder at different metas (phases / miss_chunk /
    drain_reclaim), exactly like the single-chip pipeline_step."""
    lane = P(DATA)

    def body(state, drs, dsvc, src_f, dst_f, proto, sport, dport, now,
             gen, valid, no_commit, flags, lens):
        local = jax.tree.map(lambda x: x[0], state)
        local, out = pl._pipeline_step(
            local, drs, dsvc, src_f, dst_f, proto, sport, dport, now, gen,
            meta=meta, hit_combine=_pmin_rule, valid=valid,
            no_commit=no_commit, flags=flags,
            lens=lens if meta.count_flow_stats else None,
        )
        # scalar per shard -> (D,) vector of per-data-shard counts (the
        # prune keys exist iff the meta carries a prune budget)
        for k in ("n_miss", "n_evict", "n_reclaim", "n_prune_skips",
                  "n_prune_fb", "prune_cand_hist",
                  "tel_probe_hit", "tel_probe_stale", "tel_probe_miss",
                  "tel_dma_hb", "tel_chance_bumps"):
            if k in out:
                out[k] = out[k][None]
        return jax.tree.map(lambda x: x[None], local), out

    return jax.jit(_shard_map(
        body,
        mesh=mesh,
        in_specs=(_state_specs(),
                  _drs_specs(agg=meta.match.prune_budget > 0),
                  _svc_specs(),
                  lane, lane, lane, lane, lane, P(), P(),
                  lane, lane, lane, lane),
        out_specs=(_state_specs(), P(DATA)),
    ))


@lru_cache(maxsize=32)
def _mesh_step_full_fn(mesh, meta: pl.PipelineMeta, has_arp: bool):
    """The sharded FULL per-packet walk (SpoofGuard/ARP -> policy/service
    pipeline -> L2/L3 forward -> Output, models/forwarding
    ._pipeline_step_full) — the mesh twin of the single-chip step().
    Forwarding is stateless per-packet, so it shards trivially over the
    data axis with replicated topology tables; the rule axis participates
    only in the classification pmin, exactly as in the policy-only step.
    `has_arp` keys the variant the way the single-chip step's conditional
    ARP lane does — pure-IP batches keep the no-ARP program."""
    lane = P(DATA)

    def body(state, drs, dsvc, dft, src_f, dst_f, proto, sport, dport,
             in_port, now, gen, flags, arp_op, valid, no_commit, lens,
             prune_excl):
        local = jax.tree.map(lambda x: x[0], state)
        local, out = fw._pipeline_step_full(
            local, drs, dsvc, dft, src_f, dst_f, proto, sport, dport,
            in_port, now, gen, flags,
            arp_op if has_arp else None,
            lens if meta.count_flow_stats else None,
            meta=meta, hit_combine=_pmin_rule, valid=valid,
            no_commit=no_commit, prune_exclude=prune_excl,
        )
        # scalar per shard -> (D,) vector of per-data-shard counts (the
        # prune keys exist iff the meta carries a prune budget)
        for k in ("n_miss", "n_evict", "n_reclaim", "n_prune_skips",
                  "n_prune_fb", "prune_cand_hist",
                  "tel_probe_hit", "tel_probe_stale", "tel_probe_miss",
                  "tel_dma_hb", "tel_chance_bumps"):
            if k in out:
                out[k] = out[k][None]
        return jax.tree.map(lambda x: x[None], local), out

    return jax.jit(_shard_map(
        body,
        mesh=mesh,
        in_specs=(_state_specs(),
                  _drs_specs(agg=meta.match.prune_budget > 0),
                  _svc_specs(), _fwd_specs(),
                  lane, lane, lane, lane, lane, lane, P(), P(),
                  lane, lane, lane, lane, lane, lane),
        out_specs=(_state_specs(), P(DATA)),
    ))


@lru_cache(maxsize=8)
def _mesh_canary_fn(mesh, match_meta, fused):
    """Per-replica canary classify: probes tiled over the data axis, each
    replica's devices walking their own physical table copies; verdicts
    land (D * n,) and reshape to (D, n) for datapath/commit.py's
    replica-resolved diff.  One XLA compile per rule-table SHAPE (probes
    are padded to a fixed lane count by the commit plane, so repeated
    installs of same-shaped bundles share the program).  `fused` carries
    the instance's serving-consumer discipline — a fused engine's probes
    must certify the pallas consumer the step kernel uses, not the
    shadow XLA path (the fused consumer is shard-aware, so it composes
    with the pmin seam like the serving dispatch)."""
    def body(drs, src_f, dst_f, proto, dport):
        return m.classify_batch(
            drs, src_f, dst_f, proto, dport, meta=match_meta,
            hit_combine=_pmin_rule, fused=fused,
        )["code"]

    return jax.jit(_shard_map(
        body,
        mesh=mesh,
        in_specs=(_drs_specs(agg=match_meta.prune_budget > 0),
                  P(DATA), P(DATA), P(DATA), P(DATA)),
        out_specs=P(DATA),
    ))


# The vmapped maintenance/census helpers are keyed by at most the
# timeout tuple (reconfigured rarely, but each distinct value retains a
# compiled executable) — bounded like the step/canary caches above so a
# timeout-churning control plane can never grow device memory without
# limit (the analysis `bounded-cache` pass gates this).

@lru_cache(maxsize=8)
def _vmapped_maintain(timeouts):
    return jax.jit(jax.vmap(partial(pl._maintain_scan, timeouts=timeouts),
                            in_axes=(0, None, None)))


@lru_cache(maxsize=1)
def _vmapped_revalidate():
    return jax.jit(jax.vmap(pl._revalidate_scan, in_axes=(0, None)))


@lru_cache(maxsize=8)
def _vmapped_age(timeouts):
    return jax.jit(jax.vmap(partial(pl._age_scan, timeouts=timeouts),
                            in_axes=(0, None)))


@lru_cache(maxsize=1)
def _vmapped_cache_stats():
    return jax.jit(jax.vmap(pl._cache_stats))


def _shard_placement(shard: np.ndarray, n_data: int):
    """Batch lanes -> mesh slots under the shard-affinity hash.

    Every packet whose home shard has free capacity (B / D lanes per
    shard) lands in its home slice; hash-skew overflow packets SPILL into
    other shards' free slots and are flagged (the caller classifies them
    with no_commit, so a foreign shard never caches a stray flow).

    -> (perm, inv, spill): perm maps slot -> packet index, inv maps
    packet -> slot, spill flags slots holding an off-home packet."""
    B = shard.size
    C = B // n_data
    order = np.argsort(shard, kind="stable")
    counts = np.bincount(shard, minlength=n_data)
    bounds = np.concatenate([[0], np.cumsum(counts)])
    perm = np.empty(B, np.int64)
    spill = np.zeros(B, bool)
    leftovers, free = [], []
    for r in range(n_data):
        seg = order[bounds[r]:bounds[r + 1]]
        home = min(seg.size, C)
        perm[r * C:r * C + home] = seg[:home]
        if home < C:
            free.append(np.arange(r * C + home, (r + 1) * C))
        if seg.size > home:
            leftovers.append(seg[home:])
    if leftovers:
        lv = np.concatenate(leftovers)
        fs = np.concatenate(free)[:lv.size]  # conservation: |free| == |left|
        perm[fs] = lv
        spill[fs] = True
    inv = np.empty(B, np.int64)
    inv[perm] = np.arange(B)
    return perm, inv, spill


class _MeshQueueView:
    """Aggregate read surface over the per-replica miss queues, so the
    shared Datapath plumbing (dump_miss_queue, trace overlay, stats)
    keeps its single-queue contract.  `base` carries the cumulative
    counters of a PREVIOUS queue generation across a reshard cutover
    (the queue set is rebuilt at the new replica width; the meters must
    not reset or double-count the re-route pops)."""

    def __init__(self, queues: list[MissQueue], base: Optional[dict] = None):
        self.queues = queues
        self._base = base or {"admitted_total": 0, "overflows_total": 0,
                              "drained_total": 0}

    @property
    def depth(self) -> int:
        return sum(q.depth for q in self.queues)

    @property
    def capacity(self) -> int:
        return sum(q.capacity for q in self.queues)

    @property
    def admitted_total(self) -> int:
        return self._base["admitted_total"] + sum(
            q.admitted_total for q in self.queues)

    @property
    def overflows_total(self) -> int:
        return self._base["overflows_total"] + sum(
            q.overflows_total for q in self.queues)

    @property
    def drained_total(self) -> int:
        return self._base["drained_total"] + sum(
            q.drained_total for q in self.queues)

    def dump(self) -> list[dict]:
        return [row for q in self.queues for row in q.dump()]

    def contains(self, *tup) -> bool:
        return any(q.contains(*tup) for q in self.queues)


class MeshSlowPath(SlowPathEngine):
    """Per-replica miss queues + mesh-wide epoch swap.

    One engine, D bounded queues (miss_queue_slots is PER REPLICA).  The
    epoch plane stays a single counter: a drain classifies one popped
    block per replica in ONE sharded dispatch and `_publish` bumps that
    one counter — the mesh-wide swap.  Atomicity is by construction: the
    next lookup on ANY replica consumes the state pytree that dispatch
    published, never a mix."""

    def __init__(self, owner, n_data: int, *, capacity: int,
                 admission: str, drain_batch: int,
                 source_rate=None, source_burst=None):
        # capacity=1 seed: the base queue is immediately replaced by the
        # per-replica set below (its buffer would be dead weight).
        super().__init__(owner, capacity=1, admission=admission,
                         drain_batch=drain_batch, source_rate=source_rate,
                         source_burst=source_burst)
        self.n_data = int(n_data)
        self._q_capacity = int(capacity)  # per-replica; resize() reuses it
        self.queues = [MissQueue(capacity) for _ in range(self.n_data)]
        self.queue = _MeshQueueView(self.queues)

    # -- admission: route by home shard --------------------------------------

    def admit(self, cols: dict, miss_mask, now: int, shard=None):
        if shard is None:
            raise ValueError(
                "mesh admission requires the batch's shard assignment "
                "(shard_of_tuples ids)")
        self._seen_now = max(self._seen_now, int(now))
        if self._published_at == 0:
            self._published_at = int(now)
        mask = np.asarray(miss_mask, bool)
        # Per-source rate limiting is replica-independent (the bucket
        # keys on the source prefix, not the home shard): ONE batch-wide
        # pass ahead of the per-replica early-drop ramps, mirroring the
        # single-chip admission order.
        base = mask
        mask = self._source_limit(cols, mask, now)
        if self.deny_sink is not None and mask.sum() < base.sum():
            self.deny_sink(cols, base & ~mask, "source-limit", now)
        # admission="drop": the hash coin is replica-independent — one
        # batch-wide compute, thresholded per replica below (each
        # replica's OWN queue depth drives its early-drop ramp; capacity
        # is per-replica, so is the floor).
        coin = (self._drop_coin(cols, mask.shape[0])
                if self.admission == ADMIT_DROP and mask.any() else None)
        admitted = dropped = 0
        for r in range(self.n_data):
            mr = mask & (np.asarray(shard) == r)
            if not mr.any():
                continue
            mr0 = mr
            mr, _shed = self._early_drop(cols, mr, self.queues[r], coin=coin)
            if self.deny_sink is not None and _shed:
                self.deny_sink(cols, mr0 & ~mr, "early-drop", now)
            if not mr.any():
                continue
            a, d = self.queues[r].admit(cols, mr, self.epoch, int(now))
            admitted += a
            dropped += d
            if d:
                self._emit("queue-overflow", replica=int(r), dropped=int(d),
                           depth=int(self.queues[r].depth), at=int(now))
                if self.deny_sink is not None:
                    over = np.zeros(mr.shape, bool)
                    over[np.nonzero(mr)[0][a:]] = True
                    self.deny_sink(cols, over, "queue-overflow", now)
        return admitted, dropped

    # -- epoch plane: the mesh-wide swap -------------------------------------

    def _publish(self, now: int) -> None:
        self.epoch += 1
        self._published_at = int(now)
        self._seen_now = max(self._seen_now, int(now))
        self._emit("mesh-epoch-swap", epoch=int(self.epoch),
                   replicas=int(self.n_data), at=int(now))

    # -- drain: one block per replica, one sharded dispatch ------------------

    def begin_drain(self, now: int, n: Optional[int] = None) -> bool:
        if self._inflight is not None:
            raise RuntimeError("a drain batch is already in flight")
        # The popped chunk rides the in-flight record: an explicit n >
        # drain_batch must size the drain dispatch's per-replica lane
        # slices too, or one replica's rows would overflow into the
        # next replica's slice (and its foreign cache).
        chunk = int(n) if n is not None else self.drain_batch
        blocks = [q.pop(chunk) for q in self.queues]
        if all(b is None for b in blocks):
            return False
        self._inflight = (blocks, chunk, self.epoch,
                          int(self.owner.generation))
        self._seen_now = max(self._seen_now, int(now))
        self._emit("drain-begin",
                   n=sum(len(b["src_ip"]) for b in blocks if b is not None),
                   replicas=sum(b is not None for b in blocks),
                   epoch=int(self.epoch), gen=int(self.owner.generation))
        return True

    def finish_drain(self, now: int) -> dict:
        if self._inflight is None:
            raise RuntimeError("no drain batch in flight")
        blocks, chunk, _epoch0, gen0 = self._inflight
        self._inflight = None
        k = sum(len(b["src_ip"]) for b in blocks if b is not None)
        stale = int(self.owner.generation) != gen0
        if stale:
            self.stale_reclassified_total += k
        self.owner._drain_classify(blocks, int(now), chunk=chunk)
        self.drains_total += 1
        self.drain_hist.observe(k)
        self._emit("drain-finish", drained=k,
                   stale_reclassified=k if stale else 0, deferred=0)
        self._publish(now)
        return {"drained": k, "stale_reclassified": k if stale else 0}

    # -- elastic resharding: re-home the queue set ---------------------------

    def resize(self, n_data: int, home_fn, now: int) -> tuple[int, int]:
        """Rebuild the per-replica queue set at a new data-axis width and
        re-home every queued miss under the new topology map (the flip
        half of the reshard cutover, parallel/reshard.py) -> (requeued,
        dropped).  Rows move VERBATIM (epoch/enq_ts preserved — these are
        re-routes, not re-admissions, so admitted_total is untouched);
        the previous generation's cumulative meters carry over through
        the view's base.  A shrink can overflow the smaller aggregate
        capacity: overflow rows tail-drop with accounting, the ordinary
        bounded-queue contract (the flow re-admits on its next miss)."""
        base = {"admitted_total": self.queue.admitted_total,
                "overflows_total": self.queue.overflows_total,
                "drained_total": self.queue.drained_total}
        blocks = [q.pop(q.depth) for q in self.queues]
        self.n_data = int(n_data)
        self.queues = [MissQueue(self._q_capacity)
                       for _ in range(self.n_data)]
        self.queue = _MeshQueueView(self.queues, base)
        requeued = dropped = 0
        for b in blocks:
            if b is None:
                continue
            home = np.asarray(home_fn(b))
            for r in range(self.n_data):
                idx = np.nonzero(home == r)[0]
                if idx.size == 0:
                    continue
                t, d = self.queues[r].requeue(b, idx)
                requeued += t
                dropped += d
        if dropped:
            self._emit("queue-overflow", dropped=int(dropped),
                       depth=int(self.queue.depth), at=int(now))
        return requeued, dropped

    def evacuate_replica(self, dead: int, home_fn, now: int
                         ) -> tuple[int, int]:
        """Requeue a quarantined replica's queued misses VERBATIM onto
        the survivor queues (parallel/failover.py quarantine): same
        re-route-not-re-admission contract as resize(), but the queue
        set itself survives — only the dead replica's rows move, homed
        by the survivor-ring map.  Overflow rows tail-drop with
        accounting (the flow re-admits on its next miss) -> (requeued,
        dropped)."""
        q = self.queues[dead]
        block = q.pop(q.depth)
        if block is None:
            return 0, 0
        home = np.asarray(home_fn(block))
        requeued = dropped = 0
        for r in range(self.n_data):
            if r == dead:
                continue
            idx = np.nonzero(home == r)[0]
            if idx.size == 0:
                continue
            t, d = self.queues[r].requeue(block, idx)
            requeued += t
            dropped += d
        if dropped:
            self._emit("queue-overflow", dropped=int(dropped),
                       depth=int(self.queue.depth), at=int(now))
        return requeued, dropped

    def stats(self) -> dict:
        s = super().stats()
        s["replicas"] = self.n_data
        s["replica_depths"] = [q.depth for q in self.queues]
        return s


class MeshDatapath(TpuflowDatapath):
    """TpuflowDatapath served SPMD over a (data × rule) mesh.

    Same Datapath surface, same planes, same knobs — minus the
    single-chip-only ones (module docstring).  `miss_queue_slots` is
    per-replica; `flow_slots`/`aff_slots` are per-replica table sizes
    (global capacity = D × slots, which is what `cache_stats`/
    `audit_stats` report)."""

    # The mesh engine's per-world swap set: the single-chip members plus
    # the TOPOLOGY slice — a world serves on its OWN mesh at its own
    # width/generation (the per-world topology latch of
    # parallel/reshard.py), so _mesh/_n_data/_topo_gen/
    # _replica_audit_entries/_fo_mask must swap with it.  Pure literal:
    # the analysis tenant + reshard passes parse it dependency-free.
    _TENANT_WORLD_FIELDS = (
        "_ps", "_cps", "_drs", "_meta", "_meta_step", "_state", "_gen",
        "_has_named_ports", "_n_deltas", "_delta_host", "_name_gids",
        "_gid_ident", "_group_members", "_static_blocks", "_member_meta",
        "_stats_in", "_stats_out", "_bytes_in", "_bytes_out",
        "_default_allow", "_default_deny", "_evictions", "_reclaims",
        "_state_mutations", "_pipe_kw", "_persist_dirty",
        "_mesh", "_n_data", "_topo_gen", "_replica_audit_entries",
        "_fo_mask",
    )

    def __init__(self, ps=None, services=None, *, mesh=None, n_data: int = 2,
                 n_rule: int = 1, devices=None, reshard_budget: int = 256,
                 failover: bool = False, failover_knobs=None, **kw):
        if kw.get("dual_stack"):
            raise ConfigError(
                "the mesh datapath is v4-only (like the async slow path); "
                "dual-stack nodes keep the single-chip engine")
        if kw.get("overlap_commits") or kw.get("autotune_drain"):
            raise ConfigError(
                "overlap_commits/autotune_drain are single-chip knobs: the "
                "mesh drain is already one fused sharded dispatch per "
                "replica set")
        if int(reshard_budget) <= 0:
            raise ConfigError(
                f"reshard_budget must be positive (rows per maintenance "
                f"tick), got {reshard_budget}")
        self._mesh = mesh if mesh is not None else make_mesh(
            n_data, n_rule, devices)
        self._n_data = int(self._mesh.shape[DATA])
        self._n_rule = int(self._mesh.shape[RULE])
        self._replica_audit_entries = [0] * self._n_data
        self._spill_lanes_total = 0
        self._spill_retried_total = 0
        # Elastic resharding plane (parallel/reshard.py): the affinity
        # topology generation (0 = the boot dense map; every resized
        # topology elects on the consistent ring), the in-flight plane,
        # and the cumulative meters that outlive individual planes.
        self._reshard_budget = int(reshard_budget)
        self._topo_gen = 0
        self._reshard = None
        self._reshard_canary = None  # (mesh, drs, match_meta, D) redirect
        self._reshard_cutovers = 0
        self._reshard_aborts = 0
        self._reshard_migrated_total = 0
        self._reshard_catchup_total = 0
        self._reshard_requeued_total = 0
        self._reshard_resident_rows = 0
        self._last_reshard_span = None
        self._reshard_tenant_rows_total = 0
        self._reshard_tenant_vetoes = 0
        # Chaos hook (arm_reshard_faults): (FaultPlan, site prefix) for
        # the per-tenant forced-canary-veto sites.
        self._reshard_faults = None
        # Per-world survivor-mask latch (parallel/failover.mask_shard's
        # world branch): (dead old-topology index, survivor width,
        # survivor generation).  A WORLD field — _world_ctx swaps it —
        # and always None on the default world (the fleet mask covers
        # it).
        self._fo_mask = None
        # Replica-loss failover plane (parallel/failover.py): None when
        # disabled — every traffic-path touch is gated on the field, so
        # the disabled engine's step HLO is bit-identical.
        self._failover = None
        super().__init__(ps, services, **kw)
        if failover:
            self._failover = FailoverPlane(self, **(failover_knobs or {}))
            self._maintenance.register(MaintenanceTask(
                "replica-health", self._maint_replica_health,
                budget=max(self._failover.probe_count * self._n_data, 1),
                priority=4, shed_when_degraded=False))

    # -- placement hooks (the whole tensor estate lands on the mesh) ---------

    def _init_pipeline_state(self, flow_slots: int, aff_slots: int):
        return shard_state(pl.init_state(flow_slots, aff_slots), self._mesh)

    def _pin_state(self, state: pl.PipelineState) -> pl.PipelineState:
        """Re-assert the (D,)-sharded placement after host-orchestrated
        transforms (vmap scans, audit writebacks) — a no-op transfer when
        the sharding already matches."""
        return jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(self._mesh, s)),
            state, _state_specs())

    def _place_rules_on(self, mesh, cps):
        """Host build + rung padding + sharded placement onto `mesh`.
        `_place_rules` calls it at the serving mesh; the reshard plane
        calls it at the TARGET mesh to re-home a tenant world's
        rung-packed rule window (parallel/reshard._ensure_world_rules),
        so rung-shared shapes — and their XLA executables — survive a
        resize."""
        host, meta = to_host(cps, word_multiple=self._n_rule,
                             delta_slots=self._delta_slots,
                             prune_budget=self._prune_budget)
        # Tenant worlds: entry-axis rung padding between host build and
        # sharded placement (datapath/tenancy._pad_tables — no-op on the
        # default world), composing with the word_multiple padding above
        # so tenant shapes stay rung-determined ON the mesh too.
        drs = self._pad_tables(host)
        # The fused consumer must interpret iff the MESH's backend is CPU
        # (the default platform can differ — virtual-CPU mesh on a TPU
        # host), mirroring mesh.shard_rule_set.
        meta = meta._replace(
            fused_interpret=(mesh.devices.flat[0].platform == "cpu"))
        drs = jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            drs, _drs_specs(agg=self._prune_budget > 0))
        return drs, meta

    def _place_rules(self, cps):
        return self._place_rules_on(self._mesh, cps)

    def _place_services(self, dsvc: pl.DeviceServiceTables):
        repl = NamedSharding(self._mesh, P())
        self._shared_mesh = self._mesh  # where the shared tables live
        self._shared_remap = None
        return jax.tree.map(lambda x: jax.device_put(x, repl), dsvc)

    def _place_forwarding(self, dft):
        # Forwarding tables are the small, read-mostly side (one node's
        # pods + routes): replicated whole, like the service tables.
        repl = NamedSharding(self._mesh, P())
        self._shared_mesh = self._mesh
        self._shared_remap = None
        return jax.tree.map(lambda x: jax.device_put(x, repl), dft)

    def _shared_tables(self):
        """(dsvc, dft) placed on the SERVING mesh.  The live copies sit
        on the fleet mesh; a tenant world latched behind a resize (the
        per-world topology latch) serves on its own old mesh, so the
        replicated tables re-place there on first use — cached until
        the fleet tables or the serving mesh change.  The default path
        returns the live copies untouched (HLO pin)."""
        if self._mesh is getattr(self, "_shared_mesh", self._mesh):
            return self._dsvc, self._dft
        hit = self._shared_remap
        if (hit is not None and hit[0] is self._mesh
                and hit[1] is self._dsvc and hit[2] is self._dft):
            return hit[3], hit[4]
        repl = NamedSharding(self._mesh, P())
        dsvc = jax.tree.map(lambda x: jax.device_put(x, repl), self._dsvc)
        dft = jax.tree.map(lambda x: jax.device_put(x, repl), self._dft)
        self._shared_remap = (self._mesh, self._dsvc, self._dft, dsvc, dft)
        return dsvc, dft

    def _audit_dsvc(self):
        return self._shared_tables()[0]

    def _place_delta(self, dt):
        # The O(delta) slot path works unchanged on the mesh: the host
        # mirror's per-slot rule masks are built at the PADDED word width
        # (the match meta's w_in/w_out reflect to_device's word_multiple
        # padding), so each append re-places the whole small table with
        # the word axis sharded exactly like the incidence it patches —
        # pod churn never forces a recompile here either.
        return jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(self._mesh, s)),
            dt, _drs_specs().ip_delta)

    # -- tenancy hooks (datapath/tenancy.TenantedDatapath) -------------------

    def _tenant_init_world(self, spec, ps) -> None:
        super()._tenant_init_world(spec, ps)
        # A fresh world is fleet-aligned (its export carries the live
        # _mesh/_n_data/_topo_gen as-is) but must own its OWN audit-entry
        # list and mask latch — exporting the engine's list object would
        # alias every world's counters to the fleet's.
        self._replica_audit_entries = [0] * int(self._n_data)
        self._fo_mask = None

    def _make_slowpath(self, *, capacity, admission, drain_batch,
                       source_rate=None, source_burst=None,
                       **_single_chip_knobs):
        # autotune/overlap were rejected as ConfigError in __init__, so
        # the ignored kwargs here are always their inert defaults.
        return MeshSlowPath(self, self._n_data, capacity=capacity,
                            admission=admission, drain_batch=drain_batch,
                            source_rate=source_rate,
                            source_burst=source_burst)

    # -- unsupported single-chip surfaces ------------------------------------

    def profile(self, batch, fresh=None, **kw) -> dict:
        raise NotImplementedError(
            "profile() is a single-chip surface; the multichip regime is "
            "measured by bench.py's multichip section")

    # -- the sharded step ----------------------------------------------------

    def _step(self, batch: PacketBatch, now: int, valid=None) -> StepResult:
        D = self._n_data
        B = batch.size
        if B % D:
            raise ValueError(
                f"batch size {B} is not divisible by the data-axis size {D}")
        # Serving-batcher padding mask (canonical sizes are pow2 >= D, so
        # divisibility holds): padded lanes join the kernel's per-lane
        # validity in PERMUTED order and are excluded from the home-routed
        # spill retry below — a padding lane never caches anywhere.
        ext = None if valid is None else np.asarray(valid, bool)
        self._v6_lanes(batch)  # v4-only guard (dual_stack is always False)
        lens = np.maximum(batch.lens(), 0)
        flags = np.asarray(batch.flags()).astype(np.int32)
        in_ports = np.asarray(batch.in_ports()).astype(np.int32)
        has_arp = batch.arp_op is not None
        arp = (np.asarray(batch.arp_ops()).astype(np.int32) if has_arp
               else np.zeros(B, np.int32))
        shard = shard_of_tuples(batch.src_ip, batch.dst_ip, batch.proto,
                                batch.src_port, batch.dst_port, D,
                                self._topo_gen, tenant=self._tenant_id())
        # Replica-loss failover (parallel/failover.py): lanes homed on a
        # quarantined replica re-home HOST-SIDE onto the survivor ring —
        # the step HLO is untouched (bit-identical with the plane off).
        fo = self._failover
        fo_masked = None
        if fo is not None:
            shard, fo_masked = fo.mask_shard(
                batch.src_ip, batch.dst_ip, batch.proto, batch.src_port,
                batch.dst_port, shard, tenant=self._tenant_id())
        perm, inv, spill = _shard_placement(shard, D)
        src = batch.src_ip[perm].astype(np.uint32)
        dst = batch.dst_ip[perm].astype(np.uint32)
        proto = batch.proto[perm].astype(np.int32)
        sport = batch.src_port[perm].astype(np.int32)
        dport = batch.dst_port[perm].astype(np.int32)
        pflags = flags[perm]
        # The fused walk derives the mcast/teardown commit gating and the
        # SpoofGuard/ARP/IGMP validity masks itself (models/forwarding);
        # the engine contributes only the spill rule — an off-home lane
        # classifies but never caches in a foreign shard.
        stepf = _mesh_step_full_fn(self._mesh, self._meta_step, has_arp)
        dsvc, dft = self._shared_tables()
        t0 = time.perf_counter() if fo is not None else 0.0
        state, out = stepf(
            self._state, self._drs, dsvc, dft,
            iputil.flip_u32(src), iputil.flip_u32(dst), proto, sport, dport,
            in_ports[perm], jnp.int32(now), jnp.int32(self._gen),
            pflags, arp[perm],
            np.ones(B, bool) if ext is None else ext[perm], spill,
            lens[perm].astype(np.int32), spill,
        )
        self._state = state
        self._state_mutations += 1
        o = {k: np.asarray(v) for k, v in out.items()}
        if fo is not None:
            # Dispatch-liveness deadline: a stalled sharded dispatch (the
            # arrays above force materialization) is a wedge symptom.
            fo.note_dispatch(time.perf_counter() - t0, now)
        o.pop("n_miss")
        self._evictions += int(o.pop("n_evict").sum())
        self._reclaims += int(o.pop("n_reclaim").sum())
        # Spilled lanes are EXCLUDED from this dispatch's prune evidence
        # (prune_exclude=spill above): their foreign-shard walk is not
        # the serving walk, and the home-routed retry below accounts
        # them instead — each lane feeds the PruneAutotuner band exactly
        # once, from the walk production actually serves (round 8; the
        # PR 10 dedupe kept the foreign evidence instead).
        self._prune_account(o)
        for k in ("n_prune_skips", "n_prune_fb", "prune_cand_hist"):
            o.pop(k, None)
        # Telemetry counters ride (D,) per-replica — pop them before the
        # per-LANE reindex below.  Spilled lanes are excluded from this
        # dispatch's counters too (same prune_exclude=spill mask): their
        # serving probe is the home-routed retry's, which accounts them
        # (each lane's probe is metered exactly once, from the walk that
        # serves it).
        tel_o = {k: o.pop(k) for k in tuple(o) if k.startswith("tel_")}
        o = {k: v[inv] for k, v in o.items()}  # back to packet order
        spilled = perm[np.nonzero(
            spill if ext is None else spill & ext[perm])[0]]  # off-home
        if spilled.size:
            o = self._spill_retry(batch, o, spilled, shard, flags, in_ports,
                                  arp, has_arp, lens, now)
        # Recomputed from the MERGED per-lane mask: a retried lane's miss
        # image is its home-shard one, not the foreign always-miss.
        n_miss = int(o["miss"].sum())
        if fo_masked is not None:
            # The evacuation re-miss burst: dead-resident flows pay one
            # re-miss each on their survivor home (bounded, metered).
            fo.note_remiss(np.count_nonzero((o["miss"] != 0)[fo_masked]))
        # Dirty-row tracking for an in-flight resize (parallel/reshard):
        # every lane's home (replica, slot) may be refreshed/committed/
        # torn down by this step after its migration window — record it
        # so the cutover catch-up sweeps the touched set, not O(slots).
        if self._reshard is not None:
            self._note_reshard_touched(
                shard, batch.src_ip, batch.dst_ip, batch.proto,
                batch.src_port, batch.dst_port,
                committed=o.get("committed"), dnat_f=o.get("dnat_ip_f"),
                dnat_port=o.get("dnat_port"))
        pending = None
        if self._async:
            pending = o["miss"]
            # Route each admitted miss to its HOME replica's queue — a
            # spilled lane's drain then classifies and commits it on the
            # shard that owns it.  Tenant worlds: quota-clamped admission
            # + the tenant id column (datapath/tenancy — no-ops on the
            # default world).  The queue set is SHARED at the FLEET
            # width: a LATCHED world computes homes at its own width, and
            # MeshSlowPath.admit silently never admits ids >= n_data —
            # clamp onto the fleet's queues (the queue index is transport
            # only; the drain re-splits per tenant and re-lays rows out
            # on the world's own topology at classify time, so no
            # verdict ever sees this index).
            sp_n = self._slowpath.n_data
            admitted, _dropped = self._slowpath.admit(
                self._queue_cols(batch, batch.flags(), lens,
                                 tenant=self._tenant_id()),
                self._tenant_admit_mask(pending != 0), now,
                shard=shard if sp_n == D else shard % sp_n)
            self._tenant_note_admitted(admitted, _dropped)
        if self._telemetry is not None:
            # Engine/tenant scopes classify from the MERGED per-lane miss
            # image (a retried lane's miss is its home-shard one); each
            # replica additionally classifies from its own home lanes, so
            # a single cold shard reads cold even when the mesh-wide
            # regime is steady.
            self._telemetry_account({**tel_o, "n_miss": n_miss}, B)
            miss_rep = np.bincount(shard[o["miss"] != 0], minlength=D)
            cnt_rep = np.bincount(shard, minlength=D)
            for d in range(D):
                self._telemetry.note_regime(
                    f"replica{d}",
                    classify_regime(int(cnt_rep[d]), int(miss_rep[d])))
        in_ids = self._cps.ingress.rule_ids
        out_ids = self._cps.egress.rule_ids
        self._count_metrics(o, in_ids, out_ids, lens, pending=pending)
        if self._deny is not None:
            self._deny_verdicts(batch, o["code"], pending, now)
        unflip = iputil.unflip_u32_array
        return StepResult(
            code=o["code"],
            est=o["est"],
            pending=pending,
            reply=o["reply"],
            reject_kind=o["reject_kind"],
            snat=o["snat"],
            dsr=o["dsr"],
            svc_idx=o["svc_idx"],
            dnat_ip=unflip(o["dnat_ip_f"]),
            dnat_port=o["dnat_port"],
            ingress_rule=[_rid(in_ids, i) for i in o["ingress_rule"]],
            egress_rule=[_rid(out_ids, i) for i in o["egress_rule"]],
            committed=o["committed"],
            n_miss=n_miss,
            spoofed=o["spoofed"],
            punt=o["punt"],
            mcast_idx=o["mcast_idx"],
            l7_redirect=o["l7_redirect"],
            fwd_kind=o["fwd_kind"],
            out_port=o["out_port"],
            # peer_f is zeroed for non-deliverable lanes in the kernel; the
            # (kind==TUNNEL & deliverable) gate avoids un-flipping that 0.
            peer_ip=np.where(
                (o["fwd_kind"] == FWD_TUNNEL) & (o["out_port"] != -1),
                unflip(o["peer_f"]), 0,
            ).astype(np.uint32),
            dec_ttl=o["dec_ttl"],
            tc_act=o["tc_act"],
            tc_port=o["tc_port"],
        )

    def _spill_retry(self, batch: PacketBatch, o: dict, spilled: np.ndarray,
                     shard: np.ndarray, flags: np.ndarray,
                     in_ports: np.ndarray, arp: np.ndarray, has_arp: bool,
                     lens: np.ndarray, now: int) -> dict:
        """Second, bounded, HOME-ROUTED dispatch for hash-skew overflow.

        Spilled lanes' main-dispatch image is a foreign-shard walk: they
        can never see their home cache entry, so without this pass an
        established flow caught in skew would serve provisional verdicts
        forever (fatal under admission="hold": true-ALLOW traffic reads
        as DROP).  Here each replica gets ITS OWN spilled lanes — home
        placement by construction — padded to a power-of-two rung so the
        compile-variant count stays O(log(B/D)).  Per-shard overflow
        beyond one full home slice (B/D lanes; the all-flows-one-shard
        pathology) keeps the documented provisional-spill semantics
        rather than cascading dispatches.  Merges the retried lanes'
        outputs into `o` (packet order) and returns it."""
        D = self._n_data
        C = batch.size // D
        by_shard = [spilled[shard[spilled] == r] for r in range(D)]
        m = max(x.size for x in by_shard)
        rung = min(C, max(16, 1 << (m - 1).bit_length()))
        take = [x[:rung] for x in by_shard]
        Bm = D * rung
        idx = np.zeros(Bm, np.int64)
        valid = np.zeros(Bm, bool)
        for r, x in enumerate(take):
            idx[r * rung:r * rung + x.size] = x
            valid[r * rung:r * rung + x.size] = True
        src = batch.src_ip[idx].astype(np.uint32)
        dst = batch.dst_ip[idx].astype(np.uint32)
        proto = batch.proto[idx].astype(np.int32)
        rflags = flags[idx]
        stepf = _mesh_step_full_fn(self._mesh, self._meta_step, has_arp)
        dsvc, dft = self._shared_tables()
        state, out = stepf(
            self._state, self._drs, dsvc, dft,
            iputil.flip_u32(src), iputil.flip_u32(dst), proto,
            batch.src_port[idx].astype(np.int32),
            batch.dst_port[idx].astype(np.int32),
            in_ports[idx], jnp.int32(now), jnp.int32(self._gen),
            rflags, arp[idx], valid, np.zeros(idx.size, bool),
            lens[idx].astype(np.int32), ~valid,
        )
        self._state = state
        self._state_mutations += 1
        o2 = {k: np.asarray(v) for k, v in out.items()}
        self._evictions += int(o2.pop("n_evict").sum())
        self._reclaims += int(o2.pop("n_reclaim").sum())
        o2.pop("n_miss")
        # The retry owns the retried lanes' prune evidence (the main
        # dispatch excluded them via prune_exclude=spill): each lane is
        # metered exactly once, from its HOME (serving) walk — counting
        # both walks would double a retried lane's evidence and skew the
        # PruneAutotuner band toward the foreign always-miss shape
        # (regression-pinned by the skew-batch case in
        # tests/test_match_fused.py).  Padding lanes are excluded via
        # prune_exclude=~valid above.
        self._prune_account(o2)
        for k in ("n_prune_skips", "n_prune_fb", "prune_cand_hist"):
            o2.pop(k, None)
        if self._telemetry is not None:
            # The retry owns the retried lanes' PROBE counters too (the
            # main dispatch masked them out, same as the prune evidence);
            # padding lanes ride excluded via prune_exclude=~valid.
            self._telemetry.account(o2)
        sel = np.nonzero(valid)[0]
        pkts = idx[sel]
        for k in o:
            o[k][pkts] = o2[k][sel]
        self._spill_lanes_total += int(spilled.size)
        self._spill_retried_total += int(sel.size)
        return o

    # -- sharded slow-path callbacks -----------------------------------------

    def _drain_classify(self, blocks: list, now: int,
                        chunk: Optional[int] = None):
        """Classify one popped block PER REPLICA in a single sharded
        drain dispatch (each replica's chunk is its slice of the batch
        axis) and publish the new (D,)-sharded cache state — the commit
        half of the mesh-wide epoch swap.  Padding lanes ride masked out
        via `valid`; all lanes are home lanes (admission routed them), so
        there is no spill term here.  `chunk` is the pop size the engine
        pinned at begin_drain (an explicit begin_drain(n) may exceed
        drain_batch; each replica's lane slice must be that wide).

        Tenant rows (datapath/tenancy): blocks carrying tenant ids
        partition per tenant and each tenant's per-replica sub-blocks
        classify inside its world — zero cost without tenant worlds."""
        split = self._tenant_drain_split_blocks(blocks)
        if split is not None:
            return self._tenant_drain_dispatch_blocks(split, now, chunk)
        t0 = time.perf_counter()
        sp = self._slowpath
        chunk = int(chunk) if chunk is not None else sp.drain_batch
        D = self._n_data
        Bd = D * chunk
        valid = np.zeros(Bd, bool)

        def col(name, dtype=np.int32):
            out = np.zeros(Bd, dtype)
            for r, b in enumerate(blocks):
                if b is None:
                    continue
                k = len(b["src_ip"])
                out[r * chunk:r * chunk + k] = (
                    np.asarray(b[name])[:k].astype(dtype))
            return out

        for r, b in enumerate(blocks):
            if b is not None:
                valid[r * chunk:r * chunk + len(b["src_ip"])] = True
        src = col("src_ip", np.uint32)
        dst = col("dst_ip", np.uint32)
        proto = col("proto")
        sport = col("src_port")
        dport = col("dst_port")
        flags = col("flags")
        lens = np.maximum(col("lens"), 0)
        no_commit = pl.no_commit_mask(dst, proto, flags)
        drainf = _mesh_step_fn(self._mesh, self._drain_meta(chunk))
        dsvc, _dft = self._shared_tables()
        state, out = drainf(
            self._state, self._drs, dsvc,
            iputil.flip_u32(src), iputil.flip_u32(dst), proto, sport, dport,
            jnp.int32(now), jnp.int32(self._gen),
            valid, no_commit, flags, lens,
        )
        self._state = state
        self._state_mutations += 1
        o = {k: np.asarray(v) for k, v in out.items()}
        self._evictions += int(o["n_evict"].sum())
        self._reclaims += int(o["n_reclaim"].sum())
        self._prune_account(o)
        in_ids = self._cps.ingress.rule_ids
        out_ids = self._cps.egress.rule_ids
        sel = valid
        self._count_metrics(
            {k: o[k][sel] for k in ("code", "ingress_rule", "egress_rule")},
            in_ids, out_ids, lens[sel],
        )
        if self._telemetry is not None:
            # One sharded dispatch drains every replica at once: fold its
            # counters and its wall seconds into the engine's "drain"
            # regime (never deferred here — overlap staging is
            # single-chip).
            self._telemetry.account(o)
            self._telemetry.observe_scoped(
                "engine", "drain", time.perf_counter() - t0)
        # Dirty-row tracking for an in-flight resize: a drain COMMITS
        # rows (both conntrack directions) after their migration window.
        if self._reshard is not None:
            replica = (np.arange(Bd) // chunk).astype(np.int32)
            self._note_reshard_touched(
                replica[valid], src[valid], dst[valid], proto[valid],
                sport[valid], dport[valid],
                committed=o["committed"][valid],
                dnat_f=o["dnat_ip_f"][valid],
                dnat_port=o["dnat_port"][valid])
        return None  # never deferred: overlap staging is single-chip

    def _epoch_maintain(self, now: int) -> tuple[int, int]:
        st, n_aged, n_stale = _vmapped_maintain(self._meta.timeouts)(
            self._state, jnp.int32(now), jnp.int32(self._gen))
        self._state = self._pin_state(st)
        self._state_mutations += 1
        return int(np.asarray(n_aged).sum()), int(np.asarray(n_stale).sum())

    def _epoch_revalidate(self) -> int:
        st, n = _vmapped_revalidate()(self._state, jnp.int32(self._gen))
        self._state = self._pin_state(st)
        self._state_mutations += 1
        return int(np.asarray(n).sum())

    def _epoch_age_scan(self, now: int) -> int:
        st, n = _vmapped_age(self._meta.timeouts)(
            self._state, jnp.int32(now))
        self._state = self._pin_state(st)
        self._state_mutations += 1
        return int(np.asarray(n).sum())

    # -- commit plane hooks --------------------------------------------------

    def _canary_classify(self, batch: PacketBatch, now: int) -> np.ndarray:
        """REPLICA-RESOLVED fresh-walk verdicts: the probe set is tiled
        over the data axis and classified inside shard_map, so each data
        replica's own devices walk their own physical copies of the rule
        tables -> (D, n) codes.  datapath/commit.py diffs every row
        against the Oracle; any replica's mismatch vetoes the bundle for
        the whole mesh (the rollback restores the sharded snapshot — all
        replicas)."""
        del now  # probes are stateless fresh walks
        # A reshard plane certifying its TARGET topology redirects the
        # probe walk onto the target placement (parallel/reshard.py sets
        # _reshard_canary around the commit plane's _canary call): the
        # same replica-resolved diff and veto machinery then gates the
        # cutover the way it gates every bundle.
        tgt = self._reshard_canary
        if tgt is None:
            mesh, drs, mm, D = (self._mesh, self._drs, self._meta.match,
                                self._n_data)
        else:
            mesh, drs, mm, D = tgt
        n = batch.size
        fn = _mesh_canary_fn(mesh, mm, self._meta.fused)
        got = fn(drs,
                 np.tile(iputil.flip_u32(batch.src_ip), D),
                 np.tile(iputil.flip_u32(batch.dst_ip), D),
                 np.tile(batch.proto.astype(np.int32), D),
                 np.tile(batch.dst_port.astype(np.int32), D))
        return np.asarray(got).reshape(D, n)

    # -- audit plane hooks (striped cursor + per-replica state) --------------

    def _audit_rule_digests(self) -> dict:
        """Checksum digests over the HOST view of each sharded tensor
        group: the jitted XOR reduce cannot lower across device shards on
        every backend (CPU rejects cross-shard xor reductions), so the
        mesh scrub gathers and folds host-side.  The logical-bytes
        contract is unchanged — state corruption on any replica's private
        slice lands in the gathered view; per-device divergence of a
        REPLICATED tensor is (as on single-chip) the canary's to catch,
        which the replica-resolved canary does."""
        leaves = jax.tree_util.tree_leaves
        return {
            "drs": pl.tensor_digest(np.asarray(x) for x in leaves(self._drs)),
            "dsvc": pl.tensor_digest(
                np.asarray(x) for x in leaves(self._dsvc)),
            "dft": pl.tensor_digest(np.asarray(x) for x in leaves(self._dft)),
        }

    def _audit_state_digest(self) -> int:
        return pl.tensor_digest(
            np.asarray(x) for x in jax.tree_util.tree_leaves(self._state))

    def _audit_slots(self) -> int:
        return self._n_data * self._meta.flow_slots

    def _audit_window(self, cursor: int, k: int, now: int) -> list[dict]:
        """Striped window over the GLOBAL slot space: global slot g lives
        at (replica g % D, local slot g // D), so one budgeted window
        advances audit coverage on every replica simultaneously and
        `audit_cursor_coverage_ratio` keeps its meaning fleet-wide."""
        D, S = self._n_data, self._meta.flow_slots
        G = D * S
        cursor %= G
        rows: list[dict] = []
        for r in range(D):
            first = cursor + ((r - cursor) % D)
            if first >= cursor + k:
                continue
            count = (cursor + k - first + D - 1) // D
            local_start = first // D
            local = jax.tree.map(lambda x, r=r: x[r], self._state)
            keys_d, meta_d, ts_d = pl.audit_gather(
                local, jnp.int32(local_start % S), window=count)
            got = self._decode_audit_rows(
                keys_d, meta_d, ts_d, now,
                lambda i, r=r, ls=local_start: (((ls + i) % S) * D + r))
            self._replica_audit_entries[r] += len(got)
            rows.extend(got)
        rows.sort(key=lambda e: (e["slot"] - cursor) % G)
        return rows

    def _audit_fresh(self, rows: list, now: int) -> list[dict]:
        """Fresh-walk re-proof per HOME replica: each audited row is
        re-proved against its owning replica's local state slice (the
        affinity view that classified it), through the shared eager
        trace machinery."""
        by_replica: dict[int, list[int]] = {}
        for i, e in enumerate(rows):
            by_replica.setdefault(e["slot"] % self._n_data, []).append(i)
        out: list = [None] * len(rows)
        for r, idxs in sorted(by_replica.items()):
            local = jax.tree.map(lambda x, r=r: x[r], self._state)
            got = self._audit_fresh_state(local, [rows[i] for i in idxs], now)
            for i, rec in zip(idxs, got):
                out[i] = rec
        return out

    def _audit_evict(self, slots: list) -> None:
        D = self._n_data
        groups: dict[int, list[int]] = {}
        for g in slots:
            groups.setdefault(int(g) % D, []).append(int(g) // D)
        st = self._state
        for r, ls in sorted(groups.items()):
            n = max(1, len(ls))
            padded = np.full(1 << (n - 1).bit_length(), -1, np.int32)
            padded[:len(ls)] = np.asarray(ls, np.int32)
            local = jax.tree.map(lambda x, r=r: x[r], st)
            new_local, _n = pl.audit_evict(local, jnp.asarray(padded))
            st = jax.tree.map(lambda full, nl, r=r: full.at[r].set(nl),
                              st, new_local)
        self._state = self._pin_state(st)
        self._state_mutations += 1

    def _audit_corrupt(self, kind: str, now: Optional[int] = None) -> str:
        if kind == "tensor":
            return super()._audit_corrupt(kind, now)
        # Verdict-bit flip on ONE replica's private state slice — real
        # replica-local corruption only the striped audit cursor can see.
        D = self._n_data
        flow = self._state.flow
        keys_all = np.asarray(flow.keys)
        _, M1C, _, _ = pl._meta_cols(self._meta.key_words - 2)
        for r in range(D):
            keys = keys_all[r, :-1].astype(np.int64)
            if now is not None:
                meta_np = np.asarray(flow.meta[r])[:-1].astype(np.int64)
                ts_np = np.asarray(flow.ts[r])[:-1]
                live, _egen = self._live_mask(keys, meta_np, ts_np, now)
            else:
                kpg = keys[:, -1]
                gen_w = self._gen % pl.GEN_ETERNAL
                egen = (kpg >> 9) & pl.GEN_ETERNAL
                live = (kpg != 0) & ((egen == pl.GEN_ETERNAL)
                                     | (egen == gen_w))
            idx = np.nonzero(live)[0]
            if idx.size == 0:
                continue
            slot = int(idx[0])
            mta = self._state.flow.meta
            self._state = self._state._replace(flow=self._state.flow._replace(
                meta=mta.at[r, slot, M1C].set(mta[r, slot, M1C] ^ 1)))
            return (f"flipped cached verdict bit of replica {r} "
                    f"slot {slot}")
        return super()._audit_corrupt("tensor")

    def corrupt_replica(self, replica: int) -> str:
        """Chaos helper: flip the rule-side table copies held by ONE data
        replica's devices — real per-device divergence of a logically
        replicated tensor (the HBM-bit-flip-on-one-chip model).  The next
        replica-resolved canary (install gate or watchdog) diverges on
        exactly this replica and vetoes, rolling back / degrading the
        WHOLE mesh; recovery is the ordinary canary-gated recompile,
        whose fresh placement rebuilds every copy from the host mirror.
        The mutation counter is deliberately not bumped — silent
        corruption is the thing being modeled."""
        devs = set(self._mesh.devices[replica, :].flat)

        def flip(arr):
            bufs = []
            for s in arr.addressable_shards:
                buf = np.array(s.data)
                if s.device in devs:
                    buf = buf ^ 1
                bufs.append(jax.device_put(buf, s.device))
            return jax.make_array_from_single_device_arrays(
                arr.shape, arr.sharding, bufs)

        drs = self._drs
        self._drs = drs._replace(
            ingress=drs.ingress._replace(action=flip(drs.ingress.action)),
            egress=drs.egress._replace(action=flip(drs.egress.action)),
            iso_in=drs.iso_in._replace(val=flip(drs.iso_in.val)),
            iso_out=drs.iso_out._replace(val=flip(drs.iso_out.val)),
        )
        return (f"flipped rule-side device copies held by data replica "
                f"{replica}")

    # -- host-side observability over the (D,) axis --------------------------

    def dump_flows(self, now: int) -> list[dict]:
        return [e for r in range(self._n_data)
                for e in self._dump_flows_state(
                    jax.tree.map(lambda x, r=r: x[r], self._state), now)]

    def cache_stats(self) -> dict:
        per = _vmapped_cache_stats()(self._state)
        c = {k: int(np.asarray(v).sum()) for k, v in per.items()}
        c["evictions"] = self._evictions
        c["reclaims"] = self._reclaims
        return c

    def _tenant_occupied(self, fields: dict) -> int:
        """Snapshot-state occupancy, (D,)-summed (tenancy tenant_stats —
        the scrape path must never swap worlds)."""
        per = _vmapped_cache_stats()(fields["_state"])
        return int(np.asarray(per["occupied"]).sum())

    def _tenant_drain_dispatch_blocks(self, split: dict, now: int,
                                      chunk) -> None:
        """Mesh override of the per-tenant drain dispatch: a LATCHED
        world (per-world topology latch, parallel/reshard.py) serves on
        its own mesh at its own width — the fleet-indexed per-replica
        layout the queues popped is transport only, so such a world's
        rows re-split onto the world's OWN topology before its drain
        classifies (verdict-safe by construction: homes are re-derived
        from the tuple columns the rows carry verbatim)."""
        fleet = (self._n_data, self._topo_gen)
        for tid, subs in sorted(split.items()):
            n = sum(len(b["src_ip"]) for b in subs if b is not None)
            if tid == 0:
                self._drain_classify(subs, now, chunk=chunk)
                continue
            with self._world_ctx(tid) as w:
                if (self._n_data, self._topo_gen) != fleet:
                    wsubs, chunk_w = self._relayout_world_blocks(subs)
                    self._drain_classify(wsubs, now, chunk=chunk_w)
                else:
                    self._drain_classify(subs, now, chunk=chunk)
                w.queued = max(0, w.queued - n)
        return None

    def _relayout_world_blocks(self, subs: list):
        """Concatenate a latched world's per-replica sub-blocks and
        re-split them by the world's OWN affinity topology (the
        tenant-salted ring at the world's width/generation, the world's
        survivor mask applied) -> (blocks, chunk).  Runs inside the
        world's ctx.  The chunk is pow2-rounded from the max per-replica
        count so the drain's compile-variant set stays O(log), the spill
        retry's rung discipline."""
        rows = [b for b in subs if b is not None]
        block = {c: np.concatenate([np.asarray(b[c]) for b in rows])
                 for c in rows[0]}
        cols = (block["src_ip"].astype(np.uint32),
                block["dst_ip"].astype(np.uint32),
                block["proto"].astype(np.int32),
                block["src_port"].astype(np.int32),
                block["dst_port"].astype(np.int32))
        home = shard_of_tuples(*cols, self._n_data, self._topo_gen,
                               tenant=self._tenant_id())
        if self._failover is not None:
            home, _m = self._failover.mask_shard(
                *cols, home, tenant=self._tenant_id())
        out = []
        mx = 1
        for r in range(self._n_data):
            idx = np.nonzero(home == r)[0]
            if idx.size == 0:
                out.append(None)
                continue
            out.append({c: v[idx] for c, v in block.items()})
            mx = max(mx, int(idx.size))
        chunk = 1 << max(4, (mx - 1).bit_length())
        return out, chunk

    def trace(self, batch: PacketBatch, now: int) -> list[dict]:
        if not self._gates.enabled("Traceflow"):
            raise RuntimeError("Traceflow feature gate is disabled")
        D = self._n_data
        shard = shard_of_tuples(batch.src_ip, batch.dst_ip, batch.proto,
                                batch.src_port, batch.dst_port, D,
                                self._topo_gen, tenant=self._tenant_id())
        if self._failover is not None:
            # Trace what serving serves: quarantined-home lanes re-home
            # onto the survivor ring exactly like _step.
            shard, _m = self._failover.mask_shard(
                batch.src_ip, batch.dst_ip, batch.proto, batch.src_port,
                batch.dst_port, shard, tenant=self._tenant_id())
        out: list = [None] * batch.size
        for r in range(D):
            idx = np.nonzero(shard == r)[0]
            if idx.size == 0:
                continue
            sub = PacketBatch.from_packets(
                [batch.packet(int(i)) for i in idx])
            local = jax.tree.map(lambda x, r=r: x[r], self._state)
            for i, rec in zip(idx, self._trace_batch(local, sub, now)):
                out[int(i)] = rec
        return out

    # -- elastic resharding plane (parallel/reshard.py) ----------------------

    def _note_reshard_touched(self, replica, src, dst, proto, sport, dport,
                              committed=None, dnat_f=None,
                              dnat_port=None) -> None:
        """Record the home (replica, local slot) of every lane a live
        dispatch may have refreshed/committed/torn down, plus — for
        conntrack-committed lanes — the REPLY-direction entry's slot
        (keyed on the post-DNAT swapped tuple, written in the same
        replica's slice).  Conservative: marking an untouched slot just
        re-sweeps one row at catch-up; the one write class NOT derivable
        host-side is the deferred partner-refresh ts stamp (its slot
        comes from cached meta) — a missed ts refresh is the documented
        verdict-safe staleness class, re-proved by the revalidator."""
        plane = self._reshard
        if plane is None:
            return
        tid = self._tenant_id()
        if plane.dirty_all_for(tid):
            return
        N = self._meta.flow_slots
        src = np.asarray(src).astype(np.uint32)
        dst = np.asarray(dst).astype(np.uint32)
        proto = np.asarray(proto).astype(np.int32)
        sport = np.asarray(sport).astype(np.int32)
        dport = np.asarray(dport).astype(np.int32)
        h = hashing.flow_hash(src, dst, proto, sport, dport, xp=np)
        plane.note_touched(np.asarray(replica),
                           (h & np.uint32(N - 1)).astype(np.int64),
                           tenant=tid)
        if committed is None or dnat_f is None:
            return
        com = np.asarray(committed) != 0
        if not com.any():
            return
        dnat = iputil.unflip_u32_array(np.asarray(dnat_f)[com])
        dp = np.asarray(dnat_port)[com].astype(np.int32)
        rh = hashing.flow_hash(dnat.astype(np.uint32), src[com], proto[com],
                               dp, sport[com], xp=np)
        plane.note_touched(np.asarray(replica)[com],
                           (rh & np.uint32(N - 1)).astype(np.int64),
                           tenant=tid)

    def _remap_cached_attribution(self, old_in: list, old_out: list) -> None:
        # Same-ids-in-same-order is the base method's no-op fast path
        # (services-only bundles, degraded-recovery recompiles): zero
        # cache rows rewritten, so the bounded dirty set must survive.
        changed = (list(old_in) != list(self._cps.ingress.rule_ids)
                   or list(old_out) != list(self._cps.egress.rule_ids))
        super()._remap_cached_attribution(old_in, old_out)
        # A mid-resize bundle that REALLY remapped attribution touched
        # the WHOLE cache: no bounded dirty set covers that — fall back
        # to the full catch-up sweep (metered; the pre-tracking shape).
        # Per-world: only the world that remapped degrades to the full
        # walk.
        if changed and self._reshard is not None:
            self._reshard.note_all_dirty(tenant=self._tenant_id())

    def reshard_begin(self, n_data: int, devices=None) -> dict:
        """Begin a LIVE resize of the data axis to `n_data` replicas.

        Constructs the target mesh and the next affinity-hash generation
        (dual-topology serving: in-flight batches keep resolving against
        the old topology), and registers the budgeted `reshard-migrate`
        maintenance task that walks the flow-cache/conntrack tables and
        re-commits rows to their target ring homes — live TENANT worlds
        included, each under its own `_world_ctx` with its own certified
        per-world cutover.  The fleet flips only after the target passes
        its replica-resolved canary and a migrated-row audit sweep; a
        default-world veto aborts back to the old mesh with the
        generation unchanged, while a tenant world's veto latches only
        that world.  -> the plane's status dict."""
        if self._reshard is not None:
            raise RuntimeError(
                "a reshard is already in flight; wait for its cutover or "
                "abort it first (reshard_abort)")
        if self.degraded:
            raise RuntimeError(
                "datapath is degraded (serving last-known-good): the "
                "cutover gate could never certify a target topology — "
                "recover before resizing")
        plane = ReshardPlane(self, int(n_data), devices=devices)
        self._install_reshard_plane(plane)
        return plane.status()

    def _install_reshard_plane(self, plane) -> None:
        """Adopt a constructed ReshardPlane — the ordinary reshard_begin
        above, or the failover plane's emergency evacuation/certified
        readmission (which build their planes directly: the evacuation
        must skip reshard_begin's degraded refusal by design — see
        parallel/failover.py) — and register its budgeted migration
        task."""
        self._reshard = plane
        self._maintenance.register(MaintenanceTask(
            "reshard-migrate", self._maint_reshard,
            budget=self._reshard_budget, priority=4,
            shed_when_degraded=True))

    def _maint_reshard(self, now: int, budget: int) -> int:
        """The reshard plane's maintenance-task runner: budgeted
        migration windows while migrating, then the certified cutover
        (true cost reported unclamped — the scheduler's overrun path
        meters it, the canary/scrub discipline)."""
        plane = self._reshard
        if plane is None:
            return 0
        return plane.advance(now, budget)

    def reshard_status(self):
        """The in-flight resize's progress (None when no resize is in
        flight) — see ReshardPlane.status."""
        return None if self._reshard is None else self._reshard.status()

    def reshard_abort(self, reason: str = "operator abort") -> None:
        """Abandon the in-flight resize: the old mesh keeps serving, the
        affinity generation never flips, target structures are dropped."""
        if self._reshard is None:
            raise RuntimeError("no reshard in flight")
        self._reshard.abort(reason)

    def arm_reshard_faults(self, plan, name: str) -> None:
        """Chaos hook (tests): arm the per-tenant forced-canary-veto
        sites f"{name}.tenant_canary.t{tid}" consulted by the per-world
        cutover certification (parallel/reshard._certify_world) — a
        deterministic single-world veto without corrupting device
        state."""
        self._reshard_faults = (plan, str(name))
        plan.bind_recorder(getattr(self, "_flightrec", None))

    def tenant_reshard_resync(self, tid: int, now: int) -> dict:
        """Re-home ONE latched tenant world onto the current fleet
        topology (the readmission half of a per-world canary veto): the
        full migrate + certify + flip walk for just that world, under
        the same veto rules — a second veto re-latches, journaled.
        Refused while a fleet resize is in flight (the plane's own
        per-world migration would race this walk)."""
        if self._reshard is not None:
            raise RuntimeError(
                "a reshard is in flight; the latched world re-certifies "
                "at that plane's cutover — wait for it")
        return resync_world(self, int(tid), int(now))

    def _finish_reshard(self, plane) -> None:
        """Plane lifecycle callback (cutover or abort): unregister the
        migration task and fold the plane's meters into the engine's."""
        if self._reshard is plane:
            self._reshard = None
            self._maintenance.unregister("reshard-migrate")
        if self._failover is not None:
            # Evacuation/readmission outcomes fold into the failover
            # state machine; ordinary resizes pass through untouched.
            self._failover.note_reshard_finished(plane)

    def reshard_stats(self) -> dict:
        """Elastic-mesh observability (schema-stable whether or not a
        resize is in flight): the live affinity-topology generation,
        migration progress/volume, resident target rows, and cutover/
        abort counters — rendered as the reshard metric families."""
        plane = self._reshard
        st = plane.status() if plane is not None else None
        migrated = self._reshard_migrated_total + (
            plane.migrated_rows if plane is not None else 0)
        return {
            "topology_generation": self._topo_gen,
            "active": int(plane is not None),
            "phase": None if st is None else st["phase"],
            "target_n_data": None if st is None else st["n_data_to"],
            "progress_ratio": 0.0 if st is None else st["progress_ratio"],
            "migrated_rows_total": migrated,
            # Cutover catch-up volume: slots the dirty-row sweep walked
            # (the full O(slots) fallback only after a whole-cache
            # write — the production-boundedness meter of ROADMAP 3).
            "catchup_rows_total": self._reshard_catchup_total + (
                plane.catchup_scanned if plane is not None else 0),
            "resident_rows": (plane.resident_rows if plane is not None
                              else self._reshard_resident_rows),
            "requeued_total": self._reshard_requeued_total,
            "cutovers_total": self._reshard_cutovers,
            "aborts_total": self._reshard_aborts,
            "last_span": self._last_reshard_span,
            # Tenant-labeled resize observability: rows migrated into
            # tenant worlds (folded at flip; the live plane's in-flight
            # rows ride on top), per-world cutover vetoes, and the live
            # plane's world count.
            "tenant_rows_total": self._reshard_tenant_rows_total + (
                plane.tenant_rows() if plane is not None else 0),
            "tenant_vetoes_total": self._reshard_tenant_vetoes,
            "tenant_worlds_migrating": (len(plane.worlds)
                                        if plane is not None else 0),
        }

    def mesh_stats(self) -> dict:
        """Shard-labeled observability (rendered as the replica-labeled
        metric families in observability/metrics.py): per-replica
        miss-queue depth, replica-resolved canary mismatches, and
        audited-entry volume under the striped cursor."""
        cp = self._commit
        depths = ([q.depth for q in self._slowpath.queues]
                  if self._slowpath is not None else [0] * self._n_data)
        return {
            "mesh": {"data": self._n_data, "rule": self._n_rule},
            "devices": self._n_data * self._n_rule,
            # Hash-skew pressure: lanes placed off-home, and how many of
            # them the bounded home-routed retry dispatch re-served
            # (equal counters = no lane ever kept foreign semantics).
            "spill_lanes_total": self._spill_lanes_total,
            "spill_retried_total": self._spill_retried_total,
            "replica_miss_queue_depth": depths,
            "replica_canary_mismatches": {
                int(r): int(n)
                for r, n in (cp.replica_mismatches.items()
                             if cp is not None else ())},
            "replica_audit_entries": list(self._replica_audit_entries),
        }

    # -- replica-loss failover plane (parallel/failover.py) ------------------

    def _maint_replica_health(self, now: int, budget: int) -> int:
        """The `replica-health` maintenance-task runner: one probe round
        per grant, plus evacuation begin/retry and auto-readmission
        (NOT shed when degraded — a degraded mesh is exactly when
        replica loss must still be detected)."""
        fo = self._failover
        if fo is None:
            return 0
        return fo.advance(now, budget)

    def arm_failover_faults(self, plan, name: str) -> None:
        """FlakyDatapath hook: arm the f"{name}.replica_dead" /
        f"{name}.replica_wedge" sites on the failover plane (no-op when
        the plane is disabled)."""
        if self._failover is not None:
            self._failover.arm(plan, name)

    def failover_stats(self) -> dict:
        """Replica-loss failover observability (schema-stable whether or
        not the plane is enabled; rendered as the failover metric
        families in observability/metrics.py and GET /failover)."""
        fo = self._failover
        if fo is None:
            return {"enabled": 0, "n_shards": 0, "phase": "disabled",
                    "quarantined_shard": None, "mask_active": 0,
                    "probes_total": 0, "probe_failures_total": 0,
                    "slow_dispatches_total": 0, "quarantines_total": 0,
                    "evacuations_total": 0, "readmissions_total": 0,
                    "remiss_total": 0, "requeued_total": 0,
                    "fail_streaks": {}, "probe_rounds": 0,
                    "probe_history": [],
                    "tenants_pending_evacuation": []}
        return {"enabled": 1, "n_shards": fo._orig_n, **fo.status()}

    def failover_readmit(self) -> dict:
        """Operator re-admission (GET /failover?readmit=1, `antctl
        failover --readmit`): pre-flip heal unmasks; an evacuated
        replica rejoins via the ordinary certified grow-resize — never
        a blind flip.  -> refreshed failover stats."""
        if self._failover is None:
            raise RuntimeError(
                "the failover plane is not enabled (failover=True)")
        st = self._failover.readmit(mode="operator")
        return {"enabled": 1, "n_shards": self._failover._orig_n, **st}
