"""ConfigStore: ctypes binding for the native ovsdb_lite store.

The OVSDB seam of the reference, made native per SURVEY §2.5 ("in-process
config store with on-disk snapshot ... same transactional semantics"):
the C++ journaled KV store (native/ovsdb_lite.cc) holds the durable
config/state the reference keeps in ovsdb-server — cookie round numbers,
interface external-IDs, bridge config.  The library builds on demand with
g++ (cached next to the source); environments without a toolchain fall
back to a pure-Python journal with the SAME record format, so the two
implementations are interchangeable on the same file.
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import zlib
from typing import Optional

_SRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native", "ovsdb_lite.cc",
)
_SO = os.path.join(os.path.dirname(_SRC), "ovsdb_lite.so")
_MAGIC = 0x0A17DB01

_lib = None
_lib_err: Optional[str] = None


def _build() -> Optional[str]:
    if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return None
    try:
        r = subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-o", _SO, _SRC],
            capture_output=True, text=True, timeout=120,
        )
    except (OSError, subprocess.TimeoutExpired) as e:
        return f"g++ unavailable: {e}"
    if r.returncode != 0:
        return f"g++ failed: {r.stderr[-500:]}"
    return None


def _load():
    global _lib, _lib_err
    if _lib is not None or _lib_err is not None:
        return
    err = _build()
    if err is not None:
        _lib_err = err
        return
    try:
        lib = ctypes.CDLL(_SO)
    except OSError as e:
        _lib_err = str(e)
        return
    lib.ovsdb_open.restype = ctypes.c_void_p
    lib.ovsdb_open.argtypes = [ctypes.c_char_p]
    lib.ovsdb_close.argtypes = [ctypes.c_void_p]
    lib.ovsdb_txn_set.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_uint32,
    ]
    lib.ovsdb_txn_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.ovsdb_txn_abort.argtypes = [ctypes.c_void_p]
    lib.ovsdb_commit.restype = ctypes.c_int
    lib.ovsdb_commit.argtypes = [ctypes.c_void_p]
    lib.ovsdb_get.restype = ctypes.c_int64
    lib.ovsdb_get.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_uint32,
    ]
    lib.ovsdb_count.restype = ctypes.c_uint64
    lib.ovsdb_count.argtypes = [ctypes.c_void_p]
    lib.ovsdb_key_at.restype = ctypes.c_int64
    lib.ovsdb_key_at.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint32,
    ]
    lib.ovsdb_compact.restype = ctypes.c_int
    lib.ovsdb_compact.argtypes = [ctypes.c_void_p]
    _lib = lib


def native_available() -> bool:
    _load()
    return _lib is not None


class _PyJournal:
    """Pure-Python fallback speaking the identical on-disk format."""

    def __init__(self, path: str):
        self.path = path
        self.table: dict[bytes, bytes] = {}
        self.staged: list[tuple[int, bytes, bytes]] = []
        if os.path.exists(path):
            self._replay()
        self._f = open(path, "ab")

    def _replay(self) -> None:
        data = open(self.path, "rb").read()
        off = 0
        while off + 12 <= len(data):
            magic, blen, crc = struct.unpack_from("<III", data, off)
            if magic != _MAGIC or off + 12 + blen > len(data):
                break
            body = data[off + 12: off + 12 + blen]
            if zlib.crc32(body) != crc:
                break
            self._apply(body)
            off += 12 + blen
        # torn/corrupt tail records are dropped, matching the C++ replay

    def _apply(self, body: bytes) -> None:
        o = 0
        (nops,) = struct.unpack_from("<I", body, o); o += 4
        for _ in range(nops):
            kind = body[o]; o += 1
            (klen,) = struct.unpack_from("<I", body, o); o += 4
            key = body[o:o + klen]; o += klen
            if kind == 0:
                (vlen,) = struct.unpack_from("<I", body, o); o += 4
                val = body[o:o + vlen]; o += vlen
                self.table[key] = val
            else:
                self.table.pop(key, None)

    def _encode(self, ops) -> bytes:
        body = struct.pack("<I", len(ops))
        for kind, key, val in ops:
            body += bytes([kind]) + struct.pack("<I", len(key)) + key
            if kind == 0:
                body += struct.pack("<I", len(val)) + val
        return body

    def commit(self) -> bool:
        if not self.staged:
            return True
        body = self._encode(self.staged)
        rec = struct.pack("<III", _MAGIC, len(body), zlib.crc32(body)) + body
        self._f.write(rec)
        self._f.flush()
        for kind, key, val in self.staged:
            if kind == 0:
                self.table[key] = val
            else:
                self.table.pop(key, None)
        self.staged.clear()
        return True

    def compact(self) -> bool:
        ops = [(0, k, v) for k, v in sorted(self.table.items())]
        body = self._encode(ops)
        rec = struct.pack("<III", _MAGIC, len(body), zlib.crc32(body)) + body
        tmp = self.path + ".compact"
        with open(tmp, "wb") as f:
            f.write(rec)
        self._f.close()
        os.replace(tmp, self.path)
        self._f = open(self.path, "ab")
        return True

    def close(self) -> None:
        self._f.close()


class ConfigStore:
    """Transactional KV store over the native lib (Python fallback kept
    wire-compatible).  Usage: stage set()/delete() then commit()."""

    def __init__(self, path: str, force_python: bool = False):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._py: Optional[_PyJournal] = None
        self._h = None
        if not force_python:
            _load()
        if not force_python and _lib is not None:
            h = _lib.ovsdb_open(path.encode())
            if not h:
                raise OSError(f"ovsdb_lite: cannot open {path}")
            self._h = ctypes.c_void_p(h)
        else:
            self._py = _PyJournal(path)

    @property
    def backend(self) -> str:
        return "native" if self._h is not None else "python"

    def set(self, key: str, value: bytes) -> None:
        if self._h is not None:
            _lib.ovsdb_txn_set(self._h, key.encode(), value, len(value))
        else:
            self._py.staged.append((0, key.encode(), value))

    def delete(self, key: str) -> None:
        if self._h is not None:
            _lib.ovsdb_txn_delete(self._h, key.encode())
        else:
            self._py.staged.append((1, key.encode(), b""))

    def abort(self) -> None:
        if self._h is not None:
            _lib.ovsdb_txn_abort(self._h)
        else:
            self._py.staged.clear()

    def commit(self) -> None:
        ok = (_lib.ovsdb_commit(self._h) == 1) if self._h is not None \
            else self._py.commit()
        if not ok:
            raise OSError("ovsdb_lite: commit failed")

    def get(self, key: str) -> Optional[bytes]:
        if self._h is not None:
            buf = ctypes.create_string_buffer(1 << 16)
            n = _lib.ovsdb_get(self._h, key.encode(), buf, len(buf))
            if n < 0:
                return None
            if n > len(buf):  # value larger than the probe buffer
                buf = ctypes.create_string_buffer(n)
                n = _lib.ovsdb_get(self._h, key.encode(), buf, n)
            return buf.raw[:n]
        return self._py.table.get(key.encode())

    def keys(self) -> list[str]:
        if self._h is not None:
            out = []
            n = _lib.ovsdb_count(self._h)
            buf = ctypes.create_string_buffer(1 << 12)
            for i in range(n):
                k = _lib.ovsdb_key_at(self._h, i, buf, len(buf))
                if k >= 0:
                    out.append(buf.raw[:k].decode())
            return out
        return sorted(k.decode() for k in self._py.table)

    def compact(self) -> None:
        ok = (_lib.ovsdb_compact(self._h) == 1) if self._h is not None \
            else self._py.compact()
        if not ok:
            raise OSError("ovsdb_lite: compact failed")

    def close(self) -> None:
        if self._h is not None:
            _lib.ovsdb_close(self._h)
            self._h = None
        elif self._py is not None:
            self._py.close()
            self._py = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
