"""Native (C++) runtime components + ctypes bindings."""

from .store import ConfigStore, native_available

__all__ = ["ConfigStore", "native_available"]
