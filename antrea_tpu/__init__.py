"""antrea_tpu: a TPU-native re-implementation of Antrea's dataplane stack.

The reference (thebigbone/antrea) compiles Kubernetes/Antrea NetworkPolicy and
Service load-balancing state into Open vSwitch flow tables; per-packet
classification happens inside OVS (C, kernel datapath).  Here the per-packet
hot path is a batched tuple-space classification kernel in JAX/XLA ("tpuflow"),
and the surrounding control plane (policy computation, address-group factoring,
span-based dissemination, AntreaProxy endpoint selection) is re-expressed
TPU-first: rule sets compile into match tensors, packets flow through the
pipeline as (B,) field arrays, and multi-chip scale-out uses jax.sharding
collectives instead of tunnels.

Layer map (mirrors SURVEY.md section 1):
  apis/        controlplane wire types (ref: pkg/apis/controlplane/types.go)
  utils/       IP / CIDR helpers (ref: pkg/util/ip)
  oracle/      scalar CPU reference interpreter == the verdict-parity spec
  compiler/    rule IR -> match tensors (ref: pkg/agent/openflow rule compile)
  ops/         JAX/Pallas kernels (interval LPM, conjunctive match, hash tables)
  models/      the staged datapath pipeline (ref: pkg/agent/openflow/pipeline.go)
  parallel/    device-mesh sharding of the classification step
  datapath/    datapath-type plugin boundary (ref: pkg/ovs/ovsconfig)
  controller/  central policy computation + watch store (ref: pkg/controller)
  agent/       node-agent analog: rule cache, reconciler, proxy (ref: pkg/agent)
  simulator/   synthetic traffic/agent driver (ref: cmd/antrea-agent-simulator)
"""

__version__ = "0.1.0"
