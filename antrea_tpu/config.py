"""Typed configuration for the agent and controller processes.

The analog of the reference's YAML ConfigMap -> typed config structs path
(/root/reference/pkg/config/agent, pkg/config/controller, parsed and
validated by cmd/antrea-agent/options.go): a YAML (or JSON) document maps
onto dataclasses with defaults, validation, and a featureGates section
checked against the registry (features.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .features import FeatureGates


class ConfigError(ValueError):
    """A configuration knob combination that cannot work was rejected at
    CONSTRUCTION time, with the reason — instead of failing deep inside
    the first drain/scan it would have broken.  Subclasses ValueError so
    pre-existing callers that catch/raise ValueError keep working."""


@dataclass
class AgentConfig:
    """antrea-agent.conf analog (the subset this build consumes)."""

    node_name: str = ""
    node_ips: list = field(default_factory=list)
    # Datapath sizing (tpuflow tensors).
    flow_slots: int = 1 << 20
    aff_slots: int = 1 << 18
    ct_timeout_s: int = 3600
    miss_chunk: int = 4096
    delta_slots: int = 128
    # Unified maintenance scheduler (datapath/maintenance.py): total
    # budget units per tick across every registered background task
    # (None = unlimited; per-task quanta still clamp each task).
    maint_budget: Optional[int] = None
    datapath_type: str = "tpuflow"  # ovsconfig.OVSDatapathType analog
    persist_dir: Optional[str] = None
    filestore_dir: Optional[str] = None
    audit_log_path: Optional[str] = None
    feature_gates: FeatureGates = field(default_factory=FeatureGates)

    def validate(self) -> None:
        for name, v in (("flow_slots", self.flow_slots),
                        ("aff_slots", self.aff_slots)):
            if v < 2 or v & (v - 1):
                raise ValueError(f"{name} must be a power of two >= 2, got {v}")
        if self.datapath_type not in ("tpuflow", "oracle"):
            raise ValueError(f"unknown datapathType {self.datapath_type!r}")
        if self.miss_chunk < 1:
            raise ValueError("missChunk must be >= 1")
        if self.maint_budget is not None and self.maint_budget <= 0:
            raise ConfigError(
                f"maintBudget must be positive (or unset for unlimited), "
                f"got {self.maint_budget}"
            )


@dataclass
class ControllerConfig:
    """antrea-controller.conf analog."""

    feature_gates: FeatureGates = field(default_factory=FeatureGates)


_AGENT_KEYS = {
    "nodeName": "node_name",
    "nodeIPs": "node_ips",
    "flowSlots": "flow_slots",
    "affinitySlots": "aff_slots",
    "ctTimeoutSeconds": "ct_timeout_s",
    "missChunk": "miss_chunk",
    "deltaSlots": "delta_slots",
    "maintBudget": "maint_budget",
    "datapathType": "datapath_type",
    "persistDir": "persist_dir",
    "filestoreDir": "filestore_dir",
    "auditLogPath": "audit_log_path",
}


def _load_doc(path: str) -> dict:
    import yaml

    with open(path) as f:
        doc = yaml.safe_load(f) or {}
    if not isinstance(doc, dict):
        raise ValueError(f"config {path}: top level must be a mapping")
    return doc


def load_agent_config(path: str) -> AgentConfig:
    doc = _load_doc(path)
    cfg = AgentConfig()
    for key, val in doc.items():
        if key == "featureGates":
            cfg.feature_gates = FeatureGates(val or {})
        elif key in _AGENT_KEYS:
            setattr(cfg, _AGENT_KEYS[key], val)
        else:
            raise ValueError(f"unknown agent config key {key!r}")
    cfg.validate()
    return cfg


def load_controller_config(path: str) -> ControllerConfig:
    doc = _load_doc(path)
    cfg = ControllerConfig()
    for key, val in doc.items():
        if key == "featureGates":
            cfg.feature_gates = FeatureGates(val or {})
        else:
            raise ValueError(f"unknown controller config key {key!r}")
    return cfg


def build_datapath(cfg: AgentConfig):
    """Config -> a constructed Datapath (the initializer seam,
    ref agent.go setupOVSBridge/initOpenFlowPipeline)."""
    from .datapath import OracleDatapath, TpuflowDatapath

    cls = TpuflowDatapath if cfg.datapath_type == "tpuflow" else OracleDatapath
    kw = dict(
        flow_slots=cfg.flow_slots, aff_slots=cfg.aff_slots,
        ct_timeout_s=cfg.ct_timeout_s,
        node_ips=list(cfg.node_ips), node_name=cfg.node_name,
        persist_dir=cfg.persist_dir,
        feature_gates=cfg.feature_gates,
        maint_budget=cfg.maint_budget,
    )
    if cls is TpuflowDatapath:
        kw.update(miss_chunk=cfg.miss_chunk, delta_slots=cfg.delta_slots)
    return cls(**kw)
