"""Pass `audit-plane` — every mutable tensor is scrubbed or waived
(migrated from tools/check_audit_plane.py, which remains as a shim).

The checksum scrub (datapath/audit.py mechanism 2) only protects what
it digests.  The authoritative inventory of everything a commit can
touch is `_commit_snapshot` on the two engines — a snapshot key must be
covered by SCRUB_MANIFEST ("rule" | "state") or waived with a reason in
SCRUB_ALLOWLIST; SCRUB_SUBTENSORS stays consistent with
ops/match.DimTable.agg; engines implement the scrub hooks and inherit
AuditableDatapath."""

from __future__ import annotations

import ast
import re

from .core import Finding, SourceCache, analysis_pass

ENGINE_CLASSES = {
    "datapath/tpuflow.py": "TpuflowDatapath",
    "datapath/oracle_dp.py": "OracleDatapath",
}
HOOKS = ("_audit_rule_digests", "_audit_state_digest", "_audit_reupload",
         "_audit_window", "_audit_fresh", "_audit_evict")

_DICT_LITERAL = r"^{name}\s*(?::[^=]+)?=\s*(\{{.*?^\}})"


def load_table(text: str, name: str) -> dict:
    """Extract + literal-eval a module-level dict assignment from audit.py
    (pure literals by contract — the docstring on the tables says so)."""
    m = re.search(_DICT_LITERAL.format(name=name), text, re.M | re.S)
    if m is None:
        raise ValueError(f"datapath/audit.py defines no {name} literal")
    return ast.literal_eval(m.group(1))


def snapshot_keys(text: str, name: str) -> list[str]:
    """String keys of the dict `_commit_snapshot` returns."""
    m = re.search(r"def _commit_snapshot\(.*?(?=\n    def )", text, re.S)
    if m is None:
        raise ValueError(f"{name}: no _commit_snapshot found")
    body = m.group(0)
    ret = body[body.index("return {"):]
    return re.findall(r'^\s*"(\w+)":', ret, re.M)


@analysis_pass("audit-plane", "every commit-snapshot tensor is checksum-"
                              "scrubbed or waived with a reason")
def check(src: SourceCache) -> list[Finding]:
    audit_rel = "antrea_tpu/datapath/audit.py"
    audit_text = src.text(src.pkg / "datapath" / "audit.py")
    if not audit_text:
        return [Finding("audit-plane", audit_rel, 0,
                        f"{audit_rel} is missing", obj="missing")]

    def f(reason, obj, path=audit_rel, line=0):
        return Finding("audit-plane", path, line, reason, obj=obj)

    try:
        manifest = load_table(audit_text, "SCRUB_MANIFEST")
        allowlist = load_table(audit_text, "SCRUB_ALLOWLIST")
    except ValueError as e:
        return [f(str(e), "tables-unreadable")]

    problems: list[Finding] = []
    for key, klass in manifest.items():
        if klass not in ("rule", "state"):
            problems.append(f(
                f"SCRUB_MANIFEST[{key!r}] = {klass!r} — must be 'rule' or "
                f"'state'", f"bad-class:{key}"))
    for key, reason in allowlist.items():
        if not (isinstance(reason, str) and reason.strip()):
            problems.append(f(
                f"SCRUB_ALLOWLIST[{key!r}] has no reason — every waived "
                f"snapshot key must say WHY it needs no scrub",
                f"no-reason:{key}"))
    for key in set(manifest) & set(allowlist):
        problems.append(f(
            f"{key!r} is both scrubbed (SCRUB_MANIFEST) and waived "
            f"(SCRUB_ALLOWLIST) — pick one", f"both:{key}"))

    # Round-7 aggregate tables: while DimTable carries an `agg` field the
    # SUB-tensor table must carry its "drs.agg" row (a corrupt aggregate
    # bit can flip a verdict — see the SCRUB_SUBTENSORS comment; it rides
    # the `drs` digest, so it must NOT be a manifest row, which would
    # inflate the maintenance scheduler's scrub cost) and vice versa (a
    # stale row must not outlive the field).
    try:
        subtensors = load_table(audit_text, "SCRUB_SUBTENSORS")
    except ValueError as e:
        return problems + [f(str(e), "subtensors-unreadable")]
    for key in set(subtensors) & set(manifest):
        problems.append(f(
            f"{key!r} is in both SCRUB_MANIFEST and SCRUB_SUBTENSORS — "
            f"sub-tensors ride a group digest, they are not extra folds",
            f"sub-and-manifest:{key}"))
    match_text = src.text(src.pkg / "ops" / "match.py") or ""
    dim_cls = re.search(r"^class DimTable\(.*?(?=^class |^def )",
                        match_text, re.M | re.S)
    has_agg_field = bool(dim_cls) and bool(
        re.search(r"^    agg\s*:", dim_cls.group(0), re.M))
    if has_agg_field and "drs.agg" not in subtensors:
        problems.append(f(
            "ops/match.DimTable declares `agg` but SCRUB_SUBTENSORS has "
            "no 'drs.agg' row — aggregate/table divergence would go "
            "undocumented/ungated", "agg-unlisted"))
    if not has_agg_field and "drs.agg" in subtensors:
        problems.append(f(
            "SCRUB_SUBTENSORS carries 'drs.agg' but ops/match.DimTable "
            "declares no `agg` field — stale row", "agg-stale"))

    for relpath, cls in ENGINE_CLASSES.items():
        path = src.pkg / relpath
        rel = f"antrea_tpu/{relpath}"
        text = src.text(path) or ""
        try:
            keys = snapshot_keys(text, relpath)
        except ValueError as e:
            problems.append(f(str(e), f"snapshot-unreadable:{relpath}", rel))
            continue
        if not keys:
            problems.append(f(f"{rel}: _commit_snapshot returns no keys?",
                              f"snapshot-empty:{relpath}", rel))
        for key in keys:
            if key not in manifest and key not in allowlist:
                problems.append(f(
                    f"{rel}: _commit_snapshot key {key!r} is neither in "
                    f"SCRUB_MANIFEST nor SCRUB_ALLOWLIST — new state must "
                    f"be checksum-scrubbed or explicitly waived with a "
                    f"reason (datapath/audit.py)",
                    f"uncovered:{relpath}:{key}", rel))
        m = re.search(rf"^class {cls}\(([^)]*)\)", text, re.M | re.S)
        if m is None or "AuditableDatapath" not in m.group(1):
            problems.append(f(
                f"{rel}: {cls} does not inherit AuditableDatapath",
                f"no-mixin:{cls}", rel))
        for hook in HOOKS:
            if not re.search(rf"^\s*def {hook}\(", text, re.M):
                problems.append(f(f"{rel} does not implement {hook}()",
                                  f"no-hook:{relpath}:{hook}", rel))
    return problems
