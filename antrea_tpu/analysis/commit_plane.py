"""Pass `commit-plane` — every install routes through datapath/commit.py
(migrated from tools/check_commit_plane.py, which remains as a shim).

The self-healing guarantees of the transactional commit plane (compile
-> canary -> atomic swap -> settle, rollback to last-known-good,
degraded mode) hold only if NO datapath exposes a tensor-swap entry
point that bypasses the plane: engines must not define the public
install_bundle/apply_group_delta themselves, nothing may call an
`_impl` hook outside commit.py, engines must inherit
TransactionalDatapath, and no engine impl performs its own settle."""

from __future__ import annotations

import re

from .core import Finding, SourceCache, analysis_pass
from .core import pat_slug as _pat_slug

ENGINE_CLASSES = {
    "datapath/tpuflow.py": "TpuflowDatapath",
    "datapath/oracle_dp.py": "OracleDatapath",
}
PUBLIC = ("install_bundle", "apply_group_delta")
IMPLS = ("_install_bundle_impl", "_apply_group_delta_impl")
SETTLE = (r"self\._persist\(\)", r"self\._record_round\(\)")


@analysis_pass("commit-plane", "every bundle install routes through the "
                               "transactional commit plane's canary gate")
def check(src: SourceCache) -> list[Finding]:
    commit_rel = "antrea_tpu/datapath/commit.py"
    commit_text = src.text(src.pkg / "datapath" / "commit.py")
    if not commit_text:
        return [Finding("commit-plane", commit_rel, 0,
                        f"{commit_rel} is missing", obj="missing")]

    problems: list[Finding] = []

    def f(reason, obj, path, line=0):
        return Finding("commit-plane", path, line, reason, obj=obj)

    # 1 + 3 + 4: per-engine rules.
    for relpath, cls in ENGINE_CLASSES.items():
        path = src.pkg / relpath
        rel = f"antrea_tpu/{relpath}"
        text = src.text(path) or ""
        for name in PUBLIC:
            if re.search(rf"^\s*def {name}\(", text, re.M):
                problems.append(f(
                    f"{rel} defines public {name}() — installs must route "
                    f"through the commit plane (datapath/commit.py)",
                    f"public:{relpath}:{name}", rel))
        for name in IMPLS:
            if not re.search(rf"^\s*def {name}\(", text, re.M):
                problems.append(f(
                    f"{rel} does not implement {name}()",
                    f"no-impl:{relpath}:{name}", rel))
        m = re.search(rf"^class {cls}\(([^)]*)\)", text, re.M | re.S)
        if m is None or "TransactionalDatapath" not in m.group(1):
            problems.append(f(
                f"{rel}: {cls} does not inherit TransactionalDatapath",
                f"no-mixin:{cls}", rel))
        for pat in SETTLE:
            for ln, line in enumerate(text.splitlines(), 1):
                if re.search(pat, line) and not line.lstrip().startswith("#"):
                    problems.append(f(
                        f"{rel}:{ln} settles its own persistence "
                        f"({pat.replace(chr(92), '')}) — settle belongs to "
                        f"the commit plane, after the canary",
                        f"self-settle:{relpath}:{_pat_slug(pat)}", rel, ln))

    # 2: _impl call sites only inside commit.py.
    for path in src.pkg_files():
        rel = src.rel(path)
        if rel == commit_rel:
            continue
        text = src.text(path) or ""
        for name in IMPLS:
            for ln, line in enumerate(text.splitlines(), 1):
                if f"{name}(" not in line:
                    continue
                stripped = line.lstrip()
                if stripped.startswith(("def ", "#")):
                    continue  # the definition / commentary, not a call
                problems.append(f(
                    f"{rel}:{ln} calls {name}() outside datapath/commit.py "
                    f"— a tensor swap bypassing the canary gate",
                    f"bypass:{rel}:{name}", rel, ln))

    # The mixin really carries the public surface.
    for name in PUBLIC:
        if not re.search(rf"^\s*def {name}\(", commit_text, re.M):
            problems.append(f(
                f"datapath/commit.py defines no {name}()",
                f"mixin-missing:{name}", commit_rel))
    return problems
