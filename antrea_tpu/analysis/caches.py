"""Pass `bounded-cache` — executable-retaining caches must declare a
bound.

The bug class (caught by hand in PR 9 review): a module-level
`@lru_cache` whose entries hold JITTED CALLABLES retains one XLA
executable (host + device memory) per distinct key for the agent's
whole lifetime — rule shapes churn across bundle installs, so an
unbounded cache is a slow leak that no test sees and no metric names.
The PR 9 fix bounded the mesh step/canary caches; this pass makes the
discipline structural:

  * every `functools.lru_cache` / `functools.cache` decorated function
    whose body references the jit machinery (`jax.jit`, `shard_map`,
    `vmap`, `pmap` — i.e. it builds or returns compiled callables) must
    declare an explicit integer `maxsize` — `maxsize=None` and the
    unbounded bare forms are findings;
  * `functools.cache` (which HAS no bound) on such a function is always
    a finding.

Functions that cache plain host data (numpy tables, parsed literals)
are out of scope — eviction of a compiled executable merely re-traces,
so a bound is always safe to add where this pass asks for one."""

from __future__ import annotations

import ast

from .core import Finding, SourceCache, analysis_pass, apply_allowlist

# Names whose presence in a decorated function's body marks it as
# building/returning compiled callables.
JIT_MARKERS = {"jit", "vmap", "pmap", "shard_map", "_shard_map", "xla_call"}

#: obj key ("relpath:function") -> reason.
CACHE_ALLOWLIST: dict[str, str] = {}


def _decorator_cache_call(dec: ast.AST):
    """-> ("lru_cache"|"cache", Call node or None) when `dec` is a
    functools cache decorator (bare or called), else None."""
    target = dec.func if isinstance(dec, ast.Call) else dec
    name = (target.attr if isinstance(target, ast.Attribute)
            else target.id if isinstance(target, ast.Name) else None)
    if name in ("lru_cache", "cache"):
        return name, dec if isinstance(dec, ast.Call) else None
    return None


def _jit_marked(fn: ast.FunctionDef) -> bool:
    for node in ast.walk(fn):
        name = None
        if isinstance(node, ast.Attribute):
            name = node.attr
        elif isinstance(node, ast.Name):
            name = node.id
        if name in JIT_MARKERS:
            return True
    return False


def _explicit_maxsize(call: ast.Call | None) -> bool:
    """True when the decorator call declares maxsize=<int literal> (or a
    positional first arg that is an int literal)."""
    if call is None:
        return False
    for kw in call.keywords:
        if kw.arg == "maxsize":
            return (isinstance(kw.value, ast.Constant)
                    and isinstance(kw.value.value, int))
    if call.args:
        first = call.args[0]
        return (isinstance(first, ast.Constant)
                and isinstance(first.value, int))
    return False


@analysis_pass("bounded-cache", "caches retaining jitted executables "
                                "declare an explicit maxsize")
def check(src: SourceCache) -> list[Finding]:
    problems: list[Finding] = []
    for p in src.pkg_files():
        tree = src.tree(p)
        if tree is None:
            continue
        rel = src.rel(p)
        pkg_rel = str(p.relative_to(src.pkg)).replace("\\", "/")
        for node in ast.walk(tree):
            if not isinstance(node, ast.FunctionDef):
                continue
            for dec in node.decorator_list:
                hit = _decorator_cache_call(dec)
                if hit is None:
                    continue
                kind, call = hit
                if not _jit_marked(node):
                    continue  # host-data cache: out of scope
                if kind == "cache":
                    problems.append(Finding(
                        "bounded-cache", rel, node.lineno,
                        f"{node.name}() builds/returns jitted callables "
                        f"under @functools.cache, which has no bound — "
                        f"one XLA executable is retained per key forever; "
                        f"use @lru_cache(maxsize=N)",
                        obj=f"{pkg_rel}:{node.name}"))
                elif not _explicit_maxsize(call):
                    problems.append(Finding(
                        "bounded-cache", rel, node.lineno,
                        f"{node.name}() builds/returns jitted callables "
                        f"but its lru_cache declares no integer maxsize "
                        f"(bare/None = unbounded) — rule-shape churn "
                        f"retains one XLA executable per key for the "
                        f"agent's lifetime (the PR 9 leak class); "
                        f"eviction only re-traces, so bound it",
                        obj=f"{pkg_rel}:{node.name}"))
    return apply_allowlist("bounded-cache",
                           "antrea_tpu/analysis/caches.py",
                           problems, CACHE_ALLOWLIST)
