"""Core of the unified static-analysis plane.

One engine for every compile-time gate in the repo: the nine legacy
`tools/check_*.py` drift checks (migrated here as passes — the CLIs
remain as thin shims) and the semantic passes that pin the bug classes
review kept catching by hand (handler-thread reads of live engine
state, unbounded executable-retaining caches, host coercion of tracers
inside jitted bodies, donated-buffer reuse).

Design (mirrors the "verified lifting" discipline of the compiler
plane — the datapath is only trustworthy because invariants are machine
checked, and so is the repo):

  * DEPENDENCY-FREE: stdlib `ast`/`re`/`json` only, no jax, no heavy
    package import — every pass runs on any CI image, and the whole
    suite runs from the tier-1 suite (tests/test_static_analysis.py)
    in ONE invocation.
  * ONE PARSED-MODULE CACHE: `SourceCache` parses each file at most
    once per run, shared by all passes — the nine legacy tools each
    re-read and re-parsed the tree; the suite now pays one walk.
  * TYPED FINDINGS: every problem is a `Finding` with file:line, the
    pass id, a stable key and a human reason — machine-readable via
    `tools/analyze.py --json`.
  * REASONED ALLOWLISTS: a pass-level allowlist entry must carry a
    non-empty reason string; a stale entry (waiving something that no
    longer exists or no longer fires) is itself a finding.
  * BASELINE: `BASELINE.analysis.json` at the repo root suppresses
    known findings BY KEY with a reason; a baseline row that matches
    no live finding is stale and fails the build, so suppressions can
    never outlive the code they excuse.

Scanning scope note: package-wide scans (`SourceCache.pkg_files`)
exclude `antrea_tpu/analysis/` itself — the passes quote the very
patterns they police (emit kinds, forbidden call sites, metric-name
prefixes), and self-matching would make every gate trivially red.
"""

from __future__ import annotations

import ast
import json
import pathlib
import re
from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional

# Repo root when scanning ourselves (tools/ shims and tests default to
# it); every entry point also accepts an explicit root so the parity
# and seeded-violation tests can run the same passes over synthetic
# trees.
REPO = pathlib.Path(__file__).resolve().parent.parent.parent

BASELINE_NAME = "BASELINE.analysis.json"


@dataclass(frozen=True)
class Finding:
    """One problem a pass proved about the tree.

    `obj` is the stable identity of the finding (a symbol like
    "FlowCache.ts" or "TpuflowDatapath._drain_classify") — the baseline
    keys on (pass, path, obj) so line churn never invalidates a
    suppression.  Legacy-ported passes that predate symbol identities
    fall back to the reason text, which is equally stable under the
    no-drift assumption those gates exist to enforce."""

    pass_id: str
    path: str  # repo-relative, "/"-separated
    line: int
    reason: str
    obj: str = ""

    @property
    def key(self) -> str:
        return f"{self.pass_id}:{self.path}:{self.obj or self.reason}"

    def render(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"DRIFT[{self.pass_id}] {loc}: {self.reason}"


class SourceCache:
    """The one parsed-module cache of a run: text + AST per file, and
    the package file walk, each computed at most once."""

    def __init__(self, root: pathlib.Path | str = REPO):
        self.root = pathlib.Path(root)
        self.pkg = self.root / "antrea_tpu"
        self._text: dict[pathlib.Path, Optional[str]] = {}
        self._tree: dict[pathlib.Path, Optional[ast.AST]] = {}
        self._pkg_files: Optional[list[pathlib.Path]] = None

    def rel(self, path: pathlib.Path) -> str:
        return str(path.relative_to(self.root)).replace("\\", "/")

    def text(self, path: pathlib.Path) -> Optional[str]:
        """File contents, or None when missing (callers decide whether
        a missing file is itself a finding)."""
        path = pathlib.Path(path)
        if path not in self._text:
            try:
                self._text[path] = path.read_text()
            except OSError:
                self._text[path] = None
        return self._text[path]

    def tree(self, path: pathlib.Path) -> Optional[ast.AST]:
        path = pathlib.Path(path)
        if path not in self._tree:
            text = self.text(path)
            try:
                self._tree[path] = None if text is None else ast.parse(text)
            except SyntaxError:
                self._tree[path] = None
        return self._tree[path]

    def pkg_files(self) -> list[pathlib.Path]:
        """Every antrea_tpu/**/*.py EXCEPT the analysis plane itself
        (whose sources quote the patterns the passes police)."""
        if self._pkg_files is None:
            self._pkg_files = sorted(
                p for p in self.pkg.rglob("*.py")
                if "analysis" not in p.relative_to(self.pkg).parts[:1]
            )
        return self._pkg_files


# --------------------------------------------------------------------------
# Pass registry.
# --------------------------------------------------------------------------

#: pass id -> (callable(SourceCache) -> list[Finding], one-line invariant)
PASSES: dict[str, tuple[Callable[[SourceCache], list[Finding]], str]] = {}


def analysis_pass(pass_id: str, invariant: str):
    """Register `fn(src) -> list[Finding]` as a pass of the suite."""

    def deco(fn):
        if pass_id in PASSES:
            raise ValueError(f"duplicate analysis pass id {pass_id!r}")
        PASSES[pass_id] = (fn, invariant)
        fn.pass_id = pass_id
        return fn

    return deco


def pat_slug(pattern: str) -> str:
    """A regex/pattern literal reduced to a stable identifier for
    finding keys (escapes and parens stripped, dots trimmed) — keys
    must survive line churn, so passes key rogue-call-site findings on
    the PATTERN, never the line number."""
    return re.sub(r"[\\()]", "", pattern).strip(".")


def apply_allowlist(pass_id: str, path: str, findings: list[Finding],
                    allowlist: dict[str, str]) -> list[Finding]:
    """Shared allowlist discipline: drop findings whose `obj` is waived,
    require a reason on every entry, and flag stale entries (waiving an
    obj no pass run produced) — `path` attributes the allowlist table
    itself for those meta-findings."""
    seen_objs = {f.obj for f in findings}
    out = [f for f in findings if f.obj not in allowlist]
    for obj, reason in allowlist.items():
        if not (isinstance(reason, str) and reason.strip()):
            out.append(Finding(pass_id, path, 0,
                               f"allowlist entry {obj!r} carries no reason",
                               obj=f"allowlist:{obj}"))
        elif obj not in seen_objs:
            out.append(Finding(pass_id, path, 0,
                               f"allowlist entry {obj!r} waives nothing the "
                               f"pass still finds — stale waiver, drop it",
                               obj=f"allowlist-stale:{obj}"))
    return out


# --------------------------------------------------------------------------
# Baseline suppression.
# --------------------------------------------------------------------------

@dataclass
class RunResult:
    findings: list[Finding] = field(default_factory=list)  # unsuppressed
    suppressed: list[Finding] = field(default_factory=list)
    errors: list[str] = field(default_factory=list)  # baseline problems
    pass_ids: list[str] = field(default_factory=list)

    @property
    def clean(self) -> bool:
        return not self.findings and not self.errors

    def to_json(self) -> dict:
        def row(f: Finding, suppressed: bool) -> dict:
            return {"pass": f.pass_id, "path": f.path, "line": f.line,
                    "obj": f.obj, "reason": f.reason, "key": f.key,
                    "suppressed": suppressed}

        return {
            "passes": self.pass_ids,
            "clean": self.clean,
            "findings": ([row(f, False) for f in self.findings]
                         + [row(f, True) for f in self.suppressed]),
            "errors": self.errors,
        }


def load_baseline(root: pathlib.Path) -> tuple[dict[str, str], list[str]]:
    """-> ({finding key: reason}, structural problems).  A missing file
    is an empty baseline; a malformed one fails the build."""
    path = pathlib.Path(root) / BASELINE_NAME
    if not path.exists():
        return {}, []
    try:
        raw = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError) as e:
        return {}, [f"{BASELINE_NAME}: unreadable ({e})"]
    rows = raw.get("findings", raw) if isinstance(raw, dict) else None
    if not isinstance(rows, dict):
        return {}, [f"{BASELINE_NAME}: expected a JSON object mapping "
                    f"finding keys to suppression reasons"]
    problems = [
        f"{BASELINE_NAME}: entry {k!r} carries no reason"
        for k, v in rows.items()
        if not (isinstance(v, str) and v.strip())
    ]
    return dict(rows), problems


def run(root: pathlib.Path | str = REPO,
        pass_ids: Optional[Iterable[str]] = None) -> RunResult:
    """Run the selected passes (default: all, in registration order)
    over `root`, apply the baseline, and return the typed result.

    Baseline semantics: every selected pass's findings are suppressed
    by key; a baseline row whose pass was selected but whose key no
    finding produced is STALE and fails the run (rows belonging to
    unselected passes are left alone, so `--pass` stays usable)."""
    import antrea_tpu.analysis  # noqa: F401 — ensure all passes registered

    src = SourceCache(root)
    ids = list(pass_ids) if pass_ids is not None else list(PASSES)
    unknown = [i for i in ids if i not in PASSES]
    if unknown:
        raise KeyError(
            f"unknown analysis pass(es) {unknown} — registered: "
            f"{', '.join(PASSES)}")
    baseline, errors = load_baseline(src.root)
    result = RunResult(errors=list(errors), pass_ids=ids)
    matched: set[str] = set()
    for pid in ids:
        fn, _invariant = PASSES[pid]
        for f in fn(src):
            if f.key in baseline:
                matched.add(f.key)
                result.suppressed.append(f)
            else:
                result.findings.append(f)
    selected = set(ids)
    for key, _reason in baseline.items():
        kpass = key.split(":", 1)[0]
        if kpass in selected and key not in matched:
            result.errors.append(
                f"{BASELINE_NAME}: stale entry {key!r} — pass {kpass!r} no "
                f"longer produces this finding; drop the row")
        elif kpass not in PASSES:
            result.errors.append(
                f"{BASELINE_NAME}: entry {key!r} names unknown pass "
                f"{kpass!r}")
    return result


def run_cli(pass_id: str, argv: Optional[list[str]] = None) -> int:
    """The thin-shim entry point of the nine migrated tools/check_*.py
    CLIs: run ONE pass (baseline applied, exactly like the full suite),
    print findings in the legacy DRIFT format, exit 0/1 — verdict parity
    with the pre-migration tools is pinned by
    tests/test_static_analysis.py.  Accepts an optional `--root PATH`
    (the parity/seeded-violation harness) ahead of the legacy no-arg
    form."""
    argv = list(argv or [])
    root = REPO
    if "--root" in argv:
        i = argv.index("--root")
        try:
            root = pathlib.Path(argv[i + 1])
        except IndexError:
            print("usage: check_*.py [--root PATH]")
            return 2
    result = run(root, [pass_id])
    for f in result.findings:
        print(f.render())
    for e in result.errors:
        print(f"DRIFT[{pass_id}] {e}")
    if not result.clean:
        return 1
    _fn, invariant = PASSES[pass_id]
    extra = (f", {len(result.suppressed)} baselined"
             if result.suppressed else "")
    print(f"analysis pass {pass_id!r} clean: {invariant}{extra}")
    return 0
