"""Pass `thread-safety` — the apiserver handler-thread surface is
declared, and declared methods stay snapshot-only.

The agent API server (agent/apiserver.py) serves from a
ThreadingHTTPServer: every request handler runs on its OWN thread,
concurrently with the engine thread that steps traffic, swaps tenant
worlds and publishes epochs.  The bug class this pass pins is the one
PR 12 review caught by hand twice — `tenant_stats()` originally entered
`_world_ctx` from the /metrics handler (interleaving with the engine's
own swap), and the flight recorder's `spans()`/`events()` needed
seq-window snapshot reads.  Machine-checked from now on:

  1. every datapath attribute the handler thread touches (directly in
     the apiserver routes, or through the /metrics renderers in
     observability/metrics.py, which take the datapath as a parameter
     and run on the handler thread) must be DECLARED in the
     `HANDLER_SAFE` literal of agent/apiserver.py — growing the
     operator surface means consciously adding to the contract;
  2. a declared name nobody touches is a stale declaration (finding);
  3. the BODY of every declared method (searched across the datapath/,
     parallel/ and observability/ subtrees) must not enter
     `_world_ctx(` (a world swap on a handler thread races the engine
     thread's swap) and must not assign `self.<attr>` (handler threads
     read snapshots; engine-state mutation belongs to the engine
     thread) — unless waived in THREAD_ALLOWLIST with a reason.

Scope note: the body check is ONE level deep on purpose — it gates the
declared surface's own discipline (the level where both hand-caught
bugs lived); transitive callees are engine code shared with the engine
thread and are the allowlisted tick endpoints' documented risk."""

from __future__ import annotations

import ast

from .core import Finding, SourceCache, analysis_pass, apply_allowlist

# Handler-thread entry points: Handler.do_GET -> outer._route -> these.
HANDLER_ENTRY_METHODS = ("_route", "_json_route", "_live_traceflow")

# Subtrees whose `def <name>(self` bodies implement the declared
# surface.  Controller/agent-side classes reuse method names like
# `stats` for unrelated surfaces and are not reachable through the
# datapath object the handlers hold.
IMPL_SUBTREES = ("datapath", "parallel", "observability")

# Modules whose MODULE-LEVEL functions receive the live datapath as a
# `datapath` parameter FROM a handler route and therefore run on the
# handler thread: the /metrics renderers and the /agentinfo collector.
# Their datapath reads count toward the HANDLER_SAFE contract exactly
# like the apiserver's own `self._dp` touches.
RENDER_MODULES = ("observability/metrics.py", "observability/agentinfo.py")

# obj key -> reason.  Keys are the finding objs below
# ("world-ctx:Class.method" / "mutates:Class.method:attr").  Empty at
# HEAD: every declared surface already serves from snapshots (the PR 8/
# PR 12 hardening) — future waivers must explain why a handler-thread
# write or swap is safe.
THREAD_ALLOWLIST: dict[str, str] = {}


def _attr_of_dp(node: ast.AST, dp_check) -> str | None:
    """`<dp>.attr` -> attr when the value matches dp_check."""
    if isinstance(node, ast.Attribute) and dp_check(node.value):
        return node.attr
    return None


def _collect_used(body: list[ast.stmt], dp_check) -> dict[str, int]:
    """Datapath attribute paths touched in a handler-thread body ->
    first line.  One level of local aliasing is followed
    (`tracer = self._dp.realization_tracer; tracer.spans(...)` counts
    as `realization_tracer.spans`)."""
    used: dict[str, int] = {}
    aliases: dict[str, str] = {}
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                attr = _attr_of_dp(node.value, dp_check)
                if attr is not None:
                    aliases[node.targets[0].id] = attr
            if isinstance(node, ast.Attribute):
                attr = _attr_of_dp(node, dp_check)
                if attr is not None:
                    used.setdefault(attr, node.lineno)
                elif (isinstance(node.value, ast.Name)
                      and node.value.id in aliases):
                    used.setdefault(
                        f"{aliases[node.value.id]}.{node.attr}", node.lineno)
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "getattr"
                    and len(node.args) >= 2
                    and dp_check(node.args[0])
                    and isinstance(node.args[1], ast.Constant)
                    and isinstance(node.args[1].value, str)):
                used.setdefault(node.args[1].value, node.lineno)
    return used


def _handler_safe(tree: ast.AST):
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "HANDLER_SAFE"
                for t in node.targets):
            return ast.literal_eval(node.value)
    return None


def _is_self_dp(node: ast.AST) -> bool:
    return (isinstance(node, ast.Attribute) and node.attr == "_dp"
            and isinstance(node.value, ast.Name) and node.value.id == "self")


def _iter_methods(tree: ast.AST):
    """(class name, FunctionDef) for every method in a module."""
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            for stmt in node.body:
                if isinstance(stmt, ast.FunctionDef):
                    yield node.name, stmt


@analysis_pass("thread-safety", "apiserver handler threads touch only the "
                                "declared snapshot-safe surface")
def check(src: SourceCache) -> list[Finding]:
    api_rel = "antrea_tpu/agent/apiserver.py"
    api_path = src.pkg / "agent" / "apiserver.py"
    tree = src.tree(api_path)
    if tree is None:
        return [Finding("thread-safety", api_rel, 0,
                        f"{api_rel} is missing/unparseable", obj="missing")]
    declared = _handler_safe(tree)
    if declared is None:
        return [Finding(
            "thread-safety", api_rel, 0,
            "agent/apiserver.py declares no HANDLER_SAFE literal — the "
            "handler-thread surface must be an explicit contract",
            obj="no-handler-safe")]
    declared = tuple(declared)

    problems: list[Finding] = []

    # 1. collect every datapath attr the handler thread touches.
    used: dict[str, tuple[str, int]] = {}
    for cls, meth in _iter_methods(tree):
        if meth.name not in HANDLER_ENTRY_METHODS:
            continue
        for attr, line in _collect_used(meth.body, _is_self_dp).items():
            used.setdefault(attr, (api_rel, line))
    # Handler-thread helpers handed the live object: the /metrics
    # renderers and the /agentinfo collector (the apiserver passes
    # self._dp bare into them, so their own `datapath.<attr>` reads ARE
    # handler-thread touches).
    def _is_dp_name(n: ast.AST) -> bool:
        return isinstance(n, ast.Name) and n.id == "datapath"

    for mod in RENDER_MODULES:
        mod_rel = f"antrea_tpu/{mod}"
        mod_tree = src.tree(src.pkg / mod)
        if mod_tree is None:
            continue
        for node in ast.iter_child_nodes(mod_tree):
            if not (isinstance(node, ast.FunctionDef)
                    and "datapath" in [a.arg for a in node.args.args]):
                continue
            for attr, line in _collect_used(node.body, _is_dp_name).items():
                used.setdefault(attr, (mod_rel, line))

    for attr in sorted(set(used) - set(declared)):
        path, line = used[attr]
        problems.append(Finding(
            "thread-safety", path, line,
            f"handler thread touches datapath.{attr} but HANDLER_SAFE "
            f"(agent/apiserver.py) does not declare it — every handler-"
            f"reachable surface must be a conscious snapshot-safe "
            f"contract", obj=f"undeclared:{attr}"))
    for attr in sorted(set(declared) - set(used)):
        problems.append(Finding(
            "thread-safety", api_rel, 0,
            f"HANDLER_SAFE declares {attr!r} but no handler-thread path "
            f"touches it — stale declaration", obj=f"stale:{attr}"))

    # 3. body discipline of every declared method.
    wanted = {entry.split(".")[-1] for entry in declared}
    for p in src.pkg_files():
        parts = p.relative_to(src.pkg).parts
        if not parts or parts[0] not in IMPL_SUBTREES:
            continue
        mtree = src.tree(p)
        if mtree is None:
            continue
        rel = src.rel(p)
        for cls, meth in _iter_methods(mtree):
            if meth.name not in wanted:
                continue
            for node in ast.walk(meth):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "_world_ctx"):
                    problems.append(Finding(
                        "thread-safety", rel, node.lineno,
                        f"{cls}.{meth.name} enters _world_ctx() but is "
                        f"HANDLER_SAFE-declared — a world swap on the "
                        f"handler thread interleaves with the engine "
                        f"thread's own swap (the tenant_stats race class); "
                        f"read the stored world snapshots instead",
                        obj=f"world-ctx:{cls}.{meth.name}"))
                targets = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    targets = [node.target]
                for t in targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        problems.append(Finding(
                            "thread-safety", rel, node.lineno,
                            f"{cls}.{meth.name} assigns self.{t.attr} but "
                            f"is HANDLER_SAFE-declared — handler threads "
                            f"must read snapshots, never mutate engine "
                            f"state; move the write to the engine thread "
                            f"or waive with a reason",
                            obj=f"mutates:{cls}.{meth.name}:{t.attr}"))
    return apply_allowlist("thread-safety",
                           "antrea_tpu/analysis/threads.py",
                           problems, THREAD_ALLOWLIST)
