"""Pass `maintenance` — every background loop runs ONLY via the unified
scheduler (migrated from tools/check_maintenance.py, which remains as a
shim).

PR 7's consolidation guarantee (datapath/maintenance.py) only holds if
no plane grows a private cadence again: a direct call site of the
off-hot-step loop entry points anywhere under antrea_tpu/ outside the
scheduler module re-introduces exactly the plane-vs-plane interleaving
races the scheduler's single serialization point retired.  MAINT_TASKS
must name every consolidated loop, every inventoried task must be
constructed, both engines mix the scheduler in, and the forbidden call
patterns appear only at their allowlisted delegation sites."""

from __future__ import annotations

import ast
import re

from .core import Finding, SourceCache, analysis_pass
from .core import pat_slug as _pat_slug

ENGINES = {
    "datapath/tpuflow.py": "TpuflowDatapath",
    "datapath/oracle_dp.py": "OracleDatapath",
}

REQUIRED_TASKS = {
    "canary", "audit-cursor", "tensor-scrub", "cache-maintain",
    "fqdn-ttl", "degraded-recompile",
}

# pattern -> set of package-relative paths allowed to carry it (the
# scheduler module itself is always exempt).
FORBIDDEN = {
    r"\.canary_scan\(": {"datapath/commit.py"},
    # interface.py: the Datapath base default for maintenance_force_audit
    # — the fallback for audit-capable datapaths WITHOUT a scheduler
    # (nothing to serialize against); both engines override through the
    # mixin, which routes via MaintenanceScheduler.force.
    r"\.audit_scan\(": {"datapath/interface.py"},
    r"\.maintain\(": {"datapath/slowpath/engine.py"},
    r"\.tick\(": {"agent/fqdn.py"},
}


def load_tasks(text: str) -> dict:
    m = re.search(r"^MAINT_TASKS\s*(?::[^=]+)?=\s*(\{.*?^\})", text,
                  re.M | re.S)
    if m is None:
        raise ValueError(
            "datapath/maintenance.py defines no MAINT_TASKS literal")
    return ast.literal_eval(m.group(1))


@analysis_pass("maintenance", "every background loop runs only via the "
                              "unified maintenance scheduler")
def check(src: SourceCache) -> list[Finding]:
    maint_rel = "antrea_tpu/datapath/maintenance.py"
    maint_text = src.text(src.pkg / "datapath" / "maintenance.py")
    if not maint_text:
        return [Finding("maintenance", maint_rel, 0,
                        f"{maint_rel} is missing", obj="missing")]

    def f(reason, obj, path=maint_rel, line=0):
        return Finding("maintenance", path, line, reason, obj=obj)

    try:
        tasks = load_tasks(maint_text)
    except ValueError as e:
        return [f(str(e), "no-task-table")]

    problems: list[Finding] = []
    for name in sorted(REQUIRED_TASKS - set(tasks)):
        problems.append(f(
            f"MAINT_TASKS is missing the consolidated loop {name!r}",
            f"missing-task:{name}"))
    for name, plane in tasks.items():
        if not (isinstance(plane, str) and plane.strip()):
            problems.append(f(
                f"MAINT_TASKS[{name!r}] names no owning plane",
                f"no-plane:{name}"))

    # Every inventoried task must be constructed somewhere in the package.
    ctor = re.compile(r"MaintenanceTask\(\s*\n?\s*[\"']([a-z-]+)[\"']")
    constructed: set[str] = set()
    for p in src.pkg_files():
        constructed |= set(ctor.findall(src.text(p) or ""))
    for name in sorted(set(tasks) - constructed):
        problems.append(f(
            f"MAINT_TASKS names {name!r} but no MaintenanceTask("
            f"\"{name}\", ...) is registered anywhere under antrea_tpu/",
            f"unconstructed:{name}"))

    for relpath, cls in ENGINES.items():
        rel = f"antrea_tpu/{relpath}"
        text = src.text(src.pkg / relpath) or ""
        m = re.search(rf"^class {cls}\(([^)]*)\)", text, re.M | re.S)
        if m is None or "MaintainableDatapath" not in m.group(1):
            problems.append(f(
                f"{rel}: {cls} does not inherit MaintainableDatapath",
                f"no-mixin:{cls}", rel))
        if "_init_maintenance(" not in text:
            problems.append(f(
                f"{rel}: {cls} never calls _init_maintenance",
                f"no-init:{cls}", rel))

    for p in src.pkg_files():
        rel = str(p.relative_to(src.pkg)).replace("\\", "/")
        if rel == "datapath/maintenance.py":
            continue
        text = src.text(p) or ""
        for pat, allowed in FORBIDDEN.items():
            if rel in allowed:
                continue
            for ln, line in enumerate(text.splitlines(), 1):
                stripped = line.strip()
                if stripped.startswith("#"):
                    continue
                if re.search(pat, line):
                    problems.append(f(
                        f"antrea_tpu/{rel}:{ln}: direct background-loop "
                        f"call site ({pat}) outside the maintenance "
                        f"scheduler — register a MaintenanceTask and run "
                        f"it via MaintenanceScheduler.tick() instead",
                        f"rogue:{rel}:{_pat_slug(pat)}",
                        f"antrea_tpu/{rel}", ln))
    return problems
