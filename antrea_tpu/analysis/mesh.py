"""Pass `mesh` — partition-spec drift: every sharded pytree field is
specced (migrated from tools/check_mesh.py, which remains as a shim).

The multichip datapath (parallel/mesh.py + parallel/meshpath.py) places
three pytrees on the (data × rule) mesh under the PartitionSpecs built
by `_state_specs` / `_drs_specs` / `_svc_specs`.  Those builders
enumerate every field BY NAME on purpose: a field that is merely
splatted would let a new single-chip state column ship
replicated-by-accident (or sharded on the wrong axis) the first time
someone grows a NamedTuple.  Fails when any field of the tracked
NamedTuples is neither named as a keyword in a spec builder nor waived
in `mesh.MESH_SPEC_ALLOWLIST` with a reason — and when the allowlist
itself goes stale."""

from __future__ import annotations

import ast

from .core import Finding, SourceCache, analysis_pass

# NamedTuples whose fields must be specced, per defining module (package
# relative).  The nested leaf types are tracked alongside their
# containers so a field added anywhere in the tree is caught.
TRACKED = {
    "models/pipeline.py": (
        "PipelineState", "FlowCache", "AffinityTable", "DeviceServiceTables",
    ),
    "ops/match.py": (
        "DeviceRuleSet", "DeviceDirection", "DimTable", "IsoTable",
        "DeltaTable",
    ),
}

SPEC_BUILDERS = ("_state_specs", "_drs_specs", "_svc_specs")


def namedtuple_fields(src: SourceCache, relpath: str, classes) -> dict:
    """class name -> ordered field names (AnnAssign rows of NamedTuple
    class bodies)."""
    tree = src.tree(src.pkg / relpath)
    out: dict[str, list[str]] = {}
    if tree is None:
        return out
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef) or node.name not in classes:
            continue
        out[node.name] = [
            stmt.target.id
            for stmt in node.body
            if isinstance(stmt, ast.AnnAssign)
            and isinstance(stmt.target, ast.Name)
        ]
    return out


def specced_kwargs(src: SourceCache) -> dict:
    """Constructor-class name -> keyword-argument names used at its call
    sites inside the spec builder functions of parallel/mesh.py.  Keyed
    PER CLASS (the callee's name), not pooled: field names legitimately
    collide across the tracked NamedTuples, and a pooled set would let a
    new field ride a same-named field of a DIFFERENT class through the
    gate unspecced."""
    tree = src.tree(src.pkg / "parallel" / "mesh.py")
    by_class: dict[str, set] = {}
    if tree is None:
        return by_class
    for node in ast.walk(tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        if node.name not in SPEC_BUILDERS:
            continue
        for call in ast.walk(node):
            if not isinstance(call, ast.Call):
                continue
            fn = call.func
            name = (fn.attr if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else None)
            if name is None:
                continue
            by_class.setdefault(name, set()).update(
                kw.arg for kw in call.keywords if kw.arg)
    return by_class


def allowlist(src: SourceCache) -> dict:
    tree = src.tree(src.pkg / "parallel" / "mesh.py")
    if tree is None:
        raise ValueError("antrea_tpu/parallel/mesh.py is missing/unparseable")
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target,
                                                           ast.Name):
            targets = [node.target.id]
        else:
            continue
        if "MESH_SPEC_ALLOWLIST" in targets and node.value is not None:
            return ast.literal_eval(node.value)
    raise ValueError("parallel/mesh.py defines no MESH_SPEC_ALLOWLIST literal")


@analysis_pass("mesh", "every sharded pytree field carries an explicit "
                       "PartitionSpec or a reasoned waiver")
def check(src: SourceCache) -> list[Finding]:
    mesh_rel = "antrea_tpu/parallel/mesh.py"

    def f(reason, obj="", path=mesh_rel, line=0):
        return Finding("mesh", path, line, reason, obj=obj)

    try:
        waived = allowlist(src)
    except (OSError, ValueError) as e:
        return [f(str(e), obj="no-allowlist")]
    specced = specced_kwargs(src)
    if not specced:
        return [f(f"parallel/mesh.py spec builders {SPEC_BUILDERS} name no "
                  f"fields at all", obj="no-spec-builders")]

    problems: list[Finding] = []
    qualified: set[str] = set()  # "Class.field" of every tracked field
    for relpath, classes in TRACKED.items():
        fields_by_class = namedtuple_fields(src, relpath, classes)
        for cls in classes:
            if cls not in fields_by_class:
                problems.append(f(
                    f"antrea_tpu/{relpath} no longer defines {cls} — update "
                    f"the analysis mesh pass's TRACKED table",
                    obj=f"missing-class:{cls}",
                    path=f"antrea_tpu/{relpath}"))
                continue
            for field in fields_by_class[cls]:
                qualified.add(f"{cls}.{field}")
                if (field in specced.get(cls, ())
                        or f"{cls}.{field}" in waived):
                    continue
                problems.append(f(
                    f"{cls}.{field} (antrea_tpu/{relpath}) has no explicit "
                    f"PartitionSpec at a {cls}(...) call in parallel/mesh.py "
                    f"{SPEC_BUILDERS} and no MESH_SPEC_ALLOWLIST waiver — it "
                    f"would ship on the mesh with an accidental layout",
                    obj=f"{cls}.{field}"))

    for key, reason in waived.items():
        cls, _, field = key.partition(".")
        if key not in qualified:
            problems.append(f(
                f"MESH_SPEC_ALLOWLIST waives {key!r} (expected 'Class.field' "
                f"of a tracked NamedTuple) — stale waiver",
                obj=f"stale-waiver:{key}"))
        elif field in specced.get(cls, ()):
            problems.append(f(
                f"MESH_SPEC_ALLOWLIST waives {key!r}, but it IS specced in "
                f"the builders — drop the stale waiver",
                obj=f"specced-waiver:{key}"))
        if not (isinstance(reason, str) and reason.strip()):
            problems.append(f(
                f"MESH_SPEC_ALLOWLIST waiver {key!r} carries no reason",
                obj=f"reasonless-waiver:{key}"))
    return problems
