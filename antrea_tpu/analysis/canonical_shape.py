"""Pass `canonical-shape` — jitted traffic-path dispatches take
canonical batch shapes.

The bug class (ROADMAP item 3, closed by the serving batcher): a
dispatch site that feeds a TRAFFIC-SHAPED lane subset straight into the
jitted step — `self.step(_sub_batch(batch, sel), now)` — makes the
per-call batch dimension whatever traffic produced, so the XLA
executable count tracks tenant arrival patterns instead of anything
declared.  The pre-batcher `step_tenants` was exactly this shape: one
fresh compile per distinct per-tenant lane count.

The rule made structural: no `.step(...)` / `.tenant_step(...)` call
may receive a batch built by `_sub_batch(...)` — neither inline nor
through a local name assigned from it.  Re-shaping lane subsets for
dispatch belongs to the serving batcher (`serving/batcher.py`), which
pads onto the declared pow2 canonical ladder and masks the padding via
`valid`; staging a sub-batch into the batcher (`submit(_sub_batch(...))`)
is the sanctioned pattern and is not a dispatch, so it never matches.
"""

from __future__ import annotations

import ast

from .core import Finding, SourceCache, analysis_pass, apply_allowlist

# The jitted traffic-path dispatch surface.
DISPATCH_METHODS = {"step", "tenant_step"}

# The lane-subset constructor whose output is traffic-shaped.
SUBSET_BUILDERS = {"_sub_batch"}

#: obj key ("relpath:scope:method") -> reason.
SHAPE_ALLOWLIST: dict[str, str] = {}


def _call_name(node: ast.AST):
    """Callable's terminal name for Call nodes (Name or Attribute)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _is_subset_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and _call_name(node.func) in SUBSET_BUILDERS)


def _scan_function(fn: ast.FunctionDef, rel: str, pkg_rel: str,
                   problems: list) -> None:
    # Local names holding a traffic-shaped subset: assigned (directly or
    # tuple-unpacked is out of scope — the builder returns one value)
    # from a SUBSET_BUILDERS call anywhere in this function body.
    tainted: set[str] = set()
    for node in ast.walk(fn):
        if (isinstance(node, ast.Assign) and _is_subset_call(node.value)):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    tainted.add(tgt.id)
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        callee = _call_name(node.func)
        if (callee not in DISPATCH_METHODS
                or not isinstance(node.func, ast.Attribute)):
            continue
        for arg in node.args:
            traffic_shaped = (
                _is_subset_call(arg)
                or (isinstance(arg, ast.Name) and arg.id in tainted))
            if traffic_shaped:
                problems.append(Finding(
                    "canonical-shape", rel, node.lineno,
                    f"{fn.name}() dispatches a _sub_batch()-shaped batch "
                    f"through .{callee}() — the jit batch dimension then "
                    f"tracks traffic, one XLA executable per distinct "
                    f"lane count (the pre-batcher step_tenants compile "
                    f"storm); stage the subset into the serving batcher "
                    f"(submit + flush packs it onto the canonical pow2 "
                    f"ladder, padding masked via valid) instead",
                    obj=f"{pkg_rel}:{fn.name}:{callee}"))
                break


@analysis_pass("canonical-shape", "jitted traffic-path dispatches take "
                                  "pow2-padded or declared-canonical "
                                  "batch shapes")
def check(src: SourceCache) -> list[Finding]:
    problems: list[Finding] = []
    for p in src.pkg_files():
        tree = src.tree(p)
        if tree is None:
            continue
        rel = src.rel(p)
        pkg_rel = str(p.relative_to(src.pkg)).replace("\\", "/")
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _scan_function(node, rel, pkg_rel, problems)
    return apply_allowlist("canonical-shape",
                           "antrea_tpu/analysis/canonical_shape.py",
                           problems, SHAPE_ALLOWLIST)
