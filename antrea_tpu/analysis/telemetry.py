"""Pass `telemetry-registry` — hot-path telemetry schema drift.

The telemetry plane spans four layers that must agree on ONE counter
schema: the kernel's `tel_*` outputs (models/pipeline.py), the
TelemetryPlane accumulator literal (observability/telemetry.py
TELEMETRY_COUNTERS — the plane builds its counter dict from it, so the
literal IS the accumulator set), the registered metric families
(observability/metrics.METRICS `antrea_tpu_telemetry_<name>_total`),
and the operator documentation (README counter table).  A counter added
in any one layer without the other three silently renders as zero or
scrapes as an unregistered family — this pass fails the build instead.
Regime names (REGIMES) must likewise each carry a README row, and the
sentinel's histogram/regression families must stay registered."""

from __future__ import annotations

import re

from .core import Finding, SourceCache, analysis_pass
from .events import _literal

TELEMETRY_REL = "antrea_tpu/observability/telemetry.py"
KERNEL_REL = "antrea_tpu/models/pipeline.py"
METRICS_REL = "antrea_tpu/observability/metrics.py"

# Kernel emit sites: out-dict stores with a literal "tel_<name>" key.
TEL_KEY_RE = re.compile(r"\"tel_([a-z0-9_]+)\"")

# The families the sentinel/regime plane registers beyond the per-counter
# totals.
EXTRA_FAMILIES = (
    "antrea_tpu_telemetry_regime_step_seconds",
    "antrea_tpu_telemetry_perf_regressions_total",
)


@analysis_pass("telemetry-registry",
               "kernel tel_* outputs == TelemetryPlane accumulators == "
               "metric families == README counter/regime rows")
def check(src: SourceCache) -> list[Finding]:
    def f(reason, obj, path=TELEMETRY_REL):
        return Finding("telemetry-registry", path, 0, reason, obj=obj)

    try:
        counters = _literal(src, src.pkg / "observability" / "telemetry.py",
                            "TELEMETRY_COUNTERS")
        regimes = _literal(src, src.pkg / "observability" / "telemetry.py",
                           "REGIMES")
        registry = _literal(src, src.pkg / "observability" / "metrics.py",
                            "METRICS")
    except (OSError, ValueError) as e:
        return [f(str(e), "literal-unreadable")]
    kernel_text = src.text(src.pkg / "models" / "pipeline.py")
    if kernel_text is None:
        return [f(f"{KERNEL_REL} is missing", "kernel-unreadable",
                  KERNEL_REL)]
    readme = src.text(src.root / "README.md") or ""

    problems: list[Finding] = []

    # Layer 1: kernel outputs <-> the accumulator literal.
    kernel = set(TEL_KEY_RE.findall(kernel_text))
    for name in sorted(kernel - set(counters)):
        problems.append(f(
            f"kernel emits tel_{name} but TELEMETRY_COUNTERS does not "
            f"declare {name!r} — the plane would drop it on account()",
            f"undeclared:{name}", KERNEL_REL))
    for name in sorted(set(counters) - kernel):
        problems.append(f(
            f"TELEMETRY_COUNTERS declares {name!r} but no kernel site "
            f"emits tel_{name} — dead accumulator, renders 0 forever",
            f"unmeasured:{name}"))

    # Layer 2: one registered counter family per declared counter, and
    # the renderer's name->family map covers exactly the declared set
    # (a missing key raises at render time; a stale one renders a dead
    # family).
    try:
        families = _literal(src, src.pkg / "observability" / "metrics.py",
                            "_TELEMETRY_FAMILIES")
    except (OSError, ValueError) as e:
        return problems + [f(str(e), "families-unreadable", METRICS_REL)]
    for name in sorted(set(counters) - set(families)):
        problems.append(f(
            f"_TELEMETRY_FAMILIES has no entry for declared counter "
            f"{name!r} — render_metrics would KeyError on it",
            f"family-unmapped:{name}", METRICS_REL))
    for name in sorted(set(families) - set(counters)):
        problems.append(f(
            f"_TELEMETRY_FAMILIES maps {name!r} which TELEMETRY_COUNTERS "
            f"does not declare — dead map row",
            f"family-stale:{name}", METRICS_REL))
    for name, fam in sorted(families.items()):
        if fam not in registry:
            problems.append(f(
                f"{fam} is not registered in observability/metrics.METRICS",
                f"family-unregistered:{name}", METRICS_REL))
    for fam in EXTRA_FAMILIES:
        if fam not in registry:
            problems.append(f(
                f"{fam} is not registered in observability/metrics.METRICS",
                f"family-unregistered:{fam}", METRICS_REL))

    # Layer 3: operator documentation — a README row per counter, per
    # regime, and per family (the Hot-path telemetry section).
    for name in counters:
        if f"`{name}`" not in readme:
            problems.append(f(
                f"counter {name!r} has no README row (counter table in "
                f"the Hot-path telemetry section)",
                f"undocumented:{name}", "README.md"))
    for regime in regimes:
        if f"`{regime}`" not in readme:
            problems.append(f(
                f"regime {regime!r} has no README row (regime table in "
                f"the Hot-path telemetry section)",
                f"regime-undocumented:{regime}", "README.md"))
    for name, fam in sorted(families.items()):
        if fam not in readme:
            problems.append(f(f"{fam} has no README row",
                              f"family-undocumented:{name}", "README.md"))
    for fam in EXTRA_FAMILIES:
        if fam not in readme:
            problems.append(f(f"{fam} has no README row",
                              f"family-undocumented:{fam}", "README.md"))
    return problems
