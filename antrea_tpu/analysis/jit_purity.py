"""Pass `jit-purity` — no host coercion of tracers, no Python-side
state mutation, inside jit-compiled bodies.

The bug class: a `.item()` / `int(...)` / `float(...)` / `bool(...)`
on a traced value inside a function handed to `jax.jit` / `shard_map`
forces a device->host sync per call (the boxed-int-on-the-traffic-path
class PR 12 review caught in the reshard dirty tracking: a set-based
host structure boxed ints on every dispatch), and a `self.<attr>`
assignment inside a traced body runs ONCE at trace time, then silently
never again — both are invisible to every parity test because the
verdicts stay right; only the latency (or the stale attribute) is
wrong.

Detection (one module at a time, the granularity the repo's jit usage
actually has):

  * a function is JITTED when it is decorated with `jax.jit` (bare or
    via functools.partial) or its name is passed to a `jit` /
    `shard_map` / `_shard_map` / `vmap` / `pmap` call in the module
    (`pipeline_step = jax.jit(_pipeline_step, ...)`), including the
    local `body` functions handed to `_shard_map(...)` inside cached
    builders;
  * `static_argnames=` / `static_argnums=` literals at the jit site
    exclude those parameters from the tracer set (coercing a STATIC
    argument is host-side and legal — `int(meta.miss_chunk)` stays
    fine);
  * findings inside a jitted body: `.item()` anywhere; `int()` /
    `float()` / `bool()` whose argument expression mentions a tracer
    parameter; `self.<attr>` assignment; `global` / `nonlocal`
    declarations."""

from __future__ import annotations

import ast

from .core import Finding, SourceCache, analysis_pass, apply_allowlist

JIT_CALLEES = {"jit", "shard_map", "_shard_map", "vmap", "pmap"}
COERCIONS = ("int", "float", "bool")

#: obj key ("relpath:function:detail") -> reason.
PURITY_ALLOWLIST: dict[str, str] = {}


def _callee_name(call: ast.Call) -> str | None:
    fn = call.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _static_names(call: ast.Call, fn: ast.FunctionDef) -> set[str]:
    """Parameter names the jit site marks static (by name or position)."""
    params = [a.arg for a in (fn.args.posonlyargs + fn.args.args)]
    out: set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            try:
                names = ast.literal_eval(kw.value)
            except ValueError:
                continue
            out |= set((names,) if isinstance(names, str) else names)
        elif kw.arg == "static_argnums":
            try:
                nums = ast.literal_eval(kw.value)
            except ValueError:
                continue
            for i in ((nums,) if isinstance(nums, int) else nums):
                if 0 <= i < len(params):
                    out.add(params[i])
    return out


def _jitted_functions(tree: ast.AST):
    """-> [(FunctionDef, static param names, how)] for every function
    the module jits: decorated, or referenced by name at a jit/shard_map
    call site anywhere in the module (matched per enclosing scope would
    be stricter; per module matches how the repo names things — the
    `_pipeline_step` / `body` pattern)."""
    by_name: dict[str, list[ast.FunctionDef]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            by_name.setdefault(node.name, []).append(node)

    out = []
    seen: set[int] = set()

    def add(fn: ast.FunctionDef, statics: set[str], how: str):
        if id(fn) not in seen:
            seen.add(id(fn))
            out.append((fn, statics, how))

    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                target = dec
                statics: set[str] = set()
                if isinstance(dec, ast.Call):
                    # @partial(jax.jit, static_argnames=...) or @jax.jit(...)
                    inner = [a for a in dec.args
                             if _callee_name_node(a) in JIT_CALLEES]
                    if _callee_name(dec) == "partial" and inner:
                        add(node, _static_names(dec, node), "decorator")
                        continue
                    target = dec.func
                    statics = _static_names(dec, node)
                if _callee_name_node(target) in JIT_CALLEES:
                    add(node, statics, "decorator")
        if isinstance(node, ast.Call) and _callee_name(node) in JIT_CALLEES:
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    for fn in by_name.get(arg.id, ()):
                        add(fn, _static_names(node, fn), "call")
    return out


def _callee_name_node(node: ast.AST) -> str | None:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _mentions(expr: ast.AST, names: set[str]) -> bool:
    return any(isinstance(n, ast.Name) and n.id in names
               for n in ast.walk(expr))


@analysis_pass("jit-purity", "jitted bodies never coerce tracers to host "
                             "scalars or mutate Python state")
def check(src: SourceCache) -> list[Finding]:
    problems: list[Finding] = []
    for p in src.pkg_files():
        tree = src.tree(p)
        if tree is None:
            continue
        rel = src.rel(p)
        pkg_rel = str(p.relative_to(src.pkg)).replace("\\", "/")
        for fn, statics, _how in _jitted_functions(tree):
            params = {a.arg for a in (fn.args.posonlyargs + fn.args.args
                                      + fn.args.kwonlyargs)} - statics
            for node in ast.walk(fn):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "item"):
                    problems.append(Finding(
                        "jit-purity", rel, node.lineno,
                        f"{fn.name}() is jitted but calls .item() — a "
                        f"device->host sync inside the traced body (the "
                        f"boxed-scalar-on-the-traffic-path class)",
                        obj=f"{pkg_rel}:{fn.name}:item"))
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id in COERCIONS
                        and node.args
                        and _mentions(node.args[0], params)):
                    problems.append(Finding(
                        "jit-purity", rel, node.lineno,
                        f"{fn.name}() is jitted but coerces a traced "
                        f"parameter with {node.func.id}() — host boxing "
                        f"inside the traced body; keep it a jnp array "
                        f"(static arguments are exempt via "
                        f"static_argnames/static_argnums)",
                        obj=f"{pkg_rel}:{fn.name}:{node.func.id}"))
                targets = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    targets = [node.target]
                for t in targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        problems.append(Finding(
                            "jit-purity", rel, node.lineno,
                            f"{fn.name}() is jitted but assigns "
                            f"self.{t.attr} — the write runs once at "
                            f"trace time and never again",
                            obj=f"{pkg_rel}:{fn.name}:self.{t.attr}"))
                if isinstance(node, (ast.Global, ast.Nonlocal)):
                    problems.append(Finding(
                        "jit-purity", rel, node.lineno,
                        f"{fn.name}() is jitted but declares "
                        f"{'global' if isinstance(node, ast.Global) else 'nonlocal'} "
                        f"{', '.join(node.names)} — Python-side mutation "
                        f"inside a traced body runs once at trace time",
                        obj=f"{pkg_rel}:{fn.name}:mutation"))
    return apply_allowlist("jit-purity",
                           "antrea_tpu/analysis/jit_purity.py",
                           problems, PURITY_ALLOWLIST)
