"""Pass `bounded-buffer` — scanned-module buffers must declare their cap.

The bug class (the storm-soak round's structural lesson): the
dissemination plane sits between an unbounded producer (controller
churn) and slow consumers (10k agents on real sockets), so ANY
buffering structure in it — watcher queues, framing buffers, resync
cursors — is a fleet-wide memory liability unless something bounds it.
The watcher-overflow cap, the coalescing dict and the cursor snapshot
each earned an explicit bound; this pass makes the discipline
structural instead of reviewed-by-hand.  The replica-loss failover
plane (parallel/failover.py) joined the scan set: its probe-history
ring sits between an unbounded producer (every maintenance tick for the
engine's whole lifetime) and a consumer that may never read it
(supportbundle/debug), the same liability class.

  * every buffer-shaped instance attribute assigned in a scanned module
    (SCANNED_PREFIXES) — `self.<attr> = <container builder>`
    where <attr> smells like a buffer (queue/buf/pending/backlog/
    latest/cursor/inbox/ring/keys) and the value constructs a
    container (call, list/dict/set literal or comprehension, bytes
    literal) — must carry a row in that module's `BUFFER_CAPS` dict
    ("Class.attr" -> one-line reason naming the bound), or a reasoned
    allowlist entry here;
  * a stale `BUFFER_CAPS` row naming an attribute the module no longer
    assigns is itself a finding — declarations cannot outlive the
    buffers they excuse (the same discipline as the baseline file).
"""

from __future__ import annotations

import ast
import re

from .core import Finding, SourceCache, analysis_pass, apply_allowlist

# Attribute names that mark an instance attribute as a buffer.
BUFFER_RE = re.compile(
    r"queue|buf|pending|backlog|latest|cursor|inbox|ring|keys",
    re.IGNORECASE)

#: obj key ("relpath:Class.attr") -> reason.
BUFFER_ALLOWLIST: dict[str, str] = {}

# Modules the pass scans: whole packages (trailing "/") or single files.
# Growing this set is deliberate API — a new plane that buffers between
# an unbounded producer and a maybe-never consumer earns its entry here,
# and its module then owes BUFFER_CAPS rows.
SCANNED_PREFIXES = (
    "dissemination/",
    "parallel/failover.py",
)


def _is_container_builder(value: ast.AST) -> bool:
    """True when the assigned value constructs a growable container:
    any call (deque(), list(), bytearray(), factory...), a literal
    list/dict/set, a comprehension, or a bytes/str constant (framing
    accumulators start as b"")."""
    if isinstance(value, (ast.Call, ast.List, ast.Dict, ast.Set,
                          ast.ListComp, ast.DictComp, ast.SetComp,
                          ast.GeneratorExp)):
        return True
    return (isinstance(value, ast.Constant)
            and isinstance(value.value, (bytes, str)))


def _buffer_caps(tree: ast.AST) -> tuple[dict, int]:
    """-> (the module's BUFFER_CAPS literal, its line) — ({}, 0) when
    absent or not a pure literal."""
    for node in tree.body if isinstance(tree, ast.Module) else []:
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "BUFFER_CAPS"
                        for t in node.targets)):
            try:
                val = ast.literal_eval(node.value)
            except (ValueError, SyntaxError):
                return {}, node.lineno
            return (val if isinstance(val, dict) else {}), node.lineno
    return {}, 0


def _class_buffers(cls: ast.ClassDef):
    """Yield (attr_name, lineno) for every buffer-shaped
    `self.<attr> = <builder>` in the class's methods."""
    for fn in ast.walk(cls):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            else:
                continue
            for tgt in targets:
                if (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                        and BUFFER_RE.search(tgt.attr)
                        and _is_container_builder(value)):
                    yield tgt.attr, node.lineno


@analysis_pass("bounded-buffer", "dissemination buffering structures "
                                 "declare an explicit cap (BUFFER_CAPS)")
def check(src: SourceCache) -> list[Finding]:
    problems: list[Finding] = []
    for p in src.pkg_files():
        pkg_rel = str(p.relative_to(src.pkg)).replace("\\", "/")
        if not pkg_rel.startswith(SCANNED_PREFIXES):
            continue
        tree = src.tree(p)
        if tree is None:
            continue
        rel = src.rel(p)
        caps, caps_line = _buffer_caps(tree)
        seen: set[str] = set()
        for cls in (n for n in ast.walk(tree)
                    if isinstance(n, ast.ClassDef)):
            for attr, line in _class_buffers(cls):
                key = f"{cls.name}.{attr}"
                seen.add(key)
                reason = caps.get(key)
                if not (isinstance(reason, str) and reason.strip()):
                    problems.append(Finding(
                        "bounded-buffer", rel, line,
                        f"{key} builds a buffer with no declared cap — "
                        f"between an unbounded producer and a slow (or "
                        f"never-reading) consumer every scanned-module "
                        f"buffer needs an explicit bound; add a reasoned "
                        f"BUFFER_CAPS row naming what bounds it",
                        obj=f"{pkg_rel}:{key}"))
        for key in caps:
            if key not in seen:
                problems.append(Finding(
                    "bounded-buffer", rel, caps_line,
                    f"stale BUFFER_CAPS row {key!r}: the module no "
                    f"longer assigns that buffer — declarations must "
                    f"not outlive the buffers they excuse",
                    obj=f"{pkg_rel}:{key}:stale"))
    return apply_allowlist("bounded-buffer",
                           "antrea_tpu/analysis/bounded_buffer.py",
                           problems, BUFFER_ALLOWLIST)
