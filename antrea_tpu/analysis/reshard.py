"""Pass `reshard` — every (D,)-sharded state field migrates (migrated
from tools/check_reshard.py, which remains as a shim).

The elastic resharding plane (parallel/reshard.py) moves the stateful
tables — the pytree fields `parallel/mesh._state_specs` shards with a
leading ``data`` axis — to their new home shards when the data axis
resizes.  A NEW stateful field that nobody taught the migrator is a
silent flow-loss bug.  Fails when any field specced `P(DATA, ...)` in
`_state_specs` has no migration rule in `reshard.RESHARD_MANIFEST` —
and when the manifest itself goes stale."""

from __future__ import annotations

import ast

from .core import Finding, SourceCache, analysis_pass

STATE_BUILDER = "_state_specs"


def data_sharded_fields(src: SourceCache) -> set:
    """'Class.field' for every kwarg of a constructor call inside
    _state_specs whose value is a P(DATA, ...) spec — the fields that
    carry a leading data axis and therefore must migrate on resize."""
    tree = src.tree(src.pkg / "parallel" / "mesh.py")
    out: set[str] = set()
    if tree is None:
        return out
    for node in ast.walk(tree):
        if not (isinstance(node, ast.FunctionDef)
                and node.name == STATE_BUILDER):
            continue
        for call in ast.walk(node):
            if not isinstance(call, ast.Call):
                continue
            fn = call.func
            cls = (fn.attr if isinstance(fn, ast.Attribute)
                   else fn.id if isinstance(fn, ast.Name) else None)
            if cls is None:
                continue
            for kw in call.keywords:
                v = kw.value
                if (isinstance(v, ast.Call)
                        and isinstance(v.func, ast.Name)
                        and v.func.id == "P"
                        and v.args
                        and isinstance(v.args[0], ast.Name)
                        and v.args[0].id == "DATA"):
                    out.add(f"{cls}.{kw.arg}")
    return out


def manifest(src: SourceCache) -> dict:
    tree = src.tree(src.pkg / "parallel" / "reshard.py")
    if tree is None:
        raise ValueError("antrea_tpu/parallel/reshard.py is missing")
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target,
                                                           ast.Name):
            targets = [node.target.id]
        else:
            continue
        if "RESHARD_MANIFEST" in targets and node.value is not None:
            return ast.literal_eval(node.value)
    raise ValueError(
        "parallel/reshard.py defines no RESHARD_MANIFEST literal")


@analysis_pass("reshard", "every (D,)-sharded state field has a reshard "
                          "migration rule")
def check(src: SourceCache) -> list[Finding]:
    reshard_rel = "antrea_tpu/parallel/reshard.py"
    mesh_rel = "antrea_tpu/parallel/mesh.py"

    def f(reason, obj, path=reshard_rel):
        return Finding("reshard", path, 0, reason, obj=obj)

    try:
        rules = manifest(src)
    except (OSError, ValueError) as e:
        return [f(str(e), "no-manifest")]
    sharded = data_sharded_fields(src)
    if not sharded:
        return [f(f"parallel/mesh.py {STATE_BUILDER} names no P(DATA, ...) "
                  f"fields at all — the parse is broken or the specs moved",
                  "no-sharded-fields", mesh_rel)]

    problems: list[Finding] = []
    for key in sorted(sharded - set(rules)):
        problems.append(f(
            f"{key} is (D,)-sharded in parallel/mesh.py {STATE_BUILDER} "
            f"but has NO migration rule in reshard.RESHARD_MANIFEST — a "
            f"live resize would silently zero it (flow loss); teach the "
            f"migrator and document the rule", f"unmigrated:{key}"))
    for key in sorted(set(rules) - sharded):
        problems.append(f(
            f"RESHARD_MANIFEST names {key!r}, which is not a (D,)-sharded "
            f"field of {STATE_BUILDER} — stale manifest row",
            f"stale:{key}"))
    for key, rule in rules.items():
        if not (isinstance(rule, str) and rule.strip()):
            problems.append(f(
                f"RESHARD_MANIFEST[{key!r}] carries no rule text",
                f"no-rule:{key}"))
    return problems
