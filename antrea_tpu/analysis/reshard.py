"""Pass `reshard` — every (D,)-sharded state field migrates (migrated
from tools/check_reshard.py, which remains as a shim).

The elastic resharding plane (parallel/reshard.py) moves the stateful
tables — the pytree fields `parallel/mesh._state_specs` shards with a
leading ``data`` axis — to their new home shards when the data axis
resizes.  A NEW stateful field that nobody taught the migrator is a
silent flow-loss bug.  Fails when any field specced `P(DATA, ...)` in
`_state_specs` has no migration rule in `reshard.RESHARD_MANIFEST` —
and when the manifest itself goes stale.

Tenant extension (PR 20): tenant worlds carry their OWN (D,)-sharded
state — any `MeshDatapath._TENANT_WORLD_FIELDS` member assigned from
the sharded-state builders (`shard_state` / `_pin_state` /
`_init_pipeline_state`) is a per-world device table that a resize must
walk under `_world_ctx`, so each such member must carry a rule in
`reshard.WORLD_MIGRATION`.  A new per-world sharded field without one
is the SAME silent flow-loss bug, scoped to every tenant at once."""

from __future__ import annotations

import ast

from .core import Finding, SourceCache, analysis_pass

STATE_BUILDER = "_state_specs"

# Call targets whose result is (D,)-sharded device state: a world field
# assigned from one of these holds per-replica rows a resize must
# migrate (the detection is assignment-shaped, not name-shaped, so a
# new sharded world field cannot dodge the pass by picking a fresh
# name).
SHARDED_BUILDERS = {"shard_state", "_pin_state", "_init_pipeline_state"}


def data_sharded_fields(src: SourceCache) -> set:
    """'Class.field' for every kwarg of a constructor call inside
    _state_specs whose value is a P(DATA, ...) spec — the fields that
    carry a leading data axis and therefore must migrate on resize."""
    tree = src.tree(src.pkg / "parallel" / "mesh.py")
    out: set[str] = set()
    if tree is None:
        return out
    for node in ast.walk(tree):
        if not (isinstance(node, ast.FunctionDef)
                and node.name == STATE_BUILDER):
            continue
        for call in ast.walk(node):
            if not isinstance(call, ast.Call):
                continue
            fn = call.func
            cls = (fn.attr if isinstance(fn, ast.Attribute)
                   else fn.id if isinstance(fn, ast.Name) else None)
            if cls is None:
                continue
            for kw in call.keywords:
                v = kw.value
                if (isinstance(v, ast.Call)
                        and isinstance(v.func, ast.Name)
                        and v.func.id == "P"
                        and v.args
                        and isinstance(v.args[0], ast.Name)
                        and v.args[0].id == "DATA"):
                    out.add(f"{cls}.{kw.arg}")
    return out


def _module_literal(src: SourceCache, path, name: str):
    tree = src.tree(path)
    if tree is None:
        raise ValueError(f"{src.rel(path)} is missing")
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target,
                                                           ast.Name):
            targets = [node.target.id]
        else:
            continue
        if name in targets and node.value is not None:
            return ast.literal_eval(node.value)
    raise ValueError(f"{src.rel(path)} defines no {name} literal")


def manifest(src: SourceCache) -> dict:
    return _module_literal(src, src.pkg / "parallel" / "reshard.py",
                           "RESHARD_MANIFEST")


def world_migration(src: SourceCache) -> dict:
    return _module_literal(src, src.pkg / "parallel" / "reshard.py",
                           "WORLD_MIGRATION")


def sharded_world_fields(src: SourceCache) -> set:
    """_TENANT_WORLD_FIELDS members of the mesh engine that are assigned
    from a sharded-state builder anywhere in parallel/meshpath.py — the
    per-world device tables a resize must migrate."""
    path = src.pkg / "parallel" / "meshpath.py"
    tree = src.tree(path)
    if tree is None:
        return set()
    world_fields = set(
        _module_literal(src, path, "_TENANT_WORLD_FIELDS"))
    assigned: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        v = node.value
        if not isinstance(v, ast.Call):
            continue
        fn = v.func
        callee = (fn.attr if isinstance(fn, ast.Attribute)
                  else fn.id if isinstance(fn, ast.Name) else None)
        if callee not in SHARDED_BUILDERS:
            continue
        for t in node.targets:
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                assigned.add(t.attr)
    return assigned & world_fields


@analysis_pass("reshard", "every (D,)-sharded state field has a reshard "
                          "migration rule")
def check(src: SourceCache) -> list[Finding]:
    reshard_rel = "antrea_tpu/parallel/reshard.py"
    mesh_rel = "antrea_tpu/parallel/mesh.py"

    def f(reason, obj, path=reshard_rel):
        return Finding("reshard", path, 0, reason, obj=obj)

    try:
        rules = manifest(src)
    except (OSError, ValueError) as e:
        return [f(str(e), "no-manifest")]
    sharded = data_sharded_fields(src)
    if not sharded:
        return [f(f"parallel/mesh.py {STATE_BUILDER} names no P(DATA, ...) "
                  f"fields at all — the parse is broken or the specs moved",
                  "no-sharded-fields", mesh_rel)]

    problems: list[Finding] = []
    for key in sorted(sharded - set(rules)):
        problems.append(f(
            f"{key} is (D,)-sharded in parallel/mesh.py {STATE_BUILDER} "
            f"but has NO migration rule in reshard.RESHARD_MANIFEST — a "
            f"live resize would silently zero it (flow loss); teach the "
            f"migrator and document the rule", f"unmigrated:{key}"))
    for key in sorted(set(rules) - sharded):
        problems.append(f(
            f"RESHARD_MANIFEST names {key!r}, which is not a (D,)-sharded "
            f"field of {STATE_BUILDER} — stale manifest row",
            f"stale:{key}"))
    for key, rule in rules.items():
        if not (isinstance(rule, str) and rule.strip()):
            problems.append(f(
                f"RESHARD_MANIFEST[{key!r}] carries no rule text",
                f"no-rule:{key}"))

    # Tenant worlds: every _TENANT_WORLD_FIELDS member assigned from a
    # sharded-state builder must carry a WORLD_MIGRATION rule (the
    # per-world analog of the manifest check above).
    meshpath_rel = "antrea_tpu/parallel/meshpath.py"
    try:
        wrules = world_migration(src)
    except (OSError, ValueError) as e:
        return problems + [f(str(e), "no-world-migration")]
    try:
        wsharded = sharded_world_fields(src)
    except (OSError, ValueError) as e:
        return problems + [f(str(e), "no-world-fields", meshpath_rel)]
    if not wsharded:
        problems.append(f(
            "parallel/meshpath.py names no _TENANT_WORLD_FIELDS member "
            "assigned from a sharded-state builder — the parse is broken "
            "or the world state moved", "no-sharded-world-fields",
            meshpath_rel))
    for key in sorted(wsharded - set(wrules)):
        problems.append(f(
            f"{key} is a (D,)-sharded _TENANT_WORLD_FIELDS member "
            f"(parallel/meshpath.py) but has NO rule in "
            f"reshard.WORLD_MIGRATION — a live resize would silently "
            f"zero EVERY tenant world's copy (flow loss); teach the "
            f"per-world migrator and document the rule",
            f"unmigrated-world:{key}"))
    for key in sorted(set(wrules) - wsharded):
        problems.append(f(
            f"WORLD_MIGRATION names {key!r}, which is not a sharded "
            f"_TENANT_WORLD_FIELDS member — stale rule",
            f"stale-world:{key}"))
    for key, rule in wrules.items():
        if not (isinstance(rule, str) and rule.strip()):
            problems.append(f(
                f"WORLD_MIGRATION[{key!r}] carries no rule text",
                f"no-rule-world:{key}"))
    return problems
