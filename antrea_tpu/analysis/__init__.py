"""Unified static-analysis plane: one AST engine, every drift gate.

Public surface:

  run(root=REPO, pass_ids=None) -> RunResult   the whole suite (or a
                                               subset), baseline applied
  run_cli(pass_id, argv) -> int                the legacy tools/check_*
                                               shim entry point
  PASSES                                       id -> (fn, invariant)

The nine legacy `tools/check_*.py` gates live here as passes (the tools
remain as thin CLI shims, verdict-identical — pinned by
tests/test_static_analysis.py), joined by the semantic passes that
pin the hand-caught bug classes: `thread-safety`, `bounded-cache`,
`jit-purity`, `donation-safety`, `bounded-buffer`, `canonical-shape`.
Everything is stdlib-only (ast/re/
json): importing this subpackage never pulls jax, so every gate runs on
any CI image.  See core.py for the engine contract (SourceCache,
Finding, allowlists, BASELINE.analysis.json)."""

from .core import (  # noqa: F401
    BASELINE_NAME,
    Finding,
    PASSES,
    REPO,
    RunResult,
    SourceCache,
    analysis_pass,
    load_baseline,
    run,
    run_cli,
)

# Importing the pass modules registers them (registration order is the
# run order: the nine migrated gates first, then the semantic passes).
from . import (  # noqa: E402,F401
    mesh,
    metrics,
    phases,
    events,
    commit_plane,
    audit_plane,
    maintenance,
    reshard,
    tenant,
    threads,
    caches,
    jit_purity,
    donation,
    bounded_buffer,
    telemetry,
    canonical_shape,
)

__all__ = [
    "BASELINE_NAME", "Finding", "PASSES", "REPO", "RunResult",
    "SourceCache", "analysis_pass", "load_baseline", "run", "run_cli",
]
