"""Pass `metrics` — metric-name drift: registry == emissions == README
(migrated from tools/check_metrics.py, which remains as a shim).

Three-way consistency over the `antrea_tpu_*` metric namespace:

  1. every name in the METRICS registry
     (antrea_tpu/observability/metrics.py) appears in README.md's
     "Observability" metric inventory, and vice versa — the README table
     is the operator contract;
  2. every `antrea_tpu_*` literal anywhere under antrea_tpu/ resolves to
     a registered family (histogram `_bucket`/`_sum`/`_count` suffixes
     fold to their family), so nothing can be emitted unregistered.

metrics.py is loaded directly from its path (it depends only on the
stdlib by design), never via the package import — no jax, ever."""

from __future__ import annotations

import importlib.util
import re

from .core import Finding, SourceCache, analysis_pass

NAME_RE = re.compile(r"antrea_tpu_[a-z0-9_]+")
_SUFFIXES = ("_bucket", "_sum", "_count")


def load_registry(src: SourceCache) -> dict:
    path = src.pkg / "observability" / "metrics.py"
    spec = importlib.util.spec_from_file_location("_metrics_standalone", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return dict(mod.METRICS)


def _family(name: str, registry: dict) -> str:
    """Fold histogram sample suffixes onto their family name."""
    if name in registry:
        return name
    for suf in _SUFFIXES:
        if name.endswith(suf) and name[: -len(suf)] in registry:
            return name[: -len(suf)]
    return name


def readme_names(src: SourceCache, registry: dict) -> set:
    text = src.text(src.root / "README.md") or ""
    return {_family(n, registry) for n in NAME_RE.findall(text)}


def source_names(src: SourceCache, registry: dict) -> set:
    """Every antrea_tpu_* literal under antrea_tpu/ (emissions + the
    comments that cite them — citing an unregistered name is drift too).
    The analysis plane itself is excluded (core.SourceCache.pkg_files):
    passes quote name prefixes they classify by."""
    out = set()
    for p in src.pkg_files():
        for n in NAME_RE.findall(src.text(p) or ""):
            out.add(_family(n, registry))
    return out


@analysis_pass("metrics", "metric registry == README table == source "
                          "emissions")
def check(src: SourceCache) -> list[Finding]:
    reg_rel = "antrea_tpu/observability/metrics.py"
    try:
        registry = load_registry(src)
    except Exception as e:  # noqa: BLE001 — any load failure is the finding
        return [Finding("metrics", reg_rel, 0,
                        f"cannot load METRICS registry: {e}",
                        obj="registry-unloadable")]
    reg = set(registry)
    readme = readme_names(src, registry)
    source = source_names(src, registry)
    problems = []
    for n in sorted(reg - readme):
        problems.append(Finding(
            "metrics", "README.md", 0,
            f"registered but missing from README.md: {n}", obj=f"readme:{n}"))
    for n in sorted(readme - reg):
        problems.append(Finding(
            "metrics", "README.md", 0,
            f"in README.md but not registered: {n}", obj=f"unreg-readme:{n}"))
    for n in sorted(source - reg):
        problems.append(Finding(
            "metrics", reg_rel, 0,
            f"referenced in source but not registered: {n}",
            obj=f"unreg-src:{n}"))
    # The registry itself lives in source, so reg - source only flags names
    # nobody renders NOR documents in code — dead registry entries.
    return problems
