"""Pass `phases` — phase-mask drift: pipeline PH_* == profile chains ==
bench_profile (migrated from tools/check_phases.py, which remains as a
shim).

The churn profiler's honesty rests on three surfaces staying in
lockstep: the PH_* mask bits in antrea_tpu/models/pipeline.py (with
PH_ALL their OR), the cumulative chains in antrea_tpu/models/profile.py
(each chain starts at 0, grows by exactly one PH_ bit per entry, ends
at PH_ALL, unique names), and bench_profile.py reporting its phase list
FROM the chain, not from a hand-copied name list."""

from __future__ import annotations

import re

from .core import Finding, SourceCache, analysis_pass

_PH_DEF = re.compile(r"^(PH_[A-Z0-9_]+)\s*=\s*(.+?)\s*(?:#.*)?$", re.M)
_CHAIN = re.compile(
    r"^(PHASE_CHAIN|ASYNC_PHASE_CHAIN|OVERLAP_PHASE_CHAIN"
    r"|MAINT_PHASE_CHAIN|PRUNE_PHASE_CHAIN|FUSED_PHASE_CHAIN)"
    r"\s*:.*?=\s*\((.*?)^\)",
    re.M | re.S,
)
_ENTRY = re.compile(r'\(\s*"([a-z0-9_]+)"\s*,\s*([^)]*?)\s*\)', re.S)

REQUIRED_CHAINS = ("PHASE_CHAIN", "ASYNC_PHASE_CHAIN", "OVERLAP_PHASE_CHAIN",
                   "MAINT_PHASE_CHAIN", "PRUNE_PHASE_CHAIN",
                   "FUSED_PHASE_CHAIN")


def parse_ph_bits(src: SourceCache) -> dict:
    """PH_* constants from pipeline.py, numerically evaluated in
    definition order (later definitions may reference earlier ones)."""
    text = src.text(src.pkg / "models" / "pipeline.py") or ""
    bits: dict[str, int] = {}
    for name, expr in _PH_DEF.findall(text):
        try:
            bits[name] = eval(expr, {"__builtins__": {}}, dict(bits))
        except Exception:
            continue  # not a constant definition (e.g. inside a function)
    return bits


def parse_chains(src: SourceCache) -> dict:
    """{chain name: [(entry name, mask int), ...]} from profile.py."""
    text = src.text(src.pkg / "models" / "profile.py") or ""
    bits = parse_ph_bits(src)
    chains: dict[str, list] = {}
    for cname, body in _CHAIN.findall(text):
        entries = []
        for ename, expr in _ENTRY.findall(body):
            expr = expr.strip().rstrip(",")
            try:
                mask = eval(expr.replace("pl.", ""), {"__builtins__": {}},
                            dict(bits))
            except Exception:
                entries.append((ename, None))
                continue
            entries.append((ename, mask))
        chains[cname] = entries
    return chains


@analysis_pass("phases", "PH_* mask bits == profile chains == "
                         "bench_profile's reported phase list")
def check(src: SourceCache) -> list[Finding]:
    pipeline_rel = "antrea_tpu/models/pipeline.py"
    profile_rel = "antrea_tpu/models/profile.py"

    def f(reason, obj, path=profile_rel):
        return Finding("phases", path, 0, reason, obj=obj)

    problems: list[Finding] = []
    bits = parse_ph_bits(src)
    phase_bits = {k: v for k, v in bits.items() if k != "PH_ALL"}
    if "PH_ALL" not in bits:
        return [f("pipeline.py defines no PH_ALL", "no-ph-all", pipeline_rel)]
    union = 0
    for v in phase_bits.values():
        union |= v
    if union != bits["PH_ALL"]:
        problems.append(f(
            f"PH_ALL ({bits['PH_ALL']:#x}) != OR of phase bits ({union:#x})",
            "ph-all-mismatch", pipeline_rel))
    for a, va in phase_bits.items():
        if va & (va - 1):
            problems.append(f(f"{a} ({va:#x}) is not a single bit",
                              f"multi-bit:{a}", pipeline_rel))
        for b, vb in phase_bits.items():
            if a < b and va & vb:
                problems.append(f(
                    f"{a} and {b} overlap ({va:#x} & {vb:#x})",
                    f"overlap:{a}:{b}", pipeline_rel))

    chains = parse_chains(src)
    for required in REQUIRED_CHAINS:
        if required not in chains:
            problems.append(f(f"profile.py defines no {required}",
                              f"missing-chain:{required}"))
    seen_names: set[str] = set()
    for cname, entries in chains.items():
        if not entries:
            problems.append(f(f"{cname} parsed empty", f"empty:{cname}"))
            continue
        names = [n for n, _m in entries]
        dup = {n for n in names if names.count(n) > 1}
        if dup:
            problems.append(f(f"{cname}: duplicate phase names {sorted(dup)}",
                              f"dup:{cname}"))
        overlap = seen_names & set(names)
        if overlap:
            problems.append(f(
                f"{cname}: phase names {sorted(overlap)} reused across "
                f"chains (bench/profile consumers key on the name)",
                f"reuse:{cname}"))
        seen_names |= set(names)
        prev = None
        for ename, mask in entries:
            if mask is None:
                problems.append(f(f"{cname}.{ename}: unparseable mask",
                                  f"unparseable:{cname}.{ename}"))
                continue
            if prev is None:
                if mask != 0:
                    problems.append(f(f"{cname} must start at mask 0",
                                      f"start:{cname}"))
            else:
                added = mask & ~prev
                if mask & prev != prev:
                    problems.append(f(
                        f"{cname}.{ename}: mask {mask:#x} is not a superset "
                        f"of its predecessor {prev:#x}",
                        f"superset:{cname}.{ename}"))
                if added == 0 or added & (added - 1):
                    problems.append(f(
                        f"{cname}.{ename}: must add exactly one PH_ bit "
                        f"(adds {added:#x})", f"one-bit:{cname}.{ename}"))
            prev = mask
        if prev != bits["PH_ALL"]:
            problems.append(f(
                f"{cname} ends at {prev:#x}, not PH_ALL "
                f"({bits['PH_ALL']:#x}) — a PH_ bit has no phase entry",
                f"end:{cname}"))

    bench = src.text(src.root / "bench_profile.py") or ""
    if not re.search(r"from antrea_tpu\.models\.profile import .*PHASE_CHAIN",
                     bench):
        problems.append(f("bench_profile.py does not import PHASE_CHAIN",
                          "bench-import", "bench_profile.py"))
    if not re.search(r'"phase_chain":.*PHASE_CHAIN', bench):
        problems.append(f(
            "bench_profile.py does not derive its reported phase_chain "
            "from profile.PHASE_CHAIN", "bench-derive", "bench_profile.py"))
    return problems
