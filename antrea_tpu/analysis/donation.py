"""Pass `donation-safety` — donated buffers are never read after the
dispatch that consumed them.

`jax.jit(..., donate_argnums=(i,))` lets XLA alias argument i's buffers
in place (the overlapped drain's whole point: ~150MB of cache columns
scatter in place instead of copying, models/pipeline.py
`pipeline_step_donated`).  The contract is one-sided: after the
dispatch, the PASSED arrays are deleted — a host read of the same
reference returns garbage or raises, and nothing in the type system
says so.  The comment block over `pipeline_step_donated` states the
caller discipline ("callers MUST drop every reference to the passed
state"); this pass enforces it:

  * collect every callable built with a `donate_argnums=` literal
    anywhere under antrea_tpu/ (by its bound name), plus per-function
    local aliases whose right-hand side references one (the
    `step_fn = pl.pipeline_step_donated if overlap else ...` pattern);
  * at every call site of such a callable, each argument at a donated
    position that is a plain name or `self.<attr>` must not be LOADED
    again in the enclosing function after the dispatch — in EXECUTION
    order: (line, col) positions, and a dispatch inside a loop wraps
    around to the body's earlier lines (they run again next iteration)
    — until it is re-BOUND (the `self._state = state` publish kills the
    taint).

Reads hidden behind further calls are out of scope (the donated
arguments in this repo are the engines' single-owner `self._state`
columns, whose only readers are the methods this pass walks)."""

from __future__ import annotations

import ast

from .core import Finding, SourceCache, analysis_pass, apply_allowlist

#: obj key ("relpath:function:arg") -> reason.
DONATION_ALLOWLIST: dict[str, str] = {}


def _last_component(node: ast.AST) -> str | None:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _donated_positions(call: ast.Call) -> tuple[int, ...] | None:
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            try:
                v = ast.literal_eval(kw.value)
            except ValueError:
                return None
            return (v,) if isinstance(v, int) else tuple(v)
    return None


def collect_donated_names(src: SourceCache) -> dict[str, tuple[int, ...]]:
    """Bound name -> donated positions, for every
    `NAME = ...jit(..., donate_argnums=...)` under the package."""
    out: dict[str, tuple[int, ...]] = {}
    for p in src.pkg_files():
        tree = src.tree(p)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            for call in ast.walk(node.value):
                if isinstance(call, ast.Call):
                    pos = _donated_positions(call)
                    if pos:
                        out[node.targets[0].id] = pos
    return out


def _arg_key(node: ast.AST) -> str | None:
    """Trackable donated-argument shapes: a bare name, or self.<attr>."""
    if isinstance(node, ast.Name):
        return node.id
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return f"self.{node.attr}"
    return None


def _matches(node: ast.AST, key: str) -> bool:
    if "." in key:
        _self, attr = key.split(".", 1)
        return (isinstance(node, ast.Attribute) and node.attr == attr
                and isinstance(node.value, ast.Name)
                and node.value.id == "self")
    return isinstance(node, ast.Name) and node.id == key


def _check_function(fn: ast.FunctionDef, donated: dict[str, tuple[int, ...]],
                    rel: str, pkg_rel: str) -> list[Finding]:
    # Per-function aliases: `x = <expr referencing a donated name>`.
    local = dict(donated)
    for node in ast.walk(fn):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            for ref in ast.walk(node.value):
                name = _last_component(ref)
                if name in donated and not isinstance(ref, ast.Call):
                    local[node.targets[0].id] = donated[name]

    nested: set[int] = set()
    for sub in ast.walk(fn):
        if isinstance(sub, ast.FunctionDef) and sub is not fn:
            nested.update(id(n) for n in ast.walk(sub))
    # Enclosing loops, innermost last: a dispatch INSIDE a loop is
    # followed (in execution order) by the loop body's earlier lines on
    # the next iteration, so the event order wraps around.
    loops = [(n, {id(d) for d in ast.walk(n)})
             for n in ast.walk(fn)
             if isinstance(n, (ast.For, ast.AsyncFor, ast.While))]

    problems: list[Finding] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call) or id(node) in nested:
            continue  # nested defs own their call sites
        callee = _last_component(node.func)
        if callee not in local:
            continue
        enclosing = [ids for _loop, ids in loops if id(node) in ids]
        loop_ids = min(enclosing, key=len) if enclosing else None
        for pos in local[callee]:
            if pos >= len(node.args):
                continue
            key = _arg_key(node.args[pos])
            if key is None:
                continue
            # Events on the donated reference, in EXECUTION order after
            # the dispatch: (lineno, col) position tuples (so a read
            # later on the dispatch's own line counts), wrapping around
            # the enclosing loop body (a read at an earlier line runs
            # again on the next iteration, AFTER this dispatch).  The
            # first re-binding store kills the taint; loads before it
            # read deleted buffers.  The call's own argument nodes are
            # the dispatch itself — excluded by identity.
            own = {id(n) for n in ast.walk(node)}
            call_pos = (node.lineno, node.col_offset)
            events = []  # (phase, position, is_store, lineno)
            for ev in ast.walk(fn):
                ln = getattr(ev, "lineno", None)
                if ln is None or id(ev) in own or not _matches(ev, key):
                    continue
                ev_pos = (ln, ev.col_offset)
                if loop_ids is not None and id(ev) in loop_ids:
                    # same iteration (0) or next iteration's prefix (1)
                    phase = 0 if ev_pos > call_pos else 1
                elif ev_pos > call_pos:
                    phase = 2  # after the loop / straight-line tail
                else:
                    continue  # strictly before any dispatch
                events.append((phase, ev_pos,
                               isinstance(ev.ctx, ast.Store), ln))
            for _phase, _pos, is_store, ln in sorted(events):
                if is_store:
                    break  # rebound: the taint dies here
                problems.append(Finding(
                    "donation-safety", rel, ln,
                    f"{fn.name}() reads {key} at line {ln} after passing "
                    f"it to {callee}() (donated position {pos}, line "
                    f"{node.lineno}) — XLA aliased those buffers in "
                    f"place; rebind before reading or drop the read",
                    obj=f"{pkg_rel}:{fn.name}:{key}"))
    return problems


@analysis_pass("donation-safety", "donated arguments are never read after "
                                  "their dispatch site")
def check(src: SourceCache) -> list[Finding]:
    donated = collect_donated_names(src)
    if not donated:
        return []
    problems: list[Finding] = []
    for p in src.pkg_files():
        tree = src.tree(p)
        if tree is None:
            continue
        rel = src.rel(p)
        pkg_rel = str(p.relative_to(src.pkg)).replace("\\", "/")
        # Innermost-ownership walk: check each FunctionDef, skipping
        # call sites that belong to a nested def (the nested def is
        # checked in its own right).
        for node in ast.walk(tree):
            if not isinstance(node, ast.FunctionDef):
                continue
            problems.extend(_check_function(node, donated, rel, pkg_rel))
    return apply_allowlist("donation-safety",
                           "antrea_tpu/analysis/donation.py",
                           problems, DONATION_ALLOWLIST)
