"""Pass `tenant` — every 5-tuple-keyed or per-world surface carries the
tenant id (migrated from tools/check_tenant.py, which remains as a shim).

A multi-tenant datapath is only isolated if NO surface that hashes,
keys, or commits on the 5-tuple can silently drop the owning world:
the miss-queue schema carries the tenant column, every _queue_cols /
shard_of_tuples call site passes tenant= (or is allowlisted with a
reason), each engine's _TENANT_WORLD_FIELDS covers the required
per-world members, the commit plane's per-world slice names real
CommitPlane attributes, and every tenant metric family renders
tenant-labeled."""

from __future__ import annotations

import ast
import pathlib
import re

from .core import Finding, SourceCache, analysis_pass

# shard_of_tuples call sites allowed WITHOUT a tenant= kwarg, with the
# reason each is default-world-only by construction.
SHARD_ALLOWLIST = {
    "parallel/mesh.py":
        "the definition site (tenant defaults to 0 = the default world)",
}

# _queue_cols call sites allowed WITHOUT tenant= (the definition).
QUEUE_ALLOWLIST = {
    "datapath/interface.py":
        "the definition site (tenant defaults to 0)",
}

REQUIRED_WORLD_FIELDS = {
    "datapath/tpuflow.py": {
        "_ps", "_cps", "_drs", "_meta", "_meta_step", "_state", "_gen",
        "_stats_in", "_stats_out", "_evictions", "_state_mutations",
        "_pipe_kw",
    },
    "datapath/oracle_dp.py": {
        "_ps", "_oracle", "_gen", "_stats_in", "_stats_out",
        "_state_mutations",
    },
}

REQUIRED_COMMIT_FIELDS = {"degraded", "last_error", "lkg_generation",
                          "lkg_at"}


def _literal_tuple(src: SourceCache, path: pathlib.Path, name: str):
    text = src.text(path)
    if text is None:
        raise ValueError(f"{src.rel(path)} is missing")
    m = re.search(rf"^\s*{name}\s*(?::[^=]+)?=\s*(\(.*?\))", text,
                  re.M | re.S)
    if m is None:
        raise ValueError(f"{src.rel(path)} defines no {name} literal")
    return ast.literal_eval(m.group(1))


def _call_sites(src: SourceCache, pattern: str) -> list[tuple[str, int, str]]:
    """(pkg-relative path, lineno, full call text) of every `pattern(`
    site — the call text spans to the balanced closing paren."""
    out = []
    rx = re.compile(re.escape(pattern) + r"\(")
    for p in src.pkg_files():
        text = src.text(p) or ""
        rel = str(p.relative_to(src.pkg)).replace("\\", "/")
        for m in rx.finditer(text):
            start = m.end() - 1
            depth = 0
            for i in range(start, min(len(text), start + 2000)):
                if text[i] == "(":
                    depth += 1
                elif text[i] == ")":
                    depth -= 1
                    if depth == 0:
                        break
            line = text.count("\n", 0, m.start()) + 1
            out.append((rel, line, text[m.start():i + 1]))
    return out


@analysis_pass("tenant", "every 5-tuple-keyed or per-world surface carries "
                         "the tenant id")
def check(src: SourceCache) -> list[Finding]:
    problems: list[Finding] = []

    def f(reason, obj, path, line=0):
        return Finding("tenant", path, line, reason, obj=obj)

    # 1. queue schema + builder.
    queue_rel = "antrea_tpu/datapath/slowpath/queue.py"
    qtext = src.text(src.pkg / "datapath" / "slowpath" / "queue.py") or ""
    m = re.search(r"^COLUMNS\s*=\s*(\(.*?\))", qtext, re.M | re.S)
    cols = ast.literal_eval(m.group(1)) if m else ()
    if "tenant" not in cols:
        problems.append(f(
            "datapath/slowpath/queue.COLUMNS has no 'tenant' column — "
            "queued misses cannot be classified in their owner's world",
            "no-tenant-column", queue_rel))
    itext = src.text(src.pkg / "datapath" / "interface.py") or ""
    if '"tenant"' not in itext:
        problems.append(f(
            "datapath/interface._queue_cols does not produce the "
            "'tenant' column", "no-tenant-builder",
            "antrea_tpu/datapath/interface.py"))

    # 2./3. call sites must pass tenant=.
    for pattern, allow, why in (
        ("_queue_cols", QUEUE_ALLOWLIST,
         "queued rows would land in the default world"),
        ("shard_of_tuples", SHARD_ALLOWLIST,
         "two tenants' identical tuples would share one home"),
    ):
        for rel, line, call in _call_sites(src, pattern):
            if rel in allow:
                continue
            if re.search(r"def\s+" + pattern, call):
                continue
            if "tenant=" not in call:
                problems.append(f(
                    f"{rel}:{line}: {pattern}(...) drops the tenant id "
                    f"({why}) — pass tenant= or allowlist with a reason",
                    f"dropped:{pattern}:{rel}",
                    f"antrea_tpu/{rel}", line))

    # 4. world-field coverage.
    for relpath, required in REQUIRED_WORLD_FIELDS.items():
        rel = f"antrea_tpu/{relpath}"
        try:
            fields = set(_literal_tuple(src, src.pkg / relpath,
                                        "_TENANT_WORLD_FIELDS"))
        except ValueError as e:
            problems.append(f(str(e), f"no-world-fields:{relpath}", rel))
            continue
        for name in sorted(required - fields):
            problems.append(f(
                f"{rel}: _TENANT_WORLD_FIELDS is missing {name!r} — that "
                f"state would leak across world swaps",
                f"world-field:{relpath}:{name}", rel))

    # 5. commit-plane slice.
    tenancy_rel = "antrea_tpu/datapath/tenancy.py"
    try:
        cw = set(_literal_tuple(src, src.pkg / "datapath" / "tenancy.py",
                                "COMMIT_WORLD_FIELDS"))
    except ValueError as e:
        problems.append(f(str(e), "no-commit-fields", tenancy_rel))
        cw = set()
    for name in sorted(REQUIRED_COMMIT_FIELDS - cw):
        problems.append(f(
            f"datapath/tenancy.COMMIT_WORLD_FIELDS is missing {name!r} — "
            f"a tenant rollback would not be tenant-scoped",
            f"commit-field:{name}", tenancy_rel))
    commit_text = src.text(src.pkg / "datapath" / "commit.py") or ""
    for name in sorted(cw):
        if not re.search(rf"self\.{name}\b", commit_text):
            problems.append(f(
                f"COMMIT_WORLD_FIELDS names {name!r} but CommitPlane has "
                f"no such attribute — the swap would silently no-op",
                f"commit-attr:{name}", "antrea_tpu/datapath/commit.py"))

    # 6. tenant metric families render tenant-labeled.
    metrics_rel = "antrea_tpu/observability/metrics.py"
    mtext = src.text(src.pkg / "observability" / "metrics.py") or ""
    m = re.search(r"^METRICS\s*(?::[^=]+)?=\s*(\{.*?^\})", mtext,
                  re.M | re.S)
    registry = ast.literal_eval(m.group(1)) if m else {}
    tenant_fams = [n for n in registry
                   if n.startswith("antrea_tpu_tenant_")
                   and n != "antrea_tpu_tenant_worlds"]
    if not tenant_fams:
        problems.append(f(
            "no antrea_tpu_tenant_* families in the metrics registry",
            "no-tenant-families", metrics_rel))
    if "_labels(tenant=tid, node=node)" not in mtext:
        problems.append(f(
            "observability/metrics.py renders no tenant-labeled sample "
            "lines (_labels(tenant=...)) — tenant meters would "
            "aggregate worlds together", "unlabeled-render", metrics_rel))
    return problems
