"""Pass `events` — flight-recorder / realization-tracing drift
(migrated from tools/check_events.py, which remains as a shim).

The post-mortem journal is only trustworthy if its schema, its emit
sites and its operator documentation agree: every literal emit kind is
declared in flightrec.EVENT_KINDS, every declared kind has >= 1 emit
site and a README row, and the realization stage labels each have a
README row with the antrea_tpu_policy_realization_seconds family
registered."""

from __future__ import annotations

import ast
import pathlib
import re

from .core import Finding, SourceCache, analysis_pass

# Emit call sites carrying a LITERAL kind: the recorder's own keyword
# form and the planes' positional `_emit("kind", ...)` helpers.
EMIT_RES = (
    re.compile(r"\.emit\(\s*kind=\"([a-z0-9-]+)\""),
    re.compile(r"\._emit\(\s*\"([a-z0-9-]+)\""),
)


def _literal(src: SourceCache, path: pathlib.Path, name: str):
    """Evaluate a module-level literal assignment without importing."""
    text = src.text(path)
    if text is None:
        raise ValueError(f"{src.rel(path)} is missing")
    m = re.search(rf"^{name}\s*(?::[^=]+)?=\s*(\{{.*?^\}}|\(.*?^\))", text,
                  re.M | re.S)
    if m is None:
        raise ValueError(f"{src.rel(path)} defines no {name} literal")
    return ast.literal_eval(m.group(1))


def emit_sites(src: SourceCache) -> dict:
    """kind -> [package-relative paths with a literal emit of it]."""
    out: dict[str, list[str]] = {}
    for p in src.pkg_files():
        text = src.text(p) or ""
        for rx in EMIT_RES:
            for kind in rx.findall(text):
                out.setdefault(kind, []).append(src.rel(p))
    return out


@analysis_pass("events", "journal schema == emit sites == README event "
                         "and span tables")
def check(src: SourceCache) -> list[Finding]:
    flightrec_rel = "antrea_tpu/observability/flightrec.py"
    tracing_rel = "antrea_tpu/observability/tracing.py"

    def f(reason, obj, path=flightrec_rel):
        return Finding("events", path, 0, reason, obj=obj)

    try:
        kinds = _literal(src, src.pkg / "observability" / "flightrec.py",
                         "EVENT_KINDS")
        stages = _literal(src, src.pkg / "observability" / "tracing.py",
                          "REALIZATION_STAGES")
        registry = _literal(src, src.pkg / "observability" / "metrics.py",
                            "METRICS")
    except (OSError, ValueError) as e:
        return [f(str(e), "literal-unreadable")]
    readme = src.text(src.root / "README.md") or ""

    problems: list[Finding] = []
    sites = emit_sites(src)
    for kind in sorted(set(sites) - set(kinds)):
        problems.append(f(
            f"emit site uses undeclared kind {kind!r} "
            f"({', '.join(sites[kind])}) — declare it in EVENT_KINDS",
            f"undeclared:{kind}"))
    for kind in sorted(set(kinds) - set(sites)):
        problems.append(f(
            f"declared kind {kind!r} has no emit site under antrea_tpu/ — "
            f"dead schema row", f"dead:{kind}"))
    for kind in sorted(kinds):
        if f"`{kind}`" not in readme:
            problems.append(f(
                f"declared kind {kind!r} has no README row (event-kind "
                f"table in the Observability section)",
                f"undocumented:{kind}", "README.md"))

    fam = "antrea_tpu_policy_realization_seconds"
    if fam not in registry:
        problems.append(f(
            f"{fam} is not registered in observability/metrics.METRICS",
            "realization-family-unregistered",
            "antrea_tpu/observability/metrics.py"))
    if fam not in readme:
        problems.append(f(f"{fam} has no README row",
                          "realization-family-undocumented", "README.md"))
    for stage in stages:
        if f"`{stage}`" not in readme:
            problems.append(f(
                f"realization stage {stage!r} has no README row "
                f"(span-stage table in the Observability section)",
                f"stage-undocumented:{stage}", tracing_rel))
    return problems
