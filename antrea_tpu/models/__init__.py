from .pipeline import PipelineState, make_pipeline, init_state  # noqa: F401
