"""The tpuflow staged datapath pipeline (the flagship "model").

One jitted step processes a packet batch through the stage sequence the
reference realizes as OVS tables
(/root/reference/pkg/agent/openflow/framework.go:96-118 stages,
pipeline.go:114-195 tables), re-expressed as batched tensor transforms:

  ConntrackState   device conn-table lookup; established (-new+est) bypasses
                   all policy tables, reproducing the ct_state semantics in
                   docs/design/ovs-pipeline.md:1685-1691.
  ServiceLB        exact-match frontend lookup + endpoint selection: session
                   affinity (learn-flow analog, pipeline.go:2316) or 5-tuple
                   hash over the endpoint buckets (group select analog);
                   no-endpoint services reject (SvcReject packet-in analog).
  EndpointDNAT     rewrite dst to the chosen endpoint (ct(commit,nat) analog).
  Egress/Ingress   the conjunctive-match classification kernel (ops/match)
  security         on the POST-DNAT tuple (PreRouting precedes EgressSecurity
                   in the reference's stage order).
  ConntrackCommit  allowed new connections enter the conn table (batched
                   scatter) => subsequent packets take the est fast path.

State (conn table + affinity table) is carried functionally: step(state, ...)
-> (state', verdicts).  Tables are direct-mapped hash tables in device memory;
a slot collision evicts (cache semantics — correctness is preserved because a
miss just re-classifies, and endpoint choice is a deterministic hash).

Batch semantics are "simultaneous arrival": lookups see the state at batch
start, commits apply at batch end.  Within-batch same-slot writes are
last-writer-wins (enforced deterministically, see _scatter_last).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..compiler.compile import ACT_ALLOW, ACT_REJECT, CompiledPolicySet
from ..compiler.services import ServiceTables
from ..ops import hashing
from ..ops.match import DeviceRuleSet, StaticMeta, classify_batch, to_device

MISS = jnp.int32(-1)


class ConnTable(NamedTuple):
    """Direct-mapped connection table; row N (the last) is a write dump for
    masked-out scatters."""

    key_src: jax.Array  # (N+1,) i32 flipped bits
    key_dst: jax.Array
    key_pp: jax.Array  # sport<<16 | dport
    key_proto: jax.Array
    valid: jax.Array  # (N+1,) i32 0/1
    dnat_ip_f: jax.Array  # resolved post-DNAT dst
    dnat_port: jax.Array
    ts: jax.Array  # last-seen seconds


class AffinityTable(NamedTuple):
    key_client: jax.Array  # (M+1,) i32 flipped bits
    key_svc: jax.Array  # (M+1,) i32
    valid: jax.Array
    ep: jax.Array  # endpoint slot index within the service bucket row
    ts: jax.Array  # creation seconds (hard timeout, no refresh — learn-flow)


class PipelineState(NamedTuple):
    conn: ConnTable
    aff: AffinityTable


class DeviceServiceTables(NamedTuple):
    uip_f: jax.Array
    ppk: jax.Array
    slot_svc: jax.Array
    n_ep: jax.Array
    has_ep: jax.Array
    aff_timeout: jax.Array
    ep_ip_f: jax.Array
    ep_port: jax.Array


class PipelineMeta(NamedTuple):
    match: StaticMeta
    conn_slots: int
    aff_slots: int
    ct_timeout_s: int


def svc_to_device(st: ServiceTables) -> DeviceServiceTables:
    return DeviceServiceTables(
        uip_f=jnp.asarray(st.uip_f),
        ppk=jnp.asarray(st.ppk),
        slot_svc=jnp.asarray(st.slot_svc),
        n_ep=jnp.asarray(st.n_ep),
        has_ep=jnp.asarray(st.has_ep),
        aff_timeout=jnp.asarray(st.aff_timeout),
        ep_ip_f=jnp.asarray(st.ep_ip_f),
        ep_port=jnp.asarray(st.ep_port),
    )


def init_state(conn_slots: int = 1 << 20, aff_slots: int = 1 << 18) -> PipelineState:
    def zeros(n):
        return jnp.zeros(n + 1, dtype=jnp.int32)

    conn = ConnTable(
        key_src=zeros(conn_slots),
        key_dst=zeros(conn_slots),
        key_pp=zeros(conn_slots),
        key_proto=zeros(conn_slots),
        valid=zeros(conn_slots),
        dnat_ip_f=zeros(conn_slots),
        dnat_port=zeros(conn_slots),
        ts=zeros(conn_slots),
    )
    aff = AffinityTable(
        key_client=zeros(aff_slots),
        key_svc=zeros(aff_slots),
        valid=zeros(aff_slots),
        ep=zeros(aff_slots),
        ts=zeros(aff_slots),
    )
    return PipelineState(conn=conn, aff=aff)


def _raw_bits(x_f: jax.Array) -> jax.Array:
    """Sign-flipped i32 -> i32 whose u32 reinterpretation is the raw value."""
    return x_f ^ jnp.int32(-(2**31))


def _scatter_last(arr: jax.Array, slots: jax.Array, vals: jax.Array, mask: jax.Array, dump: int):
    """Masked scatter with deterministic last-writer-wins on duplicate slots.

    XLA leaves overlapping scatter order unspecified; we disambiguate by
    scattering the winning batch index first (max wins), then gathering each
    slot's winner's value.  Cost: one extra scatter+gather — negligible next
    to the rule scan.
    """
    B = slots.shape[0]
    slots_m = jnp.where(mask, slots, dump)
    order = jnp.arange(B, dtype=jnp.int32)
    winner = jnp.full(arr.shape[0], -1, dtype=jnp.int32).at[slots_m].max(order)
    win_idx = winner[slots_m]  # (B,) winning batch index for my slot
    is_winner = (win_idx == order) & mask
    return arr.at[jnp.where(is_winner, slots, dump)].set(vals)


def make_pipeline(
    cps: CompiledPolicySet,
    svc: ServiceTables,
    *,
    chunk: int = 512,
    conn_slots: int = 1 << 20,
    aff_slots: int = 1 << 18,
    ct_timeout_s: int = 3600,
):
    """-> (step fn, initial PipelineState, (DeviceRuleSet, DeviceServiceTables)).

    step(state, drs, dsvc, src_f, dst_f, proto, sport, dport, now) ->
    (state', out dict).  drs/dsvc are explicit args so a control-plane bundle
    commit is just "call with the new tensors" — the double-buffered rule-swap
    analog of OVS bundle transactions (ofctrl_bridge.go:468).
    """
    drs, match_meta = to_device(cps, chunk)
    dsvc = svc_to_device(svc)
    meta = PipelineMeta(
        match=match_meta,
        conn_slots=conn_slots,
        aff_slots=aff_slots,
        ct_timeout_s=ct_timeout_s,
    )
    state = init_state(conn_slots, aff_slots)

    def step(state, drs, dsvc, src_f, dst_f, proto, sport, dport, now):
        return pipeline_step(
            state, drs, dsvc, src_f, dst_f, proto, sport, dport, now, meta=meta
        )

    return step, state, (drs, dsvc)


def _pipeline_step(
    state: PipelineState,
    drs: DeviceRuleSet,
    dsvc: DeviceServiceTables,
    src_f: jax.Array,
    dst_f: jax.Array,
    proto: jax.Array,
    sport: jax.Array,
    dport: jax.Array,
    now: jax.Array,  # scalar i32 seconds
    *,
    meta: PipelineMeta,
):
    conn, aff = state.conn, state.aff
    B = src_f.shape[0]

    src_raw = _raw_bits(src_f)
    dst_raw = _raw_bits(dst_f)
    pp = (sport << 16) | dport

    # ---- ConntrackState: lookup -------------------------------------------
    h = hashing.flow_hash(src_raw, dst_raw, proto, sport, dport, xp=jnp)
    slot = (h & jnp.uint32(meta.conn_slots - 1)).astype(jnp.int32)
    ct_key_hit = (
        (conn.valid[slot] == 1)
        & (conn.key_src[slot] == src_f)
        & (conn.key_dst[slot] == dst_f)
        & (conn.key_pp[slot] == pp)
        & (conn.key_proto[slot] == proto)
    )
    fresh = (now - conn.ts[slot]) <= meta.ct_timeout_s
    est = ct_key_hit & fresh

    # ---- ServiceLB + EndpointDNAT -----------------------------------------
    row = jnp.searchsorted(dsvc.uip_f, dst_f, side="left")
    row = jnp.clip(row, 0, dsvc.uip_f.shape[0] - 1)
    ip_is_svc = dsvc.uip_f[row] == dst_f
    key = (proto << 16) + dport
    slot_eq = dsvc.ppk[row] == key[:, None]  # (B, MAXP)
    slot_found = slot_eq.any(axis=1)
    slot_col = jnp.argmax(slot_eq, axis=1)
    svc_idx = jnp.where(
        ip_is_svc & slot_found, dsvc.slot_svc[row, slot_col], MISS
    )
    is_svc = svc_idx >= 0
    svc_safe = jnp.clip(svc_idx, 0, dsvc.n_ep.shape[0] - 1)
    no_ep = is_svc & (dsvc.has_ep[svc_safe] == 0)

    # Session affinity lookup (ClientIP affinity, hard timeout).
    aff_on = is_svc & (dsvc.aff_timeout[svc_safe] > 0)
    ah = hashing.fnv_mix([src_raw, svc_safe], xp=jnp)
    aslot = (ah & jnp.uint32(meta.aff_slots - 1)).astype(jnp.int32)
    aff_key_hit = (
        (aff.valid[aslot] == 1)
        & (aff.key_client[aslot] == src_f)
        & (aff.key_svc[aslot] == svc_idx)
    )
    aff_fresh = (now - aff.ts[aslot]) <= dsvc.aff_timeout[svc_safe]
    aff_hit = aff_on & aff_key_hit & aff_fresh

    hash_ep = (h.astype(jnp.int32) & jnp.int32(0x7FFFFFFF)) % dsvc.n_ep[svc_safe]
    ep_col = jnp.where(aff_hit, aff.ep[aslot], hash_ep)
    ep_col = jnp.clip(ep_col, 0, dsvc.ep_ip_f.shape[1] - 1)

    dnat_ip_new = jnp.where(is_svc & ~no_ep, dsvc.ep_ip_f[svc_safe, ep_col], dst_f)
    dnat_port_new = jnp.where(is_svc & ~no_ep, dsvc.ep_port[svc_safe, ep_col], dport)

    # Established connections reuse the committed NAT resolution.
    dnat_ip = jnp.where(est, conn.dnat_ip_f[slot], dnat_ip_new)
    dnat_port = jnp.where(est, conn.dnat_port[slot], dnat_port_new)

    # ---- Egress/Ingress security (post-DNAT tuple) ------------------------
    cls = classify_batch(drs, src_f, dnat_ip, proto, dnat_port, meta=meta.match)

    # ---- verdict resolution ----------------------------------------------
    # est bypass: -new+est traffic skips policy tables (ovs-pipeline.md:1685).
    # no-endpoint services reject before policy (SvcReject).
    code = jnp.where(
        est,
        ACT_ALLOW,
        jnp.where(no_ep, ACT_REJECT, cls["code"]),
    ).astype(jnp.int32)

    # ---- ConntrackCommit ---------------------------------------------------
    commit = (~est) & (code == ACT_ALLOW)
    dump = meta.conn_slots
    conn = ConnTable(
        key_src=_scatter_last(conn.key_src, slot, src_f, commit, dump),
        key_dst=_scatter_last(conn.key_dst, slot, dst_f, commit, dump),
        key_pp=_scatter_last(conn.key_pp, slot, pp, commit, dump),
        key_proto=_scatter_last(conn.key_proto, slot, proto, commit, dump),
        valid=_scatter_last(conn.valid, slot, jnp.ones(B, jnp.int32), commit, dump),
        dnat_ip_f=_scatter_last(conn.dnat_ip_f, slot, dnat_ip, commit, dump),
        dnat_port=_scatter_last(conn.dnat_port, slot, dnat_port, commit, dump),
        ts=_scatter_last(conn.ts, slot, jnp.full(B, now, jnp.int32), commit, dump),
    )
    # Refresh last-seen on established hits (idle-timeout semantics).
    refresh_slot = jnp.where(est, slot, dump)
    conn = conn._replace(ts=conn.ts.at[refresh_slot].set(now))

    # Affinity learn: new service packets on affinity services without a live
    # entry learn their endpoint — before policy verdict, like the OVS learn
    # action in ServiceLB (pipeline.go:2316).
    learn = (~est) & aff_on & ~aff_hit & ~no_ep
    adump = meta.aff_slots
    aff = AffinityTable(
        key_client=_scatter_last(aff.key_client, aslot, src_f, learn, adump),
        key_svc=_scatter_last(aff.key_svc, aslot, svc_idx, learn, adump),
        valid=_scatter_last(aff.valid, aslot, jnp.ones(B, jnp.int32), learn, adump),
        ep=_scatter_last(aff.ep, aslot, ep_col, learn, adump),
        ts=_scatter_last(aff.ts, aslot, jnp.full(B, now, jnp.int32), learn, adump),
    )

    out = {
        "code": code,
        "est": est.astype(jnp.int32),
        "svc_idx": svc_idx,
        "dnat_ip_f": dnat_ip,
        "dnat_port": dnat_port,
        "egress_code": jnp.where(est, ACT_ALLOW, cls["egress_code"]),
        "egress_rule": jnp.where(est, MISS, cls["egress_rule"]),
        "ingress_code": jnp.where(est, ACT_ALLOW, cls["ingress_code"]),
        "ingress_rule": jnp.where(est, MISS, cls["ingress_rule"]),
        "committed": commit.astype(jnp.int32),
    }
    return PipelineState(conn=conn, aff=aff), out


# jit wrapper: meta is static.
pipeline_step = jax.jit(_pipeline_step, static_argnames=("meta",))
