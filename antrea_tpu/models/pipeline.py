"""The tpuflow staged datapath pipeline (the flagship "model").

One jitted step processes a packet batch through the stage semantics the
reference realizes as OVS tables
(/root/reference/pkg/agent/openflow/framework.go:96-118 stages,
pipeline.go:114-195 tables), re-architected around the same two-tier design
OVS itself uses for performance — a per-flow exact-match cache in front of
the full classifier (OVS's EMC/megaflow cache + kernel conntrack, which the
reference leans on for its own datapath performance;
docs/design/ovs-pipeline.md conntrack sections):

  FAST PATH (every packet, pure gathers — the throughput path):
    unified flow cache keyed by the 5-tuple.  A hit yields the cached
    verdict, DNAT resolution, rule attribution and service id.  Entries are
    generation-tagged:
      * ALLOW entries are inserted with the ETERNAL generation — they are
        the conntrack-committed connections, and a hit is exactly the
        ct_state -new+est policy-table bypass of the reference
        (docs/design/ovs-pipeline.md:1685-1691): established connections
        keep flowing (and keep their DNAT endpoint) across policy changes.
      * DROP/REJECT entries carry the rule generation — a control-plane
        bundle commit bumps `gen`, instantly invalidating every cached
        denial (the megaflow revalidation analog) while leaving
        established-connection state untouched.

  SLOW PATH (cache misses only, under lax.cond so it costs nothing in
  steady state; chunked by a while_loop for cold batches):
    ServiceLB     exact-match frontend lookup, session affinity (learn-flow
                  analog, ref pipeline.go serviceLearnFlow), endpoint
                  selection by deterministic 5-tuple hash (group select
                  analog), no-endpoint reject (SvcReject analog).
    EndpointDNAT  rewrite dst to the chosen endpoint (ct(commit,nat)).
    Egress/Ingress security
                  the conjunctive-match classification kernel (ops/match)
                  on the POST-DNAT tuple.
    Commit        verdict + DNAT + rule ids inserted into the flow cache
                  (ConntrackCommit analog; denials are cached too, as OVS
                  caches drop megaflows).

State is carried functionally: step(state, ...) -> (state', verdicts).
Tables are direct-mapped hash tables in device memory as SEPARATE (N+1,)
i32 columns — on TPU, independent 1-D gathers are markedly faster than
row-packed (N, 8) gathers (measured on v5e), and the +1 row is a write dump
for masked scatters.  A slot collision evicts (cache semantics — a miss
just re-classifies; endpoint choice is a deterministic hash, so re-derived
state is identical).

Batch semantics are "simultaneous arrival": lookups see the state at batch
start, inserts apply at batch end, last-writer-wins deterministically on
within-batch slot duplicates (see _scatter_last).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..apis.controlplane import PROTO_TCP
from ..compiler.compile import ACT_ALLOW, ACT_REJECT, CompiledPolicySet
from ..compiler.services import ServiceTables
from ..ops import hashing
from ..ops import match as _m
from ..ops.match import (PRUNE_HIST_BOUNDS, DeviceRuleSet, StaticMeta,
                         classify_batch, to_device, to_host)

# Python ints, never eager jnp scalars: see the BIG comment in ops/match.py.
MISS = -1
# Generation tag reserved for conntrack-committed (ALLOW) entries; rule
# generations are taken mod GEN_ETERNAL so they never collide with it.
GEN_BITS = 22
GEN_ETERNAL = (1 << GEN_BITS) - 1
# Bit 31 of the packed proto/gen key word marks a REPLY-direction entry
# (the reverse-tuple conntrack row committed alongside every ALLOW — the
# ct reply-direction state of the reference's ConntrackZone/UnSNAT tables,
# /root/reference/pkg/agent/openflow/pipeline.go UnSNAT/ConntrackState;
# docs/design/ovs-pipeline.md ct sections).
REPLY_BIT = -(2**31)

# Flow-entry meta column 3 layout: bit 31 SNAT mark, bit 30 DSR mark,
# bit 29 CONFIRMED (two-way traffic seen — the kernel-conntrack
# SYN_SENT -> ESTABLISHED transition; set on the first reply-direction hit
# and propagated to the partner entry), bits 0-28 the partner-refresh
# stamp (seconds mod 2^29; ages compare in mod arithmetic, exact for any
# live entry).
PREF_MASK = (1 << 29) - 1
CONF_BIT = 1 << 29
DSR_BIT = 1 << 30

# Thrash-resistant replacement (round 8, opt-in via second_chance): a
# 2-bit saturating collision counter in meta3 bits 27-28.  A live,
# CONFIRMED (two-way-traffic) established entry survives a colliding
# insert while its counter is below CHANCE_MAX — the challenger simply
# stays uncached (cache semantics: it re-classifies on its next packet)
# and the counter bumps once per commit pass; the entry's own next hit
# resets it.  An ACTIVE established flow therefore cannot be evicted by
# a gen_cache_thrash storm (its hits keep resetting the counter), while
# an idle-but-unconfirmed or silent entry yields after CHANCE_MAX
# collisions — bounded protection, never a wedged slot.  With the knob
# on, the partner-refresh stamp narrows to bits 0-26 (mod-2^27 age
# arithmetic, exact for any live entry); off (the default) keeps the
# full PREF_MASK layout and the compiled step bit-identical.
CHANCE_SHIFT = 27
CHANCE_MAX = 3
CHANCE_MASK = CHANCE_MAX << CHANCE_SHIFT
PREF_MASK_CHANCE = (1 << CHANCE_SHIFT) - 1

# REJECT synthesis kinds (ref pkg/agent/controller/networkpolicy/reject.go:
# TCP gets an RST, everything else an ICMP port-unreachable).
REJECT_NONE = 0
REJECT_TCP_RST = 1
REJECT_ICMP_UNREACH = 2

# TCP wire flag bits consumed by conntrack teardown.
TCP_FIN = 0x01
TCP_RST = 0x04
_TEARDOWN_FLAGS = TCP_FIN | TCP_RST


def no_commit_mask(dst, proto, flags, xp=np):
    """Never-cacheable lanes of a v4 miss batch: multicast destinations
    (conntrack bypass) and FIN/RST-flagged TCP misses (a closing segment
    is not a new flow).  The ONE host-side commit-gating expression the
    drain/fast-dispatch paths share — tpuflow and the mesh engine both
    consume it; the fused device walk derives its own family-aware
    variant in models/forwarding.py."""
    return ((xp.asarray(dst) >> 28) == 0xE) | (
        (xp.asarray(proto) == PROTO_TCP)
        & ((xp.asarray(flags) & _TEARDOWN_FLAGS) != 0)
    )

# Slow-path phase bits (PipelineMeta.phases): a PROFILING surface, not a
# correctness knob — masking a phase substitutes cheap defaults so the
# on-device cost of each churn-loop section can be isolated by telescoped
# differencing (models/profile.py; round-5 verdict weak #1: the churn
# regime was never profiled).  Production datapaths always run PH_ALL.
#   PH_SLOW    miss-detect scaffolding: index compaction, the chunked
#              round loop, output scatters (the lax.cond body itself)
#   PH_LB      ServiceLB frontend lookup + affinity + endpoint choice
#   PH_CLS     the conjunctive-match classifier on the post-DNAT tuple
#   PH_CLS_SUM the classifier's AGGREGATE phase alone (round-7 two-level
#              pruning, ops/match summary_only): summary gathers + AND +
#              short-circuit defaults, no candidate gather and no
#              fallback.  Only meaningful under PH_CLS's absence and a
#              prune_budget > 0 meta (a no-op bit otherwise) — the
#              profiler entry that splits summary-gather from
#              candidate-gather cost.
#   PH_COMMIT  flow-cache insert prep + both-direction scatters + learn
#   PH_EVICT   eviction accounting (requires PH_COMMIT: it audits the
#              insert targets)
PH_SLOW = 1
PH_LB = 2
PH_CLS = 4
PH_COMMIT = 8
PH_EVICT = 16
PH_CLS_SUM = 32
PH_ALL = PH_SLOW | PH_LB | PH_CLS | PH_COMMIT | PH_EVICT | PH_CLS_SUM


def _prune_bucket_counts(cand: jax.Array, mask: jax.Array) -> jax.Array:
    """Per-lane candidate-superblock counts -> per-bucket counts PLUS a
    trailing value-sum element: (len(PRUNE_HIST_BOUNDS)+2,) i32.  Bucket
    indexing replicates observability.metrics.Histogram.observe's
    bisect_left over the SAME bounds (ops/match.PRUNE_HIST_BOUNDS), so
    the device counts merge into the host histogram loss-free
    (Histogram.add_counts)."""
    bounds = jnp.asarray(PRUNE_HIST_BOUNDS, jnp.int32)
    idx = (cand[:, None] > bounds[None, :]).sum(axis=1)  # == bisect_left
    mi = mask.astype(jnp.int32)
    counts = jnp.zeros(len(PRUNE_HIST_BOUNDS) + 1, jnp.int32).at[idx].add(mi)
    vsum = (cand * mi).sum(dtype=jnp.int32)
    return jnp.concatenate([counts, vsum[None]])


def reject_kind_of(code, proto, xp=jnp):
    """REJECT synthesis kind for a verdict (scalar or array): TCP -> RST,
    anything else -> ICMP port-unreachable; 0 when not a REJECT."""
    return xp.where(
        code == ACT_REJECT,
        xp.where(proto == PROTO_TCP, REJECT_TCP_RST, REJECT_ICMP_UNREACH),
        REJECT_NONE,
    )


class FlowCache(NamedTuple):
    """Direct-mapped unified flow cache, row-packed for the fast path.

    Layout chosen from measurement on v5e (see docstring history): the fast
    path is gather-bound, and one (N, 4) ROW gather is ~10-30x faster than
    four 1-D column gathers (contiguous 16B reads vs four scattered 4B
    reads), while row SCATTERS are slow and 1-D column scatters fast — so
    the hit-path write (ts refresh) keeps its own column and full-entry
    writes (inserts) happen only on the miss path where the batch is small.

      keys (N+1, 4) i32: [src_f, dst_f, sport<<16|dport, proto|0x100|gen<<9]
        key_pg packs proto (8 bits + valid bit 8) with the entry generation
        (GEN_BITS): zero rows (valid bit unset) can never match a packet.
        Bit 31 (REPLY_BIT) marks a reply-direction entry (below).
      meta (N+1, 4) i32: [dnat_ip_f, meta1, rules,
                          snat<<31|dsr<<30|conf<<29|pref]
        meta1 = code(2) | (svc_idx+1)(14) | dnat_port(16)
        rules = (rule_in+1)(16) | (rule_out+1)(16); 0 = default/none
        pref = last partner-refresh attempt seconds mod 2^29 (29 bits;
        ages compare in mod arithmetic, exact for any live entry);
        bits 31/30 cache the frontend SNAT mark and the DSR delivery mark
        at commit time, so an established connection keeps both marks even
        if later service updates renumber LB programs (the ct-mark
        persistence analog — both marks live in ct_mark in the reference);
        bit 29 is the conntrack CONFIRMED state (see CONF_BIT)
      ts   (N+1,)  i32: last-seen seconds (refreshed on every hit)
      pkts/octets + pkts_hi/octets_hi (N+1,) i32: per-DIRECTION traffic
        counters (conntrack OriginalPackets/OriginalBytes,
        flowexporter/types.go:59) in 64-bit little-endian limb pairs —
        the low limb is the u32 view of the i32 column, the high limb
        carries the overflow, so volumes accumulate to 2^63 like the
        kernel's u64 counters instead of saturating at i32 (the old
        documented 2GB bound).  TPU lanes stay i32 (no x64 dependency);
        the hit path adds with a wrapping scatter + one carry per slot
        (_wide_add), exact as long as ONE entry receives < 2^32 bytes
        within a single batch.  1-D columns because the hit path updates
        them with fast column scatters (the layout rationale above);
        zero-cost when PipelineMeta.count_flow_stats is off (the update
        compiles out).

    dst in keys is the ORIGINAL (pre-DNAT) dst; dnat_ip_f the resolved one.

    Every ALLOW commit also inserts a REPLY-direction entry (conntrack
    commits both directions): keyed on the post-DNAT tuple with ports
    swapped (endpoint ip, client ip, ep_port<<16|client_port), REPLY_BIT
    set, eternal generation; its meta carries the UN-DNAT rewrite — the
    original frontend (pre-DNAT dst ip / dst port) that the reply packet's
    source must be restored to.  A reply hit is an established-connection
    hit (est bypass of the policy tables) with `reply`=1 in the output.
    Occupancy cost: a committed connection takes two slots, as kernel
    conntrack keys both tuple directions.
    """

    keys: jax.Array
    meta: jax.Array
    ts: jax.Array
    pkts: jax.Array
    octets: jax.Array
    pkts_hi: jax.Array
    octets_hi: jax.Array


class AffinityTable(NamedTuple):
    """Session-affinity learn table (slow-path only)."""

    key_client: jax.Array  # (M+1,) sign-flipped client ip
    key_svc: jax.Array  # (M+1,) service index
    ep: jax.Array  # endpoint slot within the service bucket row
    ts: jax.Array  # creation seconds (hard timeout, no refresh — learn-flow)


class PipelineState(NamedTuple):
    flow: FlowCache
    aff: AffinityTable


class DeviceServiceTables(NamedTuple):
    uip_f: jax.Array
    ppk: jax.Array
    slot_svc: jax.Array
    n_ep: jax.Array
    has_ep: jax.Array
    aff_timeout: jax.Array
    ep_base: jax.Array  # (P,) offsets into the flat endpoint arrays
    ep_ip_f: jax.Array  # (E,) flat — unbounded endpoints per program
    ep_port: jax.Array  # (E,) flat
    slot_snat: jax.Array  # (NU, MAXP) 0/1 per-frontend SNAT-mark flag
    prog_svc: jax.Array  # (P,) owning service index per program (toServices)
    prog_dsr: jax.Array  # (P,) 0/1 per-program DSR delivery flag
    # v6 frontend sub-table + wide endpoint words (compiler/services.py
    # dual-stack split; (0, ...) shapes compile the v6 probe out).
    uip6_w: jax.Array  # (NU6, 4) sorted lex, per-word flipped
    ppk6: jax.Array  # (NU6, MAXP6)
    slot_svc6: jax.Array
    slot_snat6: jax.Array
    ep_ipw_f: jax.Array  # (E, 4) wide flipped words, every endpoint


class PipelineMeta(NamedTuple):
    match: StaticMeta
    flow_slots: int
    aff_slots: int
    # Per-state conntrack lifetimes (the kernel's nf_conntrack_tcp_timeout_*
    # distinctions, polled by the reference's flow exporter via
    # conntrack_linux.go): ct_timeout_s is the TCP ESTABLISHED (confirmed)
    # lifetime; syn covers half-open TCP (committed, no reply seen);
    # other_* cover non-TCP (kernel UDP unreplied/stream).  None = inherit
    # ct_timeout_s (per-state handling compiles out entirely).
    ct_timeout_s: int
    miss_chunk: int  # slow-path round size
    ct_syn_timeout_s: Optional[int] = None
    ct_other_new_s: Optional[int] = None
    ct_other_est_s: Optional[int] = None
    # Classify cache misses through the fused pallas consumer
    # (ops/match.classify_batch fused=True) — shard-aware: composes with
    # the rule-axis hit_combine seam via global word offsets.
    fused: bool = False
    # Maintain per-entry packet/byte counters (FlowCache.pkts/octets).
    # Off by default: counting adds a column gather + two scatters to the
    # hit path, the cost the kernel pays only when the observability
    # plane (FlowExporter gate) wants volumes.
    count_flow_stats: bool = False
    # Flow-cache key row width: 4 (v4-only: [src, dst, pp, pg]) or 10
    # (dual-stack: [s0..s3, d0..d3, pp, pg] — addresses in wide v4-mapped
    # word form, the xxreg3 analog).  Static, so pure-v4 worlds compile the
    # narrow fast path unchanged.
    key_words: int = 4
    # Slow-path phase mask (PH_* bits).  Two legitimate uses: the profiler
    # compiles cumulative chains of it (models/profile), and the ASYNC
    # slow-path engine (datapath/slowpath) runs its fast step at phases=0 —
    # misses then keep the fast-path default image, get admitted to the
    # miss queue, and are classified later by a coalesced drain step at
    # PH_ALL.  Synchronous production datapaths always run PH_ALL.
    phases: int = PH_ALL
    # Fast-path default verdict for UNclassified miss lanes (only
    # observable when PH_SLOW is masked, i.e. in the async fast step):
    # the miss-queue admission policy — ACT_ALLOW = provisional
    # default-forward (the OVS "normal" upcall treatment), ACT_DROP =
    # hold until the background engine classifies (datapath/slowpath).
    miss_code: int = ACT_ALLOW
    # Overlapped-drain maintenance fusion (ROADMAP item 2): the commit
    # pass already gathers each insert target's old key row for the
    # eviction audit; with drain_reclaim set it additionally reads the
    # target's ts/conf and splits overwrites of DEAD rows (idle-expired
    # per the per-state timeout, or stale-generation denials — both
    # already invisible to lookups) out of `n_evict` into `n_reclaim`.
    # The drain round thus ages and revalidates the rows it touches in
    # the one pass that already holds them, and the engine's dedicated
    # full-table scans (age_scan/revalidate_scan) collapse into ONE fused
    # maintain_scan run only on epoch-stale heal.  Off (False) for
    # synchronous steps so their compiled program is unchanged.
    drain_reclaim: bool = False
    # One-kernel fast path (round 8): the slow path runs as ONE pallas
    # pass over the full batch (probe decode + aggregate prune +
    # candidate DMA + first-match + resolve + commit-row packing in
    # VMEM) instead of the chunked round loop — requires the aggregate
    # layer (match.prune_budget > 0) and the narrow (v4) key layout.
    # False keeps the staged program bit-identical.
    onepass: bool = False
    # Thrash-resistant replacement (the 2-bit second-chance counter, see
    # CHANCE_SHIFT above).  False keeps the compiled step bit-identical.
    second_chance: bool = False
    # Hot-path telemetry (observability/telemetry.py): the step emits
    # cheap in-kernel counter outputs — cache probe hit/stale/miss
    # splits, DMA half-blocks issued by the one-pass kernel, and
    # second-chance protection bumps — as tel_* keys in the output dict.
    # Everything is derived XLA-side from values the step already
    # gathers (kr0/ts0 from _cache_lookup, the guard's protected mask),
    # so False compiles the whole plane out: no extra gathers, no extra
    # outputs, HLO bit-identical — the same discipline as every knob
    # above.
    telemetry: bool = False

    @property
    def pref_mask(self) -> int:
        """Effective partner-refresh stamp mask: the second-chance
        counter (bits 27-28) narrows it; off keeps the full layout."""
        return PREF_MASK_CHANCE if self.second_chance else PREF_MASK

    @property
    def timeouts(self) -> tuple[int, int, int, int]:
        """(tcp_syn, tcp_est, other_new, other_est), Nones resolved."""
        t = self.ct_timeout_s
        return (
            self.ct_syn_timeout_s if self.ct_syn_timeout_s is not None else t,
            t,
            self.ct_other_new_s if self.ct_other_new_s is not None else t,
            self.ct_other_est_s if self.ct_other_est_s is not None else t,
        )


def svc_to_host(st: ServiceTables) -> DeviceServiceTables:
    """Numpy-resident variant (zero device placement; see ops/match.to_host)."""
    return DeviceServiceTables(
        uip_f=np.asarray(st.uip_f),
        ppk=np.asarray(st.ppk),
        slot_svc=np.asarray(st.slot_svc),
        n_ep=np.asarray(st.n_ep),
        has_ep=np.asarray(st.has_ep),
        aff_timeout=np.asarray(st.aff_timeout),
        ep_base=np.asarray(st.ep_base),
        ep_ip_f=np.asarray(st.ep_ip_f),
        ep_port=np.asarray(st.ep_port),
        slot_snat=np.asarray(st.slot_snat),
        prog_svc=np.asarray(st.prog_svc),
        prog_dsr=np.asarray(st.prog_dsr),
        uip6_w=np.asarray(st.uip6_w),
        ppk6=np.asarray(st.ppk6),
        slot_svc6=np.asarray(st.slot_svc6),
        slot_snat6=np.asarray(st.slot_snat6),
        ep_ipw_f=np.asarray(st.ep_ipw_f),
    )


def svc_to_device(st: ServiceTables) -> DeviceServiceTables:
    return jax.tree_util.tree_map(jnp.asarray, svc_to_host(st))


def init_state(
    flow_slots: int = 1 << 20, aff_slots: int = 1 << 18, xp=jnp,
    key_words: int = 4,
) -> PipelineState:
    def zeros(n):
        return xp.zeros(n + 1, dtype=xp.int32)

    wide = key_words > 4
    flow = FlowCache(
        keys=xp.zeros((flow_slots + 1, key_words), dtype=xp.int32),
        # Wide worlds store the 4-word DNAT resolution in meta cols 0-3
        # ([w0..w3, meta1, rules, zcol, pad] — padded to 8 so the row
        # gather stays a power-of-two stride); narrow keeps the 4-col
        # layout documented on FlowCache.
        meta=xp.zeros((flow_slots + 1, 8 if wide else 4), dtype=xp.int32),
        ts=zeros(flow_slots),
        pkts=zeros(flow_slots),
        octets=zeros(flow_slots),
        pkts_hi=zeros(flow_slots),
        octets_hi=zeros(flow_slots),
    )
    aff = AffinityTable(
        # Wide worlds key affinity on the client's 4-word form (v6
        # clients need all 128 bits — a truncated key would mis-affine
        # across colliding clients).
        key_client=(xp.zeros((aff_slots + 1, 4), dtype=xp.int32)
                    if wide else zeros(aff_slots)),
        key_svc=zeros(aff_slots),
        ep=zeros(aff_slots),
        ts=zeros(aff_slots),
    )
    return PipelineState(flow=flow, aff=aff)


def _meta_cols(A: int) -> tuple[int, int, int, int]:
    """Meta-row column indices (dn_narrow, meta1, rules, zcol) for an
    address width — the ONE place the narrow/wide meta layouts are
    defined (narrow: [dnat_ip, m1, rules, z]; wide: [w0..w3, m1, rules,
    z, pad], with the narrow dnat view = wide word 3, the v4-mapped
    column)."""
    return (0, 1, 2, 3) if A == 2 else (3, 4, 5, 6)


def _raw_bits(x_f: jax.Array) -> jax.Array:
    """Sign-flipped i32 -> i32 whose u32 reinterpretation is the raw value."""
    return x_f ^ jnp.int32(-(2**31))


# RFC 4291 v4-mapped word constants in FLIPPED lane space: flip(0) and
# flip(0xffff).  Single source of truth with utils/ip.key_to_flipped_words
# (the oracle-side projection) — parity-critical.
_MAP0 = -(2**31)
_MAPF = -(2**31) + 0xFFFF


def _wide_words(col_f: jax.Array, w6, is6) -> jax.Array:
    """(B,) flipped v4 column + optional (B,4) flipped v6 words + family
    mask -> (B, 4) wide address words (v4 lanes in v4-mapped form).  The
    ONE device-side implementation of the wide projection; every wide-key
    construction (fast path, reverse commit, partner probe, trace) must go
    through here."""
    m = jnp.stack([
        jnp.full_like(col_f, _MAP0), jnp.full_like(col_f, _MAP0),
        jnp.full_like(col_f, _MAPF), col_f,
    ], axis=1)
    if w6 is None:
        return m
    return jnp.where((is6 != 0)[:, None], w6, m)


def _winner_mask(n_slots, slots, mask, dump):
    """Deterministic last-writer-wins for duplicate slots in one batch."""
    B = slots.shape[0]
    slots_m = jnp.where(mask, slots, dump)
    order = jnp.arange(B, dtype=jnp.int32)
    winner = jnp.full((n_slots + 1,), -1, jnp.int32).at[slots_m].max(order)
    return (winner[slots_m] == order) & mask


def _scatter_last(arr, slots, vals, mask, dump):
    """Masked 1-D scatter with last-writer-wins on duplicate slots."""
    is_winner = _winner_mask(arr.shape[0] - 1, slots, mask, dump)
    return arr.at[jnp.where(is_winner, slots, dump)].set(vals)


def _scatter_last_rows(arr, slots, rows, mask, dump):
    """Masked row scatter ((M, K) payload into (N+1, K)) with
    last-writer-wins; used only on the miss path where M is small (row
    scatters are slow on TPU — see FlowCache layout rationale)."""
    is_winner = _winner_mask(arr.shape[0] - 1, slots, mask, dump)
    return arr.at[jnp.where(is_winner, slots, dump)].set(rows)


def _second_chance_guard(flow: FlowCache, slot2, keys2, ins2, now, meta, A,
                         dump):
    """Thrash-resistant replacement (meta.second_chance): suppress
    inserts whose direct-mapped target is a LIVE, CONFIRMED established
    entry still under its 2-bit collision budget, and bump that entry's
    counter once per commit pass (winner-deduplicated).  The challenger
    stays uncached — cache semantics, it re-classifies on its next
    packet — so a gen_cache_thrash storm cannot evict an active
    established flow on first collision.  -> (flow', ins2').

    Known divergence (cache-topology observable, verdict-safe): the
    chunked sync path runs one commit pass PER ROUND, so a step whose
    misses span multiple miss_chunk rounds can bump a slot once per
    round while the scalar twin bumps once per step — colliding
    challengers in a later round may then evict an entry the oracle
    keeps.  The evicted flow re-misses and re-classifies to the same
    verdict (the PR 6 lost-update discipline); the one-pass kernel and
    single-round passes match the oracle exactly.

    -> (flow', ins2', n_protected) — n_protected is the lane count the
    guard suppressed this pass (the telemetry `chance_bumps` counter),
    None unless meta.telemetry so the off path traces no extra ops."""
    ZC = _meta_cols(A)[3]
    tgt2 = jnp.where(ins2, slot2, dump)
    okr = flow.keys[tgt2]
    om3 = flow.meta[tgt2, ZC]
    id3 = 0xFF | REPLY_BIT
    tuple_differs = (
        (okr[:, : A + 1] != keys2[:, : A + 1]).any(axis=1)
        | ((okr[:, A + 1] & id3) != (keys2[:, A + 1] & id3))
    )
    ogen = (okr[:, A + 1] >> 9) & GEN_ETERNAL
    otmo = entry_timeout((om3 >> 29) & 1, okr[:, A + 1] & 0xFF,
                         meta.timeouts)
    cnt = (om3 >> CHANCE_SHIFT) & CHANCE_MAX
    protected = (
        ins2
        & (okr[:, A + 1] != 0)
        & tuple_differs
        & (ogen == GEN_ETERNAL)
        & (((om3 >> 29) & 1) != 0)
        & ((now - flow.ts[tgt2]) <= otmo)
        & (cnt < CHANCE_MAX)
    )
    ins2 = ins2 & ~protected
    n_protected = (protected.sum(dtype=jnp.int32) if meta.telemetry
                   else None)
    # One counter bump per protected slot per pass.
    win = _winner_mask(flow.keys.shape[0] - 1, slot2, protected, dump)
    bt = jnp.where(win, slot2, dump)
    cur = flow.meta[bt, ZC]
    newc = jnp.minimum(((cur >> CHANCE_SHIFT) & CHANCE_MAX) + 1, CHANCE_MAX)
    meta_col = (cur & ~CHANCE_MASK) | (newc << CHANCE_SHIFT)
    return (flow._replace(meta=flow.meta.at[bt, ZC].set(meta_col)), ins2,
            n_protected)


def _pack_meta1(code, svc_idx, dnat_port):
    return code | ((svc_idx + 1) << 2) | (dnat_port << 16)


def _unpack_meta1(m1):
    code = m1 & 3
    svc_idx = ((m1 >> 2) & 0x3FFF) - 1
    dnat_port = (m1 >> 16) & 0xFFFF
    return code, svc_idx, dnat_port


def _pack_rules(rule_in, rule_out):
    # Rule indices fit 16 bits each (check_rule_capacity, invoked by every
    # pipeline constructor, guards n_rules < 0xFFFE per direction; callers
    # composing to_device + _pack_rules directly must call it themselves).
    # Stored +1 so the zero row means "no rule" (MISS).
    return (rule_in + 1) | ((rule_out + 1) << 16)


def _unpack_rules(rp):
    return (rp & 0xFFFF) - 1, ((rp >> 16) & 0xFFFF) - 1


def _fused_pack_rows(src_f, dst_f, proto, sport, dport, pp, f_code, svc_idx,
                     dnat_ip, dnat_port, snat_m, dsr_m, f_ri, f_ro,
                     miss_m, nc_m, now, gen_w, n_slots, pmask):
    """XLA twin of the one-pass kernel's commit-row packing (round 8):
    the same _pack_meta1/_pack_rules/flow-hash formulas, producing the
    interleave-ready forward + reply rows for a set of lanes.  Used by
    the rule-sharded one-pass (rows pack post-pmin) and the fallback-
    lane override; the in-kernel pack mirrors it field for field
    (parity-pinned by tests/test_match_fused.py).  -> dict(committed,
    ins, rev_ins, rev_slot, keys8, meta8)."""
    committed = miss_m & (f_code == ACT_ALLOW) & ~nc_m
    ins = miss_m & ~nc_m
    rev_ins = ins & committed & (dsr_m == 0)
    egen = jnp.where(committed, GEN_ETERNAL, gen_w)
    pg_ins = proto | 0x100 | (egen << 9)
    m1 = _pack_meta1(f_code, svc_idx, dnat_port)
    rules_p = _pack_rules(f_ri, f_ro)
    pref_col = jnp.zeros_like(proto) + (now & pmask)
    zcol = (pref_col
            | jnp.where(snat_m > 0, REPLY_BIT, 0)
            | jnp.where(dsr_m > 0, DSR_BIT, 0))
    rev_h = hashing.flow_hash(_raw_bits(dnat_ip), _raw_bits(src_f), proto,
                              dnat_port, sport, xp=jnp)
    rev_slot = (rev_h & jnp.uint32(n_slots - 1)).astype(jnp.int32)
    rev_pg = proto | 0x100 | (GEN_ETERNAL << 9) | REPLY_BIT
    keys8 = jnp.stack(
        [src_f, dst_f, pp, pg_ins,
         dnat_ip, src_f, (dnat_port << 16) | sport, rev_pg], axis=1)
    meta8 = jnp.stack(
        [dnat_ip, m1, rules_p, zcol,
         dst_f, _pack_meta1(f_code, svc_idx, dport), rules_p, pref_col],
        axis=1)
    return dict(committed=committed, ins=ins, rev_ins=rev_ins,
                rev_slot=rev_slot, keys8=keys8, meta8=meta8)


class PolicyCapacityError(ValueError):
    """A compiled policy set exceeds a hard datapath capacity bound (e.g.
    the 16-bit packed rule-attribution space).  DETERMINISTIC: the same
    bundle fails the same way every time, so the agent classifies it as a
    permanent (poison-bundle) rejection and reports a Failed realization
    upstream instead of burning its retry/backoff loop on it
    (agent/controller.sync).  Subclasses ValueError for callers that
    predate the typed error."""


def check_rule_capacity(cps: CompiledPolicySet) -> None:
    """Rule attribution is cached in one packed 16/16 column (_pack_rules);
    guard both the single-chip and sharded pipelines against overflow."""
    for dt in (cps.ingress, cps.egress):
        if dt.n_rules >= 0xFFFE:
            raise PolicyCapacityError(
                f"flow-cache rule packing supports < 65534 rules per "
                f"direction, got {dt.n_rules}; split the policy set across "
                f"datapath instances (per-Node span dissemination keeps "
                f"per-instance rule counts bounded in the reference, "
                f"architecture.md:57-60)"
            )


def make_pipeline(
    cps: CompiledPolicySet,
    svc: ServiceTables,
    *,
    flow_slots: int = 1 << 20,
    aff_slots: int = 1 << 18,
    ct_timeout_s: int = 3600,
    miss_chunk: int = 4096,
    host: bool = False,
    ct_syn_timeout_s: Optional[int] = None,
    ct_other_new_s: Optional[int] = None,
    ct_other_est_s: Optional[int] = None,
    fused: bool = False,
    dual_stack: bool = False,
    count_flow_stats: bool = False,
    prune_budget: int = 0,
    second_chance: bool = False,
    onepass: Optional[bool] = None,
    telemetry: bool = False,
):
    """-> (step fn, initial PipelineState, (DeviceRuleSet, DeviceServiceTables)).

    step(state, drs, dsvc, src_f, dst_f, proto, sport, dport, now, gen) ->
    (state', out dict).  drs/dsvc are explicit args so a control-plane bundle
    commit is just "call with the new tensors + a bumped gen" — the
    double-buffered rule-swap analog of OVS bundle transactions
    (ofctrl_bridge.go:468); bumping gen invalidates cached denials while
    established (ALLOW) entries persist, per conntrack semantics.

    host=True keeps every tensor numpy-resident (no device placement) — for
    compile checks on hosts whose accelerator runtime may be broken; jit
    places numpy leaves itself at call time.
    """
    check_rule_capacity(cps)
    if host:
        drs, match_meta = to_host(cps, prune_budget=prune_budget)
        dsvc = svc_to_host(svc)
    else:
        drs, match_meta = to_device(cps, prune_budget=prune_budget)
        dsvc = svc_to_device(svc)
    meta = PipelineMeta(
        match=match_meta,
        flow_slots=flow_slots,
        aff_slots=aff_slots,
        ct_timeout_s=ct_timeout_s,
        miss_chunk=miss_chunk,
        ct_syn_timeout_s=ct_syn_timeout_s,
        ct_other_new_s=ct_other_new_s,
        ct_other_est_s=ct_other_est_s,
        fused=fused,
        key_words=10 if dual_stack else 4,
        count_flow_stats=count_flow_stats,
        # fused=True over an aggregate-pruned v4 world upgrades to the
        # one-kernel fast path (round 8); fused without the aggregate
        # layer (or with wide keys) keeps the staged consumer fusion.
        # An explicit onepass=False pins the staged kernel (the
        # bench_profile --mode prune regime); onepass=True demands it.
        onepass=(bool(fused and prune_budget > 0 and not dual_stack)
                 if onepass is None else bool(onepass)),
        second_chance=second_chance,
        telemetry=telemetry,
    )
    state = init_state(flow_slots, aff_slots, xp=np if host else jnp,
                       key_words=meta.key_words)

    def step(state, drs, dsvc, src_f, dst_f, proto, sport, dport, now, gen,
             v6=None, lens=None):
        return pipeline_step(
            state, drs, dsvc, src_f, dst_f, proto, sport, dport, now, gen,
            meta=meta, v6=v6, lens=lens,
        )

    step.meta = meta  # expose for callers embedding the step in larger jits
    return step, state, (drs, dsvc)


def _service_lb(
    aff: AffinityTable,
    dsvc: DeviceServiceTables,
    h: jax.Array,
    src_f: jax.Array,
    dst_f: jax.Array,
    proto: jax.Array,
    dport: jax.Array,
    now: jax.Array,
    aff_slots: int,
    wide=None,
):
    """ServiceLB + affinity + endpoint choice for a (miss) sub-batch.

    svc_idx is an LB-program index (compiler/services.py): ClusterIP
    frontends resolve to the cluster view (== service index), external
    frontends (LoadBalancer IP / NodePort) to their per-policy shadow view.

    dsr flags lanes whose program is a DSR delivery program (ref
    pipeline.go:145 DSRServiceMarkTable): the endpoint is SELECTED (it
    drives forwarding and policy) but the packet's L3 destination is NOT
    rewritten and no SNAT applies — dnat_ip/dnat_port then carry the
    delivery endpoint, with the no-rewrite semantic signaled by the flag.

    wide (dual-stack worlds): (saddr, daddr, is6) — the lanes' 4-word
    address forms.  v4 lanes probe the narrow frontend table exactly as
    in v4-only mode; v6 lanes probe the lexicographic v6 sub-table
    (dsvc.uip6_w — the metaProxier family split, proxier.go:1379-1465)
    and their DNAT resolution is the endpoint's wide word row.

    -> (svc_idx, no_ep, dnat_ip_f, dnat_port, snat, dsr, dnat_w, learn)
    — dnat_w is the wide post-DNAT dst ((M, 4), None in v4-only mode).
    """
    saddr = daddr = is6 = None
    if wide is not None:
        saddr, daddr, is6 = wide
    row = jnp.searchsorted(dsvc.uip_f, dst_f, side="left")
    row = jnp.clip(row, 0, dsvc.uip_f.shape[0] - 1)
    ip_is_svc = dsvc.uip_f[row] == dst_f
    key = (proto << 16) + dport
    slot_eq = dsvc.ppk[row] == key[:, None]  # (M, MAXP)
    slot_found = slot_eq.any(axis=1)
    slot_col = jnp.argmax(slot_eq, axis=1)
    hit_lane = ip_is_svc & slot_found
    if is6 is not None:
        # v6 lanes carry a don't-care v4 dst column: never match narrow.
        hit_lane = hit_lane & (is6 == 0)
    svc_idx = jnp.where(hit_lane, dsvc.slot_svc[row, slot_col], MISS)
    snat_sel = jnp.where(hit_lane, dsvc.slot_snat[row, slot_col], 0)

    if is6 is not None and dsvc.uip6_w.shape[0] > 0:
        # v6 frontend probe: exact 4-word match (all-pairs — the v6
        # frontend table is small; same shape rationale as
        # ops/match._searchsorted6).
        eq6 = (dsvc.uip6_w[None, :, :] == daddr[:, None, :]).all(axis=2)
        ip6_hit = eq6.any(axis=1)
        row6 = jnp.argmax(eq6, axis=1)
        slot_eq6 = dsvc.ppk6[row6] == key[:, None]
        hit6 = (is6 != 0) & ip6_hit & slot_eq6.any(axis=1)
        col6 = jnp.argmax(slot_eq6, axis=1)
        svc_idx = jnp.where(hit6, dsvc.slot_svc6[row6, col6], svc_idx)
        snat_sel = jnp.where(hit6, dsvc.slot_snat6[row6, col6], snat_sel)

    is_svc = svc_idx >= 0
    svc_safe = jnp.clip(svc_idx, 0, dsvc.n_ep.shape[0] - 1)
    no_ep = is_svc & (dsvc.has_ep[svc_safe] == 0)

    # Session affinity (ClientIP, hard timeout) — the learn-flow analog.
    aff_on = is_svc & (dsvc.aff_timeout[svc_safe] > 0)
    if saddr is None:
        src_raw = _raw_bits(src_f)
        ah = hashing.fnv_mix([src_raw, svc_safe], xp=jnp)
    else:
        # Wide client hash: all 4 raw words + the program — the oracle
        # twin mixes the identical sequence (PipelineOracle.fresh_walk).
        ah = hashing.fnv_mix(
            [_raw_bits(saddr[:, i]) for i in range(4)] + [svc_safe], xp=jnp
        )
    aslot = (ah & jnp.uint32(aff_slots - 1)).astype(jnp.int32)
    # Entry liveness = stored ep+1 > 0 (works even for learns at now == 0).
    # A stored ep slot >= the service's current endpoint count is stale
    # (endpoints shrank since the learn) — treat as a miss and re-select, the
    # analog of AntreaProxy's stale learn-flow/conntrack cleanup on endpoint
    # deletion (ref proxier.go syncProxyRules endpoint-change handling).
    if saddr is None:
        client_match = aff.key_client[aslot] == src_f
    else:
        client_match = (aff.key_client[aslot] == saddr).all(axis=1)
    aff_hit = (
        aff_on
        & (aff.ep[aslot] > 0)
        & (aff.ep[aslot] - 1 < dsvc.n_ep[svc_safe])
        & client_match
        & (aff.key_svc[aslot] == svc_idx)
        & ((now - aff.ts[aslot]) <= dsvc.aff_timeout[svc_safe])
    )
    hash_ep = (h.astype(jnp.int32) & jnp.int32(0x7FFFFFFF)) % dsvc.n_ep[svc_safe]
    ep_col = jnp.where(aff_hit, aff.ep[aslot] - 1, hash_ep)
    # Flat indirect endpoint gather — no per-program endpoint cap (the
    # reference's group buckets are unbounded, serviceEndpointGroup).
    eidx = jnp.clip(dsvc.ep_base[svc_safe] + ep_col, 0, dsvc.ep_ip_f.shape[0] - 1)

    use_ep = is_svc & ~no_ep
    dnat_ip = jnp.where(use_ep, dsvc.ep_ip_f[eidx], dst_f)
    dnat_port = jnp.where(use_ep, dsvc.ep_port[eidx], dport)
    dnat_w = None
    if saddr is not None:
        # Wide post-DNAT dst: v4 lanes map their narrow resolution; v6
        # service lanes gather the endpoint's wide row; v6 non-service
        # lanes keep their literal dst words.
        dnat_w = jnp.where(
            (use_ep & (is6 != 0))[:, None],
            dsvc.ep_ipw_f[eidx],
            _wide_words(dnat_ip, daddr, is6),
        )
    # SNAT is a property of the matched FRONTEND entry (NodePort/LB under
    # ETP=Cluster), not of the endpoint program.
    snat = jnp.where(use_ep, snat_sel, 0)
    # DSR is a property of the PROGRAM (dedicated per-service DSR view),
    # so fast-path hits can recover it from the cached svc_idx alone.
    dsr = jnp.where(use_ep, dsvc.prog_dsr[svc_safe], 0)
    learn = {
        "mask": aff_on & ~aff_hit & ~no_ep,
        "aslot": aslot,
        "client": src_f if saddr is None else saddr,
        "svc": svc_idx,
        "ep": ep_col + 1,  # stored +1: 0 means empty slot
    }
    return svc_idx, no_ep, dnat_ip, dnat_port, snat, dsr, dnat_w, learn


def _svc_ref_of(svc_idx: jax.Array, dsvc: DeviceServiceTables) -> jax.Array:
    """toServices probe identity (ops/match svcref contract): the lane's
    resolved LB program mapped to its OWNING service index via prog_svc;
    MISS (-1) for non-service lanes.  The ONE implementation shared by
    step and trace so the probe-key contract cannot drift between them."""
    return jnp.where(
        svc_idx >= 0,
        dsvc.prog_svc[jnp.clip(svc_idx, 0, dsvc.prog_svc.shape[0] - 1)],
        MISS,
    )


def entry_timeout(conf, proto, timeouts, xp=jnp):
    """Per-entry idle timeout from the CONFIRMED bit + protocol (scalar or
    array): the kernel's per-state conntrack lifetime selection.  Single
    source of truth for step/trace/dump on both datapaths."""
    t_syn, t_est, t_onew, t_oest = timeouts
    is_tcp = proto == PROTO_TCP
    return xp.where(
        is_tcp,
        xp.where(conf != 0, t_est, t_syn),
        xp.where(conf != 0, t_oest, t_onew),
    )


def _cache_lookup(flow, slot, addr, pp, pg_cur, pg_est, now, proto, meta):
    """Shared fast-path flow-cache probe for step and trace (single source of
    truth for the FlowCache row layout).

    addr is the packet's (B, A) address-column matrix — A=2 ([src_f,
    dst_f]) in v4-only worlds, A=8 (wide word form) in dual-stack worlds;
    key rows are [addr..., pp, pg].

    -> (hit, est, rpl, meta_row (B,4), key_row, ts_col) where meta_row/
    key_row/ts_col are the gathered cache rows (the one-pass kernel
    re-derives the probe from the SAME gathered rows, so the two probe
    decodes cannot diverge).  rpl flags reply-direction (reverse-tuple)
    hits: their meta row carries the un-DNAT rewrite (original service
    frontend ip/port) instead of a DNAT resolution.

    Freshness is per-state (entry_timeout): half-open TCP and non-TCP
    entries can carry shorter lifetimes than confirmed connections.  With
    uniform timeouts (the default) the per-lane selection compiles out.
    """
    A = addr.shape[1]
    kr = flow.keys[slot]  # (B, A+2) row gather
    kpg = kr[:, A + 1]
    pg_rpl = pg_est | REPLY_BIT
    key_hit = (
        (kr[:, :A] == addr).all(axis=1)
        & (kr[:, A] == pp)
        & ((kpg == pg_cur) | (kpg == pg_est) | (kpg == pg_rpl))
    )
    mr = flow.meta[slot]
    _, _, _, ZC = _meta_cols(A)
    tmo = meta.timeouts
    if tmo[0] == tmo[1] == tmo[2] == tmo[3]:
        timeout = tmo[1]  # uniform: scalar, no per-lane work
    else:
        timeout = entry_timeout((mr[:, ZC] >> 29) & 1, proto, tmo)
    fresh = (now - flow.ts[slot]) <= timeout
    hit = key_hit & fresh
    est = hit & ((kpg == pg_est) | (kpg == pg_rpl))
    rpl = hit & (kpg == pg_rpl)
    return hit, est, rpl, mr, kr, flow.ts[slot]


def _pipeline_step(
    state: PipelineState,
    drs: DeviceRuleSet,
    dsvc: DeviceServiceTables,
    src_f: jax.Array,
    dst_f: jax.Array,
    proto: jax.Array,
    sport: jax.Array,
    dport: jax.Array,
    now: jax.Array,  # scalar i32 seconds
    gen: jax.Array,  # scalar i32 rule-set generation (bundle commit counter)
    *,
    meta: PipelineMeta,
    hit_combine=None,
    valid=None,
    no_commit=None,
    flags=None,
    v6=None,
    lens=None,
    prune_exclude=None,
):
    flow, aff = state.flow, state.aff
    B = src_f.shape[0]
    N = meta.flow_slots
    M = meta.miss_chunk
    dump = N
    A = meta.key_words - 2  # address columns: 2 (v4) / 8 (dual-stack wide)
    if meta.onepass and (A != 2 or meta.match.prune_budget <= 0):
        raise ValueError(
            "the one-kernel fast path (onepass) requires the narrow v4 "
            "key layout and an aggregate-pruned meta (prune_budget > 0)")

    src_raw = _raw_bits(src_f)
    dst_raw = _raw_bits(dst_f)
    pp = (sport << 16) | dport
    gen_w = jnp.asarray(gen, jnp.int32) % GEN_ETERNAL  # never == GEN_ETERNAL

    # ---- fast path: flow-cache lookup (2 row gathers + 1 column gather) ----
    if A == 2:
        if v6 is not None:
            raise ValueError(
                "v6 lanes require a dual_stack pipeline "
                "(make_pipeline(dual_stack=True))"
            )
        saddr = daddr = is6 = None  # wide-mode-only locals
        addr = jnp.stack([src_f, dst_f], axis=1)
        h = hashing.flow_hash(src_raw, dst_raw, proto, sport, dport, xp=jnp)
    else:
        # Wide (dual-stack) addressing: every lane is a 4-word v4-mapped /
        # v6 quadruple (sign-flipped per word, utils/ip.key_to_words).
        if v6 is not None:
            src6w, dst6w, is6 = v6
        else:
            is6 = jnp.zeros_like(src_f)
            src6w = dst6w = None
        saddr = _wide_words(src_f, src6w, is6)
        daddr = _wide_words(dst_f, dst6w, is6)
        addr = jnp.concatenate([saddr, daddr], axis=1)
        h = hashing.flow_hash_wide(
            [addr[:, i] for i in range(8)], proto, sport, dport, xp=jnp
        )
    slot = (h & jnp.uint32(N - 1)).astype(jnp.int32)
    pg_cur = proto | 0x100 | (gen_w << 9)
    pg_est = proto | 0x100 | (GEN_ETERNAL << 9)
    hit, est, rpl, mr, kr0, ts0 = _cache_lookup(
        flow, slot, addr, pp, pg_cur, pg_est, now, proto, meta
    )
    if valid is not None:
        # Lane mask (SpoofGuard gating, models/forwarding.py): excluded
        # lanes neither refresh nor commit any state and take the fast-path
        # default image — the stage order of the reference, where
        # SpoofGuard drops happen BEFORE conntrack/policy tables.
        hit = hit & valid
        est = est & valid
        rpl = rpl & valid
    tel_on = meta.telemetry
    if tel_on:
        # Probe-split telemetry (hit / stale / miss), recomputed XLA-side
        # from the SAME gathered key rows the probe decoded (kr0), so it
        # costs three reductions and zero extra gathers.  `stale` = the
        # key matched but the entry aged out (the megaflow-revalidation
        # signal: the flow was cached and expired under traffic);
        # generation-stale denials count as plain misses — they are
        # invisible to lookups by design, not aged occupancy.  Lanes
        # another dispatch owns (mesh spill retries, prune_exclude) and
        # valid-masked lanes are excluded, the exactly-once discipline
        # prune metering already follows.
        tv = jnp.ones(B, bool) if valid is None else (valid != 0)
        if prune_exclude is not None:
            tv = tv & ~prune_exclude
        kpg0 = kr0[:, A + 1]
        key_hit0 = (
            (kr0[:, :A] == addr).all(axis=1)
            & (kr0[:, A] == pp)
            & ((kpg0 == pg_cur) | (kpg0 == pg_est)
               | (kpg0 == (pg_est | REPLY_BIT)))
        )
        tel_probe_hit = (hit & tv).sum(dtype=jnp.int32)
        tel_probe_stale = (key_hit0 & ~hit & tv).sum(dtype=jnp.int32)
        tel_probe_miss = (~key_hit0 & tv).sum(dtype=jnp.int32)
    DC, M1C, RC, ZC = _meta_cols(A)
    c_code, c_svc, c_dport = _unpack_meta1(mr[:, M1C])
    # Narrow dnat view: the v4 value (wide worlds: word 3, the v4-mapped
    # column — a don't-care for v6 lanes, whose consumers read c_dnat_w).
    c_dnat_ip = mr[:, DC]
    c_dnat_w = mr[:, 0:4] if A == 8 else None
    c_rule_in, c_rule_out = _unpack_rules(mr[:, RC])

    # Idle-timeout refresh for hits.
    flow = flow._replace(ts=flow.ts.at[jnp.where(hit, slot, dump)].set(now))

    if meta.second_chance:
        # Second-chance reset: a hit is the entry's "referenced" event —
        # clear the 2-bit collision counter so active flows keep their
        # protection (the CLOCK-algorithm reference bit, see CHANCE_SHIFT).
        ZC_ = _meta_cols(A)[3]
        tgt_h = jnp.where(hit, slot, dump)
        flow = flow._replace(meta=flow.meta.at[tgt_h, ZC_].set(
            flow.meta[tgt_h, ZC_] & ~CHANCE_MASK))

    if meta.count_flow_stats:
        # Per-direction traffic counters (conntrack OriginalPackets/
        # OriginalBytes, flowexporter/types.go:59): every hit adds to ITS
        # entry's columns.  64-bit accumulation in two i32 limbs (the
        # kernel's u64 counters; the old i32 saturation capped volumes at
        # 2GB): the low limb adds with a wrapping scatter, and one carry
        # per slot propagates into the high limb — exact as long as one
        # entry receives < 2^32 bytes within a SINGLE batch (a per-batch
        # bound, not a lifetime cap).
        lv = jnp.zeros(B, jnp.int32) if lens is None else lens
        ctgt = jnp.where(hit, slot, dump)
        cwin = _winner_mask(N, slot, hit, dump)  # one carry writer per slot

        def wide_add(lo, hi, add):
            old = lo[ctgt]
            lo = lo.at[ctgt].add(add)
            # u32 view shrank => the slot's low limb wrapped exactly once.
            carried = lo[ctgt].astype(jnp.uint32) < old.astype(jnp.uint32)
            hi = hi.at[jnp.where(cwin & carried, ctgt, dump)].add(1)
            return lo, hi

        new_pk, new_pkh = wide_add(flow.pkts, flow.pkts_hi,
                                   jnp.ones(B, jnp.int32))
        new_oc, new_och = wide_add(flow.octets, flow.octets_hi,
                                   jnp.maximum(lv, 0))
        flow = flow._replace(pkts=new_pk, octets=new_oc,
                             pkts_hi=new_pkh, octets_hi=new_och)

    # Conntrack refreshes BOTH tuple directions on traffic in either
    # direction (one kernel-ct connection == our two cache entries): an
    # active connection's reply leg must not idle out while forward traffic
    # keeps flowing (ovs-pipeline.md:1200 — reply traffic of an established
    # connection is never policy-dropped).  Refreshing the partner on EVERY
    # hit would add a key gather + ts scatter to the throughput path
    # (~20% measured on v5e), so it is DEFERRED: meta[:,3] (pref) records
    # the last partner-refresh attempt, and the partner walk runs only for
    # lanes older than ct_timeout/2 — under lax.cond, so batches with no
    # due lane pay nothing.  Sound because a verified refresh also
    # resurrects a stale-but-unevicted partner: the connection provably
    # stayed active (this entry's own freshness), matching kernel ct which
    # would have refreshed the shared entry at every packet.  The partner
    # slot is recomputed from the cached DNAT meta and its key VERIFIED
    # before the refresh, so an unrelated entry that evicted the partner is
    # never life-extended.
    #   fwd est hit:  partner = reply entry (dnat_ip, src, dnat_port, sport)
    #   reply hit:    partner = fwd entry (dst=client, frontend ip/port)
    p_half = max(1, meta.ct_timeout_s // 2)
    pmask = meta.pref_mask
    c_pref = mr[:, ZC] & pmask  # strip the cached snat/dsr(/chance) bits
    # Age in mod-2^29 arithmetic (PREF_MASK; bits 0-28 carry pref, bit 29
    # is CONFIRMED in the meta3 layout; under second_chance the stamp
    # narrows to bits 0-26): exact whenever the true age < the mask
    # width, which the idle timeout guarantees for any live entry.
    p_need = est & (((now - c_pref) & pmask) >= p_half)

    def partner_probe(keys, mask):
        """Derive each lane's PARTNER tuple (the other conntrack direction
        of its hit entry, un/re-DNAT applied) and key-verify it against
        `keys` — shared by the deferred partner refresh and the FIN/RST
        teardown so the two can never drift.  -> (p_slot, live_mask).

        Dual-stack: the cached meta rows carry the 4-word DNAT / un-DNAT
        resolution (c_dnat_w), so the wide partner tuple is the exact
        structural mirror of the narrow one — forward hits pair with
        (dnat, src), reply hits with (dst, cached frontend)."""
        p_sport = jnp.where(rpl, dport, c_dport)
        p_dport = jnp.where(rpl, c_dport, sport)
        p_pg = jnp.where(rpl, pg_est, pg_est | REPLY_BIT)
        if A == 2:
            p_src = jnp.where(rpl, dst_f, c_dnat_ip)
            p_dst = jnp.where(rpl, c_dnat_ip, src_f)
            p_addr = jnp.stack([p_src, p_dst], axis=1)
            p_h = hashing.flow_hash(
                _raw_bits(p_src), _raw_bits(p_dst), proto, p_sport, p_dport,
                xp=jnp,
            )
        else:
            rplw = (rpl != 0)[:, None]
            p_srcw = jnp.where(rplw, daddr, c_dnat_w)
            p_dstw = jnp.where(rplw, c_dnat_w, saddr)
            p_addr = jnp.concatenate([p_srcw, p_dstw], axis=1)
            p_h = hashing.flow_hash_wide(
                [p_addr[:, i] for i in range(8)], proto, p_sport, p_dport,
                xp=jnp,
            )
        p_slot = (p_h & jnp.uint32(N - 1)).astype(jnp.int32)
        pkr = keys[p_slot]
        live = (
            mask
            & (pkr[:, :A] == p_addr).all(axis=1)
            & (pkr[:, A] == ((p_sport << 16) | p_dport))
            & (pkr[:, A + 1] == p_pg)
        )
        return p_slot, live

    def partner_refresh(flow):
        p_slot, p_live = partner_probe(flow.keys, p_need)
        if meta.second_chance:
            # Read the CURRENT meta for the preserved high bits: the
            # hit-path reset above already cleared the chance counter on
            # this very slot, and re-stamping from the start-of-batch
            # snapshot would resurrect it.
            tgt_p = jnp.where(p_need, slot, dump)
            return flow._replace(
                ts=flow.ts.at[jnp.where(p_live, p_slot, dump)].set(now),
                meta=flow.meta.at[tgt_p, ZC].set(
                    (now & pmask) | (flow.meta[tgt_p, ZC] & ~pmask)
                ),
            )
        return flow._replace(
            ts=flow.ts.at[jnp.where(p_live, p_slot, dump)].set(now),
            # Attempt-time update even when the partner is gone, so an
            # evicted partner doesn't drag the walk into every batch.
            # Preserve the cached snat/dsr bits alongside the new stamp.
            meta=flow.meta.at[jnp.where(p_need, slot, dump), ZC].set(
                (now & pmask) | (mr[:, ZC] & ~pmask)
            ),
        )

    flow = jax.lax.cond(p_need.any(), partner_refresh, lambda f: f, flow)

    # SYN_SENT -> ESTABLISHED confirmation (the kernel ct state machine's
    # two-way-traffic transition): the FIRST reply-direction hit proves the
    # peer answered; set CONF on the hit entry and its verified partner so
    # both directions graduate to the confirmed lifetime.  Once per
    # connection -> under lax.cond, zero steady-state cost.
    conf_need = rpl & (((mr[:, ZC] >> 29) & 1) == 0)

    def confirm(flow):
        # OR into the CURRENT meta (partner_refresh may have just stamped
        # pref on this very slot; clobbering it with the start-of-batch
        # snapshot would diverge from the scalar oracle's pref=now).
        m = flow.meta
        tgt0 = jnp.where(conf_need, slot, dump)
        m = m.at[tgt0, ZC].set(m[tgt0, ZC] | CONF_BIT)
        c_slot, c_live = partner_probe(flow.keys, conf_need)
        tgt = jnp.where(c_live, c_slot, dump)
        m = m.at[tgt, ZC].set(m[tgt, ZC] | CONF_BIT)
        return flow._replace(meta=m)

    flow = jax.lax.cond(conf_need.any(), confirm, lambda f: f, flow)

    # TCP connection teardown (conntrack close): a FIN or RST on an
    # established entry removes BOTH tuple directions after this packet's
    # own (still-established) verdict — subsequent same-tuple packets
    # re-classify under the CURRENT policy instead of est-bypassing a
    # connection that no longer exists.  Conservative vs kernel ct (which
    # walks FIN_WAIT/TIME_WAIT): trailing segments of a closing connection
    # re-classify; nothing ever bypasses policy MORE than the kernel.
    # Out-of-window teardown cost: zero when no lane carries the flags.
    if flags is not None:
        td = est & (proto == PROTO_TCP) & ((flags & _TEARDOWN_FLAGS) != 0)

        def teardown(flow):
            keys = flow.keys.at[jnp.where(td, slot, dump)].set(0)
            t_slot, t_live = partner_probe(keys, td)
            keys = keys.at[jnp.where(t_live, t_slot, dump)].set(0)
            return flow._replace(keys=keys)

        flow = jax.lax.cond(td.any(), teardown, lambda f: f, flow)

    miss = ~hit if valid is None else (~hit & valid)
    n_miss = miss.sum(dtype=jnp.int32)

    # Fast-path output images (+1 dump element for masked slow-path scatter).
    def outbuf(vals):
        return jnp.concatenate([vals, jnp.zeros((1,), jnp.int32)])

    # ADMITTED miss lanes default to meta.miss_code: ACT_ALLOW in
    # synchronous mode (overwritten by the slow path anyway), the
    # admission policy's provisional verdict in the async fast step
    # (PH_SLOW masked, misses queued for the background engine —
    # datapath/slowpath).  Valid-masked lanes (SpoofGuard/ARP/IGMP-punt,
    # handled BEFORE the pipeline) are NOT misses and keep the plain
    # ALLOW image their kind overrides expect (forwarding.py) — a hold
    # policy must never report DROP for a lane it never evaluated.
    out_code = outbuf(jnp.where(
        hit, c_code, jnp.where(miss, meta.miss_code, ACT_ALLOW)
    ))
    out_svc = outbuf(jnp.where(hit, c_svc, MISS))
    out_dnat_ip = outbuf(jnp.where(hit, c_dnat_ip, dst_f))
    out_dnat_port = outbuf(jnp.where(hit, c_dport, dport))
    out_rule_in = outbuf(jnp.where(hit, c_rule_in, MISS))
    out_rule_out = outbuf(jnp.where(hit, c_rule_out, MISS))
    out_committed = outbuf(jnp.zeros(B, jnp.int32))
    # SNAT mark cached in meta3's sign bit at commit time; reply-direction
    # hits carry the un-SNAT implicitly via the restored frontend tuple.
    c_snat = (mr[:, ZC] >> 31) & 1
    out_snat = outbuf(jnp.where(hit & ~rpl, c_snat, 0))
    # DSR delivery mark, pinned into the entry at commit time exactly like
    # the SNAT mark (meta3 bit 30): service updates that renumber LB
    # programs cannot flip an established connection's delivery mode.
    c_dsr = (mr[:, ZC] >> 30) & 1
    out_dsr = outbuf(jnp.where(hit & ~rpl, c_dsr, 0))
    # Wide DNAT image ((B+1, 4), wide worlds only): cache hits read the
    # cached word row, misses default to the literal dst words and are
    # overwritten by the slow path.
    if A == 8:
        out_dnat_w = jnp.concatenate(
            [jnp.where(hit[:, None], c_dnat_w, daddr),
             jnp.zeros((1, 4), jnp.int32)], axis=0,
        )
    else:
        out_dnat_w = None

    # Round-7 prune observability (python-static: zero ops, zero extra
    # outputs when the budget is 0 — the HLO-identity contract).
    prune_on = meta.match.prune_budget > 0
    # Telemetry appends two slow-path counters LAST (tel_dma_hb,
    # tel_chance_bumps) — after the wide-DNAT image and the prune trio —
    # so every existing position is unchanged when the knob is off.
    n_extra = ((1 if A == 8 else 0) + (3 if prune_on else 0)
               + (2 if tel_on else 0))

    # ---- slow path: ServiceLB + classify + commit, misses only -------------
    def slow(args):
        flow, aff, outs = args
        (out_code, out_svc, out_dnat_ip, out_dnat_port, out_rule_in,
         out_rule_out, out_committed, out_snat, out_dsr, n_evict0,
         n_reclaim0) = outs[:11]
        pos = 11
        out_dnat_w = None
        if A == 8:
            out_dnat_w = outs[pos]
            pos += 1
        if prune_on:
            pr_sk0, pr_fb0, pr_hist0 = outs[pos:pos + 3]
            pos += 3
        if tel_on:
            tel_hb0, tel_sc0 = outs[pos:pos + 2]
        # Batch semantics: affinity LOOKUPS see start-of-batch state even
        # across slow-path rounds; learns land in the carried table.
        aff_snap = aff
        midx = jnp.nonzero(miss, size=B, fill_value=B)[0].astype(jnp.int32)

        def round_body(carry):
            (r, n_evict, n_reclaim, flow, aff, out_code, out_svc,
             out_dnat_ip, out_dnat_port, out_rule_in, out_rule_out,
             out_committed, out_snat, out_dsr) = carry[:14]
            pos = 14
            out_dnat_w = None
            if A == 8:
                out_dnat_w = carry[pos]
                pos += 1
            if prune_on:
                pr_sk, pr_fb, pr_hist = carry[pos:pos + 3]
                pos += 3
            if tel_on:
                tel_hb, tel_sc = carry[pos:pos + 2]
            idx = jax.lax.dynamic_slice(
                jnp.concatenate([midx, jnp.full((M,), B, jnp.int32)]),
                (r * M,),
                (M,),
            )
            valid = idx < B
            safe = jnp.clip(idx, 0, B - 1)
            s_f = src_f[safe]
            d_f = dst_f[safe]
            p_m = proto[safe]
            sp_m = sport[safe]
            dp_m = dport[safe]
            h_m = h[safe]
            slot_m = slot[safe]
            pp_m = pp[safe]
            if meta.count_flow_stats:
                lv_m = (jnp.zeros(M, jnp.int32) if lens is None
                        else jnp.maximum(lens[safe], 0))
            if A == 8:
                saddr_m = saddr[safe]
                daddr_m = daddr[safe]
                is6_m = is6[safe]
                wide_m = (saddr_m, daddr_m, is6_m)
            else:
                is6_m = None
                wide_m = None

            if meta.phases & PH_LB:
                (svc_idx, no_ep, dnat_ip, dnat_port, snat_m, dsr_m, dnat_w,
                 learn) = _service_lb(
                    aff_snap, dsvc, h_m, s_f, d_f, p_m, dp_m, now,
                    meta.aff_slots, wide=wide_m,
                )
            else:
                # Phase masked (profiling): no service resolution — lanes
                # keep their literal destination, nothing learns.
                svc_idx = jnp.full((M,), MISS, jnp.int32)
                no_ep = jnp.zeros((M,), bool)
                dnat_ip, dnat_port = d_f, dp_m
                snat_m = dsr_m = jnp.zeros((M,), jnp.int32)
                dnat_w = daddr_m if A == 8 else None
                learn = {
                    "mask": jnp.zeros((M,), bool),
                    "aslot": jnp.zeros((M,), jnp.int32),
                    "client": s_f if A == 2 else saddr_m,
                    "svc": svc_idx,
                    "ep": jnp.zeros((M,), jnp.int32),
                }

            cls = None
            if meta.phases & PH_CLS:
                # Lanes classify on their POST-DNAT tuple (EndpointDNAT
                # before the policy tables, ref pipeline.go table order);
                # v6 lanes' post-DNAT words (dnat_w) double as the
                # classifier's v6 lanes (same flipped-word layout the
                # interval tables expect).
                cls = classify_batch(
                    drs, s_f, dnat_ip, p_m, dnat_port,
                    meta=meta.match, hit_combine=hit_combine,
                    # The fused consumer is shard-aware (global word
                    # offsets from word_idx), so it composes with
                    # hit_combine.
                    fused=meta.fused,
                    v6=None if wide_m is None else (saddr_m, dnat_w, is6_m),
                    svc_ref=_svc_ref_of(svc_idx, dsvc),
                )
            elif prune_on and (meta.phases & PH_CLS_SUM):
                # Summary-only classify (round-7 profiling surface): the
                # aggregate gathers + AND + short-circuit defaults, no
                # candidate gather, no fallback — PRUNE_PHASE_CHAIN's
                # summary-gather vs candidate-gather split.
                cls = classify_batch(
                    drs, s_f, dnat_ip, p_m, dnat_port,
                    meta=meta.match, hit_combine=hit_combine,
                    fused=meta.fused,
                    v6=None if wide_m is None else (saddr_m, dnat_w, is6_m),
                    svc_ref=_svc_ref_of(svc_idx, dsvc),
                    summary_only=True,
                )
            if cls is not None:
                code = jnp.where(
                    no_ep, ACT_REJECT, cls["code"]).astype(jnp.int32)
                # SvcReject happens in EndpointDNAT, BEFORE the policy
                # tables (ref pipeline.go table order): no rule
                # attribution for it.
                rule_in = jnp.where(no_ep, MISS, cls["ingress_rule"])
                rule_out = jnp.where(no_ep, MISS, cls["egress_rule"])
            else:
                # Phase masked (profiling): every lane default-allows
                # (SvcReject still applies — it is an LB decision).
                code = jnp.where(no_ep, ACT_REJECT, ACT_ALLOW).astype(jnp.int32)
                rule_in = jnp.full((M,), MISS, jnp.int32)
                rule_out = jnp.full((M,), MISS, jnp.int32)
            if prune_on and cls is not None:
                # Prune observability (valid lanes only — padding lanes
                # classify garbage tuples and must not meter).
                # prune_exclude (round 8): lanes another dispatch owns
                # the evidence for — the mesh's spilled lanes, whose
                # HOME-routed retry re-walks them — are excluded here so
                # the PruneAutotuner band sees each lane's SERVING walk
                # exactly once (parallel/meshpath._spill_retry).
                pv = valid
                if prune_exclude is not None:
                    pv = pv & ~prune_exclude[safe]
                pr_sk = pr_sk + (cls["prune_skip"] & pv).sum(
                    dtype=jnp.int32)
                pr_fb = pr_fb + (cls["prune_fb"] & pv).sum(
                    dtype=jnp.int32)
                pr_hist = pr_hist + _prune_bucket_counts(
                    cls["prune_cand"], pv)

            # no_commit lanes (multicast dst — the reference's multicast
            # pipeline bypasses conntrack entirely, pkg/agent/openflow/
            # multicast.go) classify fresh every time: no cache entry in
            # either direction, and `committed` reports 0.
            committed_m = code == ACT_ALLOW
            ins = valid
            if no_commit is not None:
                nc_m = no_commit[safe]
                committed_m = committed_m & ~nc_m
                ins = ins & ~nc_m

            # Scatter results into the output images.
            tgt = jnp.where(valid, idx, B)
            out_code = out_code.at[tgt].set(code)
            out_svc = out_svc.at[tgt].set(svc_idx)
            out_dnat_ip = out_dnat_ip.at[tgt].set(dnat_ip)
            if A == 8:
                out_dnat_w = out_dnat_w.at[tgt].set(dnat_w)
            out_dnat_port = out_dnat_port.at[tgt].set(dnat_port)
            out_rule_in = out_rule_in.at[tgt].set(rule_in)
            out_rule_out = out_rule_out.at[tgt].set(rule_out)
            out_committed = out_committed.at[tgt].set(committed_m.astype(jnp.int32))
            out_snat = out_snat.at[tgt].set(snat_m)
            out_dsr = out_dsr.at[tgt].set(dsr_m)

            # Insert into the flow cache: ALLOW entries as ETERNAL
            # (conntrack commit), denials tagged with the current gen.
            # Phase-gated (PH_COMMIT; the eviction audit additionally
            # requires PH_COMMIT since it reads the insert targets) so the
            # profiler can isolate the commit scatters' cost.
            def do_commit(flow, aff, n_evict, n_reclaim, tel_sc):
                egen = jnp.where(committed_m, GEN_ETERNAL, gen_w)
                pg_ins = p_m | 0x100 | (egen << 9)
                m1 = _pack_meta1(code, svc_idx, dnat_port)
                rules_p = _pack_rules(rule_in, rule_out)
                # Column 3 = snat(31) | dsr(30) | pref (the commit
                # freshens both directions; the frontend SNAT mark and the
                # DSR delivery mark are pinned here for the connection's
                # lifetime).
                pref_col = jnp.full((M,), now & pmask, jnp.int32)
                zcol = (pref_col
                        | jnp.where(snat_m > 0, REPLY_BIT, 0)
                        | jnp.where(dsr_m > 0, DSR_BIT, 0))
                if A == 2:
                    addr_m = jnp.stack([s_f, d_f], axis=1)
                    meta_rows = jnp.stack([dnat_ip, m1, rules_p, zcol], axis=1)
                else:
                    addr_m = jnp.concatenate([saddr_m, daddr_m], axis=1)
                    # Wide meta row: [dn_w0..3, m1, rules, z, pad] — the
                    # 4-word DNAT resolution IS the narrow column's role
                    # (word 3 doubles as the v4 view, _meta_cols).
                    meta_rows = jnp.concatenate(
                        [dnat_w,
                         jnp.stack([m1, rules_p, zcol,
                                    jnp.zeros((M,), jnp.int32)], axis=1)],
                        axis=1,
                    )
                key_rows = jnp.concatenate(
                    [addr_m, pp_m[:, None], pg_ins[:, None]], axis=1
                )

                # Conntrack commits BOTH directions (ref ConntrackCommit +
                # reply-direction ct state, docs/design/ovs-pipeline.md ct
                # sections): alongside every ALLOW, insert the
                # reverse-tuple entry keyed on the POST-DNAT tuple with
                # ports swapped (endpoint -> client), whose meta carries
                # the un-DNAT rewrite — the original frontend (pre-DNAT
                # dst ip/port) the reply's source must be restored to
                # (UnSNAT/EndpointDNAT reverse).  DSR connections commit
                # NO reply leg: the endpoint answers the client directly
                # and the reply never re-traverses this node (ref
                # pipeline.go:698-708 DSR flows bypass the reply path).
                rev_ins = ins & committed_m & (dsr_m == 0)
                if A == 2:
                    rev_h = hashing.flow_hash(
                        _raw_bits(dnat_ip), _raw_bits(s_f), p_m, dnat_port,
                        sp_m, xp=jnp,
                    )
                    rev_addr = jnp.stack([dnat_ip, s_f], axis=1)
                    rev_meta = jnp.stack(
                        [d_f, _pack_meta1(code, svc_idx, dp_m), rules_p,
                         pref_col], axis=1,
                    )
                else:
                    # Reverse tuple in wide form: src = the 4-word DNAT
                    # resolution (v6 endpoints included), dst = the
                    # client; the reverse meta carries the ORIGINAL
                    # frontend words (daddr) — the un-DNAT rewrite replies
                    # restore.
                    rev_addr = jnp.concatenate([dnat_w, saddr_m], axis=1)
                    rev_h = hashing.flow_hash_wide(
                        [rev_addr[:, i] for i in range(8)], p_m, dnat_port,
                        sp_m, xp=jnp,
                    )
                    rev_meta = jnp.concatenate(
                        [daddr_m,
                         jnp.stack([_pack_meta1(code, svc_idx, dp_m),
                                    rules_p, pref_col,
                                    jnp.zeros((M,), jnp.int32)],
                                   axis=1)],
                        axis=1,
                    )
                rev_slot = (rev_h & jnp.uint32(N - 1)).astype(jnp.int32)
                rev_pg = p_m | 0x100 | (GEN_ETERNAL << 9) | REPLY_BIT
                rev_keys = jnp.concatenate(
                    [rev_addr, ((dnat_port << 16) | sp_m)[:, None],
                     rev_pg[:, None]], axis=1
                )

                # Interleave per-packet [fwd_i, rev_i] so last-writer-wins
                # slot collisions resolve in the same order as the
                # oracle's per-packet insert sequence (parity on eviction
                # races).
                MC = 4 if A == 2 else 8
                slot2 = jnp.stack([slot_m, rev_slot], axis=1).reshape(2 * M)
                keys2 = jnp.stack([key_rows, rev_keys], axis=1).reshape(
                    2 * M, A + 2)
                meta2 = jnp.stack([meta_rows, rev_meta], axis=1).reshape(
                    2 * M, MC)
                ins2 = jnp.stack([ins, rev_ins], axis=1).reshape(2 * M)

                if meta.second_chance:
                    flow, ins2, sc_n = _second_chance_guard(
                        flow, slot2, keys2, ins2, now, meta, A, dump)
                    if tel_on:
                        tel_sc = tel_sc + sc_n

                if meta.phases & PH_EVICT:
                    # Eviction accounting (round-2 verdict weak #5:
                    # quantify the direct-mapped collision cost): an
                    # insert over a live entry whose TUPLE differs (cols
                    # 0-2 + proto/direction bits of col 3 — a same-tuple
                    # rewrite is an update, not an eviction).
                    tgt2 = jnp.where(ins2, slot2, dump)
                    okr = flow.keys[tgt2]
                    id3 = 0xFF | REPLY_BIT
                    tuple_differs = (
                        (okr[:, : A + 1] != keys2[:, : A + 1]).any(axis=1)
                        | ((okr[:, A + 1] & id3) != (keys2[:, A + 1] & id3))
                    )
                    overwrote = ins2 & (okr[:, A + 1] != 0) & tuple_differs
                    if meta.drain_reclaim:
                        # Fused maintenance (overlapped drain): a target
                        # row that is DEAD to lookups — idle-expired per
                        # its per-state timeout, or a stale-generation
                        # denial — is reclaimed occupancy, not a live
                        # eviction; the drain round ages/revalidates the
                        # rows it touches in the pass that already
                        # gathered them (the ts/conf reads ride the same
                        # tgt2 the audit uses).
                        om3 = flow.meta[tgt2, ZC]
                        otmo = entry_timeout(
                            (om3 >> 29) & 1, okr[:, A + 1] & 0xFF,
                            meta.timeouts,
                        )
                        ogen = (okr[:, A + 1] >> 9) & GEN_ETERNAL
                        dead = ((now - flow.ts[tgt2]) > otmo) | (
                            (ogen != GEN_ETERNAL) & (ogen != gen_w)
                        )
                        n_reclaim = n_reclaim + (overwrote & dead).sum(
                            dtype=jnp.int32)
                        overwrote = overwrote & ~dead
                    n_evict = n_evict + overwrote.sum(dtype=jnp.int32)

                if meta.count_flow_stats:
                    # Fresh entries start at this packet's contribution on
                    # the forward leg; the reply leg starts empty (its own
                    # direction's traffic hasn't flowed yet).  High limbs
                    # reset to zero — a reused slot must not inherit the
                    # evicted entry's carry.
                    pk2 = jnp.stack(
                        [jnp.ones(M, jnp.int32), jnp.zeros(M, jnp.int32)],
                        axis=1).reshape(2 * M)
                    oc2 = jnp.stack(
                        [lv_m, jnp.zeros(M, jnp.int32)],
                        axis=1).reshape(2 * M)
                    z2 = jnp.zeros(2 * M, jnp.int32)
                    new_pkts = _scatter_last(flow.pkts, slot2, pk2, ins2,
                                             dump)
                    new_octets = _scatter_last(flow.octets, slot2, oc2,
                                               ins2, dump)
                    new_pkts_hi = _scatter_last(flow.pkts_hi, slot2, z2,
                                                ins2, dump)
                    new_octets_hi = _scatter_last(flow.octets_hi, slot2, z2,
                                                  ins2, dump)
                else:
                    new_pkts, new_octets = flow.pkts, flow.octets
                    new_pkts_hi, new_octets_hi = flow.pkts_hi, flow.octets_hi
                flow = FlowCache(
                    keys=_scatter_last_rows(flow.keys, slot2, keys2, ins2, dump),
                    meta=_scatter_last_rows(flow.meta, slot2, meta2, ins2, dump),
                    ts=_scatter_last(flow.ts, slot2, jnp.full((2 * M,), now, jnp.int32), ins2, dump),
                    pkts=new_pkts,
                    octets=new_octets,
                    pkts_hi=new_pkts_hi,
                    octets_hi=new_octets_hi,
                )
                lm = learn["mask"] & valid
                adump = meta.aff_slots
                if A == 2:
                    new_client = _scatter_last(
                        aff.key_client, learn["aslot"], learn["client"], lm,
                        adump)
                else:
                    new_client = _scatter_last_rows(
                        aff.key_client, learn["aslot"], learn["client"], lm,
                        adump)
                aff = AffinityTable(
                    key_client=new_client,
                    key_svc=_scatter_last(aff.key_svc, learn["aslot"], learn["svc"], lm, adump),
                    ep=_scatter_last(aff.ep, learn["aslot"], learn["ep"], lm, adump),
                    ts=_scatter_last(aff.ts, learn["aslot"], jnp.full((M,), now, jnp.int32), lm, adump),
                )
                return flow, aff, n_evict, n_reclaim, tel_sc

            if meta.phases & PH_COMMIT:
                flow, aff, n_evict, n_reclaim, tel_sc = do_commit(
                    flow, aff, n_evict, n_reclaim,
                    tel_sc if tel_on else None)
            return (r + 1, n_evict, n_reclaim, flow, aff, out_code, out_svc,
                    out_dnat_ip, out_dnat_port, out_rule_in, out_rule_out,
                    out_committed, out_snat, out_dsr) + (
                    (out_dnat_w,) if A == 8 else ()) + (
                    (pr_sk, pr_fb, pr_hist) if prune_on else ()) + (
                    (tel_hb, tel_sc) if tel_on else ())

        def round_cond(carry):
            r = carry[0]
            return r * M < n_miss

        carry = (jnp.int32(0), n_evict0, n_reclaim0, flow, aff, out_code,
                 out_svc, out_dnat_ip, out_dnat_port, out_rule_in,
                 out_rule_out, out_committed, out_snat, out_dsr) + (
                 (out_dnat_w,) if A == 8 else ()) + (
                 (pr_sk0, pr_fb0, pr_hist0) if prune_on else ()) + (
                 (tel_hb0, tel_sc0) if tel_on else ())
        carry = jax.lax.while_loop(round_cond, round_body, carry)
        (_, n_evict, n_reclaim, flow, aff, out_code, out_svc, out_dnat_ip,
         out_dnat_port, out_rule_in, out_rule_out, out_committed,
         out_snat, out_dsr) = carry[:14]
        return flow, aff, (out_code, out_svc, out_dnat_ip, out_dnat_port,
                           out_rule_in, out_rule_out, out_committed,
                           out_snat, out_dsr, n_evict, n_reclaim) + tuple(
                           carry[14:14 + n_extra])

    def slow_onepass(args):
        """Round-8 one-kernel slow path (meta.onepass): the whole miss
        walk — probe decode, aggregate prune, candidate DMA, first
        match, resolve, commit-row packing — runs as ONE pallas pass
        over the full batch (ops/match._onepass_call) instead of the
        chunked round loop; only the gathers feeding it, the fallback
        redispatch and the commit scatters remain XLA (the study-note
        walls: gather/scatter engines are XLA-only on this toolchain).
        v4 + prune_budget > 0 only (make_pipeline gates)."""
        flow, aff, outs = args
        (out_code0, out_svc0, out_dnat0, out_dport0, out_ri0, out_ro0,
         out_cmt0, out_snat0, out_dsr0, n_evict, n_reclaim) = outs[:11]
        pr_sk0, pr_fb0, pr_hist0 = outs[11:14]
        if tel_on:
            tel_hb, tel_sc = outs[14:16]
            if meta.phases & PH_CLS:
                # DMA half-blocks the one-pass kernel issues for this
                # dispatch: its main loop walks EVERY _OP_HB half-block
                # of the padded batch unconditionally (the double-buffer
                # schedule, ops/match round-8 study note), so the count
                # is a physical constant of the batch shape — replicated
                # -safe, and the denominator the candidate-hist numbers
                # are read against.
                tel_hb = tel_hb + jnp.int32(
                    (B + (-B) % _m._FUSE_TB) // _m._OP_HB)
        aff_snap = aff
        validm = jnp.ones(B, bool) if valid is None else (valid != 0)
        ncm = (jnp.zeros(B, bool) if no_commit is None
               else (no_commit != 0))
        z = jnp.zeros(B, jnp.int32)
        BIGS = jnp.full((B,), _m.BIG, jnp.int32)

        # ---- ServiceLB over the full batch (PH_LB) --------------------
        if meta.phases & PH_LB:
            (svc_idx, no_ep, dnat_ip, dnat_port, snat_m, dsr_m, _dw,
             learn) = _service_lb(aff_snap, dsvc, h, src_f, dst_f, proto,
                                  dport, now, meta.aff_slots)
        else:
            svc_idx = jnp.full((B,), MISS, jnp.int32)
            no_ep = jnp.zeros((B,), bool)
            dnat_ip, dnat_port = dst_f, dport
            snat_m = dsr_m = z
            learn = {"mask": jnp.zeros((B,), bool), "aslot": z,
                     "client": src_f, "svc": svc_idx, "ep": z}

        # ---- classification probes on the POST-DNAT tuple -------------
        ing, eg = drs.ingress, drs.egress
        svc_key = (proto << 16) | dnat_port
        sref = _svc_ref_of(svc_idx, dsvc) if meta.match.svcref else None

        def midx(tab, x):
            # Miss-masked interval rows: hit/invalid lanes gather the hot
            # row 0 (the steady-state volume guard) and spawn nothing.
            return jnp.where(miss, _m._dim_index(tab, x, None, None), 0)

        iv6 = (midx(ing.at, dnat_ip), midx(ing.peer, src_f),
               midx(ing.svc, svc_key), midx(eg.at, src_f),
               midx(eg.peer, dnat_ip), midx(eg.svc, svc_key))
        iv_ref = (midx(eg.svc, _m._svcref_key(svc_key, sref))
                  if meta.match.svcref else z)
        iso_in = drs.iso_in.val[midx(drs.iso_in, dnat_ip)]
        iso_out = drs.iso_out.val[midx(drs.iso_out, src_f)]

        d = drs.ip_delta if meta.match.delta_slots > 0 else None
        delta_fb = jnp.zeros(B, bool)
        if d is not None:
            iso_in = _m._patch_iso(iso_in, dnat_ip, d, 0)
            iso_out = _m._patch_iso(iso_out, src_f, d, 1)

        aggs = [ing.at.agg[iv6[0]], ing.peer.agg[iv6[1]],
                ing.svc.agg[iv6[2]], eg.at.agg[iv6[3]],
                eg.peer.agg[iv6[4]], eg.svc.agg[iv6[5]]]
        if meta.match.svcref:
            aggs[5] = aggs[5] | eg.svc.agg[iv_ref]
        if d is not None:
            aggs[0] = _m._patch_agg(aggs[0], dnat_ip, d, d.at_in)
            aggs[1] = _m._patch_agg(aggs[1], src_f, d, d.peer_in)
            aggs[3] = _m._patch_agg(aggs[3], src_f, d, d.at_out)
            aggs[4] = _m._patch_agg(aggs[4], dnat_ip, d, d.peer_out)

            # Delta-affected lanes force the full-width fallback: SET
            # slots are conservative in the aggregate (patched above),
            # but CLEAR slots only resolve at full precision — the
            # candidate words the kernel DMAs are unpatched, so a lane a
            # pending delta touches must never trust them (exactness
            # before speed; deltas are the rare between-recompiles case).
            def dfb(i, acc):
                return (acc | _m._delta_lane_match(src_f, d, i, None)
                        | _m._delta_lane_match(dnat_ip, d, i, None))

            delta_fb = jax.lax.fori_loop(0, d.n, dfb, delta_fb)

        K = meta.match.prune_budget
        sharded = hit_combine is not None
        resolve = not sharded
        if meta.match.fused_interpret is not None:
            interp = meta.match.fused_interpret
        else:
            interp = jax.devices()[0].platform == "cpu"
        s_in = aggs[0].shape[1]
        s_out = aggs[3].shape[1]
        w0i = ing.word_idx[0]
        w0o = eg.word_idx[0]
        run_kernel = bool(meta.phases & PH_CLS)
        summary = (not run_kernel) and bool(meta.phases & PH_CLS_SUM)

        def full_hits(safe):
            """Full-width (exact) re-walk of compacted fallback lanes —
            the `_classify_pruned` fallback discipline, delta patches
            applied at full precision."""
            ra = ing.at.inc[iv6[0][safe]]
            rp = ing.peer.inc[iv6[1][safe]]
            rs = ing.svc.inc[iv6[2][safe]]
            oa = eg.at.inc[iv6[3][safe]]
            opr = eg.peer.inc[iv6[4][safe]]
            osv = eg.svc.inc[iv6[5][safe]]
            if meta.match.svcref:
                osv = osv | eg.svc.inc[iv_ref[safe]]
            if d is not None:
                ra = _m._patch_rows(ra, dnat_ip[safe], d, d.at_in)
                rp = _m._patch_rows(rp, src_f[safe], d, d.peer_in)
                oa = _m._patch_rows(oa, src_f[safe], d, d.at_out)
                opr = _m._patch_rows(opr, dnat_ip[safe], d, d.peer_out)
            return (_m._phase_hits(ra & rp & rs, ing.word_idx,
                                   meta.match.in_phases)
                    + _m._phase_hits(oa & opr & osv, eg.word_idx,
                                     meta.match.out_phases))

        def fb_switch(fbb, carried, fixup):
            """Pow2-rung compacted redispatch of the fallback lanes (the
            in-jit _spill_retry shape shared with _classify_pruned)."""
            fb_idx = jnp.nonzero(fbb, size=B, fill_value=B)[0].astype(
                jnp.int32)
            n_fb = fbb.sum(dtype=jnp.int32)
            rungs = []
            r = _m._FB_MIN
            while r < B:
                rungs.append(r)
                r *= 4
            rungs = sorted(set(min(x, B) for x in rungs + [B]))

            def apply_rung(r):
                def go(c):
                    idx = fb_idx[:r]
                    safe = jnp.minimum(idx, B - 1)
                    tgt = jnp.where(idx < B, idx, B)
                    return fixup(c, safe, tgt)

                return go

            branches = [lambda c: c] + [apply_rung(r) for r in rungs]
            sel = jnp.where(
                n_fb == 0, 0,
                1 + sum(((n_fb > r).astype(jnp.int32)
                         for r in rungs[:-1]), start=jnp.int32(0)))
            return jax.lax.switch(sel, branches, carried)

        def resolve_fresh(hits6, iso_i, iso_o, noep):
            """Shared hit->fresh-image resolution (the slow-path verdict
            overlay: SvcReject precedes the policy tables)."""
            in_code, in_rule = _m._resolve(ing.action, hits6[:3], iso_i)
            out_code, out_rule = _m._resolve(eg.action, hits6[3:], iso_o)
            cls_code = jnp.where(out_code != ACT_ALLOW, out_code, in_code)
            f_code = jnp.where(noep, ACT_REJECT, cls_code).astype(jnp.int32)
            f_ri = jnp.where(noep, MISS, in_rule)
            f_ro = jnp.where(noep, MISS, out_rule)
            return f_code, f_ri, f_ro

        # Cached-image decode (start-of-batch rows — the merge source).
        c_code, c_svc, c_dport = _unpack_meta1(mr[:, 1])
        c_dnat = mr[:, 0]
        c_ri, c_ro = _unpack_rules(mr[:, 2])
        c_snat_b = (mr[:, 3] >> 31) & 1
        c_dsr_b = (mr[:, 3] >> 30) & 1

        def merged_images(f_code, f_ri, f_ro):
            o_code = jnp.where(hit, c_code,
                               jnp.where(miss, f_code, ACT_ALLOW))
            o_svc = jnp.where(hit, c_svc, jnp.where(miss, svc_idx, MISS))
            o_dnat = jnp.where(hit, c_dnat, jnp.where(miss, dnat_ip, dst_f))
            o_dport = jnp.where(hit, c_dport,
                                jnp.where(miss, dnat_port, dport))
            o_ri = jnp.where(hit, c_ri, jnp.where(miss, f_ri, MISS))
            o_ro = jnp.where(hit, c_ro, jnp.where(miss, f_ro, MISS))
            o_snat = jnp.where(hit & ~rpl, c_snat_b,
                               jnp.where(miss, snat_m, 0))
            o_dsr = jnp.where(hit & ~rpl, c_dsr_b,
                              jnp.where(miss, dsr_m, 0))
            return (o_code, o_svc, o_dnat, o_dport, o_ri, o_ro, o_snat,
                    o_dsr)

        skipv = z
        fbv = z
        candv = z
        if run_kernel:
            # ---- the one-pass kernel --------------------------------------
            pad = (-B) % _m._FUSE_TB

            def padr(x):
                if not pad:
                    return x
                return jnp.pad(x, ((0, pad), (0, 0)))

            pkt = padr(jnp.stack(
                [src_f, dst_f, proto, sport, dport, pp, z, z], axis=1))
            prb = padr(jnp.stack([ts0, iso_in, iso_out, z], axis=1))
            mskm = padr(jnp.stack(
                [validm.astype(jnp.int32), ncm.astype(jnp.int32),
                 delta_fb.astype(jnp.int32), z], axis=1))
            lbm = padr(jnp.stack(
                [svc_idx, no_ep.astype(jnp.int32), dnat_ip, dnat_port,
                 snat_m, dsr_m, z, z], axis=1))
            ivm = padr(jnp.stack(list(iv6) + [iv_ref, z], axis=1))
            scal = jnp.stack([
                jnp.asarray(now, jnp.int32), gen_w,
                jnp.asarray(w0i, jnp.int32), jnp.asarray(w0o, jnp.int32),
            ]).reshape(1, 4)
            inc_tabs = (ing.at, ing.peer, ing.svc, eg.at, eg.peer, eg.svc)
            inc2 = [t.inc.reshape(-1, _m.AGG_BLOCK) for t in inc_tabs]
            if meta.match.svcref:
                inc2.append(eg.svc.inc.reshape(-1, _m.AGG_BLOCK))
            acts = (ing.action, eg.action) if resolve else ()
            call = _m._onepass_call(
                B + pad, s_in, s_out, K, K, meta.match.in_phases,
                meta.match.out_phases, meta.match.svcref, resolve,
                meta.timeouts, N, pmask, interp)
            res = call(pkt, padr(kr0), prb, padr(mr), mskm, lbm,
                       *[padr(a) for a in aggs], ivm, scal, *inc2, *acts)
            res = [x[:B] for x in res]
            if resolve:
                main, keys8, meta8, aux = res
                o_code, o_ri, o_ro = main[:, 0], main[:, 1], main[:, 2]
                o_svc, o_dnat, o_dport = main[:, 3], main[:, 4], main[:, 5]
                o_snat, o_dsr = main[:, 6], main[:, 7]
                committed = main[:, 8] != 0
                rev_ins = main[:, 9] != 0
                rev_slot = main[:, 10]
                ins = main[:, 14] != 0
                skipv, fbv, candv = aux[:, 0], aux[:, 1], aux[:, 2]

                def fix_resolve(c, safe, tgt):
                    (o_code, o_ri, o_ro, committed, rev_ins, keys8,
                     meta8) = c
                    h6 = full_hits(safe)
                    f_code, f_ri, f_ro = resolve_fresh(
                        h6, iso_in[safe], iso_out[safe], no_ep[safe])
                    rows = _fused_pack_rows(
                        src_f[safe], dst_f[safe], proto[safe], sport[safe],
                        dport[safe], pp[safe], f_code, svc_idx[safe],
                        dnat_ip[safe], dnat_port[safe], snat_m[safe],
                        dsr_m[safe], f_ri, f_ro, miss[safe], ncm[safe],
                        now, gen_w, N, pmask)
                    return (
                        o_code.at[tgt].set(f_code, mode="drop"),
                        o_ri.at[tgt].set(f_ri, mode="drop"),
                        o_ro.at[tgt].set(f_ro, mode="drop"),
                        committed.at[tgt].set(rows["committed"],
                                              mode="drop"),
                        rev_ins.at[tgt].set(rows["rev_ins"], mode="drop"),
                        keys8.at[tgt].set(rows["keys8"], mode="drop"),
                        meta8.at[tgt].set(rows["meta8"], mode="drop"),
                    )

                (o_code, o_ri, o_ro, committed, rev_ins, keys8,
                 meta8) = fb_switch(
                    fbv > 0,
                    (o_code, o_ri, o_ro, committed, rev_ins, keys8, meta8),
                    fix_resolve)
                images = (o_code, o_svc, o_dnat, o_dport, o_ri, o_ro,
                          o_snat, o_dsr)
                rows = dict(committed=committed, ins=ins, rev_ins=rev_ins,
                            rev_slot=rev_slot, keys8=keys8, meta8=meta8)
            else:
                hits8, aux = res
                hits6 = tuple(hits8[:, i] for i in range(6))

                def fix_hits(c, safe, tgt):
                    h6 = full_hits(safe)
                    return tuple(
                        cur.at[tgt].set(new, mode="drop")
                        for cur, new in zip(c, h6))

                hits6 = fb_switch(aux[:, 1] > 0, hits6, fix_hits)
                in_hits = tuple(hit_combine(x) for x in hits6[:3])
                out_hits = tuple(hit_combine(x) for x in hits6[3:])
                # Shard-local prune observables -> the replicated view
                # (the _classify_pruned min-combine discipline).
                skipv = hit_combine(aux[:, 0])
                fbv = 1 - hit_combine(1 - aux[:, 1])
                candv = -hit_combine(-aux[:, 2])
                f_code, f_ri, f_ro = resolve_fresh(
                    in_hits + out_hits, iso_in, iso_out, no_ep)
                images = merged_images(f_code, f_ri, f_ro)
                rows = _fused_pack_rows(
                    src_f, dst_f, proto, sport, dport, pp, f_code, svc_idx,
                    dnat_ip, dnat_port, snat_m, dsr_m, f_ri, f_ro, miss,
                    ncm, now, gen_w, N, pmask)
        else:
            if summary:
                # PH_CLS_SUM tier: aggregate AND + short-circuit only —
                # live lanes take the default-verdict image (the
                # profiling surface, never a production path).
                g_in = aggs[0] & aggs[1] & aggs[2]
                g_out = aggs[3] & aggs[4] & aggs[5]
                nc_in = jnp.where(miss, (g_in != jnp.uint32(0)).sum(
                    axis=1, dtype=jnp.int32), 0)
                nc_out = jnp.where(miss, (g_out != jnp.uint32(0)).sum(
                    axis=1, dtype=jnp.int32), 0)
                skipv = (miss & (nc_in == 0) & (nc_out == 0)).astype(
                    jnp.int32)
                candv = jnp.maximum(nc_in, nc_out)
                if hit_combine is not None:
                    skipv = hit_combine(skipv)
                    candv = -hit_combine(-candv)
            f_code, f_ri, f_ro = resolve_fresh(
                (BIGS,) * 6, iso_in, iso_out, no_ep)
            if not summary:
                # Neither classify bit: the staged default-allow image.
                f_code = jnp.where(no_ep, ACT_REJECT, ACT_ALLOW).astype(
                    jnp.int32)
                f_ri = jnp.full((B,), MISS, jnp.int32)
                f_ro = jnp.full((B,), MISS, jnp.int32)
            images = merged_images(f_code, f_ri, f_ro)
            rows = _fused_pack_rows(
                src_f, dst_f, proto, sport, dport, pp, f_code, svc_idx,
                dnat_ip, dnat_port, snat_m, dsr_m, f_ri, f_ro, miss, ncm,
                now, gen_w, N, pmask)

        (o_code, o_svc, o_dnat, o_dport, o_ri, o_ro, o_snat,
         o_dsr) = images
        committed = rows["committed"]
        ins = rows["ins"]
        rev_ins = rows["rev_ins"]
        rev_slot = rows["rev_slot"]
        keys8 = rows["keys8"]
        meta8 = rows["meta8"]

        # ---- prune observability (exactly-once per lane; the mesh's
        # spilled lanes are excluded — their home retry owns the evidence).
        pv = validm if prune_exclude is None else (validm & ~prune_exclude)
        pr_sk = pr_sk0 + ((skipv > 0) & pv).sum(dtype=jnp.int32)
        pr_fb = pr_fb0 + ((fbv > 0) & pv).sum(dtype=jnp.int32)
        if run_kernel or summary:
            pr_hist = pr_hist0 + _prune_bucket_counts(candv, miss & pv)
        else:
            pr_hist = pr_hist0

        # ---- commit: interleaved [fwd, rev] scatters off the packed rows
        if meta.phases & PH_COMMIT:
            slot2 = jnp.stack([slot, rev_slot], axis=1).reshape(2 * B)
            keys2 = jnp.stack([keys8[:, :4], keys8[:, 4:]],
                              axis=1).reshape(2 * B, 4)
            meta2 = jnp.stack([meta8[:, :4], meta8[:, 4:]],
                              axis=1).reshape(2 * B, 4)
            ins2 = jnp.stack([ins, rev_ins], axis=1).reshape(2 * B)

            if meta.second_chance:
                flow, ins2, sc_n = _second_chance_guard(
                    flow, slot2, keys2, ins2, now, meta, A, dump)
                if tel_on:
                    tel_sc = tel_sc + sc_n

            if meta.phases & PH_EVICT:
                tgt2 = jnp.where(ins2, slot2, dump)
                okr = flow.keys[tgt2]
                id3 = 0xFF | REPLY_BIT
                tuple_differs = (
                    (okr[:, : A + 1] != keys2[:, : A + 1]).any(axis=1)
                    | ((okr[:, A + 1] & id3) != (keys2[:, A + 1] & id3))
                )
                overwrote = ins2 & (okr[:, A + 1] != 0) & tuple_differs
                if meta.drain_reclaim:
                    om3 = flow.meta[tgt2, 3]
                    otmo = entry_timeout(
                        (om3 >> 29) & 1, okr[:, A + 1] & 0xFF,
                        meta.timeouts)
                    ogen = (okr[:, A + 1] >> 9) & GEN_ETERNAL
                    dead = ((now - flow.ts[tgt2]) > otmo) | (
                        (ogen != GEN_ETERNAL) & (ogen != gen_w))
                    n_reclaim = n_reclaim + (overwrote & dead).sum(
                        dtype=jnp.int32)
                    overwrote = overwrote & ~dead
                n_evict = n_evict + overwrote.sum(dtype=jnp.int32)

            if meta.count_flow_stats:
                lv = (jnp.zeros(B, jnp.int32) if lens is None
                      else jnp.maximum(lens, 0))
                pk2 = jnp.stack([jnp.ones(B, jnp.int32), z],
                                axis=1).reshape(2 * B)
                oc2 = jnp.stack([lv, z], axis=1).reshape(2 * B)
                z2 = jnp.zeros(2 * B, jnp.int32)
                new_pkts = _scatter_last(flow.pkts, slot2, pk2, ins2, dump)
                new_octets = _scatter_last(flow.octets, slot2, oc2, ins2,
                                           dump)
                new_pkts_hi = _scatter_last(flow.pkts_hi, slot2, z2, ins2,
                                            dump)
                new_octets_hi = _scatter_last(flow.octets_hi, slot2, z2,
                                              ins2, dump)
            else:
                new_pkts, new_octets = flow.pkts, flow.octets
                new_pkts_hi, new_octets_hi = flow.pkts_hi, flow.octets_hi
            flow = FlowCache(
                keys=_scatter_last_rows(flow.keys, slot2, keys2, ins2,
                                        dump),
                meta=_scatter_last_rows(flow.meta, slot2, meta2, ins2,
                                        dump),
                ts=_scatter_last(flow.ts, slot2,
                                 jnp.full((2 * B,), now, jnp.int32), ins2,
                                 dump),
                pkts=new_pkts,
                octets=new_octets,
                pkts_hi=new_pkts_hi,
                octets_hi=new_octets_hi,
            )
            lm = learn["mask"] & miss
            adump = meta.aff_slots
            aff = AffinityTable(
                key_client=_scatter_last(aff.key_client, learn["aslot"],
                                         learn["client"], lm, adump),
                key_svc=_scatter_last(aff.key_svc, learn["aslot"],
                                      learn["svc"], lm, adump),
                ep=_scatter_last(aff.ep, learn["aslot"], learn["ep"], lm,
                                 adump),
                ts=_scatter_last(aff.ts, learn["aslot"],
                                 jnp.full((B,), now, jnp.int32), lm,
                                 adump),
            )

        return flow, aff, (
            outbuf(o_code), outbuf(o_svc), outbuf(o_dnat), outbuf(o_dport),
            outbuf(o_ri), outbuf(o_ro),
            outbuf(committed.astype(jnp.int32)), outbuf(o_snat),
            outbuf(o_dsr), n_evict, n_reclaim, pr_sk, pr_fb, pr_hist) + (
            (tel_hb, tel_sc) if tel_on else ())

    def noop(args):
        return args

    slow_init = (flow, aff, (out_code, out_svc, out_dnat_ip, out_dnat_port,
                             out_rule_in, out_rule_out, out_committed,
                             out_snat, out_dsr, jnp.int32(0),
                             jnp.int32(0)) + (
                             (out_dnat_w,) if A == 8 else ()) + ((
                             jnp.int32(0), jnp.int32(0),
                             jnp.zeros(len(PRUNE_HIST_BOUNDS) + 2,
                                       jnp.int32)) if prune_on else ()) + (
                             (jnp.int32(0), jnp.int32(0))
                             if tel_on else ()))
    if meta.phases & PH_SLOW:
        slow_body = slow_onepass if meta.onepass else slow
        flow, aff, outs = jax.lax.cond(n_miss > 0, slow_body, noop,
                                       slow_init)
    else:
        # Slow path masked out entirely (profiling floor): misses keep the
        # fast-path default image and commit nothing.
        flow, aff, outs = slow_init
    (out_code, out_svc, out_dnat_ip, out_dnat_port,
     out_rule_in, out_rule_out, out_committed, out_snat, out_dsr,
     n_evict, n_reclaim) = outs[:11]
    if A == 8:
        out_dnat_w = outs[11]

    final_code = out_code[:B]
    out = {
        "code": final_code,
        "est": est.astype(jnp.int32),
        # Reply-direction hit: dnat_ip_f/dnat_port carry the UN-DNAT rewrite
        # (the frontend tuple the reply's SOURCE is restored to), not a
        # destination rewrite.
        "reply": rpl.astype(jnp.int32),
        # REJECT synthesis kind (reject.go analog), derived from the
        # packet's own proto so cached REJECT hits get the right kind too.
        "reject_kind": reject_kind_of(final_code, proto),
        "svc_idx": out_svc[:B],
        "dnat_ip_f": out_dnat_ip[:B],
        "dnat_port": out_dnat_port[:B],
        "ingress_rule": out_rule_in[:B],
        "egress_rule": out_rule_out[:B],
        "committed": out_committed[:B],
        # Per-lane cache-miss mask (1 = this lane took / would take the
        # slow path).  In synchronous mode an informational overlay; in
        # the async fast step (PH_SLOW masked) it is the miss-queue
        # ADMISSION mask the engine consumes (datapath/slowpath).
        "miss": miss.astype(jnp.int32),
        # SNAT-mark classification (pipeline.go SNATMark analog): external
        # frontend traffic under ETP=Cluster needs masquerade on egress.
        "snat": out_snat[:B],
        # DSR delivery mark (pipeline.go:145 DSRServiceMarkTable): forward
        # toward dnat_ip_f (the selected endpoint) but do NOT rewrite the
        # L3 destination and do NOT SNAT; the endpoint owns the VIP and
        # replies straight to the client (pipeline.go:698-708).
        "dsr": out_dsr[:B],
        "n_miss": n_miss,
        # Live entries overwritten by a different tuple this step (the
        # direct-mapped collision cost; weak-#5 measurement surface).
        "n_evict": n_evict,
        # Dead rows (idle-expired / stale-gen) reclaimed by inserts —
        # split out of n_evict only under meta.drain_reclaim (the
        # overlapped drain's fused maintenance); always 0 otherwise.
        "n_reclaim": n_reclaim,
    }
    if prune_on:
        pos = 11 + (1 if A == 8 else 0)
        # Round-7 prune observability, aggregated over the slow-path
        # rounds (valid lanes only): aggregate-AND-zero short circuits,
        # full-width fallback redispatches, and the candidate-superblock
        # bucket counts + value sum (_prune_bucket_counts layout).  Keys
        # exist iff prune_budget > 0, so the unpruned step's output
        # pytree — and its compiled HLO — is unchanged.
        out["n_prune_skips"] = outs[pos]
        out["n_prune_fb"] = outs[pos + 1]
        out["prune_cand_hist"] = outs[pos + 2]
    if A == 8:
        # Wide (4-word) DNAT resolution — the full-address view v6
        # consumers (forwarding, StepResult) read; v4 lanes' word 3 equals
        # dnat_ip_f.  Reply hits carry the un-DNAT frontend words.
        out["dnat_w_f"] = out_dnat_w[:B]
    if tel_on:
        # Hot-path telemetry counters (observability/telemetry.py
        # TELEMETRY_COUNTERS): keys exist iff meta.telemetry, so the off
        # path's output pytree — and its compiled HLO — is unchanged.
        # The prune trio above doubles as the telemetry candidate-hist /
        # skip / fallback source when prune_budget > 0.
        pos_t = 11 + (1 if A == 8 else 0) + (3 if prune_on else 0)
        out["tel_probe_hit"] = tel_probe_hit
        out["tel_probe_stale"] = tel_probe_stale
        out["tel_probe_miss"] = tel_probe_miss
        out["tel_dma_hb"] = outs[pos_t]
        out["tel_chance_bumps"] = outs[pos_t + 1]
    return PipelineState(flow=flow, aff=aff), out


# jit wrapper: meta is static.
pipeline_step = jax.jit(_pipeline_step, static_argnames=("meta", "hit_combine"))

# Overlapped-drain variant with the STATE argument DONATED (the donated
# carries of SNIPPETS [3]'s pjit shape): the drain rewrites keys/meta/ts
# wholesale, so without donation XLA must allocate fresh output buffers
# for ~150MB of cache columns per drain and the dispatch pipeline stalls
# on the copies.  Donation lets XLA alias the scatters in place and
# pipeline drain N's commit under batch N+1's dispatch.  Callers MUST
# drop every reference to the passed state (the datapath's single-owner
# `self._state` discipline guarantees this between host calls; the
# commit plane's snapshots live only inside an install transaction,
# during which no drain runs).
pipeline_step_donated = jax.jit(
    _pipeline_step, static_argnames=("meta", "hit_combine"),
    donate_argnums=(0,),
)


def _cache_stats(state: PipelineState):
    """On-demand flow-cache census (full scan — not for the per-step path):
    occupancy, committed (eternal-gen, incl. reply) and denial entries."""
    kpg = state.flow.keys[:-1, -1]  # pg is the LAST key column (any width)
    valid = kpg != 0
    gen = (kpg >> 9) & GEN_ETERNAL
    est = valid & (gen == GEN_ETERNAL)
    return {
        "occupied": valid.sum(dtype=jnp.int32),
        "committed": est.sum(dtype=jnp.int32),
        "denials": (valid & ~est).sum(dtype=jnp.int32),
        "slots": jnp.int32(kpg.shape[0]),
    }


cache_stats = jax.jit(_cache_stats)


def _live_rows(keys: jax.Array) -> jax.Array:
    """Occupied-entry mask over the full (N+1,) row space with the dump
    row (index N, the masked-scatter junk target) excluded."""
    kpg = keys[:, -1]
    n = kpg.shape[0]
    return (kpg != 0) & (jnp.arange(n, dtype=jnp.int32) < n - 1)


def _age_scan(state: PipelineState, now: jax.Array, *, timeouts):
    """Off-hot-step aging scan (datapath/slowpath epoch plane): physically
    clear entries idle past their per-state conntrack lifetime.

    Semantics-neutral by construction: an expired entry is already dead to
    lookups (_cache_lookup freshness check), so clearing it changes no
    verdict — it reclaims the slot, turning a later insert over it from an
    "eviction" into plain occupancy.  The synchronous datapath never runs
    this (expiry-by-lookup suffices); the async engine runs it between
    drains and publishes the result via epoch swap.

    -> (state', n_reclaimed).
    """
    flow = state.flow
    kpg = flow.keys[:, -1]
    conf = (flow.meta[:, _meta_cols(flow.keys.shape[1] - 2)[3]] >> 29) & 1
    tmo = entry_timeout(conf, kpg & 0xFF, timeouts)
    expired = _live_rows(flow.keys) & ((now - flow.ts) > tmo)
    keys = jnp.where(expired[:, None], 0, flow.keys)
    return (
        state._replace(flow=flow._replace(keys=keys)),
        expired.sum(dtype=jnp.int32),
    )


age_scan = jax.jit(_age_scan, static_argnames=("timeouts",))


def _revalidate_scan(state: PipelineState, gen: jax.Array):
    """Off-hot-step revalidation (datapath/slowpath epoch plane): clear
    DENIAL entries whose generation predates the current bundle.

    Stale-gen denials are already dead to lookups (the megaflow
    revalidation analog — _cache_lookup's gen compare), so this is the
    lazy slot-reclaim a bundle swap schedules instead of flushing the
    cache; established (eternal-gen) entries, reply legs included, are
    untouched — the flows-survive-churn invariant.  -> (state', n_cleared).
    """
    flow = state.flow
    kpg = flow.keys[:, -1]
    egen = (kpg >> 9) & GEN_ETERNAL
    gen_w = jnp.asarray(gen, jnp.int32) % GEN_ETERNAL
    stale = (
        _live_rows(flow.keys) & (egen != GEN_ETERNAL) & (egen != gen_w)
    )
    keys = jnp.where(stale[:, None], 0, flow.keys)
    return (
        state._replace(flow=flow._replace(keys=keys)),
        stale.sum(dtype=jnp.int32),
    )


revalidate_scan = jax.jit(_revalidate_scan)


def _maintain_scan(state: PipelineState, now: jax.Array, gen: jax.Array,
                   *, timeouts):
    """FUSED off-hot-step maintenance (ROADMAP item 2 / round 6): one pass
    over the flow cache performing both the aging scan and the
    stale-generation revalidation that previously ran as two separate
    full-table transforms — keys/meta/ts are each read ONCE and the keys
    written once, halving the HBM traffic of an epoch-stale heal.

    Semantics-neutral exactly like its two parents: both row classes are
    already dead to lookups (freshness / gen compare), so clearing them
    changes no verdict.  A row that is both expired AND stale counts as
    aged (the partition the oracle twin applies in the same order).

    -> (state', n_aged, n_revalidated).
    """
    flow = state.flow
    kpg = flow.keys[:, -1]
    live = _live_rows(flow.keys)
    conf = (flow.meta[:, _meta_cols(flow.keys.shape[1] - 2)[3]] >> 29) & 1
    tmo = entry_timeout(conf, kpg & 0xFF, timeouts)
    expired = live & ((now - flow.ts) > tmo)
    egen = (kpg >> 9) & GEN_ETERNAL
    gen_w = jnp.asarray(gen, jnp.int32) % GEN_ETERNAL
    stale = (
        live & (egen != GEN_ETERNAL) & (egen != gen_w) & ~expired
    )
    keys = jnp.where((expired | stale)[:, None], 0, flow.keys)
    return (
        state._replace(flow=flow._replace(keys=keys)),
        expired.sum(dtype=jnp.int32),
        stale.sum(dtype=jnp.int32),
    )


maintain_scan = jax.jit(_maintain_scan, static_argnames=("timeouts",))


# ---- audit plane transforms (datapath/audit.py) ---------------------------
# The continuous revalidator runs OFF the hot step, like age_scan and
# canary_scan: nothing here is reachable from pipeline_step, so with the
# audit plane idle the compiled step is bit-identical to a plane-less
# build (tests/test_cache_audit.py verifies the lowered HLO, the same way
# tools/check_phases.py pins the PH_* masks).


def _audit_gather(state: PipelineState, cursor: jax.Array, *, window: int):
    """Rotating-cursor window gather for the cache revalidation scan: rows
    [cursor, cursor+window) of the flow cache (mod slot count, dump row
    excluded) -> (keys, meta, ts) — the device side of one audit step; the
    host decodes and re-proves the sampled entries."""
    N = state.flow.keys.shape[0] - 1
    idx = (jnp.arange(window, dtype=jnp.int32) + cursor) % N
    return state.flow.keys[idx], state.flow.meta[idx], state.flow.ts[idx]


audit_gather = jax.jit(_audit_gather, static_argnames=("window",))


def _audit_evict(state: PipelineState, slots: jax.Array):
    """Repair-by-eviction for divergent audited entries: clear the key rows
    of `slots` ((K,) i32, -1 padding ignored) so the flows reclassify
    lazily on their next packet — the mark_stale discipline; the cached
    value is never trusted, never patched in place.  -> (state', n)."""
    N = state.flow.keys.shape[0] - 1
    live = (slots >= 0) & (slots < N)
    tgt = jnp.where(live, slots, N)
    keys = state.flow.keys.at[tgt].set(0)
    return (
        state._replace(flow=state.flow._replace(keys=keys)),
        live.sum(dtype=jnp.int32),
    )


audit_evict = jax.jit(_audit_evict)


def _digest_pair(words: jax.Array) -> jax.Array:
    """(N,) i32 -> (2,) i32 [xor-fold, wrapping sum]: the Fletcher-style
    pair the tensor scrub compares — XOR catches any single bit flip, the
    order-weighted-by-nothing sum catches the paired flips XOR folds out."""
    return jnp.stack([
        jax.lax.reduce(words, jnp.int32(0), jax.lax.bitwise_xor, (0,)),
        jnp.sum(words, dtype=jnp.int32),
    ])


_digest_fold = jax.jit(_digest_pair)


def _digest_words_of(arr) -> jax.Array:
    """Any device array -> a flat i32 view (32-bit dtypes bitcast, others
    value-cast — determinism is what the digest needs, not bit fidelity)."""
    a = jnp.asarray(arr).reshape(-1)
    if a.dtype == jnp.int32:
        return a
    if a.dtype.itemsize == 4:
        return jax.lax.bitcast_convert_type(a, jnp.int32)
    return a.astype(jnp.int32)


def tensor_digest(leaves) -> int:
    """Checksum-scrub digest of a pytree-leaf iterable: per-leaf jitted
    XOR/sum folds (device-side; only two scalars transfer back per leaf)
    combined into one host int.  Shape-stable per bundle, so the folds hit
    the jit cache on every scan after the first."""
    h = 0
    for leaf in leaves:
        words = _digest_words_of(leaf)
        if words.shape[0] == 0:
            xor, s = 0, 0
        else:
            pair = np.asarray(_digest_fold(words))
            xor, s = int(pair[0]) & 0xFFFFFFFF, int(pair[1]) & 0xFFFFFFFF
        h = (h * 1000003 + xor) & 0xFFFFFFFFFFFFFFFF
        h = (h * 1000003 + s) & 0xFFFFFFFFFFFFFFFF
    return h


def _pipeline_trace(
    state: PipelineState,
    drs: DeviceRuleSet,
    dsvc: DeviceServiceTables,
    src_f: jax.Array,
    dst_f: jax.Array,
    proto: jax.Array,
    sport: jax.Array,
    dport: jax.Array,
    now: jax.Array,
    gen: jax.Array,
    *,
    meta: PipelineMeta,
    hit_combine=None,
    v6=None,
):
    """Read-only per-packet stage trace (the Traceflow analog,
    ref framework.go:328-338): every packet is walked through ServiceLB and
    the full classifier regardless of cache state, and the cache lookup is
    reported alongside — no state is mutated, like a Traceflow probe marked
    to bypass conntrack commit.
    """
    flow, aff = state.flow, state.aff
    N = meta.flow_slots
    A = meta.key_words - 2
    src_raw = _raw_bits(src_f)
    dst_raw = _raw_bits(dst_f)
    pp = (sport << 16) | dport
    gen_w = jnp.asarray(gen, jnp.int32) % GEN_ETERNAL

    if A == 2:
        if v6 is not None:
            raise ValueError(
                "v6 lanes require a dual_stack pipeline "
                "(make_pipeline(dual_stack=True))"
            )
        is6 = None
        saddr = daddr = None
        addr = jnp.stack([src_f, dst_f], axis=1)
        h = hashing.flow_hash(src_raw, dst_raw, proto, sport, dport, xp=jnp)
    else:
        if v6 is not None:
            src6w, dst6w, is6 = v6
        else:
            is6 = jnp.zeros_like(src_f)
            src6w = dst6w = None
        saddr = _wide_words(src_f, src6w, is6)
        daddr = _wide_words(dst_f, dst6w, is6)
        addr = jnp.concatenate([saddr, daddr], axis=1)
        h = hashing.flow_hash_wide(
            [addr[:, i] for i in range(8)], proto, sport, dport, xp=jnp
        )
    slot = (h & jnp.uint32(N - 1)).astype(jnp.int32)
    pg_cur = proto | 0x100 | (gen_w << 9)
    pg_est = proto | 0x100 | (GEN_ETERNAL << 9)
    hit, est, rpl, mr, _kr, _ts = _cache_lookup(
        flow, slot, addr, pp, pg_cur, pg_est, now, proto, meta
    )
    DC, M1C, _RC, _ZC = _meta_cols(A)
    c_code, c_svc, c_dport = _unpack_meta1(mr[:, M1C])

    svc_idx, no_ep, dnat_ip, dnat_port, snat, dsr, dnat_w, _learn = _service_lb(
        aff, dsvc, h, src_f, dst_f, proto, dport, now, meta.aff_slots,
        wide=None if A == 2 else (saddr, daddr, is6),
    )
    cls = classify_batch(
        drs, src_f, dnat_ip, proto, dnat_port,
        meta=meta.match, hit_combine=hit_combine,
        # The twin walk carries the instance's fused meta (round 8): a
        # fused datapath's canary/audit probes then exercise the SAME
        # pallas consumers the serving kernel uses, so the PR 4/5 planes
        # certify the serving configuration, not a shadow XLA path.
        fused=meta.fused,
        v6=None if A == 2 else (saddr, dnat_w, is6),
        svc_ref=_svc_ref_of(svc_idx, dsvc),
    )
    fresh_code = jnp.where(no_ep, ACT_REJECT, cls["code"]).astype(jnp.int32)
    code = jnp.where(hit, c_code, fresh_code)
    out = {
        "cache_hit": hit.astype(jnp.int32),
        "est": est.astype(jnp.int32),
        "reply": rpl.astype(jnp.int32),
        "cached_code": jnp.where(hit, c_code, -1),
        # Cached DNAT resolution (meta row), so trace consumers can derive
        # forwarding for hit lanes from the entry the STEP path would use
        # (service updates after commit may make the fresh walk differ).
        "cached_dnat_ip_f": mr[:, DC],
        "cached_dnat_port": c_dport,
        "svc_idx": svc_idx,
        "no_ep": no_ep.astype(jnp.int32),
        "dnat_ip_f": dnat_ip,
        "dnat_port": dnat_port,
        "snat": snat,
        "dsr": dsr,
        "egress_code": cls["egress_code"],
        "egress_rule": cls["egress_rule"],
        "ingress_code": cls["ingress_code"],
        "ingress_rule": cls["ingress_rule"],
        "fresh_code": fresh_code,
        "code": code,
        "reject_kind": reject_kind_of(code, proto),
    }
    if A == 8:
        out["dnat_w_f"] = dnat_w
        out["cached_dnat_w_f"] = mr[:, 0:4]
    return out


pipeline_trace = jax.jit(_pipeline_trace, static_argnames=("meta", "hit_combine"))
