"""Churn-loop phase profiler for the stateful pipeline.

The round-5 verdict's weak #1: the churn regime runs at ~0.5x the north
star and ~3x below what the component numbers predict, and the slow-path
loop had never been profiled.  This module attributes the churn-step time
to named phases WITHOUT host-side timers (which lie in both directions on
the tunneled platform, utils/timing.py): the slow path is compiled at a
chain of cumulative phase masks (models/pipeline.PH_*), each variant is
timed on-device with `device_loop_time`, and the per-phase cost is the
telescoped difference between adjacent masks — so the phase breakdown sums
EXACTLY to the full-step time by construction, and an independent
full-step measurement cross-checks the chain (bench_profile.py gates on
+-15% agreement).

Workload shape mirrors bench.measure_churn: a warmed hot set (established
traffic, fast-path hits) with a rolling window of genuinely fresh flows
from a pool replacing the first `n_new` lanes every step — every timed
iteration pays the same miss work regardless of which phases are masked.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.timing import device_loop_time
from . import pipeline as pl

# The cumulative mask chain: phase k's cost = t(chain[k]) - t(chain[k-1]).
# Order matters — each mask is a superset of the previous, and PH_EVICT
# rides last because the eviction audit reads the commit's insert targets.
PHASE_CHAIN: tuple[tuple[str, int], ...] = (
    ("fast_path", 0),
    ("miss_detect", pl.PH_SLOW),
    ("service_lb", pl.PH_SLOW | pl.PH_LB),
    # PH_CLS_SUM: the classifier's aggregate (summary) phase alone — a
    # ~zero-cost entry unless the meta carries a prune budget (round 7),
    # where it splits summary-gather cost from candidate-gather cost.
    ("classify_summary", pl.PH_SLOW | pl.PH_LB | pl.PH_CLS_SUM),
    ("classify", pl.PH_SLOW | pl.PH_LB | pl.PH_CLS_SUM | pl.PH_CLS),
    ("cache_commit",
     pl.PH_SLOW | pl.PH_LB | pl.PH_CLS_SUM | pl.PH_CLS | pl.PH_COMMIT),
    ("eviction_scan", pl.PH_ALL),
)

# Async-regime chain (datapath/slowpath): the floor is the decoupled FAST
# step (phases=0 — misses admitted, not classified), then each drain
# phase adds one PH_ bit to the COALESCED drain step, which runs the
# fresh window as ONE slow-path round (miss_chunk == drain batch) instead
# of the sync path's many chunked rounds.  Same telescoped-differencing
# honesty property; same PH_* bit set (tools/check_phases.py gates the
# two chains and the pipeline masks against each other).
ASYNC_PHASE_CHAIN: tuple[tuple[str, int], ...] = (
    ("async_fast_path", 0),
    ("drain_miss_detect", pl.PH_SLOW),
    ("drain_service_lb", pl.PH_SLOW | pl.PH_LB),
    ("drain_classify_summary", pl.PH_SLOW | pl.PH_LB | pl.PH_CLS_SUM),
    ("drain_classify", pl.PH_SLOW | pl.PH_LB | pl.PH_CLS_SUM | pl.PH_CLS),
    ("drain_cache_commit",
     pl.PH_SLOW | pl.PH_LB | pl.PH_CLS_SUM | pl.PH_CLS | pl.PH_COMMIT),
    ("drain_eviction_scan", pl.PH_ALL),
)

# Overlapped-regime chain (round 6, ROADMAP item 2): the double-buffered
# cadence — every timed iteration dispatches the FAST step of window i
# and the DRAIN of window i-1 (one-step-deferred commit, the two-slot
# pending-commit staging of datapath/slowpath), with the drain compiled
# at meta.drain_reclaim=True (the fused eviction+aging commit pass).
# Because the drain of window i-1 has no data dependency on the fast
# step of window i's OUTPUTS (only on the carried state), XLA is free to
# pipeline the two dispatches; the telescoped chain then attributes what
# the overlap actually hides — if drain phases telescope to ~0 over the
# async chain's costs, the serialization was removed; if they reappear,
# it was not.  Same honesty property, same PH_* bit set
# (tools/check_phases.py gates all three chains).
OVERLAP_PHASE_CHAIN: tuple[tuple[str, int], ...] = (
    ("overlap_fast_path", 0),
    ("overlap_miss_detect", pl.PH_SLOW),
    ("overlap_service_lb", pl.PH_SLOW | pl.PH_LB),
    ("overlap_classify_summary", pl.PH_SLOW | pl.PH_LB | pl.PH_CLS_SUM),
    ("overlap_classify",
     pl.PH_SLOW | pl.PH_LB | pl.PH_CLS_SUM | pl.PH_CLS),
    ("overlap_cache_commit",
     pl.PH_SLOW | pl.PH_LB | pl.PH_CLS_SUM | pl.PH_CLS | pl.PH_COMMIT),
    ("overlap_evict_age", pl.PH_ALL),
)

# Maintenance-regime chain (the unified background plane, ROADMAP item 5):
# the async cadence with the scheduler's fused maintenance pass
# (pl.maintain_scan — the cache-maintain task's full-table aging +
# stale-generation revalidation) riding EVERY timed iteration, chain
# entry 0 included.  Because the rider is a constant across all entries,
# the telescoped differences still attribute the pure drain phases (the
# rider cancels), the chain end is the full maintenance-cadence step (the
# honesty gate's target), and `maint_fast_path` minus a rider-free fast
# step — reported as `maintenance_s` by profile_churn_maintenance — is
# the scheduler's own attributed cost.  Same PH_* bit set
# (tools/check_phases.py gates all four chains).
MAINT_PHASE_CHAIN: tuple[tuple[str, int], ...] = (
    ("maint_fast_path", 0),
    ("maint_miss_detect", pl.PH_SLOW),
    ("maint_service_lb", pl.PH_SLOW | pl.PH_LB),
    ("maint_classify_summary", pl.PH_SLOW | pl.PH_LB | pl.PH_CLS_SUM),
    ("maint_classify", pl.PH_SLOW | pl.PH_LB | pl.PH_CLS_SUM | pl.PH_CLS),
    ("maint_cache_commit",
     pl.PH_SLOW | pl.PH_LB | pl.PH_CLS_SUM | pl.PH_CLS | pl.PH_COMMIT),
    ("maint_sweep", pl.PH_ALL),
)


# Prune-regime chain (round 7, ROADMAP item 2's kernel half): the async
# drain cadence over a prune_budget > 0 meta, with the classify entry
# SPLIT at the two-level kernel's seam — `prune_summary_gather` adds
# PH_CLS_SUM (aggregate rows gathered + ANDed, short-circuit defaults,
# no candidate work) and `prune_candidate_gather` adds PH_CLS on top
# (the K-superblock candidate gather, the first-match scan, and the
# pow2-rung fallback redispatches).  Telescoping their difference IS the
# candidate-path cost the aggregate layer was built to bound; the ±15%
# gate (bench_profile.py --mode prune) cross-checks the attribution.
PRUNE_PHASE_CHAIN: tuple[tuple[str, int], ...] = (
    ("prune_fast_path", 0),
    ("prune_miss_detect", pl.PH_SLOW),
    ("prune_service_lb", pl.PH_SLOW | pl.PH_LB),
    ("prune_summary_gather", pl.PH_SLOW | pl.PH_LB | pl.PH_CLS_SUM),
    ("prune_candidate_gather",
     pl.PH_SLOW | pl.PH_LB | pl.PH_CLS_SUM | pl.PH_CLS),
    ("prune_cache_commit",
     pl.PH_SLOW | pl.PH_LB | pl.PH_CLS_SUM | pl.PH_CLS | pl.PH_COMMIT),
    ("prune_evict", pl.PH_ALL),
)


# One-kernel (fused) regime chain (round 8, ROADMAP item 1): the async
# drain cadence over a meta.onepass=True meta.  The phases honor the
# same PH_ bits — the LB probe chain, the aggregate (summary) gathers,
# the commit scatters and the eviction audit are still maskable XLA
# stages around the kernel — but `fused_onepass` (the PH_CLS add) is
# deliberately ONE entry: probe decode, candidate DMA, first-match,
# resolve and commit-row packing have no interior dispatch boundaries
# left to telescope, which is the point of the fusion.  Diffing this
# chain against PRUNE_PHASE_CHAIN attributes exactly what the one-pass
# removed (the staged kernel's classify/commit materialization
# boundaries); the ±15% gate applies via bench_profile.py --mode fused.
FUSED_PHASE_CHAIN: tuple[tuple[str, int], ...] = (
    ("fused_fast_path", 0),
    ("fused_miss_detect", pl.PH_SLOW),
    ("fused_service_lb", pl.PH_SLOW | pl.PH_LB),
    ("fused_summary_gather", pl.PH_SLOW | pl.PH_LB | pl.PH_CLS_SUM),
    ("fused_onepass", pl.PH_SLOW | pl.PH_LB | pl.PH_CLS_SUM | pl.PH_CLS),
    ("fused_commit",
     pl.PH_SLOW | pl.PH_LB | pl.PH_CLS_SUM | pl.PH_CLS | pl.PH_COMMIT),
    ("fused_evict", pl.PH_ALL),
)


def _dev_cols(batch) -> tuple:
    """PacketBatch -> the pipeline's flipped/typed device columns."""
    from ..utils import ip as iputil

    return (
        jnp.asarray(iputil.flip_u32(batch.src_ip)),
        jnp.asarray(iputil.flip_u32(batch.dst_ip)),
        jnp.asarray(batch.proto.astype(np.int32)),
        jnp.asarray(batch.src_port.astype(np.int32)),
        jnp.asarray(batch.dst_port.astype(np.int32)),
    )


def profile_churn(
    meta: pl.PipelineMeta,
    state: pl.PipelineState,
    drs,
    dsvc,
    hot: tuple,
    pool: Optional[tuple] = None,
    *,
    n_new: Optional[int] = None,
    now0: int = 1000,
    gen: int = 0,
    k_small: int = 2,
    k_big: int = 8,
    repeats: int = 2,
    chain: tuple = PHASE_CHAIN,
) -> dict:
    """Per-phase churn-loop breakdown -> structured dict.

    hot/pool are 5-column tuples (src_f, dst_f, proto, sport, dport) of
    device arrays — hot is the established set (warmed before timing),
    pool supplies fresh flows (one lane per distinct flow); each timed
    step replaces the first n_new hot lanes with the next rolling pool
    window, so every iteration pays n_new genuine misses.  pool=None
    times a pure fast-path (never-miss) regime — the slow-path phases
    then measure only the lax.cond dispatch floor.

    The state is treated functionally: the caller's `state` is never
    mutated (warmup operates on a local copy of the carried pytree).
    """
    B = int(hot[0].shape[0])
    if pool is not None:
        pool_len = int(pool[0].shape[0])
        if n_new is None:
            n_new = max(1, B // 8)
        if n_new > B or n_new >= pool_len:
            raise ValueError(
                f"n_new={n_new} must fit the batch ({B}) and pool "
                f"({pool_len})"
            )
    else:
        pool_len = 0
        n_new = 0

    # Warm the hot set (full-phase steps) so timed hot lanes are cache
    # hits: two passes — classify + commit, then a hit pass to settle the
    # partner-refresh stamps.
    full = meta._replace(phases=pl.PH_ALL)
    st = state
    for w in range(2):
        st, _ = pl.pipeline_step(
            st, drs, dsvc, *hot, jnp.int32(now0 - 2 + w), jnp.int32(gen),
            meta=full,
        )

    def timed(mask: int) -> float:
        m = meta._replace(phases=mask)

        def body(i, carry):
            # acc leads the carry: device_loop_time fetches the FIRST leaf
            # to detect completion (utils/timing.py), so it must change
            # every iteration.
            acc, cst, drs_, dsvc_, hcols, pcols = carry
            if n_new:
                off = (acc[1] * n_new) % (pool_len - n_new)

                def mix(hcol, pcol):
                    fresh = jax.lax.dynamic_slice(pcol, (off,), (n_new,))
                    return jnp.concatenate([hcol[: B - n_new], fresh])

                cols = tuple(mix(h, p) for h, p in zip(hcols, pcols))
            else:
                cols = hcols
            cst, o = pl._pipeline_step(
                cst, drs_, dsvc_, *cols, now0 + i, gen, meta=m,
            )
            acc = acc.at[0].add(o["code"].sum(dtype=jnp.int32) + o["n_miss"])
            acc = acc.at[1].add(1)
            return (acc, cst, drs_, dsvc_, hcols, pcols)

        pcols = pool if pool is not None else hot  # unused when n_new == 0
        carry = (jnp.zeros(8, jnp.int32), st, drs, dsvc, hot, pcols)
        return device_loop_time(
            body, carry, k_small=k_small, k_big=k_big, repeats=repeats
        )

    cumulative: dict[str, float] = {}
    phases: dict[str, float] = {}
    prev = 0.0
    for name, mask in chain:
        t = timed(mask)
        cumulative[name] = t
        # Raw telescoped difference: may go slightly negative under run-to-
        # run jitter; kept UNCLAMPED so the phase sum equals the chain-end
        # time exactly (the honesty property bench_profile gates on).
        phases[name] = t - prev
        prev = t
    total = cumulative[chain[-1][0]]
    return {
        "batch": B,
        "fresh_per_step": n_new,
        "phases_s": phases,
        "cumulative_s": cumulative,
        "total_s": total,
        "pps": B / total,
        "phase_fractions": {k: v / total for k, v in phases.items()},
    }


def profile_churn_async(
    meta: pl.PipelineMeta,
    state: pl.PipelineState,
    drs,
    dsvc,
    hot: tuple,
    pool: tuple,
    *,
    n_new: Optional[int] = None,
    now0: int = 1000,
    gen: int = 0,
    k_small: int = 2,
    k_big: int = 8,
    repeats: int = 2,
    chain: tuple = ASYNC_PHASE_CHAIN,
) -> dict:
    """Per-phase breakdown of the ASYNC churn regime (datapath/slowpath).

    Models the engine's steady cadence — every step is one decoupled FAST
    dispatch over the mixed batch (phases=0: hot lanes hit, the n_new
    fresh lanes are admitted unclassified) plus one COALESCED drain
    dispatch over exactly that fresh window (miss_chunk == n_new, a
    single slow-path round).  chain[0] times the fast dispatch alone; the
    drain entries then add one PH_ bit at a time to the drain dispatch,
    so `drain_miss_detect` carries the drain call's fixed costs (its own
    lookup pass + dispatch) and the rest attribute like the sync chain.
    Telescoped differencing: phase sums equal the chain-end (full async
    step) time by construction.
    """
    B = int(hot[0].shape[0])
    if pool is None:
        raise ValueError("async profiling needs a fresh-flow pool "
                         "(the regime under study is miss handling)")
    pool_len = int(pool[0].shape[0])
    if n_new is None:
        n_new = max(1, B // 8)
    if n_new > B or n_new >= pool_len:
        raise ValueError(
            f"n_new={n_new} must fit the batch ({B}) and pool ({pool_len})"
        )

    full = meta._replace(phases=pl.PH_ALL)
    meta_fast = meta._replace(phases=0)
    st = state
    for w in range(2):
        st, _ = pl.pipeline_step(
            st, drs, dsvc, *hot, jnp.int32(now0 - 2 + w), jnp.int32(gen),
            meta=full,
        )

    def timed(mask: int, with_drain: bool) -> float:
        m_drain = meta._replace(phases=mask, miss_chunk=n_new)

        def body(i, carry):
            acc, cst, drs_, dsvc_, hcols, pcols = carry
            off = (acc[1] * n_new) % (pool_len - n_new)
            fresh = tuple(
                jax.lax.dynamic_slice(pc, (off,), (n_new,)) for pc in pcols
            )
            cols = tuple(
                jnp.concatenate([h[: B - n_new], f])
                for h, f in zip(hcols, fresh)
            )
            cst, o = pl._pipeline_step(
                cst, drs_, dsvc_, *cols, now0 + i, gen, meta=meta_fast,
            )
            acc = acc.at[0].add(o["code"].sum(dtype=jnp.int32) + o["n_miss"])
            if with_drain:
                cst, od = pl._pipeline_step(
                    cst, drs_, dsvc_, *fresh, now0 + i, gen, meta=m_drain,
                )
                acc = acc.at[0].add(
                    od["code"].sum(dtype=jnp.int32) + od["n_miss"]
                )
            acc = acc.at[1].add(1)
            return (acc, cst, drs_, dsvc_, hcols, pcols)

        carry = (jnp.zeros(8, jnp.int32), st, drs, dsvc, hot, pool)
        return device_loop_time(
            body, carry, k_small=k_small, k_big=k_big, repeats=repeats
        )

    cumulative: dict[str, float] = {}
    phases: dict[str, float] = {}
    prev = 0.0
    for j, (name, mask) in enumerate(chain):
        t = timed(mask, with_drain=j > 0)
        cumulative[name] = t
        phases[name] = t - prev  # unclamped (honesty property; see sync)
        prev = t
    total = cumulative[chain[-1][0]]
    return {
        "mode": "async",
        "batch": B,
        "fresh_per_step": n_new,
        "drain_batch": n_new,
        "phases_s": phases,
        "cumulative_s": cumulative,
        "total_s": total,
        "pps": B / total,
        "phase_fractions": {k: v / total for k, v in phases.items()},
    }


def profile_churn_overlap(
    meta: pl.PipelineMeta,
    state: pl.PipelineState,
    drs,
    dsvc,
    hot: tuple,
    pool: tuple,
    *,
    n_new: Optional[int] = None,
    now0: int = 1000,
    gen: int = 0,
    k_small: int = 2,
    k_big: int = 8,
    repeats: int = 2,
    chain: tuple = OVERLAP_PHASE_CHAIN,
) -> dict:
    """Per-phase breakdown of the OVERLAPPED churn regime (round 6).

    Models the double-buffered engine cadence: iteration i dispatches the
    decoupled FAST step over the mixed batch (phases=0, window i's fresh
    lanes admitted unclassified) and then the COALESCED drain of window
    i-1 — the one-step commit deferral of the two-slot pending-commit
    staging, under which drain i-1's scatters carry no data dependency on
    fast step i's outputs and XLA can pipeline the dispatches.  The drain
    runs at meta.drain_reclaim=True (fused eviction+aging accounting).
    The chain telescopes exactly like the async chain, so diffing the two
    breakdowns attributes the overlap win phase by phase.

    Semantics note: window i's verdicts land one iteration late (the
    lost-update guard makes them visible to iteration i+1's lookups via
    the carried state), which is exactly the engine's staged-commit
    observable behavior — the profiled program IS the production cadence.
    """
    B = int(hot[0].shape[0])
    if pool is None:
        raise ValueError("overlap profiling needs a fresh-flow pool "
                         "(the regime under study is miss handling)")
    pool_len = int(pool[0].shape[0])
    if n_new is None:
        n_new = max(1, B // 8)
    if n_new > B or n_new >= pool_len:
        raise ValueError(
            f"n_new={n_new} must fit the batch ({B}) and pool ({pool_len})"
        )

    full = meta._replace(phases=pl.PH_ALL)
    meta_fast = meta._replace(phases=0)
    st = state
    for w in range(2):
        st, _ = pl.pipeline_step(
            st, drs, dsvc, *hot, jnp.int32(now0 - 2 + w), jnp.int32(gen),
            meta=full,
        )

    def timed(mask: int, with_drain: bool) -> float:
        m_drain = meta._replace(phases=mask, miss_chunk=n_new,
                                drain_reclaim=True)

        def body(i, carry):
            acc, cst, drs_, dsvc_, hcols, pcols = carry
            off = (acc[1] * n_new) % (pool_len - n_new)
            # Window i-1 (the deferred commit): acc[1] counts completed
            # iterations, so the "previous" offset trails by one window —
            # iteration 0 re-drains the warmed hot prefix (same cost
            # shape, no semantic weight in a timing loop).
            off_prev = (jnp.maximum(acc[1] - 1, 0) * n_new) % (
                pool_len - n_new)
            fresh = tuple(
                jax.lax.dynamic_slice(pc, (off,), (n_new,)) for pc in pcols
            )
            prev = tuple(
                jax.lax.dynamic_slice(pc, (off_prev,), (n_new,))
                for pc in pcols
            )
            cols = tuple(
                jnp.concatenate([h[: B - n_new], f])
                for h, f in zip(hcols, fresh)
            )
            cst, o = pl._pipeline_step(
                cst, drs_, dsvc_, *cols, now0 + i, gen, meta=meta_fast,
            )
            acc = acc.at[0].add(o["code"].sum(dtype=jnp.int32) + o["n_miss"])
            if with_drain:
                cst, od = pl._pipeline_step(
                    cst, drs_, dsvc_, *prev, now0 + i, gen, meta=m_drain,
                )
                acc = acc.at[0].add(
                    od["code"].sum(dtype=jnp.int32) + od["n_miss"]
                )
            acc = acc.at[1].add(1)
            return (acc, cst, drs_, dsvc_, hcols, pcols)

        carry = (jnp.zeros(8, jnp.int32), st, drs, dsvc, hot, pool)
        return device_loop_time(
            body, carry, k_small=k_small, k_big=k_big, repeats=repeats
        )

    cumulative: dict[str, float] = {}
    phases: dict[str, float] = {}
    prev = 0.0
    for j, (name, mask) in enumerate(chain):
        t = timed(mask, with_drain=j > 0)
        cumulative[name] = t
        phases[name] = t - prev  # unclamped (honesty property; see sync)
        prev = t
    total = cumulative[chain[-1][0]]
    return {
        "mode": "overlap",
        "batch": B,
        "fresh_per_step": n_new,
        "drain_batch": n_new,
        "phases_s": phases,
        "cumulative_s": cumulative,
        "total_s": total,
        "pps": B / total,
        "phase_fractions": {k: v / total for k, v in phases.items()},
    }


def profile_churn_maintenance(
    meta: pl.PipelineMeta,
    state: pl.PipelineState,
    drs,
    dsvc,
    hot: tuple,
    pool: tuple,
    *,
    n_new: Optional[int] = None,
    now0: int = 1000,
    gen: int = 0,
    k_small: int = 2,
    k_big: int = 8,
    repeats: int = 2,
    chain: tuple = MAINT_PHASE_CHAIN,
) -> dict:
    """Per-phase breakdown of the MAINTENANCE cadence (the unified
    background plane, datapath/maintenance.py): the async churn cadence
    with the scheduler's fused maintenance pass (pl.maintain_scan — one
    full-table aging + stale-generation revalidation, the cache-maintain
    task) riding every timed iteration, chain entry 0 included.

    Attribution: the rider is constant across chain entries, so the
    telescoped differences still isolate the pure drain phases (the
    rider cancels), while `maintenance_s` — maint_fast_path minus a
    separately-timed rider-FREE fast step — is the background plane's
    own attributed per-step cost.  Diffing this breakdown against the
    async chain's shows the consolidation's overhead phase by phase;
    sums still equal the chain-end time by construction (the honesty
    property bench_profile.py gates at ±15%)."""
    B = int(hot[0].shape[0])
    if pool is None:
        raise ValueError("maintenance profiling needs a fresh-flow pool "
                         "(the regime under study is steady churn)")
    pool_len = int(pool[0].shape[0])
    if n_new is None:
        n_new = max(1, B // 8)
    if n_new > B or n_new >= pool_len:
        raise ValueError(
            f"n_new={n_new} must fit the batch ({B}) and pool ({pool_len})"
        )

    full = meta._replace(phases=pl.PH_ALL)
    meta_fast = meta._replace(phases=0)
    st = state
    for w in range(2):
        st, _ = pl.pipeline_step(
            st, drs, dsvc, *hot, jnp.int32(now0 - 2 + w), jnp.int32(gen),
            meta=full,
        )

    def timed(mask: int, with_drain: bool, with_maint: bool) -> float:
        m_drain = meta._replace(phases=mask, miss_chunk=n_new)

        def body(i, carry):
            acc, cst, drs_, dsvc_, hcols, pcols = carry
            off = (acc[1] * n_new) % (pool_len - n_new)
            fresh = tuple(
                jax.lax.dynamic_slice(pc, (off,), (n_new,)) for pc in pcols
            )
            cols = tuple(
                jnp.concatenate([h[: B - n_new], f])
                for h, f in zip(hcols, fresh)
            )
            cst, o = pl._pipeline_step(
                cst, drs_, dsvc_, *cols, now0 + i, gen, meta=meta_fast,
            )
            acc = acc.at[0].add(o["code"].sum(dtype=jnp.int32) + o["n_miss"])
            if with_drain:
                cst, od = pl._pipeline_step(
                    cst, drs_, dsvc_, *fresh, now0 + i, gen, meta=m_drain,
                )
                acc = acc.at[0].add(
                    od["code"].sum(dtype=jnp.int32) + od["n_miss"]
                )
            if with_maint:
                # The maintenance rider: ONE fused full-table pass per
                # step (pl.maintain_scan's traced body).  gen is
                # unchanged and `now` advances 1/step against hour-scale
                # timeouts, so the pass costs real work but reclaims
                # nothing — cost without semantic disturbance.
                cst, n_aged, n_stale = pl._maintain_scan(
                    cst, jnp.int32(now0 + i), jnp.int32(gen),
                    timeouts=meta.timeouts,
                )
                acc = acc.at[0].add(n_aged + n_stale)
            acc = acc.at[1].add(1)
            return (acc, cst, drs_, dsvc_, hcols, pcols)

        carry = (jnp.zeros(8, jnp.int32), st, drs, dsvc, hot, pool)
        return device_loop_time(
            body, carry, k_small=k_small, k_big=k_big, repeats=repeats
        )

    cumulative: dict[str, float] = {}
    phases: dict[str, float] = {}
    prev = 0.0
    for j, (name, mask) in enumerate(chain):
        t = timed(mask, with_drain=j > 0, with_maint=True)
        cumulative[name] = t
        phases[name] = t - prev  # unclamped (honesty property; see sync)
        prev = t
    # The background plane's own attributed cost: the rider-free fast
    # step diffed against the chain's rider-bearing entry 0.
    t_fast_bare = timed(0, with_drain=False, with_maint=False)
    maintenance_s = cumulative[chain[0][0]] - t_fast_bare
    total = cumulative[chain[-1][0]]
    return {
        "mode": "maintenance",
        "batch": B,
        "fresh_per_step": n_new,
        "drain_batch": n_new,
        "maintenance_s": maintenance_s,
        "maintenance_fraction": maintenance_s / total,
        "phases_s": phases,
        "cumulative_s": cumulative,
        "total_s": total,
        "pps": B / total,
        "phase_fractions": {k: v / total for k, v in phases.items()},
    }


def profile_churn_prune(
    meta: pl.PipelineMeta,
    state: pl.PipelineState,
    drs,
    dsvc,
    hot: tuple,
    pool: tuple,
    *,
    n_new: Optional[int] = None,
    now0: int = 1000,
    gen: int = 0,
    k_small: int = 2,
    k_big: int = 8,
    repeats: int = 2,
    chain: tuple = PRUNE_PHASE_CHAIN,
) -> dict:
    """Per-phase breakdown of the PRUNED churn regime (round 7): the
    async drain cadence (profile_churn_async's exact body) over a
    prune_budget > 0 meta, attributed on PRUNE_PHASE_CHAIN so the
    classify cost splits at the two-level kernel's seam —
    `prune_summary_gather` (aggregate rows + AND + short-circuit) vs
    `prune_candidate_gather` (K-superblock gather + first-match scan +
    fallback redispatches).  Same telescoped-sum honesty property; the
    ±15% gate applies via bench_profile.py --mode prune."""
    if meta.match.prune_budget <= 0:
        raise ValueError(
            "profile_churn_prune needs a prune_budget > 0 meta (the "
            "two-level kernel is compiled out at 0)")
    out = profile_churn_async(
        meta, state, drs, dsvc, hot, pool, n_new=n_new, now0=now0, gen=gen,
        k_small=k_small, k_big=k_big, repeats=repeats, chain=chain,
    )
    out["mode"] = "prune"
    out["prune_budget"] = meta.match.prune_budget
    return out


def profile_churn_fused(
    meta: pl.PipelineMeta,
    state: pl.PipelineState,
    drs,
    dsvc,
    hot: tuple,
    pool: tuple,
    *,
    n_new: Optional[int] = None,
    now0: int = 1000,
    gen: int = 0,
    k_small: int = 2,
    k_big: int = 8,
    repeats: int = 2,
    chain: tuple = FUSED_PHASE_CHAIN,
) -> dict:
    """Per-phase breakdown of the ONE-KERNEL churn regime (round 8): the
    async drain cadence (profile_churn_async's exact body) over a
    meta.onepass=True meta, attributed on FUSED_PHASE_CHAIN.  The
    `fused_onepass` entry is the whole in-VMEM pass (probe decode +
    candidate DMA + first-match + resolve + commit-row packing) — one
    number by design, since the fusion removed the interior stage
    boundaries the staged chains telescope.  Same telescoped-sum honesty
    property; the ±15% gate applies via bench_profile.py --mode fused."""
    if not meta.onepass:
        raise ValueError(
            "profile_churn_fused needs a one-pass meta (fused=True with "
            "prune_budget > 0)")
    out = profile_churn_async(
        meta, state, drs, dsvc, hot, pool, n_new=n_new, now0=now0, gen=gen,
        k_small=k_small, k_big=k_big, repeats=repeats, chain=chain,
    )
    out["mode"] = "fused"
    out["prune_budget"] = meta.match.prune_budget
    return out
