"""Batched forwarding stage: SpoofGuard -> (pipeline) -> L2/L3 forward -> Output.

Device twin of compiler/topology.py's scalar spec — the forwarding tables
the reference programs as OVS L2ForwardingCalc / L3Forwarding / SpoofGuard /
TrafficControl / L3DecTTL / Output entries
(/root/reference/pkg/agent/openflow/pipeline.go:114-195), evaluated here as
two searchsorted probes + row gathers per packet, fused into the same XLA
program as the policy pipeline (`pipeline_step_full`) so the whole
per-packet walk is one device dispatch.

Placement of SpoofGuard matters for state parity: in the reference it sits
BEFORE conntrack/policy tables (framework.go stage order), so a spoofed
packet must neither refresh nor commit conntrack state — realized by
threading its mask as the pipeline's `valid` lane mask, which excludes
those lanes from cache refresh, slow-path classification and commit (a
spoofed ALLOW that committed an eternal entry would est-bypass a later
deny for the legitimate tuple).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..compiler.compile import ACT_ALLOW, ACT_DROP
from ..compiler.topology import (
    ARP_OP_REQUEST,
    FIRST_POD_OFPORT,
    FWD_ARP_FLOOD,
    FWD_ARP_REPLY,
    FWD_DROP_MCAST,
    FWD_DROP_SPOOF,
    FWD_DROP_UNKNOWN,
    FWD_GATEWAY,
    FWD_LOCAL,
    FWD_MCAST,
    FWD_PUNT,
    FWD_TUNNEL,
    MCAST_HI_F,
    MCAST_LO_F,
    OFPORT_GATEWAY,
    OFPORT_REPLICATE,
    OFPORT_TUNNEL,
    PROTO_IGMP,
    TC_REDIRECT,
    ForwardingTables,
)
from . import pipeline as pl


class DeviceForwardingTables(NamedTuple):
    lp_ip_f: jax.Array
    lp_port: jax.Array
    lp_tc_in: jax.Array
    lp_tc_eg: jax.Array
    n_lp: jax.Array
    rn_lo_f: jax.Array
    rn_hi_f: jax.Array
    rn_peer_f: jax.Array
    n_rn: jax.Array
    local_range_f: jax.Array
    mc_ip_f: jax.Array
    n_mc: jax.Array
    arp_ip_f: jax.Array
    n_arp: jax.Array
    lp6_ipw: jax.Array
    lp6_port: jax.Array
    lp6_tc_in: jax.Array
    lp6_tc_eg: jax.Array
    n_lp6: jax.Array
    rn6_lo_w: jax.Array
    rn6_hi_w: jax.Array
    rn6_peer_w: jax.Array
    n_rn6: jax.Array
    local_range6_w: jax.Array
    nd_ipw: jax.Array
    n_nd: jax.Array


def fwd_to_device(ft: ForwardingTables) -> DeviceForwardingTables:
    return DeviceForwardingTables(*[jnp.asarray(c) for c in ft])


def _lex_le(a: jax.Array, b: jax.Array) -> jax.Array:
    """Lexicographic a <= b over the trailing 4-word axis (per-word
    sign-flipped i32, so signed compares give unsigned order — the same
    contract as ops/match._searchsorted6)."""
    lt = a < b
    eq = a == b
    return lt[..., 0] | (eq[..., 0] & (lt[..., 1] | (eq[..., 1] & (
        lt[..., 2] | (eq[..., 2] & (lt[..., 3] | eq[..., 3]))))))


def _lp_row(dft: DeviceForwardingTables, ip_f: jax.Array):
    """-> (row, known) local-pod probe by flipped IP."""
    cap = dft.lp_ip_f.shape[0]
    row = jnp.clip(jnp.searchsorted(dft.lp_ip_f, ip_f), 0, cap - 1)
    known = (row < dft.n_lp[0]) & (dft.lp_ip_f[row] == ip_f)
    return row, known


def _row_eq_wide(table: jax.Array, n: jax.Array, xw: jax.Array):
    """-> (row, known) exact 4-word row match (all-pairs — per-node v6
    tables are small; same shape rationale as ops/match._searchsorted6)."""
    cap = table.shape[0]
    eq = (table[None, :, :] == xw[:, None, :]).all(axis=2)  # (B, cap)
    eq = eq & (jnp.arange(cap, dtype=jnp.int32) < n[0])[None, :]
    known = eq.any(axis=1)
    row = jnp.argmax(eq, axis=1).astype(jnp.int32)
    return row, known


def spoof_lookup(dft: DeviceForwardingTables, src_f: jax.Array, in_port: jax.Array,
                 src_w=None, is6=None):
    """SpoofGuard (ref pipeline.go SpoofGuard): packets entering on a pod
    ofport must source an IP bound to that port.  Resolves the pod by
    source IP (the table is a per-family ip<->ofport bijection, enforced
    at compile); v6 lanes resolve in the lexicographic sub-table."""
    row, known = _lp_row(dft, src_f)
    pod_in = in_port >= FIRST_POD_OFPORT
    spoof4 = pod_in & (~known | (dft.lp_port[row] != in_port))
    if src_w is None:
        return spoof4
    row6, known6 = _row_eq_wide(dft.lp6_ipw, dft.n_lp6, src_w)
    spoof6 = pod_in & (~known6 | (dft.lp6_port[row6] != in_port))
    return jnp.where(is6 != 0, spoof6, spoof4)


def forwarding_lookup(
    dft: DeviceForwardingTables, dst_f: jax.Array, in_port: jax.Array
):
    """L2ForwardingCalc + L3Forwarding + L3DecTTL
    -> dict(kind, out_port, peer_f, dec_ttl, lp_row, is_local)."""
    row, is_local = _lp_row(dft, dst_f)
    rcap = dft.rn_lo_f.shape[0]
    r = jnp.clip(jnp.searchsorted(dft.rn_hi_f, dst_f), 0, rcap - 1)
    in_rn = (
        (r < dft.n_rn[0])
        & (dft.rn_lo_f[r] <= dst_f)
        & (dst_f <= dft.rn_hi_f[r])
    )
    in_local_cidr = (dft.local_range_f[0] <= dst_f) & (
        dst_f <= dft.local_range_f[1]
    )
    # Multicast (ref pipeline.go MulticastRouting/MulticastOutput): a
    # 224.0.0.0/4 dst resolves against the joined-group table; a hit
    # replicates (the consumer resolves the port list from mcast_idx), a
    # miss drops.  Precedence over the unicast branches — mcast addresses
    # can't collide with pod IPs or podCIDRs.
    is_mc = (dst_f >= MCAST_LO_F) & (dst_f <= MCAST_HI_F)
    mcap = dft.mc_ip_f.shape[0]
    mrow = jnp.clip(jnp.searchsorted(dft.mc_ip_f, dst_f), 0, mcap - 1)
    mc_hit = is_mc & (mrow < dft.n_mc[0]) & (dft.mc_ip_f[mrow] == dst_f)
    mcast_idx = jnp.where(mc_hit, mrow, -1).astype(jnp.int32)

    kind = jnp.where(
        is_mc,
        jnp.where(mc_hit, FWD_MCAST, FWD_DROP_MCAST),
        jnp.where(
            is_local,
            FWD_LOCAL,
            jnp.where(
                in_rn,
                FWD_TUNNEL,
                jnp.where(in_local_cidr, FWD_DROP_UNKNOWN, FWD_GATEWAY),
            ),
        ),
    ).astype(jnp.int32)
    out_port = jnp.where(
        is_mc,
        jnp.where(mc_hit, OFPORT_REPLICATE, -1),
        jnp.where(
            is_local,
            dft.lp_port[row],
            jnp.where(
                in_rn,
                OFPORT_TUNNEL,
                jnp.where(in_local_cidr, -1, OFPORT_GATEWAY),
            ),
        ),
    ).astype(jnp.int32)
    peer_f = jnp.where(in_rn & ~is_local & ~is_mc, dft.rn_peer_f[r], 0)
    # L3DecTTL: every routed leg — egress via tunnel/gateway, or local
    # delivery of traffic that ARRIVED routed (tunnel/gateway ingress).
    # Multicast replication does not decrement here (the reference's
    # multicast pipeline skips L3DecTTL).
    routed_in = (in_port == OFPORT_TUNNEL) | (in_port == OFPORT_GATEWAY)
    dec_ttl = jnp.where(
        is_mc,
        0,
        jnp.where(is_local, routed_in, in_rn | (kind == FWD_GATEWAY)),
    ).astype(jnp.int32)
    return {
        "kind": kind,
        "out_port": out_port,
        "peer_f": peer_f,
        "dec_ttl": dec_ttl,
        "lp_row": row,
        "is_local": is_local,
        "is_mc": is_mc,
        "mcast_idx": mcast_idx,
    }


def forwarding_lookup6(
    dft: DeviceForwardingTables, dst_w: jax.Array, in_port: jax.Array
):
    """The v6 leg of L2ForwardingCalc + L3Forwarding + L3DecTTL (ref
    route_linux.go v6 routes): exact local-pod match in the lexicographic
    table, inclusive [lo, hi] word-interval match for remote v6 podCIDRs,
    local-CIDR unknown-pod drop, gateway default.  No v6 multicast table
    (ff00::/8 replication is not modeled — those lanes take the gateway
    default).  -> same dict shape as forwarding_lookup, with peer_w
    ((B, 4)) instead of peer_f."""
    row, is_local = _row_eq_wide(dft.lp6_ipw, dft.n_lp6, dst_w)
    rcap = dft.rn6_lo_w.shape[0]
    ge_lo = _lex_le(dft.rn6_lo_w[None, :, :], dst_w[:, None, :])
    le_hi = _lex_le(dst_w[:, None, :], dft.rn6_hi_w[None, :, :])
    in_row = ge_lo & le_hi & (
        jnp.arange(rcap, dtype=jnp.int32) < dft.n_rn6[0])[None, :]
    in_rn = in_row.any(axis=1)
    r = jnp.argmax(in_row, axis=1).astype(jnp.int32)
    in_local_cidr = (
        _lex_le(dft.local_range6_w[0][None, :], dst_w)
        & _lex_le(dst_w, dft.local_range6_w[1][None, :])
    )
    kind = jnp.where(
        is_local,
        FWD_LOCAL,
        jnp.where(
            in_rn,
            FWD_TUNNEL,
            jnp.where(in_local_cidr, FWD_DROP_UNKNOWN, FWD_GATEWAY),
        ),
    ).astype(jnp.int32)
    out_port = jnp.where(
        is_local,
        dft.lp6_port[row],
        jnp.where(
            in_rn,
            OFPORT_TUNNEL,
            jnp.where(in_local_cidr, -1, OFPORT_GATEWAY),
        ),
    ).astype(jnp.int32)
    peer_w = jnp.where((in_rn & ~is_local)[:, None], dft.rn6_peer_w[r], 0)
    routed_in = (in_port == OFPORT_TUNNEL) | (in_port == OFPORT_GATEWAY)
    dec_ttl = jnp.where(
        is_local, routed_in, in_rn | (kind == FWD_GATEWAY)
    ).astype(jnp.int32)
    return {
        "kind": kind,
        "out_port": out_port,
        "peer_w": peer_w,
        "dec_ttl": dec_ttl,
        "lp_row": row,
        "is_local": is_local,
    }


def tc_lookup(
    dft: DeviceForwardingTables,
    src_f: jax.Array,
    dst_row: jax.Array,
    dst_is_local: jax.Array,
):
    """TrafficControl mark (ref trafficcontrol controller): dst pod's
    ingress word wins, else src pod's egress word.  -> packed word."""
    srow, sknown = _lp_row(dft, src_f)
    w_in = jnp.where(dst_is_local, dft.lp_tc_in[dst_row], 0)
    w_eg = jnp.where(sknown, dft.lp_tc_eg[srow], 0)
    return jnp.where(w_in != 0, w_in, w_eg)


def tc_lookup6(
    dft: DeviceForwardingTables,
    src_w: jax.Array,
    dst_row6: jax.Array,
    dst_is_local6: jax.Array,
):
    """tc_lookup's v6 leg over the lexicographic pod table."""
    srow, sknown = _row_eq_wide(dft.lp6_ipw, dft.n_lp6, src_w)
    w_in = jnp.where(dst_is_local6, dft.lp6_tc_in[dst_row6], 0)
    w_eg = jnp.where(sknown, dft.lp6_tc_eg[srow], 0)
    return jnp.where(w_in != 0, w_in, w_eg)


def _pipeline_step_full(
    state: pl.PipelineState,
    drs,
    dsvc,
    dft: DeviceForwardingTables,
    src_f: jax.Array,
    dst_f: jax.Array,
    proto: jax.Array,
    sport: jax.Array,
    dport: jax.Array,
    in_port: jax.Array,
    now: jax.Array,
    gen: jax.Array,
    flags: jax.Array = None,
    arp_op: jax.Array = None,
    lens: jax.Array = None,
    *,
    meta: pl.PipelineMeta,
    hit_combine=None,
    v6=None,
    valid=None,
    no_commit=None,
    prune_exclude=None,
):
    """Full per-packet walk: SpoofGuard/ARP -> (IGMP punt) -> policy/
    service pipeline -> forwarding -> Output; one jit, one dispatch.

    `valid`/`no_commit` are OPTIONAL external lane masks ANDed/ORed into
    the internally derived ones (spoof/ARP/IGMP exclusion, multicast +
    FIN/RST commit gating): the mesh engine threads its padding mask and
    the spill never-cache-foreign rule through them
    (parallel/meshpath.py).  None — every single-chip call site — traces
    the identical program as before they existed.

    arp_op lanes (ref pipeline.go ARPSpoofGuard/ARPResponder, :114-195):
    ARP is handled BEFORE the IP pipeline — sender-IP spoof gating via the
    same port binding, then the responder answers requests for addresses
    this node owns (gateway/local pods/remote node IPs) back out the
    ingress port; everything else floods (OFPP_NORMAL).  ARP lanes touch
    no conntrack/policy state.

    v6 (dual-stack pipelines): the (src6w_f, dst6w_f, is6) lane extension.
    v6 lanes spoof-guard / forward / TC through the lexicographic
    sub-tables; arp_op on a v6 lane models Neighbor Discovery (NS=1 answers
    from the nd table, the ARPResponder twin)."""
    if v6 is not None:
        src6w, dst6w, is6 = v6
        saddr_w = pl._wide_words(src_f, src6w, is6)
        daddr_w = pl._wide_words(dst_f, dst6w, is6)
        m6 = is6 != 0
        spoof = spoof_lookup(dft, src_f, in_port, src_w=saddr_w, is6=is6)
    else:
        is6 = None
        spoof = spoof_lookup(dft, src_f, in_port)
    # IGMP membership traffic is punted to the controller, never forwarded
    # (ref packetin.go PacketInCategoryIGMP; pkg/agent/multicast snooping):
    # excluded from the policy pipeline like spoofed lanes so reports
    # neither commit conntrack state nor count as policy verdicts.
    is_arp = (arp_op > 0) if arp_op is not None else None
    igmp = ~spoof & (proto == PROTO_IGMP)
    if is_arp is not None:
        igmp = igmp & ~is_arp
    # Multicast data traffic bypasses conntrack (multicast.go): classified
    # every step, never cached.  The 224/4 window is a v4 range — v6 lanes
    # carry a don't-care narrow dst and must not alias into it.
    is_mc = (dst_f >= MCAST_LO_F) & (dst_f <= MCAST_HI_F)
    if is6 is not None:
        is_mc = is_mc & ~m6
    no_commit_l = is_mc
    if flags is not None:
        # A FIN/RST-flagged TCP miss classifies but never ESTABLISHES a
        # connection (a closing segment is not a new flow); established
        # hits tear down inside the pipeline (pl._TEARDOWN_FLAGS path).
        no_commit_l = no_commit_l | (
            (proto == pl.PROTO_TCP) & ((flags & pl._TEARDOWN_FLAGS) != 0)
        )
    if no_commit is not None:
        no_commit_l = no_commit_l | no_commit
    valid_l = ~spoof & ~igmp
    if is_arp is not None:
        valid_l = valid_l & ~is_arp
    if valid is not None:
        valid_l = valid_l & valid
    state, out = pl._pipeline_step(
        state, drs, dsvc, src_f, dst_f, proto, sport, dport, now, gen,
        meta=meta, hit_combine=hit_combine, valid=valid_l,
        no_commit=no_commit_l, flags=flags, v6=v6, lens=lens,
        prune_exclude=prune_exclude,
    )
    code = jnp.where(spoof, ACT_DROP, out["code"]).astype(jnp.int32)
    # Forward toward the packet's effective destination: the DNAT-resolved
    # endpoint — except reply-direction hits, whose dnat fields carry the
    # SOURCE un-rewrite; a reply forwards to its literal dst (the client).
    eff_dst = jnp.where(out["reply"] == 1, dst_f, out["dnat_ip_f"])
    fwd = forwarding_lookup(dft, eff_dst, in_port)
    peer_w = None
    if is6 is not None:
        # v6 lanes forward by their wide effective destination through the
        # lexicographic tables; merge per family.
        eff_dst_w = jnp.where((out["reply"] == 1)[:, None], daddr_w,
                              out["dnat_w_f"])
        fwd6 = forwarding_lookup6(dft, eff_dst_w, in_port)
        fwd = {
            "kind": jnp.where(m6, fwd6["kind"], fwd["kind"]),
            "out_port": jnp.where(m6, fwd6["out_port"], fwd["out_port"]),
            "peer_f": jnp.where(m6, 0, fwd["peer_f"]),
            "dec_ttl": jnp.where(m6, fwd6["dec_ttl"], fwd["dec_ttl"]),
            "lp_row": fwd["lp_row"],
            "is_local": jnp.where(m6, fwd6["is_local"], fwd["is_local"]),
            "is_mc": fwd["is_mc"] & ~m6,
            "mcast_idx": jnp.where(m6, -1, fwd["mcast_idx"]),
            "lp_row6": fwd6["lp_row"],
            "is_local6": fwd6["is_local"] & m6,
        }
        # Wide peer view: v4 tunnel peers in mapped form, v6 peers native.
        peer_w = jnp.where(
            m6[:, None], fwd6["peer_w"],
            pl._wide_words(fwd["peer_f"], None, None),
        )
    kind = jnp.where(
        spoof, FWD_DROP_SPOOF, jnp.where(igmp, FWD_PUNT, fwd["kind"])
    ).astype(jnp.int32)
    if is_arp is not None:
        # ARPResponder: answered requests reply out the ingress port;
        # unanswered (or reply-opcode) ARP floods.  ARPSpoofGuard already
        # resolved in `spoof` (sender IP vs port binding).  v6 lanes model
        # Neighbor Discovery: NS (op 1) answers from the nd table — the
        # NDP twin of the responder (route_linux.go v6 neighbors).
        acap = dft.arp_ip_f.shape[0]
        arow = jnp.clip(jnp.searchsorted(dft.arp_ip_f, dst_f), 0, acap - 1)
        answer = (
            is_arp & ~spoof
            & (arow < dft.n_arp[0]) & (dft.arp_ip_f[arow] == dst_f)
            & (arp_op == ARP_OP_REQUEST)
        )
        if is6 is not None:
            _ndrow, nd_known = _row_eq_wide(dft.nd_ipw, dft.n_nd, daddr_w)
            answer6 = (
                is_arp & ~spoof & nd_known & (arp_op == ARP_OP_REQUEST)
            )
            answer = jnp.where(m6, answer6, answer)
        kind = jnp.where(
            is_arp & ~spoof,
            jnp.where(answer, FWD_ARP_REPLY, FWD_ARP_FLOOD),
            kind,
        ).astype(jnp.int32)
    deliverable = (code == ACT_ALLOW) & (
        (kind == FWD_LOCAL) | (kind == FWD_TUNNEL) | (kind == FWD_GATEWAY)
        | (kind == FWD_MCAST)
    )
    uni_deliverable = deliverable & (kind != FWD_MCAST)
    tc_base = tc_lookup(dft, src_f, fwd["lp_row"], fwd["is_local"])
    if is6 is not None:
        tc_base = jnp.where(
            m6,
            tc_lookup6(dft, saddr_w, fwd["lp_row6"], fwd["is_local6"]),
            tc_base,
        )
    tc_w = jnp.where(uni_deliverable, tc_base, 0)
    tc_act = tc_w & 3
    tc_port = tc_w >> 2
    out_port = jnp.where(deliverable, fwd["out_port"], -1)
    if is_arp is not None:
        out_port = jnp.where(kind == FWD_ARP_REPLY, in_port, out_port)
    # Redirect replaces the output port (ref TrafficControl redirect action:
    # the packet leaves via the target device instead of its computed port).
    out_port = jnp.where(tc_act == TC_REDIRECT, tc_port, out_port)
    # L7 redirect mark (ref network_policy.go:2213 l7NPTrafficControlFlows
    # — the reg0 L7 bit + VLAN handoff to the L7 engine): set when the
    # DECIDING allow rule carries L7 protocols.  Resolved by attribution
    # index against the CURRENT rule table — cached hits inherit the
    # ct_label caveat documented on stats (datapath/tpuflow.py).
    def l7_of(dd, idx):
        n = dd.l7.shape[0]
        safe = jnp.clip(idx, 0, n - 1)
        return jnp.where((idx >= 0) & (idx < n), dd.l7[safe], 0)

    l7 = jnp.where(
        code == ACT_ALLOW,
        l7_of(drs.ingress, out["ingress_rule"])
        | l7_of(drs.egress, out["egress_rule"]),
        0,
    ).astype(jnp.int32)

    out.update(
        code=code,
        reject_kind=pl.reject_kind_of(code, proto),
        spoofed=spoof.astype(jnp.int32),
        l7_redirect=l7,
        punt=igmp.astype(jnp.int32),
        fwd_kind=kind,
        out_port=out_port.astype(jnp.int32),
        peer_f=jnp.where(uni_deliverable, fwd["peer_f"], 0),
        dec_ttl=jnp.where(uni_deliverable, fwd["dec_ttl"], 0),
        tc_act=tc_act,
        tc_port=tc_port,
        mcast_idx=jnp.where(deliverable, fwd["mcast_idx"], -1),
    )
    if peer_w is not None:
        # Wide tunnel-peer view (v6 podCIDR rows may tunnel over either
        # family); zeroed like peer_f for non-deliverable lanes.
        out["peer_w"] = jnp.where(uni_deliverable[:, None], peer_w, 0)
    return state, out


pipeline_step_full = jax.jit(
    _pipeline_step_full, static_argnames=("meta", "hit_combine")
)
