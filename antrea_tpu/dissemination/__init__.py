"""Dissemination plane (ref: pkg/apiserver RAM store + watch fan-out)."""

from .store import RamStore

__all__ = ["RamStore"]
