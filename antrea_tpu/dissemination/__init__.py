"""Dissemination plane (ref: pkg/apiserver RAM store + watch fan-out).

Failure handling lives beside the happy path: bounded watcher queues in
store.py (overflow -> resync), reconnect/re-list in netwire.py, typed
agent-death errors in transport.py, and the deterministic chaos harness
in faults.py that tests/test_chaos_dissemination.py drives."""

from .faults import FaultPlan
from .store import RamStore

__all__ = ["FaultPlan", "RamStore"]
