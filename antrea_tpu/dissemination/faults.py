"""Fault injection for the dissemination plane: deterministic chaos.

The reference's control plane is hardened by real-world failure (agents
lose the apiserver watch and re-list; reconcilers requeue failed installs).
This module is the harness that proves the SAME properties of this build
without waiting for real faults: a FaultPlan scripts WHEN faults fire, and
thin wrappers (socket / pipe / datapath) decide WHAT a fault does —
connection resets, partial writes, added latency, install failures.  Agent
crashes are injected by the chaos tests themselves (closing sockets /
killing subprocesses); the plan gives them the same deterministic schedule.

Everything is deterministic given the plan's seed: chaos tests are
reproducible, not flaky-by-design (tests/test_chaos_dissemination.py).
"""

from __future__ import annotations

import random
import socket
import time
from dataclasses import dataclass, field
from typing import Optional


class FaultClock:
    """Deterministic steppable clock for the chaos tier.

    Passed as the maintenance scheduler's clock
    (`TpuflowDatapath(..., maint_clock=FaultClock())` /
    `MaintenanceScheduler(clock=...)`), it becomes the ONE notion of
    `now` every consolidated background plane consults — FQDN TTL
    expiry, the degraded-recompile backoff, aging cadence — so a chaos
    test advances time explicitly instead of sleeping, and every
    time-driven plane behavior replays deterministically."""

    def __init__(self, start: int = 0):
        self.now = int(start)

    def advance(self, dt: int = 1) -> int:
        if dt < 0:
            raise ValueError(f"FaultClock is monotonic; got dt={dt}")
        self.now += int(dt)
        return self.now

    def __call__(self) -> int:
        return self.now


class InjectedInstallError(RuntimeError):
    """Raised by FlakyDatapath.install_bundle when the plan fires — a
    stand-in for a real datapath rejecting/timing out a rule install.
    Fires BEFORE the datapath's commit plane is entered, so it models the
    TRANSIENT fault the agent's retry/backoff loop absorbs."""


class InjectedCompileError(RuntimeError):
    """Raised INSIDE the commit plane's compile stage (datapath/commit.py)
    when the plan fires at site f"{name}.compile" — a stand-in for the
    compiler/tensor build rejecting a bundle.  Unlike InjectedInstallError
    this reaches the plane, so it drives the rollback-to-LKG + degraded
    path, not the transient retry path.  (Canary-stage faults at
    f"{name}.canary" surface as synthetic verdict mismatches instead: a
    deterministic miscompile injection.)"""


@dataclass
class _Rule:
    kind: str            # "reset" | "partial" | "delay" | "fail"
    every: int = 0       # fire on every Nth hit of the site (0 = off)
    # Fire once the site's hit count exceeds this; None = off.  Distinct
    # from 0 so after(site, 0) means "from the first hit" — the
    # plan.after(site, plan.hits(site)) idiom on a never-consulted site.
    after: Optional[int] = None
    times: int = -1      # remaining firings (-1 = unlimited)
    prob: float = 0.0    # independent per-hit probability (0 = off)
    delay_s: float = 0.0  # for kind="delay"


@dataclass
class _Injection:
    site: str
    kind: str
    hit: int


class FaultPlan:
    """Scripted fault schedule keyed by named sites.

    A *site* is a string a wrapper consults on every operation, e.g.
    "n1.send", "n1.recv", "n1.install".  Rules attach to sites:

        plan.after("n1.send", 3, "reset")       # 4th send onward: reset once
        plan.every("n1.install", 2, "fail")     # every 2nd install raises
        plan.prob("n2.recv", 0.1, "reset")      # 10% of recvs reset

    fire(site) returns the fault kind to inject (or None) and logs every
    injection in .injected so tests can assert the chaos actually
    happened — a chaos run that injected nothing proves nothing.
    """

    def __init__(self, seed: int = 0):
        self.rng = random.Random(seed)
        self._rules: dict[str, list[_Rule]] = {}
        self._hits: dict[str, int] = {}
        self.injected: list[_Injection] = []
        # Flight recorders (observability/flightrec.py) every injection
        # journals itself into — the chaos tier's cause-beside-effect
        # guarantee: a post-mortem reads "fault-injected" in the same
        # sequence-ordered journal as the rollbacks/repairs it caused.
        self._recorders: list = []

    def bind_recorder(self, recorder) -> "FaultPlan":
        """Attach a FlightRecorder (None is a no-op; duplicates are
        collapsed — a plan arming several planes of ONE datapath must
        journal each injection once)."""
        if recorder is not None and all(r is not recorder
                                        for r in self._recorders):
            self._recorders.append(recorder)
        return self

    def _add(self, site: str, rule: _Rule) -> "FaultPlan":
        self._rules.setdefault(site, []).append(rule)
        return self

    def every(self, site: str, n: int, kind: str = "reset",
              times: int = -1, delay_s: float = 0.0) -> "FaultPlan":
        return self._add(site, _Rule(kind=kind, every=n, times=times,
                                     delay_s=delay_s))

    def after(self, site: str, n: int, kind: str = "reset",
              times: int = 1, delay_s: float = 0.0) -> "FaultPlan":
        return self._add(site, _Rule(kind=kind, after=n, times=times,
                                     delay_s=delay_s))

    def prob(self, site: str, p: float, kind: str = "reset",
             times: int = -1, delay_s: float = 0.0) -> "FaultPlan":
        return self._add(site, _Rule(kind=kind, prob=p, times=times,
                                     delay_s=delay_s))

    def fire(self, site: str) -> Optional[_Rule]:
        """Register one hit of `site`; -> the rule to inject, or None."""
        hit = self._hits.get(site, 0) + 1
        self._hits[site] = hit
        for rule in self._rules.get(site, ()):
            if rule.times == 0:
                continue
            triggered = (
                (rule.every and hit % rule.every == 0)
                or (rule.after is not None and hit > rule.after)
                or (rule.prob and self.rng.random() < rule.prob)
            )
            if triggered:
                if rule.times > 0:
                    rule.times -= 1
                self.injected.append(_Injection(site, rule.kind, hit))
                for rec in self._recorders:
                    rec.emit(kind="fault-injected", site=site,
                             fault=rule.kind, hit=hit)
                return rule
        return None

    def hits(self, site: str) -> int:
        """How many times `site` has been consulted so far — lets a test
        schedule a fault on the NEXT hit: plan.after(site, plan.hits(site),
        kind, times=1)."""
        return self._hits.get(site, 0)

    def quiesce(self) -> None:
        """Drop every rule: the recovery phase of a chaos test asserts
        convergence in calm weather, and an injection firing during the
        parity check would measure the fault, not the healing."""
        self._rules.clear()

    def count(self, kind: Optional[str] = None) -> int:
        if kind is None:
            return len(self.injected)
        return sum(1 for i in self.injected if i.kind == kind)


class FaultySocket:
    """Socket wrapper injecting faults on send/recv per the plan.

    Sites consulted: f"{name}.send" and f"{name}.recv".
      reset   -> close the real socket, raise ConnectionResetError
      partial -> transmit a PREFIX of the payload, then reset (the peer's
                 framing layer must hold the torn line and discard it with
                 the connection, never parse it)
      delay   -> sleep rule.delay_s, then proceed
    Everything else delegates to the wrapped socket.
    """

    def __init__(self, sock, plan: FaultPlan, name: str):
        self._sock = sock
        self._plan = plan
        self._name = name

    def _inject(self, op: str, payload: Optional[bytes] = None):
        rule = self._plan.fire(f"{self._name}.{op}")
        if rule is None:
            return None
        if rule.kind == "delay":
            time.sleep(rule.delay_s)
            return None
        if rule.kind == "partial" and payload:
            try:
                self._sock.sendall(payload[: max(1, len(payload) // 2)])
            except OSError:
                pass
        try:
            self._sock.close()
        except OSError:
            pass
        raise ConnectionResetError(
            f"injected {rule.kind} on {self._name}.{op}")

    def sendall(self, data: bytes) -> None:
        self._inject("send", data)
        self._sock.sendall(data)

    def send(self, data: bytes) -> int:
        self._inject("send", data)
        return self._sock.send(data)

    def recv(self, n: int) -> bytes:
        self._inject("recv")
        return self._sock.recv(n)

    def fileno(self) -> int:
        # select() needs the REAL fd even after an injected close (it
        # returns -1 then; callers treat that as dead).
        return self._sock.fileno()

    def __getattr__(self, item):
        return getattr(self._sock, item)


class FaultyPipe:
    """File-like write wrapper for the pipe transport (site f"{name}.write"):
    reset -> close the pipe and raise BrokenPipeError mid-stream; partial
    -> write a prefix first.  Wraps e.g. SubprocessAgent._proc.stdin."""

    def __init__(self, pipe, plan: FaultPlan, name: str):
        self._pipe = pipe
        self._plan = plan
        self._name = name

    def write(self, data: bytes) -> int:
        rule = self._plan.fire(f"{self._name}.write")
        if rule is not None:
            if rule.kind == "delay":
                time.sleep(rule.delay_s)
            else:
                if rule.kind == "partial" and data:
                    try:
                        self._pipe.write(data[: max(1, len(data) // 2)])
                        self._pipe.flush()
                    except OSError:
                        pass
                try:
                    self._pipe.close()
                except OSError:
                    pass
                raise BrokenPipeError(
                    f"injected {rule.kind} on {self._name}.write")
        return self._pipe.write(data)

    def __getattr__(self, item):
        return getattr(self._pipe, item)


class FlakyDatapath:
    """Datapath wrapper whose install_bundle raises per the plan (site
    f"{name}.install") — drives the agent's install-retry path.  All other
    datapath behavior (step/trace/stats/...) passes through, so verdict
    parity checks run against the real datapath underneath.

    Wrapping a transactional datapath (datapath/commit.py) also arms the
    commit plane's OWN fault sites from the same plan — f"{name}.compile"
    (raises InjectedCompileError inside the compile stage) and
    f"{name}.canary" (forces a canary mismatch) — so one plan scripts both
    the transient-install faults outside the plane and the
    rollback-forcing faults inside it.

    An auditable datapath (datapath/audit.py) additionally gets its
    revalidator sites armed: f"{name}.cache" REALLY corrupts live state
    before an audit scan runs (kind "partial" flips one rule-side tensor
    word — the canary-blind service-table class; any other kind flips a
    sampled cached verdict bit), and f"{name}.audit" forces a
    false-positive divergence finding — so the chaos tier can prove
    corruption -> detection -> repair -> reconvergence deterministically.

    A mesh datapath with the replica-loss failover plane enabled
    (parallel/failover.py) gets its health-probe sites armed too:
    f"{name}.replica_dead" makes the targeted data replica's probe row
    read as diverged (the rule KIND names the replica — "r1"; anything
    else targets replica 0), and f"{name}.replica_wedge" rides the
    rule's delay_s onto that replica's measured probe latency so it
    blows the probe deadline — so replica death is deterministic in
    chaos tests, never a real device kill."""

    def __init__(self, inner, plan: FaultPlan, name: str):
        self._inner = inner
        self._plan = plan
        self._name = name
        for arm_name in ("arm_commit_faults", "arm_audit_faults",
                         "arm_failover_faults"):
            arm = getattr(inner, arm_name, None)
            if arm is not None:
                arm(plan, name)
        # Chaos post-mortems: injections at the wrapper's OWN site
        # ({name}.install) journal into the inner datapath's recorder
        # too, not only the in-plane compile/canary/cache/audit sites.
        plan.bind_recorder(getattr(inner, "_flightrec", None))

    def install_bundle(self, *a, **kw):
        rule = self._plan.fire(f"{self._name}.install")
        if rule is not None and rule.kind != "delay":
            raise InjectedInstallError(
                f"injected install failure on {self._name}")
        return self._inner.install_bundle(*a, **kw)

    def __getattr__(self, item):
        return getattr(self._inner, item)
