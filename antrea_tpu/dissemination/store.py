"""Watchable object store with span-based per-node filtering.

The analog of the reference's in-memory aggregated-API storage:
/root/reference/pkg/apiserver/storage/ram/store.go:46-80 (indexer + watchers
+ event fan-out, no etcd) with per-watcher filtering via SelectFunc
(storage/interfaces.go:60) — the mechanism behind "a Node receives an object
iff it needs it" (docs/design/architecture.md:57-60).

Two consumer modes:
  * synchronous callbacks (watch with cb) — deterministic in-process tests;
  * QUEUED watchers (watch_queue) — events buffer per watcher and drain on
    the consumer's schedule, so a slow consumer never blocks the producer
    (the reference's per-watcher event channel, store.go:230).  The
    dissemination transport pumps a queued watcher over a process boundary.
Watchers are handles with stop() — unsubscribing removes them (the
round-2 verdict noted the watcher list grew forever).

Key behavior shared with the reference: a watcher is told about an object
when the object's span GROWS to include its node (synthesized ADDED), and
gets a DELETED when the span shrinks away from it — the span diff IS the
subscription filter.

Robustness: a queued watcher may carry a depth cap (max_pending).  When a
consumer falls so far behind that its buffer hits the cap, the buffer is
DROPPED and the watcher flips to needs_resync — the reference's "watch
channel full -> client must re-list" semantics (store.go:230 drops the
watcher; here the transport converts the flag into a full replay via
RamStore.resync, so a slow agent costs one snapshot, never unbounded
memory)."""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, replace
from typing import Callable, Optional

from ..controller.networkpolicy import WatchEvent


@dataclass
class _Stored:
    obj: object
    span: set


class Watcher:
    """One node subscription.  cb-mode delivers inline; queue-mode buffers
    until drain()/pop() — never blocking the store's apply()."""

    def __init__(self, node: str, cb: Optional[Callable[[WatchEvent], None]],
                 max_pending: Optional[int] = None):
        self.node = node
        self._cb = cb
        self._queue: deque[WatchEvent] = deque()
        self._known: set = set()
        self._stopped = False
        # Bounded-queue mode: cap the buffer; overflow invalidates the
        # stream (needs_resync) instead of growing without bound.
        self.max_pending = max_pending
        self.needs_resync = False
        self.overflows = 0

    def _deliver(self, ev: WatchEvent) -> None:
        if self._cb is not None:
            self._cb(ev)
            return
        if self.needs_resync:
            # Stream already invalidated: every buffered/new event is
            # superseded by the coming full resync — don't re-grow.
            return
        if self.max_pending is not None and len(self._queue) >= self.max_pending:
            self._queue.clear()
            self._known.clear()
            self.needs_resync = True
            self.overflows += 1
            return
        self._queue.append(ev)

    def pop(self) -> Optional[WatchEvent]:
        return self._queue.popleft() if self._queue else None

    def drain(self) -> list[WatchEvent]:
        out = list(self._queue)
        self._queue.clear()
        return out

    def pending(self) -> int:
        return len(self._queue)

    def stop(self) -> None:
        """Unsubscribe: the store drops this watcher on its next pass."""
        self._stopped = True
        self._queue.clear()


class RamStore:
    """One store instance per object type family; here one instance carries
    all three types keyed by (obj_type, name) since WatchEvent is uniform."""

    def __init__(self):
        self._objs: dict[tuple[str, str], _Stored] = {}
        self._watchers: list[Watcher] = []

    # -- producer side -------------------------------------------------------

    def apply(self, ev: WatchEvent) -> None:
        # Controller-commit stamp (dissemination-latency origin): the
        # moment the event enters the plane.  monotonic so it survives
        # wall-clock jumps and stays comparable across same-host processes
        # (the pipe/netwire transports); pre-stamped events keep theirs.
        if not ev.ts:
            ev = replace(ev, ts=time.monotonic())
        key = (ev.obj_type, ev.name)
        live = [w for w in self._watchers if not w._stopped]
        self._watchers = live
        if ev.kind == "DELETED":
            self._objs.pop(key, None)
            for w in live:
                if key in w._known:
                    w._known.discard(key)
                    w._deliver(WatchEvent(
                        kind="DELETED", obj_type=ev.obj_type, name=ev.name,
                        ts=ev.ts,
                    ))
            return

        self._objs[key] = _Stored(obj=ev.obj, span=set(ev.span))
        for w in live:
            relevant = w.node in ev.span
            if relevant and key not in w._known:
                w._known.add(key)
                w._deliver(replace(ev, kind="ADDED"))
            elif relevant:
                w._deliver(ev)
            elif key in w._known:
                # Span shrank away from this node: retract the object.
                w._known.discard(key)
                w._deliver(WatchEvent(
                    kind="DELETED", obj_type=ev.obj_type, name=ev.name,
                    ts=ev.ts,
                ))

    # -- consumer side -------------------------------------------------------

    def _replay(self, w: Watcher) -> None:
        for (obj_type, name), st in sorted(self._objs.items()):
            if w.node in st.span:
                w._known.add((obj_type, name))
                w._deliver(WatchEvent(
                    kind="ADDED", obj_type=obj_type, name=name,
                    obj=st.obj, span=set(st.span),
                ))

    def watch(self, node: str, cb: Callable[[WatchEvent], None]) -> Watcher:
        """Subscribe a node with a synchronous callback: replays current
        relevant objects as ADDED, then streams filtered events (the
        reference's watch bookmark semantics).  Returns the Watcher handle;
        stop() unsubscribes."""
        w = Watcher(node, cb)
        self._replay(w)
        self._watchers.append(w)
        return w

    def watch_queue(self, node: str, max_pending: Optional[int] = None,
                    *, replay: bool = True) -> Watcher:
        """Subscribe a node in queued mode: events (including the initial
        replay) buffer in the returned Watcher until drained — the
        per-watcher channel of the reference's RAM store.  max_pending
        bounds the buffer (overflow -> needs_resync, see resync()).

        replay=False skips the initial snapshot buffering — for consumers
        that serve a full resync() on first pump anyway (the netwire
        server's fresh connections): replaying into a bounded queue there
        is wasted work and, when the snapshot exceeds the cap, counts a
        slow-consumer overflow that never happened."""
        w = Watcher(node, None, max_pending=max_pending)
        if replay:
            self._replay(w)
        self._watchers.append(w)
        return w

    def resync(self, w: Watcher) -> list[WatchEvent]:
        """Full re-list for a queued watcher whose stream was invalidated
        (overflow or reconnect): rebuilds the watcher's known-set from the
        CURRENT store state and returns the snapshot as ADDED events —
        bypassing the bounded queue, so a resync always completes even when
        the snapshot exceeds max_pending.  The transport brackets these
        events with resync markers so the consumer can retract anything it
        holds that is absent from the snapshot (re-list semantics)."""
        w._queue.clear()
        w._known.clear()
        w.needs_resync = False
        out: list[WatchEvent] = []
        for (obj_type, name), st in sorted(self._objs.items()):
            if w.node in st.span:
                w._known.add((obj_type, name))
                out.append(WatchEvent(
                    kind="ADDED", obj_type=obj_type, name=name,
                    obj=st.obj, span=set(st.span),
                ))
        return out

    @property
    def n_watchers(self) -> int:
        return sum(1 for w in self._watchers if not w._stopped)
