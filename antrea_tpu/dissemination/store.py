"""Watchable object store with span-based per-node filtering.

The analog of the reference's in-memory aggregated-API storage:
/root/reference/pkg/apiserver/storage/ram/store.go:46-80 (indexer + watchers
+ event fan-out, no etcd) with per-watcher filtering via SelectFunc
(storage/interfaces.go:60) — the mechanism behind "a Node receives an object
iff it needs it" (docs/design/architecture.md:57-60).

Differences by design: events are delivered synchronously to subscriber
callbacks (the network/serialization boundary arrives with the gRPC service
in the C++ runtime layer); the reference's resourceVersion bookkeeping
reduces to Python object identity because there is one producer.

Key behavior shared with the reference: a watcher is told about an object
when the object's span GROWS to include its node (synthesized ADDED), and
gets a DELETED when the span shrinks away from it — the span diff IS the
subscription filter.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Callable, Optional

from ..controller.networkpolicy import WatchEvent


@dataclass
class _Stored:
    obj: object
    span: set


class RamStore:
    """One store instance per object type family; here one instance carries
    all three types keyed by (obj_type, name) since WatchEvent is uniform."""

    def __init__(self):
        self._objs: dict[tuple[str, str], _Stored] = {}
        self._watchers: list[tuple[str, Callable[[WatchEvent], None], set]] = []

    # -- producer side -------------------------------------------------------

    def apply(self, ev: WatchEvent) -> None:
        key = (ev.obj_type, ev.name)
        if ev.kind == "DELETED":
            self._objs.pop(key, None)
            for node, cb, known in self._watchers:
                if key in known:
                    known.discard(key)
                    cb(WatchEvent(kind="DELETED", obj_type=ev.obj_type, name=ev.name))
            return

        self._objs[key] = _Stored(obj=ev.obj, span=set(ev.span))
        for node, cb, known in self._watchers:
            relevant = node in ev.span
            if relevant and key not in known:
                known.add(key)
                cb(replace(ev, kind="ADDED"))
            elif relevant:
                cb(ev)
            elif key in known:
                # Span shrank away from this node: retract the object.
                known.discard(key)
                cb(WatchEvent(kind="DELETED", obj_type=ev.obj_type, name=ev.name))

    # -- consumer side -------------------------------------------------------

    def watch(self, node: str, cb: Callable[[WatchEvent], None]) -> None:
        """Subscribe a node: replays current relevant objects as ADDED, then
        streams filtered events (the reference's watch bookmark semantics)."""
        known: set = set()
        for (obj_type, name), st in sorted(self._objs.items()):
            if node in st.span:
                known.add((obj_type, name))
                cb(WatchEvent(
                    kind="ADDED", obj_type=obj_type, name=name,
                    obj=st.obj, span=set(st.span),
                ))
        self._watchers.append((node, cb, known))
