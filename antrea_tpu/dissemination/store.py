"""Watchable object store with span-based per-node filtering.

The analog of the reference's in-memory aggregated-API storage:
/root/reference/pkg/apiserver/storage/ram/store.go:46-80 (indexer + watchers
+ event fan-out, no etcd) with per-watcher filtering via SelectFunc
(storage/interfaces.go:60) — the mechanism behind "a Node receives an object
iff it needs it" (docs/design/architecture.md:57-60).

Two consumer modes:
  * synchronous callbacks (watch with cb) — deterministic in-process tests;
  * QUEUED watchers (watch_queue) — events buffer per watcher and drain on
    the consumer's schedule, so a slow consumer never blocks the producer
    (the reference's per-watcher event channel, store.go:230).  The
    dissemination transport pumps a queued watcher over a process boundary.
Watchers are handles with stop() — unsubscribing removes them (the
round-2 verdict noted the watcher list grew forever).

Key behavior shared with the reference: a watcher is told about an object
when the object's span GROWS to include its node (synthesized ADDED), and
gets a DELETED when the span shrinks away from it — the span diff IS the
subscription filter.

Robustness: a queued watcher may carry a depth cap (max_pending).  The
queue COALESCES latest-wins per (obj_type, name) — a storm rewriting the
same object 500× occupies one slot, in its original arrival position —
so only churn across DISTINCT keys can fill it.  When a consumer falls
so far behind that distinct-key churn hits the cap anyway, the buffer is
DROPPED and the watcher flips to needs_resync — the reference's "watch
channel full -> client must re-list" semantics (store.go:230 drops the
watcher; here the transport converts the flag into a re-list via
RamStore.resync, so a slow agent costs one snapshot, never unbounded
memory).  resync() returns a resumable ResyncCursor rather than a
materialized list, so the transport can ship the snapshot in bounded
chunks interleaved with other agents' live traffic."""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, replace
from typing import Callable, Optional

from ..controller.networkpolicy import WatchEvent
from ..observability.flightrec import emit_into

# bounded-buffer analysis-pass contract (analysis/bounded_buffer.py): every
# buffer-shaped attribute in this package declares its cap here.
BUFFER_CAPS = {
    "Watcher._queue": "holds at most max_pending distinct keys; overflow "
                      "drops the buffer and flips needs_resync",
    "Watcher._latest": "one entry per key queued in Watcher._queue — the "
                       "same max_pending cap",
    "ResyncCursor._keys": "span-filtered key snapshot taken at cursor "
                          "birth (<= store size), strictly drained by "
                          "take(), never refilled",
}


@dataclass
class _Stored:
    obj: object
    span: set


class Watcher:
    """One node subscription.  cb-mode delivers inline; queue-mode buffers
    until drain()/pop() — never blocking the store's apply().

    The queue is KEY-COALESCING: `_queue` keeps arrival order of distinct
    (obj_type, name) keys and `_latest` the newest event per key.  A
    re-delivery for a queued key replaces the buffered event in place
    (latest-wins, order preserved) — safe because events are full-object
    replacements, not diffs: ADDED then UPDATED collapses to one upsert,
    ADDED then DELETED to a DELETE the consumer's tolerant pop absorbs."""

    def __init__(self, node: str, cb: Optional[Callable[[WatchEvent], None]],
                 max_pending: Optional[int] = None):
        self.node = node
        self._cb = cb
        self._queue: deque[tuple[str, str]] = deque()
        self._latest: dict[tuple[str, str], WatchEvent] = {}
        self._known: set = set()
        self._stopped = False
        # Bounded-queue mode: cap the buffer; overflow invalidates the
        # stream (needs_resync) instead of growing without bound.
        self.max_pending = max_pending
        self.needs_resync = False
        self.overflows = 0
        self.coalesced = 0
        # Optional FlightRecorder wired in by the transport that owns this
        # watcher (emit_into no-ops while unset).
        self._flightrec = None

    def _emit(self, kind: str, **fields) -> None:
        emit_into(self, kind, **fields)

    def _deliver(self, ev: WatchEvent) -> None:
        if self._cb is not None:
            self._cb(ev)
            return
        if self.needs_resync:
            # Stream already invalidated: every buffered/new event is
            # superseded by the coming full resync — don't re-grow.
            return
        key = (ev.obj_type, ev.name)
        if key in self._latest:
            # Latest-wins coalescing: the key keeps its queue slot (and
            # ordering), only the payload is replaced.
            self._latest[key] = ev
            self.coalesced += 1
            return
        if self.max_pending is not None and len(self._queue) >= self.max_pending:
            dropped = len(self._queue)
            self._clear_queue()
            self._known.clear()
            self.needs_resync = True
            self.overflows += 1
            self._emit("watcher-overflow", node=self.node, dropped=dropped,
                       overflows=self.overflows)
            return
        self._queue.append(key)
        self._latest[key] = ev

    def _clear_queue(self) -> None:
        self._queue.clear()
        self._latest.clear()

    def pop(self) -> Optional[WatchEvent]:
        if not self._queue:
            return None
        return self._latest.pop(self._queue.popleft())

    def drain(self, limit: Optional[int] = None) -> list[WatchEvent]:
        """Dequeue buffered events in arrival order; `limit` bounds the
        batch (None = everything) so the transport can budget per-watcher
        send work in one pump round."""
        if limit is None or limit >= len(self._queue):
            out = [self._latest[k] for k in self._queue]
            self._clear_queue()
            return out
        return [self._latest.pop(self._queue.popleft())
                for _ in range(max(0, limit))]

    def pending(self) -> int:
        return len(self._queue)

    def stop(self) -> None:
        """Unsubscribe: the store drops this watcher on its next pass."""
        self._stopped = True
        self._clear_queue()


class RamStore:
    """One store instance per object type family; here one instance carries
    all three types keyed by (obj_type, name) since WatchEvent is uniform."""

    def __init__(self):
        self._objs: dict[tuple[str, str], _Stored] = {}
        self._watchers: list[Watcher] = []

    # -- producer side -------------------------------------------------------

    def apply(self, ev: WatchEvent) -> None:
        # Controller-commit stamp (dissemination-latency origin): the
        # moment the event enters the plane.  monotonic so it survives
        # wall-clock jumps and stays comparable across same-host processes
        # (the pipe/netwire transports); pre-stamped events keep theirs.
        if not ev.ts:
            ev = replace(ev, ts=time.monotonic())
        key = (ev.obj_type, ev.name)
        live = [w for w in self._watchers if not w._stopped]
        self._watchers = live
        if ev.kind == "DELETED":
            self._objs.pop(key, None)
            for w in live:
                if key in w._known:
                    w._known.discard(key)
                    w._deliver(WatchEvent(
                        kind="DELETED", obj_type=ev.obj_type, name=ev.name,
                        ts=ev.ts,
                    ))
            return

        self._objs[key] = _Stored(obj=ev.obj, span=set(ev.span))
        for w in live:
            relevant = w.node in ev.span
            if relevant and key not in w._known:
                w._known.add(key)
                w._deliver(replace(ev, kind="ADDED"))
            elif relevant:
                w._deliver(ev)
            elif key in w._known:
                # Span shrank away from this node: retract the object.
                w._known.discard(key)
                w._deliver(WatchEvent(
                    kind="DELETED", obj_type=ev.obj_type, name=ev.name,
                    ts=ev.ts,
                ))

    # -- consumer side -------------------------------------------------------

    def _replay(self, w: Watcher) -> None:
        for (obj_type, name), st in sorted(self._objs.items()):
            if w.node in st.span:
                w._known.add((obj_type, name))
                w._deliver(WatchEvent(
                    kind="ADDED", obj_type=obj_type, name=name,
                    obj=st.obj, span=set(st.span),
                ))

    def watch(self, node: str, cb: Callable[[WatchEvent], None]) -> Watcher:
        """Subscribe a node with a synchronous callback: replays current
        relevant objects as ADDED, then streams filtered events (the
        reference's watch bookmark semantics).  Returns the Watcher handle;
        stop() unsubscribes."""
        w = Watcher(node, cb)
        self._replay(w)
        self._watchers.append(w)
        return w

    def watch_queue(self, node: str, max_pending: Optional[int] = None,
                    *, replay: bool = True) -> Watcher:
        """Subscribe a node in queued mode: events (including the initial
        replay) buffer in the returned Watcher until drained — the
        per-watcher channel of the reference's RAM store.  max_pending
        bounds the buffer (overflow -> needs_resync, see resync()).

        replay=False skips the initial snapshot buffering — for consumers
        that serve a full resync() on first pump anyway (the netwire
        server's fresh connections): replaying into a bounded queue there
        is wasted work and, when the snapshot exceeds the cap, counts a
        slow-consumer overflow that never happened."""
        w = Watcher(node, None, max_pending=max_pending)
        if replay:
            self._replay(w)
        self._watchers.append(w)
        return w

    def resync(self, w: Watcher) -> "ResyncCursor":
        """Re-list for a queued watcher whose stream was invalidated
        (overflow or reconnect): clears the watcher's known-set and returns
        a resumable ResyncCursor over the CURRENT span-filtered state —
        bypassing the bounded queue, so a resync always completes even when
        the snapshot exceeds max_pending.  Iterating the cursor yields the
        whole snapshot (list-compatible with the old API); take(n) lets a
        transport ship it in bounded chunks across pump rounds.  The
        transport brackets the emitted events with resync markers so the
        consumer can retract anything it holds that is absent from the
        snapshot (re-list semantics)."""
        return ResyncCursor(self, w)

    @property
    def n_watchers(self) -> int:
        return sum(1 for w in self._watchers if not w._stopped)


class ResyncCursor:
    """Resumable span-filtered re-list for ONE watcher.

    Construction atomically re-arms the watcher: queue and known-set are
    cleared and needs_resync drops, so live churn arriving MID-resync lands
    in the (coalescing) queue instead of invalidating the stream again.
    The cursor snapshots only the KEYS in the watcher's span; take() reads
    the live store at emission time, so a key deleted or span-shrunk while
    the cursor was parked is silently skipped (never replayed stale) and a
    key the live queue already delivered (now in the known-set) is not sent
    twice — the snapshot degrades into a known-set diff as live traffic
    overtakes it.  Emitted events are unstamped: a resync replays state of
    unknowable age, so realization tracing meters them separately instead
    of inventing a latency."""

    def __init__(self, store: RamStore, w: Watcher):
        self._store = store
        self._w = w
        w._clear_queue()
        w._known.clear()
        w.needs_resync = False
        self._keys: deque[tuple[str, str]] = deque(sorted(
            key for key, st in store._objs.items() if w.node in st.span))
        self.total = len(self._keys)
        self.sent = 0
        self.chunks = 0

    @property
    def done(self) -> bool:
        return not self._keys

    def take(self, n: Optional[int] = None) -> list[WatchEvent]:
        """Emit up to `n` snapshot events (None = all remaining), marking
        each key known as it ships."""
        w = self._w
        out: list[WatchEvent] = []
        while self._keys and (n is None or len(out) < n):
            key = self._keys.popleft()
            st = self._store._objs.get(key)
            if st is None or w.node not in st.span:
                continue  # deleted / span-shrunk while the cursor was parked
            if key in w._known:
                continue  # the live queue already delivered a fresher event
            w._known.add(key)
            out.append(WatchEvent(
                kind="ADDED", obj_type=key[0], name=key[1],
                obj=st.obj, span=set(st.span),
            ))
        if out:
            self.sent += len(out)
            self.chunks += 1
        return out

    def __iter__(self):
        return iter(self.take())
