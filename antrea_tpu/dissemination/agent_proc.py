"""Agent process: consumes a serialized watch stream on stdin.

The remote half of dissemination/transport.py — an antrea-agent-shaped
process (ref cmd/antrea-agent: watch -> ruleCache -> reconcile -> datapath)
whose ONLY input is the framed event stream; it holds no reference to the
controller's memory, so everything it enforces provably crossed the
serialization boundary.

Protocol (newline-delimited JSON on stdin; one-line JSON responses on
stdout — only control commands respond):
  {"ev": <serde-encoded WatchEvent>}   apply to the local agent controller
  {"ctl": "resync_begin"/"resync_end"} full re-list window (no response):
                                       events inside are the complete
                                       snapshot; stale local state is
                                       retracted at resync_end
  {"cmd": "sync"}                      reconcile into the datapath
  {"cmd": "step", "now": N, "packets": {...}}  run a batch, return verdicts
  {"cmd": "summary"}                   local PolicySet shape (debugging)
  {"cmd": "exit"}                      clean shutdown
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--node", required=True)
    ap.add_argument("--datapath", default="oracle", choices=["oracle", "tpuflow"])
    ap.add_argument("--flow-slots", type=int, default=1 << 12)
    ap.add_argument("--aff-slots", type=int, default=1 << 8)
    args = ap.parse_args()

    from ..agent.controller import AgentPolicyController
    from ..datapath import OracleDatapath, TpuflowDatapath
    from ..packet import PacketBatch
    from . import serde

    kw = dict(flow_slots=args.flow_slots, aff_slots=args.aff_slots)
    if args.datapath == "tpuflow":
        dp = TpuflowDatapath(miss_chunk=32, **kw)
    else:
        dp = OracleDatapath(**kw)
    agent = AgentPolicyController(args.node, dp, store=None)

    out = sys.stdout.buffer

    def respond(obj: dict) -> None:
        out.write((json.dumps(obj, separators=(",", ":")) + "\n").encode())
        out.flush()

    for raw in sys.stdin.buffer:
        try:
            msg = json.loads(raw.decode())
        except ValueError as e:
            # Event frames have no reader waiting: responding here would
            # desynchronize the RPC stream (the next readline would eat
            # it).  Log and drop.
            print(f"agent_proc[{args.node}]: bad frame: {e}", file=sys.stderr)
            continue
        if "ev" in msg:
            try:
                agent.handle_event(serde.decode_event(msg["ev"]))
            except Exception as e:  # keep consuming; report out-of-band
                print(
                    f"agent_proc[{args.node}]: event failed: "
                    f"{type(e).__name__}: {e}",
                    file=sys.stderr,
                )
            continue
        if "ctl" in msg:
            # Resync markers are stream framing, not RPCs: no response
            # (responding would desynchronize the request/response pairing).
            if msg["ctl"] == "resync_begin":
                agent.begin_resync()
            elif msg["ctl"] == "resync_end":
                agent.end_resync()
            continue
        cmd = msg.get("cmd")
        try:
            if cmd == "sync":
                agent.sync()
                # Realization report rides the sync response: {policy uid:
                # realized spec generation} — the wire form of the agent's
                # UpdateStatus RPC (status_controller.go:140); the parent
                # relays it into the StatusAggregator.
                respond({
                    "ok": True,
                    "generation": dp.generation,
                    "realized": agent.realized_generations(),
                })
            elif cmd == "step":
                p = msg["packets"]
                batch = PacketBatch(
                    src_ip=np.asarray(p["src_ip"], np.uint32),
                    dst_ip=np.asarray(p["dst_ip"], np.uint32),
                    proto=np.asarray(p["proto"], np.int32),
                    src_port=np.asarray(p["src_port"], np.int32),
                    dst_port=np.asarray(p["dst_port"], np.int32),
                )
                r = dp.step(batch, msg["now"])
                respond({
                    "code": [int(x) for x in r.code],
                    "est": [int(x) for x in r.est],
                    "reply": [int(x) for x in r.reply],
                    "reject_kind": [int(x) for x in r.reject_kind],
                    "snat": [int(x) for x in r.snat],
                    "svc_idx": [int(x) for x in r.svc_idx],
                    "dnat_ip": [int(x) for x in r.dnat_ip],
                    "dnat_port": [int(x) for x in r.dnat_port],
                    "ingress_rule": r.ingress_rule,
                    "egress_rule": r.egress_rule,
                })
            elif cmd == "summary":
                ps = agent.policy_set
                respond({
                    "policies": sorted(p.uid for p in ps.policies),
                    "addressGroups": sorted(ps.address_groups),
                    "appliedToGroups": sorted(ps.applied_to_groups),
                })
            elif cmd == "exit":
                respond({"ok": True})
                return 0
            else:
                respond({"error": f"unknown cmd {cmd!r}"})
        except Exception as e:  # report, don't die: the stream continues
            respond({"error": f"{type(e).__name__}: {e}"})
    return 0


if __name__ == "__main__":
    sys.exit(main())
