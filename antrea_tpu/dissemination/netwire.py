"""Network dissemination wire: the controller<->agent channel over REAL
mutual-TLS TCP sockets.

The reference's control plane is a protobuf watch over HTTPS with CA-signed
certificates on both ends (/root/reference/pkg/apiserver/apiserver.go:97-99,
pkg/apiserver/certificate/; agents authenticate and verify the server).
This module materializes that wire for the TPU build:

  * X.509 PKI (make_ca / issue_cert, real certificates via `cryptography`)
    — the wire-level counterpart of the semantic CSR flow in
    controller/certificates.py;
  * DisseminationServer: accepts mTLS connections (client certs REQUIRED
    and verified against the CA), registers a queued span watcher per
    agent and streams serde-encoded WatchEvents (newline-JSON — the
    protobuf-role codec of dissemination/serde.py);
  * the SAME connection carries the agent's realization-status reports
    upstream ({"status": {...}} frames -> StatusAggregator), the
    UpdateStatus RPC of status_controller.go:140;
  * NetAgent: the agent-side client feeding an AgentPolicyController.

Delivery is explicitly pumped (server.pump() / agent.pump()) so tests are
deterministic; the sockets, handshakes and certificate verification are
real.  A client without a CA-signed certificate cannot connect; an agent
refusing the server certificate cannot be fed.
"""

from __future__ import annotations

import datetime
import ipaddress
import json
import os
import random
import select
import socket
import ssl
import subprocess
import threading
import time
import zlib
from dataclasses import dataclass
from typing import Optional

from . import serde
from ..observability.flightrec import emit_into
from .store import RamStore, ResyncCursor, Watcher

# bounded-buffer analysis-pass contract (analysis/bounded_buffer.py): every
# buffer-shaped attribute in this package declares its cap here.
BUFFER_CAPS = {
    "_LineConn._buf": "holds at most one partial frame; the framing loops "
                      "bound a line at 64KiB (hello) / 1MiB (iter_json_"
                      "lines) and recv_ready drains complete lines "
                      "immediately",
}


def _min_opt(*vals: Optional[int]) -> Optional[int]:
    """Smallest non-None bound (None = unbounded)."""
    present = [v for v in vals if v is not None]
    return min(present) if present else None


# -- PKI ---------------------------------------------------------------------
#
# Primary backend: the `cryptography` package.  Fallback: the openssl CLI —
# some deployment images ship libssl (so the stdlib `ssl` module works) but
# not the Python cryptography wheel; the PKI must not take the whole
# dissemination plane down with an ImportError there.  Both backends emit
# the same PEM artifacts, so everything downstream (SSLContext loading,
# peer-CN verification) is backend-blind.


def _write(path: str, data: bytes) -> None:
    with open(path, "wb") as f:
        f.write(data)


def _openssl(*args: str, cwd: Optional[str] = None) -> None:
    subprocess.run(
        ["openssl", *args], cwd=cwd, check=True,
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
    )


def _make_ca_openssl(dirpath: str, cn: str) -> None:
    _openssl("ecparam", "-name", "prime256v1", "-genkey", "-noout",
             "-out", os.path.join(dirpath, "ca.key"))
    _openssl("req", "-new", "-x509", "-key", os.path.join(dirpath, "ca.key"),
             "-out", os.path.join(dirpath, "ca.crt"),
             "-days", "365", "-subj", f"/CN={cn}")


def _issue_cert_openssl(dirpath: str, cn: str, server: bool,
                        cp: str, kp: str) -> None:
    csr = os.path.join(dirpath, f"{cn}.csr")
    ext = os.path.join(dirpath, f"{cn}.ext")
    try:
        _openssl("ecparam", "-name", "prime256v1", "-genkey", "-noout",
                 "-out", kp)
        _openssl("req", "-new", "-key", kp, "-subj", f"/CN={cn}",
                 "-out", csr)
        sign = ["x509", "-req", "-in", csr,
                "-CA", os.path.join(dirpath, "ca.crt"),
                "-CAkey", os.path.join(dirpath, "ca.key"),
                "-CAcreateserial", "-days", "30", "-out", cp]
        if server:
            _write(ext, b"subjectAltName=DNS:localhost,IP:127.0.0.1\n")
            sign += ["-extfile", ext]
        _openssl(*sign)
    finally:
        for p in (csr, ext):
            try:
                os.unlink(p)
            except OSError:
                pass


def make_ca(dirpath: str, cn: str = "antrea-tpu-ca") -> None:
    """Create ca.crt/ca.key under dirpath (idempotent)."""
    os.makedirs(dirpath, exist_ok=True)
    if os.path.exists(os.path.join(dirpath, "ca.crt")):
        return
    try:
        from cryptography import x509
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import ec
        from cryptography.x509.oid import NameOID
    except ImportError:
        _make_ca_openssl(dirpath, cn)
        return
    key = ec.generate_private_key(ec.SECP256R1())
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, cn)])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name).issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=365))
        .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                       critical=True)
        .sign(key, hashes.SHA256())
    )
    _write(os.path.join(dirpath, "ca.key"), key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.TraditionalOpenSSL,
        serialization.NoEncryption()))
    _write(os.path.join(dirpath, "ca.crt"),
           cert.public_bytes(serialization.Encoding.PEM))


def _cert_usable(cp: str, margin_s: int = 86400) -> bool:
    """True when the cached cert still outlives the margin.  Leaf certs
    are valid 30 days: reusing one past expiry would make every reconnect
    handshake fail forever (the reconnect loop would re-dial an identity
    the server must reject) — an expiring cert re-mints instead."""
    try:
        from cryptography import x509
    except ImportError:
        try:
            _openssl("x509", "-checkend", str(margin_s), "-noout", "-in", cp)
            return True
        except (subprocess.SubprocessError, OSError):
            return False
    try:
        with open(cp, "rb") as f:
            cert = x509.load_pem_x509_certificate(f.read())
    except (OSError, ValueError):
        return False
    exp = getattr(cert, "not_valid_after_utc", None)
    if exp is None:  # older cryptography: naive UTC datetime
        exp = cert.not_valid_after.replace(tzinfo=datetime.timezone.utc)
    now = datetime.datetime.now(datetime.timezone.utc)
    return exp - now > datetime.timedelta(seconds=margin_s)


def issue_cert(dirpath: str, cn: str, *, server: bool = False) -> tuple[str, str]:
    """CA-sign a cert for cn -> (cert path, key path).  Server certs get
    the 127.0.0.1/localhost SANs the client verifies against.  An already
    issued, still-valid (cert, key) pair for this CN is reused — a
    reconnecting agent re-handshakes with its existing identity instead
    of re-running key generation on every backoff attempt; an expiring
    one is re-minted (see _cert_usable)."""
    cp = os.path.join(dirpath, f"{cn}.crt")
    kp = os.path.join(dirpath, f"{cn}.key")
    if os.path.exists(cp) and os.path.exists(kp) and _cert_usable(cp):
        return cp, kp
    try:
        from cryptography import x509
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import ec
        from cryptography.x509.oid import NameOID
    except ImportError:
        _issue_cert_openssl(dirpath, cn, server, cp, kp)
        return cp, kp

    with open(os.path.join(dirpath, "ca.key"), "rb") as f:
        ca_key = serialization.load_pem_private_key(f.read(), None)
    with open(os.path.join(dirpath, "ca.crt"), "rb") as f:
        ca_cert = x509.load_pem_x509_certificate(f.read())
    key = ec.generate_private_key(ec.SECP256R1())
    now = datetime.datetime.now(datetime.timezone.utc)
    b = (
        x509.CertificateBuilder()
        .subject_name(x509.Name(
            [x509.NameAttribute(NameOID.COMMON_NAME, cn)]))
        .issuer_name(ca_cert.subject)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=30))
    )
    if server:
        b = b.add_extension(x509.SubjectAlternativeName([
            x509.DNSName("localhost"),
            x509.IPAddress(ipaddress.ip_address("127.0.0.1")),
        ]), critical=False)
    cert = b.sign(ca_key, hashes.SHA256())
    _write(kp, key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.TraditionalOpenSSL,
        serialization.NoEncryption()))
    _write(cp, cert.public_bytes(serialization.Encoding.PEM))
    return cp, kp


# -- framing -----------------------------------------------------------------


class Backoff:
    """Capped exponential backoff with jitter — the reconnect discipline
    of the reference's client-go watch retry (wait.Backoff).

    Two jitter layers keep a fleet that lost one controller from
    re-handshaking in lockstep: a per-attempt random factor, and a
    DETERMINISTIC per-node factor derived from the node name — so even
    clients constructed with identical (or identically-seeded) rngs
    spread out.  After a controller restart, 10k agents redial on 10k
    distinct schedules, each still bounded by `cap`."""

    def __init__(self, base: float = 0.05, cap: float = 2.0, rng=None,
                 node: Optional[str] = None):
        self.base = base
        self.cap = cap
        self._rng = rng if rng is not None else random.Random()
        # Node-name hash -> factor in [0.6, 1.0]: scales EVERY delay (cap
        # included, so delays never exceed cap) and differs node-to-node.
        if node:
            h = zlib.crc32(node.encode())
            self.node_factor = 0.6 + 0.4 * ((h % 4096) / 4095.0)
        else:
            self.node_factor = 1.0
        self.attempt = 0

    def next_delay(self) -> float:
        # Clamp the exponent: attempt grows without bound across a long
        # outage, and 2**~1030 overflows float — the cap wins long before.
        d = min(self.cap, self.base * (2 ** min(self.attempt, 30)))
        self.attempt += 1
        return d * self.node_factor * (0.5 + 0.5 * self._rng.random())

    def reset(self) -> None:
        self.attempt = 0


# The reconnect-policy name used by docs/tests; Backoff is the
# implementation class.
BackoffPolicy = Backoff


class _LineConn:
    """Newline-JSON framing over a (TLS) socket, nonblocking reads."""

    def __init__(self, sock):
        self.sock = sock
        self._buf = b""
        # Orderly EOF observed on recv: the peer is gone — callers use
        # this to trigger reconnect instead of pumping a dead socket.
        self.closed = False

    def send(self, obj: dict) -> None:
        self.sock.sendall(
            (json.dumps(obj, separators=(",", ":")) + "\n").encode())

    def recv_ready(self, first_wait: float = 0.0) -> list[dict]:
        """Drain whatever is available -> decoded frames.  first_wait
        bounds the wait for the FIRST chunk (loopback TLS records can land
        an instant after the peer's sendall returns); subsequent reads
        never block."""
        out = []
        wait = first_wait
        while True:
            r, _, _ = select.select([self.sock], [], [], wait)
            wait = 0.0
            if not r:
                # TLS may hold decrypted bytes even when the raw socket is
                # quiet; poll the SSL buffer too.
                if getattr(self.sock, "pending", lambda: 0)() == 0:
                    break
            try:
                chunk = self.sock.recv(65536)
            except ssl.SSLWantReadError:
                break
            if not chunk:
                self.closed = True  # peer closed
                break
            self._buf += chunk
        while b"\n" in self._buf:
            line, self._buf = self._buf.split(b"\n", 1)
            if line:
                out.append(json.loads(line.decode()))
        return out


def iter_json_lines(sock, max_line: int = 1 << 20):
    """Yield decoded JSON objects from newline-framed lines on a BLOCKING
    socket until EOF — the one blocking-side framing loop (the
    non-blocking twin is _LineConn.recv_ready; the 64KiB hello bound in
    _handshake_inner is this same discipline).  Malformed JSON yields a
    ValueError to the caller; an oversized line raises."""
    buf = b""
    while True:
        chunk = sock.recv(65536)
        if not chunk:
            return
        buf += chunk
        if len(buf) > max_line:
            raise ValueError(f"frame exceeds {max_line} bytes")
        while b"\n" in buf:
            line, buf = buf.split(b"\n", 1)
            if line:
                yield json.loads(line.decode())


def recv_one_json(sock, buf: bytes, max_line: int = 1 << 20):
    """Blocking read of ONE newline-framed JSON object -> (obj, rest) —
    the client-side half of iter_json_lines' framing."""
    while b"\n" not in buf:
        chunk = sock.recv(65536)
        if not chunk:
            raise ConnectionError("peer closed the socket")
        buf += chunk
        if len(buf) > max_line:
            raise ValueError(f"frame exceeds {max_line} bytes")
    line, rest = buf.split(b"\n", 1)
    return json.loads(line.decode()), rest


# -- server ------------------------------------------------------------------


@dataclass
class _ConnState:
    """One registered agent connection.  fresh=True until the first resync
    completes (bracketed in resync markers so the agent can retract state
    a previous connection left behind); cursor holds the in-flight
    chunked resync, if any."""

    conn: _LineConn
    watcher: Watcher
    seq: int
    fresh: bool = True
    cursor: Optional[ResyncCursor] = None


class DisseminationServer:
    """mTLS dissemination endpoint in front of a RamStore.

    Failure model: an agent connection that dies is pruned (its events
    stay in the store); on re-handshake the server REPLAYS the node's
    span-filtered snapshot between {"ctl": "resync_begin"}/{"ctl":
    "resync_end"} markers — the reference's watch re-list semantics — so
    the agent can reconcile away anything stale.  Per-agent watcher queues
    are bounded by watcher_max_pending: a consumer that falls behind the
    cap costs one resync, never unbounded controller memory.

    Storm disciplines (all opt-in; None = the permissive legacy behavior):
      * resync_chunk — a resync ships at most this many events per pump
        round, via a resumable ResyncCursor, interleaved with other
        agents' live drains (no head-of-line blocking behind a big
        snapshot).  Live churn arriving mid-resync lands in the watcher's
        coalescing queue and ships inside the SAME resync window.
      * resync_concurrency — at most this many watchers mid-resync at
        once; the rest are shed to later rounds (their gated queues hold
        no memory while parked), so a fleet-wide overflow storm becomes a
        metered trickle of re-lists, never a replay storm.
      * drain_max / send_budget — per-watcher and per-round send bounds so
        one hot agent cannot dominate a round (the 2s send timeout +
        identity-aware prune stays the backstop for wedged peers)."""

    def __init__(self, store: RamStore, certdir: str, *,
                 host: str = "127.0.0.1", port: int = 0,
                 status_aggregator=None,
                 watcher_max_pending: Optional[int] = None,
                 resync_chunk: Optional[int] = None,
                 resync_concurrency: Optional[int] = None,
                 drain_max: Optional[int] = None,
                 send_budget: Optional[int] = None,
                 flightrec=None):
        self._store = store
        self._status = status_aggregator
        self._watcher_max_pending = watcher_max_pending
        self._resync_chunk = resync_chunk
        self._resync_concurrency = resync_concurrency
        self._drain_max = drain_max
        self._send_budget = send_budget
        self._flightrec = flightrec
        # Dissemination-health counters (scraped by
        # observability.metrics.render_dissemination_metrics).
        self.resyncs_total = 0      # completed resyncs (incl. hellos)
        self.reconnects_total = 0   # re-handshakes replacing a live node
        self.resync_chunks_total = 0   # non-empty cursor chunks shipped
        self.resyncs_shed_total = 0    # admission-gate deferrals
        # Coalesce counts of retired watchers (stop/replace) fold in here
        # so dissemination_stats' total survives reconnect churn.
        self._coalesced_retired = 0
        # Round-robin rotation so budget exhaustion starves fairly.
        self._rr = 0
        cert, key = issue_cert(certdir, "controller", server=True)
        self._ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        self._ctx.load_cert_chain(cert, key)
        self._ctx.load_verify_locations(os.path.join(certdir, "ca.crt"))
        self._ctx.verify_mode = ssl.CERT_REQUIRED  # mutual TLS
        self._lsock = socket.create_server((host, port))
        self.address = self._lsock.getsockname()
        # node -> _ConnState; handshakes land here from the acceptor.
        # seq is the ACCEPT order: concurrent handshake threads may finish
        # out of order, and a stale connection finishing last must never
        # evict the agent's newer live one.
        self._conns: dict[str, _ConnState] = {}
        self._lock = threading.Lock()
        self._closing = False
        self._accept_seq = 0
        # Any peer that can reach the listener gets a handshake thread
        # BEFORE authenticating; bound them so a raw-TCP flood cannot
        # accumulate threads without limit.
        self._handshakes = threading.Semaphore(32)
        # TLS handshakes are inherently concurrent with the client's
        # connect, so accept+handshake+hello run on a daemon thread (the
        # reference's apiserver accepts concurrently too); event delivery
        # and status consumption stay on the explicit pump() for
        # deterministic tests.
        self._acceptor = threading.Thread(target=self._accept_loop,
                                          daemon=True)
        self._acceptor.start()

    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                raw, _ = self._lsock.accept()
            except OSError:
                return  # listener closed
            # A slow or malicious peer (even certless) must not stall
            # registration of every other agent for its 5s timeout:
            # handshake+hello run on a short-lived per-connection thread;
            # only registration takes the lock.
            if not self._handshakes.acquire(blocking=False):
                raw.close()  # at capacity: shed before spending a thread
                continue
            self._accept_seq += 1
            threading.Thread(
                target=self._handshake, args=(raw, self._accept_seq),
                daemon=True,
            ).start()

    def _handshake(self, raw, seq: int) -> None:
        try:
            self._handshake_inner(raw, seq)
        finally:
            self._handshakes.release()

    def _handshake_inner(self, raw, seq: int) -> None:
        try:
            raw.settimeout(5.0)
            tls = self._ctx.wrap_socket(raw, server_side=True)
        except (ssl.SSLError, OSError):
            raw.close()  # unauthenticated peer: handshake rejected
            return
        try:
            buf = b""
            while b"\n" not in buf:
                if len(buf) > 65536:
                    # A certified peer streaming newline-less bytes must
                    # not grow the hello buffer without bound (each recv
                    # resets the per-op timeout): reject.
                    raise ValueError("hello line exceeds 64KiB")
                chunk = tls.recv(4096)
                if not chunk:
                    break
                buf += chunk
            if not buf:
                tls.close()
                return
            line, rest = buf.split(b"\n", 1)
            hello = json.loads(line.decode())
            node = hello["hello"]
            # Bind the VERIFIED certificate identity to the claimed
            # node: a CA-signed cert for agent-X must not register as
            # node Y (the mutual-TLS authentication contract — antrea's
            # apiserver authenticates agents by identity, not just by
            # holding any cluster cert).
            cert = tls.getpeercert()
            cns = [v for rdn in cert.get("subject", ())
                   for k, v in rdn if k == "commonName"]
            if cns != [f"agent-{node}"]:
                raise ValueError(
                    f"cert identity {cns} does not match node {node!r}"
                )
        except (ssl.SSLError, OSError, ValueError, KeyError, TypeError):
            # Malformed/stalled hello (TypeError: valid JSON that is not
            # an object): close the HANDSHAKEN socket (its fd moved out
            # of `raw` at wrap time).
            tls.close()
            return
        tls.settimeout(None)
        tls.setblocking(False)
        conn = _LineConn(tls)
        # Frames coalesced into the hello's TLS record (e.g. an eager
        # status report) must not be dropped.
        conn._buf = rest
        with self._lock:
            if self._closing:
                # close() already snapshotted _conns: registering now
                # would leak an un-stopped watcher that buffers store
                # events forever plus an open TLS socket.
                tls.close()
                return
            old = self._conns.get(node)
            if old is not None and old.seq > seq:
                # A NEWER connection for this node already registered
                # (this thread's hello was slower): this one is stale —
                # evicting the live registration would stream to a socket
                # the agent abandoned.
                tls.close()
                return
            w = self._store.watch_queue(
                # replay=False: fresh=True already forces a full resync on
                # the first pump — buffering the snapshot here would be
                # discarded work and could spuriously count an overflow.
                node, max_pending=self._watcher_max_pending, replay=False)
            w._flightrec = self._flightrec
            self._conns[node] = _ConnState(conn, w, seq)
            if old is not None:
                self.reconnects_total += 1
                self._coalesced_retired += old.watcher.coalesced
        if old is not None:
            # Reconnect: retire the previous registration — an
            # un-stopped watcher would buffer events forever.
            old.watcher.stop()
            try:
                old.conn.sock.close()
            except OSError:
                pass

    def wait_connected(self, n: int, timeout: float = 5.0) -> None:
        """Block until n agents have completed handshake+hello (the
        acceptor thread registers them asynchronously)."""
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if len(self._conns) >= n:
                    return
            time.sleep(0.01)
        raise TimeoutError(f"{n} agents not connected within {timeout}s")

    def _emit(self, kind: str, **fields) -> None:
        emit_into(self, kind, **fields)

    def pump(self) -> int:
        """Stream queued events, consume status reports -> events shipped.

        A fresh connection (hello or reconnect) and a watcher whose
        bounded queue overflowed are served a RESYNC: the node's
        span-filtered snapshot bracketed in resync markers, bypassing the
        queue (so a snapshot larger than the cap still converges).  With
        resync_chunk set, the snapshot ships cursor-chunked across rounds,
        interleaved with other agents' live traffic; resync_concurrency
        bounds how many such cursors run at once; drain_max/send_budget
        bound per-watcher and per-round send work (class docstring)."""
        shipped = 0
        budget = self._send_budget
        with self._lock:
            conns = list(self._conns.items())
        inflight = sum(1 for _n, st in conns if st.cursor is not None)
        if conns:
            # Rotate the serving order so a budget that runs out mid-round
            # starves a DIFFERENT tail next round.
            self._rr = (self._rr + 1) % len(conns)
            conns = conns[self._rr:] + conns[:self._rr]
        dead: list[tuple[str, _LineConn]] = []
        live = []
        for node, st in conns:
            conn = st.conn
            if budget is not None and shipped >= budget:
                live.append((node, conn))  # still select it for statuses
                continue
            try:
                # Bounded send: an agent that stopped reading (full TCP
                # buffer) must not block the pump forever — a timed-out
                # sendall raises and the agent is pruned as dead.
                conn.sock.settimeout(2.0)
                if st.cursor is None and (st.fresh
                                          or st.watcher.needs_resync):
                    if (self._resync_concurrency is not None
                            and inflight >= self._resync_concurrency):
                        # Admission gate: defer this re-list to a later
                        # round.  The parked watcher stays gated
                        # (needs_resync drops live events), so waiting
                        # costs no memory.
                        self.resyncs_shed_total += 1
                        self._emit("resync-shed", node=node,
                                   inflight=inflight)
                        conn.sock.setblocking(False)
                        live.append((node, conn))
                        continue
                    st.cursor = self._store.resync(st.watcher)
                    inflight += 1
                    conn.send({"ctl": "resync_begin"})
                    self._emit("resync-begin", node=node,
                               objects=st.cursor.total)
                elif st.cursor is not None and st.watcher.needs_resync:
                    # The coalescing queue overflowed AGAIN mid-resync
                    # (distinct-key churn past the cap): restart the
                    # cursor inside the same window — a repeated begin
                    # marker resets the consumer's seen-set.
                    st.cursor = self._store.resync(st.watcher)
                    conn.send({"ctl": "resync_begin"})
                    self._emit("resync-begin", node=node,
                               objects=st.cursor.total, restart=True)
                if st.cursor is not None:
                    room = None if budget is None else budget - shipped
                    chunk = st.cursor.take(
                        _min_opt(self._resync_chunk, room))
                    for ev in chunk:
                        conn.send({"ev": serde.encode_event(ev)})
                        shipped += 1
                    if chunk:
                        self.resync_chunks_total += 1
                    # Live churn that landed mid-resync ships INSIDE the
                    # open window (the consumer's resync seen-set covers
                    # it), under the same drain bound as healthy traffic.
                    room = None if budget is None else budget - shipped
                    for ev in st.watcher.drain(
                            _min_opt(self._drain_max, room)):
                        conn.send({"ev": serde.encode_event(ev)})
                        shipped += 1
                    if st.cursor.done and not st.watcher.needs_resync:
                        conn.send({"ctl": "resync_end"})
                        self._emit("resync-end", node=node,
                                   chunks=st.cursor.chunks,
                                   events=st.cursor.sent)
                        st.cursor = None
                        st.fresh = False
                        inflight -= 1
                        with self._lock:
                            self.resyncs_total += 1
                else:
                    room = None if budget is None else budget - shipped
                    for ev in st.watcher.drain(
                            _min_opt(self._drain_max, room)):
                        conn.send({"ev": serde.encode_event(ev)})
                        shipped += 1
                conn.sock.setblocking(False)
                live.append((node, conn))
            except (OSError, ssl.SSLError, ValueError):
                # One dead agent must not halt dissemination to the rest:
                # prune it (its events stay in the STORE's history; a
                # reconnect replays).
                dead.append((node, conn))
        # ONE bounded select across every agent socket (not 50ms per idle
        # connection serially), then drain only the ready/buffered ones.
        if live:
            try:
                ready, _, _ = select.select([c.sock for _n, c in live],
                                            [], [], 0.05)
            except (OSError, ValueError):
                ready = [c.sock for _n, c in live]  # sort out per-conn below
            ready_ids = {id(s) for s in ready}
            for node, conn in live:
                try:
                    if (id(conn.sock) in ready_ids or conn._buf
                            or conn.sock.pending()):
                        for frame in conn.recv_ready():
                            if "status" in frame and self._status is not None:
                                self._status.update_node_statuses(
                                    node, frame["status"])
                except (OSError, ssl.SSLError, ValueError):
                    dead.append((node, conn))
        for node, failed_conn in dead:
            with self._lock:
                entry = self._conns.get(node)
                # Identity-aware prune: if the node RECONNECTED between
                # our snapshot and now, the registered entry is a fresh
                # healthy connection — tearing it down by name would
                # disconnect a live agent.
                if entry is None or entry.conn is not failed_conn:
                    entry = None
                else:
                    del self._conns[node]
                    self._coalesced_retired += entry.watcher.coalesced
            if entry is not None:
                entry.watcher.stop()
                try:
                    entry.conn.sock.close()
                except OSError:
                    pass
            else:
                try:
                    failed_conn.sock.close()
                except OSError:
                    pass
        return shipped

    def dissemination_stats(self) -> dict:
        """Health snapshot for the metrics surface: per-node watcher depth
        / overflow / coalesce / resync-pending state plus the server
        counters (chunks shipped, resyncs in flight, admission shedding)."""
        with self._lock:
            return {
                "watchers": {
                    node: {
                        "pending": st.watcher.pending(),
                        "overflows": st.watcher.overflows,
                        "coalesced": st.watcher.coalesced,
                        "needs_resync": bool(
                            st.fresh or st.watcher.needs_resync
                            or st.cursor is not None),
                    }
                    for node, st in self._conns.items()
                },
                "resyncs_total": self.resyncs_total,
                "reconnects_total": self.reconnects_total,
                "resync_chunks_total": self.resync_chunks_total,
                "resyncs_inflight": sum(
                    1 for st in self._conns.values()
                    if st.cursor is not None),
                "resyncs_shed_total": self.resyncs_shed_total,
                "coalesced_total": self._coalesced_retired + sum(
                    st.watcher.coalesced for st in self._conns.values()),
            }

    def close(self) -> None:
        with self._lock:
            # Flag + snapshot under ONE lock hold: any in-flight
            # _handshake thread either registered before this (and is in
            # the snapshot) or will observe _closing and self-close.
            self._closing = True
            conns = list(self._conns.values())
        for st in conns:
            st.watcher.stop()
            st.conn.sock.close()
        # shutdown() BEFORE close(): closing an fd does not wake a thread
        # blocked in accept() — the acceptor would stay parked on the
        # stale fd number, and once the kernel reuses it for a NEW
        # server's listener, the dead server's acceptor steals that
        # server's connections and answers with the wrong certificate.
        try:
            self._lsock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass  # never-connected listener on some platforms
        self._lsock.close()
        self._acceptor.join(timeout=2)


# -- agent client ------------------------------------------------------------


def connect_client(node: str, address, certdir: str,
                   client_cn: Optional[str] = None):
    """The ONE agent-side mTLS bring-up (cert issue, TLS connect, hello,
    non-blocking socket) shared by NetAgent and the fleet's watch-only
    clients — client-side wire changes live here exactly once.
    -> (tls socket, _LineConn)."""
    cert, key = issue_cert(certdir, client_cn or f"agent-{node}")
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.load_cert_chain(cert, key)
    ctx.load_verify_locations(os.path.join(certdir, "ca.crt"))
    raw = socket.create_connection(tuple(address))
    sock = ctx.wrap_socket(raw, server_hostname="localhost")
    conn = _LineConn(sock)
    conn.send({"hello": node})
    sock.setblocking(False)
    return sock, conn


class ReconnectingClient:
    """The ONE agent-side wire lifecycle, shared by every client flavor
    (NetAgent here, the fleet's NetFakeAgent): dial via connect_client,
    detect a dead socket, re-dial with capped exponential backoff +
    jitter.  Subclasses call _init_wire() from __init__ and consume
    self._sock/self._conn; _mark_dead() schedules the backoff,
    _try_reconnect() honors it.  The FIRST connect still raises to the
    caller — a misconfigured CA should fail loudly, not spin in
    backoff."""

    def _init_wire(self, node: str, address, certdir: str, *,
                   client_cn: Optional[str] = None, reconnect: bool = True,
                   backoff: Optional[Backoff] = None, clock=time.monotonic,
                   fault_wrap=None) -> None:
        self.node = node
        self._address = tuple(address)
        self._certdir = certdir
        self._client_cn = client_cn
        self._reconnect_enabled = reconnect
        # Default backoff carries the node's deterministic jitter factor so
        # a herd of default-constructed clients never redials in lockstep.
        self._backoff = backoff if backoff is not None else Backoff(node=node)
        self._clock = clock
        self._fault_wrap = fault_wrap
        self._next_attempt = 0.0
        self.reconnects_total = 0
        self._sock = None
        self._conn = None
        self._connect()

    def _connect(self) -> None:
        sock, conn = connect_client(self.node, self._address, self._certdir,
                                    self._client_cn)
        if self._fault_wrap is not None:
            # Chaos harness hook (dissemination/faults.py): interpose a
            # fault-injecting wrapper AFTER the authenticated handshake so
            # injected resets/partial writes exercise the steady state.
            sock = self._fault_wrap(sock)
            conn.sock = sock
        self._sock, self._conn = sock, conn

    @property
    def connected(self) -> bool:
        return self._sock is not None

    def _mark_dead(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._sock = None
        self._conn = None
        if self._reconnect_enabled:
            self._next_attempt = self._clock() + self._backoff.next_delay()

    def _try_reconnect(self) -> bool:
        """One re-dial attempt if the backoff window has elapsed."""
        if not self._reconnect_enabled:
            return False
        if self._clock() < self._next_attempt:
            return False
        try:
            self._connect()
        except (OSError, ssl.SSLError, ConnectionError):
            self._next_attempt = self._clock() + self._backoff.next_delay()
            return False
        self._backoff.reset()
        self.reconnects_total += 1
        return True

    def close(self) -> None:
        self._reconnect_enabled = False
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        # Cleared like _mark_dead: a closed client must answer
        # connected=False and never re-enter a fleet select set.
        self._sock = None
        self._conn = None


class NetAgent(ReconnectingClient):
    """Agent-side client: TLS-verified event stream into an
    AgentPolicyController + upstream realization reports.

    Failure model: a dead socket (reset, orderly close, send failure) is
    detected on the next pump/report, the connection is torn down, and the
    agent re-dials per ReconnectingClient.  The server replays the node's
    snapshot on re-handshake between resync markers; the local
    AgentPolicyController reconciles that snapshot so objects deleted
    while disconnected are retracted."""

    def __init__(self, node: str, address, certdir: str, datapath,
                 client_cn: Optional[str] = None, *,
                 reconnect: bool = True, backoff: Optional[Backoff] = None,
                 clock=time.monotonic, fault_wrap=None):
        from ..agent.controller import AgentPolicyController

        self.resyncs_total = 0
        self.agent = AgentPolicyController(node, datapath)
        self._init_wire(node, address, certdir, client_cn=client_cn,
                        reconnect=reconnect, backoff=backoff, clock=clock,
                        fault_wrap=fault_wrap)

    def pump(self, wait: float = 0.5) -> int:
        if self._sock is None and not self._try_reconnect():
            return 0
        n = 0
        try:
            frames = self._conn.recv_ready(first_wait=wait)
        except (OSError, ssl.SSLError, ValueError):
            self._mark_dead()
            return 0
        for frame in frames:
            if "ev" in frame:
                self.agent.handle_event(serde.decode_event(frame["ev"]))
                n += 1
            elif frame.get("ctl") == "resync_begin":
                self.agent.begin_resync()
            elif frame.get("ctl") == "resync_end":
                self.agent.end_resync()
                self.resyncs_total += 1
        if self._conn.closed:
            self._mark_dead()
        return n

    def sync_and_report(self) -> dict:
        """Reconcile into the datapath, then send the realization report
        upstream (the UpdateStatus RPC over the same mTLS channel).  The
        datapath sync happens regardless of wire health; a failed report
        send just marks the connection dead for the reconnect path."""
        self.agent.sync()
        realized = self.agent.realized_generations()
        if self._sock is None and not self._try_reconnect():
            return realized
        try:
            self._sock.setblocking(True)
            self._conn.send({"status": realized})
            self._sock.setblocking(False)
        except (OSError, ssl.SSLError):
            self._mark_dead()
        return realized
