"""Network dissemination wire: the controller<->agent channel over REAL
mutual-TLS TCP sockets.

The reference's control plane is a protobuf watch over HTTPS with CA-signed
certificates on both ends (/root/reference/pkg/apiserver/apiserver.go:97-99,
pkg/apiserver/certificate/; agents authenticate and verify the server).
This module materializes that wire for the TPU build:

  * X.509 PKI (make_ca / issue_cert, real certificates via `cryptography`)
    — the wire-level counterpart of the semantic CSR flow in
    controller/certificates.py;
  * DisseminationServer: accepts mTLS connections (client certs REQUIRED
    and verified against the CA), registers a queued span watcher per
    agent and streams serde-encoded WatchEvents (newline-JSON — the
    protobuf-role codec of dissemination/serde.py);
  * the SAME connection carries the agent's realization-status reports
    upstream ({"status": {...}} frames -> StatusAggregator), the
    UpdateStatus RPC of status_controller.go:140;
  * NetAgent: the agent-side client feeding an AgentPolicyController.

Delivery is explicitly pumped (server.pump() / agent.pump()) so tests are
deterministic; the sockets, handshakes and certificate verification are
real.  A client without a CA-signed certificate cannot connect; an agent
refusing the server certificate cannot be fed.
"""

from __future__ import annotations

import datetime
import ipaddress
import json
import os
import select
import socket
import ssl
import threading
from typing import Optional

from . import serde
from .store import RamStore, Watcher


# -- PKI ---------------------------------------------------------------------


def _write(path: str, data: bytes) -> None:
    with open(path, "wb") as f:
        f.write(data)


def make_ca(dirpath: str, cn: str = "antrea-tpu-ca") -> None:
    """Create ca.crt/ca.key under dirpath (idempotent)."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    os.makedirs(dirpath, exist_ok=True)
    if os.path.exists(os.path.join(dirpath, "ca.crt")):
        return
    key = ec.generate_private_key(ec.SECP256R1())
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, cn)])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name).issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=365))
        .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                       critical=True)
        .sign(key, hashes.SHA256())
    )
    _write(os.path.join(dirpath, "ca.key"), key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.TraditionalOpenSSL,
        serialization.NoEncryption()))
    _write(os.path.join(dirpath, "ca.crt"),
           cert.public_bytes(serialization.Encoding.PEM))


def issue_cert(dirpath: str, cn: str, *, server: bool = False) -> tuple[str, str]:
    """CA-sign a cert for cn -> (cert path, key path).  Server certs get
    the 127.0.0.1/localhost SANs the client verifies against."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    with open(os.path.join(dirpath, "ca.key"), "rb") as f:
        ca_key = serialization.load_pem_private_key(f.read(), None)
    with open(os.path.join(dirpath, "ca.crt"), "rb") as f:
        ca_cert = x509.load_pem_x509_certificate(f.read())
    key = ec.generate_private_key(ec.SECP256R1())
    now = datetime.datetime.now(datetime.timezone.utc)
    b = (
        x509.CertificateBuilder()
        .subject_name(x509.Name(
            [x509.NameAttribute(NameOID.COMMON_NAME, cn)]))
        .issuer_name(ca_cert.subject)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=30))
    )
    if server:
        b = b.add_extension(x509.SubjectAlternativeName([
            x509.DNSName("localhost"),
            x509.IPAddress(ipaddress.ip_address("127.0.0.1")),
        ]), critical=False)
    cert = b.sign(ca_key, hashes.SHA256())
    cp = os.path.join(dirpath, f"{cn}.crt")
    kp = os.path.join(dirpath, f"{cn}.key")
    _write(kp, key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.TraditionalOpenSSL,
        serialization.NoEncryption()))
    _write(cp, cert.public_bytes(serialization.Encoding.PEM))
    return cp, kp


# -- framing -----------------------------------------------------------------


class _LineConn:
    """Newline-JSON framing over a (TLS) socket, nonblocking reads."""

    def __init__(self, sock):
        self.sock = sock
        self._buf = b""

    def send(self, obj: dict) -> None:
        self.sock.sendall(
            (json.dumps(obj, separators=(",", ":")) + "\n").encode())

    def recv_ready(self, first_wait: float = 0.0) -> list[dict]:
        """Drain whatever is available -> decoded frames.  first_wait
        bounds the wait for the FIRST chunk (loopback TLS records can land
        an instant after the peer's sendall returns); subsequent reads
        never block."""
        out = []
        wait = first_wait
        while True:
            r, _, _ = select.select([self.sock], [], [], wait)
            wait = 0.0
            if not r:
                # TLS may hold decrypted bytes even when the raw socket is
                # quiet; poll the SSL buffer too.
                if getattr(self.sock, "pending", lambda: 0)() == 0:
                    break
            try:
                chunk = self.sock.recv(65536)
            except ssl.SSLWantReadError:
                break
            if not chunk:
                break  # peer closed
            self._buf += chunk
        while b"\n" in self._buf:
            line, self._buf = self._buf.split(b"\n", 1)
            if line:
                out.append(json.loads(line.decode()))
        return out


def iter_json_lines(sock, max_line: int = 1 << 20):
    """Yield decoded JSON objects from newline-framed lines on a BLOCKING
    socket until EOF — the one blocking-side framing loop (the
    non-blocking twin is _LineConn.recv_ready; the 64KiB hello bound in
    _handshake_inner is this same discipline).  Malformed JSON yields a
    ValueError to the caller; an oversized line raises."""
    buf = b""
    while True:
        chunk = sock.recv(65536)
        if not chunk:
            return
        buf += chunk
        if len(buf) > max_line:
            raise ValueError(f"frame exceeds {max_line} bytes")
        while b"\n" in buf:
            line, buf = buf.split(b"\n", 1)
            if line:
                yield json.loads(line.decode())


def recv_one_json(sock, buf: bytes, max_line: int = 1 << 20):
    """Blocking read of ONE newline-framed JSON object -> (obj, rest) —
    the client-side half of iter_json_lines' framing."""
    while b"\n" not in buf:
        chunk = sock.recv(65536)
        if not chunk:
            raise ConnectionError("peer closed the socket")
        buf += chunk
        if len(buf) > max_line:
            raise ValueError(f"frame exceeds {max_line} bytes")
    line, rest = buf.split(b"\n", 1)
    return json.loads(line.decode()), rest


# -- server ------------------------------------------------------------------


class DisseminationServer:
    """mTLS dissemination endpoint in front of a RamStore."""

    def __init__(self, store: RamStore, certdir: str, *,
                 host: str = "127.0.0.1", port: int = 0,
                 status_aggregator=None):
        self._store = store
        self._status = status_aggregator
        cert, key = issue_cert(certdir, "controller", server=True)
        self._ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        self._ctx.load_cert_chain(cert, key)
        self._ctx.load_verify_locations(os.path.join(certdir, "ca.crt"))
        self._ctx.verify_mode = ssl.CERT_REQUIRED  # mutual TLS
        self._lsock = socket.create_server((host, port))
        self.address = self._lsock.getsockname()
        # node -> (conn, watcher, seq); handshakes land here from the
        # acceptor.  seq is the ACCEPT order: concurrent handshake threads
        # may finish out of order, and a stale connection finishing last
        # must never evict the agent's newer live one.
        self._conns: dict[str, tuple[_LineConn, Watcher, int]] = {}
        self._lock = threading.Lock()
        self._closing = False
        self._accept_seq = 0
        # Any peer that can reach the listener gets a handshake thread
        # BEFORE authenticating; bound them so a raw-TCP flood cannot
        # accumulate threads without limit.
        self._handshakes = threading.Semaphore(32)
        # TLS handshakes are inherently concurrent with the client's
        # connect, so accept+handshake+hello run on a daemon thread (the
        # reference's apiserver accepts concurrently too); event delivery
        # and status consumption stay on the explicit pump() for
        # deterministic tests.
        self._acceptor = threading.Thread(target=self._accept_loop,
                                          daemon=True)
        self._acceptor.start()

    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                raw, _ = self._lsock.accept()
            except OSError:
                return  # listener closed
            # A slow or malicious peer (even certless) must not stall
            # registration of every other agent for its 5s timeout:
            # handshake+hello run on a short-lived per-connection thread;
            # only registration takes the lock.
            if not self._handshakes.acquire(blocking=False):
                raw.close()  # at capacity: shed before spending a thread
                continue
            self._accept_seq += 1
            threading.Thread(
                target=self._handshake, args=(raw, self._accept_seq),
                daemon=True,
            ).start()

    def _handshake(self, raw, seq: int) -> None:
        try:
            self._handshake_inner(raw, seq)
        finally:
            self._handshakes.release()

    def _handshake_inner(self, raw, seq: int) -> None:
        try:
            raw.settimeout(5.0)
            tls = self._ctx.wrap_socket(raw, server_side=True)
        except (ssl.SSLError, OSError):
            raw.close()  # unauthenticated peer: handshake rejected
            return
        try:
            buf = b""
            while b"\n" not in buf:
                if len(buf) > 65536:
                    # A certified peer streaming newline-less bytes must
                    # not grow the hello buffer without bound (each recv
                    # resets the per-op timeout): reject.
                    raise ValueError("hello line exceeds 64KiB")
                chunk = tls.recv(4096)
                if not chunk:
                    break
                buf += chunk
            if not buf:
                tls.close()
                return
            line, rest = buf.split(b"\n", 1)
            hello = json.loads(line.decode())
            node = hello["hello"]
            # Bind the VERIFIED certificate identity to the claimed
            # node: a CA-signed cert for agent-X must not register as
            # node Y (the mutual-TLS authentication contract — antrea's
            # apiserver authenticates agents by identity, not just by
            # holding any cluster cert).
            cert = tls.getpeercert()
            cns = [v for rdn in cert.get("subject", ())
                   for k, v in rdn if k == "commonName"]
            if cns != [f"agent-{node}"]:
                raise ValueError(
                    f"cert identity {cns} does not match node {node!r}"
                )
        except (ssl.SSLError, OSError, ValueError, KeyError, TypeError):
            # Malformed/stalled hello (TypeError: valid JSON that is not
            # an object): close the HANDSHAKEN socket (its fd moved out
            # of `raw` at wrap time).
            tls.close()
            return
        tls.settimeout(None)
        tls.setblocking(False)
        conn = _LineConn(tls)
        # Frames coalesced into the hello's TLS record (e.g. an eager
        # status report) must not be dropped.
        conn._buf = rest
        with self._lock:
            if self._closing:
                # close() already snapshotted _conns: registering now
                # would leak an un-stopped watcher that buffers store
                # events forever plus an open TLS socket.
                tls.close()
                return
            old = self._conns.get(node)
            if old is not None and old[2] > seq:
                # A NEWER connection for this node already registered
                # (this thread's hello was slower): this one is stale —
                # evicting the live registration would stream to a socket
                # the agent abandoned.
                tls.close()
                return
            self._conns[node] = (conn, self._store.watch_queue(node), seq)
        if old is not None:
            # Reconnect: retire the previous registration — an
            # un-stopped watcher would buffer events forever.
            old[1].stop()
            try:
                old[0].sock.close()
            except OSError:
                pass

    def wait_connected(self, n: int, timeout: float = 5.0) -> None:
        """Block until n agents have completed handshake+hello (the
        acceptor thread registers them asynchronously)."""
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if len(self._conns) >= n:
                    return
            time.sleep(0.01)
        raise TimeoutError(f"{n} agents not connected within {timeout}s")

    def pump(self) -> int:
        """Stream queued events, consume status reports -> events shipped."""
        shipped = 0
        with self._lock:
            conns = list(self._conns.items())
        dead: list[tuple[str, _LineConn]] = []
        live = []
        for node, (conn, watcher, _seq) in conns:
            try:
                # Bounded send: an agent that stopped reading (full TCP
                # buffer) must not block the pump forever — a timed-out
                # sendall raises and the agent is pruned as dead.
                conn.sock.settimeout(2.0)
                for ev in watcher.drain():
                    conn.send({"ev": serde.encode_event(ev)})
                    shipped += 1
                conn.sock.setblocking(False)
                live.append((node, conn))
            except (OSError, ssl.SSLError, ValueError):
                # One dead agent must not halt dissemination to the rest:
                # prune it (its events stay in the STORE's history; a
                # reconnect replays).
                dead.append((node, conn))
        # ONE bounded select across every agent socket (not 50ms per idle
        # connection serially), then drain only the ready/buffered ones.
        if live:
            try:
                ready, _, _ = select.select([c.sock for _n, c in live],
                                            [], [], 0.05)
            except (OSError, ValueError):
                ready = [c.sock for _n, c in live]  # sort out per-conn below
            ready_ids = {id(s) for s in ready}
            for node, conn in live:
                try:
                    if (id(conn.sock) in ready_ids or conn._buf
                            or conn.sock.pending()):
                        for frame in conn.recv_ready():
                            if "status" in frame and self._status is not None:
                                self._status.update_node_statuses(
                                    node, frame["status"])
                except (OSError, ssl.SSLError, ValueError):
                    dead.append((node, conn))
        for node, failed_conn in dead:
            with self._lock:
                entry = self._conns.get(node)
                # Identity-aware prune: if the node RECONNECTED between
                # our snapshot and now, the registered entry is a fresh
                # healthy connection — tearing it down by name would
                # disconnect a live agent.
                if entry is None or entry[0] is not failed_conn:
                    entry = None
                else:
                    del self._conns[node]
            if entry is not None:
                entry[1].stop()
                try:
                    entry[0].sock.close()
                except OSError:
                    pass
            else:
                try:
                    failed_conn.sock.close()
                except OSError:
                    pass
        return shipped

    def close(self) -> None:
        with self._lock:
            # Flag + snapshot under ONE lock hold: any in-flight
            # _handshake thread either registered before this (and is in
            # the snapshot) or will observe _closing and self-close.
            self._closing = True
            conns = list(self._conns.values())
        for conn, watcher, _seq in conns:
            watcher.stop()
            conn.sock.close()
        self._lsock.close()
        self._acceptor.join(timeout=2)


# -- agent client ------------------------------------------------------------


def connect_client(node: str, address, certdir: str,
                   client_cn: Optional[str] = None):
    """The ONE agent-side mTLS bring-up (cert issue, TLS connect, hello,
    non-blocking socket) shared by NetAgent and the fleet's watch-only
    clients — client-side wire changes live here exactly once.
    -> (tls socket, _LineConn)."""
    cert, key = issue_cert(certdir, client_cn or f"agent-{node}")
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.load_cert_chain(cert, key)
    ctx.load_verify_locations(os.path.join(certdir, "ca.crt"))
    raw = socket.create_connection(tuple(address))
    sock = ctx.wrap_socket(raw, server_hostname="localhost")
    conn = _LineConn(sock)
    conn.send({"hello": node})
    sock.setblocking(False)
    return sock, conn


class NetAgent:
    """Agent-side client: TLS-verified event stream into an
    AgentPolicyController + upstream realization reports."""

    def __init__(self, node: str, address, certdir: str, datapath,
                 client_cn: Optional[str] = None):
        from ..agent.controller import AgentPolicyController

        self._sock, self._conn = connect_client(node, address, certdir,
                                                client_cn)
        self.node = node
        self.agent = AgentPolicyController(node, datapath)

    def pump(self, wait: float = 0.5) -> int:
        n = 0
        for frame in self._conn.recv_ready(first_wait=wait):
            if "ev" in frame:
                self.agent.handle_event(serde.decode_event(frame["ev"]))
                n += 1
        return n

    def sync_and_report(self) -> dict:
        """Reconcile into the datapath, then send the realization report
        upstream (the UpdateStatus RPC over the same mTLS channel)."""
        self.agent.sync()
        realized = self.agent.realized_generations()
        self._sock.setblocking(True)
        self._conn.send({"status": realized})
        self._sock.setblocking(False)
        return realized

    def close(self) -> None:
        self._sock.close()
