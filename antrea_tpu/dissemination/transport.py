"""Process-boundary dissemination: serialized watch stream to agent procs.

The reference's controller->agent plane is a protobuf watch over HTTPS
(/root/reference/docs/design/architecture.md:50-64; per-watcher channel in
pkg/apiserver/storage/ram/store.go:230).  This module realizes the same
architecture with the pieces this build owns: WatchEvents serialized by
dissemination/serde.py (the protobuf analog) stream over an OS pipe to an
agent running in a REAL subprocess (antrea_tpu.dissemination.agent_proc),
which assembles its local PolicySet from the wire alone and drives its own
Datapath.  Control messages on the same framed stream let tests probe the
remote datapath (step/trace) and read back verdicts — the differential
harness crosses the process boundary.

Framing: newline-delimited JSON (serde.event_to_wire).  Event messages are
{"ev": <encoded WatchEvent>}; control messages are {"cmd": ...}; responses
are one JSON line each.  Delivery is pumped from a QUEUED store watcher
(RamStore.watch_queue), so a slow or dead agent never blocks the
controller — pump() moves whatever is buffered, in order.

NOTE: the PRIMARY dissemination transport is the authenticated mTLS
network wire (dissemination/netwire.py — the apiserver.go:97-99 analog),
which the fleet (simulator/fleet.py transport="netwire") and the
end-to-end reachability tests ride.  This pipe transport remains as a
FALLBACK harness for subprocess isolation tests where PKI setup would
add nothing (the framing and serde layers are shared with the wire).
"""

from __future__ import annotations

import json
import os
import select
import subprocess
import sys
import tempfile
import time
from typing import Optional

from . import serde
from .store import RamStore, Watcher

# bounded-buffer analysis-pass contract (analysis/bounded_buffer.py): every
# buffer-shaped attribute in this package declares its cap.
BUFFER_CAPS = {
    "SubprocessAgent._rdbuf": "holds at most one partial response line; "
                              "_read_response_line consumes a complete "
                              "line per RPC under the RPC deadline",
}


class AgentDiedError(RuntimeError):
    """The agent subprocess is gone (crashed, killed, or wedged past the
    RPC deadline).  Carries what the operator needs to diagnose it without
    attaching a debugger: the child's exit code and its stderr tail."""

    def __init__(self, node: str, exit_code: Optional[int],
                 stderr_tail: str, context: str = ""):
        self.node = node
        self.exit_code = exit_code
        self.stderr_tail = stderr_tail
        detail = f"agent {node} died (exit code {exit_code})"
        if context:
            detail += f" {context}"
        if stderr_tail:
            detail += f"; stderr tail:\n{stderr_tail}"
        super().__init__(detail)


class SubprocessAgent:
    """Parent-side handle: one agent process consuming one node's stream.

    Failure model: a dead or wedged child surfaces as AgentDiedError (with
    exit code + stderr tail) from send_event/pump/_rpc instead of a bare
    BrokenPipeError or an indefinite readline block; _rpc enforces a read
    deadline (rpc_timeout) and kills a wedged child rather than hanging
    the controller."""

    def __init__(
        self,
        node: str,
        store: Optional[RamStore] = None,
        *,
        datapath_type: str = "oracle",
        flow_slots: int = 1 << 12,
        aff_slots: int = 1 << 8,
        rpc_timeout: float = 60.0,
        watcher_max_pending: Optional[int] = None,
    ):
        self.node = node
        self._rpc_timeout = rpc_timeout
        # The child's stderr lands in a temp file (not a pipe we would
        # have to drain) so AgentDiedError can carry its tail.
        self._stderr = tempfile.TemporaryFile()
        self._rdbuf = b""
        env = dict(os.environ)
        # The child never needs an accelerator; keep it hermetic like the
        # test suite (tests/conftest.py rationale).
        env.setdefault("JAX_PLATFORMS", "cpu")
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        )))
        self._proc = subprocess.Popen(
            [
                sys.executable, "-m", "antrea_tpu.dissemination.agent_proc",
                "--node", node,
                "--datapath", datapath_type,
                "--flow-slots", str(flow_slots),
                "--aff-slots", str(aff_slots),
            ],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=self._stderr,
            cwd=repo_root,
            env=env,
        )
        self._store = store
        self._watcher: Optional[Watcher] = None
        if store is not None:
            self._watcher = store.watch_queue(
                node, max_pending=watcher_max_pending)

    # -- death diagnostics ---------------------------------------------------

    def _stderr_tail(self, limit: int = 4096) -> str:
        try:
            self._stderr.flush()
            size = self._stderr.seek(0, os.SEEK_END)
            self._stderr.seek(max(0, size - limit))
            return self._stderr.read().decode(errors="replace").strip()
        except (OSError, ValueError):
            return ""

    def _died(self, context: str) -> AgentDiedError:
        """Reap the (dead or dying) child -> typed error with its exit
        code and stderr tail.  Never blocks long: a pipe already broke or
        we decided to kill, so the wait is bounded."""
        if self._proc.poll() is None:
            try:
                self._proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._proc.kill()
                try:
                    self._proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    pass
        return AgentDiedError(self.node, self._proc.poll(),
                             self._stderr_tail(), context)

    # -- stream pump ---------------------------------------------------------

    def pump(self) -> int:
        """Ship everything buffered on the store watcher to the agent;
        returns the number of events sent.  A watcher that overflowed its
        bounded queue is served a full resync bracketed in ctl markers
        (the same re-list protocol the netwire server speaks)."""
        if self._watcher is None:
            return 0
        if self._watcher.needs_resync:
            self._send_frame({"ctl": "resync_begin"})
            events = list(self._store.resync(self._watcher))
            for ev in events:
                self.send_event(ev)
            self._send_frame({"ctl": "resync_end"})
            return len(events)
        events = self._watcher.drain()
        for ev in events:
            self.send_event(ev)
        return len(events)

    def _send_frame(self, frame: dict) -> None:
        line = json.dumps(frame, separators=(",", ":")) + "\n"
        try:
            self._proc.stdin.write(line.encode())
            self._proc.stdin.flush()
        except (BrokenPipeError, OSError) as e:
            # The child died between frames (kill mid-stream): reap it and
            # raise the typed error instead of a bare BrokenPipeError.
            raise self._died(f"writing frame: {e}") from e

    def send_event(self, ev) -> None:
        self._send_frame({"ev": serde.encode_event(ev)})

    # -- control RPCs --------------------------------------------------------

    def _read_response_line(self) -> bytes:
        """One newline-framed response from the child's stdout, under the
        RPC deadline.  Reads the raw fd (os.read + own buffer — a buffered
        readline could block past the deadline on a partial line); a
        wedged child is killed and surfaced as AgentDiedError."""
        fd = self._proc.stdout.fileno()
        deadline = time.monotonic() + self._rpc_timeout
        while b"\n" not in self._rdbuf:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                self._proc.kill()
                raise self._died(
                    f"wedged: no RPC response within {self._rpc_timeout}s")
            r, _, _ = select.select([fd], [], [], min(remaining, 0.25))
            if not r:
                if self._proc.poll() is not None:
                    raise self._died("while awaiting RPC response")
                continue
            chunk = os.read(fd, 65536)
            if not chunk:
                raise self._died("stdout closed awaiting RPC response")
            self._rdbuf += chunk
        line, self._rdbuf = self._rdbuf.split(b"\n", 1)
        return line

    def _rpc(self, msg: dict) -> dict:
        self._send_frame(msg)
        resp = json.loads(self._read_response_line().decode())
        if "error" in resp:
            raise RuntimeError(f"agent {self.node}: {resp['error']}")
        return resp

    def sync(self) -> dict:
        """Reconcile received state into the agent's datapath.  The response
        carries "realized" ({policy uid: realized spec generation}) — relay
        it to a StatusAggregator via update_node_statuses(node, realized)
        to close the realization-status loop across the process boundary."""
        return self._rpc({"cmd": "sync"})

    def step(self, batch, now: int) -> dict:
        """Run a packet batch through the agent's datapath; verdict lists."""
        return self._rpc({
            "cmd": "step",
            "now": now,
            "packets": {
                "src_ip": [int(x) for x in batch.src_ip],
                "dst_ip": [int(x) for x in batch.dst_ip],
                "proto": [int(x) for x in batch.proto],
                "src_port": [int(x) for x in batch.src_port],
                "dst_port": [int(x) for x in batch.dst_port],
            },
        })

    def state_summary(self) -> dict:
        return self._rpc({"cmd": "summary"})

    def stop(self) -> None:
        if self._watcher is not None:
            self._watcher.stop()
        if self._proc.poll() is None:
            try:
                self._rpc({"cmd": "exit"})
            except (RuntimeError, OSError, ValueError):
                pass  # child already dead/closed: fall through to reap
            try:
                self._proc.stdin.close()
            except OSError:
                pass
            try:
                self._proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self._proc.kill()
                self._proc.wait(timeout=10)
        # Pipes close even when the child was ALREADY dead (the
        # AgentDiedError path skips the branch above): a controller
        # respawning agents must not leak two fds per death.
        for pipe in (self._proc.stdin, self._proc.stdout):
            try:
                pipe.close()
            except OSError:
                pass
        try:
            self._stderr.close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
