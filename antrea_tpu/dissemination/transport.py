"""Process-boundary dissemination: serialized watch stream to agent procs.

The reference's controller->agent plane is a protobuf watch over HTTPS
(/root/reference/docs/design/architecture.md:50-64; per-watcher channel in
pkg/apiserver/storage/ram/store.go:230).  This module realizes the same
architecture with the pieces this build owns: WatchEvents serialized by
dissemination/serde.py (the protobuf analog) stream over an OS pipe to an
agent running in a REAL subprocess (antrea_tpu.dissemination.agent_proc),
which assembles its local PolicySet from the wire alone and drives its own
Datapath.  Control messages on the same framed stream let tests probe the
remote datapath (step/trace) and read back verdicts — the differential
harness crosses the process boundary.

Framing: newline-delimited JSON (serde.event_to_wire).  Event messages are
{"ev": <encoded WatchEvent>}; control messages are {"cmd": ...}; responses
are one JSON line each.  Delivery is pumped from a QUEUED store watcher
(RamStore.watch_queue), so a slow or dead agent never blocks the
controller — pump() moves whatever is buffered, in order.

NOTE: the PRIMARY dissemination transport is the authenticated mTLS
network wire (dissemination/netwire.py — the apiserver.go:97-99 analog),
which the fleet (simulator/fleet.py transport="netwire") and the
end-to-end reachability tests ride.  This pipe transport remains as a
FALLBACK harness for subprocess isolation tests where PKI setup would
add nothing (the framing and serde layers are shared with the wire).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from typing import Optional

from . import serde
from .store import RamStore, Watcher


class SubprocessAgent:
    """Parent-side handle: one agent process consuming one node's stream."""

    def __init__(
        self,
        node: str,
        store: Optional[RamStore] = None,
        *,
        datapath_type: str = "oracle",
        flow_slots: int = 1 << 12,
        aff_slots: int = 1 << 8,
    ):
        self.node = node
        env = dict(os.environ)
        # The child never needs an accelerator; keep it hermetic like the
        # test suite (tests/conftest.py rationale).
        env.setdefault("JAX_PLATFORMS", "cpu")
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        )))
        self._proc = subprocess.Popen(
            [
                sys.executable, "-m", "antrea_tpu.dissemination.agent_proc",
                "--node", node,
                "--datapath", datapath_type,
                "--flow-slots", str(flow_slots),
                "--aff-slots", str(aff_slots),
            ],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            cwd=repo_root,
            env=env,
        )
        self._watcher: Optional[Watcher] = None
        if store is not None:
            self._watcher = store.watch_queue(node)

    # -- stream pump ---------------------------------------------------------

    def pump(self) -> int:
        """Ship everything buffered on the store watcher to the agent;
        returns the number of events sent."""
        if self._watcher is None:
            return 0
        events = self._watcher.drain()
        for ev in events:
            self.send_event(ev)
        return len(events)

    def send_event(self, ev) -> None:
        line = json.dumps(
            {"ev": serde.encode_event(ev)}, separators=(",", ":")
        ) + "\n"
        self._proc.stdin.write(line.encode())
        self._proc.stdin.flush()

    # -- control RPCs --------------------------------------------------------

    def _rpc(self, msg: dict) -> dict:
        self._proc.stdin.write(
            (json.dumps(msg, separators=(",", ":")) + "\n").encode()
        )
        self._proc.stdin.flush()
        line = self._proc.stdout.readline()
        if not line:
            raise RuntimeError(f"agent {self.node} died (no response)")
        resp = json.loads(line.decode())
        if "error" in resp:
            raise RuntimeError(f"agent {self.node}: {resp['error']}")
        return resp

    def sync(self) -> dict:
        """Reconcile received state into the agent's datapath.  The response
        carries "realized" ({policy uid: realized spec generation}) — relay
        it to a StatusAggregator via update_node_statuses(node, realized)
        to close the realization-status loop across the process boundary."""
        return self._rpc({"cmd": "sync"})

    def step(self, batch, now: int) -> dict:
        """Run a packet batch through the agent's datapath; verdict lists."""
        return self._rpc({
            "cmd": "step",
            "now": now,
            "packets": {
                "src_ip": [int(x) for x in batch.src_ip],
                "dst_ip": [int(x) for x in batch.dst_ip],
                "proto": [int(x) for x in batch.proto],
                "src_port": [int(x) for x in batch.src_port],
                "dst_port": [int(x) for x in batch.dst_port],
            },
        })

    def state_summary(self) -> dict:
        return self._rpc({"cmd": "summary"})

    def stop(self) -> None:
        if self._watcher is not None:
            self._watcher.stop()
        if self._proc.poll() is None:
            try:
                self._rpc({"cmd": "exit"})
            except (RuntimeError, OSError, ValueError):
                pass  # child already dead/closed: fall through to reap
            try:
                self._proc.stdin.close()
            except OSError:
                pass
            try:
                self._proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self._proc.kill()
                self._proc.wait(timeout=10)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
