"""Wire serialization for the control-plane API types.

The analog of the reference's protobuf codecs for the controlplane API
group (/root/reference/pkg/apis/controlplane — versioned v1beta2 objects,
serialized protobuf over the watch connection, architecture.md:63-64).
JSON is the wire format here — the schema discipline is the same: explicit
field maps per type, a version tag, and round-trip tests.  Everything that
crosses a process boundary (dissemination transport) or survives a restart
(datapath snapshots, agent filestore) goes through these functions.
"""

from __future__ import annotations

import json

from ..apis import controlplane as cp
from ..apis.service import Endpoint, ServiceEntry
from ..compiler.ir import PolicySet
from ..controller.networkpolicy import WatchEvent

WIRE_VERSION = 1


# -- controlplane objects ----------------------------------------------------


def _member(m: cp.GroupMember) -> dict:
    out = {"ip": m.ip, "node": m.node, "ns": m.pod_namespace, "pod": m.pod_name}
    if m.ports:
        # Named ports (types.go:87-88): [[name, port, protocol], ...]
        out["ports"] = [list(t) for t in m.ports]
    return out


def _member_from(d: dict) -> cp.GroupMember:
    return cp.GroupMember(
        ip=d["ip"], node=d.get("node", ""),
        pod_namespace=d.get("ns", ""), pod_name=d.get("pod", ""),
        ports=tuple(
            (str(n), int(pt), int(pr)) for n, pt, pr in d.get("ports", ())
        ),
    )


def _block(b: cp.IPBlock) -> dict:
    return {"cidr": b.cidr, "except": list(b.excepts)}


def _block_from(d: dict) -> cp.IPBlock:
    return cp.IPBlock(cidr=d["cidr"], excepts=tuple(d.get("except", ())))


def _peer(p: cp.NetworkPolicyPeer) -> dict:
    out = {
        "addressGroups": list(p.address_groups),
        "ipBlocks": [_block(b) for b in p.ip_blocks],
    }
    if p.to_services:
        # controlplane ServiceReference list (types.go ToServices wire form).
        out["toServices"] = [
            {"name": sr.name, "namespace": sr.namespace}
            for sr in p.to_services
        ]
    return out


def _peer_from(d: dict) -> cp.NetworkPolicyPeer:
    return cp.NetworkPolicyPeer(
        address_groups=list(d.get("addressGroups", ())),
        ip_blocks=[_block_from(b) for b in d.get("ipBlocks", ())],
        to_services=[
            cp.ServiceReference(name=sr["name"],
                                namespace=sr.get("namespace", "default"))
            for sr in d.get("toServices", ())
        ],
    )


def _service(s: cp.Service) -> dict:
    out = {"protocol": s.protocol, "port": s.port, "endPort": s.end_port}
    if s.port_name:
        out["portName"] = s.port_name  # IntOrString string form
    if s.icmp_type is not None:
        out["icmpType"] = s.icmp_type  # types.go:311 ICMPType/ICMPCode
        if s.icmp_code is not None:
            out["icmpCode"] = s.icmp_code
    return out


def _service_from(d: dict) -> cp.Service:
    return cp.Service(
        protocol=d.get("protocol"), port=d.get("port"),
        end_port=d.get("endPort"), port_name=d.get("portName", ""),
        icmp_type=d.get("icmpType"), icmp_code=d.get("icmpCode"),
    )


def _rule(r: cp.NetworkPolicyRule) -> dict:
    return {
        "direction": r.direction.value,
        "from": _peer(r.from_peer),
        "to": _peer(r.to_peer),
        "services": [_service(s) for s in r.services],
        "action": r.action.value,
        "priority": r.priority,
        "name": r.name,
        "appliedToGroups": list(r.applied_to_groups),
        "l7Protocols": list(r.l7_protocols),
    }


def _rule_from(d: dict) -> cp.NetworkPolicyRule:
    return cp.NetworkPolicyRule(
        direction=cp.Direction(d["direction"]),
        from_peer=_peer_from(d.get("from", {})),
        to_peer=_peer_from(d.get("to", {})),
        services=[_service_from(s) for s in d.get("services", ())],
        action=cp.RuleAction(d.get("action", "Allow")),
        priority=d.get("priority", -1),
        name=d.get("name", ""),
        applied_to_groups=list(d.get("appliedToGroups", ())),
        l7_protocols=list(d.get("l7Protocols", ())),
    )


def encode_policy(p: cp.NetworkPolicy) -> dict:
    return {
        "uid": p.uid,
        "name": p.name,
        "namespace": p.namespace,
        "type": p.type.value,
        "rules": [_rule(r) for r in p.rules],
        "appliedToGroups": list(p.applied_to_groups),
        "policyTypes": [d.value for d in p.policy_types],
        "tierPriority": p.tier_priority,
        "priority": p.priority,
        "generation": p.generation,
    }


def decode_policy(d: dict) -> cp.NetworkPolicy:
    return cp.NetworkPolicy(
        uid=d["uid"],
        name=d.get("name", ""),
        namespace=d.get("namespace", ""),
        type=cp.NetworkPolicyType(d.get("type", "K8sNetworkPolicy")),
        rules=[_rule_from(r) for r in d.get("rules", ())],
        applied_to_groups=list(d.get("appliedToGroups", ())),
        policy_types=[cp.Direction(x) for x in d.get("policyTypes", ())],
        tier_priority=d.get("tierPriority"),
        priority=d.get("priority"),
        generation=int(d.get("generation", 0)),
    )


def encode_address_group(g: cp.AddressGroup) -> dict:
    return {
        "name": g.name,
        "members": [_member(m) for m in g.members],
        "ipBlocks": [_block(b) for b in g.ip_blocks],
    }


def decode_address_group(d: dict) -> cp.AddressGroup:
    return cp.AddressGroup(
        name=d["name"],
        members=[_member_from(m) for m in d.get("members", ())],
        ip_blocks=[_block_from(b) for b in d.get("ipBlocks", ())],
    )


def encode_applied_to_group(g: cp.AppliedToGroup) -> dict:
    return {"name": g.name, "members": [_member(m) for m in g.members]}


def decode_applied_to_group(d: dict) -> cp.AppliedToGroup:
    return cp.AppliedToGroup(
        name=d["name"], members=[_member_from(m) for m in d.get("members", ())]
    )


_OBJ_CODECS = {
    "NetworkPolicy": (encode_policy, decode_policy),
    "AddressGroup": (encode_address_group, decode_address_group),
    "AppliedToGroup": (encode_applied_to_group, decode_applied_to_group),
}


# -- PolicySet + services (snapshot surface) ---------------------------------


def encode_policy_set(ps: PolicySet) -> dict:
    return {
        "policies": [encode_policy(p) for p in ps.policies],
        "addressGroups": {
            k: encode_address_group(g) for k, g in ps.address_groups.items()
        },
        "appliedToGroups": {
            k: encode_applied_to_group(g) for k, g in ps.applied_to_groups.items()
        },
    }


def decode_policy_set(d: dict) -> PolicySet:
    return PolicySet(
        policies=[decode_policy(p) for p in d.get("policies", ())],
        address_groups={
            k: decode_address_group(g)
            for k, g in d.get("addressGroups", {}).items()
        },
        applied_to_groups={
            k: decode_applied_to_group(g)
            for k, g in d.get("appliedToGroups", {}).items()
        },
    )


def encode_service_entry(s: ServiceEntry) -> dict:
    return {
        "clusterIP": s.cluster_ip,
        "port": s.port,
        "protocol": s.protocol,
        "endpoints": [
            {"ip": e.ip, "port": e.port, "node": e.node} for e in s.endpoints
        ],
        "affinitySeconds": s.affinity_timeout_s,
        "name": s.name,
        "namespace": s.namespace,
        "externalIPs": list(s.external_ips),
        "nodePort": s.node_port,
        "externalTrafficPolicy": s.external_traffic_policy,
        # service.antrea.io/load-balancer-mode analog: without this a
        # persisted DSR service would silently revert to regular DNAT
        # (and SNAT) after an agent restart.
        "loadBalancerModeDSR": s.dsr,
    }


def decode_service_entry(d: dict) -> ServiceEntry:
    return ServiceEntry(
        cluster_ip=d["clusterIP"],
        port=d["port"],
        protocol=d["protocol"],
        endpoints=[
            Endpoint(ip=e["ip"], port=e["port"], node=e.get("node", ""))
            for e in d.get("endpoints", ())
        ],
        affinity_timeout_s=d.get("affinitySeconds", 0),
        name=d.get("name", ""),
        namespace=d.get("namespace", ""),
        external_ips=list(d.get("externalIPs", ())),
        node_port=d.get("nodePort", 0),
        external_traffic_policy=d.get("externalTrafficPolicy", "Cluster"),
        dsr=d.get("loadBalancerModeDSR", False),
    )


# -- Topology (forwarding plane; datapath snapshots) -------------------------


def encode_topology(t) -> dict:
    return {
        "node": t.node_name,
        "gatewayIP": t.gateway_ip,
        "gatewayIPv6": t.gateway_ip6,
        "podCIDR": t.pod_cidr,
        "podCIDRv6": t.pod_cidr6,
        "localPods": [[ip, port] for ip, port in t.local_pods],
        "remoteNodes": [
            {"name": n.name, "nodeIP": n.node_ip, "podCIDR": n.pod_cidr}
            for n in t.remote_nodes
        ],
        "tcRules": [
            {"name": r.name, "podIPs": list(r.pod_ips), "action": r.action,
             "targetPort": r.target_port, "direction": r.direction}
            for r in t.tc_rules
        ],
        "mcastGroups": [
            {"group": g.group_ip, "ports": list(g.local_ports),
             "nodes": list(g.remote_nodes)}
            for g in t.mcast_groups
        ],
    }


def decode_topology(d: dict):
    from ..compiler.topology import (
        McastGroup, NodeRoute, Topology, TrafficControlRule,
    )

    return Topology(
        node_name=d.get("node", ""),
        gateway_ip=d.get("gatewayIP", ""),
        gateway_ip6=d.get("gatewayIPv6", ""),
        pod_cidr6=d.get("podCIDRv6", ""),
        pod_cidr=d.get("podCIDR", ""),
        local_pods=[(ip, port) for ip, port in d.get("localPods", ())],
        remote_nodes=[
            NodeRoute(name=n["name"], node_ip=n["nodeIP"], pod_cidr=n["podCIDR"])
            for n in d.get("remoteNodes", ())
        ],
        tc_rules=[
            TrafficControlRule(
                name=r["name"], pod_ips=tuple(r["podIPs"]), action=r["action"],
                target_port=r["targetPort"], direction=r.get("direction", "both"),
            )
            for r in d.get("tcRules", ())
        ],
        mcast_groups=[
            McastGroup(group_ip=g["group"], local_ports=tuple(g["ports"]),
                       remote_nodes=tuple(g["nodes"]))
            for g in d.get("mcastGroups", ())
        ],
    )


# -- WatchEvent (the dissemination wire unit) --------------------------------


def encode_event(ev: WatchEvent) -> dict:
    enc = _OBJ_CODECS[ev.obj_type][0] if ev.obj is not None else None
    return {
        "v": WIRE_VERSION,
        "kind": ev.kind,
        "objType": ev.obj_type,
        "name": ev.name,
        "obj": enc(ev.obj) if enc else None,
        "span": sorted(ev.span),
        "added": [_member(m) for m in ev.added],
        "removed": [_member(m) for m in ev.removed],
        "spanOnly": ev.span_only,
        # Controller-commit stamp (dissemination-latency origin); omitted
        # when unstamped so pre-existing captures stay byte-identical.
        **({"ts": ev.ts} if ev.ts else {}),
    }


def decode_event(d: dict) -> WatchEvent:
    v = d.get("v", 0)
    if v != WIRE_VERSION:
        raise ValueError(f"unsupported wire version {v}")
    obj = None
    if d.get("obj") is not None:
        obj = _OBJ_CODECS[d["objType"]][1](d["obj"])
    return WatchEvent(
        kind=d["kind"],
        obj_type=d["objType"],
        name=d["name"],
        obj=obj,
        span=set(d.get("span", ())),
        added=[_member_from(m) for m in d.get("added", ())],
        removed=[_member_from(m) for m in d.get("removed", ())],
        span_only=d.get("spanOnly", False),
        ts=d.get("ts", 0.0),
    )


def event_to_wire(ev: WatchEvent) -> bytes:
    """One length-free JSON line (newline-delimited framing)."""
    return (json.dumps(encode_event(ev), separators=(",", ":")) + "\n").encode()


def event_from_wire(line: bytes) -> WatchEvent:
    return decode_event(json.loads(line.decode()))
