"""Feature gates: named on/off switches with maturity levels.

The analog of the reference's k8s component-base feature gates
(/root/reference/pkg/features/antrea_features.go:193-226 — 31 gates with
Alpha/Beta/GA maturity and per-component applicability).  The registry
below mirrors the reference's gate NAMES for the subsystems this build
implements; gates for not-yet-built subsystems are registered (so configs
referencing them parse) but nothing consults them yet.

Wired consumers:
  AntreaPolicy       NetworkPolicyController rejects ACNP/ANNP when off
  NetworkPolicyStats datapaths skip per-rule counters when off
  Traceflow          Datapath.trace() refuses when off
  AuditLogging       observability.AuditLogger refuses construction when off
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class _Gate:
    default: bool
    maturity: str  # Alpha / Beta / GA


# name -> (default, maturity); names mirror antrea_features.go.
REGISTRY: dict[str, _Gate] = {
    "AntreaPolicy": _Gate(True, "GA"),
    "AntreaProxy": _Gate(True, "GA"),
    "NetworkPolicyStats": _Gate(True, "Beta"),
    "Traceflow": _Gate(True, "Beta"),
    "AuditLogging": _Gate(True, "Beta"),
    "Egress": _Gate(True, "Beta"),
    "FlowExporter": _Gate(False, "Alpha"),
    "EndpointSlice": _Gate(True, "GA"),
    "NodePortLocal": _Gate(True, "GA"),
    "ServiceExternalIP": _Gate(False, "Alpha"),
    "Multicast": _Gate(False, "Alpha"),
    "Multicluster": _Gate(False, "Alpha"),
    "SecondaryNetwork": _Gate(False, "Alpha"),
    "TrafficControl": _Gate(False, "Alpha"),
    "L7NetworkPolicy": _Gate(False, "Alpha"),
    "AdminNetworkPolicy": _Gate(False, "Alpha"),
    "TopologyAwareHints": _Gate(True, "Beta"),
    "LoadBalancerModeDSR": _Gate(False, "Alpha"),
    "CleanupStaleUDPSvcConntrack": _Gate(True, "Beta"),
    "NodeNetworkPolicy": _Gate(False, "Alpha"),
    "BGPPolicy": _Gate(False, "Alpha"),
    "NodeLatencyMonitor": _Gate(False, "Alpha"),
    "PacketCapture": _Gate(False, "Alpha"),
}


class FeatureGates:
    """Immutable-after-parse gate set (component-base semantics: unknown
    gate names are a config error, not silently ignored)."""

    def __init__(self, overrides: dict | None = None):
        self._enabled = {name: g.default for name, g in REGISTRY.items()}
        for name, val in (overrides or {}).items():
            if name not in REGISTRY:
                raise ValueError(f"unknown feature gate {name!r}")
            self._enabled[name] = bool(val)

    def enabled(self, name: str) -> bool:
        if name not in REGISTRY:
            raise ValueError(f"unknown feature gate {name!r}")
        return self._enabled[name]

    def as_dict(self) -> dict:
        return dict(self._enabled)


DEFAULT_GATES = FeatureGates()
