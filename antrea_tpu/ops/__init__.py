from .match import DeviceRuleSet, classify_batch, make_classifier  # noqa: F401
