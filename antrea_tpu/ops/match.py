"""Batched conjunctive-match classification kernel (the tpuflow hot path).

This is the TPU execution of what OVS does per-packet in C: walk the policy
tables and produce a verdict.  Instead of a flow-table walk, we do:

  1. interval lookup: searchsorted over the compiled elementary-interval
     boundaries for src IP, dst IP and the (proto<<16|port) service key;
  2. one row-gather per dimension from the bit-packed group-membership
     matrix -> per-packet group bitmaps (the factored address sets);
  3. a lax.scan over rule chunks: each chunk tests appliedTo/peer/service
     bits per (packet, rule) pair — the conjunction(id, k/n) analog
     (ref: /root/reference/pkg/agent/openflow/network_policy.go:325) —
     and folds per-evaluation-phase first-match indices;
  4. phase resolution replicating the OVS table order:
     AntreaPolicy{In,E}gressRule -> K8s {In,E}gressRule + isolation
     default-deny -> Baseline -> default allow
     (ref: /root/reference/pkg/agent/openflow/pipeline.go:114-195).

All arrays are int32 lanes; IPs are sign-flipped so signed compares give
unsigned order (see compiler/compile.py).  Everything is static-shaped and
jit-compatible; batch size is the only trace-time variable.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..compiler.compile import (
    ACT_ALLOW,
    ACT_DROP,
    ACT_PASS,
    CompiledPolicySet,
    DirectionTensors,
)
from ..utils import ip as iputil

# "No match" sentinel for first-match indices.  Deliberately a PYTHON int,
# not an eager jnp scalar: a concrete device array captured by a jitted
# function becomes a buffer-backed executable constant, which on some TPU
# runtimes (observed on the axon platform) both slows that executable ~1000x
# and degrades every subsequent dispatch in the process.  Python scalars
# trace to HLO literals and stay fast.
BIG = 1 << 30


class DeltaTable(NamedTuple):
    """Fixed-capacity incremental membership-delta table (device-resident).

    The TPU answer to the reference's incremental address-group watch deltas
    (docs/design/architecture.md:61-62): a pod joining/leaving a group does
    NOT recompile the interval bitmap — the host appends one row per affected
    bitmap column and re-uploads only these five small arrays.  The kernel
    patches the gathered per-packet membership rows before the rule scan, so
    every consumer (peer bits, appliedTo bits, isolation bits) sees the
    updated membership.  A full recompile (bundle commit) folds the deltas
    back into the bitmap and clears the table — the megaflow-revalidation
    analog, triggered on capacity overflow.

    Empty slots: sign == 0 (and lo > hi so the range never matches).
    """

    lo_f: jax.Array  # (D,) sign-flipped i32, inclusive
    hi_f: jax.Array  # (D,) sign-flipped i32, inclusive
    word: jax.Array  # (D,) i32 — bitmap word column
    bit: jax.Array  # (D,) u32 — single-bit mask
    sign: jax.Array  # (D,) i32 — +1 set, -1 clear, 0 empty


def empty_delta(slots: int, xp=jnp) -> DeltaTable:
    return DeltaTable(
        lo_f=xp.full((slots,), 2**31 - 1, dtype=xp.int32),
        hi_f=xp.full((slots,), -(2**31), dtype=xp.int32),
        word=xp.zeros((slots,), dtype=xp.int32),
        bit=xp.zeros((slots,), dtype=xp.uint32),
        sign=xp.zeros((slots,), dtype=xp.int32),
    )


def _apply_delta(rows: jax.Array, ip_f: jax.Array, dt: DeltaTable) -> jax.Array:
    """rows (B, W) u32 gathered membership rows -> patched rows.

    Slots apply in order, so a later delta for the same bit wins
    (chronological append order on the host side).
    """

    def body(rows, x):
        lo, hi, w, bitmask, sign = x
        m = (ip_f >= lo) & (ip_f <= hi)
        col = jax.lax.dynamic_index_in_dim(rows, w, axis=1, keepdims=False)
        col = jnp.where(m & (sign > 0), col | bitmask, col)
        col = jnp.where(m & (sign < 0), col & ~bitmask, col)
        return jax.lax.dynamic_update_index_in_dim(rows, col, w, axis=1), None

    rows, _ = jax.lax.scan(body, rows, (dt.lo_f, dt.hi_f, dt.word, dt.bit, dt.sign))
    return rows


class DeviceDirection(NamedTuple):
    # (n_chunks, C) chunked rule arrays.
    at_gid: jax.Array
    peer_gid: jax.Array
    peer_lo: jax.Array  # (n_chunks, C, K)
    peer_hi: jax.Array
    svc_gid: jax.Array
    action: jax.Array  # (R_padded,) flat, for post-scan gather
    # (n_chunks,) global chunk index — carried as data (not an arange built in
    # the kernel) so a rule-axis shard_map slice still knows its global rule
    # offsets and cross-shard first-match combines stay a plain lax.pmin.
    chunk_idx: jax.Array


class DeviceRuleSet(NamedTuple):
    """Device-resident compiled rule tensors (the double-buffered side of a
    bundle commit; ref bundle semantics: pkg/ovs/openflow/ofctrl_bridge.go:468)."""

    ip_bounds: jax.Array
    ip_bitmap: jax.Array
    svc_bounds: jax.Array
    svc_bitmap: jax.Array
    ingress: DeviceDirection
    egress: DeviceDirection
    ip_delta: DeltaTable


class StaticMeta(NamedTuple):
    """Trace-time constants (not pytree leaves)."""

    chunk: int
    in_phases: tuple[int, int, int]  # (n_phase0, n_k8s, n_baseline)
    out_phases: tuple[int, int, int]
    iso_in_gid: int
    iso_out_gid: int
    delta_slots: int = 0


def _chunked(dt: DirectionTensors, chunk: int, chunk_multiple: int = 1) -> DeviceDirection:
    R = dt.n_rules
    n_chunks = max(1, -(-R // chunk))
    n_chunks = -(-n_chunks // chunk_multiple) * chunk_multiple
    pad = n_chunks * chunk - R

    def pad1(a: np.ndarray, fill) -> np.ndarray:
        if pad == 0:
            return a
        shape = (pad,) + a.shape[1:]
        return np.concatenate([a, np.full(shape, fill, dtype=a.dtype)])

    # at_gid fill = 0 == the EMPTY group: padded rules never match.
    return DeviceDirection(
        at_gid=np.ascontiguousarray(pad1(dt.at_gid, 0).reshape(n_chunks, chunk)),
        peer_gid=np.ascontiguousarray(pad1(dt.peer_gid, 0).reshape(n_chunks, chunk)),
        peer_lo=np.ascontiguousarray(
            pad1(dt.peer_lo, np.int32(2**31 - 1)).reshape(n_chunks, chunk, -1)
        ),
        peer_hi=np.ascontiguousarray(
            pad1(dt.peer_hi, np.int32(-(2**31))).reshape(n_chunks, chunk, -1)
        ),
        svc_gid=np.ascontiguousarray(pad1(dt.svc_gid, 0).reshape(n_chunks, chunk)),
        action=np.ascontiguousarray(pad1(dt.action, ACT_DROP)),
        chunk_idx=np.arange(n_chunks, dtype=np.int32),
    )


def to_host(
    cps: CompiledPolicySet,
    chunk: int = 512,
    chunk_multiple: int = 1,
    delta_slots: int = 0,
) -> tuple[DeviceRuleSet, StaticMeta]:
    """Numpy-resident variant of to_device: the same pytree, zero device
    placement.  Used by the driver's compile-check entry() so constructing
    example args performs NO eager transfer (a broken-libtpu host must be able
    to build the args; jit accepts numpy leaves and places them itself)."""
    drs = DeviceRuleSet(
        ip_bounds=np.asarray(cps.ip_bounds),
        ip_bitmap=np.asarray(cps.ip_bitmap),
        svc_bounds=np.asarray(cps.svc_bounds),
        svc_bitmap=np.asarray(cps.svc_bitmap),
        ingress=_chunked(cps.ingress, chunk, chunk_multiple),
        egress=_chunked(cps.egress, chunk, chunk_multiple),
        ip_delta=empty_delta(max(delta_slots, 1), xp=np),
    )
    meta = StaticMeta(
        chunk=chunk,
        in_phases=(cps.ingress.n_phase0, cps.ingress.n_k8s, cps.ingress.n_baseline),
        out_phases=(cps.egress.n_phase0, cps.egress.n_k8s, cps.egress.n_baseline),
        iso_in_gid=cps.iso_in_gid,
        iso_out_gid=cps.iso_out_gid,
        delta_slots=delta_slots,
    )
    return drs, meta


def to_device(
    cps: CompiledPolicySet,
    chunk: int = 512,
    chunk_multiple: int = 1,
    delta_slots: int = 0,
) -> tuple[DeviceRuleSet, StaticMeta]:
    """chunk_multiple pads each direction's chunk count to a multiple (so the
    leading chunk axis divides evenly across a rule-parallel mesh axis).
    delta_slots reserves capacity for incremental membership deltas
    (see DeltaTable); 0 compiles the delta machinery out entirely."""
    host, meta = to_host(cps, chunk, chunk_multiple, delta_slots)
    return jax.tree_util.tree_map(jnp.asarray, host), meta


def _bit(rows: jax.Array, gids: jax.Array) -> jax.Array:
    """rows (B, W) u32, gids (C,) -> (B, C) 0/1 int32."""
    w = gids >> 5
    b = (gids & 31).astype(jnp.uint32)
    words = jnp.take(rows, w, axis=1)  # (B, C)
    return ((words >> b[None, :]) & 1).astype(jnp.int32)


def _scalar_bit(rows: jax.Array, gid: int) -> jax.Array:
    """rows (B, W), static gid -> (B,) 0/1."""
    return ((rows[:, gid >> 5] >> np.uint32(gid & 31)) & 1).astype(jnp.int32)


def _direction_scan(
    dd: DeviceDirection,
    phases: tuple[int, int, int],
    pod_row: jax.Array,
    peer_row: jax.Array,
    svc_row: jax.Array,
    peer_ip_f: jax.Array,
    chunk: int,
):
    """-> (hit0, hitK, hitB): per-packet first-match global rule index per
    evaluation phase (BIG = none)."""
    n0, nk, _nb = phases
    B = pod_row.shape[0]

    def body(carry, xs):
        h0, hk, hb = carry
        ci, at_g, pg_g, plo, phi, sg_g = xs
        base = ci * chunk
        gidx = base + jnp.arange(chunk, dtype=jnp.int32)  # (C,)

        pod_ok = _bit(pod_row, at_g)
        peer_ok = _bit(peer_row, pg_g)
        # inline literal ranges (sign-flipped inclusive bounds)
        in_rng = (
            (peer_ip_f[:, None, None] >= plo[None, :, :])
            & (peer_ip_f[:, None, None] <= phi[None, :, :])
        ).any(axis=2)
        svc_ok = _bit(svc_row, sg_g)
        match = pod_ok & (peer_ok | in_rng.astype(jnp.int32)) & svc_ok  # (B, C)

        cand = jnp.where(match == 1, gidx[None, :], BIG)  # (B, C)
        h0 = jnp.minimum(h0, jnp.where(gidx[None, :] < n0, cand, BIG).min(axis=1))
        hk = jnp.minimum(
            hk,
            jnp.where((gidx[None, :] >= n0) & (gidx[None, :] < n0 + nk), cand, BIG).min(axis=1),
        )
        hb = jnp.minimum(hb, jnp.where(gidx[None, :] >= n0 + nk, cand, BIG).min(axis=1))
        return (h0, hk, hb), None

    init = (
        jnp.full(B, BIG, dtype=jnp.int32),
        jnp.full(B, BIG, dtype=jnp.int32),
        jnp.full(B, BIG, dtype=jnp.int32),
    )
    xs = (
        dd.chunk_idx,
        dd.at_gid,
        dd.peer_gid,
        dd.peer_lo,
        dd.peer_hi,
        dd.svc_gid,
    )
    (h0, hk, hb), _ = jax.lax.scan(body, init, xs)
    return h0, hk, hb


def _resolve(
    dd: DeviceDirection,
    hits,
    pod_iso: jax.Array,
):
    """Phase resolution -> (code (B,), rule_idx (B,) [-1 = default])."""
    h0, hk, hb = hits
    a0 = dd.action[jnp.clip(h0, 0, dd.action.shape[0] - 1)]
    ab = dd.action[jnp.clip(hb, 0, dd.action.shape[0] - 1)]
    has0 = h0 < BIG
    hask = hk < BIG
    hasb = hb < BIG

    decided0 = has0 & (a0 != ACT_PASS)
    decidedb = hasb & (ab != ACT_PASS)

    k8s_code = jnp.where(hask, ACT_ALLOW, ACT_DROP)
    k8s_rule = jnp.where(hask, hk, -1)

    code = jnp.where(
        decided0,
        a0,
        jnp.where(
            pod_iso == 1,
            k8s_code,
            jnp.where(decidedb, ab, ACT_ALLOW),
        ),
    )
    rule = jnp.where(
        decided0,
        h0,
        jnp.where(
            pod_iso == 1,
            k8s_rule,
            jnp.where(decidedb, hb, -1),
        ),
    )
    return code.astype(jnp.int32), rule.astype(jnp.int32)


def classify_batch(
    drs: DeviceRuleSet,
    src_ip_f: jax.Array,  # (B,) sign-flipped i32
    dst_ip_f: jax.Array,
    proto: jax.Array,  # (B,) i32
    dst_port: jax.Array,  # (B,) i32
    *,
    meta: StaticMeta,
    hit_combine=None,
):
    """-> dict with final/egress/ingress codes and deciding rule indices.

    Codes use the oracle encoding: 0 allow, 1 drop, 2 reject.

    hit_combine, if given, is applied to each per-phase first-match hit tensor
    between the rule scan and phase resolution — the rule-parallel seam: a
    shard_map caller passes ``lambda h: lax.pmin(h, 'rule')`` so each rule
    shard scans only its local chunks and the global first match is an
    all-reduce over ICI (the TPU analog of OVS evaluating one shared table).
    """
    src_iv = jnp.searchsorted(drs.ip_bounds, src_ip_f, side="right")
    dst_iv = jnp.searchsorted(drs.ip_bounds, dst_ip_f, side="right")
    svc_key = (proto << 16) | dst_port
    svc_iv = jnp.searchsorted(drs.svc_bounds, svc_key, side="right")

    src_row = drs.ip_bitmap[src_iv]  # (B, GW)
    dst_row = drs.ip_bitmap[dst_iv]
    svc_row = drs.svc_bitmap[svc_iv]

    if meta.delta_slots > 0:
        # Incremental membership deltas patch the gathered rows, so peer/
        # appliedTo/isolation consumers all see post-delta membership.
        src_row = _apply_delta(src_row, src_ip_f, drs.ip_delta)
        dst_row = _apply_delta(dst_row, dst_ip_f, drs.ip_delta)

    # Ingress: pod = dst, peer = src. Egress: pod = src, peer = dst.
    in_hits = _direction_scan(
        drs.ingress, meta.in_phases, dst_row, src_row, svc_row, src_ip_f, meta.chunk
    )
    out_hits = _direction_scan(
        drs.egress, meta.out_phases, src_row, dst_row, svc_row, dst_ip_f, meta.chunk
    )

    if hit_combine is not None:
        in_hits = tuple(hit_combine(h) for h in in_hits)
        out_hits = tuple(hit_combine(h) for h in out_hits)

    in_code, in_rule = _resolve(
        drs.ingress, in_hits, _scalar_bit(dst_row, meta.iso_in_gid)
    )
    out_code, out_rule = _resolve(
        drs.egress, out_hits, _scalar_bit(src_row, meta.iso_out_gid)
    )

    final = jnp.where(out_code != ACT_ALLOW, out_code, in_code)
    return {
        "code": final,
        "egress_code": out_code,
        "egress_rule": out_rule,
        "ingress_code": in_code,
        "ingress_rule": in_rule,
    }


def flip_ips(a: np.ndarray) -> np.ndarray:
    """Host helper: u32 IP array -> sign-flipped i32 (kernel input layout)."""
    return iputil.flip_u32(a)


# meta is static (plain ints/tuples, hashable); drs is a traced pytree arg so
# the big bitmap tensors stay runtime inputs instead of baked-in constants.
_classify_jit = jax.jit(classify_batch, static_argnames=("meta", "hit_combine"))


def make_classifier(cps: CompiledPolicySet, chunk: int = 512):
    """-> (fn(src_f, dst_f, proto, dport) -> verdict dict, DeviceRuleSet)."""
    drs, meta = to_device(cps, chunk)

    def fn(src_f, dst_f, proto, dport):
        return _classify_jit(drs, src_f, dst_f, proto, dport, meta=meta)

    return fn, drs
