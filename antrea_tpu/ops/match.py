"""Batched conjunctive-match classification kernel (the tpuflow hot path).

This is the TPU execution of what OVS does per-packet in C: walk the policy
tables and produce a verdict.  The kernel is gather-structured (round-3
redesign; the round-2 kernel was a lax.scan over rule chunks testing per-rule
group bits plus a (B, C, K) inline-range broadcast, and topped out at 176k
pps @ 100k rules — 0.018x the 10M target):

  1. per-dimension interval lookup: searchsorted over the dimension's OWN
     elementary-interval boundaries (appliedTo / peer over the u32 IP space,
     service over the (proto << 16 | dst_port) key space);
  2. one row gather per dimension from that dimension's bit-packed
     RULE-INCIDENCE table: inc[iv] is a bitmap over rules — bit r set iff
     rule r's interned group for this dimension contains interval iv.  This
     is the factored address-set sharing of the reference's conjunction
     engine (/root/reference/pkg/agent/openflow/network_policy.go:325,:442),
     transposed from (interval -> groups) to (interval -> rule bits) at
     compile time so the kernel never walks groups at all;
  3. AND the three rows -> per-packet rule-match bitmap (B, ceil(R/32));
  4. per-evaluation-phase first-set-bit (isolate-lowest-bit + popcount +
     min-reduce) replicating the OVS table order:
     AntreaPolicy{In,E}gressRule -> K8s {In,E}gressRule + isolation
     default-deny -> Baseline -> default allow
     (ref: /root/reference/pkg/agent/openflow/pipeline.go:114-195).

Per packet the work is three ~R/32-word row gathers per direction plus a
handful of vector word ops — HBM-streaming-bound with no per-rule scan, no
data-dependent control flow, and no gather along the lane axis (row gathers
along the major axis are the fast pattern on TPU; see the FlowCache layout
rationale in models/pipeline.py).  Inline peer CIDR blocks are folded into
interned groups by the compiler, so they are ordinary incidence bits here.

All arrays are i32/u32 lanes; IPs are sign-flipped so signed compares give
unsigned order (see compiler/compile.py).  Everything is static-shaped and
jit-compatible; batch size is the only trace-time variable.
"""

from __future__ import annotations

from functools import lru_cache
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..compiler.compile import (
    ACT_ALLOW,
    ACT_DROP,
    ACT_PASS,
    CompiledPolicySet,
    DirectionTensors,
)
from ..utils import ip as iputil

# "No match" sentinel for first-match indices.  Deliberately a PYTHON int,
# not an eager jnp scalar: a concrete device array captured by a jitted
# function becomes a buffer-backed executable constant, which on some TPU
# runtimes (observed on the axon platform) both slows that executable ~1000x
# and degrades every subsequent dispatch in the process.  Python scalars
# trace to HLO literals and stay fast.
BIG = 1 << 30

_ALL1 = 0xFFFFFFFF

# Aggregated-bitmap pruning (round 7; ABV-style two-level incidence).
# One aggregate BIT summarizes one incidence WORD (32 rules); one
# aggregate WORD therefore summarizes a 32-word SUPERBLOCK (1024 rules),
# which is the granularity the candidate gather fetches at.  Aggregate
# bits are conservative: never a false negative (a zero aggregate AND
# proves no-match), possibly a false positive (the candidate gather then
# finds an all-zero AND and the lane takes the default verdict).
AGG_BLOCK = 32

# The K-budget autotuner's closed rung ladder (one jit-cached classify
# variant per rung, like the drain CHUNK_LADDER) and its hysteresis.
PRUNE_LADDER = (1, 2, 4, 8, 16)
PRUNE_STICKY = 2
# Fallback-rate pressure band: above the high-water mark the budget
# presses UP (too many full-width redispatches), below the low-water
# mark it presses DOWN (budget head-room wasted on candidate volume).
PRUNE_FB_HIGH = 0.05
PRUNE_FB_LOW = 0.005

# Candidate-superblock histogram bucket bounds (per-lane max over the
# two directions) — shared by the device-side bucket counts
# (models/pipeline._prune_bucket_counts) and the host Histogram they
# merge into, so the exposition buckets can never drift.
PRUNE_HIST_BOUNDS = (0, 1, 2, 4, 8, 16, 32)

# Smallest in-kernel fallback rung (pow2 ladder, x4 steps up to the
# batch size): unresolved lanes are compacted and redispatched at full
# incidence width inside ONE lax.switch branch — the in-jit analog of
# the PR 9 _spill_retry pow2-rung host dispatch.
_FB_MIN = 64


class DimTable(NamedTuple):
    """One match dimension: interval bounds + rule-incidence rows.

    Dual-stack (ref pipeline.go IPv6 table, fields.go:184-185 xxreg3): the
    incidence rows concatenate the v4 interval space (rows 0..NB4) and the
    v6 interval space (rows NB4+1..NB4+1+NB6) — v6 boundaries live in a
    separate 4-word lexicographic table, and once a packet resolves to an
    interval INDEX everything downstream is family-blind.  bounds6 always
    exists (possibly 0 rows; the v6 space then has the single whole-space
    interval, still painted by family-spanning groups like any-peer)."""

    bounds: jax.Array  # (NB4,) i32 ascending (sign-flipped for IP dims)
    # (NB6, 4) i32 — v6 boundaries as per-word sign-flipped u32 quadruples,
    # ascending lexicographically.  Empty (0, 4) for the svc dimension.
    bounds6: jax.Array
    inc: jax.Array  # (NB4+1+NB6+1, W) u32 — rule bitmap per interval
    # Aggregate level (round 7, built only under prune_budget > 0 so the
    # unpruned pytree — and every jit signature over it — is unchanged):
    # (rows, W/AGG_BLOCK) u32, bit j of word s set iff inc word
    # s*AGG_BLOCK+j is nonzero (build_agg is the ONE builder, shared with
    # the consistency property tests).  W is padded to an AGG_BLOCK
    # multiple whenever agg is built, so superblocks never straddle the
    # row end (or a rule-axis shard boundary — see _width).
    agg: Optional[jax.Array] = None


class DeviceDirection(NamedTuple):
    at: DimTable  # appliedTo, probed with the pod-side IP
    peer: DimTable  # peer, probed with the other side's IP
    svc: DimTable  # service, probed with (proto << 16 | dst_port)
    action: jax.Array  # (W*32,) i32 flat, for post-resolve gather
    # (W*32,) i32 0/1 L7-redirect mark per rule, replicated like `action`
    # (indexed post-pmin by the deciding rule).
    l7: jax.Array
    # (W,) global word index — carried as data (not an arange built in the
    # kernel) so a rule-axis shard_map slice still knows its global rule
    # offsets and cross-shard first-match combines stay a plain lax.pmin.
    word_idx: jax.Array


class IsoTable(NamedTuple):
    """K8s default-deny isolation membership (one bit per packet);
    dual-stack like DimTable (val rows = K4+1+K6+1)."""

    bounds: jax.Array  # (K4,) i32 sign-flipped
    bounds6: jax.Array  # (K6, 4) i32 per-word sign-flipped
    val: jax.Array  # (K4+1+K6+1,) i32 0/1


class DeltaTable(NamedTuple):
    """Fixed-capacity incremental membership-delta table (device-resident).

    The TPU answer to the reference's incremental address-group watch deltas
    (docs/design/architecture.md:61-62): a pod joining/leaving a group does
    NOT recompile any interval table — the host appends one slot carrying
    the affected ip range plus PRE-RESOLVED per-dimension rule masks (the
    bitmaps of rules whose at/peer gid is the patched group), and the kernel
    patches the gathered incidence rows before the AND, so every consumer
    sees the updated membership.  A full recompile (bundle commit) folds the
    deltas back into the tables and clears this — the megaflow-revalidation
    analog, triggered on capacity overflow.

    Slots apply in append order inside a dynamic-trip-count loop (`n`), so
    zero pending deltas cost zero iterations and a later delta for the same
    rule bit wins.  Empty slots: sign == 0.

    Dual-stack: a slot is single-family (`fam`) — v4 slots compare the
    narrow range, v6 slots the 4-word lexicographic one (same pre-resolved
    masks either way), so v6 pod churn stays O(1) instead of forcing a
    recompile.
    """

    lo_f: jax.Array  # (D,) sign-flipped i32, inclusive (v4 slots)
    hi_f: jax.Array  # (D,) sign-flipped i32, inclusive
    sign: jax.Array  # (D,) i32 — +1 set, -1 clear, 0 empty
    iso: jax.Array  # (D,) i32 — bit0: patches iso_in, bit1: patches iso_out
    at_in: jax.Array  # (D, W_in) u32 rule mask for the ingress appliedTo dim
    peer_in: jax.Array  # (D, W_in)
    at_out: jax.Array  # (D, W_out)
    peer_out: jax.Array  # (D, W_out)
    n: jax.Array  # () i32 — active slots
    fam: jax.Array  # (D,) i32 — 0: v4 slot, 1: v6 slot
    lo6_w: jax.Array  # (D, 4) per-word flipped, inclusive (v6 slots)
    hi6_w: jax.Array  # (D, 4)


class DeviceRuleSet(NamedTuple):
    """Device-resident compiled rule tensors (the double-buffered side of a
    bundle commit; ref bundle semantics: pkg/ovs/openflow/ofctrl_bridge.go:468)."""

    ingress: DeviceDirection
    egress: DeviceDirection
    iso_in: IsoTable
    iso_out: IsoTable
    ip_delta: DeltaTable


class StaticMeta(NamedTuple):
    """Trace-time constants (not pytree leaves)."""

    in_phases: tuple[int, int, int]  # (n_phase0, n_k8s, n_baseline)
    out_phases: tuple[int, int, int]
    w_in: int  # ingress rule words (incl. shard padding)
    w_out: int
    delta_slots: int = 0
    # Fused-consumer interpret override: None = infer from the DEFAULT
    # platform.  The sharded builders set this from the MESH's platform —
    # a CPU mesh on a TPU-default host (the virtual-device dryrun) must
    # interpret, and vice versa.
    fused_interpret: "bool | None" = None
    # Egress rules include toServices lowerings (compiler SVCREF_BASE
    # sub-space): classify_batch probes the egress svc dimension with a
    # SECOND key derived from the lane's ServiceLB resolution.  Static so
    # svcref-free rule sets compile the extra gather out entirely.
    svcref: bool = False
    # Two-level aggregate pruning (round 7): K = max candidate
    # superblocks gathered per lane and direction; 0 compiles the whole
    # aggregate layer out (the tables are then not even built — agg is
    # None and the classify HLO is bit-identical to the pre-aggregate
    # kernel).  Runtime-retunable on PRUNE_LADDER by swapping the meta
    # (one jit-cached variant per rung; the tables are K-independent).
    prune_budget: int = 0


def empty_delta(slots: int, w_in: int, w_out: int, xp=jnp) -> DeltaTable:
    return DeltaTable(
        lo_f=xp.full((slots,), 2**31 - 1, dtype=xp.int32),
        hi_f=xp.full((slots,), -(2**31), dtype=xp.int32),
        sign=xp.zeros((slots,), dtype=xp.int32),
        iso=xp.zeros((slots,), dtype=xp.int32),
        at_in=xp.zeros((slots, w_in), dtype=xp.uint32),
        peer_in=xp.zeros((slots, w_in), dtype=xp.uint32),
        at_out=xp.zeros((slots, w_out), dtype=xp.uint32),
        peer_out=xp.zeros((slots, w_out), dtype=xp.uint32),
        n=xp.zeros((), dtype=xp.int32),
        fam=xp.zeros((slots,), dtype=xp.int32),
        lo6_w=xp.full((slots, 4), 2**31 - 1, dtype=xp.int32),
        hi6_w=xp.full((slots, 4), -(2**31), dtype=xp.int32),
    )


# ---------------------------------------------------------------------------
# Host-side table construction
# ---------------------------------------------------------------------------


def _rules_by_gid(gids: np.ndarray) -> dict[int, np.ndarray]:
    order = np.argsort(gids, kind="stable").astype(np.int64)
    sg = gids[order]
    uniq, starts = np.unique(sg, return_index=True)
    out: dict[int, np.ndarray] = {}
    for i, g in enumerate(uniq):
        end = starts[i + 1] if i + 1 < len(uniq) else len(sg)
        out[int(g)] = order[starts[i] : end]
    return out


def _inc_mask(rule_idx: np.ndarray, w: int) -> np.ndarray:
    """Rule indices -> (w,) u32 bitmap."""
    inc = np.zeros(w, dtype=np.uint32)
    np.bitwise_or.at(inc, rule_idx >> 5, (1 << (rule_idx & 31)).astype(np.uint32))
    return inc


def build_agg(inc: np.ndarray) -> np.ndarray:
    """(rows, W) u32 incidence -> (rows, ceil(W/AGG_BLOCK)) u32 aggregate:
    bit j of aggregate word s == (inc word s*AGG_BLOCK+j) != 0.  The ONE
    aggregate builder — to_host, the delta kernel's on-the-fly mask
    aggregation (_agg_mask) and the consistency property tests all follow
    this definition, so table/aggregate divergence is a scrub finding,
    never a construction ambiguity."""
    inc = np.asarray(inc)
    rows, w = inc.shape
    s = -(-w // AGG_BLOCK)
    pad = s * AGG_BLOCK - w
    if pad:
        inc = np.pad(inc, ((0, 0), (0, pad)))
    nz = (inc.reshape(rows, s, AGG_BLOCK) != 0).astype(np.uint32)
    return (nz << np.arange(AGG_BLOCK, dtype=np.uint32)[None, None, :]).sum(
        axis=2, dtype=np.uint32)  # disjoint bits: sum == OR


_V6_OFF = iputil.V6_OFF
_V6_END = 1 << 128  # exclusive end of the v6-relative space


def _span_list(bounds: list, lo: int, hi: int) -> tuple[int, int]:
    """[lo, hi) range -> inclusive interval-row span [a, b] over a SORTED
    python-int bounds list (bisect 'right' index space, row i covering
    (bounds[i-1], bounds[i]])."""
    import bisect

    a = bisect.bisect_right(bounds, lo)
    b = bisect.bisect_right(bounds, hi - 1)
    return a, b


def _family_split(lo: int, hi: int):
    """Combined-keyspace [lo, hi) -> (v4 part or None, v6-relative part or
    None); family-spanning ranges (any-peer) contribute to both."""
    v4 = v6 = None
    if lo < (1 << 32):
        v4 = (lo, min(hi, 1 << 32))
    if hi > _V6_OFF:
        v6 = (max(lo, _V6_OFF) - _V6_OFF, hi - _V6_OFF)
    return v4, v6


def _dual_bounds(range_lists) -> tuple[list, list]:
    """Boundary points of both families from combined ranges."""
    p4: set[int] = set()
    p6: set[int] = set()
    for ranges in range_lists:
        for lo, hi in ranges:
            r4, r6 = _family_split(int(lo), int(hi))
            if r4 is not None:
                p4.add(r4[0])
                if r4[1] < (1 << 32):
                    p4.add(r4[1])
            if r6 is not None:
                p6.add(r6[0])
                if r6[1] < _V6_END:
                    p6.add(r6[1])
    return sorted(p4), sorted(p6)


def _v6_words(vals: list) -> np.ndarray:
    """Sorted v6-relative ints -> (N, 4) sign-flipped i32 word quadruples
    (lexicographic order preserved word-wise)."""
    out = np.zeros((len(vals), 4), dtype=np.uint32)
    for i, v in enumerate(vals):
        out[i] = [(v >> 96) & 0xFFFFFFFF, (v >> 64) & 0xFFFFFFFF,
                  (v >> 32) & 0xFFFFFFFF, v & 0xFFFFFFFF]
    return iputil.flip_u32(out)


def _paint(b4: list, b6: list, lo: int, hi: int, write) -> None:
    """Paint combined range [lo, hi) into the dual interval row space via
    the write(row_a, row_b) callback: v4 rows [0..len(b4)], v6 rows
    [len(b4)+1 ..]."""
    r4, r6 = _family_split(int(lo), int(hi))
    if r4 is not None and r4[0] < r4[1]:
        a, b = _span_list(b4, *r4)
        write(a, b)
    if r6 is not None and r6[0] < r6[1]:
        a, b = _span_list(b6, *r6)
        off = len(b4) + 1
        write(off + a, off + b)


def _dim_table_host(gids: np.ndarray, groups: list, w: int, ip_dim: bool,
                    agg: bool = False) -> DimTable:
    """Build one dimension's (bounds, bounds6, incidence) triple.

    Only the groups this dimension actually uses contribute boundary points,
    so each dimension's interval table stays as small as its own address
    structure (the appliedTo dimension is typically far coarser than peer).
    """
    by = _rules_by_gid(gids)
    b4, b6 = _dual_bounds(groups[g] for g in by)
    if not ip_dim:
        # svc keys live entirely below 2^32; no v6 sub-space.
        b6 = []
    n_rows = len(b4) + 1 + (len(b6) + 1 if ip_dim else 0)
    inc = np.zeros((n_rows, w), dtype=np.uint32)
    for g, rr in by.items():
        ranges = groups[g]
        if not ranges or rr.size == 0:
            continue
        gmask = _inc_mask(rr, w)
        nzw = np.nonzero(gmask)[0]
        vals = gmask[nzw]

        def write(a, b):
            inc[a : b + 1][:, nzw] |= vals

        for lo, hi in ranges:
            if ip_dim:
                _paint(b4, b6, lo, hi, write)
            else:
                a, b = _span_list(b4, int(lo), int(hi))
                write(a, b)
    if ip_dim:
        bounds = iputil.flip_u32(np.array(b4, dtype=np.uint64).astype(np.uint32))
        bounds6 = _v6_words(b6)
    else:
        bounds = np.array(b4, dtype=np.int64).astype(np.int32)
        bounds6 = np.zeros((0, 4), dtype=np.int32)
    return DimTable(bounds=bounds, bounds6=bounds6, inc=inc,
                    agg=build_agg(inc) if agg else None)


def _iso_host(gid: int, groups: list) -> IsoTable:
    ranges = groups[gid]
    b4, b6 = _dual_bounds([ranges])
    val = np.zeros(len(b4) + 1 + len(b6) + 1, dtype=np.int32)

    def write(a, b):
        val[a : b + 1] = 1

    for lo, hi in ranges:
        _paint(b4, b6, lo, hi, write)
    return IsoTable(
        bounds=iputil.flip_u32(np.array(b4, dtype=np.uint64).astype(np.uint32)),
        bounds6=_v6_words(b6),
        val=val,
    )


def _direction_host(
    dt: DirectionTensors, cps: CompiledPolicySet, w: int, agg: bool = False
) -> DeviceDirection:
    action = np.full(w * 32, ACT_DROP, dtype=np.int32)
    action[: dt.n_rules] = dt.action
    l7 = np.zeros(w * 32, dtype=np.int32)
    if dt.l7 is not None:
        l7[: dt.n_rules] = dt.l7
    return DeviceDirection(
        at=_dim_table_host(dt.at_gid, cps.ip_groups, w, ip_dim=True, agg=agg),
        peer=_dim_table_host(dt.peer_gid, cps.ip_groups, w, ip_dim=True,
                             agg=agg),
        svc=_dim_table_host(dt.svc_gid, cps.svc_groups, w, ip_dim=False,
                            agg=agg),
        action=action,
        l7=l7,
        word_idx=np.arange(w, dtype=np.int32),
    )


def _width(n_rules: int, word_multiple: int, agg: bool = False) -> int:
    # Dual-level alignment under pruning: W must divide by word_multiple
    # (the rule-axis shard count) AND each shard's W/word_multiple slice
    # must itself be an AGG_BLOCK multiple, so aggregate words never
    # straddle a shard boundary and the agg axis shards evenly — hence
    # word_multiple * AGG_BLOCK, not lcm (lcm alone leaves per-SHARD
    # widths misaligned whenever gcd(word_multiple, 32) > 1).
    if agg:
        word_multiple *= AGG_BLOCK
    w = max(1, -(-n_rules // 32))
    return -(-w // word_multiple) * word_multiple


def to_host(
    cps: CompiledPolicySet,
    word_multiple: int = 1,
    delta_slots: int = 0,
    prune_budget: int = 0,
) -> tuple[DeviceRuleSet, StaticMeta]:
    """Numpy-resident variant of to_device: the same pytree, zero device
    placement (jit accepts numpy leaves and places them itself — used by the
    driver's compile-check entry() so a broken accelerator runtime can still
    build example args).

    word_multiple pads each direction's rule-word count to a multiple (so
    the incidence word axis divides evenly across a rule-parallel mesh
    axis).  delta_slots reserves capacity for incremental membership deltas
    (see DeltaTable); 0 compiles the delta machinery out entirely.
    prune_budget > 0 builds the aggregate tables (DimTable.agg) and enables
    the two-level pruned classify at K = prune_budget candidate superblocks
    per lane and direction; 0 builds the exact pre-aggregate pytree.
    """
    agg = prune_budget > 0
    w_in = _width(cps.ingress.n_rules, word_multiple, agg=agg)
    w_out = _width(cps.egress.n_rules, word_multiple, agg=agg)
    drs = DeviceRuleSet(
        ingress=_direction_host(cps.ingress, cps, w_in, agg=agg),
        egress=_direction_host(cps.egress, cps, w_out, agg=agg),
        iso_in=_iso_host(cps.iso_in_gid, cps.ip_groups),
        iso_out=_iso_host(cps.iso_out_gid, cps.ip_groups),
        ip_delta=empty_delta(max(delta_slots, 1), w_in, w_out, xp=np),
    )
    meta = StaticMeta(
        in_phases=(cps.ingress.n_phase0, cps.ingress.n_k8s, cps.ingress.n_baseline),
        out_phases=(cps.egress.n_phase0, cps.egress.n_k8s, cps.egress.n_baseline),
        w_in=w_in,
        w_out=w_out,
        delta_slots=delta_slots,
        svcref=cps.has_svcref,
        prune_budget=prune_budget,
    )
    return drs, meta


def to_device(
    cps: CompiledPolicySet,
    word_multiple: int = 1,
    delta_slots: int = 0,
    prune_budget: int = 0,
) -> tuple[DeviceRuleSet, StaticMeta]:
    host, meta = to_host(cps, word_multiple, delta_slots, prune_budget)
    return jax.tree_util.tree_map(jnp.asarray, host), meta


# ---------------------------------------------------------------------------
# Entry-axis rung padding (round 9, the multi-tenant packing layer)
# ---------------------------------------------------------------------------

# Smallest entry-axis rung a padded table lands on: interval-boundary
# counts below this all share one shape, so small tenants collapse onto
# one compiled program instead of one per distinct group structure.
ENTRY_RUNG_FLOOR = 16

# Padding boundary values: the MAXIMUM of each key space.  searchsorted
# side='right' counts bounds <= x, so every x below the maximum resolves
# to its original interval row unchanged; x == maximum lands past the
# pad block, which is why the padder REPLICATES the top row's incidence
# across the whole pad region (any bisect variant then reads the same
# row content).  For IP dims the flipped-space max is the flip of
# 255.255.255.255 == int32 max; svc keys live below 2^24, so int32 max
# is unreachable there outright.
_PAD_BOUND = 2**31 - 1


def _entry_cap(n: int, floor: int = ENTRY_RUNG_FLOOR) -> int:
    """Natural entry count -> its pow2 rung (0 stays 0: an empty v6
    sub-table is a SHAPE, shared by every all-v4 world on the rung)."""
    if n <= 0:
        return 0
    return max(floor, 1 << (n - 1).bit_length())


def _pad_rows(rows: np.ndarray, at: int, count: int) -> np.ndarray:
    """Insert `count` replicas of row `at` directly after it."""
    if count <= 0:
        return rows
    return np.concatenate(
        [rows[: at + 1], np.repeat(rows[at : at + 1], count, axis=0),
         rows[at + 1 :]], axis=0)


def _pad_dim_table(tab: DimTable, cap4: int, cap6: int) -> DimTable:
    bounds = np.asarray(tab.bounds)
    bounds6 = np.asarray(tab.bounds6)
    inc = np.asarray(tab.inc)
    nb4, nb6 = bounds.shape[0], bounds6.shape[0]
    ip_dim = inc.shape[0] == nb4 + 1 + nb6 + 1  # svc dims have no v6 rows
    p4 = max(0, cap4 - nb4)
    p6 = max(0, cap6 - nb6) if ip_dim else 0
    if p4 == 0 and p6 == 0:
        return tab
    if p4:
        bounds = np.concatenate(
            [bounds, np.full(p4, _PAD_BOUND, bounds.dtype)])
        inc = _pad_rows(inc, nb4, p4)  # replicate the v4 top row
    if p6:
        bounds6 = np.concatenate(
            [bounds6, np.full((p6, 4), _PAD_BOUND, bounds6.dtype)], axis=0)
        inc = _pad_rows(inc, inc.shape[0] - 1, p6)  # replicate the v6 top row
    return DimTable(
        bounds=bounds, bounds6=bounds6, inc=inc,
        agg=build_agg(inc) if tab.agg is not None else None)


def _pad_iso_table(tab: IsoTable, cap4: int, cap6: int) -> IsoTable:
    bounds = np.asarray(tab.bounds)
    bounds6 = np.asarray(tab.bounds6)
    val = np.asarray(tab.val)
    nb4, nb6 = bounds.shape[0], bounds6.shape[0]
    p4 = max(0, cap4 - nb4)
    p6 = max(0, cap6 - nb6)
    if p4 == 0 and p6 == 0:
        return tab
    if p4:
        bounds = np.concatenate(
            [bounds, np.full(p4, _PAD_BOUND, bounds.dtype)])
        val = _pad_rows(val, nb4, p4)
    if p6:
        bounds6 = np.concatenate(
            [bounds6, np.full((p6, 4), _PAD_BOUND, bounds6.dtype)], axis=0)
        val = _pad_rows(val, val.shape[0] - 1, p6)
    return IsoTable(bounds=bounds, bounds6=bounds6, val=val)


def pad_ruleset_entries(
    drs: DeviceRuleSet, cap4: Optional[int] = None,
    cap6: Optional[int] = None,
) -> tuple[DeviceRuleSet, tuple[int, int]]:
    """Pad every dimension's ENTRY axes (interval boundaries + incidence
    rows) of a HOST ruleset to pow2 rungs -> (padded drs, (cap4, cap6)).

    The word axis is already rung-shaped by the caller (padded rule
    counts through `_width`); this pads the other jit-signature axes —
    per-dim boundary counts, which otherwise vary with each tenant's
    group structure — so two rule worlds on the same rung produce
    IDENTICAL tensor shapes and share one compiled program (the
    multi-tenant shared-compile contract, datapath/tenancy.py).  Padding
    is semantically invisible: pad boundaries sit at the key-space
    maximum and every pad row replicates its neighbor's incidence, so no
    probe can resolve to different rule bits (regression-pinned by the
    tenancy parity suite).  Aggregate tables are rebuilt from the padded
    incidence (build_agg is the one builder, so the scrub/property tests
    keep their consistency contract)."""
    dims = [drs.ingress.at, drs.ingress.peer, drs.ingress.svc,
            drs.egress.at, drs.egress.peer, drs.egress.svc]
    isos = [drs.iso_in, drs.iso_out]
    if cap4 is None:
        cap4 = _entry_cap(max(
            [np.asarray(t.bounds).shape[0] for t in dims + isos]))
    if cap6 is None:
        cap6 = _entry_cap(max(
            [np.asarray(t.bounds6).shape[0] for t in dims + isos]))

    def pad_dir(d: DeviceDirection) -> DeviceDirection:
        return d._replace(
            at=_pad_dim_table(d.at, cap4, cap6),
            peer=_pad_dim_table(d.peer, cap4, cap6),
            svc=_pad_dim_table(d.svc, cap4, cap6),
        )

    return drs._replace(
        ingress=pad_dir(drs.ingress),
        egress=pad_dir(drs.egress),
        iso_in=_pad_iso_table(drs.iso_in, cap4, cap6),
        iso_out=_pad_iso_table(drs.iso_out, cap4, cap6),
    ), (int(cap4), int(cap6))


# ---------------------------------------------------------------------------
# Kernel
# ---------------------------------------------------------------------------


def _lex_le4(a: jax.Array, b: jax.Array) -> jax.Array:
    """Lexicographic a <= b over a trailing 4-word axis (per-word flipped
    i32 — the same compare _searchsorted6 builds from)."""
    lt = a < b
    eq = a == b
    return lt[..., 0] | (eq[..., 0] & (lt[..., 1] | (eq[..., 1] & (
        lt[..., 2] | (eq[..., 2] & (lt[..., 3] | eq[..., 3]))))))


def _delta_lane_match(ip_f, dt: DeltaTable, i, wide):
    """Lanes slot i's range covers: v4 slots compare the narrow column of
    v4 lanes; v6 slots the wide words of v6 lanes (family-pure slots —
    the dual-stack membership test, shared by rows and iso)."""
    m4 = (ip_f >= dt.lo_f[i]) & (ip_f <= dt.hi_f[i])
    if wide is None:
        return m4
    xw, is6 = wide
    m4 = m4 & (is6 == 0) & (dt.fam[i] == 0)
    m6 = (
        (is6 != 0) & (dt.fam[i] == 1)
        & _lex_le4(dt.lo6_w[i][None, :], xw)
        & _lex_le4(xw, dt.hi6_w[i][None, :])
    )
    return m4 | m6


def _patch_rows(rows: jax.Array, ip_f: jax.Array, dt: DeltaTable, masks,
                wide=None) -> jax.Array:
    """Apply the active delta slots to gathered incidence rows (B, W).
    wide = (xw (B,4), is6) in dual-stack worlds — the dimension's lane
    words, so v6 slots patch v6 lanes."""

    def body(i, rows):
        m = _delta_lane_match(ip_f, dt, i, wide)
        mask = masks[i][None, :]
        s = dt.sign[i]
        rows = jnp.where((m & (s > 0))[:, None], rows | mask, rows)
        rows = jnp.where((m & (s < 0))[:, None], rows & ~mask, rows)
        return rows

    return jax.lax.fori_loop(0, dt.n, body, rows)


def _patch_iso(bit: jax.Array, ip_f: jax.Array, dt: DeltaTable, which: int,
               wide=None) -> jax.Array:
    def body(i, bit):
        m = (
            _delta_lane_match(ip_f, dt, i, wide)
            & (((dt.iso[i] >> which) & 1) == 1)
        )
        s = dt.sign[i]
        bit = jnp.where(m & (s > 0), 1, bit)
        bit = jnp.where(m & (s < 0), 0, bit)
        return bit

    return jax.lax.fori_loop(0, dt.n, body, bit)


def _agg_mask(mask_w: jax.Array) -> jax.Array:
    """(W,) u32 delta rule mask -> (W/AGG_BLOCK,) u32 aggregate mask, the
    device-side twin of build_agg over one row (delta-slot aggregate
    patching needs no new DeltaTable fields — the aggregate of a slot's
    pre-resolved mask is derived in-kernel from the mask itself, so the
    two can never drift)."""
    s = mask_w.shape[0] // AGG_BLOCK
    nz = (mask_w.reshape(s, AGG_BLOCK) != 0).astype(jnp.uint32)
    j = jnp.arange(AGG_BLOCK, dtype=jnp.uint32)[None, :]
    return (nz << j).sum(axis=1, dtype=jnp.uint32)  # disjoint bits: sum==OR


def _patch_agg(rows: jax.Array, ip_f: jax.Array, dt: DeltaTable, masks,
               wide=None) -> jax.Array:
    """Delta-slot aggregate patching of gathered aggregate rows (B, S):
    SET slots OR their aggregate mask in (a new member may light words the
    compiled table left dark — skipping this would be a false NEGATIVE);
    CLEAR slots leave the aggregate alone (a stale set bit is a legal
    false positive — the candidate gather fetches the full words, applies
    the full-width clear, and finds no match)."""

    def body(i, rows):
        m = _delta_lane_match(ip_f, dt, i, wide) & (dt.sign[i] > 0)
        am = _agg_mask(masks[i])[None, :]
        return jnp.where(m[:, None], rows | am, rows)

    return jax.lax.fori_loop(0, dt.n, body, rows)


def _patch_cand(cw: jax.Array, widx: jax.Array, ip_f: jax.Array,
                dt: DeltaTable, masks, wide=None) -> jax.Array:
    """_patch_rows over CANDIDATE-shaped rows (B, K, AGG_BLOCK): each
    slot's (W,) mask is gathered at the lanes' candidate word indices
    `widx` so set AND clear apply at full precision on exactly the words
    the pruned path fetched."""

    def body(i, cw):
        m = _delta_lane_match(ip_f, dt, i, wide)
        mw = masks[i][widx]  # (B, K, AGG_BLOCK) gather from (W,)
        s = dt.sign[i]
        cw = jnp.where((m & (s > 0))[:, None, None], cw | mw, cw)
        cw = jnp.where((m & (s < 0))[:, None, None], cw & ~mw, cw)
        return cw

    return jax.lax.fori_loop(0, dt.n, body, cw)


def _phase_first_from_base(mu: jax.Array, base: jax.Array, phases):
    """Per-phase first-set-bit over words with PER-ELEMENT global rule
    bases: mu (..., n) u32 match words, base (..., n) i32 = global word
    index * 32.  The _phase_hits/_phase_scan_tile_dyn mask discipline
    applied element-wise — shared by the pruned candidate scan (XLA and
    pallas consumer alike) so the three first-match paths cannot drift.
    -> 3 x (...,) i32 global rule indices (BIG = no match)."""

    def first_bounded(lo_rule, hi_rule):
        k_lo = jnp.clip(lo_rule - base, 0, 32)
        k_hi = jnp.clip(hi_rule - base, 0, 32)
        mask_lo = jnp.where(
            k_lo <= 0,
            jnp.uint32(_ALL1),
            ~((jnp.uint32(1) << jnp.minimum(k_lo, 31).astype(jnp.uint32))
              - jnp.uint32(1)),
        )
        mask_lo = jnp.where(k_lo >= 32, jnp.uint32(0), mask_lo)
        mask_hi = jnp.where(
            k_hi >= 32,
            jnp.uint32(_ALL1),
            (jnp.uint32(1) << jnp.clip(k_hi, 0, 31).astype(jnp.uint32))
            - jnp.uint32(1),
        )
        mw = mu & mask_lo & mask_hi
        lsb = mw & (jnp.uint32(0) - mw)
        tz = jax.lax.population_count(lsb - jnp.uint32(1))
        v = jnp.where(mw == jnp.uint32(0), BIG, base + tz.astype(jnp.int32))
        return jnp.min(v, axis=-1)

    n0, nk, _nb = phases
    return (
        first_bounded(0, n0),
        first_bounded(n0, n0 + nk),
        first_bounded(n0 + nk, 1 << 30),
    )


class PruneAutotuner:
    """Bounded hysteresis controller for the prune K budget (the
    DrainAutotuner pattern, fed by the measured fallback rate instead of
    queue depth).  Pure decision logic: observe(classified, fallbacks)
    -> the budget for subsequent classifies.  One rung per move, only
    after `sticky` consecutive same-direction pressure signals; empty
    windows hold."""

    def __init__(self, initial: int, sticky: int = PRUNE_STICKY,
                 fb_high: float = PRUNE_FB_HIGH, fb_low: float = PRUNE_FB_LOW):
        self.rungs = list(PRUNE_LADDER)
        self.idx = min(
            range(len(self.rungs)),
            key=lambda i: (abs(self.rungs[i] - int(initial)), self.rungs[i]),
        )
        self.sticky = int(sticky)
        self.fb_high = float(fb_high)
        self.fb_low = float(fb_low)
        self._streak = 0
        self.decisions_up = 0
        self.decisions_down = 0

    @property
    def budget(self) -> int:
        return self.rungs[self.idx]

    def observe(self, classified: int, fallbacks: int) -> int:
        if classified <= 0:
            return self.budget  # empty window: no signal, streak kept
        rate = fallbacks / classified
        if rate > self.fb_high:
            signal = 1
        elif rate < self.fb_low:
            signal = -1
        else:
            signal = 0
        if signal == 0 or (self._streak and (signal > 0) != (self._streak > 0)):
            self._streak = signal
            return self.budget
        self._streak += signal
        if self._streak >= self.sticky and self.idx < len(self.rungs) - 1:
            self.idx += 1
            self.decisions_up += 1
            self._streak = 0
        elif self._streak <= -self.sticky and self.idx > 0:
            self.idx -= 1
            self.decisions_down += 1
            self._streak = 0
        return self.budget


def _dim_index(tab, x: jax.Array, x6w, is6) -> jax.Array:
    """Interval row index for one dimension: searchsorted in the v4
    sub-space, or (for v6 lanes) the lexicographic v6 sub-space offset by
    the v4 rows (DimTable dual-stack layout).  x6w=None = no v6 lanes for
    this probe (pure-v4 batch, or the family-blind svc key space).  The
    ONE derivation — shared by the full-width and pruned classify paths
    so the v6 index math cannot drift between them."""
    i4 = _searchsorted_right(tab.bounds, x)
    if x6w is None:
        return i4
    i6 = tab.bounds.shape[0] + 1 + _searchsorted6(tab.bounds6, x6w)
    return jnp.where(is6 != 0, i6, i4)


def _svcref_key(svc_key: jax.Array, svc_ref) -> jax.Array:
    """The toServices SECOND probe key (compiler SVCREF_BASE contract):
    the lane's ServiceLB-resolved service index mapped into the reference
    sub-space, SVCREF_NONE for non-service lanes.  The ONE derivation —
    shared by the full-width and pruned classify paths so the probe-key
    contract cannot drift between them."""
    from ..compiler.compile import SVCREF_BASE, SVCREF_NONE

    if svc_ref is None:
        return jnp.full_like(svc_key, SVCREF_NONE)
    return jnp.where(svc_ref >= 0, SVCREF_BASE + svc_ref, SVCREF_NONE)


def _phase_hits(match: jax.Array, word_idx: jax.Array, phases: tuple[int, int, int]):
    """match (B, W) u32 -> per-phase first-set global rule index (BIG = none).

    First-match-in-priority-order == lowest set bit: rule order encodes
    priority (compiler/compile.py), bit r of word w is global rule 32w+r.
    """
    n0, nk, _nb = phases
    base = word_idx * 32  # (W,) i32

    def mask_lt(n: int) -> jax.Array:
        """(W,) u32 — bits whose global rule index < n."""
        k = jnp.clip(n - base, 0, 32)
        m = (jnp.uint32(1) << jnp.minimum(k, 31).astype(jnp.uint32)) - jnp.uint32(1)
        return jnp.where(k >= 32, jnp.uint32(_ALL1), m)

    m0 = mask_lt(n0)
    mhi = mask_lt(n0 + nk)
    phase_masks = (m0, mhi & ~m0, ~mhi)

    def first(pm: jax.Array) -> jax.Array:
        mw = match & pm[None, :]
        lsb = mw & (jnp.uint32(0) - mw)
        tz = jax.lax.population_count(lsb - jnp.uint32(1))  # 32 when mw == 0
        idx = base[None, :] + tz.astype(jnp.int32)
        idx = jnp.where(mw == jnp.uint32(0), BIG, idx)
        return idx.min(axis=1)

    return tuple(first(pm) for pm in phase_masks)


# Optimization note (measured on v5e, 100k rules, B=32k): replacing the
# three full-width masked scans with STATIC per-phase word slices (phases
# are contiguous rule ranges, so each phase only owns words
# [lo//32, ceil(hi/32))) was tried and is ~1.5x SLOWER (8.3ms vs 5.6ms per
# batch) — the slices break XLA's fusion of gather -> AND -> scan into one
# streaming loop and force the (B, W) match tensor to materialize.
#
# Negative result (round 3, measured on the 100k-rule bench world): a
# TWO-LEVEL incidence hierarchy (per-dimension 32-word block summaries,
# AND the summaries, walk only candidate blocks) does NOT pay: per-DIM
# summary density is 0.90/0.94/1.00 (at/peer/svc), so the summary AND
# leaves ~86% of blocks as candidates (51 of 59 per packet) even though
# true matches average 0.7 rules/packet — the sparsity lives in the 3-way
# intersection, which is only knowable after the gathers the hierarchy
# was meant to avoid.
#
# Round-4 cold-path study (all measured on the axon v5e + this Mosaic
# toolchain, 100k-rule bench world, B=32k; scripts preserved in the round
# notes).  Cost decomposition of the round-3 classifier at 7.0ms/batch
# (4.6M pps): searchsorted 0.77ms; the 6 row gathers ALONE are 4.4ms —
# XLA's gather engine runs at ~84% of HBM peak but counts double, because
# gather output always round-trips HBM (read 1.23GB + write 1.23GB), and
# every unfused consumer re-reads it.  Attempts to eliminate the
# write-back, each DEAD by measurement:
#   1. Pallas scalar-prefetch pipelined per-row loads (grid over packet
#      tiles, BlockSpec index_map from prefetched interval indices):
#      38 GB/s — the per-DMA fixed cost is ~200ns/row and 196k rows/batch
#      need <8ns each.  No DMA-descriptor path can fetch scattered ~7KB
#      rows at line rate; only XLA's gather engine can.
#   2. In-VMEM dynamic gather (tpu.dynamic_gather via take_along_axis):
#      Mosaic lowers it INTRA-VREG ONLY — sublane gathers beyond 8 rows
#      and lane gathers beyond 128 lanes crash the backend.  Arbitrary
#      VMEM table gathers are unavailable on this toolchain.
#   3. Cluster-compressed incidence (u8 ids into VMEM-resident distinct
#      sub-row tables, expanded by intra-vreg lane gather): per-128-word
#      chunk the bench world has 850-3240 DISTINCT sub-rows per dimension
#      — far beyond the 128-lane gather reach.  Genuine entropy.
#   4. Rule-triple dedup (rules sharing (at,peer,svc) gids have identical
#      match conditions; per-phase triple bitmaps ordered by first-rule
#      priority preserve first-match-=-first-bit): distinct-triple ratio
#      measured 1.00x — every rule is a unique triple here.  Zero width
#      reduction.
#   5. MXU one-hot expansion (radix-partitioned packets x 128-row blocks):
#      O(B x 128 x W) FLOPs = ~4ms at bf16 peak before sort costs.  The
#      128x FLOP blowup over the gather's O(B x W) never pays.
# Roofline conclusion: per-packet row volume is ~37.5KB (irreducible —
# notes 2-4 above rule out structural sparsity), and the only functional
# fetch path (XLA gather) doubles it.  2 x 37.5KB at the measured
# 684 GB/s is 9.1M pps for the gather alone, before searchsorted and the
# scan — so ~10M pps cold is out of reach on this chip/toolchain, and the
# remaining winnable margin was the unfused-consumer re-reads.  That win
# is taken by classify_batch_fused below: XLA performs the 6 gathers, ONE
# pallas kernel consumes each gathered byte exactly once (AND + per-phase
# first-set-bit in VMEM, contiguous 1MB block DMAs), measured 6.3ms vs
# 7.1ms (5.2M vs 4.6M pps).  The honest gap to the 10M target is
# reported, not hidden, in bench.py's cold extras.
#
# Round-5 follow-up (round-4 verdict weak #1 asked whether the
# 1.9ms/batch of non-gather time could be overlapped or folded; same
# world, B=32k, /tmp/cold_study.py methodology):
#   Measured decomposition: searchsorted ALONE 1.52ms; searchsorted +
#   6 gathers + a reduction FUSED into the gather loops 4.44ms; fused
#   end-to-end 6.80ms.  4.44 equals the round-4 "gathers alone" bound —
#   i.e. searchsorted is ALREADY hidden under the gather streams (its
#   1.52ms of VPU compare work overlaps the DMA wavefronts inside XLA's
#   fused loops).  Verdict idea (a), "overlap searchsorted with the
#   gather stream", is therefore already in effect; there is no further
#   cross-op overlap to program — a TensorCore runs one XLA op at a
#   time, and fusion is the only overlap mechanism exposed.
#   Verdict idea (b), "fold the two-level searchsorted's in-block finish
#   into the consumer kernel": the in-block finish needs a per-lane
#   dynamic 256-word window from the bounds table — exactly the
#   arbitrary-VMEM-gather shape note 2 above measured as unavailable
#   (Mosaic dynamic_gather is intra-vreg only).  Dead by the same wall.
#   New idea (c), AND the three gathered rows IN XLA and hand the pallas
#   consumer ONE matrix per direction (hoping gather->AND fuses and
#   halves the consumer's read volume): measured 7.61ms — WORSE than the
#   6-input consumer.  XLA materializes all six gather outputs AND the
#   two AND results (multi-consumer gathers don't fuse into one loop),
#   adding ~12.5KB/packet of traffic instead of removing any.
# Residual: end-to-end minus the gather bound is 2.36ms — the pallas
# consumer's re-read of the 37.5KB/packet the gathers materialized
# (37.5KB x 32k / 684 GB/s = 1.75ms floor + tile scheduling).  Removing
# it requires gathering INTO the consumer, which note 1 bounds at
# 38 GB/s.  The cold ceiling on this chip/toolchain therefore stands at
# ~4.8-5.4M pps as shipped, with ~7.4M the hard gather-bound limit.
#
# Round-6 overlap study (ROADMAP item 2: the churn gap is SERIALIZATION,
# not kernel speed — BENCH_r05 steady_churn 4.97M pps = 26.4ms per 131k
# batch, vs the Amdahl prediction of the measured parts: 5.7ms fast step
# + ~3.4ms for one coalesced 16k drain = 9.1ms, ~14M pps.  The ~17ms gap
# is the drain pipeline running IN SEQUENCE with the fast path: lookup
# pass, classify, commit scatters, eviction gather, plus the engine's
# two separate full-table maintenance scans and the per-call output
# fetch blocking the next dispatch).  What was restructured, and what
# was ruled out:
#   OVERLAPPED (shipped, models/pipeline + datapath/slowpath):
#   (a) eviction-scan + aging + revalidation folded into the drain's
#       commit pass (meta.drain_reclaim): the PH_EVICT audit already
#       gathers each insert target's old key row; reading its ts/conf in
#       the same pass classifies dead rows (idle-expired / stale-gen) as
#       reclaims, so the engine's stale-epoch heal needs ONE fused
#       maintain_scan (age + revalidate in a single keys/meta/ts read)
#       instead of two full passes over PipelineState — at 2^22 slots
#       that removes ~150MB of HBM traffic per heal.
#   (b) the drain dispatched with the STATE DONATED
#       (pl.pipeline_step_donated): without donation every per-call
#       drain allocates fresh output buffers for the rewritten cache
#       columns (~150MB at 2^22 slots) and copies; donation lets XLA
#       alias the scatters in place — the eager-dispatch analog of the
#       fori_loop carry aliasing the bench already enjoyed.
#   (c) one-step commit deferral (two-slot staging): drain of window i-1
#       dispatches after fast step i with no dependency on its OUTPUTS
#       (only the carried state), and the host-side materialization of
#       drain outputs retires two slots later — so the host never blocks
#       the device pipeline on np.asarray between fast and drain, and
#       XLA/the runtime can pipeline the dispatch stream.  Verdict
#       visibility lags exactly one window (the admitted lanes' flows
#       were pending anyway); state visibility is immediate via the
#       carried pytree (the lost-update guard).
#   NOT overlapped, dead by the same walls as rounds 4-5:
#   (d) lowering the commit scatters into the pallas classify consumer
#       (one kernel classifying + writing the cache): Mosaic on this
#       toolchain has no arbitrary-VMEM-scatter path, the same wall as
#       note 2's intra-vreg-only dynamic_gather — and the flow cache is
#       64MB+ per column, far beyond VMEM residency anyway.
#   (e) true cross-op concurrency: a TensorCore runs one XLA op at a
#       time, so "overlap" here means removing redundant passes, copies
#       and host round-trips from the serial schedule, not co-executing
#       fast and drain — the honest mechanism, and why the decomposition
#       (bench_cold_study.py case 5: fast alone / drain alone /
#       serialized / overlapped) is the proof obligation: the overlapped
#       step time must approach max-ish(fast, drain) only through the
#       removed work, and serialized-minus-overlapped IS the recovered
#       serialization.  On-chip numbers land with BENCH_r06 /
#       PROFILE bench_profile.py --mode overlap (the ±15% gate
#       cross-checks the attribution); this container is CPU-only, so
#       the r06 record is the bench's to write, not this note's.
#
# Round-7: aggregated-bitmap pruning (ROADMAP item 2's kernel half; the
# two-level classify shipped below as _classify_pruned).  Why this is NOT
# the round-3 negative result re-tried: round 3 summarized at 32-WORD
# block granularity (one bit per 1024 rules), where per-dim summary
# density was 0.90/0.94/1.00 and the AND left 86% of blocks candidates.
# The round-7 aggregate is one bit per WORD (32 rules) — 32x finer — and
# the 32-word superblock is the candidate unit only for the SECOND
# gather's shape (contiguous 128B block rows, the fast TPU gather
# pattern), not for the pruning decision: a superblock is live iff its
# aggregate WORD is nonzero, i.e. iff at least one of its 32
# word-granular AND bits survives.  The ABV lesson (aggregated bit
# vectors over sparse rule bitmaps) is that the 3-way AND at word
# granularity is what is sparse, and that is knowable from ~W/32 words
# per dimension instead of W.  Volume math at the bench world (W=3136
# agg-padded, S=98): phase 1 gathers 6 x 98 u32 = ~2.4KB/packet (vs
# ~75KB full-width, XLA's gather write-back doubling both); phase 2 at
# K=4 adds 6 x 128 words = ~3KB for lanes the aggregate AND leaves live
# — ~12x less candidate-path row volume, moving the ~7.4M pps hard
# gather bound (round 4) past the 10M target, while the
# aggregate-AND-zero short circuit drops the adversarial/all-miss
# regime to phase-1 volume alone.  Exactness is structural, not
# statistical: aggregate bits admit false positives (the candidate
# gather then finds an all-zero AND -> default verdict) but never false
# negatives, and lanes whose candidate count exceeds K redispatch at
# full width in a pow2-rung lax.switch (the PR 9 _spill_retry shape,
# in-jit), metered as match_prune_fallbacks_total and fed to the
# K-budget autotuner (PruneAutotuner, the PR 6 DrainAutotuner pattern).
# Decomposition + fallback-rate-vs-K + match-density sweeps:
# bench_cold_study.py case 6; per-phase attribution: PRUNE_PHASE_CHAIN
# (prune_summary_gather vs prune_candidate_gather) under the ±15% gate.
# This container is CPU-only — the on-chip r07 cold/churn numbers, and
# the honest fallback rate beside them, are the driver's to write.
#
# Round-8: the one-kernel fast path (_onepass_call + models/pipeline
# meta.onepass; ROADMAP item 1).  The round-4/7 residual past the gather
# bound (~2.36ms/batch) is XLA's STAGE BOUNDARIES: cache probe,
# aggregate AND, candidate gather, first-match and LB/verdict resolution
# are separate fusions with HBM materialization between them, and every
# boundary re-reads what the previous stage wrote.  The one-pass kernel
# keeps per-lane state in VMEM end to end: probe decode, aggregate AND +
# zero-AND short circuit, candidate-superblock DMA (double-buffered —
# half-block j+1's AND/top-K/DMA-issue overlap the wait on half-block
# j's copies), the shared _phase_first_from_base scan, verdict
# resolution and commit-ROW packing, one pass per batch.  What stays
# XLA, and why (the measured walls above): the index-driven row gathers
# feeding the kernel (cache row, aggregate rows, LB probe chain — note 1
# bounds any DMA-descriptor fetch of scattered rows at 38 GB/s; XLA's
# gather engine is the only fast fetch path), the commit SCATTERS (study
# idea (d): no arbitrary-VMEM-scatter path on this Mosaic, and the cache
# exceeds VMEM — but their INPUT ROWS are now kernel outputs, so the
# classify->commit materialization is gone), and the pow2-rung fallback
# redispatch (full-width rows are an XLA gather by the same note-1
# wall).  Under rule sharding the kernel emits GLOBAL hits for the pmin
# seam and resolution runs post-allreduce (`resolve=False`) — the
# cross-shard first-match needs the ICI combine between scan and
# resolve, physics no fusion removes.  HONEST RISKS for the on-chip
# measurement (the driver's r08 numbers): the candidate path issues
# ~6*K small DMAs per live lane plus 4 single-word action DMAs — at the
# note-1 ~200ns/DMA fixed cost the double buffer must hide ~(6K+4)*200ns
# per lane behind the AND/scan compute, and a large K x rung product can
# exceed VMEM scratch; both failure modes FALL BACK to the staged
# kernel (construct with fused=False — bit-identical verdicts, the
# parity suite pins it), never to a wrong verdict.  WHAT THE COUNTERS
# DECIDE (hot-path telemetry, observability/telemetry.py): with
# PipelineMeta.telemetry on, every dispatch emits tel_dma_hb — the
# _OP_HB half-blocks this schedule walked, a physical constant of the
# padded batch shape (models/pipeline.py derives it next to the probe
# hit/stale/miss split) — and those counters are the PRODUCTION inputs
# to the batching call above: dma_hb x (6K+4) x ~200ns is the fixed DMA
# cost the double buffer must currently be hiding, so a steady-regime
# p99 that climbs (the sentinel's perf-regression verdict) while
# dma_hb/step holds flat means the overlap stopped covering the
# descriptor cost — the operator reads that as "fall back to
# fused=False" from the journal, before any bench run reproduces it.
# Interpret mode (fused_interpret / CPU platform) runs the whole kernel
# on the CPU tier, which is what tests/test_match_fused.py certifies.


def _resolve(action: jax.Array, hits, pod_iso: jax.Array):
    """Phase resolution -> (code (B,), rule_idx (B,) [-1 = default])."""
    h0, hk, hb = hits
    a0 = action[jnp.clip(h0, 0, action.shape[0] - 1)]
    ab = action[jnp.clip(hb, 0, action.shape[0] - 1)]
    return _resolve_from_actions(a0, ab, hits, pod_iso)


def _resolve_from_actions(a0: jax.Array, ab: jax.Array, hits,
                          pod_iso: jax.Array):
    """_resolve with the two action gathers already performed — the seam
    the one-pass kernel (round 8) resolves through: it fetches a0/ab by
    per-lane DMA instead of an XLA gather, then runs the IDENTICAL phase
    resolution, so the two paths cannot drift."""
    h0, hk, hb = hits
    has0 = h0 < BIG
    hask = hk < BIG
    hasb = hb < BIG

    decided0 = has0 & (a0 != ACT_PASS)
    decidedb = hasb & (ab != ACT_PASS)

    # K8s NP rules are any-match ALLOW within the isolation model.
    k8s_code = jnp.where(hask, ACT_ALLOW, ACT_DROP)
    k8s_rule = jnp.where(hask, hk, -1)

    code = jnp.where(
        decided0,
        a0,
        jnp.where(
            pod_iso == 1,
            k8s_code,
            jnp.where(decidedb, ab, ACT_ALLOW),
        ),
    )
    rule = jnp.where(
        decided0,
        h0,
        jnp.where(
            pod_iso == 1,
            k8s_rule,
            jnp.where(decidedb, hb, -1),
        ),
    )
    return code.astype(jnp.int32), rule.astype(jnp.int32)


_SS_BLOCK = 256  # ~sqrt(NB) at the 100k-rule scale; compares/pkt = NB/256+256


def _searchsorted_right(bounds: jax.Array, x: jax.Array) -> jax.Array:
    """TPU-tuned searchsorted(side='right').

    jnp's default 'scan' (binary-search) method lowers to a sequential
    gather loop that is ~40x slower on TPU than an all-pairs compare-reduce
    for our table sizes (measured on v5e: 10.9 ms vs 0.28 ms at B=32k,
    NB=33k).  compare_all is O(B*NB) and wins up to a few thousand bounds;
    beyond that a TWO-LEVEL blocked search cuts the compare volume ~128x:
    compare_all over the ~NB/256 block maxima picks the block, one (B, 256)
    row gather + mask-count finishes inside it.  Both levels are streaming
    VPU work with static shapes (vmap/shard_map friendly).
    """
    nb = bounds.shape[0]
    if nb <= 4096:
        return jnp.searchsorted(bounds, x, side="right", method="compare_all")
    K = _SS_BLOCK
    nblk = -(-nb // K)
    pad = nblk * K - nb
    # Pads sit at int32 max; they are masked out of the in-block count, so a
    # genuine max-valued bound (flip of 0xFFFFFFFF) still counts correctly.
    bp = jnp.concatenate(
        [bounds, jnp.full((pad,), 2**31 - 1, bounds.dtype)]
    ).reshape(nblk, K)
    blk = jnp.searchsorted(bp[:, -1], x, side="right", method="compare_all")
    blk_c = jnp.minimum(blk, nblk - 1)
    window = bp[blk_c]  # (B, K) row gather
    off = jnp.arange(K, dtype=jnp.int32)
    valid = (blk_c[:, None] * K + off[None, :]) < nb
    inblock = ((window <= x[:, None]) & valid).sum(axis=1, dtype=jnp.int32)
    return blk_c * K + inblock


def _searchsorted6(bounds6: jax.Array, xw: jax.Array) -> jax.Array:
    """Lexicographic searchsorted(side='right') over 4-word v6 boundaries.

    bounds6 (N, 4) and xw (B, 4) are per-word sign-flipped i32, so word-wise
    signed compares give unsigned lexicographic order.  v6 boundary tables
    are small (group CIDR endpoints), so all-pairs compare-count is the
    right TPU shape (see _searchsorted_right's rationale).
    """
    n = bounds6.shape[0]
    if n == 0:
        return jnp.zeros(xw.shape[0], dtype=jnp.int32)
    leq = _lex_le4(bounds6[None, :, :], xw[:, None, :])  # (B, N)
    return leq.sum(axis=1, dtype=jnp.int32)


def classify_batch(
    drs: DeviceRuleSet,
    src_ip_f: jax.Array,  # (B,) sign-flipped i32
    dst_ip_f: jax.Array,
    proto: jax.Array,  # (B,) i32
    dst_port: jax.Array,  # (B,) i32
    *,
    meta: StaticMeta,
    hit_combine=None,
    fused: bool = False,
    v6=None,
    svc_ref=None,
    summary_only: bool = False,
):
    """-> dict with final/egress/ingress codes and deciding rule indices.

    Codes use the oracle encoding: 0 allow, 1 drop, 2 reject.

    hit_combine, if given, is applied to each per-phase first-match hit
    tensor between the word scan and phase resolution — the rule-parallel
    seam: a shard_map caller passes ``lambda h: lax.pmin(h, 'rule')`` so
    each rule shard ANDs only its local incidence words and the global first
    match is an all-reduce over ICI (the TPU analog of OVS evaluating one
    shared table).

    v6, if given, is the dual-stack lane extension (ref pipeline.go IPv6
    table): a (src6w_f (B,4), dst6w_f (B,4), is6 (B,)) tuple of per-word
    sign-flipped v6 addresses plus the family mask.  v6 lanes resolve in
    each dimension's v6 interval sub-space; their v4-lane inputs are
    ignored.  None = pure-v4 batch (zero extra work — the v4 interval rows
    come first, so indices need no adjustment).

    fused=True consumes the gathered rows through the pallas consumer
    kernel (one read per gathered byte; see the cold-path study above).
    Composes with hit_combine's rule-axis sharding: each shard's kernel
    receives its global word offset (word_idx[0], carried as data for
    exactly this) and emits GLOBAL rule indices, so the pmin all-reduce
    combines them like the XLA-scan path — the sharded walk keeps the
    fused cold-path win.  Delta patching composes (it runs on the
    gathered rows before the consumer).  Off-TPU the kernel runs in
    interpret mode (slow; parity tests only).

    meta.prune_budget > 0 routes through the two-level aggregated-bitmap
    path (_classify_pruned, round 7); summary_only is its profiling
    sub-mode (aggregate phase only, live lanes take defaults — the
    PH_CLS_SUM surface, never a production verdict path) and is ignored
    when pruning is off.
    """
    if meta.prune_budget > 0 and drs.ingress.at.agg is not None:
        return _classify_pruned(
            drs, src_ip_f, dst_ip_f, proto, dst_port, meta=meta,
            hit_combine=hit_combine, fused=fused, v6=v6, svc_ref=svc_ref,
            summary_only=summary_only,
        )
    ing, eg = drs.ingress, drs.egress
    svc_key = (proto << 16) | dst_port
    if v6 is not None:
        src6w, dst6w, is6 = v6
    else:
        is6 = None

    def dim_row(tab: DimTable, x: jax.Array, x6w=None) -> jax.Array:
        # x6w is None for the svc dimension (the (proto<<16|port) key
        # space is shared by both families — no v6 sub-space).
        return tab.inc[_dim_index(tab, x, x6w, is6)]

    def iso_bit(tab: IsoTable, x: jax.Array, x6w=None) -> jax.Array:
        return tab.val[_dim_index(tab, x, x6w, is6)]

    # Ingress: pod = dst, peer = src.  Egress: pod = src, peer = dst.
    s6 = src6w if v6 is not None else None
    d6 = dst6w if v6 is not None else None
    in_at = dim_row(ing.at, dst_ip_f, d6)
    in_peer = dim_row(ing.peer, src_ip_f, s6)
    in_svc = dim_row(ing.svc, svc_key)
    out_at = dim_row(eg.at, src_ip_f, s6)
    out_peer = dim_row(eg.peer, dst_ip_f, d6)
    out_svc = dim_row(eg.svc, svc_key)
    if meta.svcref:
        # toServices probe (the ServiceGroupID-conjunction analog): a
        # second egress svc-dim gather keyed on the lane's ServiceLB
        # resolution in the reference sub-space.  OR is exact — ordinary
        # port ranges live below SVCREF_BASE and reference ranges at
        # SVCREF_BASE + idx, so each rule can match via exactly one of
        # the two probes (compiler/compile.py SVCREF_BASE contract).
        out_svc = out_svc | dim_row(eg.svc, _svcref_key(svc_key, svc_ref))
    iso_in = iso_bit(drs.iso_in, dst_ip_f, d6)
    iso_out = iso_bit(drs.iso_out, src_ip_f, s6)

    if meta.delta_slots > 0:
        # Incremental membership deltas patch the gathered rows, so peer/
        # appliedTo/isolation consumers all see post-delta membership.
        # Slots are family-pure: v4 slots patch v4 lanes on the narrow
        # column, v6 slots patch v6 lanes on their wide words — v6 pod
        # churn stays O(1), no recompile (DeltaTable docstring).
        d = drs.ip_delta
        wide_d = None if v6 is None else (d6, is6)
        wide_s = None if v6 is None else (s6, is6)
        in_at = _patch_rows(in_at, dst_ip_f, d, d.at_in, wide_d)
        in_peer = _patch_rows(in_peer, src_ip_f, d, d.peer_in, wide_s)
        out_at = _patch_rows(out_at, src_ip_f, d, d.at_out, wide_s)
        out_peer = _patch_rows(out_peer, dst_ip_f, d, d.peer_out, wide_d)
        iso_in = _patch_iso(iso_in, dst_ip_f, d, 0, wide_d)
        iso_out = _patch_iso(iso_out, src_ip_f, d, 1, wide_s)

    if fused:
        shard = hit_combine is not None
        in_hits, out_hits = _fused_hits(
            (in_at, in_peer, in_svc), (out_at, out_peer, out_svc), meta,
            w0_in=ing.word_idx[0] if shard else None,
            w0_out=eg.word_idx[0] if shard else None,
        )
    else:
        in_hits = _phase_hits(
            in_at & in_peer & in_svc, ing.word_idx, meta.in_phases
        )
        out_hits = _phase_hits(
            out_at & out_peer & out_svc, eg.word_idx, meta.out_phases
        )

    if hit_combine is not None:
        in_hits = tuple(hit_combine(h) for h in in_hits)
        out_hits = tuple(hit_combine(h) for h in out_hits)

    in_code, in_rule = _resolve(ing.action, in_hits, iso_in)
    out_code, out_rule = _resolve(eg.action, out_hits, iso_out)

    final = jnp.where(out_code != ACT_ALLOW, out_code, in_code)
    return {
        "code": final,
        "egress_code": out_code,
        "egress_rule": out_rule,
        "ingress_code": in_code,
        "ingress_rule": in_rule,
    }


# ---------------------------------------------------------------------------
# Fused consumer kernel (the round-4 cold-path lever; see the study above):
# XLA performs the row gathers, one pallas kernel then consumes each
# gathered byte exactly once — AND + per-phase first-set-bit entirely in
# VMEM, fed by contiguous ~1MB block DMAs instead of XLA's materialize-and-
# re-read consumer chain.
# ---------------------------------------------------------------------------

_FUSE_TB = 128  # packet rows per grid step (~4.8MB of VMEM blocks, 2x buffered)


def _phase_scan_tile(m, w, phases):
    """(TB, w) i32 match tile -> per-phase first-set global rule index.

    Phases are contiguous rule ranges, so each phase owns a STATIC word
    slice; only its two boundary words need bit masking.  Inside pallas
    there is no XLA-fusion concern (the round-3 negative result on static
    slices was about breaking XLA loop fusion), so the sliced form wins.
    """
    mu = m.astype(jnp.uint32)

    def first_bounded(lo_rule, hi_rule):
        if lo_rule >= hi_rule:
            return jnp.full((m.shape[0],), BIG, jnp.int32)
        lo_w, hi_w = lo_rule // 32, -(-hi_rule // 32)
        sub = mu[:, lo_w:hi_w]
        base = jax.lax.broadcasted_iota(
            jnp.int32, (m.shape[0], hi_w - lo_w), 1
        ) * 32 + lo_w * 32
        k_lo = jnp.clip(lo_rule - base, 0, 32)
        k_hi = jnp.clip(hi_rule - base, 0, 32)
        mask_lo = jnp.where(
            k_lo <= 0,
            jnp.uint32(_ALL1),
            ~((jnp.uint32(1) << jnp.minimum(k_lo, 31).astype(jnp.uint32))
              - jnp.uint32(1)),
        )
        mask_lo = jnp.where(k_lo >= 32, jnp.uint32(0), mask_lo)
        mask_hi = jnp.where(
            k_hi >= 32,
            jnp.uint32(_ALL1),
            (jnp.uint32(1) << jnp.clip(k_hi, 0, 31).astype(jnp.uint32))
            - jnp.uint32(1),
        )
        mw = sub & mask_lo & mask_hi
        lsb = mw & (jnp.uint32(0) - mw)
        tz = jax.lax.population_count(lsb - jnp.uint32(1))
        v = jnp.where(mw == jnp.uint32(0), BIG, base + tz.astype(jnp.int32))
        return jnp.min(v, axis=1)

    n0, nk, _nb = phases
    return (
        first_bounded(0, n0),
        first_bounded(n0, n0 + nk),
        first_bounded(n0 + nk, w * 32),
    )


def _phase_scan_tile_dyn(m, w, phases, w0):
    """_phase_scan_tile with a DYNAMIC global word offset (the rule-axis
    shard seam): this tile's words are global words [w0, w0+w), so phase
    boundaries cannot be static slices — each phase masks the full width
    by its global-rule window instead (the _phase_hits mask discipline,
    inside VMEM).  w0 is a traced scalar from word_idx, NOT a python int."""
    mu = m.astype(jnp.uint32)
    base = (jax.lax.broadcasted_iota(jnp.int32, (m.shape[0], w), 1)
            + w0) * 32

    def first_bounded(lo_rule, hi_rule):
        k_lo = jnp.clip(lo_rule - base, 0, 32)
        k_hi = jnp.clip(hi_rule - base, 0, 32)
        mask_lo = jnp.where(
            k_lo <= 0,
            jnp.uint32(_ALL1),
            ~((jnp.uint32(1) << jnp.minimum(k_lo, 31).astype(jnp.uint32))
              - jnp.uint32(1)),
        )
        mask_lo = jnp.where(k_lo >= 32, jnp.uint32(0), mask_lo)
        mask_hi = jnp.where(
            k_hi >= 32,
            jnp.uint32(_ALL1),
            (jnp.uint32(1) << jnp.clip(k_hi, 0, 31).astype(jnp.uint32))
            - jnp.uint32(1),
        )
        mw = mu & mask_lo & mask_hi
        lsb = mw & (jnp.uint32(0) - mw)
        tz = jax.lax.population_count(lsb - jnp.uint32(1))
        v = jnp.where(mw == jnp.uint32(0), BIG, base + tz.astype(jnp.int32))
        return jnp.min(v, axis=1)

    n0, nk, _nb = phases
    # Baseline phase upper bound: unbounded (padding words carry zero bits).
    return (
        first_bounded(0, n0),
        first_bounded(n0, n0 + nk),
        first_bounded(n0 + nk, 1 << 30),
    )


@lru_cache(maxsize=32)
def _consumer_call(b, w_in, w_out, in_phases, out_phases, interpret,
                   sharded):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    tb = _FUSE_TB
    if sharded:
        # Shard-aware variant: two SMEM scalars carry each direction's
        # global word offset (word_idx[0] — data, so the SAME compiled
        # kernel serves every rule shard under shard_map).
        def kernel(ia, ip_, is_, oa, op_, os_, w0i, w0o, o_ref):
            i0, ik, ib = _phase_scan_tile_dyn(
                ia[:] & ip_[:] & is_[:], w_in, in_phases, w0i[0, 0])
            o0, ok_, ob = _phase_scan_tile_dyn(
                oa[:] & op_[:] & os_[:], w_out, out_phases, w0o[0, 0])
            o_ref[:] = jnp.stack(
                [i0, ik, ib, o0, ok_, ob,
                 jnp.zeros_like(i0), jnp.zeros_like(i0)], axis=1,
            )

        extra = [pl.BlockSpec((1, 1), lambda i: (0, 0),
                              memory_space=pltpu.SMEM)] * 2
    else:
        def kernel(ia, ip_, is_, oa, op_, os_, o_ref):
            i0, ik, ib = _phase_scan_tile(
                ia[:] & ip_[:] & is_[:], w_in, in_phases)
            o0, ok_, ob = _phase_scan_tile(
                oa[:] & op_[:] & os_[:], w_out, out_phases)
            o_ref[:] = jnp.stack(
                [i0, ik, ib, o0, ok_, ob,
                 jnp.zeros_like(i0), jnp.zeros_like(i0)], axis=1,
            )

        extra = []

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, 8), jnp.int32),
        grid=(b // tb,),
        in_specs=[pl.BlockSpec((tb, w), lambda i: (i, 0))
                  for w in (w_in, w_in, w_in, w_out, w_out, w_out)] + extra,
        out_specs=pl.BlockSpec((tb, 8), lambda i: (i, 0)),
        interpret=interpret,
    )


def _fused_hits(rows_in, rows_out, meta: StaticMeta, w0_in=None, w0_out=None):
    """6 gathered row sets -> (in_hits, out_hits) via the fused consumer.

    Pads the batch to the tile multiple (tiny worlds / odd slow-path
    chunks); interpret mode keeps the kernel testable off-TPU.

    w0_in/w0_out (traced scalars): each direction's global word offset —
    pass word_idx[0] under rule-axis shard_map so the kernel emits GLOBAL
    rule indices that compose with the hit_combine pmin (the shard seam;
    None = single-chip, offsets statically zero).  Widths come from the
    rows themselves (per-shard width != meta.w_* under sharding).
    """
    b = rows_in[0].shape[0]
    w_in = rows_in[0].shape[1]
    w_out = rows_out[0].shape[1]
    pad = (-b) % _FUSE_TB
    if pad:
        rows_in = tuple(jnp.pad(r, ((0, pad), (0, 0))) for r in rows_in)
        rows_out = tuple(jnp.pad(r, ((0, pad), (0, 0))) for r in rows_out)
    if meta.fused_interpret is not None:
        interpret = meta.fused_interpret
    else:
        interpret = jax.devices()[0].platform == "cpu"
    sharded = w0_in is not None
    call = _consumer_call(
        b + pad, w_in, w_out, meta.in_phases, meta.out_phases,
        interpret, sharded,
    )
    if sharded:
        scal = lambda x: jnp.asarray(x, jnp.int32).reshape(1, 1)  # noqa: E731
        hits = call(*rows_in, *rows_out, scal(w0_in), scal(w0_out))[:b]
    else:
        hits = call(*rows_in, *rows_out)[:b]
    return (hits[:, 0], hits[:, 1], hits[:, 2]), (hits[:, 3], hits[:, 4], hits[:, 5])


# ---------------------------------------------------------------------------
# Two-level aggregated-bitmap pruning (round 7; see the study notes above).
# Phase 1 gathers only the aggregate rows (~W/32 words per dimension), ANDs
# them per direction, and proves most lanes no-match outright; phase 2
# gathers the K lowest candidate superblocks (K x AGG_BLOCK words) and
# finishes the first-match scan on them; lanes with more than K candidate
# superblocks redispatch at full width inside a pow2-rung lax.switch (the
# in-jit analog of the PR 9 _spill_retry shape) so verdicts are always
# exact — the aggregate layer can cost a fallback, never flip a verdict.
# ---------------------------------------------------------------------------


@lru_cache(maxsize=32)
def _pruned_consumer_call(b, kw_in, kw_out, in_phases, out_phases, interpret):
    """Pallas consumer for the pruned candidate matrices: per direction,
    3 x (B, K*AGG_BLOCK) candidate words + 1 x (B, K*AGG_BLOCK) i32
    per-element rule-base matrix (global word index * 32 — the base folds
    in the rule-shard word offset, so one compiled kernel serves every
    shard and emits GLOBAL rule indices for the pmin seam)."""
    from jax.experimental import pallas as pl

    tb = _FUSE_TB

    def kernel(ia, ip_, is_, bi, oa, op_, os_, bo, o_ref):
        i0, ik, ib = _phase_first_from_base(
            ia[:] & ip_[:] & is_[:], bi[:], in_phases)
        o0, ok_, ob = _phase_first_from_base(
            oa[:] & op_[:] & os_[:], bo[:], out_phases)
        o_ref[:] = jnp.stack(
            [i0, ik, ib, o0, ok_, ob,
             jnp.zeros_like(i0), jnp.zeros_like(i0)], axis=1,
        )

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, 8), jnp.int32),
        grid=(b // tb,),
        in_specs=[pl.BlockSpec((tb, w), lambda i: (i, 0))
                  for w in (kw_in, kw_in, kw_in, kw_in,
                            kw_out, kw_out, kw_out, kw_out)],
        out_specs=pl.BlockSpec((tb, 8), lambda i: (i, 0)),
        interpret=interpret,
    )


def _classify_pruned(
    drs: DeviceRuleSet,
    src_ip_f: jax.Array,
    dst_ip_f: jax.Array,
    proto: jax.Array,
    dst_port: jax.Array,
    *,
    meta: StaticMeta,
    hit_combine=None,
    fused: bool = False,
    v6=None,
    svc_ref=None,
    summary_only: bool = False,
):
    """Two-level pruned classify (classify_batch's round-7 fast path).

    Exactness: an aggregate bit is set iff its incidence word is nonzero
    (build_agg), so a zero aggregate AND proves a zero full AND (no false
    negatives) and candidates are a superset of match words.  Candidate
    superblocks are scanned LOWEST-FIRST (first-match priority == lowest
    set bit), so any phase hit found within the K lowest candidates is
    the true first match; only a phase that found NOTHING on a lane with
    more than K candidates is unproven — those lanes redispatch at full
    width.  Delta slots patch the aggregate rows conservatively (SET ORs
    the slot's aggregate mask; CLEAR leaves false-positive bits for the
    candidate gather's full-width clear to resolve).

    Returns the classify_batch dict plus per-lane prune observability
    (REPLICATED over the rule axis under hit_combine — skip combines as
    AND, fb as OR, cand as the per-shard MAX, all through the same
    min-combine the hits use):
      prune_skip (B,) bool — both directions proved no-match by the
                             aggregate AND alone (the short-circuit lanes)
      prune_fb   (B,) bool — lane took the full-width fallback redispatch
                             (on ANY rule shard)
      prune_cand (B,) i32  — candidate superblocks, max over directions
                             and rule shards (what the per-shard K budget
                             must cover)

    summary_only (the PH_CLS_SUM profiling surface): stop after phase 1 —
    every live lane takes the default-verdict image, nothing falls back.
    """
    ing, eg = drs.ingress, drs.egress
    B = src_ip_f.shape[0]
    K = meta.prune_budget
    svc_key = (proto << 16) | dst_port
    if v6 is not None:
        src6w, dst6w, is6 = v6
    else:
        src6w = dst6w = is6 = None

    def dim_idx(tab, x, x6w):
        return _dim_index(tab, x, x6w, is6)

    iv_in_at = dim_idx(ing.at, dst_ip_f, dst6w)
    iv_in_peer = dim_idx(ing.peer, src_ip_f, src6w)
    iv_in_svc = dim_idx(ing.svc, svc_key, None)
    iv_out_at = dim_idx(eg.at, src_ip_f, src6w)
    iv_out_peer = dim_idx(eg.peer, dst_ip_f, dst6w)
    iv_out_svc = dim_idx(eg.svc, svc_key, None)
    iv_ref = None
    if meta.svcref:
        iv_ref = dim_idx(eg.svc, _svcref_key(svc_key, svc_ref), None)

    iso_in = drs.iso_in.val[dim_idx(drs.iso_in, dst_ip_f, dst6w)]
    iso_out = drs.iso_out.val[dim_idx(drs.iso_out, src_ip_f, src6w)]

    d = drs.ip_delta if meta.delta_slots > 0 else None
    wide_d = None if v6 is None else (dst6w, is6)
    wide_s = None if v6 is None else (src6w, is6)
    if d is not None:
        iso_in = _patch_iso(iso_in, dst_ip_f, d, 0, wide_d)
        iso_out = _patch_iso(iso_out, src_ip_f, d, 1, wide_s)

    # Per-direction dimension wiring: (tables, interval rows, probe ip
    # column + wide words per ip dim, delta masks, phases).  Ingress: pod
    # = dst probes appliedTo, peer = src; egress mirrored.
    dir_in = dict(
        dd=ing, iv_at=iv_in_at, iv_peer=iv_in_peer, iv_svc=iv_in_svc,
        iv_ref=None, ip_at=dst_ip_f, ip_peer=src_ip_f, w_at=wide_d,
        w_peer=wide_s, m_at=None if d is None else d.at_in,
        m_peer=None if d is None else d.peer_in, phases=meta.in_phases,
    )
    dir_out = dict(
        dd=eg, iv_at=iv_out_at, iv_peer=iv_out_peer, iv_svc=iv_out_svc,
        iv_ref=iv_ref, ip_at=src_ip_f, ip_peer=dst_ip_f, w_at=wide_s,
        w_peer=wide_d, m_at=None if d is None else d.at_out,
        m_peer=None if d is None else d.peer_out, phases=meta.out_phases,
    )

    def agg_and(dc):
        a = dc["dd"].at.agg[dc["iv_at"]]
        p = dc["dd"].peer.agg[dc["iv_peer"]]
        s = dc["dd"].svc.agg[dc["iv_svc"]]
        if dc["iv_ref"] is not None:
            s = s | dc["dd"].svc.agg[dc["iv_ref"]]
        if d is not None:
            a = _patch_agg(a, dc["ip_at"], d, dc["m_at"], dc["w_at"])
            p = _patch_agg(p, dc["ip_peer"], d, dc["m_peer"], dc["w_peer"])
        g = a & p & s
        return g, (g != jnp.uint32(0)).sum(axis=1, dtype=jnp.int32)

    g_in, nc_in = agg_and(dir_in)
    g_out, nc_out = agg_and(dir_out)
    BIGS = jnp.full((B,), BIG, jnp.int32)
    no_fb = jnp.zeros((B,), bool)

    def cand_mats(dc, g):
        """Phase-2 candidate gather for one direction -> ((ca, cp, cs,
        base) flattened to (B, Ke*AGG_BLOCK), Ke); the caller derives the
        fallback mask from nc vs Ke."""
        dd = dc["dd"]
        S = dd.at.agg.shape[1]
        Ke = min(K, S)
        w = dd.at.inc.shape[1]  # == S * AGG_BLOCK (agg-padded width)
        score = jnp.where(
            g != jnp.uint32(0),
            jax.lax.broadcasted_iota(jnp.int32, (B, S), 1),
            S,
        )
        neg, _idx = jax.lax.top_k(-score, Ke)
        cand = -neg  # (B, Ke) ascending superblock ids, S = fill
        valid = cand < S
        candc = jnp.minimum(cand, S - 1)

        def cwords(tab, iv_):
            inc2 = tab.inc.reshape(-1, AGG_BLOCK)
            return inc2[iv_[:, None] * S + candc]  # (B, Ke, 32) block rows

        ca = cwords(dd.at, dc["iv_at"])
        cp = cwords(dd.peer, dc["iv_peer"])
        cs = cwords(dd.svc, dc["iv_svc"])
        if dc["iv_ref"] is not None:
            cs = cs | cwords(dd.svc, dc["iv_ref"])
        if d is not None:
            widx = jnp.minimum(
                candc[:, :, None] * AGG_BLOCK
                + jnp.arange(AGG_BLOCK, dtype=jnp.int32)[None, None, :],
                w - 1,
            )
            ca = _patch_cand(ca, widx, dc["ip_at"], d, dc["m_at"],
                             dc["w_at"])
            cp = _patch_cand(cp, widx, dc["ip_peer"], d, dc["m_peer"],
                             dc["w_peer"])
        # Fill candidates must contribute nothing: zero ONE dim (the AND
        # kills the rest); done after delta patching on purpose.
        ca = jnp.where(valid[:, :, None], ca, jnp.uint32(0))
        j = jnp.arange(AGG_BLOCK, dtype=jnp.int32)[None, None, :]
        base = (dd.word_idx[0] + candc[:, :, None] * AGG_BLOCK + j) * 32
        flat = lambda x: x.reshape(B, Ke * AGG_BLOCK)  # noqa: E731
        return (flat(ca), flat(cp), flat(cs), flat(base)), Ke

    def full_dir_hits(dc, safe):
        """Full-width fallback walk of the compacted lanes `safe`."""
        dd = dc["dd"]
        ra = dd.at.inc[dc["iv_at"][safe]]
        rp = dd.peer.inc[dc["iv_peer"][safe]]
        rs = dd.svc.inc[dc["iv_svc"][safe]]
        if dc["iv_ref"] is not None:
            rs = rs | dd.svc.inc[dc["iv_ref"][safe]]
        if d is not None:
            def sub(wd):
                return None if wd is None else (wd[0][safe], wd[1][safe])

            ra = _patch_rows(ra, dc["ip_at"][safe], d, dc["m_at"],
                             sub(dc["w_at"]))
            rp = _patch_rows(rp, dc["ip_peer"][safe], d, dc["m_peer"],
                             sub(dc["w_peer"]))
        return _phase_hits(ra & rp & rs, dd.word_idx, dc["phases"])

    if summary_only:
        in_hits = (BIGS, BIGS, BIGS)
        out_hits = (BIGS, BIGS, BIGS)
        fb = no_fb
    else:
        def phase2(_):
            mats_in, ke_in = cand_mats(dir_in, g_in)
            mats_out, ke_out = cand_mats(dir_out, g_out)
            if fused:
                if meta.fused_interpret is not None:
                    interpret = meta.fused_interpret
                else:
                    interpret = jax.devices()[0].platform == "cpu"
                pad = (-B) % _FUSE_TB
                if pad:
                    mats_in = tuple(jnp.pad(x, ((0, pad), (0, 0)))
                                    for x in mats_in)
                    mats_out = tuple(jnp.pad(x, ((0, pad), (0, 0)))
                                     for x in mats_out)
                call = _pruned_consumer_call(
                    B + pad, ke_in * AGG_BLOCK, ke_out * AGG_BLOCK,
                    meta.in_phases, meta.out_phases, interpret,
                )
                hits = call(*mats_in, *mats_out)[:B]
                hits6 = tuple(hits[:, i] for i in range(6))
            else:
                ia, ipr, isv, bi = mats_in
                oa, opr, osv, bo = mats_out
                hits6 = (_phase_first_from_base(ia & ipr & isv, bi,
                                                meta.in_phases)
                         + _phase_first_from_base(oa & opr & osv, bo,
                                                  meta.out_phases))
            fb = (nc_in > ke_in) | (nc_out > ke_out)
            fb_idx = jnp.nonzero(fb, size=B, fill_value=B)[0].astype(
                jnp.int32)
            n_fb = fb.sum(dtype=jnp.int32)
            rungs = []
            r = _FB_MIN
            while r < B:
                rungs.append(r)
                r *= 4
            rungs = sorted(set(min(r, B) for r in rungs + [B]))

            def apply_rung(r):
                def go(h6):
                    idx = fb_idx[:r]
                    safe = jnp.minimum(idx, B - 1)
                    ih = full_dir_hits(dir_in, safe)
                    oh = full_dir_hits(dir_out, safe)
                    tgt = jnp.where(idx < B, idx, B)  # B drops (OOB)
                    return tuple(
                        cur.at[tgt].set(new, mode="drop")
                        for cur, new in zip(h6, ih + oh)
                    )

                return go

            branches = [lambda h6: h6] + [apply_rung(r) for r in rungs]
            sel = jnp.where(
                n_fb == 0,
                0,
                1 + sum(((n_fb > r).astype(jnp.int32) for r in rungs[:-1]),
                        start=jnp.int32(0)),
            )
            hits6 = jax.lax.switch(sel, branches, hits6)
            return hits6 + (fb,)

        def all_dead(_):
            # Aggregate-AND-zero short circuit for the whole batch (the
            # adversarial / default-deny cold shape): no candidate
            # gather, no fallback — straight to the default verdicts.
            return (BIGS,) * 6 + (no_fb,)

        res = jax.lax.cond(
            ((nc_in > 0) | (nc_out > 0)).any(), phase2, all_dead, None
        )
        in_hits, out_hits, fb = res[0:3], res[3:6], res[6]

    skip = ((nc_in == 0) & (nc_out == 0)).astype(jnp.int32)
    cand = jnp.maximum(nc_in, nc_out)
    fbi = fb.astype(jnp.int32)
    if hit_combine is not None:
        in_hits = tuple(hit_combine(h) for h in in_hits)
        out_hits = tuple(hit_combine(h) for h in out_hits)
        # The prune observables are SHARD-LOCAL under rule sharding
        # (each shard prunes its own aggregate slice); emitting them raw
        # would violate the replicated-output contract every other
        # output keeps via the pmin (mesh._probe_shard_map).  Combine
        # them through the SAME min-combine: skip is an AND (min of
        # 0/1 — no shard had a candidate), fallback an OR (1 - min of
        # the complement — ANY shard redispatched), and cand the MAX
        # per-shard count (min of the negation) — the quantity the
        # per-shard K budget must actually cover, which is what the
        # autotuner and the histogram exist to answer.
        skip = hit_combine(skip)
        fbi = 1 - hit_combine(1 - fbi)
        cand = -hit_combine(-cand)

    in_code, in_rule = _resolve(ing.action, in_hits, iso_in)
    out_code, out_rule = _resolve(eg.action, out_hits, iso_out)
    final = jnp.where(out_code != ACT_ALLOW, out_code, in_code)
    return {
        "code": final,
        "egress_code": out_code,
        "egress_rule": out_rule,
        "ingress_code": in_code,
        "ingress_rule": in_rule,
        "prune_skip": skip > 0,
        "prune_fb": fbi > 0,
        "prune_cand": cand,
    }


# ---------------------------------------------------------------------------
# One-kernel fast path (round 8): ONE pallas pass per batch that keeps
# per-lane state in VMEM end-to-end — flow-cache probe (key compare +
# freshness + generation against the XLA-gathered cache row), aggregate
# AND with the zero-AND short-circuit, double-buffered candidate-
# superblock DMA (half-block j+1's aggregate AND + DMA issue overlap the
# wait on half-block j's candidates), first-match via the SHARED
# _phase_first_from_base discipline, and (single-chip `resolve` variant)
# verdict resolution + cached/fresh output merge + cache-commit ROW
# packing in the same pass.  The commit SCATTERS stay XLA (study note (d):
# Mosaic has no arbitrary-VMEM-scatter path and the cache exceeds VMEM)
# but their input rows are kernel outputs — the inter-stage HBM
# materializations (probe image, LB image, classify image, packed rows)
# are gone.  Under rule-axis sharding (`resolve=False`) the kernel emits
# GLOBAL hit indices for the pmin seam and resolution runs post-allreduce,
# the same physics as every other sharded first-match path.
# ---------------------------------------------------------------------------

_OP_HB = 64  # lane half-block: the candidate-DMA double-buffer granule


@lru_cache(maxsize=16)
def _onepass_call(b, s_in, s_out, k_in, k_out, in_phases, out_phases,
                  svcref, resolve, timeouts, n_slots, pref_mask, interpret):
    """Build the one-pass kernel (the `_pruned_consumer_call` seam grown
    three stages: probe in, candidate gather in-kernel via DMA, resolve/
    commit-pack out).  Static key = every shape/phase/flag, so a
    prune-budget retune (k_in/k_out move on PRUNE_LADDER) is a meta-only
    swap hitting this cache — one compiled variant per rung, no storms.

    Inputs (per grid tile of _FUSE_TB lanes; all i32/u32):
      pkt  (tb, 8)  [src_f, dst_f, proto, sport, dport, pp, 0, 0]
      kr   (tb, 4)  gathered flow-cache key row
      prb  (tb, 4)  [ts, iso_in, iso_out, 0]
      mrow (tb, 4)  gathered flow-cache meta row
      msk  (tb, 4)  [valid, no_commit, fb_force, 0]
      lb   (tb, 8)  [svc_idx, no_ep, dnat_ip_f, dnat_port, snat, dsr, 0, 0]
      agg x6 (tb, s) aggregate rows (delta-agg patched, miss-index-masked)
      iv   (tb, 8)  SMEM interval rows [in_at, in_peer, in_svc, out_at,
                    out_peer, out_svc, svc_ref, 0]
      scal (1, 4)   SMEM [now, gen_w, w0_in, w0_out]
      inc2 x6       ANY (rows*S, AGG_BLOCK) u32 — the DMA source tables
      act  x2       ANY (w*32,) i32 (resolve variant only)

    Outputs: resolve -> (main (b,16), keys8 (b,8), meta8 (b,8), aux (b,4));
    hits-only -> (hits8 (b,8), aux (b,4)).  main columns:
    [code, rule_in, rule_out, svc, dnat_ip_f, dnat_port, snat, dsr,
     committed, rev_ins, rev_slot, hit, est, rpl, ins, 0]; aux columns:
    [skip, fb, cand, 0]."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from ..compiler.compile import ACT_REJECT
    from ..models.pipeline import (DSR_BIT, GEN_ETERNAL, MISS, REPLY_BIT,
                                   _pack_meta1, _pack_rules, _unpack_meta1,
                                   _unpack_rules, entry_timeout)
    from . import hashing

    tb = _FUSE_TB
    hb = _OP_HB
    nh = tb // hb
    ke_in = min(k_in, s_in)
    ke_out = min(k_out, s_out)
    # (S, Ke, direction) per dim slot; slot 6 = the svcref second svc row.
    dims = [(s_in, ke_in, 0)] * 3 + [(s_out, ke_out, 1)] * 3
    if svcref:
        dims = dims + [(s_out, ke_out, 1)]
    n_inc = len(dims)

    def kernel(*refs):
        (pkt, kr, prb, mrow, msk, lb,
         g_ia, g_ip, g_is, g_oa, g_op, g_os, iv, scal) = refs[:14]
        inc2 = refs[14:14 + n_inc]
        pos = 14 + n_inc
        if resolve:
            act_in, act_out = refs[pos:pos + 2]
            pos += 2
        n_out = 4 if resolve else 2
        outs = refs[pos:pos + n_out]
        scratch = refs[pos + n_out:]
        bufs = [[scratch[2 * d + s] for s in range(2)] for d in range(n_inc)]
        sp = 2 * n_inc
        cidx = [[scratch[sp + 2 * dirn + s] for s in range(2)]
                for dirn in range(2)]
        sp += 4
        cvld = [[scratch[sp + 2 * dirn + s] for s in range(2)]
                for dirn in range(2)]
        sp += 4
        sems = [scratch[sp], scratch[sp + 1]]
        sp += 2
        if resolve:
            hbuf, abuf, asem = scratch[sp:sp + 3]

        # ---- tile-wide per-lane state (VMEM vectors) ----------------------
        src_f = pkt[:, 0]
        dst_f = pkt[:, 1]
        proto = pkt[:, 2]
        sport = pkt[:, 3]
        dport = pkt[:, 4]
        pp = pkt[:, 5]
        now = scal[0, 0]
        gen_w = scal[0, 1]
        w0_in = scal[0, 2]
        w0_out = scal[0, 3]
        valid = msk[:, 0] != 0
        nc = msk[:, 1] != 0
        fb_force = msk[:, 2] != 0

        # Flow-cache probe: the _cache_lookup discipline on the gathered
        # row (key compare + generation + per-state freshness), in VMEM.
        krr = kr[:]
        kpg = krr[:, 3]
        pg_cur = proto | 0x100 | (gen_w << 9)
        pg_est = proto | 0x100 | (GEN_ETERNAL << 9)
        pg_rpl = pg_est | REPLY_BIT
        key_hit = ((krr[:, 0] == src_f) & (krr[:, 1] == dst_f)
                   & (krr[:, 2] == pp)
                   & ((kpg == pg_cur) | (kpg == pg_est) | (kpg == pg_rpl)))
        mr = mrow[:]
        ts = prb[:, 0]
        iso_in = prb[:, 1]
        iso_out = prb[:, 2]
        if timeouts[0] == timeouts[1] == timeouts[2] == timeouts[3]:
            timeout = timeouts[1]
        else:
            timeout = entry_timeout((mr[:, 3] >> 29) & 1, proto, timeouts)
        fresh = (now - ts) <= timeout
        hit = key_hit & fresh & valid
        est = hit & ((kpg == pg_est) | (kpg == pg_rpl))
        rpl = hit & (kpg == pg_rpl)
        miss = ~hit & valid

        # Aggregate AND + zero-AND short circuit (non-miss lanes gathered
        # row 0 — masked dead here so they spawn no candidates).
        g_in = g_ia[:] & g_ip[:] & g_is[:]
        g_out = g_oa[:] & g_op[:] & g_os[:]
        nc_in = jnp.where(
            miss, (g_in != jnp.uint32(0)).sum(axis=1, dtype=jnp.int32), 0)
        nc_out = jnp.where(
            miss, (g_out != jnp.uint32(0)).sum(axis=1, dtype=jnp.int32), 0)
        skip = miss & (nc_in == 0) & (nc_out == 0)
        fb = miss & ((nc_in > ke_in) | (nc_out > ke_out) | fb_force)
        cand = jnp.maximum(nc_in, nc_out)

        # ---- candidate selection + double-buffered DMA per half-block -----
        def select(j, slot):
            """Aggregate top-K for half-block j -> cidx/cvld[.][slot]."""
            off = j * hb
            miss_h = miss[off:off + hb]
            for dirn, (g, S, K) in enumerate(
                    ((g_in, s_in, ke_in), (g_out, s_out, ke_out))):
                gh = g[off:off + hb]
                score = jnp.where(
                    (gh != jnp.uint32(0)) & miss_h[:, None],
                    jax.lax.broadcasted_iota(jnp.int32, (hb, S), 1), S)
                neg, _i = jax.lax.top_k(-score, K)
                c = -neg  # ascending superblock ids, S = fill
                cvld[dirn][slot][:, :K] = (c < S).astype(jnp.int32)
                cidx[dirn][slot][:, :K] = jnp.minimum(c, S - 1)

        def dma_half(j, slot, start):
            """Issue (or wait) the candidate-row copies for half j."""
            off = j * hb

            def lane_body(i, _):
                for d, (S, K, dirn) in enumerate(dims):
                    ivd = iv[off + i, d]
                    for k in range(K):
                        row = ivd * S + cidx[dirn][slot][i, k]
                        cp = pltpu.make_async_copy(
                            inc2[d].at[row], bufs[d][slot].at[i, k],
                            sems[slot])
                        if start:
                            cp.start()
                        else:
                            cp.wait()
                return 0

            jax.lax.fori_loop(0, hb, lane_body, 0)

        def first_match(j, slot):
            """Candidate AND + the shared per-element-base first-match."""
            off = j * hb

            def mats(d3, dirn, S, K, w0):
                ca = bufs[d3][slot][:]
                cpr = bufs[d3 + 1][slot][:]
                cs = bufs[d3 + 2][slot][:]
                if svcref and dirn == 1:
                    cs = cs | bufs[6][slot][:]
                # Fill candidates contribute nothing: zero ONE dim.
                ca = jnp.where(cvld[dirn][slot][:, :K][:, :, None] != 0, ca,
                               jnp.uint32(0))
                m = (ca & cpr & cs).reshape(hb, K * AGG_BLOCK)
                jj = jnp.arange(AGG_BLOCK, dtype=jnp.int32)[None, None, :]
                base = ((w0 + cidx[dirn][slot][:, :K][:, :, None] * AGG_BLOCK
                         + jj) * 32).reshape(hb, K * AGG_BLOCK)
                return m, base

            m_i, b_i = mats(0, 0, s_in, ke_in, w0_in)
            m_o, b_o = mats(3, 1, s_out, ke_out, w0_out)
            return (_phase_first_from_base(m_i, b_i, in_phases)
                    + _phase_first_from_base(m_o, b_o, out_phases))

        def emit(j, hits6):
            """Resolve + merge + commit-row pack for half j (resolve
            variant) or raw hit emission (sharded variant)."""
            off = j * hb
            sl = slice(off, off + hb)
            if not resolve:
                outs[0][sl, :] = jnp.stack(
                    list(hits6) + [jnp.zeros(hb, jnp.int32)] * 2, axis=1)
                outs[1][sl, :] = jnp.stack(
                    [skip[sl].astype(jnp.int32), fb[sl].astype(jnp.int32),
                     cand[sl], jnp.zeros(hb, jnp.int32)], axis=1)
                return
            i0, ik, ib, o0, ok_, ob = hits6
            # Per-lane action DMA for the deciding phase-0/baseline rules
            # (the _resolve gathers, fetched from the ANY-space tables).
            na = act_in.shape[0]
            nb = act_out.shape[0]
            hbuf[:] = jnp.stack([
                jnp.clip(i0, 0, na - 1), jnp.clip(ib, 0, na - 1),
                jnp.clip(o0, 0, nb - 1), jnp.clip(ob, 0, nb - 1)], axis=1)

            def act_loop(start):
                def body(i, _):
                    for k, ref in ((0, act_in), (1, act_in),
                                   (2, act_out), (3, act_out)):
                        cp = pltpu.make_async_copy(
                            ref.at[pl.ds(hbuf[i, k], 1)],
                            abuf.at[i, pl.ds(k, 1)], asem)
                        if start:
                            cp.start()
                        else:
                            cp.wait()
                    return 0

                jax.lax.fori_loop(0, hb, body, 0)

            act_loop(True)
            act_loop(False)

            in_code, in_rule = _resolve_from_actions(
                abuf[:, 0], abuf[:, 1], (i0, ik, ib), iso_in[sl])
            out_code, out_rule = _resolve_from_actions(
                abuf[:, 2], abuf[:, 3], (o0, ok_, ob), iso_out[sl])
            cls_code = jnp.where(out_code != ACT_ALLOW, out_code, in_code)

            # LB/no-endpoint overlay (SvcReject precedes the policy
            # tables) -> the fresh (slow-path) image of each lane.
            no_ep = lb[sl, 1] != 0
            f_code = jnp.where(no_ep, ACT_REJECT, cls_code).astype(jnp.int32)
            f_ri = jnp.where(no_ep, MISS, in_rule)
            f_ro = jnp.where(no_ep, MISS, out_rule)
            svc_idx = lb[sl, 0]
            dnat_ip = lb[sl, 2]
            dnat_port = lb[sl, 3]
            snat_m = lb[sl, 4]
            dsr_m = lb[sl, 5]

            # Cached image decode + the hit/miss/default merge — the
            # fast-path output images, produced in the same pass.
            h_h = hit[sl]
            m_h = miss[sl]
            r_h = rpl[sl]
            c_code, c_svc, c_dport = _unpack_meta1(mr[sl, 1])
            c_dnat = mr[sl, 0]
            c_ri, c_ro = _unpack_rules(mr[sl, 2])
            c_snat = (mr[sl, 3] >> 31) & 1
            c_dsr = (mr[sl, 3] >> 30) & 1
            o_code = jnp.where(h_h, c_code,
                               jnp.where(m_h, f_code, ACT_ALLOW))
            o_svc = jnp.where(h_h, c_svc, jnp.where(m_h, svc_idx, MISS))
            o_dnat = jnp.where(h_h, c_dnat,
                               jnp.where(m_h, dnat_ip, dst_f[sl]))
            o_dport = jnp.where(h_h, c_dport,
                                jnp.where(m_h, dnat_port, dport[sl]))
            o_ri = jnp.where(h_h, c_ri, jnp.where(m_h, f_ri, MISS))
            o_ro = jnp.where(h_h, c_ro, jnp.where(m_h, f_ro, MISS))
            o_snat = jnp.where(h_h & ~r_h, c_snat,
                               jnp.where(m_h, snat_m, 0))
            o_dsr = jnp.where(h_h & ~r_h, c_dsr, jnp.where(m_h, dsr_m, 0))

            committed = m_h & (f_code == ACT_ALLOW) & ~nc[sl]
            ins = m_h & ~nc[sl]
            rev_ins = ins & committed & (dsr_m == 0)

            # Commit-row packing (forward + reply-direction conntrack
            # rows) — the scatter consumes these verbatim.
            egen = jnp.where(committed, GEN_ETERNAL, gen_w)
            pg_ins = proto[sl] | 0x100 | (egen << 9)
            m1 = _pack_meta1(f_code, svc_idx, dnat_port)
            rules_p = _pack_rules(f_ri, f_ro)
            pref_col = jnp.full((hb,), 0, jnp.int32) + (now & pref_mask)
            zcol = (pref_col
                    | jnp.where(snat_m > 0, REPLY_BIT, 0)
                    | jnp.where(dsr_m > 0, DSR_BIT, 0))
            raw = lambda x: x ^ jnp.int32(-(2 ** 31))  # noqa: E731
            rev_h = hashing.flow_hash(
                raw(dnat_ip), raw(src_f[sl]), proto[sl], dnat_port,
                sport[sl], xp=jnp)
            rev_slot = (rev_h & jnp.uint32(n_slots - 1)).astype(jnp.int32)
            rev_pg = proto[sl] | 0x100 | (GEN_ETERNAL << 9) | REPLY_BIT
            outs[0][sl, :] = jnp.stack(
                [o_code, o_ri, o_ro, o_svc, o_dnat, o_dport, o_snat, o_dsr,
                 committed.astype(jnp.int32), rev_ins.astype(jnp.int32),
                 rev_slot, h_h.astype(jnp.int32), est[sl].astype(jnp.int32),
                 r_h.astype(jnp.int32), ins.astype(jnp.int32),
                 jnp.zeros(hb, jnp.int32)], axis=1)
            outs[1][sl, :] = jnp.stack(
                [src_f[sl], dst_f[sl], pp[sl], pg_ins,
                 dnat_ip, src_f[sl], (dnat_port << 16) | sport[sl], rev_pg],
                axis=1)
            outs[2][sl, :] = jnp.stack(
                [dnat_ip, m1, rules_p, zcol,
                 dst_f[sl], _pack_meta1(f_code, svc_idx, dport[sl]),
                 rules_p, pref_col], axis=1)
            outs[3][sl, :] = jnp.stack(
                [skip[sl].astype(jnp.int32), fb[sl].astype(jnp.int32),
                 cand[sl], jnp.zeros(hb, jnp.int32)], axis=1)

        # Software pipeline: select+issue half 0, then for each half j
        # overlap half j+1's aggregate AND / top-K / DMA issue with the
        # wait on half j's candidate copies — the double buffer.
        select(0, 0)
        dma_half(0, 0, start=True)
        for j in range(nh):
            if j + 1 < nh:
                select(j + 1, (j + 1) % 2)
                dma_half(j + 1, (j + 1) % 2, start=True)
            dma_half(j, j % 2, start=False)
            emit(j, first_match(j, j % 2))

    grid = (b // tb,)
    tile = lambda w: pl.BlockSpec((tb, w), lambda i: (i, 0))  # noqa: E731
    in_specs = (
        [tile(8), tile(4), tile(4), tile(4), tile(4), tile(8)]
        + [tile(s_in)] * 3 + [tile(s_out)] * 3
        + [pl.BlockSpec((tb, 8), lambda i: (i, 0),
                        memory_space=pltpu.SMEM),
           pl.BlockSpec((1, 4), lambda i: (0, 0),
                        memory_space=pltpu.SMEM)]
        + [pl.BlockSpec(memory_space=pltpu.ANY)] * n_inc
        + ([pl.BlockSpec(memory_space=pltpu.ANY)] * 2 if resolve else [])
    )
    if resolve:
        out_shape = (jax.ShapeDtypeStruct((b, 16), jnp.int32),
                     jax.ShapeDtypeStruct((b, 8), jnp.int32),
                     jax.ShapeDtypeStruct((b, 8), jnp.int32),
                     jax.ShapeDtypeStruct((b, 4), jnp.int32))
        out_specs = (pl.BlockSpec((tb, 16), lambda i: (i, 0)),
                     tile(8), tile(8), tile(4))
    else:
        out_shape = (jax.ShapeDtypeStruct((b, 8), jnp.int32),
                     jax.ShapeDtypeStruct((b, 4), jnp.int32))
        out_specs = (tile(8), tile(4))
    scratch = []
    for (S, K, _dirn) in dims:
        for _s in range(2):
            scratch.append(pltpu.VMEM((hb, K, AGG_BLOCK), jnp.uint32))
    for _dirn in range(2):
        for _s in range(2):
            scratch.append(pltpu.VMEM((hb, max(ke_in, ke_out)), jnp.int32))
    for _dirn in range(2):
        for _s in range(2):
            scratch.append(pltpu.VMEM((hb, max(ke_in, ke_out)), jnp.int32))
    scratch += [pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.DMA]
    if resolve:
        scratch += [pltpu.VMEM((hb, 4), jnp.int32),
                    pltpu.VMEM((hb, 4), jnp.int32),
                    pltpu.SemaphoreType.DMA]
    return pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=scratch,
        interpret=interpret,
    )


def flip_ips(a: np.ndarray) -> np.ndarray:
    """Host helper: u32 IP array -> sign-flipped i32 (kernel input layout)."""
    return iputil.flip_u32(a)


# meta is static (plain ints/tuples, hashable); drs is a traced pytree arg so
# the big incidence tensors stay runtime inputs instead of baked-in constants.
_classify_jit = jax.jit(
    classify_batch,
    static_argnames=("meta", "hit_combine", "fused", "summary_only"),
)


def make_classifier(cps: CompiledPolicySet):
    """-> (fn(src_f, dst_f, proto, dport, v6=None) -> verdict dict, DRS)."""
    drs, meta = to_device(cps)

    def fn(src_f, dst_f, proto, dport, v6=None):
        return _classify_jit(drs, src_f, dst_f, proto, dport, meta=meta,
                             v6=v6)

    return fn, drs
