"""Batched conjunctive-match classification kernel (the tpuflow hot path).

This is the TPU execution of what OVS does per-packet in C: walk the policy
tables and produce a verdict.  The kernel is gather-structured (round-3
redesign; the round-2 kernel was a lax.scan over rule chunks testing per-rule
group bits plus a (B, C, K) inline-range broadcast, and topped out at 176k
pps @ 100k rules — 0.018x the 10M target):

  1. per-dimension interval lookup: searchsorted over the dimension's OWN
     elementary-interval boundaries (appliedTo / peer over the u32 IP space,
     service over the (proto << 16 | dst_port) key space);
  2. one row gather per dimension from that dimension's bit-packed
     RULE-INCIDENCE table: inc[iv] is a bitmap over rules — bit r set iff
     rule r's interned group for this dimension contains interval iv.  This
     is the factored address-set sharing of the reference's conjunction
     engine (/root/reference/pkg/agent/openflow/network_policy.go:325,:442),
     transposed from (interval -> groups) to (interval -> rule bits) at
     compile time so the kernel never walks groups at all;
  3. AND the three rows -> per-packet rule-match bitmap (B, ceil(R/32));
  4. per-evaluation-phase first-set-bit (isolate-lowest-bit + popcount +
     min-reduce) replicating the OVS table order:
     AntreaPolicy{In,E}gressRule -> K8s {In,E}gressRule + isolation
     default-deny -> Baseline -> default allow
     (ref: /root/reference/pkg/agent/openflow/pipeline.go:114-195).

Per packet the work is three ~R/32-word row gathers per direction plus a
handful of vector word ops — HBM-streaming-bound with no per-rule scan, no
data-dependent control flow, and no gather along the lane axis (row gathers
along the major axis are the fast pattern on TPU; see the FlowCache layout
rationale in models/pipeline.py).  Inline peer CIDR blocks are folded into
interned groups by the compiler, so they are ordinary incidence bits here.

All arrays are i32/u32 lanes; IPs are sign-flipped so signed compares give
unsigned order (see compiler/compile.py).  Everything is static-shaped and
jit-compatible; batch size is the only trace-time variable.
"""

from __future__ import annotations

from functools import lru_cache
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..compiler.compile import (
    ACT_ALLOW,
    ACT_DROP,
    ACT_PASS,
    CompiledPolicySet,
    DirectionTensors,
)
from ..utils import ip as iputil

# "No match" sentinel for first-match indices.  Deliberately a PYTHON int,
# not an eager jnp scalar: a concrete device array captured by a jitted
# function becomes a buffer-backed executable constant, which on some TPU
# runtimes (observed on the axon platform) both slows that executable ~1000x
# and degrades every subsequent dispatch in the process.  Python scalars
# trace to HLO literals and stay fast.
BIG = 1 << 30

_ALL1 = 0xFFFFFFFF


class DimTable(NamedTuple):
    """One match dimension: interval bounds + rule-incidence rows.

    Dual-stack (ref pipeline.go IPv6 table, fields.go:184-185 xxreg3): the
    incidence rows concatenate the v4 interval space (rows 0..NB4) and the
    v6 interval space (rows NB4+1..NB4+1+NB6) — v6 boundaries live in a
    separate 4-word lexicographic table, and once a packet resolves to an
    interval INDEX everything downstream is family-blind.  bounds6 always
    exists (possibly 0 rows; the v6 space then has the single whole-space
    interval, still painted by family-spanning groups like any-peer)."""

    bounds: jax.Array  # (NB4,) i32 ascending (sign-flipped for IP dims)
    # (NB6, 4) i32 — v6 boundaries as per-word sign-flipped u32 quadruples,
    # ascending lexicographically.  Empty (0, 4) for the svc dimension.
    bounds6: jax.Array
    inc: jax.Array  # (NB4+1+NB6+1, W) u32 — rule bitmap per interval


class DeviceDirection(NamedTuple):
    at: DimTable  # appliedTo, probed with the pod-side IP
    peer: DimTable  # peer, probed with the other side's IP
    svc: DimTable  # service, probed with (proto << 16 | dst_port)
    action: jax.Array  # (W*32,) i32 flat, for post-resolve gather
    # (W*32,) i32 0/1 L7-redirect mark per rule, replicated like `action`
    # (indexed post-pmin by the deciding rule).
    l7: jax.Array
    # (W,) global word index — carried as data (not an arange built in the
    # kernel) so a rule-axis shard_map slice still knows its global rule
    # offsets and cross-shard first-match combines stay a plain lax.pmin.
    word_idx: jax.Array


class IsoTable(NamedTuple):
    """K8s default-deny isolation membership (one bit per packet);
    dual-stack like DimTable (val rows = K4+1+K6+1)."""

    bounds: jax.Array  # (K4,) i32 sign-flipped
    bounds6: jax.Array  # (K6, 4) i32 per-word sign-flipped
    val: jax.Array  # (K4+1+K6+1,) i32 0/1


class DeltaTable(NamedTuple):
    """Fixed-capacity incremental membership-delta table (device-resident).

    The TPU answer to the reference's incremental address-group watch deltas
    (docs/design/architecture.md:61-62): a pod joining/leaving a group does
    NOT recompile any interval table — the host appends one slot carrying
    the affected ip range plus PRE-RESOLVED per-dimension rule masks (the
    bitmaps of rules whose at/peer gid is the patched group), and the kernel
    patches the gathered incidence rows before the AND, so every consumer
    sees the updated membership.  A full recompile (bundle commit) folds the
    deltas back into the tables and clears this — the megaflow-revalidation
    analog, triggered on capacity overflow.

    Slots apply in append order inside a dynamic-trip-count loop (`n`), so
    zero pending deltas cost zero iterations and a later delta for the same
    rule bit wins.  Empty slots: sign == 0.

    Dual-stack: a slot is single-family (`fam`) — v4 slots compare the
    narrow range, v6 slots the 4-word lexicographic one (same pre-resolved
    masks either way), so v6 pod churn stays O(1) instead of forcing a
    recompile.
    """

    lo_f: jax.Array  # (D,) sign-flipped i32, inclusive (v4 slots)
    hi_f: jax.Array  # (D,) sign-flipped i32, inclusive
    sign: jax.Array  # (D,) i32 — +1 set, -1 clear, 0 empty
    iso: jax.Array  # (D,) i32 — bit0: patches iso_in, bit1: patches iso_out
    at_in: jax.Array  # (D, W_in) u32 rule mask for the ingress appliedTo dim
    peer_in: jax.Array  # (D, W_in)
    at_out: jax.Array  # (D, W_out)
    peer_out: jax.Array  # (D, W_out)
    n: jax.Array  # () i32 — active slots
    fam: jax.Array  # (D,) i32 — 0: v4 slot, 1: v6 slot
    lo6_w: jax.Array  # (D, 4) per-word flipped, inclusive (v6 slots)
    hi6_w: jax.Array  # (D, 4)


class DeviceRuleSet(NamedTuple):
    """Device-resident compiled rule tensors (the double-buffered side of a
    bundle commit; ref bundle semantics: pkg/ovs/openflow/ofctrl_bridge.go:468)."""

    ingress: DeviceDirection
    egress: DeviceDirection
    iso_in: IsoTable
    iso_out: IsoTable
    ip_delta: DeltaTable


class StaticMeta(NamedTuple):
    """Trace-time constants (not pytree leaves)."""

    in_phases: tuple[int, int, int]  # (n_phase0, n_k8s, n_baseline)
    out_phases: tuple[int, int, int]
    w_in: int  # ingress rule words (incl. shard padding)
    w_out: int
    delta_slots: int = 0
    # Fused-consumer interpret override: None = infer from the DEFAULT
    # platform.  The sharded builders set this from the MESH's platform —
    # a CPU mesh on a TPU-default host (the virtual-device dryrun) must
    # interpret, and vice versa.
    fused_interpret: "bool | None" = None
    # Egress rules include toServices lowerings (compiler SVCREF_BASE
    # sub-space): classify_batch probes the egress svc dimension with a
    # SECOND key derived from the lane's ServiceLB resolution.  Static so
    # svcref-free rule sets compile the extra gather out entirely.
    svcref: bool = False


def empty_delta(slots: int, w_in: int, w_out: int, xp=jnp) -> DeltaTable:
    return DeltaTable(
        lo_f=xp.full((slots,), 2**31 - 1, dtype=xp.int32),
        hi_f=xp.full((slots,), -(2**31), dtype=xp.int32),
        sign=xp.zeros((slots,), dtype=xp.int32),
        iso=xp.zeros((slots,), dtype=xp.int32),
        at_in=xp.zeros((slots, w_in), dtype=xp.uint32),
        peer_in=xp.zeros((slots, w_in), dtype=xp.uint32),
        at_out=xp.zeros((slots, w_out), dtype=xp.uint32),
        peer_out=xp.zeros((slots, w_out), dtype=xp.uint32),
        n=xp.zeros((), dtype=xp.int32),
        fam=xp.zeros((slots,), dtype=xp.int32),
        lo6_w=xp.full((slots, 4), 2**31 - 1, dtype=xp.int32),
        hi6_w=xp.full((slots, 4), -(2**31), dtype=xp.int32),
    )


# ---------------------------------------------------------------------------
# Host-side table construction
# ---------------------------------------------------------------------------


def _rules_by_gid(gids: np.ndarray) -> dict[int, np.ndarray]:
    order = np.argsort(gids, kind="stable").astype(np.int64)
    sg = gids[order]
    uniq, starts = np.unique(sg, return_index=True)
    out: dict[int, np.ndarray] = {}
    for i, g in enumerate(uniq):
        end = starts[i + 1] if i + 1 < len(uniq) else len(sg)
        out[int(g)] = order[starts[i] : end]
    return out


def _inc_mask(rule_idx: np.ndarray, w: int) -> np.ndarray:
    """Rule indices -> (w,) u32 bitmap."""
    inc = np.zeros(w, dtype=np.uint32)
    np.bitwise_or.at(inc, rule_idx >> 5, (1 << (rule_idx & 31)).astype(np.uint32))
    return inc


_V6_OFF = iputil.V6_OFF
_V6_END = 1 << 128  # exclusive end of the v6-relative space


def _span_list(bounds: list, lo: int, hi: int) -> tuple[int, int]:
    """[lo, hi) range -> inclusive interval-row span [a, b] over a SORTED
    python-int bounds list (bisect 'right' index space, row i covering
    (bounds[i-1], bounds[i]])."""
    import bisect

    a = bisect.bisect_right(bounds, lo)
    b = bisect.bisect_right(bounds, hi - 1)
    return a, b


def _family_split(lo: int, hi: int):
    """Combined-keyspace [lo, hi) -> (v4 part or None, v6-relative part or
    None); family-spanning ranges (any-peer) contribute to both."""
    v4 = v6 = None
    if lo < (1 << 32):
        v4 = (lo, min(hi, 1 << 32))
    if hi > _V6_OFF:
        v6 = (max(lo, _V6_OFF) - _V6_OFF, hi - _V6_OFF)
    return v4, v6


def _dual_bounds(range_lists) -> tuple[list, list]:
    """Boundary points of both families from combined ranges."""
    p4: set[int] = set()
    p6: set[int] = set()
    for ranges in range_lists:
        for lo, hi in ranges:
            r4, r6 = _family_split(int(lo), int(hi))
            if r4 is not None:
                p4.add(r4[0])
                if r4[1] < (1 << 32):
                    p4.add(r4[1])
            if r6 is not None:
                p6.add(r6[0])
                if r6[1] < _V6_END:
                    p6.add(r6[1])
    return sorted(p4), sorted(p6)


def _v6_words(vals: list) -> np.ndarray:
    """Sorted v6-relative ints -> (N, 4) sign-flipped i32 word quadruples
    (lexicographic order preserved word-wise)."""
    out = np.zeros((len(vals), 4), dtype=np.uint32)
    for i, v in enumerate(vals):
        out[i] = [(v >> 96) & 0xFFFFFFFF, (v >> 64) & 0xFFFFFFFF,
                  (v >> 32) & 0xFFFFFFFF, v & 0xFFFFFFFF]
    return iputil.flip_u32(out)


def _paint(b4: list, b6: list, lo: int, hi: int, write) -> None:
    """Paint combined range [lo, hi) into the dual interval row space via
    the write(row_a, row_b) callback: v4 rows [0..len(b4)], v6 rows
    [len(b4)+1 ..]."""
    r4, r6 = _family_split(int(lo), int(hi))
    if r4 is not None and r4[0] < r4[1]:
        a, b = _span_list(b4, *r4)
        write(a, b)
    if r6 is not None and r6[0] < r6[1]:
        a, b = _span_list(b6, *r6)
        off = len(b4) + 1
        write(off + a, off + b)


def _dim_table_host(gids: np.ndarray, groups: list, w: int, ip_dim: bool) -> DimTable:
    """Build one dimension's (bounds, bounds6, incidence) triple.

    Only the groups this dimension actually uses contribute boundary points,
    so each dimension's interval table stays as small as its own address
    structure (the appliedTo dimension is typically far coarser than peer).
    """
    by = _rules_by_gid(gids)
    b4, b6 = _dual_bounds(groups[g] for g in by)
    if not ip_dim:
        # svc keys live entirely below 2^32; no v6 sub-space.
        b6 = []
    n_rows = len(b4) + 1 + (len(b6) + 1 if ip_dim else 0)
    inc = np.zeros((n_rows, w), dtype=np.uint32)
    for g, rr in by.items():
        ranges = groups[g]
        if not ranges or rr.size == 0:
            continue
        gmask = _inc_mask(rr, w)
        nzw = np.nonzero(gmask)[0]
        vals = gmask[nzw]

        def write(a, b):
            inc[a : b + 1][:, nzw] |= vals

        for lo, hi in ranges:
            if ip_dim:
                _paint(b4, b6, lo, hi, write)
            else:
                a, b = _span_list(b4, int(lo), int(hi))
                write(a, b)
    if ip_dim:
        bounds = iputil.flip_u32(np.array(b4, dtype=np.uint64).astype(np.uint32))
        bounds6 = _v6_words(b6)
    else:
        bounds = np.array(b4, dtype=np.int64).astype(np.int32)
        bounds6 = np.zeros((0, 4), dtype=np.int32)
    return DimTable(bounds=bounds, bounds6=bounds6, inc=inc)


def _iso_host(gid: int, groups: list) -> IsoTable:
    ranges = groups[gid]
    b4, b6 = _dual_bounds([ranges])
    val = np.zeros(len(b4) + 1 + len(b6) + 1, dtype=np.int32)

    def write(a, b):
        val[a : b + 1] = 1

    for lo, hi in ranges:
        _paint(b4, b6, lo, hi, write)
    return IsoTable(
        bounds=iputil.flip_u32(np.array(b4, dtype=np.uint64).astype(np.uint32)),
        bounds6=_v6_words(b6),
        val=val,
    )


def _direction_host(
    dt: DirectionTensors, cps: CompiledPolicySet, w: int
) -> DeviceDirection:
    action = np.full(w * 32, ACT_DROP, dtype=np.int32)
    action[: dt.n_rules] = dt.action
    l7 = np.zeros(w * 32, dtype=np.int32)
    if dt.l7 is not None:
        l7[: dt.n_rules] = dt.l7
    return DeviceDirection(
        at=_dim_table_host(dt.at_gid, cps.ip_groups, w, ip_dim=True),
        peer=_dim_table_host(dt.peer_gid, cps.ip_groups, w, ip_dim=True),
        svc=_dim_table_host(dt.svc_gid, cps.svc_groups, w, ip_dim=False),
        action=action,
        l7=l7,
        word_idx=np.arange(w, dtype=np.int32),
    )


def _width(n_rules: int, word_multiple: int) -> int:
    w = max(1, -(-n_rules // 32))
    return -(-w // word_multiple) * word_multiple


def to_host(
    cps: CompiledPolicySet,
    word_multiple: int = 1,
    delta_slots: int = 0,
) -> tuple[DeviceRuleSet, StaticMeta]:
    """Numpy-resident variant of to_device: the same pytree, zero device
    placement (jit accepts numpy leaves and places them itself — used by the
    driver's compile-check entry() so a broken accelerator runtime can still
    build example args).

    word_multiple pads each direction's rule-word count to a multiple (so
    the incidence word axis divides evenly across a rule-parallel mesh
    axis).  delta_slots reserves capacity for incremental membership deltas
    (see DeltaTable); 0 compiles the delta machinery out entirely.
    """
    w_in = _width(cps.ingress.n_rules, word_multiple)
    w_out = _width(cps.egress.n_rules, word_multiple)
    drs = DeviceRuleSet(
        ingress=_direction_host(cps.ingress, cps, w_in),
        egress=_direction_host(cps.egress, cps, w_out),
        iso_in=_iso_host(cps.iso_in_gid, cps.ip_groups),
        iso_out=_iso_host(cps.iso_out_gid, cps.ip_groups),
        ip_delta=empty_delta(max(delta_slots, 1), w_in, w_out, xp=np),
    )
    meta = StaticMeta(
        in_phases=(cps.ingress.n_phase0, cps.ingress.n_k8s, cps.ingress.n_baseline),
        out_phases=(cps.egress.n_phase0, cps.egress.n_k8s, cps.egress.n_baseline),
        w_in=w_in,
        w_out=w_out,
        delta_slots=delta_slots,
        svcref=cps.has_svcref,
    )
    return drs, meta


def to_device(
    cps: CompiledPolicySet,
    word_multiple: int = 1,
    delta_slots: int = 0,
) -> tuple[DeviceRuleSet, StaticMeta]:
    host, meta = to_host(cps, word_multiple, delta_slots)
    return jax.tree_util.tree_map(jnp.asarray, host), meta


# ---------------------------------------------------------------------------
# Kernel
# ---------------------------------------------------------------------------


def _lex_le4(a: jax.Array, b: jax.Array) -> jax.Array:
    """Lexicographic a <= b over a trailing 4-word axis (per-word flipped
    i32 — the same compare _searchsorted6 builds from)."""
    lt = a < b
    eq = a == b
    return lt[..., 0] | (eq[..., 0] & (lt[..., 1] | (eq[..., 1] & (
        lt[..., 2] | (eq[..., 2] & (lt[..., 3] | eq[..., 3]))))))


def _delta_lane_match(ip_f, dt: DeltaTable, i, wide):
    """Lanes slot i's range covers: v4 slots compare the narrow column of
    v4 lanes; v6 slots the wide words of v6 lanes (family-pure slots —
    the dual-stack membership test, shared by rows and iso)."""
    m4 = (ip_f >= dt.lo_f[i]) & (ip_f <= dt.hi_f[i])
    if wide is None:
        return m4
    xw, is6 = wide
    m4 = m4 & (is6 == 0) & (dt.fam[i] == 0)
    m6 = (
        (is6 != 0) & (dt.fam[i] == 1)
        & _lex_le4(dt.lo6_w[i][None, :], xw)
        & _lex_le4(xw, dt.hi6_w[i][None, :])
    )
    return m4 | m6


def _patch_rows(rows: jax.Array, ip_f: jax.Array, dt: DeltaTable, masks,
                wide=None) -> jax.Array:
    """Apply the active delta slots to gathered incidence rows (B, W).
    wide = (xw (B,4), is6) in dual-stack worlds — the dimension's lane
    words, so v6 slots patch v6 lanes."""

    def body(i, rows):
        m = _delta_lane_match(ip_f, dt, i, wide)
        mask = masks[i][None, :]
        s = dt.sign[i]
        rows = jnp.where((m & (s > 0))[:, None], rows | mask, rows)
        rows = jnp.where((m & (s < 0))[:, None], rows & ~mask, rows)
        return rows

    return jax.lax.fori_loop(0, dt.n, body, rows)


def _patch_iso(bit: jax.Array, ip_f: jax.Array, dt: DeltaTable, which: int,
               wide=None) -> jax.Array:
    def body(i, bit):
        m = (
            _delta_lane_match(ip_f, dt, i, wide)
            & (((dt.iso[i] >> which) & 1) == 1)
        )
        s = dt.sign[i]
        bit = jnp.where(m & (s > 0), 1, bit)
        bit = jnp.where(m & (s < 0), 0, bit)
        return bit

    return jax.lax.fori_loop(0, dt.n, body, bit)


def _phase_hits(match: jax.Array, word_idx: jax.Array, phases: tuple[int, int, int]):
    """match (B, W) u32 -> per-phase first-set global rule index (BIG = none).

    First-match-in-priority-order == lowest set bit: rule order encodes
    priority (compiler/compile.py), bit r of word w is global rule 32w+r.
    """
    n0, nk, _nb = phases
    base = word_idx * 32  # (W,) i32

    def mask_lt(n: int) -> jax.Array:
        """(W,) u32 — bits whose global rule index < n."""
        k = jnp.clip(n - base, 0, 32)
        m = (jnp.uint32(1) << jnp.minimum(k, 31).astype(jnp.uint32)) - jnp.uint32(1)
        return jnp.where(k >= 32, jnp.uint32(_ALL1), m)

    m0 = mask_lt(n0)
    mhi = mask_lt(n0 + nk)
    phase_masks = (m0, mhi & ~m0, ~mhi)

    def first(pm: jax.Array) -> jax.Array:
        mw = match & pm[None, :]
        lsb = mw & (jnp.uint32(0) - mw)
        tz = jax.lax.population_count(lsb - jnp.uint32(1))  # 32 when mw == 0
        idx = base[None, :] + tz.astype(jnp.int32)
        idx = jnp.where(mw == jnp.uint32(0), BIG, idx)
        return idx.min(axis=1)

    return tuple(first(pm) for pm in phase_masks)


# Optimization note (measured on v5e, 100k rules, B=32k): replacing the
# three full-width masked scans with STATIC per-phase word slices (phases
# are contiguous rule ranges, so each phase only owns words
# [lo//32, ceil(hi/32))) was tried and is ~1.5x SLOWER (8.3ms vs 5.6ms per
# batch) — the slices break XLA's fusion of gather -> AND -> scan into one
# streaming loop and force the (B, W) match tensor to materialize.
#
# Negative result (round 3, measured on the 100k-rule bench world): a
# TWO-LEVEL incidence hierarchy (per-dimension 32-word block summaries,
# AND the summaries, walk only candidate blocks) does NOT pay: per-DIM
# summary density is 0.90/0.94/1.00 (at/peer/svc), so the summary AND
# leaves ~86% of blocks as candidates (51 of 59 per packet) even though
# true matches average 0.7 rules/packet — the sparsity lives in the 3-way
# intersection, which is only knowable after the gathers the hierarchy
# was meant to avoid.
#
# Round-4 cold-path study (all measured on the axon v5e + this Mosaic
# toolchain, 100k-rule bench world, B=32k; scripts preserved in the round
# notes).  Cost decomposition of the round-3 classifier at 7.0ms/batch
# (4.6M pps): searchsorted 0.77ms; the 6 row gathers ALONE are 4.4ms —
# XLA's gather engine runs at ~84% of HBM peak but counts double, because
# gather output always round-trips HBM (read 1.23GB + write 1.23GB), and
# every unfused consumer re-reads it.  Attempts to eliminate the
# write-back, each DEAD by measurement:
#   1. Pallas scalar-prefetch pipelined per-row loads (grid over packet
#      tiles, BlockSpec index_map from prefetched interval indices):
#      38 GB/s — the per-DMA fixed cost is ~200ns/row and 196k rows/batch
#      need <8ns each.  No DMA-descriptor path can fetch scattered ~7KB
#      rows at line rate; only XLA's gather engine can.
#   2. In-VMEM dynamic gather (tpu.dynamic_gather via take_along_axis):
#      Mosaic lowers it INTRA-VREG ONLY — sublane gathers beyond 8 rows
#      and lane gathers beyond 128 lanes crash the backend.  Arbitrary
#      VMEM table gathers are unavailable on this toolchain.
#   3. Cluster-compressed incidence (u8 ids into VMEM-resident distinct
#      sub-row tables, expanded by intra-vreg lane gather): per-128-word
#      chunk the bench world has 850-3240 DISTINCT sub-rows per dimension
#      — far beyond the 128-lane gather reach.  Genuine entropy.
#   4. Rule-triple dedup (rules sharing (at,peer,svc) gids have identical
#      match conditions; per-phase triple bitmaps ordered by first-rule
#      priority preserve first-match-=-first-bit): distinct-triple ratio
#      measured 1.00x — every rule is a unique triple here.  Zero width
#      reduction.
#   5. MXU one-hot expansion (radix-partitioned packets x 128-row blocks):
#      O(B x 128 x W) FLOPs = ~4ms at bf16 peak before sort costs.  The
#      128x FLOP blowup over the gather's O(B x W) never pays.
# Roofline conclusion: per-packet row volume is ~37.5KB (irreducible —
# notes 2-4 above rule out structural sparsity), and the only functional
# fetch path (XLA gather) doubles it.  2 x 37.5KB at the measured
# 684 GB/s is 9.1M pps for the gather alone, before searchsorted and the
# scan — so ~10M pps cold is out of reach on this chip/toolchain, and the
# remaining winnable margin was the unfused-consumer re-reads.  That win
# is taken by classify_batch_fused below: XLA performs the 6 gathers, ONE
# pallas kernel consumes each gathered byte exactly once (AND + per-phase
# first-set-bit in VMEM, contiguous 1MB block DMAs), measured 6.3ms vs
# 7.1ms (5.2M vs 4.6M pps).  The honest gap to the 10M target is
# reported, not hidden, in bench.py's cold extras.
#
# Round-5 follow-up (round-4 verdict weak #1 asked whether the
# 1.9ms/batch of non-gather time could be overlapped or folded; same
# world, B=32k, /tmp/cold_study.py methodology):
#   Measured decomposition: searchsorted ALONE 1.52ms; searchsorted +
#   6 gathers + a reduction FUSED into the gather loops 4.44ms; fused
#   end-to-end 6.80ms.  4.44 equals the round-4 "gathers alone" bound —
#   i.e. searchsorted is ALREADY hidden under the gather streams (its
#   1.52ms of VPU compare work overlaps the DMA wavefronts inside XLA's
#   fused loops).  Verdict idea (a), "overlap searchsorted with the
#   gather stream", is therefore already in effect; there is no further
#   cross-op overlap to program — a TensorCore runs one XLA op at a
#   time, and fusion is the only overlap mechanism exposed.
#   Verdict idea (b), "fold the two-level searchsorted's in-block finish
#   into the consumer kernel": the in-block finish needs a per-lane
#   dynamic 256-word window from the bounds table — exactly the
#   arbitrary-VMEM-gather shape note 2 above measured as unavailable
#   (Mosaic dynamic_gather is intra-vreg only).  Dead by the same wall.
#   New idea (c), AND the three gathered rows IN XLA and hand the pallas
#   consumer ONE matrix per direction (hoping gather->AND fuses and
#   halves the consumer's read volume): measured 7.61ms — WORSE than the
#   6-input consumer.  XLA materializes all six gather outputs AND the
#   two AND results (multi-consumer gathers don't fuse into one loop),
#   adding ~12.5KB/packet of traffic instead of removing any.
# Residual: end-to-end minus the gather bound is 2.36ms — the pallas
# consumer's re-read of the 37.5KB/packet the gathers materialized
# (37.5KB x 32k / 684 GB/s = 1.75ms floor + tile scheduling).  Removing
# it requires gathering INTO the consumer, which note 1 bounds at
# 38 GB/s.  The cold ceiling on this chip/toolchain therefore stands at
# ~4.8-5.4M pps as shipped, with ~7.4M the hard gather-bound limit.
#
# Round-6 overlap study (ROADMAP item 2: the churn gap is SERIALIZATION,
# not kernel speed — BENCH_r05 steady_churn 4.97M pps = 26.4ms per 131k
# batch, vs the Amdahl prediction of the measured parts: 5.7ms fast step
# + ~3.4ms for one coalesced 16k drain = 9.1ms, ~14M pps.  The ~17ms gap
# is the drain pipeline running IN SEQUENCE with the fast path: lookup
# pass, classify, commit scatters, eviction gather, plus the engine's
# two separate full-table maintenance scans and the per-call output
# fetch blocking the next dispatch).  What was restructured, and what
# was ruled out:
#   OVERLAPPED (shipped, models/pipeline + datapath/slowpath):
#   (a) eviction-scan + aging + revalidation folded into the drain's
#       commit pass (meta.drain_reclaim): the PH_EVICT audit already
#       gathers each insert target's old key row; reading its ts/conf in
#       the same pass classifies dead rows (idle-expired / stale-gen) as
#       reclaims, so the engine's stale-epoch heal needs ONE fused
#       maintain_scan (age + revalidate in a single keys/meta/ts read)
#       instead of two full passes over PipelineState — at 2^22 slots
#       that removes ~150MB of HBM traffic per heal.
#   (b) the drain dispatched with the STATE DONATED
#       (pl.pipeline_step_donated): without donation every per-call
#       drain allocates fresh output buffers for the rewritten cache
#       columns (~150MB at 2^22 slots) and copies; donation lets XLA
#       alias the scatters in place — the eager-dispatch analog of the
#       fori_loop carry aliasing the bench already enjoyed.
#   (c) one-step commit deferral (two-slot staging): drain of window i-1
#       dispatches after fast step i with no dependency on its OUTPUTS
#       (only the carried state), and the host-side materialization of
#       drain outputs retires two slots later — so the host never blocks
#       the device pipeline on np.asarray between fast and drain, and
#       XLA/the runtime can pipeline the dispatch stream.  Verdict
#       visibility lags exactly one window (the admitted lanes' flows
#       were pending anyway); state visibility is immediate via the
#       carried pytree (the lost-update guard).
#   NOT overlapped, dead by the same walls as rounds 4-5:
#   (d) lowering the commit scatters into the pallas classify consumer
#       (one kernel classifying + writing the cache): Mosaic on this
#       toolchain has no arbitrary-VMEM-scatter path, the same wall as
#       note 2's intra-vreg-only dynamic_gather — and the flow cache is
#       64MB+ per column, far beyond VMEM residency anyway.
#   (e) true cross-op concurrency: a TensorCore runs one XLA op at a
#       time, so "overlap" here means removing redundant passes, copies
#       and host round-trips from the serial schedule, not co-executing
#       fast and drain — the honest mechanism, and why the decomposition
#       (bench_cold_study.py case 5: fast alone / drain alone /
#       serialized / overlapped) is the proof obligation: the overlapped
#       step time must approach max-ish(fast, drain) only through the
#       removed work, and serialized-minus-overlapped IS the recovered
#       serialization.  On-chip numbers land with BENCH_r06 /
#       PROFILE bench_profile.py --mode overlap (the ±15% gate
#       cross-checks the attribution); this container is CPU-only, so
#       the r06 record is the bench's to write, not this note's.


def _resolve(action: jax.Array, hits, pod_iso: jax.Array):
    """Phase resolution -> (code (B,), rule_idx (B,) [-1 = default])."""
    h0, hk, hb = hits
    a0 = action[jnp.clip(h0, 0, action.shape[0] - 1)]
    ab = action[jnp.clip(hb, 0, action.shape[0] - 1)]
    has0 = h0 < BIG
    hask = hk < BIG
    hasb = hb < BIG

    decided0 = has0 & (a0 != ACT_PASS)
    decidedb = hasb & (ab != ACT_PASS)

    # K8s NP rules are any-match ALLOW within the isolation model.
    k8s_code = jnp.where(hask, ACT_ALLOW, ACT_DROP)
    k8s_rule = jnp.where(hask, hk, -1)

    code = jnp.where(
        decided0,
        a0,
        jnp.where(
            pod_iso == 1,
            k8s_code,
            jnp.where(decidedb, ab, ACT_ALLOW),
        ),
    )
    rule = jnp.where(
        decided0,
        h0,
        jnp.where(
            pod_iso == 1,
            k8s_rule,
            jnp.where(decidedb, hb, -1),
        ),
    )
    return code.astype(jnp.int32), rule.astype(jnp.int32)


_SS_BLOCK = 256  # ~sqrt(NB) at the 100k-rule scale; compares/pkt = NB/256+256


def _searchsorted_right(bounds: jax.Array, x: jax.Array) -> jax.Array:
    """TPU-tuned searchsorted(side='right').

    jnp's default 'scan' (binary-search) method lowers to a sequential
    gather loop that is ~40x slower on TPU than an all-pairs compare-reduce
    for our table sizes (measured on v5e: 10.9 ms vs 0.28 ms at B=32k,
    NB=33k).  compare_all is O(B*NB) and wins up to a few thousand bounds;
    beyond that a TWO-LEVEL blocked search cuts the compare volume ~128x:
    compare_all over the ~NB/256 block maxima picks the block, one (B, 256)
    row gather + mask-count finishes inside it.  Both levels are streaming
    VPU work with static shapes (vmap/shard_map friendly).
    """
    nb = bounds.shape[0]
    if nb <= 4096:
        return jnp.searchsorted(bounds, x, side="right", method="compare_all")
    K = _SS_BLOCK
    nblk = -(-nb // K)
    pad = nblk * K - nb
    # Pads sit at int32 max; they are masked out of the in-block count, so a
    # genuine max-valued bound (flip of 0xFFFFFFFF) still counts correctly.
    bp = jnp.concatenate(
        [bounds, jnp.full((pad,), 2**31 - 1, bounds.dtype)]
    ).reshape(nblk, K)
    blk = jnp.searchsorted(bp[:, -1], x, side="right", method="compare_all")
    blk_c = jnp.minimum(blk, nblk - 1)
    window = bp[blk_c]  # (B, K) row gather
    off = jnp.arange(K, dtype=jnp.int32)
    valid = (blk_c[:, None] * K + off[None, :]) < nb
    inblock = ((window <= x[:, None]) & valid).sum(axis=1, dtype=jnp.int32)
    return blk_c * K + inblock


def _searchsorted6(bounds6: jax.Array, xw: jax.Array) -> jax.Array:
    """Lexicographic searchsorted(side='right') over 4-word v6 boundaries.

    bounds6 (N, 4) and xw (B, 4) are per-word sign-flipped i32, so word-wise
    signed compares give unsigned lexicographic order.  v6 boundary tables
    are small (group CIDR endpoints), so all-pairs compare-count is the
    right TPU shape (see _searchsorted_right's rationale).
    """
    n = bounds6.shape[0]
    if n == 0:
        return jnp.zeros(xw.shape[0], dtype=jnp.int32)
    leq = _lex_le4(bounds6[None, :, :], xw[:, None, :])  # (B, N)
    return leq.sum(axis=1, dtype=jnp.int32)


def classify_batch(
    drs: DeviceRuleSet,
    src_ip_f: jax.Array,  # (B,) sign-flipped i32
    dst_ip_f: jax.Array,
    proto: jax.Array,  # (B,) i32
    dst_port: jax.Array,  # (B,) i32
    *,
    meta: StaticMeta,
    hit_combine=None,
    fused: bool = False,
    v6=None,
    svc_ref=None,
):
    """-> dict with final/egress/ingress codes and deciding rule indices.

    Codes use the oracle encoding: 0 allow, 1 drop, 2 reject.

    hit_combine, if given, is applied to each per-phase first-match hit
    tensor between the word scan and phase resolution — the rule-parallel
    seam: a shard_map caller passes ``lambda h: lax.pmin(h, 'rule')`` so
    each rule shard ANDs only its local incidence words and the global first
    match is an all-reduce over ICI (the TPU analog of OVS evaluating one
    shared table).

    v6, if given, is the dual-stack lane extension (ref pipeline.go IPv6
    table): a (src6w_f (B,4), dst6w_f (B,4), is6 (B,)) tuple of per-word
    sign-flipped v6 addresses plus the family mask.  v6 lanes resolve in
    each dimension's v6 interval sub-space; their v4-lane inputs are
    ignored.  None = pure-v4 batch (zero extra work — the v4 interval rows
    come first, so indices need no adjustment).

    fused=True consumes the gathered rows through the pallas consumer
    kernel (one read per gathered byte; see the cold-path study above).
    Composes with hit_combine's rule-axis sharding: each shard's kernel
    receives its global word offset (word_idx[0], carried as data for
    exactly this) and emits GLOBAL rule indices, so the pmin all-reduce
    combines them like the XLA-scan path — the sharded walk keeps the
    fused cold-path win.  Delta patching composes (it runs on the
    gathered rows before the consumer).  Off-TPU the kernel runs in
    interpret mode (slow; parity tests only).
    """
    ing, eg = drs.ingress, drs.egress
    svc_key = (proto << 16) | dst_port
    if v6 is not None:
        src6w, dst6w, is6 = v6

    def dim_idx(tab, x, x6w):
        i4 = _searchsorted_right(tab.bounds, x)
        if v6 is None:
            return i4
        i6 = tab.bounds.shape[0] + 1 + _searchsorted6(tab.bounds6, x6w)
        return jnp.where(is6 != 0, i6, i4)

    def dim_row(tab: DimTable, x: jax.Array, x6w=None) -> jax.Array:
        if x6w is None:
            # svc dimension: the (proto<<16|port) key space is shared by
            # both families — no v6 sub-space.
            return tab.inc[_searchsorted_right(tab.bounds, x)]
        return tab.inc[dim_idx(tab, x, x6w)]

    def iso_bit(tab: IsoTable, x: jax.Array, x6w=None) -> jax.Array:
        return tab.val[dim_idx(tab, x, x6w)]

    # Ingress: pod = dst, peer = src.  Egress: pod = src, peer = dst.
    s6 = src6w if v6 is not None else None
    d6 = dst6w if v6 is not None else None
    in_at = dim_row(ing.at, dst_ip_f, d6)
    in_peer = dim_row(ing.peer, src_ip_f, s6)
    in_svc = dim_row(ing.svc, svc_key)
    out_at = dim_row(eg.at, src_ip_f, s6)
    out_peer = dim_row(eg.peer, dst_ip_f, d6)
    out_svc = dim_row(eg.svc, svc_key)
    if meta.svcref:
        # toServices probe (the ServiceGroupID-conjunction analog): a
        # second egress svc-dim gather keyed on the lane's ServiceLB
        # resolution in the reference sub-space.  OR is exact — ordinary
        # port ranges live below SVCREF_BASE and reference ranges at
        # SVCREF_BASE + idx, so each rule can match via exactly one of
        # the two probes (compiler/compile.py SVCREF_BASE contract).
        from ..compiler.compile import SVCREF_BASE, SVCREF_NONE

        if svc_ref is None:
            ref_key = jnp.full_like(svc_key, SVCREF_NONE)
        else:
            ref_key = jnp.where(
                svc_ref >= 0, SVCREF_BASE + svc_ref, SVCREF_NONE
            )
        out_svc = out_svc | dim_row(eg.svc, ref_key)
    iso_in = iso_bit(drs.iso_in, dst_ip_f, d6)
    iso_out = iso_bit(drs.iso_out, src_ip_f, s6)

    if meta.delta_slots > 0:
        # Incremental membership deltas patch the gathered rows, so peer/
        # appliedTo/isolation consumers all see post-delta membership.
        # Slots are family-pure: v4 slots patch v4 lanes on the narrow
        # column, v6 slots patch v6 lanes on their wide words — v6 pod
        # churn stays O(1), no recompile (DeltaTable docstring).
        d = drs.ip_delta
        wide_d = None if v6 is None else (d6, is6)
        wide_s = None if v6 is None else (s6, is6)
        in_at = _patch_rows(in_at, dst_ip_f, d, d.at_in, wide_d)
        in_peer = _patch_rows(in_peer, src_ip_f, d, d.peer_in, wide_s)
        out_at = _patch_rows(out_at, src_ip_f, d, d.at_out, wide_s)
        out_peer = _patch_rows(out_peer, dst_ip_f, d, d.peer_out, wide_d)
        iso_in = _patch_iso(iso_in, dst_ip_f, d, 0, wide_d)
        iso_out = _patch_iso(iso_out, src_ip_f, d, 1, wide_s)

    if fused:
        shard = hit_combine is not None
        in_hits, out_hits = _fused_hits(
            (in_at, in_peer, in_svc), (out_at, out_peer, out_svc), meta,
            w0_in=ing.word_idx[0] if shard else None,
            w0_out=eg.word_idx[0] if shard else None,
        )
    else:
        in_hits = _phase_hits(
            in_at & in_peer & in_svc, ing.word_idx, meta.in_phases
        )
        out_hits = _phase_hits(
            out_at & out_peer & out_svc, eg.word_idx, meta.out_phases
        )

    if hit_combine is not None:
        in_hits = tuple(hit_combine(h) for h in in_hits)
        out_hits = tuple(hit_combine(h) for h in out_hits)

    in_code, in_rule = _resolve(ing.action, in_hits, iso_in)
    out_code, out_rule = _resolve(eg.action, out_hits, iso_out)

    final = jnp.where(out_code != ACT_ALLOW, out_code, in_code)
    return {
        "code": final,
        "egress_code": out_code,
        "egress_rule": out_rule,
        "ingress_code": in_code,
        "ingress_rule": in_rule,
    }


# ---------------------------------------------------------------------------
# Fused consumer kernel (the round-4 cold-path lever; see the study above):
# XLA performs the row gathers, one pallas kernel then consumes each
# gathered byte exactly once — AND + per-phase first-set-bit entirely in
# VMEM, fed by contiguous ~1MB block DMAs instead of XLA's materialize-and-
# re-read consumer chain.
# ---------------------------------------------------------------------------

_FUSE_TB = 128  # packet rows per grid step (~4.8MB of VMEM blocks, 2x buffered)


def _phase_scan_tile(m, w, phases):
    """(TB, w) i32 match tile -> per-phase first-set global rule index.

    Phases are contiguous rule ranges, so each phase owns a STATIC word
    slice; only its two boundary words need bit masking.  Inside pallas
    there is no XLA-fusion concern (the round-3 negative result on static
    slices was about breaking XLA loop fusion), so the sliced form wins.
    """
    mu = m.astype(jnp.uint32)

    def first_bounded(lo_rule, hi_rule):
        if lo_rule >= hi_rule:
            return jnp.full((m.shape[0],), BIG, jnp.int32)
        lo_w, hi_w = lo_rule // 32, -(-hi_rule // 32)
        sub = mu[:, lo_w:hi_w]
        base = jax.lax.broadcasted_iota(
            jnp.int32, (m.shape[0], hi_w - lo_w), 1
        ) * 32 + lo_w * 32
        k_lo = jnp.clip(lo_rule - base, 0, 32)
        k_hi = jnp.clip(hi_rule - base, 0, 32)
        mask_lo = jnp.where(
            k_lo <= 0,
            jnp.uint32(_ALL1),
            ~((jnp.uint32(1) << jnp.minimum(k_lo, 31).astype(jnp.uint32))
              - jnp.uint32(1)),
        )
        mask_lo = jnp.where(k_lo >= 32, jnp.uint32(0), mask_lo)
        mask_hi = jnp.where(
            k_hi >= 32,
            jnp.uint32(_ALL1),
            (jnp.uint32(1) << jnp.clip(k_hi, 0, 31).astype(jnp.uint32))
            - jnp.uint32(1),
        )
        mw = sub & mask_lo & mask_hi
        lsb = mw & (jnp.uint32(0) - mw)
        tz = jax.lax.population_count(lsb - jnp.uint32(1))
        v = jnp.where(mw == jnp.uint32(0), BIG, base + tz.astype(jnp.int32))
        return jnp.min(v, axis=1)

    n0, nk, _nb = phases
    return (
        first_bounded(0, n0),
        first_bounded(n0, n0 + nk),
        first_bounded(n0 + nk, w * 32),
    )


def _phase_scan_tile_dyn(m, w, phases, w0):
    """_phase_scan_tile with a DYNAMIC global word offset (the rule-axis
    shard seam): this tile's words are global words [w0, w0+w), so phase
    boundaries cannot be static slices — each phase masks the full width
    by its global-rule window instead (the _phase_hits mask discipline,
    inside VMEM).  w0 is a traced scalar from word_idx, NOT a python int."""
    mu = m.astype(jnp.uint32)
    base = (jax.lax.broadcasted_iota(jnp.int32, (m.shape[0], w), 1)
            + w0) * 32

    def first_bounded(lo_rule, hi_rule):
        k_lo = jnp.clip(lo_rule - base, 0, 32)
        k_hi = jnp.clip(hi_rule - base, 0, 32)
        mask_lo = jnp.where(
            k_lo <= 0,
            jnp.uint32(_ALL1),
            ~((jnp.uint32(1) << jnp.minimum(k_lo, 31).astype(jnp.uint32))
              - jnp.uint32(1)),
        )
        mask_lo = jnp.where(k_lo >= 32, jnp.uint32(0), mask_lo)
        mask_hi = jnp.where(
            k_hi >= 32,
            jnp.uint32(_ALL1),
            (jnp.uint32(1) << jnp.clip(k_hi, 0, 31).astype(jnp.uint32))
            - jnp.uint32(1),
        )
        mw = mu & mask_lo & mask_hi
        lsb = mw & (jnp.uint32(0) - mw)
        tz = jax.lax.population_count(lsb - jnp.uint32(1))
        v = jnp.where(mw == jnp.uint32(0), BIG, base + tz.astype(jnp.int32))
        return jnp.min(v, axis=1)

    n0, nk, _nb = phases
    # Baseline phase upper bound: unbounded (padding words carry zero bits).
    return (
        first_bounded(0, n0),
        first_bounded(n0, n0 + nk),
        first_bounded(n0 + nk, 1 << 30),
    )


@lru_cache(maxsize=32)
def _consumer_call(b, w_in, w_out, in_phases, out_phases, interpret,
                   sharded):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    tb = _FUSE_TB
    if sharded:
        # Shard-aware variant: two SMEM scalars carry each direction's
        # global word offset (word_idx[0] — data, so the SAME compiled
        # kernel serves every rule shard under shard_map).
        def kernel(ia, ip_, is_, oa, op_, os_, w0i, w0o, o_ref):
            i0, ik, ib = _phase_scan_tile_dyn(
                ia[:] & ip_[:] & is_[:], w_in, in_phases, w0i[0, 0])
            o0, ok_, ob = _phase_scan_tile_dyn(
                oa[:] & op_[:] & os_[:], w_out, out_phases, w0o[0, 0])
            o_ref[:] = jnp.stack(
                [i0, ik, ib, o0, ok_, ob,
                 jnp.zeros_like(i0), jnp.zeros_like(i0)], axis=1,
            )

        extra = [pl.BlockSpec((1, 1), lambda i: (0, 0),
                              memory_space=pltpu.SMEM)] * 2
    else:
        def kernel(ia, ip_, is_, oa, op_, os_, o_ref):
            i0, ik, ib = _phase_scan_tile(
                ia[:] & ip_[:] & is_[:], w_in, in_phases)
            o0, ok_, ob = _phase_scan_tile(
                oa[:] & op_[:] & os_[:], w_out, out_phases)
            o_ref[:] = jnp.stack(
                [i0, ik, ib, o0, ok_, ob,
                 jnp.zeros_like(i0), jnp.zeros_like(i0)], axis=1,
            )

        extra = []

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, 8), jnp.int32),
        grid=(b // tb,),
        in_specs=[pl.BlockSpec((tb, w), lambda i: (i, 0))
                  for w in (w_in, w_in, w_in, w_out, w_out, w_out)] + extra,
        out_specs=pl.BlockSpec((tb, 8), lambda i: (i, 0)),
        interpret=interpret,
    )


def _fused_hits(rows_in, rows_out, meta: StaticMeta, w0_in=None, w0_out=None):
    """6 gathered row sets -> (in_hits, out_hits) via the fused consumer.

    Pads the batch to the tile multiple (tiny worlds / odd slow-path
    chunks); interpret mode keeps the kernel testable off-TPU.

    w0_in/w0_out (traced scalars): each direction's global word offset —
    pass word_idx[0] under rule-axis shard_map so the kernel emits GLOBAL
    rule indices that compose with the hit_combine pmin (the shard seam;
    None = single-chip, offsets statically zero).  Widths come from the
    rows themselves (per-shard width != meta.w_* under sharding).
    """
    b = rows_in[0].shape[0]
    w_in = rows_in[0].shape[1]
    w_out = rows_out[0].shape[1]
    pad = (-b) % _FUSE_TB
    if pad:
        rows_in = tuple(jnp.pad(r, ((0, pad), (0, 0))) for r in rows_in)
        rows_out = tuple(jnp.pad(r, ((0, pad), (0, 0))) for r in rows_out)
    if meta.fused_interpret is not None:
        interpret = meta.fused_interpret
    else:
        interpret = jax.devices()[0].platform == "cpu"
    sharded = w0_in is not None
    call = _consumer_call(
        b + pad, w_in, w_out, meta.in_phases, meta.out_phases,
        interpret, sharded,
    )
    if sharded:
        scal = lambda x: jnp.asarray(x, jnp.int32).reshape(1, 1)  # noqa: E731
        hits = call(*rows_in, *rows_out, scal(w0_in), scal(w0_out))[:b]
    else:
        hits = call(*rows_in, *rows_out)[:b]
    return (hits[:, 0], hits[:, 1], hits[:, 2]), (hits[:, 3], hits[:, 4], hits[:, 5])


def flip_ips(a: np.ndarray) -> np.ndarray:
    """Host helper: u32 IP array -> sign-flipped i32 (kernel input layout)."""
    return iputil.flip_u32(a)


# meta is static (plain ints/tuples, hashable); drs is a traced pytree arg so
# the big incidence tensors stay runtime inputs instead of baked-in constants.
_classify_jit = jax.jit(
    classify_batch, static_argnames=("meta", "hit_combine", "fused")
)


def make_classifier(cps: CompiledPolicySet):
    """-> (fn(src_f, dst_f, proto, dport, v6=None) -> verdict dict, DRS)."""
    drs, meta = to_device(cps)

    def fn(src_f, dst_f, proto, dport, v6=None):
        return _classify_jit(drs, src_f, dst_f, proto, dport, meta=meta,
                             v6=v6)

    return fn, drs
