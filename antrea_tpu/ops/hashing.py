"""Shared integer hashing, written once for numpy AND jax.numpy.

The same bits must come out of the scalar oracle (numpy) and the device
kernels (jnp) — endpoint selection and conntrack slots are part of verdict
parity (the reference gets this for free because OVS group dp_hash and kernel
conntrack are single implementations; we keep a single implementation by
parameterizing the array module).
"""

from __future__ import annotations

import contextlib

import numpy as np

_FNV_PRIME = 16777619
_FNV_BASIS = 0x811C9DC5


def fnv_mix(words, xp=np):
    """FNV-1a over a sequence of u32 words -> u32 hash (array-shaped)."""
    # u32 wraparound is the point; keep numpy from warning about it.
    ctx = np.errstate(over="ignore") if xp is np else contextlib.nullcontext()
    with ctx:
        h = None
        for w in words:
            w = xp.asarray(w).astype(xp.uint32)
            if h is None:
                h = xp.full_like(w, _FNV_BASIS, dtype=xp.uint32)
            h = (h ^ w) * xp.uint32(_FNV_PRIME)
            # extra avalanche: xorshift
            h = h ^ (h >> xp.uint32(15))
    return h


def flow_hash(src, dst, proto, sport, dport, salt=0x5CA1AB1E, xp=np):
    """Symmetric-free 5-tuple hash used for endpoint selection + ct slots."""
    return fnv_mix(
        [src, dst, (xp.asarray(proto).astype(xp.uint32) << xp.uint32(16))
         ^ xp.asarray(sport).astype(xp.uint32),
         xp.asarray(dport).astype(xp.uint32) ^ xp.uint32(salt)],
        xp=xp,
    )


def flow_hash_wide(addr_cols, proto, sport, dport, salt=0x5CA1AB1E, xp=np):
    """Dual-stack 5-tuple hash: 8 address words (both endpoints in wide,
    v4-mapped word form — see utils/ip.key_to_words) + ports/proto.
    addr_cols is a sequence of 8 (B,)-shaped word arrays (sign-flipped i32
    is fine: the u32 view is hashed, identically on both twins)."""
    return fnv_mix(
        [*addr_cols,
         (xp.asarray(proto).astype(xp.uint32) << xp.uint32(16))
         ^ xp.asarray(sport).astype(xp.uint32),
         xp.asarray(dport).astype(xp.uint32) ^ xp.uint32(salt)],
        xp=xp,
    )
