"""Observability plane: NP audit logging, metrics surface (SURVEY §5),
realization tracing + the flight-recorder event journal (PR 8)."""

from .audit import AuditLogger
from .flightrec import EVENT_KINDS, FlightRecorder
from .metrics import (
    METRICS,
    Histogram,
    render_dissemination_metrics,
    render_metrics,
)
from .tracing import REALIZATION_STAGES, RealizationTracer

__all__ = [
    "AuditLogger",
    "EVENT_KINDS",
    "FlightRecorder",
    "Histogram",
    "METRICS",
    "REALIZATION_STAGES",
    "RealizationTracer",
    "render_dissemination_metrics",
    "render_metrics",
]
