"""Observability plane: NP audit logging + metrics surface (SURVEY §5)."""

from .audit import AuditLogger
from .metrics import (
    METRICS,
    Histogram,
    render_dissemination_metrics,
    render_metrics,
)

__all__ = [
    "AuditLogger",
    "Histogram",
    "METRICS",
    "render_dissemination_metrics",
    "render_metrics",
]
