"""Pull-style metrics surface (Prometheus text exposition format).

The analog of the reference's agent metrics
(/root/reference/pkg/agent/metrics/prometheus.go:33-188: rule counts,
per-table flow counts, conntrack totals) rendered from this build's
observable state: DatapathStats (per-rule packet counters), the flow-cache
census (models/pipeline.cache_stats), the cumulative eviction counter (the
weak-#5 measurement surface) and the latency histograms (datapath step,
agent sync, controller-commit->datapath-realized dissemination).
render_metrics() is the scrape function; the simulator (or any collector)
consumes the text directly.

Exposition discipline (enforced by tests/test_prom_exposition.py's strict
parser and tools/check_metrics.py's README drift check):
  * every emitted family is declared in METRICS (name -> type) and gets its
    `# TYPE` line from _type_line — an undeclared name cannot be emitted;
  * all label rendering goes through _labels (one escaping/formatting
    path; empty values are omitted, so node="" composes everywhere).
"""

from __future__ import annotations

import bisect

# The complete metric inventory: family name -> Prometheus type.  The ONE
# registry tools/check_metrics.py diffs against the README "Observability"
# table; render functions emit TYPE lines via _type_line so an unregistered
# family fails loudly at render time, not silently at scrape time.
METRICS: dict[str, str] = {
    # controller (render_controller_metrics)
    "antrea_tpu_controller_objects": "gauge",
    "antrea_tpu_controller_connected_agents": "gauge",
    # dissemination plane (render_dissemination_metrics)
    "antrea_tpu_dissemination_watcher_pending": "gauge",
    "antrea_tpu_dissemination_watcher_overflows_total": "counter",
    "antrea_tpu_dissemination_watcher_needs_resync": "gauge",
    "antrea_tpu_dissemination_resyncs_total": "counter",
    "antrea_tpu_dissemination_reconnects_total": "counter",
    "antrea_tpu_dissemination_queue_coalesced_total": "counter",
    "antrea_tpu_dissemination_resync_chunks_total": "counter",
    "antrea_tpu_dissemination_resyncs_inflight": "gauge",
    "antrea_tpu_agent_reconnects_total": "counter",
    "antrea_tpu_agent_resyncs_total": "counter",
    "antrea_tpu_agent_sync_failures_total": "counter",
    "antrea_tpu_agent_sync_seconds": "histogram",
    "antrea_tpu_dissemination_latency_seconds": "histogram",
    # datapath (render_metrics)
    "antrea_tpu_rule_packets_total": "counter",
    "antrea_tpu_rule_bytes_total": "counter",
    "antrea_tpu_default_verdict_packets_total": "counter",
    "antrea_tpu_flow_cache_entries": "gauge",
    "antrea_tpu_flow_cache_slots": "gauge",
    "antrea_tpu_flow_cache_evictions_total": "counter",
    "antrea_tpu_flow_cache_reclaims_total": "counter",
    "antrea_tpu_datapath_step_seconds": "histogram",
    # async slow-path engine (datapath/slowpath; rendered when the
    # datapath exposes slowpath_stats())
    "antrea_tpu_miss_queue_depth": "gauge",
    "antrea_tpu_miss_queue_capacity": "gauge",
    "antrea_tpu_miss_queue_admitted_total": "counter",
    "antrea_tpu_miss_queue_overflows_total": "counter",
    "antrea_tpu_miss_queue_early_drops_total": "counter",
    "antrea_tpu_miss_queue_source_limited_total": "counter",
    "antrea_tpu_slowpath_drained_total": "counter",
    "antrea_tpu_slowpath_stale_reclassified_total": "counter",
    "antrea_tpu_slowpath_drain_batch_size": "histogram",
    "antrea_tpu_flow_cache_epoch": "gauge",
    "antrea_tpu_flow_cache_epoch_age_seconds": "gauge",
    # overlapped drain-commit plane + drain-chunk autotuner (round 6:
    # double-buffered churn datapath; rendered with slowpath_stats())
    "antrea_tpu_slowpath_overlap_depth": "gauge",
    "antrea_tpu_slowpath_deferred_commits_total": "counter",
    "antrea_tpu_slowpath_deferred_commit_staleness_seconds": "gauge",
    "antrea_tpu_slowpath_drain_chunk": "gauge",
    "antrea_tpu_slowpath_autotune_decisions_total": "counter",
    # transactional bundle commit plane (datapath/commit.py; rendered when
    # the datapath exposes commit_stats())
    "antrea_tpu_bundle_commits_total": "counter",
    "antrea_tpu_bundle_rollbacks_total": "counter",
    "antrea_tpu_canary_probes_total": "counter",
    "antrea_tpu_canary_mismatches_total": "counter",
    "antrea_tpu_datapath_degraded": "gauge",
    "antrea_tpu_bundle_lkg_generation": "gauge",
    "antrea_tpu_bundle_lkg_age_seconds": "gauge",
    # continuous flow-cache revalidator (datapath/audit.py; rendered when
    # the datapath exposes audit_stats())
    "antrea_tpu_cache_audit_scans_total": "counter",
    "antrea_tpu_cache_audit_entries_total": "counter",
    "antrea_tpu_cache_audit_divergences_total": "counter",
    "antrea_tpu_cache_audit_repairs_total": "counter",
    "antrea_tpu_tensor_scrub_total": "counter",
    "antrea_tpu_audit_cursor_coverage_ratio": "gauge",
    # unified maintenance scheduler (datapath/maintenance.py; rendered
    # when the datapath exposes maintenance_stats())
    "antrea_tpu_maintenance_ticks_total": "counter",
    "antrea_tpu_maintenance_blocked_ticks_total": "counter",
    "antrea_tpu_maintenance_task_runs_total": "counter",
    "antrea_tpu_maintenance_budget_spent_total": "counter",
    "antrea_tpu_maintenance_deferrals_total": "counter",
    "antrea_tpu_maintenance_shed_total": "counter",
    "antrea_tpu_maintenance_scheduler_lag": "gauge",
    # realization tracing (observability/tracing.py; rendered when the
    # datapath exposes realization_stats()) + the agent-side pending-stamp
    # truncation meter (render_dissemination_metrics)
    "antrea_tpu_policy_realization_seconds": "histogram",
    "antrea_tpu_realization_spans": "gauge",
    "antrea_tpu_realization_spans_dropped_total": "counter",
    "antrea_tpu_realization_stamps_dropped_total": "counter",
    # flight recorder (observability/flightrec.py; rendered when the
    # datapath exposes flightrecorder_stats())
    "antrea_tpu_flightrecorder_events_total": "counter",
    "antrea_tpu_flightrecorder_dropped_total": "counter",
    "antrea_tpu_flightrecorder_seq": "gauge",
    # multichip datapath (parallel/meshpath.py; rendered when the
    # datapath exposes mesh_stats()) — shard-labeled families so a pod
    # slice's per-replica health is scrapeable replica-for-replica
    "antrea_tpu_replica_miss_queue_depth": "gauge",
    "antrea_tpu_replica_canary_mismatches_total": "counter",
    "antrea_tpu_replica_audit_entries_total": "counter",
    # elastic mesh resharding (parallel/reshard.py; rendered when the
    # datapath exposes reshard_stats()) — migration progress, resident
    # target rows, and the cutover/abort history of the resize plane
    "antrea_tpu_reshard_topology_generation": "gauge",
    "antrea_tpu_reshard_active": "gauge",
    "antrea_tpu_reshard_progress_ratio": "gauge",
    "antrea_tpu_reshard_migrated_rows_total": "counter",
    "antrea_tpu_reshard_resident_rows": "gauge",
    "antrea_tpu_reshard_cutovers_total": "counter",
    "antrea_tpu_reshard_aborts_total": "counter",
    "antrea_tpu_reshard_catchup_rows_total": "counter",
    "antrea_tpu_reshard_tenant_rows_total": "counter",
    "antrea_tpu_reshard_tenant_vetoes_total": "counter",
    # replica-loss failover plane (parallel/failover.py; rendered when
    # the datapath exposes failover_stats()) — the shard-labeled
    # quarantined gauge plus probe/quarantine/evacuation/readmission
    # totals and the evacuation re-miss burst meter
    "antrea_tpu_failover_quarantined": "gauge",
    "antrea_tpu_failover_probes_total": "counter",
    "antrea_tpu_failover_probe_failures_total": "counter",
    "antrea_tpu_failover_slow_dispatches_total": "counter",
    "antrea_tpu_failover_quarantines_total": "counter",
    "antrea_tpu_failover_evacuations_total": "counter",
    "antrea_tpu_failover_readmissions_total": "counter",
    "antrea_tpu_failover_remiss_total": "counter",
    # aggregated-bitmap match pruning (ops/match round 7; rendered when
    # the datapath exposes prune_stats())
    "antrea_tpu_match_prune_skips_total": "counter",
    "antrea_tpu_match_prune_fallbacks_total": "counter",
    "antrea_tpu_match_prune_candidate_superblocks": "histogram",
    "antrea_tpu_match_prune_budget": "gauge",
    "antrea_tpu_match_prune_retunes_total": "counter",
    # multi-tenant serving plane (datapath/tenancy.py; rendered when the
    # datapath exposes tenant_stats()) — tenant-labeled families so each
    # policy world's generation, quota pressure and isolation meters are
    # scrapeable tenant-for-tenant
    "antrea_tpu_tenant_worlds": "gauge",
    "antrea_tpu_tenant_generation": "gauge",
    "antrea_tpu_tenant_degraded": "gauge",
    "antrea_tpu_tenant_flow_quota_slots": "gauge",
    "antrea_tpu_tenant_flow_occupied": "gauge",
    "antrea_tpu_tenant_rule_words": "gauge",
    "antrea_tpu_tenant_evictions_total": "counter",
    "antrea_tpu_tenant_quota_clamps_total": "counter",
    "antrea_tpu_tenant_rollbacks_total": "counter",
    "antrea_tpu_tenant_topology_generation": "gauge",
    "antrea_tpu_tenant_latched": "gauge",
    "antrea_tpu_tenant_reshard_rows_total": "counter",
    "antrea_tpu_tenant_reshard_vetoes_total": "counter",
    # serving batcher (serving/batcher.py; rendered when the datapath
    # exposes serving_stats()) — admission/shed/flush meters for the
    # canonical-shape batching plane plus the {tenant}-labeled staging-
    # wait histogram (tick units; its p99 is the flush_deadline lever)
    "antrea_tpu_serving_submitted_lanes_total": "counter",
    "antrea_tpu_serving_shed_lanes_total": "counter",
    "antrea_tpu_serving_flushed_lanes_total": "counter",
    "antrea_tpu_serving_padded_lanes_total": "counter",
    "antrea_tpu_serving_dispatches_total": "counter",
    "antrea_tpu_serving_flushes_total": "counter",
    "antrea_tpu_serving_deadline_exceeded_total": "counter",
    "antrea_tpu_serving_results_dropped_total": "counter",
    "antrea_tpu_serving_staged_lanes": "gauge",
    "antrea_tpu_serving_wait_ticks": "histogram",
    # hot-path telemetry plane (observability/telemetry.py; rendered when
    # the datapath exposes telemetry_stats()) — one counter family per
    # TELEMETRY_COUNTERS name (family names resolve via
    # _TELEMETRY_FAMILIES below; the telemetry-registry analysis pass
    # pins that map against TELEMETRY_COUNTERS and this registry), the
    # regime-labeled step-latency histogram, and the sentinel's verdict
    # meter
    "antrea_tpu_telemetry_probe_hit_total": "counter",
    "antrea_tpu_telemetry_probe_stale_total": "counter",
    "antrea_tpu_telemetry_probe_miss_total": "counter",
    "antrea_tpu_telemetry_chance_bumps_total": "counter",
    "antrea_tpu_telemetry_dma_hb_total": "counter",
    "antrea_tpu_telemetry_regime_step_seconds": "histogram",
    "antrea_tpu_telemetry_perf_regressions_total": "counter",
}

# TELEMETRY_COUNTERS name -> its registered family.  An explicit literal
# map (not an f-string build) so every family name in this module is a
# greppable registered literal; the telemetry-registry analysis pass
# fails the build if the keys drift from TELEMETRY_COUNTERS or a value
# is not in METRICS.
_TELEMETRY_FAMILIES = {
    "probe_hit": "antrea_tpu_telemetry_probe_hit_total",
    "probe_stale": "antrea_tpu_telemetry_probe_stale_total",
    "probe_miss": "antrea_tpu_telemetry_probe_miss_total",
    "chance_bumps": "antrea_tpu_telemetry_chance_bumps_total",
    "dma_hb": "antrea_tpu_telemetry_dma_hb_total",
}


def _esc(s: str) -> str:
    # Label-value escaping per the exposition format: backslash, quote,
    # AND newline (a raw newline inside a quoted value splits the sample
    # line and breaks every scraper; rule names are user-controlled YAML).
    return (s.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _labels(**kv) -> str:
    """Render a label set -> '{k="v",...}' (or '' when every value is
    empty/None).  The single label-formatting path for all render
    functions: insertion order is preserved, values are escaped."""
    items = [(k, v) for k, v in kv.items() if v is not None and v != ""]
    if not items:
        return ""
    return "{" + ",".join(f'{k}="{_esc(str(v))}"' for k, v in items) + "}"


def _type_line(name: str) -> str:
    return f"# TYPE {name} {METRICS[name]}"


def _num(v: float) -> str:
    """Prometheus float formatting: integral values render bare."""
    return str(int(v)) if float(v) == int(v) else repr(float(v))


class Histogram:
    """Dependency-free Prometheus histogram (fixed upper bounds).

    The exposition contract (prometheus.io/docs/instrumenting/exposition
    _formats): cumulative `_bucket{le=...}` series ending in le="+Inf"
    (== `_count`), plus `_sum`/`_count`.  Default bounds cover the
    latencies this build observes: sub-ms device steps up to multi-second
    dissemination convergence under backoff.
    """

    DEFAULT_BOUNDS = (
        0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
        1.0, 2.5, 5.0, 10.0,
    )

    def __init__(self, bounds=DEFAULT_BOUNDS):
        self.bounds: tuple[float, ...] = tuple(sorted(float(b) for b in bounds))
        if not self.bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self._counts = [0] * (len(self.bounds) + 1)  # +1: the +Inf bucket
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        v = float(value)
        self._counts[bisect.bisect_left(self.bounds, v)] += 1
        self.sum += v
        self.count += 1

    def add_counts(self, counts, value_sum: float = 0.0) -> None:
        """Merge DEVICE-side per-bucket counts (one int per bucket incl.
        +Inf, indexed exactly like observe's bisect_left — see
        models/pipeline._prune_bucket_counts) plus the observations'
        value sum.  Lets a jitted kernel bucket thousands of lanes on
        device and transfer one small vector instead of per-lane
        values."""
        counts = [int(c) for c in counts]
        if len(counts) != len(self._counts):
            raise ValueError(
                f"expected {len(self._counts)} bucket counts, "
                f"got {len(counts)}")
        for i, c in enumerate(counts):
            self._counts[i] += c
        self.count += sum(counts)
        self.sum += float(value_sum)

    def merge(self, other: "Histogram") -> "Histogram":
        """Fold another histogram's observations into this one (fleet
        aggregation: a cluster-wide p99 needs ONE bucket space).  Bounds
        must match — merging across bucket layouts would misbin."""
        if other.bounds != self.bounds:
            raise ValueError("cannot merge histograms with different bounds")
        for i, c in enumerate(other._counts):
            self._counts[i] += c
        self.sum += other.sum
        self.count += other.count
        return self

    def quantile(self, q: float) -> float:
        """Upper-bound quantile estimate from the bucket bounds (the
        Prometheus histogram_quantile shape): the smallest bound whose
        cumulative count reaches q*count.  Observations past the last
        finite bound report that bound (the estimate saturates, exactly
        like a scrape-side histogram_quantile would).  0.0 when empty."""
        if self.count == 0:
            return 0.0
        need = max(0.0, min(1.0, float(q))) * self.count
        acc = 0
        for bound, c in zip(self.bounds, self._counts):
            acc += c
            if acc >= need:
                return bound
        return self.bounds[-1]

    def bucket_counts(self) -> list[int]:
        """CUMULATIVE per-bound counts (le semantics), +Inf last."""
        out, acc = [], 0
        for c in self._counts:
            acc += c
            out.append(acc)
        return out

    def sample_lines(self, name: str, **labels) -> list[str]:
        """The family's sample lines for ONE label set (no TYPE line —
        several label sets may share a family; callers group them under a
        single _type_line)."""
        lines = []
        cum = self.bucket_counts()
        for bound, c in zip(self.bounds, cum):
            lines.append(
                f"{name}_bucket{_labels(**labels, le=_num(bound))} {c}"
            )
        lines.append(f'{name}_bucket{_labels(**labels, le="+Inf")} {self.count}')
        lines.append(f"{name}_sum{_labels(**labels)} {repr(self.sum)}")
        lines.append(f"{name}_count{_labels(**labels)} {self.count}")
        return lines


def _render_histograms(rows: list) -> list[str]:
    """[(family, labels dict, Histogram)] -> exposition lines, grouped so
    each family's TYPE line precedes all of its label sets exactly once."""
    by_family: dict[str, list] = {}
    for name, labels, hist in rows:
        by_family.setdefault(name, []).append((labels, hist))
    lines = []
    for name, series in by_family.items():
        lines.append(_type_line(name))
        for labels, hist in series:
            lines.extend(hist.sample_lines(name, **labels))
    return lines


def render_controller_metrics(controller, store=None) -> str:
    """Controller-side Prometheus text (ref pkg/controller/metrics/
    prometheus.go: antrea_controller_network_policy_processed etc. — here
    the live object gauges + the connected-agent gauge)."""
    counts = controller.object_counts()
    lines = [_type_line("antrea_tpu_controller_objects")]
    for key, kind in (
        ("networkPolicies", "network_policies"),
        ("addressGroups", "address_groups"),
        ("appliedToGroups", "applied_to_groups"),
    ):
        lines.append(
            f"antrea_tpu_controller_objects{_labels(kind=kind)} {counts[key]}"
        )
    if store is not None:
        lines += [
            _type_line("antrea_tpu_controller_connected_agents"),
            f"antrea_tpu_controller_connected_agents {store.n_watchers}",
        ]
    return "\n".join(lines) + "\n"


def render_dissemination_metrics(server=None, agents=()) -> str:
    """Dissemination-plane health in Prometheus text — the scrape surface
    for the failure model (README "Failure model"): per-watcher queue
    depth/overflow/needs-resync from the server's dissemination_stats(),
    per-agent reconnect/resync counters, the reconciler's
    sync_failures_total, and the agent-side latency histograms (sync
    duration + controller-commit->datapath-realized dissemination
    latency).

    `server` is a DisseminationServer (or None for agent-only scrapes);
    `agents` is any iterable of NetAgent and/or AgentPolicyController —
    duck-typed, so a NetAgent contributes wire counters AND its embedded
    controller's install-failure counter and histograms."""
    lines = []
    if server is not None:
        stats = server.dissemination_stats()
        watchers = sorted(stats["watchers"].items())
        lines.append(_type_line("antrea_tpu_dissemination_watcher_pending"))
        for node, w in watchers:
            lines.append(
                f"antrea_tpu_dissemination_watcher_pending{_labels(node=node)}"
                f" {w['pending']}"
            )
        lines.append(_type_line("antrea_tpu_dissemination_watcher_overflows_total"))
        for node, w in watchers:
            lines.append(
                f"antrea_tpu_dissemination_watcher_overflows_total"
                f"{_labels(node=node)} {w['overflows']}"
            )
        lines.append(_type_line("antrea_tpu_dissemination_watcher_needs_resync"))
        for node, w in watchers:
            lines.append(
                f"antrea_tpu_dissemination_watcher_needs_resync"
                f"{_labels(node=node)} {int(w['needs_resync'])}"
            )
        lines.append(
            _type_line("antrea_tpu_dissemination_queue_coalesced_total"))
        for node, w in watchers:
            lines.append(
                f"antrea_tpu_dissemination_queue_coalesced_total"
                f"{_labels(node=node)} {w.get('coalesced', 0)}"
            )
        lines += [
            _type_line("antrea_tpu_dissemination_resyncs_total"),
            f"antrea_tpu_dissemination_resyncs_total {stats['resyncs_total']}",
            _type_line("antrea_tpu_dissemination_reconnects_total"),
            f"antrea_tpu_dissemination_reconnects_total "
            f"{stats['reconnects_total']}",
            _type_line("antrea_tpu_dissemination_resync_chunks_total"),
            f"antrea_tpu_dissemination_resync_chunks_total "
            f"{stats.get('resync_chunks_total', 0)}",
            _type_line("antrea_tpu_dissemination_resyncs_inflight"),
            f"antrea_tpu_dissemination_resyncs_inflight "
            f"{stats.get('resyncs_inflight', 0)}",
        ]
    agents = list(agents)

    # A NetAgent embeds its AgentPolicyController as .agent; a bare
    # controller passed directly carries its fields itself.
    def ctl(a):
        return getattr(a, "agent", a)

    for name, read in (
        ("antrea_tpu_agent_reconnects_total",
         lambda a: getattr(a, "reconnects_total", None)),
        ("antrea_tpu_agent_resyncs_total",
         lambda a: getattr(a, "resyncs_total", None)),
        ("antrea_tpu_agent_sync_failures_total",
         lambda a: getattr(ctl(a), "sync_failures_total", None)),
        # Satellite meter: dissemination-latency stamps truncated at the
        # bounded _pending_ts cap — during exactly the install outages the
        # latency histogram exists to show, dropped stamps understate p99;
        # this counter keeps the understatement visible instead of silent.
        ("antrea_tpu_realization_stamps_dropped_total",
         lambda a: getattr(ctl(a), "realization_stamps_dropped_total", None)),
    ):
        rows = [(a.node, read(a)) for a in agents if read(a) is not None]
        if rows:
            lines.append(_type_line(name))
            for node, val in rows:
                lines.append(f"{name}{_labels(node=node)} {val}")
    hist_rows = []
    for fam, attr in (
        ("antrea_tpu_agent_sync_seconds", "sync_hist"),
        ("antrea_tpu_dissemination_latency_seconds", "dissemination_hist"),
    ):
        for a in agents:
            h = getattr(ctl(a), attr, None)
            if h is not None and h.count:
                hist_rows.append((fam, {"node": a.node}, h))
    lines.extend(_render_histograms(hist_rows))
    return "\n".join(lines) + "\n"


def render_metrics(datapath, node: str = "") -> str:
    """One Prometheus-text snapshot of a Datapath's observable state."""
    stats = datapath.stats()
    lines = [_type_line("antrea_tpu_rule_packets_total")]
    for direction, table in (("ingress", stats.ingress), ("egress", stats.egress)):
        for rule, count in sorted(table.items()):
            lines.append(
                f"antrea_tpu_rule_packets_total"
                f"{_labels(direction=direction, rule=rule, node=node)} {count}"
            )
    by_bytes = (("ingress", stats.ingress_bytes or {}),
                ("egress", stats.egress_bytes or {}))
    if any(t for _d, t in by_bytes):
        lines.append(_type_line("antrea_tpu_rule_bytes_total"))
        for direction, table in by_bytes:
            for rule, count in sorted(table.items()):
                lines.append(
                    f"antrea_tpu_rule_bytes_total"
                    f"{_labels(direction=direction, rule=rule, node=node)} "
                    f"{count}"
                )
    lines += [
        _type_line("antrea_tpu_default_verdict_packets_total"),
        f"antrea_tpu_default_verdict_packets_total"
        f"{_labels(verdict='allow', node=node)} {stats.default_allow}",
        f"antrea_tpu_default_verdict_packets_total"
        f"{_labels(verdict='deny', node=node)} {stats.default_deny}",
    ]
    cs = getattr(datapath, "cache_stats", None)
    if cs is not None:
        c = cs()
        lines.append(_type_line("antrea_tpu_flow_cache_entries"))
        for kind in ("occupied", "committed", "denials"):
            lines.append(
                f"antrea_tpu_flow_cache_entries"
                f"{_labels(kind=kind, node=node)} {c[kind]}"
            )
        lines += [
            _type_line("antrea_tpu_flow_cache_slots"),
            f"antrea_tpu_flow_cache_slots{_labels(node=node)} {c['slots']}",
            _type_line("antrea_tpu_flow_cache_evictions_total"),
            f"antrea_tpu_flow_cache_evictions_total{_labels(node=node)} "
            f"{c['evictions']}",
            _type_line("antrea_tpu_flow_cache_reclaims_total"),
            f"antrea_tpu_flow_cache_reclaims_total{_labels(node=node)} "
            f"{c.get('reclaims', 0)}",
        ]
    sp = getattr(datapath, "slowpath_stats", None)
    sp = sp() if sp is not None else None
    if sp is not None:
        # Async slow-path plane (datapath/slowpath): queue depth/capacity/
        # pressure, drained volume, and the epoch-swap bookkeeping.
        for fam, key in (
            ("antrea_tpu_miss_queue_depth", "depth"),
            ("antrea_tpu_miss_queue_capacity", "capacity"),
            ("antrea_tpu_miss_queue_admitted_total", "admitted_total"),
            ("antrea_tpu_miss_queue_overflows_total", "overflows_total"),
            # admission="drop": depth-proportional early-shed admissions
            # (0 under the other policies — mode-stable scrape surface).
            ("antrea_tpu_miss_queue_early_drops_total", "early_drops_total"),
            # Per-source-/24 admission token buckets (miss_source_rate;
            # 0 when the limiter is off — mode-stable scrape surface).
            ("antrea_tpu_miss_queue_source_limited_total",
             "source_limited_total"),
            ("antrea_tpu_slowpath_drained_total", "drained_total"),
            ("antrea_tpu_slowpath_stale_reclassified_total",
             "stale_reclassified_total"),
            ("antrea_tpu_flow_cache_epoch", "epoch"),
            ("antrea_tpu_flow_cache_epoch_age_seconds", "epoch_age_s"),
            # Overlapped drain-commit plane (two-slot staging) + the
            # autotuner's current chunk rung (== drain_batch when the
            # controller is off).
            ("antrea_tpu_slowpath_overlap_depth", "overlap_depth"),
            ("antrea_tpu_slowpath_deferred_commits_total",
             "deferred_commits_total"),
            ("antrea_tpu_slowpath_deferred_commit_staleness_seconds",
             "deferred_staleness_s"),
            ("antrea_tpu_slowpath_drain_chunk", "drain_batch"),
        ):
            lines += [_type_line(fam), f"{fam}{_labels(node=node)} {sp[key]}"]
        lines.append(_type_line("antrea_tpu_slowpath_autotune_decisions_total"))
        for direction, key in (("up", "autotune_decisions_up"),
                               ("down", "autotune_decisions_down")):
            lines.append(
                f"antrea_tpu_slowpath_autotune_decisions_total"
                f"{_labels(direction=direction, node=node)} "
                f"{sp.get(key, 0)}"
            )
        dh = sp.get("drain_hist")
        if dh is not None and dh.count:
            lines.extend(_render_histograms(
                [("antrea_tpu_slowpath_drain_batch_size", {"node": node}, dh)]
            ))
    cp = getattr(datapath, "commit_stats", None)
    cp = cp() if cp is not None else None
    if cp is not None:
        # Bundle commit plane (datapath/commit.py): per-stage outcomes,
        # rollback/canary counters, degraded flag, LKG retention.
        if cp["commits"]:
            lines.append(_type_line("antrea_tpu_bundle_commits_total"))
            for key, n in sorted(cp["commits"].items()):
                stage, outcome = key.split("/", 1)
                lines.append(
                    f"antrea_tpu_bundle_commits_total"
                    f"{_labels(stage=stage, outcome=outcome, node=node)} {n}"
                )
        for fam, key in (
            ("antrea_tpu_bundle_rollbacks_total", "rollbacks_total"),
            ("antrea_tpu_canary_probes_total", "canary_probes_total"),
            ("antrea_tpu_canary_mismatches_total", "canary_mismatches_total"),
            ("antrea_tpu_datapath_degraded", "degraded"),
            ("antrea_tpu_bundle_lkg_generation", "lkg_generation"),
        ):
            lines += [_type_line(fam), f"{fam}{_labels(node=node)} {cp[key]}"]
        lines += [
            _type_line("antrea_tpu_bundle_lkg_age_seconds"),
            f"antrea_tpu_bundle_lkg_age_seconds{_labels(node=node)} "
            f"{_num(cp['lkg_age_s'])}",
        ]
    au = getattr(datapath, "audit_stats", None)
    au = au() if au is not None else None
    if au is not None:
        # Continuous flow-cache revalidator (datapath/audit.py): scan/
        # sweep coverage, per-kind divergences, scrub outcomes, repairs.
        for fam, key in (
            ("antrea_tpu_cache_audit_scans_total", "scans_total"),
            ("antrea_tpu_cache_audit_entries_total", "entries_total"),
            ("antrea_tpu_cache_audit_repairs_total", "repairs_total"),
        ):
            lines += [_type_line(fam), f"{fam}{_labels(node=node)} {au[key]}"]
        if au["divergences"]:
            lines.append(_type_line("antrea_tpu_cache_audit_divergences_total"))
            for kind, n in sorted(au["divergences"].items()):
                lines.append(
                    f"antrea_tpu_cache_audit_divergences_total"
                    f"{_labels(kind=kind, node=node)} {n}"
                )
        if au["scrub"]:
            lines.append(_type_line("antrea_tpu_tensor_scrub_total"))
            for outcome, n in sorted(au["scrub"].items()):
                lines.append(
                    f"antrea_tpu_tensor_scrub_total"
                    f"{_labels(outcome=outcome, node=node)} {n}"
                )
        lines += [
            _type_line("antrea_tpu_audit_cursor_coverage_ratio"),
            f"antrea_tpu_audit_cursor_coverage_ratio{_labels(node=node)} "
            f"{_num(au['coverage_ratio'])}",
        ]
    mt = getattr(datapath, "maintenance_stats", None)
    mt = mt() if mt is not None else None
    if mt is not None:
        # Unified maintenance scheduler (datapath/maintenance.py): tick/
        # blocked-tick counters, per-task run/spent/deferral/shed
        # accounting, and the starvation lag gauge.
        for fam, key in (
            ("antrea_tpu_maintenance_ticks_total", "ticks_total"),
            ("antrea_tpu_maintenance_blocked_ticks_total",
             "blocked_ticks_total"),
            ("antrea_tpu_maintenance_scheduler_lag", "scheduler_lag"),
        ):
            lines += [_type_line(fam), f"{fam}{_labels(node=node)} {mt[key]}"]
        for fam, key in (
            ("antrea_tpu_maintenance_task_runs_total", "runs_total"),
            ("antrea_tpu_maintenance_budget_spent_total", "spent_total"),
            ("antrea_tpu_maintenance_deferrals_total", "deferrals_total"),
            ("antrea_tpu_maintenance_shed_total", "shed_total"),
        ):
            lines.append(_type_line(fam))
            for task, row in sorted(mt["tasks"].items()):
                lines.append(
                    f"{fam}{_labels(task=task, node=node)} {row[key]}"
                )
    rz = getattr(datapath, "realization_stats", None)
    rz = rz() if rz is not None else None
    if rz is not None:
        # Realization tracing plane (observability/tracing.py): span-table
        # occupancy by lifecycle state, drop meter, per-stage latency.
        lines.append(_type_line("antrea_tpu_realization_spans"))
        for state in ("pending", "awaiting_first_hit", "closed"):
            lines.append(
                f"antrea_tpu_realization_spans"
                f"{_labels(state=state, node=node)} {rz[state]}"
            )
        lines += [
            _type_line("antrea_tpu_realization_spans_dropped_total"),
            f"antrea_tpu_realization_spans_dropped_total{_labels(node=node)} "
            f"{rz['spans_dropped_total']}",
        ]
        tracer = getattr(datapath, "realization_tracer", None)
        if tracer is not None:
            rows = [("antrea_tpu_policy_realization_seconds",
                     {"stage": stage, "node": node}, h)
                    for stage, h in tracer.hist.items() if h.count]
            lines.extend(_render_histograms(rows))
    fr = getattr(datapath, "flightrecorder_stats", None)
    fr = fr() if fr is not None else None
    if fr is not None:
        # Flight recorder (observability/flightrec.py): per-kind volumes,
        # drop-oldest losses, and the monotonic sequence head.
        if fr["kinds"]:
            lines.append(_type_line("antrea_tpu_flightrecorder_events_total"))
            for kind, n in sorted(fr["kinds"].items()):
                lines.append(
                    f"antrea_tpu_flightrecorder_events_total"
                    f"{_labels(kind=kind, node=node)} {n}"
                )
        for fam, key in (
            ("antrea_tpu_flightrecorder_dropped_total", "dropped_total"),
            ("antrea_tpu_flightrecorder_seq", "seq"),
        ):
            lines += [_type_line(fam), f"{fam}{_labels(node=node)} {fr[key]}"]
    pr = getattr(datapath, "prune_stats", None)
    pr = pr() if pr is not None else None
    if pr is not None:
        # Aggregated-bitmap match pruning (ops/match round 7): aggregate
        # short circuits, full-width fallback redispatches, the current
        # K rung, retune volume, and the candidate-superblock spread.
        for fam, key in (
            ("antrea_tpu_match_prune_skips_total", "skips_total"),
            ("antrea_tpu_match_prune_fallbacks_total", "fallbacks_total"),
            ("antrea_tpu_match_prune_budget", "budget"),
            ("antrea_tpu_match_prune_retunes_total", "retunes_total"),
        ):
            lines += [_type_line(fam), f"{fam}{_labels(node=node)} {pr[key]}"]
        ph = pr.get("hist")
        if ph is not None and ph.count:
            lines.extend(_render_histograms(
                [("antrea_tpu_match_prune_candidate_superblocks",
                  {"node": node}, ph)]
            ))
    ms = getattr(datapath, "mesh_stats", None)
    ms = ms() if ms is not None else None
    if ms is not None:
        # Multichip datapath (parallel/meshpath.py): shard-labeled
        # per-replica families — queue pressure, canary outcomes and
        # striped-audit volume, replica-for-replica.
        lines.append(_type_line("antrea_tpu_replica_miss_queue_depth"))
        for r, depth in enumerate(ms["replica_miss_queue_depth"]):
            lines.append(
                f"antrea_tpu_replica_miss_queue_depth"
                f"{_labels(replica=r, node=node)} {depth}"
            )
        lines.append(
            _type_line("antrea_tpu_replica_canary_mismatches_total"))
        for r in range(len(ms["replica_miss_queue_depth"])):
            lines.append(
                f"antrea_tpu_replica_canary_mismatches_total"
                f"{_labels(replica=r, node=node)} "
                f"{ms['replica_canary_mismatches'].get(r, 0)}"
            )
        lines.append(_type_line("antrea_tpu_replica_audit_entries_total"))
        for r, n in enumerate(ms["replica_audit_entries"]):
            lines.append(
                f"antrea_tpu_replica_audit_entries_total"
                f"{_labels(replica=r, node=node)} {n}"
            )
    rs = getattr(datapath, "reshard_stats", None)
    rs = rs() if rs is not None else None
    if rs is not None:
        # Elastic mesh resharding (parallel/reshard.py): the live
        # affinity-topology generation, migration progress/volume, and
        # the plane's cutover/abort history — schema-stable whether or
        # not a resize is in flight.
        for fam, key in (
            ("antrea_tpu_reshard_topology_generation",
             "topology_generation"),
            ("antrea_tpu_reshard_active", "active"),
            ("antrea_tpu_reshard_progress_ratio", "progress_ratio"),
            ("antrea_tpu_reshard_migrated_rows_total",
             "migrated_rows_total"),
            ("antrea_tpu_reshard_resident_rows", "resident_rows"),
            ("antrea_tpu_reshard_cutovers_total", "cutovers_total"),
            ("antrea_tpu_reshard_aborts_total", "aborts_total"),
            ("antrea_tpu_reshard_catchup_rows_total", "catchup_rows_total"),
            ("antrea_tpu_reshard_tenant_rows_total", "tenant_rows_total"),
            ("antrea_tpu_reshard_tenant_vetoes_total",
             "tenant_vetoes_total"),
        ):
            lines += [_type_line(fam),
                      f"{fam}{_labels(node=node)} {_num(rs[key])}"]
    fs = getattr(datapath, "failover_stats", None)
    fs = fs() if fs is not None else None
    if fs is not None and fs.get("enabled"):
        # Replica-loss failover plane (parallel/failover.py): the
        # quarantined gauge scrapes shard-for-shard over the boot grid
        # (1 = masked/evacuated, awaiting readmission), beside the
        # plane's cumulative probe and lifecycle totals.
        lines.append(_type_line("antrea_tpu_failover_quarantined"))
        for r in range(fs.get("n_shards", 0)):
            q = int(fs.get("quarantined_shard") == r)
            lines.append(f"antrea_tpu_failover_quarantined"
                         f"{_labels(shard=r, node=node)} {q}")
        for fam, key in (
            ("antrea_tpu_failover_probes_total", "probes_total"),
            ("antrea_tpu_failover_probe_failures_total",
             "probe_failures_total"),
            ("antrea_tpu_failover_slow_dispatches_total",
             "slow_dispatches_total"),
            ("antrea_tpu_failover_quarantines_total", "quarantines_total"),
            ("antrea_tpu_failover_evacuations_total", "evacuations_total"),
            ("antrea_tpu_failover_readmissions_total",
             "readmissions_total"),
            ("antrea_tpu_failover_remiss_total", "remiss_total"),
        ):
            lines += [_type_line(fam),
                      f"{fam}{_labels(node=node)} {_num(fs[key])}"]
    ts = getattr(datapath, "tenant_stats", None)
    ts = ts() if ts is not None else None
    if ts:
        # Multi-tenant serving plane (datapath/tenancy.py): per-world
        # generation/degrade state, quota pressure and the isolation
        # meters, labeled {tenant} so fleet scrapes aggregate per world.
        lines += [_type_line("antrea_tpu_tenant_worlds"),
                  f"antrea_tpu_tenant_worlds{_labels(node=node)} {len(ts)}"]
        per = (
            ("antrea_tpu_tenant_generation", "generation"),
            ("antrea_tpu_tenant_degraded", "degraded"),
            ("antrea_tpu_tenant_flow_quota_slots", "quota_slots"),
            ("antrea_tpu_tenant_flow_occupied", "occupied"),
            ("antrea_tpu_tenant_rule_words", "rule_words"),
            ("antrea_tpu_tenant_evictions_total", "evictions_total"),
            ("antrea_tpu_tenant_quota_clamps_total", "quota_clamps_total"),
            ("antrea_tpu_tenant_rollbacks_total", "rollbacks_total"),
            ("antrea_tpu_tenant_topology_generation",
             "topology_generation"),
            ("antrea_tpu_tenant_latched", "latched"),
            ("antrea_tpu_tenant_reshard_rows_total", "reshard_rows_total"),
            ("antrea_tpu_tenant_reshard_vetoes_total",
             "reshard_vetoes_total"),
        )
        for fam, key in per:
            lines.append(_type_line(fam))
            for tid, row in ts.items():
                lines.append(
                    f"{fam}{_labels(tenant=tid, node=node)} {_num(row[key])}")
    sv = getattr(datapath, "serving_stats", None)
    sv = sv() if sv is not None else None
    if sv is not None:
        # Serving batcher (serving/batcher.py): admission / shed / flush
        # meters, the staged-lane gauge, and the {tenant}-labeled
        # staging-wait histogram (tick units).
        for fam, key in (
            ("antrea_tpu_serving_submitted_lanes_total", "submitted_lanes"),
            ("antrea_tpu_serving_shed_lanes_total", "shed_lanes"),
            ("antrea_tpu_serving_flushed_lanes_total", "flushed_lanes"),
            ("antrea_tpu_serving_padded_lanes_total", "padded_lanes"),
            ("antrea_tpu_serving_dispatches_total", "dispatches"),
            ("antrea_tpu_serving_deadline_exceeded_total",
             "deadline_exceeded"),
            ("antrea_tpu_serving_results_dropped_total", "results_dropped"),
            ("antrea_tpu_serving_staged_lanes", "staged_lanes"),
        ):
            lines += [_type_line(fam),
                      f"{fam}{_labels(node=node)} {_num(sv[key])}"]
        fam = "antrea_tpu_serving_flushes_total"
        lines.append(_type_line(fam))
        for reason, v in sorted(sv["flushes"].items()):
            lines.append(
                f"{fam}{_labels(reason=reason, node=node)} {_num(v)}")
        plane = getattr(datapath, "serving_plane", None)
        rows = plane.hist_rows(node) if plane is not None else []
        if rows:
            lines.extend(_render_histograms(rows))
    tel = getattr(datapath, "telemetry_stats", None)
    tel = tel() if tel is not None else None
    if tel is not None:
        # Hot-path telemetry plane (observability/telemetry.py): the
        # in-kernel counter totals (one family per TELEMETRY_COUNTERS
        # name), the sentinel's verdict meter, and the {scope, regime}-
        # labeled step-latency histograms.
        for name, v in tel["counters"].items():
            fam = _TELEMETRY_FAMILIES[name]
            lines += [_type_line(fam),
                      f"{fam}{_labels(node=node)} {v}"]
        fam = "antrea_tpu_telemetry_perf_regressions_total"
        lines += [_type_line(fam),
                  f"{fam}{_labels(node=node)} {tel['regressions_total']}"]
        plane = getattr(datapath, "telemetry_plane", None)
        rows = plane.hist_rows(node) if plane is not None else []
        if rows:
            lines.extend(_render_histograms(rows))
    sh = getattr(datapath, "step_hist", None)
    if sh is not None and sh.count:
        lines.extend(_render_histograms(
            [("antrea_tpu_datapath_step_seconds", {"node": node}, sh)]
        ))
    return "\n".join(lines) + "\n"
