"""Pull-style metrics surface (Prometheus text exposition format).

The analog of the reference's agent metrics
(/root/reference/pkg/agent/metrics/prometheus.go:33-188: rule counts,
per-table flow counts, conntrack totals) rendered from this build's
observable state: DatapathStats (per-rule packet counters), the flow-cache
census (models/pipeline.cache_stats) and the cumulative eviction counter —
the weak-#5 measurement surface.  render_metrics() is the scrape function;
the simulator (or any collector) consumes the text directly.
"""

from __future__ import annotations


def _esc(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"')


def render_controller_metrics(controller, store=None) -> str:
    """Controller-side Prometheus text (ref pkg/controller/metrics/
    prometheus.go: antrea_controller_network_policy_processed etc. — here
    the live object gauges + the connected-agent gauge)."""
    counts = controller.object_counts()
    lines = ["# TYPE antrea_tpu_controller_objects gauge"]
    for key, kind in (
        ("networkPolicies", "network_policies"),
        ("addressGroups", "address_groups"),
        ("appliedToGroups", "applied_to_groups"),
    ):
        lines.append(
            f'antrea_tpu_controller_objects{{kind="{kind}"}} {counts[key]}'
        )
    if store is not None:
        lines += [
            "# TYPE antrea_tpu_controller_connected_agents gauge",
            f"antrea_tpu_controller_connected_agents {store.n_watchers}",
        ]
    return "\n".join(lines) + "\n"


def render_dissemination_metrics(server=None, agents=()) -> str:
    """Dissemination-plane health in Prometheus text — the scrape surface
    for the failure model (README "Failure model"): per-watcher queue
    depth/overflow/needs-resync from the server's dissemination_stats(),
    plus per-agent reconnect/resync counters and the reconciler's
    sync_failures_total.

    `server` is a DisseminationServer (or None for agent-only scrapes);
    `agents` is any iterable of NetAgent and/or AgentPolicyController —
    duck-typed, so a NetAgent contributes wire counters AND its embedded
    controller's install-failure counter."""
    lines = []
    if server is not None:
        stats = server.dissemination_stats()
        watchers = sorted(stats["watchers"].items())
        lines.append("# TYPE antrea_tpu_dissemination_watcher_pending gauge")
        for node, w in watchers:
            lines.append(
                f'antrea_tpu_dissemination_watcher_pending{{node="{_esc(node)}"}} '
                f'{w["pending"]}'
            )
        lines.append(
            "# TYPE antrea_tpu_dissemination_watcher_overflows_total counter")
        for node, w in watchers:
            lines.append(
                f'antrea_tpu_dissemination_watcher_overflows_total'
                f'{{node="{_esc(node)}"}} {w["overflows"]}'
            )
        lines.append(
            "# TYPE antrea_tpu_dissemination_watcher_needs_resync gauge")
        for node, w in watchers:
            lines.append(
                f'antrea_tpu_dissemination_watcher_needs_resync'
                f'{{node="{_esc(node)}"}} {int(w["needs_resync"])}'
            )
        lines += [
            "# TYPE antrea_tpu_dissemination_resyncs_total counter",
            f"antrea_tpu_dissemination_resyncs_total {stats['resyncs_total']}",
            "# TYPE antrea_tpu_dissemination_reconnects_total counter",
            f"antrea_tpu_dissemination_reconnects_total "
            f"{stats['reconnects_total']}",
        ]
    agents = list(agents)
    for metric, read in (
        ("antrea_tpu_agent_reconnects_total counter",
         lambda a: getattr(a, "reconnects_total", None)),
        ("antrea_tpu_agent_resyncs_total counter",
         lambda a: getattr(a, "resyncs_total", None)),
        # A NetAgent embeds its AgentPolicyController as .agent; a bare
        # controller passed directly carries the counter itself.
        ("antrea_tpu_agent_sync_failures_total counter",
         lambda a: getattr(getattr(a, "agent", a),
                           "sync_failures_total", None)),
    ):
        rows = [(a.node, read(a)) for a in agents if read(a) is not None]
        if rows:
            name = metric.split(" ")[0]
            lines.append(f"# TYPE {metric}")
            for node, val in rows:
                lines.append(f'{name}{{node="{_esc(node)}"}} {val}')
    return "\n".join(lines) + "\n"


def render_metrics(datapath, node: str = "") -> str:
    """One Prometheus-text snapshot of a Datapath's observable state."""
    stats = datapath.stats()
    lines = [
        "# TYPE antrea_tpu_rule_packets_total counter",
    ]
    label_node = f',node="{_esc(node)}"' if node else ""
    for direction, table in (("ingress", stats.ingress), ("egress", stats.egress)):
        for rule, count in sorted(table.items()):
            lines.append(
                f'antrea_tpu_rule_packets_total{{direction="{direction}",'
                f'rule="{_esc(rule)}"{label_node}}} {count}'
            )
    by_bytes = (("ingress", stats.ingress_bytes or {}),
                ("egress", stats.egress_bytes or {}))
    if any(t for _d, t in by_bytes):
        lines.append("# TYPE antrea_tpu_rule_bytes_total counter")
        for direction, table in by_bytes:
            for rule, count in sorted(table.items()):
                lines.append(
                    f'antrea_tpu_rule_bytes_total{{direction="{direction}",'
                    f'rule="{_esc(rule)}"{label_node}}} {count}'
                )
    lines += [
        "# TYPE antrea_tpu_default_verdict_packets_total counter",
        f'antrea_tpu_default_verdict_packets_total{{verdict="allow"{label_node}}} '
        f"{stats.default_allow}",
        f'antrea_tpu_default_verdict_packets_total{{verdict="deny"{label_node}}} '
        f"{stats.default_deny}",
    ]
    cs = getattr(datapath, "cache_stats", None)
    if cs is not None:
        c = cs()
        lines += [
            "# TYPE antrea_tpu_flow_cache_entries gauge",
            f'antrea_tpu_flow_cache_entries{{kind="occupied"{label_node}}} {c["occupied"]}',
            f'antrea_tpu_flow_cache_entries{{kind="committed"{label_node}}} {c["committed"]}',
            f'antrea_tpu_flow_cache_entries{{kind="denials"{label_node}}} {c["denials"]}',
            "# TYPE antrea_tpu_flow_cache_slots gauge",
            f"antrea_tpu_flow_cache_slots{{{label_node.lstrip(',')}}} {c['slots']}"
            if node else f"antrea_tpu_flow_cache_slots {c['slots']}",
            "# TYPE antrea_tpu_flow_cache_evictions_total counter",
            f'antrea_tpu_flow_cache_evictions_total{{{label_node.lstrip(",")}}} '
            f'{c["evictions"]}'
            if node else f"antrea_tpu_flow_cache_evictions_total {c['evictions']}",
        ]
    return "\n".join(lines) + "\n"
