"""Hot-path telemetry plane: in-kernel counters, regime-classified step
histograms, and the perf-regression sentinel.

The reference treats its datapath as a black box it can only poll from
outside (conntrack dumps via pkg/agent/flowexporter); this build OWNS the
datapath, so the kernel itself is instrumented: with
PipelineMeta.telemetry set, every step emits cheap counter outputs —
cache probe hit/stale/miss splits, DMA half-blocks issued by the
one-pass kernel, second-chance protection bumps — derived XLA-side from
values the step already gathers (models/pipeline.py tel_* keys), and
`telemetry=False` lowers to HLO bit-identical with the uninstrumented
step.  `TelemetryPlane` is the host-side accumulator both engines and
the mesh datapath mix in:

  * counters: one monotonic total per TELEMETRY_COUNTERS name, fed from
    the step's tel_* outputs (per-replica vectors sum — the counters are
    replica-additive by construction);
  * regime histograms: each batch classifies into ONE traffic regime
    from its own outputs (classify_regime below), and the step's wall
    seconds fold into a per-(scope, regime) Histogram — scope "engine"
    always, "replicaN" on the mesh, "tenant:X" where worlds exist — so
    production answers "what is my cold-regime p99 right now" without a
    bench run;
  * the sentinel: a budgeted maintenance sweep (MAINT_TASKS
    `telemetry-sentinel`, clocked by the scheduler tick so FaultClock
    drives it deterministically) compares each regime's current-window
    p99 against a rolling baseline and reports a regression when the
    window burns past ratio x baseline — journal-and-meter ONLY
    (flightrec kind `perf-regression`), never an automatic rollback.
    Regressed windows are quarantined from the baseline so a sustained
    slowdown keeps firing instead of normalizing itself away.

Failure model: everything here is bounded host-side state — histograms
are fixed buckets, pendings are cleared every step — and overflow
anywhere in the observability plane is drop-oldest (flightrec ring),
never backpressure on the hot step.

Surfaces: `GET /telemetry` (agent/apiserver.py), `antctl telemetry`,
`telemetry.json` in the support bundle, the telemetry metric families
(metrics.render_metrics — one counter family per name here, the regime
histogram, the regression meter), and bench.py's `steady_telemetry_pps`
overhead line.
"""

from __future__ import annotations

import numpy as np

from .metrics import Histogram

# The kernel counter schema: names of the tel_* outputs the instrumented
# step emits (models/pipeline.py).  Pure literal on purpose —
# analysis/telemetry.py parses this dependency-free and fails the build
# when the kernel outputs, the TelemetryPlane accumulators, the metric
# families or the README rows drift from it.
TELEMETRY_COUNTERS = (
    "probe_hit",
    "probe_stale",
    "probe_miss",
    "chance_bumps",
    "dma_hb",
)

# Traffic regimes a batch can classify into (classify_regime), in
# sentinel sweep order.  Pure literal for the same drift gate.
REGIMES = (
    "steady",
    "cold",
    "churn",
    "drain",
    "attack-shed",
)


def classify_regime(batch: int, n_miss: int, shed: int = 0) -> str:
    """One regime per batch, decided from the batch's OWN outputs — no
    history, so the kernel twin and the scalar oracle classify
    identically on the same step sequence.  Precedence:

      attack-shed  the slow-path engine shed traffic since the last
                   batch (early-drop, per-source bucket, or queue
                   overflow): the node is under admission pressure
      cold         >= half the batch missed the flow cache (boot,
                   post-epoch-swap, or a cache flush)
      churn        some lanes missed (new flows arriving under load)
      steady       every lane hit — the throughput regime the fused
                   default-flip decision needs numbers for

    The fifth regime, "drain", never classifies from a step: coalesced
    slow-path drains fold their own wall seconds in directly
    (TelemetryPlane.observe_scoped), since a drain is its own dispatch,
    not a property of a traffic batch."""
    if shed > 0:
        return "attack-shed"
    if n_miss <= 0:
        return "steady"
    if 2 * int(n_miss) >= int(batch):
        return "cold"
    return "churn"


class TelemetryPlane:
    """Host-side accumulator for the hot-path telemetry tentpole.

    Single-threaded like every plane that feeds it (the engines' control
    thread).  The per-step protocol is two calls: `note_regime` during
    `_step` for each scope the batch classifies under (the engine always,
    replicas/tenants when they exist), then `observe_step(dt)` from the
    step's timing bracket — the pending scopes fold the SAME wall
    seconds, then clear, so an exception between the two loses at most
    one observation and never corrupts state."""

    def __init__(self, min_samples: int = 16, ratio: float = 2.0):
        if min_samples <= 0:
            raise ValueError(
                f"telemetry min_samples must be > 0, got {min_samples}")
        if ratio <= 1.0:
            raise ValueError(
                f"sentinel ratio must exceed 1.0 (a threshold at or "
                f"below the baseline always fires), got {ratio}")
        self.min_samples = int(min_samples)
        self.ratio = float(ratio)
        self.counters: dict[str, int] = {n: 0 for n in TELEMETRY_COUNTERS}
        self.steps_total = 0
        self.regressions_total = 0
        self.sweeps_total = 0
        # (scope, regime) -> step-seconds Histogram; scopes appear on
        # first observation so a single-chip engine carries no replica
        # rows and a world-free engine no tenant rows.
        self._hists: dict[tuple[str, str], Histogram] = {}
        # Sentinel state, engine-scope only (one verdict per regime per
        # node): the current window and the rolling baseline it rolls
        # into once judged.
        self._wins: dict[str, Histogram] = {r: Histogram() for r in REGIMES}
        self._base: dict[str, Histogram] = {r: Histogram() for r in REGIMES}
        self._cursor = 0  # round-robin regime cursor for budgeted sweeps
        self._pending: list[tuple[str, str]] = []
        self._shed_seen = 0

    # -- feeding the plane ---------------------------------------------------

    def account(self, out: dict) -> None:
        """Fold one step's tel_* counter outputs.  Values may be scalars
        (single chip) or per-replica vectors (mesh dispatch) — the
        counters are additive across replicas, so everything sums."""
        for name in TELEMETRY_COUNTERS:
            v = out.get("tel_" + name)
            if v is not None:
                self.counters[name] += int(np.asarray(v).sum())

    def note_shed(self, shed_total: int) -> int:
        """Delta the slow-path engine's cumulative shed meters (early
        drops + source-limit + queue overflows) against the last batch's
        view -> sheds attributable to THIS batch (the attack-shed
        classification input)."""
        d = int(shed_total) - self._shed_seen
        self._shed_seen = int(shed_total)
        return max(0, d)

    def note_regime(self, scope: str, regime: str) -> None:
        """Queue one (scope, regime) classification for the step's
        timing bracket to fold (observe_step)."""
        if regime not in self._wins:
            raise ValueError(f"unknown telemetry regime {regime!r}")
        self._pending.append((scope, regime))

    def observe_step(self, dt: float) -> None:
        """Fold the step's wall seconds into every pending (scope,
        regime) histogram; engine-scope observations additionally feed
        the sentinel's current window."""
        pending, self._pending = self._pending, []
        if not pending:
            return
        self.steps_total += 1
        for scope, regime in pending:
            self._hist(scope, regime).observe(dt)
            if scope == "engine":
                self._wins[regime].observe(dt)

    def observe_scoped(self, scope: str, regime: str, dt: float) -> None:
        """Immediate-mode fold for dispatches that own their timing —
        coalesced slow-path drains fold their wall seconds into the
        "drain" regime here, outside any step bracket."""
        if regime not in self._wins:
            raise ValueError(f"unknown telemetry regime {regime!r}")
        self._hist(scope, regime).observe(dt)
        if scope == "engine":
            self._wins[regime].observe(dt)

    def _hist(self, scope: str, regime: str) -> Histogram:
        h = self._hists.get((scope, regime))
        if h is None:
            h = self._hists[(scope, regime)] = Histogram()
        return h

    # -- the sentinel --------------------------------------------------------

    def sentinel_sweep(self, budget: int) -> tuple[int, list[dict]]:
        """One budgeted sweep: judge up to `budget` regimes (round-robin
        cursor, so every regime is reached across ticks) -> (n_checked,
        regression events).  A regime is judged only once BOTH its
        current window and its baseline carry min_samples observations;
        a clean window rolls into the baseline (the rolling-baseline
        fold), a regressed window is quarantined — dropped, not merged —
        so a sustained slowdown keeps firing instead of normalizing
        itself into the baseline.  The caller journals the events
        (flightrec `perf-regression`); this plane never acts on them —
        journal-and-meter only, by design."""
        events: list[dict] = []
        checked = 0
        for _ in range(max(0, min(int(budget), len(REGIMES)))):
            regime = REGIMES[self._cursor % len(REGIMES)]
            self._cursor += 1
            checked += 1
            win = self._wins[regime]
            if win.count < self.min_samples:
                continue
            base = self._base[regime]
            regressed = False
            if base.count >= self.min_samples:
                p99 = win.quantile(0.99)
                bp99 = base.quantile(0.99)
                regressed = bp99 > 0 and p99 > self.ratio * bp99
                if regressed:
                    self.regressions_total += 1
                    events.append({
                        "regime": regime,
                        "p99": float(p99),
                        "baseline_p99": float(bp99),
                        "samples": int(win.count),
                        "ratio": self.ratio,
                    })
            if not regressed:
                base.merge(win)
            self._wins[regime] = Histogram()
        self.sweeps_total += 1
        return checked, events

    # -- reading the plane ---------------------------------------------------

    def stats(self) -> dict:
        """JSON-able snapshot: the counter totals, per-scope per-regime
        step latency summaries, and the sentinel's window/baseline
        state — the one payload GET /telemetry, antctl and the support
        bundle all serve."""
        regimes: dict[str, dict] = {}
        for (scope, regime), h in sorted(self._hists.items()):
            if not h.count:
                continue
            regimes.setdefault(scope, {})[regime] = {
                "count": int(h.count),
                "sum_seconds": float(h.sum),
                "p50_seconds": float(h.quantile(0.5)),
                "p99_seconds": float(h.quantile(0.99)),
            }
        return {
            "counters": {n: int(v) for n, v in self.counters.items()},
            "steps_total": int(self.steps_total),
            "regressions_total": int(self.regressions_total),
            "sweeps_total": int(self.sweeps_total),
            "regimes": regimes,
            "sentinel": {
                r: {
                    "window_samples": int(self._wins[r].count),
                    "baseline_samples": int(self._base[r].count),
                    "baseline_p99_seconds":
                        float(self._base[r].quantile(0.99)),
                }
                for r in REGIMES
            },
            "config": {
                "min_samples": self.min_samples,
                "ratio": self.ratio,
            },
        }

    def hist_rows(self, node: str) -> list[tuple[str, dict, Histogram]]:
        """(family, labels, Histogram) rows for metrics._render_histograms
        — one antrea_tpu_telemetry_regime_step_seconds series per live
        (scope, regime)."""
        return [
            ("antrea_tpu_telemetry_regime_step_seconds",
             {"scope": scope, "regime": regime, "node": node}, h)
            for (scope, regime), h in sorted(self._hists.items())
            if h.count
        ]
