"""Realization tracing: per-generation span timelines across the planes.

ROADMAP item 3 holds the control plane to "end-to-end realization p99
< 1s at 10k agents" — but the only realization signal used to be ONE
histogram (`antrea_tpu_dissemination_latency_seconds`) collapsing wire,
queue-wait, compile, canary, swap and settle into a single number.  This
module is the Dapper-shaped answer: one SPAN per policy realization,
keyed by a correlation id (policy uid x spec generation x bundle commit
seq), stamped at every stage boundary as the realization flows
controller -> wire -> agent queue -> commit plane -> live traffic:

  controller   WatchEvent.ts — the commit instant, stamped by
               RamStore.apply when the event enters the dissemination
               plane (the span's origin; unstamped events — resync
               replays — are EXCLUDED and metered, never guessed);
  wire         receipt at the agent's watch callback;
  queue_wait   receipt -> the commit transaction the event rode starts
               (dirty-flag latency + install backoff — retries extend
               it, which is the honest realization latency);
  compile      the engine built + swapped the candidate tensors;
  canary       fresh-probe certification against the scalar oracle;
  swap         acceptance of the certified candidate;
  settle       durability (snapshot rotation, LKG retention);
  first_hit    the first LIVE packet batch classified under the new
               bundle generation — a cheap per-generation latch in the
               engines' step() metadata (host-side only: the compiled
               step HLO is bit-identical with tracing on or off).

Stamps are clamped monotonic at record time, so every stage duration is
>= 0 and the stage durations TELESCOPE — they sum exactly to the
end-to-end latency (first_hit - controller).  Both engines share this
tracer (the commit plane stamps are plane-level), so the span STRUCTURE
is oracle-parity by construction.

Surfaces: `antrea_tpu_policy_realization_seconds{stage}` histograms, a
bounded drop-oldest span table served at `GET /realization?uid=`
(agent/apiserver.py), `antctl realization --uid <policy>`,
`realization.json` in the support bundle, and a `realization` event in
the flight recorder per closed span.  Bookkeeping cost is budgeted by
the maintenance scheduler's `observability` task.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Optional

from .metrics import Histogram

# Stage DURATIONS of one realization span, in causal order; each is the
# gap to the previous stage's stamp (origin: the controller commit).
# tools/check_events.py asserts this tuple, the README span-stage table
# and the antrea_tpu_policy_realization_seconds registration agree.
REALIZATION_STAGES = (
    "wire", "queue_wait", "compile", "canary", "swap", "settle",
    "first_hit",
)

# Histogram label values: the stage durations plus the end-to-end total.
_HIST_STAGES = REALIZATION_STAGES + ("total",)

# Commit-plane stamp names in transaction order (tracked per commit, then
# grafted onto every span the commit realized).
_COMMIT_STAMPS = ("start", "compile", "canary", "swap", "settle")


class RealizationTracer:
    """Span table + stage histograms for ONE node's realization path.

    Owned by the datapath (both engines construct one; the agent
    controller, commit plane and step latch all stamp through it).
    Single-threaded like its callers.  All tables are bounded and
    drop-oldest; drops are metered, never silent.
    """

    def __init__(self, *, span_slots: int = 256, pending_slots: int = 1024,
                 clock=time.monotonic, recorder=None):
        if span_slots <= 0 or pending_slots <= 0:
            raise ValueError(
                f"realization tracer tables must be positive, got "
                f"span_slots={span_slots} pending_slots={pending_slots}")
        self.span_slots = int(span_slots)
        self.pending_slots = int(pending_slots)
        self._clock = clock
        self._recorder = recorder
        # (uid, gen) -> span dict; three lifecycle tables, all bounded:
        # pending (stamped controller/wire, awaiting a commit), awaiting
        # (bound to a commit, awaiting first live hit), closed (the span
        # table the API serves).  OrderedDict -> drop-OLDEST on overflow.
        self._pending: OrderedDict = OrderedDict()
        self._awaiting: OrderedDict = OrderedDict()
        self._closed: OrderedDict = OrderedDict()
        self.spans_dropped_total = 0
        self.spans_closed_total = 0
        # Events that arrived without a controller stamp (resync replays):
        # excluded from the histograms, metered not guessed.
        self.unstamped_total = 0
        # The in-flight and last-completed commit transactions.
        self._open_commit: Optional[dict] = None
        self._last_commit: Optional[tuple[int, dict]] = None  # (gen, stamps)
        # First-hit latch: highest bundle generation live traffic has
        # stepped under, and when.  One int compare on the hot step.
        self._hit_gen = -1
        self._hit_at = 0.0
        # Stamp-op counter: the maintenance `observability` task reads
        # the delta as this plane's accounted cost.
        self._stamps_total = 0
        self._stamps_taken = 0
        self.hist = {s: Histogram() for s in _HIST_STAGES}
        # The most recent elastic-mesh resize span (parallel/reshard.py):
        # migrate/certify/cutover stage durations telescoping to total,
        # the realization-span shape.  One slot — resizes are rare
        # operator/autoscaler events, not a table workload.
        self.last_resize = None

    def now(self) -> float:
        return float(self._clock())

    # -- agent-side stamps ---------------------------------------------------

    def note_unstamped(self) -> None:
        """An event with no controller stamp (ts=0: resync replay /
        filestore reload) left pending work: its realization latency is
        unknowable, so it is counted out of the histograms, not guessed
        into them."""
        self.unstamped_total += 1

    def policy_event(self, uid: str, gen: int, ts: float) -> None:
        """A stamped NetworkPolicy watch event arrived at the agent:
        open (or extend) the span for (uid, spec generation).  The
        EARLIEST controller stamp wins — re-deliveries and retries must
        lengthen the span, never shorten it."""
        self._stamps_total += 1
        key = (uid, int(gen))
        t_wire = max(float(ts), self.now())
        sp = self._pending.get(key)
        if sp is None:
            old = self._awaiting.get(key)
            if old is not None:
                if float(ts) <= old["commit"]["settle"]:
                    return  # re-delivery of the realization in flight;
                    # it adds nothing
                # Stamp POSTDATES the commit that realized the old
                # lifetime: uid reuse (delete/re-add) while the old span
                # still awaits its first hit.  Retire it metered — its
                # first-hit attribution would belong to the new lifetime.
                del self._awaiting[key]
                self.spans_dropped_total += 1
            old = self._closed.get(key)
            if old is not None:
                if float(ts) <= old["closed_at"]:
                    return  # re-delivery of a realization already closed
                # Controller stamp POSTDATES the close: the controller
                # restarts spec generations at 1 after a delete/re-add,
                # so this is a NEW lifetime of the uid reusing the key.
                # Retire the old span and trace the new realization.
                del self._closed[key]
            sp = {"uid": uid, "generation": int(gen),
                  "controller_ts": float(ts), "wire_ts": t_wire}
            self._pending[key] = sp
            while len(self._pending) > self.pending_slots:
                self._pending.popitem(last=False)
                self.spans_dropped_total += 1
        else:
            sp["controller_ts"] = min(sp["controller_ts"], float(ts))

    def realized(self) -> None:
        """The agent's sync() successfully applied state: every pending
        span rode the datapath's most recent commit transaction — bind
        them to its stage stamps and start waiting for the first live
        hit on that bundle generation."""
        if not self._pending:
            return
        self._stamps_total += 1
        if self._last_commit is None:
            # No commit recorded (tracer attached mid-flight): the spans
            # cannot be stage-attributed honestly; meter them out.
            self.spans_dropped_total += len(self._pending)
            self._pending.clear()
            return
        gen, stamps = self._last_commit
        for key, sp in self._pending.items():
            sp["bundle_generation"] = int(gen)
            sp["commit"] = dict(stamps)
            self._awaiting[key] = sp
            while len(self._awaiting) > self.pending_slots:
                self._awaiting.popitem(last=False)
                self.spans_dropped_total += 1
        self._pending.clear()
        if self._hit_gen >= gen:
            # Live traffic already stepped under this (or a newer)
            # bundle: the realization is visible now.
            self._close_up_to(self._hit_gen, self._hit_at)

    # -- commit-plane stamps (datapath/commit.py) ----------------------------

    def commit_begin(self) -> None:
        """A commit transaction entered its compile stage.  queue_wait
        ends here for every span this commit realizes."""
        self._stamps_total += 1
        self._open_commit = {"start": self.now()}

    def commit_stage(self, stage: str) -> None:
        """Stamp a completed commit stage (compile/canary/swap/settle),
        clamped monotonic against the previous stamp."""
        if self._open_commit is None:
            return
        self._stamps_total += 1
        prev = max(self._open_commit.values())
        self._open_commit[stage] = max(self.now(), prev)

    def commit_done(self, gen: int) -> None:
        """The transaction settled at bundle generation `gen`: its stamps
        become the binding target for the next realized() batch."""
        oc = self._open_commit
        self._open_commit = None
        if oc is None:
            return
        self._stamps_total += 1
        # Backfill any stage a path legitimately skipped (a no-op delta
        # never swaps) so the telescoping invariant holds span-wide.
        t = oc["start"]
        for s in _COMMIT_STAMPS:
            t = oc[s] = max(oc.get(s, t), t)
        self._last_commit = (int(gen), oc)

    def commit_abort(self) -> None:
        """The transaction rolled back: nothing realized, drop the
        stamps (the retry's own transaction re-stamps from compile)."""
        self._open_commit = None

    # -- the first-hit latch (engines' step()) -------------------------------

    def first_hit(self, gen: int, batch_size: int = 0) -> None:
        """Hot-step latch: the caller is about to classify live traffic
        under bundle generation `gen`.  First call per generation stamps
        the latch and closes every span awaiting a generation <= gen;
        every later call is ONE int compare.  Pure host code — zero
        device ops, so step HLO is bit-identical with tracing disabled."""
        if gen <= self._hit_gen or batch_size <= 0:
            return
        self._stamps_total += 1
        t = self.now()
        self._hit_gen = int(gen)
        self._hit_at = t
        if self._awaiting:
            self._close_up_to(int(gen), t)

    def _close_up_to(self, gen: int, t_hit: float) -> None:
        done = [k for k, sp in self._awaiting.items()
                if sp["bundle_generation"] <= gen]
        for key in done:
            self._finish(self._awaiting.pop(key), t_hit)

    def _finish(self, sp: dict, t_hit: float) -> None:
        c = sp.pop("commit")
        # Telescoping stamp chain, clamped monotonic end to end: every
        # stage >= 0 and the stages sum EXACTLY to total.
        t0 = sp["controller_ts"]
        chain = [
            ("wire", max(sp["wire_ts"], t0)),
            ("queue_wait", c["start"]),
            ("compile", c["compile"]),
            ("canary", c["canary"]),
            ("swap", c["swap"]),
            ("settle", c["settle"]),
            ("first_hit", t_hit),
        ]
        stages, prev = {}, t0
        for name, t in chain:
            t = max(t, prev)
            stages[name] = t - prev
            prev = t
        sp["stages_s"] = stages
        sp["total_s"] = prev - t0
        sp["closed_at"] = prev
        for name, dt in stages.items():
            self.hist[name].observe(dt)
        self.hist["total"].observe(sp["total_s"])
        self.spans_closed_total += 1
        key = (sp["uid"], sp["generation"])
        self._closed[key] = sp
        self._closed.move_to_end(key)
        while len(self._closed) > self.span_slots:
            self._closed.popitem(last=False)  # drop-oldest CLOSED span:
            # served telemetry aging out of the bounded table, not loss
        if self._recorder is not None:
            self._recorder.emit(
                kind="realization", uid=sp["uid"], gen=sp["generation"],
                bundle_gen=sp["bundle_generation"],
                total_s=round(sp["total_s"], 6))

    # -- elastic-mesh resize spans (parallel/reshard.py) ---------------------

    def note_resize_span(self, span: dict) -> None:
        """Record a completed data-axis resize span so resize latency is
        measurable beside policy-realization latency (served in stats()
        as `last_resize`; the flight recorder's reshard-cutover event
        carries the same total on the journal clock)."""
        self._stamps_total += 1
        self.last_resize = dict(span)

    # -- maintenance accounting ----------------------------------------------

    def take_cost(self) -> int:
        """Stamp ops since the last take — the accounted cost the
        maintenance scheduler's `observability` task budgets."""
        d = self._stamps_total - self._stamps_taken
        self._stamps_taken = self._stamps_total
        return d

    # -- observability -------------------------------------------------------

    def spans(self, uid: Optional[str] = None) -> list[dict]:
        """Span-table rows, oldest first: closed spans plus the still
        in-flight ones (marked by state) so an operator mid-outage sees
        where a realization is STUCK, not just the ones that finished.

        Called from API handler threads while the engine thread stamps:
        a table iteration racing an insert/pop raises RuntimeError, so
        the read retries on a fresh view instead of locking the hot
        stamp path (best-effort empty after repeated conflicts)."""
        for _ in range(8):
            try:
                return self._spans_once(uid)
            except RuntimeError:
                continue
        return []

    def _spans_once(self, uid: Optional[str]) -> list[dict]:
        out = []
        for state, table in (("pending", self._pending),
                             ("awaiting_first_hit", self._awaiting),
                             ("closed", self._closed)):
            for sp in table.values():
                row = dict(sp)
                row.pop("commit", None)
                row["state"] = state
                out.append(row)
        if uid is not None:
            out = [r for r in out if r["uid"] == uid]
        return out

    def stats(self) -> dict:
        return {
            "stages": list(REALIZATION_STAGES),
            "pending": len(self._pending),
            "awaiting_first_hit": len(self._awaiting),
            "closed": len(self._closed),
            "span_slots": self.span_slots,
            "spans_closed_total": int(self.spans_closed_total),
            "spans_dropped_total": int(self.spans_dropped_total),
            "unstamped_total": int(self.unstamped_total),
            "first_hit_generation": int(self._hit_gen),
            "p99_s": (self.hist["total"].quantile(0.99)
                      if self.hist["total"].count else None),
            "last_resize": self.last_resize,
        }
