"""Support bundle: one-call diagnostic state collection.

The analog of the reference's support-bundle machinery
(/root/reference/pkg/support/dump.go:43,75 — collects logs, ovs dumps,
agent state into a tar the operator uploads;
pkg/agent/supportbundlecollection drives it).  Collected here: the
datapath's observable surfaces (stats, cache census, live flow dump,
policy/service snapshot when persisted, metrics text) written as one
tar.gz — the artifact antctl/supportbundle would fetch.
"""

from __future__ import annotations

import io
import json
import tarfile
import time


def collect_bundle(
    datapath,
    out_path: str,
    *,
    node: str = "",
    now: int = 0,
    persist_dir: str | None = None,
    audit_log_path: str | None = None,
) -> list[str]:
    """Write a support bundle tar.gz; returns the member names collected.
    Individual collectors failing never abort the bundle (dump.go keeps
    going and records what it could, ref basicDumper behavior)."""
    from .metrics import render_metrics

    members: dict[str, bytes] = {}

    def add(name: str, data) -> None:
        if isinstance(data, (dict, list)):
            data = json.dumps(data, indent=2, default=str).encode()
        elif isinstance(data, str):
            data = data.encode()
        members[name] = data

    dp_type = getattr(datapath, "datapath_type", None)
    add("meta.json", {
        "node": node,
        "collected_at_unix": time.time(),
        "datapath_type": dp_type.value if dp_type is not None else "unknown",
        "generation": getattr(datapath, "generation", None),
    })

    def _stats():
        s = datapath.stats()  # one consistent snapshot
        return {
            "ingress": s.ingress,
            "egress": s.egress,
            "default_allow": s.default_allow,
            "default_deny": s.default_deny,
        }

    def _maintenance():
        ms = getattr(datapath, "maintenance_stats", None)
        body = ms() if ms is not None else None
        if body is None:
            raise ValueError("datapath has no maintenance scheduler")
        return body

    def _failover():
        fs = getattr(datapath, "failover_stats", None)
        body = fs() if fs is not None else None
        if body is None:
            raise ValueError("datapath has no failover plane surface")
        return body

    def _flightrecorder():
        # The whole retained journal: a support bundle IS the post-mortem
        # artifact, so it carries every event the ring still holds.
        fr = getattr(datapath, "flightrecorder_stats", None)
        body = fr() if fr is not None else None
        if body is None:
            raise ValueError("datapath has no flight recorder")
        body["events"] = datapath.flightrecorder_events()
        return body

    def _telemetry():
        tl = getattr(datapath, "telemetry_stats", None)
        body = tl() if tl is not None else None
        if body is None:
            raise ValueError("datapath has no telemetry plane")
        return body

    def _realization():
        rz = getattr(datapath, "realization_stats", None)
        body = rz() if rz is not None else None
        if body is None:
            raise ValueError("datapath has no realization tracer")
        body["spans"] = datapath.realization_tracer.spans()
        return body

    for name, fn in (
        ("stats.json", _stats),
        ("cache_stats.json", datapath.cache_stats),
        ("flows.json", lambda: datapath.dump_flows(now)),
        ("maintenance.json", _maintenance),
        ("failover.json", _failover),
        ("flightrecorder.json", _flightrecorder),
        ("realization.json", _realization),
        ("telemetry.json", _telemetry),
        ("metrics.prom", lambda: render_metrics(datapath, node=node)),
    ):
        try:
            add(name, fn())
        except Exception as e:  # collector failure recorded, not fatal
            add(name + ".error", f"{type(e).__name__}: {e}")
    if persist_dir is not None:
        from ..datapath import persist as dpersist

        snap = dpersist.read_json(dpersist.snapshot_path(persist_dir))
        if snap is not None:
            add("datapath_snapshot.json", snap)
    if audit_log_path is not None:
        try:
            with open(audit_log_path, "rb") as f:
                members["audit.log"] = f.read()
        except OSError as e:
            add("audit.log.error", str(e))

    with tarfile.open(out_path, "w:gz") as tar:
        for name in sorted(members):
            data = members[name]
            info = tarfile.TarInfo(name=name)
            info.size = len(data)
            tar.addfile(info, io.BytesIO(data))
    return sorted(members)
