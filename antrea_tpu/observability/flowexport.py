"""Flow export + aggregation: conntrack-poll -> flow records -> biflows.

The analog of the reference's flow-visibility pipeline
(/root/reference/pkg/agent/flowexporter — conntrack polling into a
connection store, exported as IPFIX records — feeding
pkg/flowaggregator/flowaggregator.go:90-104, which correlates the two
directions and fans out to sinks).  The wire format here is JSON lines
(one record per flow event), the correlation semantics are the same:

  FlowExporter.poll(now)  diffs the datapath's dump_flows() against the
      connection store; NEW connections and active refreshes export
      records; connections gone past the idle timeout export a final
      record with reason=idle-end.
  FlowAggregator.ingest() merges forward and reply records of one
      connection into a single biflow keyed on the forward tuple.
"""

from __future__ import annotations

import json
from collections import deque
from dataclasses import dataclass
from typing import Callable, Optional


def _key(rec: dict) -> tuple:
    return (rec["src"], rec["dst"], rec["sport"], rec["dport"], rec["proto"],
            rec["reply"])


class DenyRing:
    """Bounded ring of deny events (policy-DROP verdicts and shed
    admissions) awaiting export — the denied-connection store of the
    reference exporter (pkg/agent/flowexporter/exporter.go polls a deny
    connection store alongside conntrack, so dropped traffic is visible
    in flow records, not only as counters).  Drop-OLDEST on overflow,
    never backpressure: losing the oldest unexported deny event is the
    observability failure mode; stalling the datapath step is not."""

    def __init__(self, capacity: int = 4096):
        self.capacity = int(capacity)
        self._buf: deque = deque(maxlen=self.capacity)
        self.recorded_total = 0
        self.dropped_total = 0  # overwritten-before-export (drop-oldest)

    def __len__(self) -> int:
        return len(self._buf)

    def record(self, rec: dict) -> None:
        if len(self._buf) == self.capacity:
            self.dropped_total += 1
        self._buf.append(rec)
        self.recorded_total += 1

    def drain(self) -> list[dict]:
        out = list(self._buf)
        self._buf.clear()
        return out


@dataclass
class _Conn:
    first_seen: int
    last_seen: int
    last_export: int
    # Last-known cumulative volumes, carried so the final idle-end record
    # reports them — the live dump no longer holds the entry by then.
    packets: int = 0
    bytes: int = 0


class FlowExporter:
    """Per-node exporter: one instance polls one datapath."""

    def __init__(
        self,
        datapath,
        node: str = "",
        active_timeout_s: int = 60,
        sink: Optional[Callable[[dict], None]] = None,
        path: Optional[str] = None,
        keep_records: Optional[bool] = None,
    ):
        self.datapath = datapath
        self.node = node
        self.active_timeout_s = active_timeout_s
        self._conns: dict[tuple, _Conn] = {}
        # The in-memory record log is a convenience for consumers with no
        # sink/path; with one configured it would grow without bound over
        # the process lifetime, so it defaults OFF then.
        self._keep = (sink is None and path is None) if keep_records is None \
            else keep_records
        self.records: list[dict] = []
        self._sink = sink
        self.path = path
        # path= is sugar for a JSONL log sink (one format, one place).
        self._path_sink = JsonlFileSink(path) if path is not None else None
        # Attaching an exporter turns the datapath's deny plane on (the
        # ring only costs anything once someone will drain it); datapaths
        # without one (test doubles) simply export no deny records.
        enable = getattr(datapath, "enable_deny_export", None)
        if enable is not None:
            enable()

    def _emit(self, rec: dict) -> None:
        if self._keep:
            self.records.append(rec)
        if self._sink is not None:
            self._sink(rec)
        if self._path_sink is not None:
            self._path_sink(rec)

    def poll(self, now: int) -> int:
        """One conntrack-poll cycle; returns records emitted."""
        emitted = 0
        seen: set = set()
        for rec in self.datapath.dump_flows(now):
            k = _key(rec)
            seen.add(k)
            st = self._conns.get(k)
            if st is None:
                st = self._conns[k] = _Conn(rec["last_seen"],
                                            rec["last_seen"], now)
                self._emit({**rec, "node": self.node, "event": "new",
                            "export_ts": now})
                emitted += 1
            else:
                st.last_seen = rec["last_seen"]
                if now - st.last_export >= self.active_timeout_s:
                    st.last_export = now
                    self._emit({**rec, "node": self.node, "event": "active",
                                "export_ts": now})
                    emitted += 1
            # Carry the cumulative volumes on EVERY poll, not only export
            # polls — the final idle-end record must report the last-known
            # counters, and by then the entry has left the live dump.
            # Max-fold: an evicted-and-recreated entry restarts its
            # cumulative counters (same reasoning as the aggregator's
            # fold), so pre-eviction volume is a floor, never regressed.
            st.packets = max(st.packets, rec.get("packets", 0))
            st.bytes = max(st.bytes, rec.get("bytes", 0))
        # Connections that left the live dump ended (idle timeout/evicted).
        for k in [k for k in self._conns if k not in seen]:
            st = self._conns.pop(k)
            src, dst, sport, dport, proto, reply = k
            self._emit({
                "src": src, "dst": dst, "sport": sport, "dport": dport,
                "proto": proto, "reply": reply, "node": self.node,
                "event": "end", "reason": "idle-end",
                "last_seen": st.last_seen,
                "packets": st.packets, "bytes": st.bytes,
                "export_ts": now,
            })
            emitted += 1
        # Deny plane: policy-DROP verdicts and shed admissions recorded by
        # the datapath since the last poll export as event="deny" records
        # (the reference's deny connection store export path).
        drain = getattr(self.datapath, "deny_drain", None)
        if drain is not None:
            for rec in drain():
                self._emit({**rec, "node": self.node, "event": "deny",
                            "export_ts": now})
                emitted += 1
        return emitted


class JsonlFileSink:
    """Log exporter analog (flowaggregator logger exporter): one JSON line
    per record appended to a file."""

    def __init__(self, path: str):
        self.path = path

    def __call__(self, rec: dict) -> None:
        with open(self.path, "a") as f:
            f.write(json.dumps(rec, separators=(",", ":")) + "\n")


class TableSink:
    """ClickHouse-exporter analog: records land as rows in an in-memory
    table with a fixed column set, queryable by equality filters (the
    export schema of pkg/flowaggregator/clickhouseclient)."""

    COLUMNS = (
        "src", "dst", "sport", "dport", "proto", "node", "event",
        "reason", "reply", "export_ts",
    )

    def __init__(self):
        self.rows: list[tuple] = []

    def __call__(self, rec: dict) -> None:
        self.rows.append(tuple(rec.get(c) for c in self.COLUMNS))

    def query(self, **eq) -> list[tuple]:
        idx = {c: i for i, c in enumerate(self.COLUMNS)}
        return [
            r for r in self.rows
            if all(r[idx[k]] == v for k, v in eq.items())
        ]


class BatchDirSink:
    """S3-uploader analog (pkg/flowaggregator s3uploader): records buffer
    into objects of `batch_size` and each full batch is written as one
    object file in the target directory; flush() uploads a partial tail."""

    def __init__(self, directory: str, batch_size: int = 100):
        import os
        import re

        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self.batch_size = batch_size
        self._buf: list[dict] = []
        # Resume past existing objects — restarting over a populated
        # directory must append, never overwrite exported batches.
        taken = [
            int(m.group(1))
            for f in os.listdir(directory)
            if (m := re.fullmatch(r"records-(\d{6})\.jsonl", f))
        ]
        self._n_objects = max(taken) + 1 if taken else 0

    def __call__(self, rec: dict) -> None:
        self._buf.append(rec)
        if len(self._buf) >= self.batch_size:
            self.flush()

    def flush(self) -> Optional[str]:
        import os

        if not self._buf:
            return None
        path = os.path.join(self.dir, f"records-{self._n_objects:06d}.jsonl")
        with open(path, "w") as f:
            for rec in self._buf:
                f.write(json.dumps(rec, separators=(",", ":")) + "\n")
        self._n_objects += 1
        self._buf = []
        return path


def fanout(*sinks) -> Callable[[dict], None]:
    """Compose sinks into one FlowExporter/aggregator callback — the
    aggregator's multi-exporter fan-out
    (pkg/flowaggregator/flowaggregator.go:90-104 wiring IPFIX + ClickHouse
    + S3 + log exporters side by side)."""

    def emit(rec: dict) -> None:
        for s in sinks:
            s(rec)

    return emit


class FlowAggregator:
    """Correlates the two directions of a connection into one biflow (the
    flowaggregator correlation step): reply records fold into the forward
    record keyed on the forward tuple."""

    def __init__(self):
        self.biflows: dict[tuple, dict] = {}
        # reply tuple -> forward biflow key, so reply 'end' records (which
        # carry no un-DNAT fields) can still find their biflow.
        self._fwd_of_reply: dict[tuple, tuple] = {}

    def ingest(self, rec: dict) -> None:
        if rec.get("event") == "end":
            # Expire the correlated biflow (the reference aggregator
            # expires records too — without this the table grows with
            # cumulative connection count forever).
            rkey = (rec["src"], rec["dst"], rec["sport"], rec["dport"],
                    rec["proto"])
            if rec["reply"]:
                fkey = self._fwd_of_reply.pop(rkey, None)
                if fkey is not None:
                    self.biflows.pop(fkey, None)
            else:
                self.biflows.pop(rkey, None)
            return
        if rec["reply"]:
            # Reply tuple (ep -> client, ports swapped); its forward tuple
            # is (client=dst, frontend=dnat_ip, sport=dport, dport=
            # dnat_port) — the un-DNAT info the record carries.  A
            # reply-first arrival creates a PLACEHOLDER with forward-
            # oriented fields (dump order is hash-slot order, so either
            # direction can be seen first); the forward record later
            # fills in its richer fields.
            fkey = (rec["dst"], rec["dnat_ip"], rec["dport"],
                    rec["dnat_port"], rec["proto"])
            self._fwd_of_reply[
                (rec["src"], rec["dst"], rec["sport"], rec["dport"],
                 rec["proto"])
            ] = fkey
            bf = self.biflows.get(fkey)
            if bf is None:
                bf = self.biflows[fkey] = {
                    "src": rec["dst"], "dst": rec["dnat_ip"],
                    "sport": rec["dport"], "dport": rec["dnat_port"],
                    "proto": rec["proto"], "reply": False,
                    "node": rec.get("node", ""), "event": rec.get("event"),
                    "last_seen": rec["last_seen"],
                    "_placeholder": True,
                }
            bf["reply_seen"] = True
            bf["last_seen"] = max(bf["last_seen"], rec["last_seen"])
            # Reply-direction volumes fold in as the biflow's reverse
            # counters (the Reverse* IPFIX elements the reference
            # aggregator emits).  Entry counters are cumulative but RESET
            # when a cache eviction recreates the entry — fold with max so
            # aggregated totals never regress (pre-eviction volume is a
            # floor, not recoverable).
            bf["reverse_packets"] = max(bf.get("reverse_packets", 0),
                                        rec.get("packets", 0))
            bf["reverse_bytes"] = max(bf.get("reverse_bytes", 0),
                                      rec.get("bytes", 0))
            return
        fkey = (rec["src"], rec["dst"], rec["sport"], rec["dport"], rec["proto"])
        bf = self.biflows.get(fkey)
        if bf is None:
            self.biflows[fkey] = {**rec, "reply_seen": False}
            return
        if bf.pop("_placeholder", None):
            seen_reply = bf.get("reply_seen", False)
            rev_p = bf.get("reverse_packets")
            rev_b = bf.get("reverse_bytes")
            last = bf["last_seen"]
            bf.clear()
            bf.update(rec, reply_seen=seen_reply)
            if rev_p is not None:
                bf["reverse_packets"], bf["reverse_bytes"] = rev_p, rev_b
            bf["last_seen"] = max(last, rec["last_seen"])
        else:
            bf["last_seen"] = max(bf["last_seen"], rec["last_seen"])
            # Forward-direction volumes: max-fold (see the reverse-side
            # comment — an evicted-and-recreated entry restarts its
            # cumulative counters).
            if "packets" in rec:
                bf["packets"] = max(bf.get("packets", 0), rec["packets"])
                bf["bytes"] = max(bf.get("bytes", 0), rec.get("bytes", 0))

    def snapshot(self) -> list[dict]:
        return [dict(v) for _, v in sorted(self.biflows.items())]
