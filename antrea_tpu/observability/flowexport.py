"""Flow export + aggregation: conntrack-poll -> flow records -> biflows.

The analog of the reference's flow-visibility pipeline
(/root/reference/pkg/agent/flowexporter — conntrack polling into a
connection store, exported as IPFIX records — feeding
pkg/flowaggregator/flowaggregator.go:90-104, which correlates the two
directions and fans out to sinks).  The wire format here is JSON lines
(one record per flow event), the correlation semantics are the same:

  FlowExporter.poll(now)  diffs the datapath's dump_flows() against the
      connection store; NEW connections and active refreshes export
      records; connections gone past the idle timeout export a final
      record with reason=idle-end.
  FlowAggregator.ingest() merges forward and reply records of one
      connection into a single biflow keyed on the forward tuple.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Callable, Optional


def _key(rec: dict) -> tuple:
    return (rec["src"], rec["dst"], rec["sport"], rec["dport"], rec["proto"],
            rec["reply"])


@dataclass
class _Conn:
    first_seen: int
    last_seen: int
    last_export: int


class FlowExporter:
    """Per-node exporter: one instance polls one datapath."""

    def __init__(
        self,
        datapath,
        node: str = "",
        active_timeout_s: int = 60,
        sink: Optional[Callable[[dict], None]] = None,
        path: Optional[str] = None,
        keep_records: Optional[bool] = None,
    ):
        self.datapath = datapath
        self.node = node
        self.active_timeout_s = active_timeout_s
        self._conns: dict[tuple, _Conn] = {}
        # The in-memory record log is a convenience for consumers with no
        # sink/path; with one configured it would grow without bound over
        # the process lifetime, so it defaults OFF then.
        self._keep = (sink is None and path is None) if keep_records is None \
            else keep_records
        self.records: list[dict] = []
        self._sink = sink
        self.path = path

    def _emit(self, rec: dict) -> None:
        if self._keep:
            self.records.append(rec)
        if self._sink is not None:
            self._sink(rec)
        if self.path is not None:
            with open(self.path, "a") as f:
                f.write(json.dumps(rec, separators=(",", ":")) + "\n")

    def poll(self, now: int) -> int:
        """One conntrack-poll cycle; returns records emitted."""
        emitted = 0
        seen: set = set()
        for rec in self.datapath.dump_flows(now):
            k = _key(rec)
            seen.add(k)
            st = self._conns.get(k)
            if st is None:
                self._conns[k] = _Conn(rec["last_seen"], rec["last_seen"], now)
                self._emit({**rec, "node": self.node, "event": "new",
                            "export_ts": now})
                emitted += 1
            else:
                st.last_seen = rec["last_seen"]
                if now - st.last_export >= self.active_timeout_s:
                    st.last_export = now
                    self._emit({**rec, "node": self.node, "event": "active",
                                "export_ts": now})
                    emitted += 1
        # Connections that left the live dump ended (idle timeout/evicted).
        for k in [k for k in self._conns if k not in seen]:
            st = self._conns.pop(k)
            src, dst, sport, dport, proto, reply = k
            self._emit({
                "src": src, "dst": dst, "sport": sport, "dport": dport,
                "proto": proto, "reply": reply, "node": self.node,
                "event": "end", "reason": "idle-end",
                "last_seen": st.last_seen, "export_ts": now,
            })
            emitted += 1
        return emitted


class FlowAggregator:
    """Correlates the two directions of a connection into one biflow (the
    flowaggregator correlation step): reply records fold into the forward
    record keyed on the forward tuple."""

    def __init__(self):
        self.biflows: dict[tuple, dict] = {}
        # reply tuple -> forward biflow key, so reply 'end' records (which
        # carry no un-DNAT fields) can still find their biflow.
        self._fwd_of_reply: dict[tuple, tuple] = {}

    def ingest(self, rec: dict) -> None:
        if rec.get("event") == "end":
            # Expire the correlated biflow (the reference aggregator
            # expires records too — without this the table grows with
            # cumulative connection count forever).
            rkey = (rec["src"], rec["dst"], rec["sport"], rec["dport"],
                    rec["proto"])
            if rec["reply"]:
                fkey = self._fwd_of_reply.pop(rkey, None)
                if fkey is not None:
                    self.biflows.pop(fkey, None)
            else:
                self.biflows.pop(rkey, None)
            return
        if rec["reply"]:
            # Reply tuple (ep -> client, ports swapped); its forward tuple
            # is (client=dst, frontend=dnat_ip, sport=dport, dport=
            # dnat_port) — the un-DNAT info the record carries.  A
            # reply-first arrival creates a PLACEHOLDER with forward-
            # oriented fields (dump order is hash-slot order, so either
            # direction can be seen first); the forward record later
            # fills in its richer fields.
            fkey = (rec["dst"], rec["dnat_ip"], rec["dport"],
                    rec["dnat_port"], rec["proto"])
            self._fwd_of_reply[
                (rec["src"], rec["dst"], rec["sport"], rec["dport"],
                 rec["proto"])
            ] = fkey
            bf = self.biflows.get(fkey)
            if bf is None:
                bf = self.biflows[fkey] = {
                    "src": rec["dst"], "dst": rec["dnat_ip"],
                    "sport": rec["dport"], "dport": rec["dnat_port"],
                    "proto": rec["proto"], "reply": False,
                    "node": rec.get("node", ""), "event": rec.get("event"),
                    "last_seen": rec["last_seen"],
                    "_placeholder": True,
                }
            bf["reply_seen"] = True
            bf["last_seen"] = max(bf["last_seen"], rec["last_seen"])
            return
        fkey = (rec["src"], rec["dst"], rec["sport"], rec["dport"], rec["proto"])
        bf = self.biflows.get(fkey)
        if bf is None:
            self.biflows[fkey] = {**rec, "reply_seen": False}
            return
        if bf.pop("_placeholder", None):
            seen_reply = bf.get("reply_seen", False)
            last = bf["last_seen"]
            bf.clear()
            bf.update(rec, reply_seen=seen_reply)
            bf["last_seen"] = max(last, rec["last_seen"])
        else:
            bf["last_seen"] = max(bf["last_seen"], rec["last_seen"])

    def snapshot(self) -> list[dict]:
        return [dict(v) for _, v in sorted(self.biflows.items())]
