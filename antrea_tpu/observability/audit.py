"""NetworkPolicy audit logging with dedup buffering.

The analog of the reference's NP audit logger
(/root/reference/pkg/agent/controller/networkpolicy/audit_logging.go:48-171):
enforced deny/reject verdicts become append-only log records; identical
records inside a buffer window aggregate into one line with a packet count
(the reference's logDedupRecord buffering, flushed after a dedup interval).

Record format mirrors the reference's fields (antrea-network-policy log):
  <ts> <rule|DefaultDeny> <verdict> <reject-kind> <src>:<sport> -> <dst>:<dport> proto <p> x<count>

Driven from StepResult batches at the Datapath boundary, so both datapath
implementations feed the same logger — and an audit parity test can diff
the records the two produce.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from ..utils import ip as iputil

_VERDICT = {1: "Drop", 2: "Reject"}
_RK = {0: "", 1: "tcp-rst", 2: "icmp-unreach"}


def deny_rule_ids(ps) -> set:
    """Rule ids whose action is Drop/Reject — the attribution filter: a
    denied packet's StepResult carries BOTH directions' deciding rules, and
    only a deny-action rule can be the denier (an opposite-direction Allow
    attribution must not be logged as the denying rule)."""
    from ..apis.controlplane import RuleAction
    from ..compiler.ir import rule_id

    out: set = set()
    for p in ps.policies:
        for i, r in enumerate(p.rules):
            if r.action in (RuleAction.DROP, RuleAction.REJECT):
                out.add(rule_id(p, i))
    return out


@dataclass
class _Pending:
    first_ts: int
    last_ts: int
    count: int


@dataclass
class AuditRecord:
    ts: int
    rule: str  # stable rule id or "DefaultDeny"
    verdict: str
    reject_kind: str
    src_ip: int
    src_port: int
    dst_ip: int
    dst_port: int
    proto: int
    count: int

    def line(self) -> str:
        rk = f" {self.reject_kind}" if self.reject_kind else ""
        return (
            f"{self.ts} {self.rule} {self.verdict}{rk} "
            f"{iputil.u32_to_ip(self.src_ip)}:{self.src_port} -> "
            f"{iputil.u32_to_ip(self.dst_ip)}:{self.dst_port} "
            f"proto {self.proto} x{self.count}"
        )


class AuditLogger:
    """Dedup-buffered deny/reject audit stream.

    observe() ingests a StepResult; identical (5-tuple, verdict, rule)
    records within `dedup_s` aggregate.  flush() emits matured records (or
    everything with force=True) in deterministic order.
    """

    def __init__(
        self,
        dedup_s: int = 5,
        path: Optional[str] = None,
        deny_rules: Optional[set] = None,
        feature_gates=None,
    ):
        if feature_gates is not None and not feature_gates.enabled("AuditLogging"):
            raise RuntimeError("AuditLogging feature gate is disabled")
        self.dedup_s = dedup_s
        self.path = path
        # See deny_rule_ids(); update via set_deny_rules on bundle changes.
        self.deny_rules = deny_rules
        self._pending: dict[tuple, _Pending] = {}
        self.records: list[AuditRecord] = []
        self._unwritten: list[AuditRecord] = []

    def set_deny_rules(self, deny_rules: set) -> None:
        self.deny_rules = deny_rules

    def _attribute(self, ingress_rule, egress_rule) -> str:
        if self.deny_rules is None:
            # Without the deny-action index, named attribution is unsafe:
            # the only populated attribution may be an ALLOW rule of the
            # direction that did NOT deny (e.g. egress default-deny + an
            # ingress allow).  Callers wanting rule names pass
            # deny_rules=deny_rule_ids(ps).
            return "DefaultDeny"
        for r in (ingress_rule, egress_rule):
            if r and r in self.deny_rules:
                return r
        return "DefaultDeny"

    def observe(self, batch, result, now: int) -> None:
        # Hot path: the common all-allowed batch must not pay a Python loop.
        denied = np.flatnonzero(np.asarray(result.code))
        for i in denied:
            i = int(i)
            code = int(result.code[i])
            rule = self._attribute(result.ingress_rule[i], result.egress_rule[i])
            key = (
                rule, code, int(result.reject_kind[i]),
                int(batch.src_ip[i]), int(batch.src_port[i]),
                int(batch.dst_ip[i]), int(batch.dst_port[i]),
                int(batch.proto[i]),
            )
            p = self._pending.get(key)
            if p is not None and now - p.first_ts <= self.dedup_s:
                p.count += 1
                p.last_ts = now
            else:
                if p is not None:
                    self._emit(key, p)
                self._pending[key] = _Pending(first_ts=now, last_ts=now, count=1)
        self._write_out()

    def _emit(self, key: tuple, p: _Pending) -> None:
        rule, code, rk, sip, sp, dip, dp, proto = key
        rec = AuditRecord(
            ts=p.first_ts, rule=rule, verdict=_VERDICT.get(code, str(code)),
            reject_kind=_RK.get(rk, str(rk)), src_ip=sip, src_port=sp,
            dst_ip=dip, dst_port=dp, proto=proto, count=p.count,
        )
        self.records.append(rec)
        if self.path is not None:
            self._unwritten.append(rec)

    def _write_out(self) -> None:
        """One open per batch of emissions, not per record."""
        if self.path is None or not self._unwritten:
            return
        with open(self.path, "a") as f:
            for rec in self._unwritten:
                f.write(rec.line() + "\n")
        self._unwritten.clear()

    def flush(self, now: int, force: bool = False) -> list[AuditRecord]:
        """Emit records whose dedup window has matured; returns them."""
        start = len(self.records)
        for key in sorted(self._pending):
            p = self._pending[key]
            if force or now - p.first_ts > self.dedup_s:
                self._emit(key, p)
                del self._pending[key]
        self._write_out()
        return self.records[start:]
