"""Agent heartbeat info: the AntreaAgentInfo CRD analog.

The reference's monitor publishes AntreaAgentInfo/AntreaControllerInfo
health CRDs every 60s (/root/reference/pkg/monitor/agent.go:30-96:
version, node, OVS info, NP counts, conditions).  collect_agent_info is
the per-tick producer; the dissemination/K8s write is the caller's."""

from __future__ import annotations

import time

from ..antctl import VERSION


def collect_agent_info(datapath, node: str, agent=None, now=None) -> dict:
    stats = datapath.stats()
    info = {
        "kind": "AntreaAgentInfo",
        "version": VERSION,
        "nodeName": node,
        "heartbeatUnix": time.time() if now is None else now,
        "datapath": {
            "type": str(datapath.datapath_type.value),
            "generation": datapath.generation,
            "cache": datapath.cache_stats(),
        },
        "networkPolicyStats": {
            "ingressRules": len(stats.ingress),
            "egressRules": len(stats.egress),
            "defaultAllow": stats.default_allow,
            "defaultDeny": stats.default_deny,
        },
        "conditions": [{
            "type": "AgentHealthy",
            "status": "True",
        }],
    }
    if agent is not None:
        ps = agent.policy_set
        info["networkPolicies"] = len(ps.policies)
        info["addressGroups"] = len(ps.address_groups)
        info["appliedToGroups"] = len(ps.applied_to_groups)
    return info


def collect_controller_info(controller, store=None, now=None, status=None) -> dict:
    """AntreaControllerInfo heartbeat (ref pkg/monitor controller side:
    version, connected-agent count, NP/group counts, conditions, service
    CIDR/cluster identity when known).  `controller` is a
    NetworkPolicyController; `store` an optional RamStore whose watcher
    count is the connected-agent gauge; `status` an optional
    StatusAggregator whose per-policy realization phases are summarized
    (the kubectl-visible NetworkPolicyStatus surface,
    status_controller.go:281-287)."""
    info = {
        "kind": "AntreaControllerInfo",
        "version": VERSION,
        "heartbeatUnix": time.time() if now is None else now,
        **controller.object_counts(),
        "conditions": [{
            "type": "ControllerHealthy",
            "status": "True",
        }],
    }
    if store is not None:
        info["connectedAgentNum"] = store.n_watchers
    if status is not None:
        statuses = status.all_statuses()
        info["networkPolicyRealization"] = {
            "policies": [
                {
                    "uid": s.uid,
                    "phase": s.phase,
                    "observedGeneration": s.observed_generation,
                    "currentNodesRealized": s.current_nodes,
                    "desiredNodesRealized": s.desired_nodes,
                    "failedNodes": s.failed_nodes,
                }
                for s in statuses
            ],
            "realized": sum(1 for s in statuses if s.phase == "Realized"),
            "total": len(statuses),
        }
    return info
