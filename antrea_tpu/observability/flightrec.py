"""Flight recorder: a black-box event journal for every plane transition.

The reference debugs its datapath with OVS's own introspection (coverage
counters, `ovs-appctl` dumps); this build owns the datapath, so it must
own the post-mortem story too.  PRs 4-7 grew plane transitions — rollback
to last-known-good, degraded mode, epoch swaps, autotune rung moves,
maintenance sheds — that left no record at all: a chaos test could assert
the FINAL state but never the PATH taken, and an operator staring at a
degraded node had counters, not causality.

`FlightRecorder` is the in-memory ring journal (the classic black-box
shape: bounded, always on, cheap enough to leave running):

  * fixed capacity, preallocated slots, DROP-OLDEST on wrap — recording
    is one dict store + two int bumps, it never blocks, backpressures, or
    reorders the hot step; overflow loses the OLDEST telemetry, metered
    in `dropped_total`, never the newest;
  * every event carries a MONOTONIC sequence number (the causal order —
    two events' seq ordering is their emission ordering) and a timestamp
    from the PR 7 maintenance tick clock (`MaintenanceScheduler.clock`),
    so fault-injected time (dissemination/faults.FaultClock) stamps the
    journal deterministically in the chaos tier;
  * events are TYPED: `emit(kind=...)` accepts only kinds declared in
    EVENT_KINDS below — tools/check_events.py fails the build on an
    undeclared kind at any call site, a declared kind with no emit site,
    or a kind missing its README row.

Emit sites (one per plane transition, threaded through):
  datapath/commit.py        commit stage outcomes, canary mismatches,
                            rollback, degrade, recover
  datapath/slowpath/        epoch swaps, drain begin/finish, queue
                            overflow, autotune rung moves
  datapath/maintenance.py   per-tick grants/sheds, blocked ticks
  datapath/audit.py         findings, repairs
  agent/controller.py       sync attempts, poison-bundle quarantine
  dissemination/faults.py   every injected fault logs itself, so a chaos
                            post-mortem correlates cause with effect
  observability/tracing.py  realization span closures

Surfaces: `GET /flightrecorder?tail=N[&kind=...]` (agent/apiserver.py),
`antctl flightrecorder [--tail N] [--kind ...]`, `flightrecorder.json` in
the support bundle, and the antrea_tpu_flightrecorder_events_total /
antrea_tpu_flightrecorder_dropped_total / antrea_tpu_flightrecorder_seq
metric families.
Recording cost is accounted by the maintenance scheduler's
`observability` task (datapath/maintenance.py) instead of smearing into
whichever plane happened to emit.
"""

from __future__ import annotations

from collections import Counter
from typing import Callable, Optional

# The typed event schema: kind -> emitting plane + meaning.  Pure
# literals on purpose — tools/check_events.py parses this dependency-free
# and fails the build when an emit site uses an undeclared kind, a
# declared kind has no emit site, or a kind has no README row.
EVENT_KINDS = {
    "commit": "datapath/commit.py — one install transaction settled or "
              "failed (stage carries the deciding stage, outcome "
              "ok/error/mismatch)",
    "canary-mismatch": "datapath/commit.py — canary probes diverged from "
                       "the scalar oracle (install gate or live watchdog)",
    "rollback": "datapath/commit.py — state restored to the retained "
                "last-known-good bundle",
    "degrade": "datapath/commit.py + datapath/audit.py — the datapath "
               "entered degraded mode (serving LKG, deltas quarantined)",
    "recover": "datapath/commit.py — a full-bundle recompile passed its "
               "canary and lifted degraded mode",
    "epoch-swap": "datapath/slowpath/engine.py — a new flow-cache epoch "
                  "published (drain commit, revalidation or aging pass)",
    "drain-begin": "datapath/slowpath/engine.py — a coalesced miss batch "
                   "popped and pinned (epoch + bundle generation)",
    "drain-finish": "datapath/slowpath/engine.py — the in-flight batch "
                    "classified and committed (stale batches re-classify)",
    "queue-overflow": "datapath/slowpath/engine.py — miss admissions "
                      "tail-dropped on a full queue",
    "autotune": "datapath/slowpath/engine.py — the drain-chunk hysteresis "
                "controller moved one ladder rung",
    "maint-tick": "datapath/maintenance.py — one scheduler round: per-task "
                  "grants, deferrals and sheds",
    "maint-blocked": "datapath/maintenance.py — a tick deferred whole by "
                     "the serialization point (in-flight drain)",
    "audit-finding": "datapath/audit.py — a revalidator scan found "
                     "divergences (cached rows or tensor digests)",
    "audit-repair": "datapath/audit.py — divergent rows evicted for lazy "
                    "reclassify / corrupt tensors healed",
    "agent-sync": "agent/controller.py — a sync() applied state to the "
                  "datapath, or the install raised (outcome + error)",
    "agent-quarantine": "agent/controller.py — a deterministic compile "
                        "rejection poisoned the bundle (no hot retry "
                        "until new upstream state)",
    "fault-injected": "dissemination/faults.py — a FaultPlan rule fired "
                      "(site, fault kind, hit count): chaos cause, "
                      "journaled beside its effects",
    "mesh-epoch-swap": "parallel/meshpath.py — the mesh datapath "
                       "published a new flow-cache epoch: one sharded "
                       "dispatch + one epoch counter flips every data "
                       "replica's generation atomically (the mesh-wide "
                       "swap)",
    "replica-canary-veto": "datapath/commit.py — a replica-resolved "
                           "canary found >= 1 data replica diverging "
                           "from the scalar oracle; the single veto "
                           "rolls back / degrades ALL replicas",
    "realization": "observability/tracing.py — a policy realization span "
                   "closed (controller commit -> first live hit)",
    "prune-retune": "datapath/tpuflow.py — the match-prune K-budget "
                    "hysteresis controller moved one PRUNE_LADDER rung "
                    "(fed by the measured fallback rate)",
    "reshard-begin": "parallel/reshard.py — a live data-axis resize "
                     "started: target mesh constructed, dual-topology "
                     "serving begins (the old affinity ring keeps "
                     "serving while migration runs)",
    "reshard-migrated": "parallel/reshard.py — the budgeted migration "
                        "cursor covered the whole source slot space; "
                        "the plane is ready to certify and cut over",
    "reshard-cutover": "parallel/reshard.py — the target topology passed "
                       "its replica-resolved canary + migrated-row audit "
                       "and the affinity hash flipped generation in one "
                       "mesh-wide epoch swap",
    "reshard-abort": "parallel/reshard.py — the resize aborted "
                     "(target-topology canary veto, audit divergence, "
                     "flip failure, or operator abort): the old mesh "
                     "keeps serving, generation unchanged",
    "tenant-create": "datapath/tenancy.py — an isolated tenant policy "
                     "world was created (rung-padded rule window, "
                     "quota-rung state tables, generation 0)",
    "tenant-quota-clamp": "datapath/tenancy.py — a tenant's miss-queue "
                          "admissions were clamped to its in-queue "
                          "quota (noisy-neighbor containment; the "
                          "clamped flows re-admit once its backlog "
                          "drains)",
    "tenant-rollback": "datapath/tenancy.py — a tenant's install failed "
                       "its transaction (canary veto / compile fault) "
                       "and rolled back ONLY that tenant's world; every "
                       "other tenant's generation is untouched",
    "tenant-reshard-cutover": "parallel/reshard.py — one tenant world's "
                              "state flipped to the target topology: its "
                              "own replica-resolved canary + migrated-row "
                              "audit certified the placement and its rows "
                              "re-homed under the tenant-salted ring",
    "tenant-reshard-veto": "parallel/reshard.py — one tenant world's "
                           "target-placement certification failed "
                           "(canary veto / audit divergence / placement "
                           "fault): ONLY that world aborted and keeps "
                           "serving its old topology via the per-world "
                           "generation latch; certified worlds still "
                           "flip",
    "watcher-overflow": "dissemination/store.py — distinct-key churn "
                        "filled a bounded watcher queue past max_pending "
                        "even after coalescing: the buffer dropped and "
                        "the stream flipped to needs_resync",
    "resync-begin": "dissemination/netwire.py — the server opened a "
                    "resync window for a node (objects = snapshot size; "
                    "restart=True when a mid-resync overflow re-listed "
                    "inside the same window)",
    "resync-end": "dissemination/netwire.py — a node's resync window "
                  "closed (chunks + events actually shipped after "
                  "known-set dedup)",
    "resync-shed": "dissemination/netwire.py — the admission gate "
                   "deferred a watcher's resync because "
                   "resync_concurrency cursors were already in flight",
    "perf-regression": "observability/telemetry.py — the telemetry "
                       "sentinel found a regime's rolling-window p99 "
                       "burning past ratio x its rolling baseline "
                       "(payload: regime, p99, baseline_p99, samples, "
                       "ratio) — journal-and-meter only, never an "
                       "automatic rollback",
    "batch-flush": "serving/batcher.py — a staging ring flushed onto the "
                   "canonical batch ladder (payload: tenant, lanes, "
                   "padded, dispatches, age_ticks, reason = depth / "
                   "deadline / forced / overflow)",
    "batch-deadline-exceeded": "serving/batcher.py — a ring flushed "
                               "LATER than its flush_deadline (budget "
                               "starvation or a stalled tick clock): "
                               "the p99 contract was at risk for that "
                               "world's staged lanes",
    "replica-probe-fail": "parallel/failover.py — a data replica failed "
                          "one health probe round (payload: replica, "
                          "reason = mismatch / deadline / fault-dead, "
                          "streak); probe_fails consecutive failures "
                          "quarantine the replica",
    "replica-quarantine": "parallel/failover.py — a replica was "
                          "quarantined: masked out of serving "
                          "immediately (lanes re-home onto the survivor "
                          "ring host-side), its queued misses requeued "
                          "verbatim to survivors, and the ring "
                          "evacuation begins",
    "replica-evacuate": "parallel/failover.py — the emergency shrink to "
                        "the survivor topology CUT OVER "
                        "(canary-certified like every resize): survivor "
                        "rows migrated, the dead replica's flows "
                        "re-miss and re-classify to identical verdicts "
                        "(payload meters the re-miss burst)",
    "replica-readmit": "parallel/failover.py — the quarantined replica "
                       "rejoined (payload: mode = auto / operator, gate "
                       "= unmask for a pre-flip heal, resize for the "
                       "certified grow back over the boot device grid)",
}


def emit_into(carrier, kind: str, **fields) -> None:
    """Journal one event into `carrier`'s flight recorder, a no-op when
    it has none — the ONE null-recorder discipline every plane's `_emit`
    shim delegates to (the shims keep the literal kind at their call
    sites, which is what tools/check_events.py greps)."""
    rec = getattr(carrier, "_flightrec", None)
    if rec is not None:
        rec.emit(kind=kind, **fields)


class FlightRecorder:
    """Fixed-capacity, drop-oldest ring journal of typed events.

    Single-threaded like every plane that feeds it (the engines' control
    thread); `emit` is append-only into preallocated slots.  `capacity=0`
    disables recording entirely (emit becomes a counter bump only), so
    the journal can be compiled out of soak runs without touching any
    emit site.
    """

    def __init__(self, capacity: int = 1024,
                 clock: Optional[Callable[[], int]] = None):
        if capacity < 0:
            raise ValueError(
                f"flight recorder capacity must be >= 0, got {capacity}")
        self.capacity = int(capacity)
        self._slots: list = [None] * self.capacity
        # Monotonic sequence number == events emitted since boot; the
        # ring keeps the LAST `capacity` of them.
        self.seq = 0
        self.dropped_total = 0
        self.emitted: Counter = Counter()  # kind -> count (survives wrap)
        self._clock = clock

    def set_clock(self, clock: Callable[[], int]) -> None:
        """Wire the timebase — the maintenance scheduler's tick clock
        (datapath/maintenance.py `_init_maintenance` calls this), so the
        journal, the backoff windows and FQDN expiry share ONE notion of
        now, fault-injectable via faults.FaultClock."""
        self._clock = clock

    def _now(self) -> int:
        return 0 if self._clock is None else int(self._clock())

    def emit(self, kind: str, **fields) -> int:
        """Journal one event -> its sequence number.  O(1), allocation
        bounded to the event dict itself: never blocks the hot step;
        on a full ring the OLDEST slot is overwritten (metered)."""
        if kind not in EVENT_KINDS:
            raise ValueError(
                f"undeclared flight-recorder event kind {kind!r} "
                f"(declare it in observability/flightrec.EVENT_KINDS)")
        seq = self.seq
        self.seq += 1
        self.emitted[kind] += 1
        if self.capacity == 0:
            self.dropped_total += 1  # disabled: every event is "lost"
            return seq
        i = seq % self.capacity
        if self._slots[i] is not None:
            self.dropped_total += 1
        self._slots[i] = {"seq": seq, "ts": self._now(), "kind": kind,
                          **fields}
        return seq

    # -- reading the journal -------------------------------------------------

    def events(self, tail: Optional[int] = None,
               kind: Optional[str] = None) -> list[dict]:
        """Journal contents in sequence order (oldest retained first);
        `kind` filters, `tail` keeps the last N AFTER filtering."""
        if self.capacity == 0:
            return []
        # API handler threads read while the engine thread emits: snapshot
        # the head, then keep only slots whose seq matches the window —
        # a slot overwritten mid-read carries a NEWER seq and is skipped
        # (drop-oldest semantics), so the result is always in sequence
        # order and never torn.
        snap = self.seq
        start = max(0, snap - self.capacity)
        out = []
        for s in range(start, snap):
            e = self._slots[s % self.capacity]
            if e is not None and e["seq"] == s:
                out.append(e)
        if kind is not None:
            out = [e for e in out if e["kind"] == kind]
        if tail is not None:
            n = max(0, int(tail))
            out = out[-n:] if n else []  # -0 would slice the WHOLE list
        return out

    def stats(self) -> dict:
        return {
            "capacity": int(self.capacity),
            "seq": int(self.seq),
            "retained": min(self.seq, self.capacity),
            "dropped_total": int(self.dropped_total),
            "kinds": {k: int(v) for k, v in sorted(self.emitted.items())},
        }
