"""Synthetic packet-batch generator (the traffic side of the simulator).

Generates batches with controllable flow locality: a Zipf-ish draw over a
fixed flow universe so the flow-cache/conntrack fast path sees realistic
repeat-flow ratios (the reference relies on the same property: OVS's megaflow
cache and kernel conntrack only pay full classification on the first packet
of a flow; ref: docs/design/ovs-pipeline.md conntrack sections).
"""

from __future__ import annotations

import numpy as np

from ..apis.controlplane import PROTO_TCP, PROTO_UDP
from ..packet import PacketBatch


def gen_traffic(
    pod_ips: list[int],
    batch: int,
    *,
    n_flows: int = 1 << 16,
    pod_to_pod_fraction: float = 0.8,
    zipf_a: float = 1.2,
    seed: int = 0,
    services=None,  # optional list[ServiceEntry]; a share of flows target them
    svc_fraction: float = 0.3,
    one_per_flow: bool = False,  # batch = a PERMUTATION of the universe
) -> PacketBatch:
    rng = np.random.default_rng(seed)
    pods = np.asarray(pod_ips, dtype=np.uint32)

    # Flow universe.
    f_src = rng.choice(pods, size=n_flows)
    f_dst = rng.choice(pods, size=n_flows)
    ext = rng.integers(0, 1 << 32, size=n_flows, dtype=np.uint32)
    external = rng.random(n_flows) > pod_to_pod_fraction
    f_src = np.where(external & (rng.random(n_flows) < 0.5), ext, f_src)
    f_dst = np.where(external & (rng.random(n_flows) >= 0.5), ext, f_dst)
    f_proto = np.where(rng.random(n_flows) < 0.85, PROTO_TCP, PROTO_UDP).astype(np.int32)
    f_sport = rng.integers(1024, 65536, size=n_flows, dtype=np.int32)
    common = np.array([80, 443, 8080, 53, 5432], dtype=np.int32)
    f_dport = np.where(
        rng.random(n_flows) < 0.7,
        rng.choice(common, size=n_flows),
        rng.integers(1, 65536, size=n_flows),
    ).astype(np.int32)

    if services:
        from ..utils import ip as iputil

        pick = rng.integers(0, len(services), size=n_flows)
        svc_ip = np.array(
            [iputil.ip_to_u32(s.cluster_ip) for s in services], dtype=np.uint32
        )[pick]
        svc_port = np.array([s.port for s in services], dtype=np.int32)[pick]
        svc_proto = np.array([s.protocol for s in services], dtype=np.int32)[pick]
        to_svc = rng.random(n_flows) < svc_fraction
        f_dst = np.where(to_svc, svc_ip, f_dst)
        f_dport = np.where(to_svc, svc_port, f_dport)
        f_proto = np.where(to_svc, svc_proto, f_proto)

    if one_per_flow:
        # Exactly one packet per universe flow, shuffled — the churn-pool
        # shape (flow ARRIVALS: every window is genuinely fresh flows,
        # no zipf head re-hitting the cache).
        if batch != n_flows:
            raise ValueError("one_per_flow requires batch == n_flows")
        idx = rng.permutation(n_flows)
    else:
        # Zipf draw over flows -> batch indices.
        idx = (rng.zipf(zipf_a, size=batch) - 1) % n_flows

    return PacketBatch(
        src_ip=f_src[idx],
        dst_ip=f_dst[idx],
        proto=f_proto[idx],
        src_port=f_sport[idx],
        dst_port=f_dport[idx],
    )
