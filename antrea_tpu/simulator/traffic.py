"""Synthetic packet-batch generator (the traffic side of the simulator).

Generates batches with controllable flow locality: a Zipf-ish draw over a
fixed flow universe so the flow-cache/conntrack fast path sees realistic
repeat-flow ratios (the reference relies on the same property: OVS's megaflow
cache and kernel conntrack only pay full classification on the first packet
of a flow; ref: docs/design/ovs-pipeline.md conntrack sections).
"""

from __future__ import annotations

import numpy as np

from ..apis.controlplane import PROTO_TCP, PROTO_UDP
from ..packet import PacketBatch


def gen_traffic(
    pod_ips: list[int],
    batch: int,
    *,
    n_flows: int = 1 << 16,
    pod_to_pod_fraction: float = 0.8,
    zipf_a: float = 1.2,
    seed: int = 0,
    services=None,  # optional list[ServiceEntry]; a share of flows target them
    svc_fraction: float = 0.3,
    one_per_flow: bool = False,  # batch = a PERMUTATION of the universe
) -> PacketBatch:
    rng = np.random.default_rng(seed)
    pods = np.asarray(pod_ips, dtype=np.uint32)

    # Flow universe.
    f_src = rng.choice(pods, size=n_flows)
    f_dst = rng.choice(pods, size=n_flows)
    ext = rng.integers(0, 1 << 32, size=n_flows, dtype=np.uint32)
    external = rng.random(n_flows) > pod_to_pod_fraction
    f_src = np.where(external & (rng.random(n_flows) < 0.5), ext, f_src)
    f_dst = np.where(external & (rng.random(n_flows) >= 0.5), ext, f_dst)
    f_proto = np.where(rng.random(n_flows) < 0.85, PROTO_TCP, PROTO_UDP).astype(np.int32)
    f_sport = rng.integers(1024, 65536, size=n_flows, dtype=np.int32)
    common = np.array([80, 443, 8080, 53, 5432], dtype=np.int32)
    f_dport = np.where(
        rng.random(n_flows) < 0.7,
        rng.choice(common, size=n_flows),
        rng.integers(1, 65536, size=n_flows),
    ).astype(np.int32)

    if services:
        from ..utils import ip as iputil

        pick = rng.integers(0, len(services), size=n_flows)
        svc_ip = np.array(
            [iputil.ip_to_u32(s.cluster_ip) for s in services], dtype=np.uint32
        )[pick]
        svc_port = np.array([s.port for s in services], dtype=np.int32)[pick]
        svc_proto = np.array([s.protocol for s in services], dtype=np.int32)[pick]
        to_svc = rng.random(n_flows) < svc_fraction
        f_dst = np.where(to_svc, svc_ip, f_dst)
        f_dport = np.where(to_svc, svc_port, f_dport)
        f_proto = np.where(to_svc, svc_proto, f_proto)

    if one_per_flow:
        # Exactly one packet per universe flow, shuffled — the churn-pool
        # shape (flow ARRIVALS: every window is genuinely fresh flows,
        # no zipf head re-hitting the cache).
        if batch != n_flows:
            raise ValueError("one_per_flow requires batch == n_flows")
        idx = rng.permutation(n_flows)
    else:
        # Zipf draw over flows -> batch indices.
        idx = (rng.zipf(zipf_a, size=batch) - 1) % n_flows

    return PacketBatch(
        src_ip=f_src[idx],
        dst_ip=f_dst[idx],
        proto=f_proto[idx],
        src_port=f_sport[idx],
        dst_port=f_dport[idx],
    )


# ---------------------------------------------------------------------------
# Attack-shaped generators (ROADMAP item 4's adversarial tier; also the
# quota-isolation stressors of the multi-tenant tests, datapath/tenancy).
# ---------------------------------------------------------------------------

def gen_syn_flood(
    dst_ips: list[int],
    batch: int,
    *,
    start_seq: int = 0,
    seed: int = 0,
) -> PacketBatch:
    """SYN-flood batch: NEVER-repeating 5-tuples — every lane is a fresh
    TCP SYN whose (src, sport) pair is unique across the whole sequence
    of calls (thread `start_seq` forward by `batch` per call), so no
    packet can ever hit the flow cache and every one is a miss-queue
    admission.  The cache-kill shape: zero locality by construction (the
    megaflow-cache attack OVS's bounded upcall sockets exist for)."""
    rng = np.random.default_rng(seed)
    seq = start_seq + np.arange(batch, dtype=np.int64)
    # 16k ephemeral ports x 2^18 source-address block: unique pairs for
    # 2^32 packets before wrap, way past any test/bench horizon.
    sport = (1024 + (seq % 16384)).astype(np.int32)
    src = (np.uint32(0xC6000000) + (seq // 16384).astype(np.uint32))
    dst = np.asarray(dst_ips, np.uint32)[
        rng.integers(0, len(dst_ips), batch)]
    return PacketBatch(
        src_ip=src.astype(np.uint32),
        dst_ip=dst,
        proto=np.full(batch, PROTO_TCP, np.int32),
        src_port=sport,
        dst_port=np.full(batch, 80, np.int32),
        tcp_flags=np.full(batch, 0x02, np.int32),  # SYN
    )


def gen_bursty(
    pod_ips: list[int],
    n_ticks: int,
    *,
    tenants: list[int],
    burst_lanes: int = 8,
    p_start: float = 0.25,
    p_stop: float = 0.5,
    n_flows: int = 64,
    seed: int = 0,
) -> list:
    """Per-tenant bursty/idle arrival schedule (the serving-batcher
    driver: uneven, trickling per-world lane counts — the traffic shape
    whose per-tenant sub-batch sizes are unbounded without the canonical
    ladder).

    Each tenant runs an independent two-state (idle/burst) Markov chain:
    idle -> burst with `p_start`, burst -> idle with `p_stop`; while
    bursting it emits 1..burst_lanes lanes per tick drawn from its OWN
    repeat-flow pool (so flow-cache behavior per world is realistic).
    Returns `n_ticks` entries, each None (fleet idle that tick) or a
    `(tenant_ids, PacketBatch)` pair in submission order.  Deterministic
    for a given seed: every draw comes from one seeded rng in a fixed
    iteration order.
    """
    import dataclasses

    rng = np.random.default_rng(seed)
    if isinstance(tenants, (int, np.integer)):
        tenants = range(int(tenants))  # a count: worlds 0..n-1
    tenants = [int(t) for t in tenants]
    pool_b = max(n_flows, 4 * burst_lanes)
    pools = {
        t: gen_traffic(pod_ips, pool_b, n_flows=n_flows,
                       seed=seed * 1009 + 17 * i + 1)
        for i, t in enumerate(tenants)
    }
    bursting = {t: False for t in tenants}
    offs = {t: 0 for t in tenants}

    def lanes_of(t: int, k: int) -> PacketBatch:
        pool = pools[t]
        idx = (offs[t] + np.arange(k)) % pool.size
        offs[t] += k
        kw = {}
        for f in dataclasses.fields(pool):
            v = getattr(pool, f.name)
            kw[f.name] = None if v is None else np.asarray(v)[idx]
        return PacketBatch(**kw)

    out = []
    for _ in range(n_ticks):
        tids = []
        segs = []
        for t in tenants:
            flip = rng.random()
            bursting[t] = ((flip < p_start) if not bursting[t]
                           else (flip >= p_stop))
            if not bursting[t]:
                continue
            k = int(rng.integers(1, burst_lanes + 1))
            tids.append(np.full(k, t, np.int64))
            segs.append(lanes_of(t, k))
        if not segs:
            out.append(None)
            continue
        kw = {}
        for f in dataclasses.fields(segs[0]):
            cols = [getattr(s, f.name) for s in segs]
            kw[f.name] = (None if any(c is None for c in cols)
                          else np.concatenate([np.asarray(c) for c in cols]))
        out.append((np.concatenate(tids), PacketBatch(**kw)))
    return out


def gen_cache_thrash(
    pod_ips: list[int],
    batch: int,
    *,
    n_flows: int,
    seed: int = 0,
) -> PacketBatch:
    """Cache-thrash batch: a UNIFORM draw over a flow universe sized far
    past the flow-cache slot count (callers pass n_flows >> slots), so
    every slot sees continuous eviction pressure and the hit rate pins
    to ~slots/n_flows.  Unlike gen_syn_flood the flows DO repeat — this
    is the thrash shape (replacement-policy stress), not the
    never-repeat shape (admission stress)."""
    rng = np.random.default_rng(seed)
    pods = np.asarray(pod_ips, dtype=np.uint32)
    f_src = pods[rng.integers(0, len(pods), n_flows)]
    f_dst = pods[rng.integers(0, len(pods), n_flows)]
    f_sport = rng.integers(1024, 65536, n_flows).astype(np.int32)
    f_dport = rng.integers(1, 65536, n_flows).astype(np.int32)
    idx = rng.integers(0, n_flows, batch)
    return PacketBatch(
        src_ip=f_src[idx],
        dst_ip=f_dst[idx],
        proto=np.full(batch, PROTO_UDP, np.int32),
        src_port=f_sport[idx],
        dst_port=f_dport[idx],
    )
