"""Synthetic cluster + policy-set generator.

The analog of the reference's scale-test drivers: the controller perf tests
(/root/reference/pkg/controller/networkpolicy/networkpolicy_controller_perf_test.go:46)
build N namespaces x pods x policies with fake clients, and
antrea-agent-simulator (/root/reference/cmd/antrea-agent-simulator) drives
scale without a dataplane.  Here the generator emits already-computed internal
objects (PolicySet) for the datapath benchmarks in BASELINE.md:
1k exact-match / 10k ACNP+tiers+CIDR / 100k multi-tenant mix.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field

from ..apis import controlplane as cp
from ..compiler.ir import PolicySet
from ..utils import ip as iputil


@dataclass
class SyntheticCluster:
    ps: PolicySet
    pod_ips: list[int] = field(default_factory=list)  # u32
    nodes: list[str] = field(default_factory=list)


def _pod_ip(node_idx: int, pod_idx: int) -> str:
    # podCIDR per node: 10.<n/256>.<n%256>.0/24 (matches the reference's
    # per-Node podCIDR model; ref: pkg/agent/agent.go initK8sNodeLocalConfig).
    return f"10.{node_idx // 256}.{node_idx % 256}.{pod_idx + 2}"


def gen_cluster(
    n_rules: int,
    *,
    n_nodes: int = 16,
    pods_per_node: int = 32,
    pods_per_group: int = 8,
    rules_per_policy: int = 4,
    cidr_fraction: float = 0.3,
    acnp_fraction: float = 0.5,
    with_tiers: bool = True,
    seed: int = 0,
) -> SyntheticCluster:
    """Generate ~n_rules rules across K8s NPs and ACNPs with shared groups.

    Group sharing mirrors production policy sets (and the reference's
    conjunctive factoring assumption, SURVEY.md section 2.6): the number of
    distinct AddressGroups is much smaller than the number of rules.
    """
    rng = random.Random(seed)
    nodes = [f"node-{i}" for i in range(n_nodes)]
    pod_ips = [
        iputil.ip_to_u32(_pod_ip(n, p)) for n in range(n_nodes) for p in range(pods_per_node)
    ]

    ps = PolicySet()

    # Address/appliedTo groups over pods.
    n_groups = max(4, min(4096, (n_rules // 4) or 4))
    for gi in range(n_groups):
        members = []
        for _ in range(pods_per_group):
            n = rng.randrange(n_nodes)
            p = rng.randrange(pods_per_node)
            members.append(
                cp.GroupMember(ip=_pod_ip(n, p), node=nodes[n], pod_name=f"pod-{n}-{p}")
            )
        ps.address_groups[f"ag-{gi}"] = cp.AddressGroup(name=f"ag-{gi}", members=members)
        ps.applied_to_groups[f"atg-{gi}"] = cp.AppliedToGroup(name=f"atg-{gi}", members=members)

    tiers = (
        [cp.TIER_EMERGENCY, cp.TIER_SECURITYOPS, cp.TIER_NETWORKOPS, cp.TIER_PLATFORM,
         cp.TIER_APPLICATION]
        if with_tiers
        else [cp.TIER_APPLICATION]
    )

    def rand_peer() -> cp.NetworkPolicyPeer:
        if rng.random() < cidr_fraction:
            plen = rng.choice([8, 12, 16, 20, 24, 28, 32])
            base = rng.getrandbits(32)
            cidr = f"{iputil.u32_to_ip(base)}/{plen}"
            if rng.random() < 0.2:
                sub = min(plen + 4, 32)
                exc = f"{iputil.u32_to_ip(base)}/{sub}"
                return cp.NetworkPolicyPeer(ip_blocks=[cp.IPBlock(cidr=cidr, excepts=(exc,))])
            return cp.NetworkPolicyPeer(ip_blocks=[cp.IPBlock(cidr=cidr)])
        return cp.NetworkPolicyPeer(address_groups=[f"ag-{rng.randrange(n_groups)}"])

    def rand_services() -> list[cp.Service]:
        r = rng.random()
        if r < 0.25:
            return []  # any
        proto = rng.choice([cp.PROTO_TCP, cp.PROTO_TCP, cp.PROTO_UDP])
        port = rng.choice([80, 443, 8080, 53, 5432, rng.randrange(1024, 60000)])
        if r < 0.4:
            return [cp.Service(protocol=proto, port=port, end_port=port + rng.randrange(1, 64))]
        return [cp.Service(protocol=proto, port=port)]

    made = 0
    pi = 0
    while made < n_rules:
        k = min(rules_per_policy, n_rules - made)
        is_acnp = rng.random() < acnp_fraction
        rules = []
        for ri in range(k):
            direction = cp.Direction.IN if rng.random() < 0.6 else cp.Direction.OUT
            peer = rand_peer()
            rule = cp.NetworkPolicyRule(
                direction=direction,
                from_peer=peer if direction == cp.Direction.IN else cp.NetworkPolicyPeer(),
                to_peer=peer if direction == cp.Direction.OUT else cp.NetworkPolicyPeer(),
                services=rand_services(),
                action=(
                    rng.choices(
                        [cp.RuleAction.ALLOW, cp.RuleAction.DROP, cp.RuleAction.REJECT,
                         cp.RuleAction.PASS],
                        weights=[0.55, 0.3, 0.05, 0.1],
                    )[0]
                    if is_acnp
                    else cp.RuleAction.ALLOW
                ),
                priority=ri if is_acnp else -1,
            )
            rules.append(rule)
        atg = f"atg-{rng.randrange(n_groups)}"
        if is_acnp:
            pol = cp.NetworkPolicy(
                uid=f"acnp-{pi}",
                name=f"acnp-{pi}",
                type=cp.NetworkPolicyType.ACNP,
                rules=rules,
                applied_to_groups=[atg],
                tier_priority=rng.choice(tiers + ([cp.TIER_BASELINE] if rng.random() < 0.1 else [])),
                priority=round(rng.uniform(1, 150), 2),
            )
        else:
            dirs = sorted({r.direction for r in rules}, key=lambda d: d.value)
            pol = cp.NetworkPolicy(
                uid=f"knp-{pi}",
                name=f"knp-{pi}",
                namespace=f"ns-{rng.randrange(32)}",
                type=cp.NetworkPolicyType.K8S,
                rules=rules,
                applied_to_groups=[atg],
                policy_types=list(dirs),
            )
        ps.policies.append(pol)
        made += k
        pi += 1

    return SyntheticCluster(ps=ps, pod_ips=pod_ips, nodes=nodes)
