"""Synthetic Service generator (the AntreaProxy test-config side:
BASELINE config 3 — ClusterIP services with endpoint selection + affinity)."""

from __future__ import annotations

import random

from ..apis.controlplane import PROTO_TCP, PROTO_UDP
from ..apis.service import Endpoint, ServiceEntry
from ..utils import ip as iputil


def gen_services(
    n_services: int,
    pod_ips: list[int],
    *,
    max_endpoints: int = 8,
    affinity_fraction: float = 0.3,
    no_ep_fraction: float = 0.02,
    seed: int = 0,
) -> list[ServiceEntry]:
    rng = random.Random(seed)
    out: list[ServiceEntry] = []
    for i in range(n_services):
        # Service CIDR analog: 10.96.0.0/12-style frontend space, disjoint
        # from the pod CIDRs used by simulator.genpolicy.
        ip = f"10.{96 + (i // 65536)}.{(i // 256) % 256}.{i % 256}"
        proto = PROTO_TCP if rng.random() < 0.9 else PROTO_UDP
        port = rng.choice([80, 443, 8080, 9090, 5432, rng.randrange(1024, 32768)])
        if rng.random() < no_ep_fraction:
            eps = []
        else:
            n_ep = rng.randrange(1, max_endpoints + 1)
            eps = [
                Endpoint(ip=iputil.u32_to_ip(rng.choice(pod_ips)), port=rng.choice([8080, 80, 9376]))
                for _ in range(n_ep)
            ]
        out.append(
            ServiceEntry(
                cluster_ip=ip,
                port=port,
                protocol=proto,
                endpoints=eps,
                affinity_timeout_s=300 if rng.random() < affinity_fraction else 0,
                name=f"svc-{i}",
                namespace=f"ns-{i % 32}",
            )
        )
    return out
