from .genpolicy import SyntheticCluster, gen_cluster  # noqa: F401
from .genservice import gen_services  # noqa: F401
from .traffic import gen_traffic  # noqa: F401
