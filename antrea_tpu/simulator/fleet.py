"""Fake-agent fleet: scale-testing the controller's watch fan-out.

The analog of /root/reference/cmd/antrea-agent-simulator
(simulator.go:15-18; docs/antrea-agent-simulator.md): watch-only fake
agents deployed at scale to stress the controller's dissemination plane —
they subscribe like real agents, track what they receive, and never touch a
dataplane.  BASELINE.json names this as the CPU-reference driver.

Each FakeAgent holds a queued watcher on the RamStore under its node name
and maintains the same local object tables a real AgentPolicyController
would, so fleet-wide assertions can check span filtering (an agent sees a
policy iff the policy spans its node) and fan-out cost (events delivered
vs objects changed)."""

from __future__ import annotations

from ..controller.networkpolicy import WatchEvent


class FakeAgent:
    def __init__(self, store, node: str, status_reporter=None):
        self.node = node
        self._watcher = store.watch_queue(node)
        self.policies: dict[str, object] = {}
        self.address_groups: dict[str, object] = {}
        self.applied_to_groups: dict[str, object] = {}
        self.events_seen = 0
        # Realization-status reporting (same callable contract as
        # AgentPolicyController): a fake agent "realizes" a policy the
        # moment it lands in its table, so a fleet agent that has NOT been
        # pumped is exactly a lagging node in the status aggregation.
        self._status_reporter = status_reporter

    def pump(self) -> int:
        """Drain pending events into the local tables; -> events consumed."""
        n = 0
        for ev in self._watcher.drain():
            self._apply(ev)
            n += 1
        self.events_seen += n
        if n and self._status_reporter is not None:
            self._status_reporter(self.node, self.realized_generations())
        return n

    def realized_generations(self) -> dict:
        return {
            uid: getattr(p, "generation", 0)
            for uid, p in self.policies.items()
        }

    def _apply(self, ev: WatchEvent) -> None:
        table = {
            "NetworkPolicy": self.policies,
            "AddressGroup": self.address_groups,
            "AppliedToGroup": self.applied_to_groups,
        }[ev.obj_type]
        if ev.kind == "DELETED":
            table.pop(ev.name, None)
        else:
            table[ev.name] = ev.obj

    def stop(self) -> None:
        self._watcher.stop()


class FakeAgentFleet:
    def __init__(self, store, nodes: list[str], status_reporter=None):
        self.agents = {
            n: FakeAgent(store, n, status_reporter=status_reporter)
            for n in nodes
        }

    def pump(self) -> int:
        return sum(a.pump() for a in self.agents.values())

    def total_events(self) -> int:
        return sum(a.events_seen for a in self.agents.values())

    def policies_on(self, node: str) -> set:
        return set(self.agents[node].policies)

    def stop(self) -> None:
        for a in self.agents.values():
            a.stop()
