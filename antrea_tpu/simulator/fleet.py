"""Fake-agent fleet: scale-testing the controller's watch fan-out.

The analog of /root/reference/cmd/antrea-agent-simulator
(simulator.go:15-18; docs/antrea-agent-simulator.md): watch-only fake
agents deployed at scale to stress the controller's dissemination plane —
they subscribe like real agents, track what they receive, and never touch a
dataplane.  BASELINE.json names this as the CPU-reference driver.

Each agent maintains the same local object tables a real
AgentPolicyController would, so fleet-wide assertions can check span
filtering (an agent sees a policy iff the policy spans its node) and
fan-out cost (events delivered vs objects changed).

Transports — the reference has exactly ONE dissemination path, the
authenticated network apiserver (apiserver.go:97-99), and the fleet's
primary mode mirrors it:

  * ``transport="netwire"`` — each agent is a real mTLS TCP client of a
    DisseminationServer (dissemination/netwire.py): events arrive over
    sockets, realization statuses flow back over the same channel.  This
    is the production-shaped path.
  * ``transport="inproc"`` — direct RamStore watchers, a fallback for
    pure fan-out unit tests where socket setup cost would dominate.
"""

from __future__ import annotations

import time
from typing import Optional

from ..controller.networkpolicy import WatchEvent
from ..dissemination.netwire import ReconnectingClient
from ..observability.metrics import Histogram


class _AgentTables:
    """Shared local-object-table logic (the watch-consumer half every
    agent flavor reuses — one _apply, one realization view, one resync
    window for the server's re-list protocol)."""

    def _init_tables(self) -> None:
        self.policies: dict[str, object] = {}
        self.address_groups: dict[str, object] = {}
        self.applied_to_groups: dict[str, object] = {}
        self.events_seen = 0
        self.resyncs_seen = 0
        self._in_resync = False
        self._resync_seen: set = set()
        # Realization latency (PR 8 span plumbing, the fleet's half of
        # ROADMAP item 3's "p99 < 1s at 10k agents" target): a fake agent
        # realizes an object the moment it lands in its table, so the
        # span is controller-commit (WatchEvent.ts, stamped by
        # RamStore.apply / carried over the wire) -> table apply.
        # Unstamped events (resync replays) are excluded and METERED,
        # never guessed into the histogram.
        self.realization_hist = Histogram()
        self.realization_unstamped = 0

    def _observe_realization(self, ev: WatchEvent) -> None:
        if ev.ts:
            self.realization_hist.observe(
                max(0.0, time.monotonic() - ev.ts))
        else:
            self.realization_unstamped += 1

    def realized_generations(self) -> dict:
        return {
            uid: getattr(p, "generation", 0)
            for uid, p in self.policies.items()
        }

    def _tables(self):
        return (
            ("NetworkPolicy", self.policies),
            ("AddressGroup", self.address_groups),
            ("AppliedToGroup", self.applied_to_groups),
        )

    def _apply(self, ev: WatchEvent) -> None:
        self._observe_realization(ev)
        table = dict(self._tables())[ev.obj_type]
        if ev.kind == "DELETED":
            table.pop(ev.name, None)
            if self._in_resync:
                self._resync_seen.discard((ev.obj_type, ev.name))
        else:
            table[ev.name] = ev.obj
            if self._in_resync:
                self._resync_seen.add((ev.obj_type, ev.name))

    def _apply_ctl(self, kind: str) -> None:
        """Resync markers bracket a full re-list: on resync_end anything
        not re-delivered inside the window is stale and dropped (state
        that changed while this agent was disconnected)."""
        if kind == "resync_begin":
            self._in_resync = True
            self._resync_seen = set()
        elif kind == "resync_end" and self._in_resync:
            for obj_type, table in self._tables():
                for name in [n for n in table
                             if (obj_type, n) not in self._resync_seen]:
                    del table[name]
            self._in_resync = False
            self.resyncs_seen += 1


class FakeAgent(_AgentTables):
    def __init__(self, store, node: str, status_reporter=None, *,
                 max_pending=None):
        self.node = node
        self._store = store
        self._watcher = store.watch_queue(node, max_pending=max_pending)
        self._init_tables()
        # Realization-status reporting (same callable contract as
        # AgentPolicyController): a fake agent "realizes" a policy the
        # moment it lands in its table, so a fleet agent that has NOT been
        # pumped is exactly a lagging node in the status aggregation.
        self._status_reporter = status_reporter

    def pump(self) -> int:
        """Drain pending events into the local tables; -> events consumed.
        A watcher that overflowed its bounded queue gets the full re-list
        (store.resync) with the same retract-stale semantics as the wire."""
        n = 0
        if self._watcher.needs_resync:
            self._apply_ctl("resync_begin")
            for ev in self._store.resync(self._watcher):
                self._apply(ev)
                n += 1
            self._apply_ctl("resync_end")
        for ev in self._watcher.drain():
            self._apply(ev)
            n += 1
        self.events_seen += n
        if n and self._status_reporter is not None:
            self._status_reporter(self.node, self.realized_generations())
        return n

    def stop(self) -> None:
        self._watcher.stop()


class NetFakeAgent(_AgentTables, ReconnectingClient):
    """Watch-only fake agent over the REAL mTLS wire: a TLS-verified
    client of DisseminationServer that maintains the same tables and
    reports realization over the same socket (netwire.NetAgent minus the
    dataplane — the agent-simulator over the production transport).

    Same failure model as NetAgent BY CONSTRUCTION: the dial / dead-socket
    / backoff-reconnect lifecycle is the shared ReconnectingClient; the
    server's resync markers drive retract-stale reconciliation on
    re-handshake."""

    def __init__(self, node: str, address, certdir: str, *,
                 reconnect: bool = True, backoff=None, fault_wrap=None):
        self._init_tables()
        self._init_wire(node, address, certdir,
                        reconnect=reconnect, backoff=backoff,
                        fault_wrap=fault_wrap)

    # Short first-wait: FakeAgentFleet.pump() ships events BEFORE draining
    # agents, so loopback frames are already buffered — a long per-agent
    # select would make an idle fleet pump O(agents * wait).
    def pump(self, wait: float = 0.05) -> int:
        import ssl

        from ..dissemination import serde

        if self._sock is None and not self._try_reconnect():
            return 0
        n = 0
        try:
            frames = self._conn.recv_ready(first_wait=wait)
        except (OSError, ssl.SSLError, ValueError):
            self._mark_dead()
            return 0
        for frame in frames:
            if "ev" in frame:
                self._apply(serde.decode_event(frame["ev"]))
                n += 1
            elif "ctl" in frame:
                self._apply_ctl(frame["ctl"])
        self.events_seen += n
        if self._conn.closed:
            self._mark_dead()
            return n
        if n:
            # Realization report upstream over the SAME TLS channel (the
            # UpdateStatus RPC analog); the server's next pump() feeds it
            # into the StatusAggregator.
            try:
                self._sock.setblocking(True)
                self._conn.send({"status": self.realized_generations()})
                self._sock.setblocking(False)
            except (OSError, ssl.SSLError):
                self._mark_dead()
        return n

    def stop(self) -> None:
        self.close()  # the fleet's uniform agent-stop verb


class FakeAgentFleet:
    """Fleet over either transport.  netwire mode needs a live
    DisseminationServer (events + statuses ride its sockets; pass its
    certdir); inproc mode needs the RamStore."""

    def __init__(self, store, nodes: list[str], status_reporter=None, *,
                 transport: str = "inproc", server=None, certdir: str = "",
                 max_pending=None, fault_plan=None, backoff_factory=None):
        self.transport = transport
        self._server = server
        if transport == "netwire":
            if server is None or not certdir:
                raise ValueError(
                    "netwire fleet needs server= (DisseminationServer) "
                    "and certdir="
                )
            if status_reporter is not None:
                raise ValueError(
                    "status_reporter is an inproc-transport hook; netwire "
                    "statuses flow to the server's StatusAggregator over "
                    "the sockets"
                )

            def _wrap(node):
                # Chaos hook: interpose FaultySocket per agent so the plan's
                # {node}.send / {node}.recv sites fire on the live fleet.
                if fault_plan is None:
                    return None
                from ..dissemination.faults import FaultySocket
                return lambda sock, _n=node: FaultySocket(
                    sock, fault_plan, _n)

            self.agents = {
                n: NetFakeAgent(
                    n, server.address, certdir,
                    backoff=backoff_factory(n) if backoff_factory else None,
                    fault_wrap=_wrap(n))
                for n in nodes
            }
            # TLS bring-up is serial per agent: scale the registration
            # deadline with fleet size (soaks run 10^2-10^4 agents).
            server.wait_connected(len(nodes),
                                  timeout=max(5.0, 0.05 * len(nodes)))
        elif transport == "inproc":
            self.agents = {
                n: FakeAgent(store, n, status_reporter=status_reporter,
                             max_pending=max_pending)
                for n in nodes
            }
        else:
            raise ValueError(f"unknown fleet transport {transport!r}")

    def pump(self) -> int:
        """One dissemination round; -> events consumed fleet-wide.

        netwire: ship queued events down every socket, then ONE bounded
        select across the whole fleet picks the agents with data (a
        serial per-agent wait would make an idle pump O(agents * wait) —
        the same discipline as DisseminationServer.pump) and only those
        block-drain; finally consume the statuses they sent back."""
        if self.transport == "netwire":
            import select

            self._server.pump()
            # Disconnected agents (backoff window) have _sock=None: they
            # must not enter the select set (None is unselectable) — their
            # pump() below is the re-dial attempt.
            socks = {a._sock: a for a in self.agents.values()
                     if a._sock is not None}
            try:
                ready, _, _ = select.select(list(socks), [], [], 0.2)
            except (OSError, ValueError):
                ready = list(socks)
            n = 0
            for a in self.agents.values():
                if a._sock is not None and (
                        a._sock in ready or a._conn._buf
                        or getattr(a._sock, "pending", lambda: 0)()):
                    n += a.pump()
                else:
                    n += a.pump(wait=0.0)  # drain/reconnect-only, no wait
            self._server.pump()  # consume the freshly-sent status frames
            return n
        return sum(a.pump() for a in self.agents.values())

    def total_events(self) -> int:
        return sum(a.events_seen for a in self.agents.values())

    def realization_hist(self) -> Histogram:
        """Fleet-wide realization-latency histogram (per-agent bucket
        counts folded into one bucket space)."""
        merged = Histogram()
        for a in self.agents.values():
            merged.merge(a.realization_hist)
        return merged

    def realization_p99_s(self) -> float:
        """Fleet-wide p99 of controller-commit -> agent-realized latency
        — the measurable form of ROADMAP item 3's soak target (upper-
        bound bucket estimate; 0.0 before any stamped event)."""
        return self.realization_hist().quantile(0.99)

    def realization_unstamped_total(self) -> int:
        return sum(a.realization_unstamped for a in self.agents.values())

    def policies_on(self, node: str) -> set:
        return set(self.agents[node].policies)

    def queue_stats(self) -> dict:
        """Per-node watcher depth/overflow/coalesce view, transport-blind:
        netwire reads the server's dissemination_stats(); inproc reads the
        store watchers directly.  The storm soak polls this every cycle to
        assert boundedness."""
        if self.transport == "netwire":
            return self._server.dissemination_stats()
        watchers = {
            n: {
                "pending": a._watcher.pending(),
                "overflows": a._watcher.overflows,
                "coalesced": a._watcher.coalesced,
                "needs_resync": a._watcher.needs_resync,
            }
            for n, a in self.agents.items()
        }
        return {
            "watchers": watchers,
            "resyncs_total": sum(a.resyncs_seen
                                 for a in self.agents.values()),
            "reconnects_total": 0,
            "resync_chunks_total": 0,
            "resyncs_inflight": 0,
            "resyncs_shed_total": 0,
            "coalesced_total": sum(w["coalesced"]
                                   for w in watchers.values()),
        }

    def resyncs_seen_total(self) -> int:
        return sum(a.resyncs_seen for a in self.agents.values())

    def stop(self) -> None:
        for a in self.agents.values():
            a.stop()


# -- policy-churn storm soak -------------------------------------------------


def _storm_policy(uid: str, cidr: str, priority: float = 5.0):
    """One storm policy: applied to app=web (so its span covers every node
    hosting a web pod — the soak worlds place one per node), denying one
    rotating ip_block.  Rewrites churn the cidr: same key, new payload."""
    from ..apis import controlplane as cp
    from ..apis import crd

    return crd.AntreaNetworkPolicy(
        uid=uid, name=uid, namespace="", tier_priority=250,
        priority=priority,
        applied_to=[crd.AntreaAppliedTo(
            pod_selector=crd.LabelSelector.make({"app": "web"}),
            ns_selector=crd.LabelSelector.make())],
        rules=[crd.AntreaNPRule(
            direction=cp.Direction.IN, action=cp.RuleAction.DROP,
            peers=[crd.AntreaPeer(ip_block=crd.IPBlock(cidr))])],
    )


def fleet_converged(ctl, fleet, nodes) -> bool:
    """Span-exact convergence against the controller's policy_set_for_node
    oracle: per node, the agent's uid/group-name sets AND per-policy
    generations match (generation parity pins latest-wins coalescing —
    a stale buffered payload would show as a lagging generation)."""
    for node in nodes:
        want = ctl.policy_set_for_node(node)
        a = fleet.agents[node]
        if {p.uid: getattr(p, "generation", 0) for p in want.policies} != {
                u: getattr(p, "generation", 0)
                for u, p in a.policies.items()}:
            return False
        if set(a.address_groups) != set(want.address_groups):
            return False
        if set(a.applied_to_groups) != set(want.applied_to_groups):
            return False
    return True


def run_churn_storm(ctl, fleet, nodes, *, rounds: int, churn: int,
                    rewrites: Optional[int] = None,
                    cap: Optional[int] = None,
                    resync_concurrency: Optional[int] = None,
                    max_cycles: int = 400) -> dict:
    """Drive `rounds` policy-churn storms through a live fleet and pump to
    span-exact convergence after each, asserting boundedness EVERY cycle.

    One round = `churn` upserts across DISTINCT policy uids (distinct
    watcher-queue keys — when churn > the watcher cap this forces a
    fleet-wide overflow, the designed-to-kill case) followed by `rewrites`
    rewrites of ONE policy (same-key churn a coalescing queue must absorb
    in one slot).  After injecting, the fleet pumps until every node in
    `nodes` matches the policy_set_for_node oracle; each cycle asserts
    that no watcher's pending exceeds `cap` and that the server never runs
    more than `resync_concurrency` resync cursors at once.

    -> meters dict (cycle counts, coalesce/overflow/resync/chunk/shed
    totals, realization p99) for the bench JSON line / test assertions."""
    rewrites = churn * 4 if rewrites is None else rewrites
    meters = {
        "rounds": rounds, "churn": churn, "rewrites": rewrites,
        "cycles": 0, "max_pending_seen": 0, "max_resyncs_inflight": 0,
        "round_cycles": [],
    }
    for r in range(rounds):
        for k in range(churn):
            ctl.upsert_antrea_policy(_storm_policy(
                f"storm-{k}", f"198.{(r + 1) % 8}.{k % 250}.0/24"))
        for j in range(rewrites):
            ctl.upsert_antrea_policy(_storm_policy(
                "storm-0", f"203.0.{j % 250}.0/24"))
        cycles = 0
        while True:
            fleet.pump()
            cycles += 1
            meters["cycles"] += 1
            qs = fleet.queue_stats()
            pend = max((w["pending"] for w in qs["watchers"].values()),
                       default=0)
            meters["max_pending_seen"] = max(
                meters["max_pending_seen"], pend)
            meters["max_resyncs_inflight"] = max(
                meters["max_resyncs_inflight"], qs["resyncs_inflight"])
            if cap is not None and pend > cap:
                raise AssertionError(
                    f"watcher pending {pend} exceeded cap {cap} "
                    f"(round {r}, cycle {cycles})")
            if (resync_concurrency is not None
                    and qs["resyncs_inflight"] > resync_concurrency):
                raise AssertionError(
                    f"{qs['resyncs_inflight']} resyncs in flight exceeds "
                    f"bound {resync_concurrency} (round {r})")
            if fleet_converged(ctl, fleet, nodes):
                break
            if cycles >= max_cycles:
                raise AssertionError(
                    f"storm round {r} did not converge within "
                    f"{max_cycles} pump cycles")
        meters["round_cycles"].append(cycles)
    qs = fleet.queue_stats()
    meters.update({
        "coalesced_total": qs["coalesced_total"],
        "overflows_total": sum(w["overflows"]
                               for w in qs["watchers"].values()),
        "resyncs_total": qs["resyncs_total"],
        "resync_chunks_total": qs["resync_chunks_total"],
        "resyncs_shed_total": qs["resyncs_shed_total"],
        "reconnects_total": qs["reconnects_total"],
        "agent_resyncs_seen": fleet.resyncs_seen_total(),
        "events_total": fleet.total_events(),
        "realization_p99_s": fleet.realization_p99_s(),
        "realization_unstamped_total": fleet.realization_unstamped_total(),
    })
    return meters
