"""Scalar reference interpreter: packet -> verdict.

This is the executable *specification* of the datapath: it walks one packet
through the same decision procedure the reference's OVS pipeline implements
with flow tables, and every batched TPU kernel must agree with it bit-for-bit
(the verdict-parity requirement in BASELINE.md).

Evaluation order per direction, mirroring the OVS tables
(/root/reference/pkg/agent/openflow/pipeline.go:114-195 and
/root/reference/docs/design/ovs-pipeline.md:1685-1760):

  1. AntreaPolicy{Ingress,Egress}Rule — Antrea-native rules from non-Baseline
     tiers, in (tier priority, policy priority, rule index) order; the first
     matching rule decides: Allow / Drop / Reject are final, Pass falls
     through to the K8s phase.
  2. {Ingress,Egress}Rule — K8s NetworkPolicy allow rules (unordered; any
     match allows), combined with {Ingress,Egress}DefaultRule isolation:
     a pod selected by any K8s NP in this direction is default-deny, so
     "isolated and no allow rule matched" => Drop, final.  K8s isolation
     cannot be overridden by Baseline-tier rules (upstream K8s semantics).
  3. Baseline-tier rules (installed in the DefaultRule tables below the K8s
     default-deny in the reference), first match decides; Pass means "no
     opinion" and falls to:
  4. default Allow.

A packet's final verdict combines the egress evaluation at its source pod and
the ingress evaluation at its destination: any Drop/Reject wins over Allow.
Service DNAT happens *before* policy evaluation (PreRouting stage precedes
EgressSecurity, pipeline.go stages), so callers evaluating post-LB traffic
pass the DNAT-ed destination; the full-pipeline oracle in
antrea_tpu.oracle.pipeline composes that ordering.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Optional

from ..apis.controlplane import (
    PROTO_ICMP,
    PROTO_SCTP,
    PROTO_TCP,
    PROTO_UDP,
    Direction,
    NetworkPolicy,
    NetworkPolicyRule,
    RuleAction,
    Service,
)
from ..compiler.ir import PolicySet, rule_id
from ..packet import Packet


class VerdictCode(enum.IntEnum):
    # Values match the compiled action encoding in compiler/compile.py.
    ALLOW = 0
    DROP = 1
    REJECT = 2


@dataclass(frozen=True)
class DirectionVerdict:
    code: VerdictCode
    rule: Optional[str]  # rule_id of the deciding rule; None = default allow


@dataclass(frozen=True)
class Verdict:
    code: VerdictCode
    egress: DirectionVerdict
    ingress: DirectionVerdict


def _service_matches(svc: Service, pkt: Packet) -> bool:
    if svc.protocol is not None and svc.protocol != pkt.proto:
        return False
    if svc.port is not None and pkt.proto in (PROTO_TCP, PROTO_UDP, PROTO_SCTP):
        hi = svc.end_port if svc.end_port is not None else svc.port
        if not (svc.port <= pkt.dst_port <= hi):
            return False
    if svc.icmp_type is not None and pkt.proto == PROTO_ICMP:
        # ICMP lanes carry (type << 8) | code in dst_port (the datapath
        # convention — Service.ICMPType/ICMPCode, types.go:311).
        if (pkt.dst_port >> 8) != svc.icmp_type:
            return False
        if svc.icmp_code is not None and (pkt.dst_port & 0xFF) != svc.icmp_code:
            return False
    return True


class Oracle:
    def __init__(self, ps: PolicySet):
        from ..compiler.ir import resolve_named_ports

        # Named ports resolve through the SAME pass the compiler uses —
        # twin parity on named-port semantics by construction.
        self.ps = resolve_named_ports(ps)
        # An Oracle treats its PolicySet as immutable (every consumer —
        # PipelineOracle.update, the commit-plane canary, parity suites —
        # builds a fresh Oracle on change), so membership material is
        # resolved once per instance instead of per classify: batch
        # consumers (the canary probes every bundle commit) would otherwise
        # re-sort the rule set and re-merge every group's ranges per
        # packet.  The cached forms preserve the PolicySet helpers'
        # semantics exactly (same ranges()/ip_to_key comparisons).
        self._ordered_cache: dict = {}  # (direction, baseline) -> rules
        self._group_ranges: dict = {}  # address-group name -> merged ranges
        self._applied_keys: dict = {}  # appliedTo-group name -> member keys
        self._block_ranges: dict = {}  # (cidr, excepts) -> ranges
        self._isolated_keys: dict = {}  # direction -> isolated pod keys

    # -- memoized membership (same semantics as the PolicySet helpers) -------

    def _ranges_of_group(self, gname: str):
        got = self._group_ranges.get(gname)
        if got is None:
            g = self.ps.address_groups.get(gname)
            got = self._group_ranges[gname] = (
                g.ranges() if g is not None else [])
        return got

    def _keys_of_applied(self, gname: str):
        got = self._applied_keys.get(gname)
        if got is None:
            from ..utils import ip as iputil

            g = self.ps.applied_to_groups.get(gname)
            got = self._applied_keys[gname] = (
                frozenset(iputil.ip_to_key(m.ip) for m in g.members)
                if g is not None else frozenset())
        return got

    def _ranges_of_block(self, block):
        from ..utils import ip as iputil

        key = (block.cidr, tuple(block.excepts))
        got = self._block_ranges.get(key)
        if got is None:
            got = self._block_ranges[key] = iputil.ipblock_to_ranges(
                block.cidr, block.excepts)
        return got

    def _peer_contains(self, peer, ip_key: int) -> bool:
        from ..utils import ip as iputil

        if peer.is_any:
            return True
        for gname in peer.address_groups:
            if iputil.ip_in_ranges(ip_key, self._ranges_of_group(gname)):
                return True
        return any(
            iputil.ip_in_ranges(ip_key, self._ranges_of_block(b))
            for b in peer.ip_blocks
        )

    def _applied_to_contains(self, policy, rule, ip_key: int) -> bool:
        groups = rule.applied_to_groups or policy.applied_to_groups
        return any(ip_key in self._keys_of_applied(g) for g in groups)

    def _k8s_isolated(self, ip_key: int, direction: Direction) -> bool:
        got = self._isolated_keys.get(direction)
        if got is None:
            keys: set = set()
            for p in self.ps.policies:
                if not p.is_k8s or direction not in p.policy_types:
                    continue
                for gname in p.applied_to_groups:
                    keys |= self._keys_of_applied(gname)
            got = self._isolated_keys[direction] = frozenset(keys)
        return ip_key in got

    # -- single rule ---------------------------------------------------------

    def _rule_matches(
        self, policy: NetworkPolicy, rule: NetworkPolicyRule, pkt: Packet,
        svc_ref=None,
    ) -> bool:
        if rule.direction == Direction.IN:
            pod_ip, peer_ip = pkt.dst_ip, pkt.src_ip
        else:
            pod_ip, peer_ip = pkt.src_ip, pkt.dst_ip
        if not self._applied_to_contains(policy, rule, pod_ip):
            return False
        if rule.direction == Direction.OUT and rule.peer.to_services:
            # toServices peer (egress-only): the match rides on the
            # packet's ServiceLB RESOLUTION, not its addresses — the
            # scalar twin of the device's svcref probe (ops/match).
            # svc_ref is the resolved service's (namespace, name), or
            # None when the packet resolved to no service.
            return svc_ref is not None and svc_ref in {
                sr.key for sr in rule.peer.to_services
            }
        if not self._peer_contains(rule.peer, peer_ip):
            return False
        if rule.services and not any(_service_matches(s, pkt) for s in rule.services):
            return False
        return True

    # -- one direction -------------------------------------------------------

    def _ordered_antrea_rules(self, direction: Direction, baseline: bool):
        cached = self._ordered_cache.get((direction, baseline))
        if cached is not None:
            return cached
        out = []
        for p in self.ps.policies:
            if p.is_k8s or p.is_baseline != baseline:
                continue
            for i, r in enumerate(p.rules):
                if r.direction != direction:
                    continue
                out.append(((p.tier_priority, p.priority, r.priority, p.uid), p, i, r))
        out.sort(key=lambda t: t[0])
        self._ordered_cache[(direction, baseline)] = out
        return out

    def evaluate_direction(self, pkt: Packet, direction: Direction,
                           svc_ref=None) -> DirectionVerdict:
        # Phase 1: Antrea-native, non-Baseline tiers.
        passed = False
        for _, p, i, r in self._ordered_antrea_rules(direction, baseline=False):
            if self._rule_matches(p, r, pkt, svc_ref):
                if r.action == RuleAction.PASS:
                    passed = True
                    break
                code = {
                    RuleAction.ALLOW: VerdictCode.ALLOW,
                    RuleAction.DROP: VerdictCode.DROP,
                    RuleAction.REJECT: VerdictCode.REJECT,
                }[r.action]
                return DirectionVerdict(code, rule_id(p, i))

        # Phase 2: K8s NetworkPolicies (allow rules + isolation default-deny).
        pod_ip = pkt.dst_ip if direction == Direction.IN else pkt.src_ip
        isolated = self._k8s_isolated(pod_ip, direction)
        if isolated:
            for p in self.ps.policies:
                if not p.is_k8s:
                    continue
                for i, r in enumerate(p.rules):
                    if r.direction == direction and self._rule_matches(p, r, pkt):
                        return DirectionVerdict(VerdictCode.ALLOW, rule_id(p, i))
            return DirectionVerdict(VerdictCode.DROP, None)
        del passed  # Pass into an empty K8s phase falls through to baseline.

        # Phase 3: Baseline tier.
        for _, p, i, r in self._ordered_antrea_rules(direction, baseline=True):
            if self._rule_matches(p, r, pkt, svc_ref):
                if r.action == RuleAction.PASS:
                    break
                code = {
                    RuleAction.ALLOW: VerdictCode.ALLOW,
                    RuleAction.DROP: VerdictCode.DROP,
                    RuleAction.REJECT: VerdictCode.REJECT,
                }[r.action]
                return DirectionVerdict(code, rule_id(p, i))

        # Phase 4: default allow.
        return DirectionVerdict(VerdictCode.ALLOW, None)

    # -- full packet ---------------------------------------------------------

    def classify(self, pkt: Packet, svc_ref=None) -> Verdict:
        """svc_ref: the packet's ServiceLB resolution as the resolved
        service's (namespace, name) — None when not service-addressed.
        Consumed only by toServices egress peers."""
        eg = self.evaluate_direction(pkt, Direction.OUT, svc_ref)
        ing = self.evaluate_direction(pkt, Direction.IN, svc_ref)
        if eg.code != VerdictCode.ALLOW:
            final = eg.code
        else:
            final = ing.code
        return Verdict(code=final, egress=eg, ingress=ing)
