"""Scalar full-pipeline oracle: conntrack + service LB + policy.

Extends the policy oracle with the stateful stages, using the SAME hash
functions and the SAME direct-mapped slot discipline as the device pipeline
(models/pipeline.py) so parity is exact, including eviction behavior.

Batch semantics match the device: a batch is "simultaneous arrival" —
lookups see start-of-batch state; commits/learns/refreshes apply afterwards
in batch order (last writer wins on slot collisions).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from ..apis.service import ServiceEntry
from ..compiler.compile import ACT_ALLOW, ACT_REJECT
from ..compiler.ir import PolicySet
from ..ops import hashing
from ..packet import Packet, PacketBatch
from ..utils import ip as iputil
from .interpreter import Oracle


@dataclass
class ScalarOutcome:
    code: int
    est: bool
    svc_idx: int  # -1 none
    dnat_ip: int  # raw u32
    dnat_port: int
    egress_rule: Optional[str]
    ingress_rule: Optional[str]
    committed: bool


class PipelineOracle:
    def __init__(
        self,
        ps: PolicySet,
        services: list[ServiceEntry],
        *,
        conn_slots: int = 1 << 20,
        aff_slots: int = 1 << 18,
        ct_timeout_s: int = 3600,
    ):
        self.oracle = Oracle(ps)
        self.services = services
        self.conn_slots = conn_slots
        self.aff_slots = aff_slots
        self.ct_timeout_s = ct_timeout_s
        self.svc_by_key: dict[tuple[int, int, int], int] = {}
        for i, s in enumerate(services):
            self.svc_by_key[(iputil.ip_to_u32(s.cluster_ip), s.protocol, s.port)] = i
        self.conn: dict[int, dict] = {}
        self.aff: dict[int, dict] = {}

    def _flow_hash(self, p: Packet) -> int:
        return int(
            hashing.flow_hash(
                np.uint32(p.src_ip), np.uint32(p.dst_ip), p.proto, p.src_port, p.dst_port
            )
        )

    def step(self, batch: PacketBatch, now: int) -> list[ScalarOutcome]:
        conn0 = {k: dict(v) for k, v in self.conn.items()}
        aff0 = {k: dict(v) for k, v in self.aff.items()}
        outs: list[ScalarOutcome] = []
        commits: list[tuple[int, dict]] = []
        refreshes: list[int] = []
        learns: list[tuple[int, dict]] = []

        for i in range(batch.size):
            p = batch.packet(i)
            h = self._flow_hash(p)
            slot = h & (self.conn_slots - 1)
            e = conn0.get(slot)
            key = (p.src_ip, p.dst_ip, (p.src_port << 16) | p.dst_port, p.proto)
            est = (
                e is not None
                and e["key"] == key
                and (now - e["ts"]) <= self.ct_timeout_s
            )

            svc_idx = self.svc_by_key.get((p.dst_ip, p.proto, p.dst_port), -1)
            svc = self.services[svc_idx] if svc_idx >= 0 else None
            no_ep = svc is not None and not svc.endpoints

            dnat_ip, dnat_port = p.dst_ip, p.dst_port
            aff_learn: Optional[tuple[int, dict]] = None
            if est:
                dnat_ip, dnat_port = e["dnat_ip"], e["dnat_port"]
            elif svc is not None and not no_ep:
                n_ep = len(svc.endpoints)
                ep_col = (h & 0x7FFFFFFF) % max(1, n_ep)
                if svc.affinity_timeout_s > 0:
                    ah = int(hashing.fnv_mix([np.uint32(p.src_ip), np.uint32(svc_idx)]))
                    aslot = ah & (self.aff_slots - 1)
                    ae = aff0.get(aslot)
                    if (
                        ae is not None
                        and ae["client"] == p.src_ip
                        and ae["svc"] == svc_idx
                        and (now - ae["ts"]) <= svc.affinity_timeout_s
                    ):
                        ep_col = ae["ep"]
                    else:
                        aff_learn = (aslot, {"client": p.src_ip, "svc": svc_idx,
                                             "ep": ep_col, "ts": now})
                ep = svc.endpoints[ep_col]
                dnat_ip, dnat_port = iputil.ip_to_u32(ep.ip), ep.port

            if est:
                outs.append(
                    ScalarOutcome(ACT_ALLOW, True, svc_idx, dnat_ip, dnat_port,
                                  None, None, False)
                )
                refreshes.append(slot)
                continue

            if no_ep:
                outs.append(
                    ScalarOutcome(ACT_REJECT, False, svc_idx, dnat_ip, dnat_port,
                                  None, None, False)
                )
                if aff_learn:
                    learns.append(aff_learn)
                continue

            v = self.oracle.classify(
                Packet(
                    src_ip=p.src_ip,
                    dst_ip=dnat_ip,
                    proto=p.proto,
                    src_port=p.src_port,
                    dst_port=dnat_port,
                )
            )
            committed = v.code == 0
            outs.append(
                ScalarOutcome(
                    int(v.code), False, svc_idx, dnat_ip, dnat_port,
                    v.egress.rule, v.ingress.rule, committed
                )
            )
            if committed:
                commits.append(
                    (slot, {"key": key, "dnat_ip": dnat_ip, "dnat_port": dnat_port,
                            "ts": now})
                )
            if aff_learn:
                learns.append(aff_learn)

        # Apply state mutations in batch order (last writer wins).
        for slot, entry in commits:
            self.conn[slot] = entry
        for slot in refreshes:
            if slot in self.conn:
                self.conn[slot]["ts"] = now
        for aslot, entry in learns:
            self.aff[aslot] = entry
        return outs
