"""Scalar full-pipeline oracle: flow cache + conntrack + service LB + policy.

This is the SPEC for models/pipeline.py — same hash functions, same
direct-mapped slot discipline, same generation semantics — so parity is
exact, including eviction behavior:

  * A unified flow cache keyed by the 5-tuple caches the verdict, DNAT
    resolution and rule attribution of every classified flow (the OVS
    EMC/megaflow-cache analog; the reference's datapath performance rests on
    the same design, docs/design/ovs-pipeline.md conntrack sections).
  * ALLOW entries are conntrack commits: they persist across rule-set
    generations (the ct_state -new+est policy bypass,
    ovs-pipeline.md:1685-1691) and pin their DNAT endpoint and service
    attribution at establishment time.
  * DROP/REJECT entries are tagged with the rule generation; a bundle
    commit (gen bump) invalidates them (megaflow revalidation analog), so
    denied flows are re-evaluated against the new rules.
  * Any hit refreshes the idle timeout.

Batch semantics match the device: a batch is "simultaneous arrival" —
lookups see start-of-batch state; inserts/learns/refreshes apply afterwards
in batch order (last writer wins on slot collisions).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from ..apis.service import ServiceEntry
from ..apis.controlplane import PROTO_TCP
from ..compiler.compile import ACT_ALLOW, ACT_REJECT
from ..models.pipeline import (
    CHANCE_MAX,
    GEN_ETERNAL,
    REJECT_ICMP_UNREACH,
    REJECT_NONE,
    REJECT_TCP_RST,
    _TEARDOWN_FLAGS,
)
from ..compiler.ir import PolicySet
from ..ops import hashing
from ..packet import Packet, PacketBatch
from ..utils import ip as iputil
from .interpreter import Oracle


@dataclass
class ScalarOutcome:
    code: int
    est: bool
    svc_idx: int  # -1 none (LB-program index, see compiler/services.py)
    dnat_ip: int  # raw u32; on reply hits: the UN-DNAT source rewrite
    dnat_port: int
    egress_rule: Optional[str]
    ingress_rule: Optional[str]
    committed: bool
    hit: bool = False  # flow-cache hit (False => slow-path classification)
    reply: bool = False  # reverse-tuple (reply-direction) conntrack hit
    reject_kind: int = 0  # 0 none / 1 tcp-rst / 2 icmp-port-unreachable
    snat: int = 0  # SNAT mark: external frontend under ETP=Cluster
    # DSR delivery mark (ref pipeline.go:145 DSRServiceMark): forward toward
    # the selected endpoint (dnat fields) WITHOUT rewriting the L3 dst and
    # without SNAT; no reply-direction conntrack leg is committed.
    dsr: int = 0
    # Lane excluded by the caller's lane modes (SpoofGuard drop or IGMP
    # punt): handled BEFORE the pipeline — no state touched, not a cache
    # miss either.
    skipped: bool = False
    # Async slow-path mode (datapath/slowpath): the lane missed the cache
    # and was left UNclassified — `code` is the admission policy's
    # provisional verdict; the caller admits the packet to the miss queue.
    pending: bool = False


def _reject_kind(code: int, proto: int) -> int:
    """Scalar twin of models.pipeline.reject_kind_of (ref reject.go) —
    plain conditionals, this runs per packet in the oracle's hot loop."""
    if code != ACT_REJECT:
        return REJECT_NONE
    return REJECT_TCP_RST if proto == PROTO_TCP else REJECT_ICMP_UNREACH


@dataclass
class _LBProgram:
    """One LB program: an endpoint view + affinity.  The scalar twin of the
    compiler's program rows (compiler/services.py): cluster views occupy
    indices 0..len(services)-1, ETP=Local / DSR shadow views follow;
    ETP=Cluster external frontends share the cluster program, with SNAT
    flagged on the FRONTEND entry.  dsr marks a DSR delivery program
    (dedicated per-service view, compiler/services.py prog_dsr)."""

    endpoints: list
    affinity_timeout_s: int
    dsr: bool = False
    # Owning service identity (namespace, name) — the scalar twin of the
    # compiler's prog_svc mapping (toServices peers match on it); None for
    # unnamed services, which cannot be referenced.
    ref: Optional[tuple] = None


def _build_programs(services, node_ips, node_name):
    """-> (programs, frontends {(ip_key, proto, port) -> (prog idx, snat)}).

    Dual-stack: frontends key on COMBINED-keyspace ints (utils/ip.py), so
    v4 and v6 frontends live in one family-agnostic map — the scalar twin
    of the compiler's narrow + lexicographic table split.  Family-purity
    validation mirrors compile_services exactly (metaProxier model)."""
    from ..apis.service import ETP_LOCAL

    node_ips4 = [ip for ip in node_ips if not iputil.is_v6(ip)]
    node_ips6 = [ip for ip in node_ips if iputil.is_v6(ip)]
    progs = [
        _LBProgram(list(s.endpoints), s.affinity_timeout_s,
                   ref=(s.namespace, s.name) if s.name else None)
        for s in services
    ]
    fronts: dict[tuple[int, int, int], tuple[int, int]] = {}

    def add_front(ip_k: int, proto: int, port: int, prog: int, snat: int) -> None:
        key = (ip_k, proto, port)
        if key in fronts:
            # Same observable rule as compile_services: duplicate frontends
            # are a config error, never silent last-writer-wins.
            raise ValueError(
                f"duplicate frontend {iputil.key_to_ip(ip_k)} "
                f"proto {proto} port {port}"
            )
        fronts[key] = (prog, snat)

    for si, svc in enumerate(services):
        fam6 = iputil.is_v6(svc.cluster_ip)
        svc_name = f"{svc.namespace}/{svc.name}" if svc.name else f"svc-{si}"
        for e in svc.endpoints:
            if iputil.is_v6(e.ip) != fam6:
                raise ValueError(
                    f"service {svc_name}: endpoint {e.ip} family differs "
                    f"from cluster IP {svc.cluster_ip} (one ServiceEntry "
                    f"per family, like the reference's per-family proxiers)"
                )
        for ip in svc.external_ips:
            if iputil.is_v6(ip) != fam6:
                raise ValueError(
                    f"service {svc_name}: external IP {ip} family differs "
                    f"from cluster IP {svc.cluster_ip}"
                )
        add_front(iputil.ip_to_key(svc.cluster_ip), svc.protocol, svc.port, si, 0)
        my_node_ips = node_ips6 if fam6 else node_ips4
        has_external = bool(svc.external_ips) or (
            svc.node_port > 0 and my_node_ips
        )
        if not has_external:
            continue
        if svc.external_traffic_policy == ETP_LOCAL:
            ext, ext_snat = len(progs), 0
            progs.append(_LBProgram(
                [e for e in svc.endpoints if e.node == node_name],
                svc.affinity_timeout_s,
                dsr=svc.dsr,
                ref=progs[si].ref,
            ))
        elif svc.dsr:
            # DSR: dedicated program (full endpoint view) carrying the
            # per-program mark; no SNAT (compile_services twin).
            ext, ext_snat = len(progs), 0
            progs.append(_LBProgram(
                list(svc.endpoints), svc.affinity_timeout_s, dsr=True,
                ref=progs[si].ref,
            ))
        else:
            ext, ext_snat = si, 1
        for ip in svc.external_ips:
            add_front(iputil.ip_to_key(ip), svc.protocol, svc.port, ext, ext_snat)
        if svc.node_port > 0:
            for nip in my_node_ips:
                add_front(
                    iputil.ip_to_key(nip), svc.protocol, svc.node_port,
                    ext, ext_snat,
                )
    return progs, fronts


class PipelineOracle:
    def __init__(
        self,
        ps: PolicySet,
        services: list[ServiceEntry],
        *,
        flow_slots: int = 1 << 20,
        aff_slots: int = 1 << 18,
        ct_timeout_s: int = 3600,
        node_ips: list[str] | None = None,
        node_name: str = "",
        ct_syn_timeout_s: int | None = None,
        ct_other_new_s: int | None = None,
        ct_other_est_s: int | None = None,
        dual_stack: bool = False,
        count_flow_stats: bool = False,
        second_chance: bool = False,
    ):
        # Dual-stack mode mirrors the device's wide (10-column) flow-cache
        # keys: addresses hash/compare as 4-word v4-mapped quadruples and
        # v4-mapped v6 twins collapse onto their v4 host (canon_key).
        self.dual_stack = dual_stack
        # Per-entry traffic counters (the device twin's
        # PipelineMeta.count_flow_stats): pkts/octets per direction.
        self.count_flow_stats = count_flow_stats
        self.oracle = Oracle(ps)
        self.flow_slots = flow_slots
        self.aff_slots = aff_slots
        self.ct_timeout_s = ct_timeout_s
        # Per-state conntrack lifetimes, matching PipelineMeta.timeouts:
        # (tcp_syn, tcp_est, other_new, other_est); None = uniform.
        self.ct_timeouts = (
            ct_syn_timeout_s if ct_syn_timeout_s is not None else ct_timeout_s,
            ct_timeout_s,
            ct_other_new_s if ct_other_new_s is not None else ct_timeout_s,
            ct_other_est_s if ct_other_est_s is not None else ct_timeout_s,
        )
        self.node_ips = list(node_ips or [])
        self.node_name = node_name
        self._set_services(services)
        # slot -> {key, code, svc, dnat_ip, dnat_port, ts, gen}; gen None = ALLOW/eternal
        self.flow: dict[int, dict] = {}
        self.aff: dict[int, dict] = {}
        # Live entries overwritten by a DIFFERENT tuple (direct-mapped
        # collision metric; counted sequentially at insert-apply time —
        # within-batch collision accounting is implementation-defined, so
        # this is an operational metric, not a parity field).
        self.evictions = 0
        # Dead rows (idle-expired / stale-gen) reclaimed by drain inserts
        # — the scalar twin of the device's n_reclaim split (counted only
        # when step() runs with reclaim=True, the overlapped drain mode).
        self.reclaims = 0
        # Thrash-resistant replacement (the device twin's second_chance
        # knob, models/pipeline CHANCE_SHIFT): a live CONFIRMED
        # established entry survives colliding inserts while its 2-bit
        # counter is under CHANCE_MAX; its own hit resets the counter.
        self.second_chance = bool(second_chance)
        self.chance_suppressed = 0

    def _set_services(self, services):
        self.services = services
        self.programs, self.svc_by_key = _build_programs(
            services, self.node_ips, self.node_name
        )

    def update(self, ps: PolicySet = None, services: list[ServiceEntry] = None,
               scrub_log: list = None):
        """Control-plane bundle commit: swap rules/services.  The caller
        bumps the device-side gen; here denials are invalidated lazily via
        the stored gen value mismatching.

        scrub_log: the ONLY in-place flow mutation this method performs is
        the vanished-rule attribution scrub below; a caller holding a
        rollback snapshot (the commit plane, oracle_dp._commit_snapshot)
        passes a list and gets (slot, rule_in, rule_out) pre-images
        appended — copy-on-scrub, so the snapshot never clones the cache."""
        if ps is not None:
            self.oracle = Oracle(ps)
            # Attribution follows rule IDENTITY across the bundle (the
            # device twin remaps cached indices by id,
            # TpuflowDatapath._remap_cached_attribution): cached entries
            # whose deciding rule no longer exists lose attribution.
            from ..compiler.ir import rule_id

            live = {
                rule_id(p, i)
                for p in self.oracle.ps.policies
                for i in range(len(p.rules))
            }
            for slot, e in self.flow.items():
                ri, ro = e.get("rule_in"), e.get("rule_out")
                scrub = ((ri is not None and ri not in live)
                         or (ro is not None and ro not in live))
                if not scrub:
                    continue
                if scrub_log is not None:
                    scrub_log.append((slot, ri, ro))
                if ri is not None and ri not in live:
                    e["rule_in"] = None
                if ro is not None and ro not in live:
                    e["rule_out"] = None
        if services is not None:
            self._set_services(services)

    def _k(self, key: int) -> int:
        """Address canonicalization for flow keys: identity in v4-only
        mode; in dual-stack mode the device's wide word form makes a
        v4-mapped v6 address and its v4 host the same key (canon_key)."""
        return iputil.canon_key(key) if self.dual_stack else key

    def _flow_hash(self, p: Packet) -> int:
        return int(self._hash5(p.src_ip, p.dst_ip, p.proto,
                               p.src_port, p.dst_port))

    def _hash5(self, src: int, dst: int, proto: int, sport: int,
               dport: int) -> int:
        if self.dual_stack:
            cols = [np.uint32(w & 0xFFFFFFFF)
                    for w in (*iputil.key_to_flipped_words(src),
                              *iputil.key_to_flipped_words(dst))]
            return int(hashing.flow_hash_wide(cols, proto, sport, dport))
        return int(hashing.flow_hash(
            np.uint32(src), np.uint32(dst), proto, sport, dport
        ))

    def _partner_of(self, e: dict, p: Packet):
        """Partner-direction tuple of a hit entry (the device twin is
        models/pipeline partner_probe — shared by refresh and teardown):
        -> (slot, key, want_rpl)."""
        rpl = e.get("rpl", False)
        t_src = p.dst_ip if rpl else e["dnat_ip"]
        t_dst = e["dnat_ip"] if rpl else p.src_ip
        t_sport = p.dst_port if rpl else e["dnat_port"]
        t_dport = e["dnat_port"] if rpl else p.src_port
        t_h = self._hash5(t_src, t_dst, p.proto, t_sport, t_dport)
        return (
            t_h & (self.flow_slots - 1),
            (self._k(t_src), self._k(t_dst),
             (t_sport << 16) | t_dport, p.proto),
            not rpl,
        )

    def _partner_live(self, flow_view: dict, e: dict, p: Packet):
        """-> verified partner slot or None."""
        slot, key, want_rpl = self._partner_of(e, p)
        e2 = flow_view.get(slot)
        if (
            e2 is not None
            and e2["key"] == key
            and e2["gen"] is None
            and e2.get("rpl", False) == want_rpl
        ):
            return slot
        return None

    def timeout_of(self, e: dict, proto: int) -> int:
        """Per-entry idle timeout (the device twin's entry_timeout): the
        CONFIRMED state + protocol select the kernel-style lifetime."""
        t_syn, t_est, t_onew, t_oest = self.ct_timeouts
        conf = e.get("conf", False)
        if proto == PROTO_TCP:
            return t_est if conf else t_syn
        return t_oest if conf else t_onew

    def lookup(self, flow_view: dict, p: Packet, h: int, now: int, gen_w: int):
        """Read-only flow-cache probe -> (slot, entry-or-None)."""
        slot = h & (self.flow_slots - 1)
        e = flow_view.get(slot)
        key = (self._k(p.src_ip), self._k(p.dst_ip),
               (p.src_port << 16) | p.dst_port, p.proto)
        hit = (
            e is not None
            and e["key"] == key
            and (now - e["ts"]) <= self.timeout_of(e, p.proto)
            and (e["gen"] is None or e["gen"] == gen_w)
        )
        return slot, (e if hit else None)

    def fresh_walk(self, aff_view: dict, p: Packet, h: int, now: int):
        """The slow-path walk (ServiceLB -> DNAT -> classify), read-only.

        -> dict with svc_idx, no_ep, dnat_ip, dnat_port, aff_learn, code,
        plus the classifier's per-direction observations (computed on the
        post-DNAT tuple even for no-endpoint rejects — the what-if a trace
        probe reports; step() discards attribution for those, matching the
        EndpointDNAT-before-policy-tables order).
        """
        svc_idx, front_snat = self.svc_by_key.get(
            (p.dst_ip, p.proto, p.dst_port), (-1, 0)
        )
        prog = self.programs[svc_idx] if svc_idx >= 0 else None
        no_ep = prog is not None and not prog.endpoints

        dnat_ip, dnat_port = p.dst_ip, p.dst_port
        snat = 0
        aff_learn: Optional[tuple[int, dict]] = None
        if prog is not None and not no_ep:
            n_ep = len(prog.endpoints)
            ep_col = (h & 0x7FFFFFFF) % max(1, n_ep)
            if prog.affinity_timeout_s > 0:
                if self.dual_stack:
                    # Wide client hash: 4 words + program, the device's
                    # dual-stack formula (_service_lb) word for word.
                    ah = int(hashing.fnv_mix(
                        [np.uint32(w) for w in iputil.key_to_words(p.src_ip)]
                        + [np.uint32(svc_idx)]
                    ))
                else:
                    ah = int(hashing.fnv_mix(
                        [np.uint32(p.src_ip), np.uint32(svc_idx)]))
                aslot = ah & (self.aff_slots - 1)
                ae = aff_view.get(aslot)
                # ae["ep"] >= n_ep means the endpoint list shrank since the
                # learn: stale — fall through to hash re-select (matches the
                # device's aff_hit staleness guard).  Client identity in
                # canon space: the device compares wide words, under which
                # a v4-mapped v6 client and its v4 host are the same.
                if (
                    ae is not None
                    and ae["client"] == self._k(p.src_ip)
                    and ae["svc"] == svc_idx
                    and ae["ep"] < n_ep
                    and (now - ae["ts"]) <= prog.affinity_timeout_s
                ):
                    ep_col = ae["ep"]
                else:
                    aff_learn = (aslot, {"client": self._k(p.src_ip),
                                         "svc": svc_idx,
                                         "ep": ep_col, "ts": now})
            ep = prog.endpoints[ep_col]
            dnat_ip, dnat_port = iputil.ip_to_key(ep.ip), ep.port
            snat = front_snat

        v = self.oracle.classify(
            Packet(src_ip=p.src_ip, dst_ip=dnat_ip, proto=p.proto,
                   src_port=p.src_port, dst_port=dnat_port),
            # toServices resolution: the owning service identity of the
            # lane's LB program (the device twin's prog_svc gather).
            svc_ref=prog.ref if prog is not None else None,
        )
        code = ACT_REJECT if no_ep else int(v.code)
        return {
            "svc_idx": svc_idx,
            "no_ep": no_ep,
            "dnat_ip": dnat_ip,
            "dnat_port": dnat_port,
            "snat": snat,
            "dsr": 1 if (prog is not None and not no_ep and prog.dsr) else 0,
            "aff_learn": aff_learn,
            "code": code,
            "ingress_code": int(v.ingress.code),
            "ingress_rule": v.ingress.rule,
            "egress_code": int(v.egress.code),
            "egress_rule": v.egress.rule,
        }

    # Lane modes for step(): process normally / SpoofGuard drop (code DROP,
    # nothing touched) / punt to controller (code ALLOW, nothing touched) —
    # the device twin realizes these as the valid mask + kind overrides in
    # models/forwarding._pipeline_step_full.
    LANE_NORMAL = 0
    LANE_SPOOF = 1
    LANE_PUNT = 2

    def step(
        self, batch: PacketBatch, now: int, gen: int = 0, lane_modes=None,
        no_commit=None, flags=None, lens=None, fast_only=None,
        reclaim: bool = False,
    ) -> list[ScalarOutcome]:
        """fast_only (async slow-path mode, datapath/slowpath): when set
        to a verdict code, cache MISSES are not classified — they report
        that provisional code with pending=True and touch no state (the
        caller queues them for a later full-mode drain step).  Hits behave
        exactly as in synchronous mode (refresh/confirm/teardown).

        reclaim (the overlapped drain's fused maintenance, device twin
        meta.drain_reclaim): inserts over DEAD rows — idle-expired per
        the per-state timeout, or stale-generation denials — count as
        `reclaims`, not `evictions` (both classes are already invisible
        to lookups, so overwriting them is reclaimed occupancy)."""
        # The device packs entry generations into GEN_BITS (22) bits, with
        # GEN_ETERNAL reserved for conntrack-committed ALLOW entries; compare
        # against the same wrapped value so spec and device agree across the
        # 2^22-1 commit horizon (the aliasing window — a denial cached
        # exactly 2^22-1 commits ago revalidates — is shared by design).
        gen = gen % GEN_ETERNAL
        flow0 = {k: dict(v) for k, v in self.flow.items()}
        aff0 = {k: dict(v) for k, v in self.aff.items()}
        outs: list[ScalarOutcome] = []
        inserts: list[tuple[int, dict]] = []
        refreshes: list[int] = []
        hit_resets: list[int] = []  # second_chance: hit lanes' own slots
        confirms: list[int] = []
        pref_updates: list[int] = []
        learns: list[tuple[int, dict]] = []
        teardowns: list[int] = []

        from ..compiler.compile import ACT_DROP

        for i in range(batch.size):
            p = batch.packet(i)
            mode = self.LANE_NORMAL if lane_modes is None else lane_modes[i]
            if mode != self.LANE_NORMAL:
                # SpoofGuard-gated or punted lane: handled before the
                # conntrack/policy tables — no lookup, no refresh, no
                # commit (stage order of the reference, framework.go; see
                # models/forwarding.py).  Spoof reports DROP, punt ALLOW
                # (the fast-path default image on the device).
                code = ACT_DROP if mode == self.LANE_SPOOF else ACT_ALLOW
                outs.append(ScalarOutcome(
                    code, False, -1, p.dst_ip, p.dst_port, None, None,
                    False, skipped=True,
                ))
                continue
            h = self._flow_hash(p)
            slot, e = self.lookup(flow0, p, h, now, gen)
            if e is not None:
                est = e["gen"] is None
                rpl_hit = e.get("rpl", False)
                # SNAT mark was pinned into the entry at commit time
                # (ct-mark persistence: later service updates renumbering
                # programs cannot flip an established connection's mark);
                # reply hits un-SNAT via the restored frontend tuple.
                snat = 0 if rpl_hit else e.get("snat", 0)
                # DSR mark was pinned into the entry at commit time, like
                # snat (the device twin's meta3 bit 30): program
                # renumbering cannot flip an established connection's
                # delivery mode.
                dsr = 0 if rpl_hit else e.get("dsr", 0)
                outs.append(
                    ScalarOutcome(
                        e["code"], est, e["svc"], e["dnat_ip"], e["dnat_port"],
                        e["rule_out"], e["rule_in"], False, hit=True,
                        reply=rpl_hit,
                        reject_kind=_reject_kind(e["code"], p.proto),
                        snat=snat, dsr=dsr,
                    )
                )
                refreshes.append(slot)
                hit_resets.append(slot)
                if self.count_flow_stats:
                    # Unbounded Python ints — the scalar twin of the
                    # device's two-limb 64-bit accumulation (the old i32
                    # saturation cap is gone on both engines).
                    ln = 0 if lens is None else max(0, int(lens[i]))
                    live = self.flow.get(slot)
                    if live is not None:
                        live["pkts"] = live.get("pkts", 0) + 1
                        live["octets"] = live.get("octets", 0) + ln
                # SYN_SENT -> ESTABLISHED confirmation (device twin: the
                # CONF_BIT cond in models/pipeline): first reply-direction
                # hit confirms BOTH tuple directions.
                if rpl_hit and not e.get("conf", False):
                    confirms.append(slot)
                    c_slot = self._partner_live(flow0, e, p)
                    if c_slot is not None:
                        confirms.append(c_slot)
                # TCP FIN/RST on an established entry: tear down BOTH tuple
                # directions after this packet's verdict (the conntrack
                # close; conservative vs kernel FIN_WAIT — see the device
                # twin's comment in models/pipeline.py).  Partner verified
                # against start-of-batch state.
                fl = 0 if flags is None else int(flags[i])
                if (est and p.proto == PROTO_TCP
                        and (fl & _TEARDOWN_FLAGS) != 0):
                    teardowns.append(slot)
                    t_slot = self._partner_live(flow0, e, p)
                    if t_slot is not None:
                        teardowns.append(t_slot)
                half = max(1, self.ct_timeout_s // 2)
                if est and (now - e.get("pref", e["ts"])) >= half:
                    # Conntrack refreshes BOTH directions; like the device,
                    # the partner walk is deferred via the entry's pref
                    # stamp (ct_timeout/2 cadence) and the partner entry is
                    # key-verified before the refresh — which also
                    # resurrects an idle-expired partner of a provably live
                    # connection.
                    pref_updates.append(slot)
                    p_slot = self._partner_live(flow0, e, p)
                    if p_slot is not None:
                        refreshes.append(p_slot)
                continue

            if fast_only is not None:
                # Async fast step: the miss is ADMITTED, not classified —
                # provisional verdict, no DNAT, no commit, no learn.
                outs.append(ScalarOutcome(
                    fast_only, False, -1, p.dst_ip, p.dst_port, None, None,
                    False, pending=True,
                ))
                continue

            # ---- slow path: ServiceLB -> classify -> commit ---------------
            w = self.fresh_walk(aff0, p, h, now)
            code = w["code"]
            # No-endpoint reject happens before the policy tables: drop the
            # classifier's what-if attribution.
            rule_in = None if w["no_ep"] else w["ingress_rule"]
            rule_out = None if w["no_ep"] else w["egress_rule"]
            # no_commit lanes (multicast dst): conntrack is bypassed —
            # fresh classification every packet, nothing cached (ref
            # pkg/agent/openflow/multicast.go pipeline skips ct).
            nc = no_commit is not None and bool(no_commit[i])
            committed = code == ACT_ALLOW and not nc
            outs.append(
                ScalarOutcome(code, False, w["svc_idx"], w["dnat_ip"],
                              w["dnat_port"], rule_out, rule_in, committed,
                              reject_kind=_reject_kind(code, p.proto),
                              snat=w["snat"], dsr=w["dsr"])
            )
            if not nc:
                key = (self._k(p.src_ip), self._k(p.dst_ip),
                       (p.src_port << 16) | p.dst_port, p.proto)
                ln = 0 if lens is None else max(0, int(lens[i]))
                inserts.append(
                    (slot, {
                        "key": key, "code": code, "svc": w["svc_idx"],
                        "dnat_ip": w["dnat_ip"], "dnat_port": w["dnat_port"],
                        "ts": now, "pref": now, "snat": w["snat"],
                        "dsr": w["dsr"], "conf": False,
                        "gen": None if committed else gen,
                        "rule_in": rule_in, "rule_out": rule_out,
                        "rpl": False,
                        "pkts": 1 if self.count_flow_stats else 0,
                        "octets": ln if self.count_flow_stats else 0,
                    })
                )
            if committed and not w["dsr"]:
                # Conntrack commits both directions: the reverse-tuple entry
                # is keyed on the post-DNAT tuple with ports swapped
                # (endpoint -> client) and carries the UN-DNAT rewrite (the
                # original frontend) in its dnat fields.  Insert order (fwd
                # then rev, per packet) matches the device's interleaved
                # scatter so eviction races resolve identically.  DSR
                # connections commit NO reply leg (the reply never
                # re-traverses this node; pipeline.go:698-708).
                rev_h = self._hash5(
                    w["dnat_ip"], p.src_ip, p.proto,
                    w["dnat_port"], p.src_port,
                )
                rev_slot = rev_h & (self.flow_slots - 1)
                rev_key = (
                    self._k(w["dnat_ip"]), self._k(p.src_ip),
                    (w["dnat_port"] << 16) | p.src_port, p.proto,
                )
                inserts.append(
                    (rev_slot, {
                        "key": rev_key, "code": code, "svc": w["svc_idx"],
                        "dnat_ip": p.dst_ip, "dnat_port": p.dst_port,
                        "ts": now, "pref": now, "gen": None, "conf": False,
                        "rule_in": rule_in, "rule_out": rule_out,
                        "rpl": True,
                        "pkts": 0, "octets": 0,
                    })
                )
            if w["aff_learn"]:
                learns.append(w["aff_learn"])

        # Apply state mutations in batch order (last writer wins).
        for slot in pref_updates:
            if slot in self.flow:
                self.flow[slot]["pref"] = now
        # Confirmations land BEFORE teardowns/inserts (device order: the
        # CONF meta write precedes key zeroing and slow-path scatters).
        for slot in confirms:
            if slot in self.flow:
                self.flow[slot]["conf"] = True
        # Second-chance hit resets land BEFORE the insert guard reads the
        # counters (device order: the fast-path reset precedes the commit
        # pass's meta read).
        if self.second_chance:
            for slot in hit_resets:
                if slot in self.flow:
                    self.flow[slot]["chance"] = 0
        # Teardowns BEFORE inserts (the device clears keys before the slow
        # path scatters — a miss lane may legitimately re-occupy the slot).
        for slot in teardowns:
            self.flow.pop(slot, None)
        # Second-chance decisions snapshot the counter at pass start (the
        # device evaluates every challenger against the same pre-pass
        # meta and bumps once per slot via the winner mask).
        chance_seen: dict[int, int] = {}
        for slot, entry in inserts:
            old = self.flow.get(slot)
            if old is not None and (
                (old["key"], old.get("rpl", False))
                != (entry["key"], entry.get("rpl", False))
            ):
                if self.second_chance and old["gen"] is None \
                        and old.get("conf", False) \
                        and (now - old["ts"]) <= self.timeout_of(
                            old, old["key"][3]):
                    cnt = chance_seen.get(slot)
                    if cnt is None:
                        cnt = chance_seen[slot] = old.get("chance", 0)
                        if cnt < CHANCE_MAX:
                            old["chance"] = min(CHANCE_MAX, cnt + 1)
                    if cnt < CHANCE_MAX:
                        self.chance_suppressed += 1
                        continue  # challenger stays uncached
                old_dead = reclaim and (
                    (now - old["ts"]) > self.timeout_of(old, old["key"][3])
                    or (old["gen"] is not None and old["gen"] != gen)
                )
                if old_dead:
                    self.reclaims += 1
                else:
                    self.evictions += 1
            self.flow[slot] = entry
        for slot in refreshes:
            if slot in self.flow:
                self.flow[slot]["ts"] = now
        for aslot, entry in learns:
            self.aff[aslot] = entry
        return outs
