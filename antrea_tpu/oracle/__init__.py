from .interpreter import Oracle, DirectionVerdict, Verdict, VerdictCode  # noqa: F401
