"""antctl: the operator CLI.

The analog of the reference's antctl command surface
(/root/reference/pkg/antctl/antctl.go command table; raw commands under
pkg/antctl/raw — traceflow, query, supportbundle): operates on the on-disk
state this build persists (datapath snapshots from datapath/persist.py,
agent filestores) — the way the reference's antctl reads controller/agent
APIs backed by the same state.

Usage (python -m antrea_tpu.antctl ...):
  get networkpolicies  --state DIR        list policies in a snapshot
  get addressgroups    --state DIR
  get appliedtogroups  --state DIR
  get services         --state DIR
  traceflow --state DIR --src IP --dst IP [--proto N] [--sport N] [--dport N]
        ofproto/trace analog: builds a datapath from the snapshot and
        reports the per-stage observations for a crafted probe packet.
  query endpoint --state DIR --namespace NS --pod NAME --ip IP
        endpoint querier over snapshot policies (group membership by ip).
  version

Live-agent mode (the reference's antctl "agent mode" over the localhost
API, docs/design/architecture.md:82-90; server: agent/apiserver.py):
  get {networkpolicies,addressgroups,appliedtogroups,podinterfaces,
       ovsflows,memberlist,featuregates,agentinfo,cache} --server URL
  traceflow --server URL --src IP --dst IP [...]
  metrics --server URL
  audit --server URL [--force] [--now N]
        continuous-revalidator status (GET /audit: cursor position,
        coverage ratio, last divergence); --force triggers a synchronous
        full-cache sweep on the agent before reporting
  maintenance --server URL [--tick] [--now N] [--budget B]
        unified background-plane scheduler state (GET /maintenance:
        per-task runs/budget-spent/deferrals/shed, scheduler lag);
        --tick runs one synchronous budgeted scheduler round first
  failover --server URL [--readmit]
        replica-loss failover state (GET /failover: phase, quarantined
        shard, probe/evacuation/readmission totals, tenant worlds
        pending evacuation); --readmit re-admits a healed replica via
        the certified path
  realization --server URL [--uid POLICY] [--json]
        realization-tracing span table (GET /realization: per-policy
        stage timelines controller-commit -> first live hit); default
        output is a per-span stage table, --json the raw body
  flightrecorder --server URL [--tail N] [--kind EVENT] [--json]
        post-mortem event journal (GET /flightrecorder: drop-oldest ring,
        monotonic seq, tick-clock timestamps); default output is one
        line per event in sequence order, --json the raw body
  telemetry --server URL [--json]
        hot-path telemetry plane (GET /telemetry: counter totals,
        per-scope per-regime latencies, sentinel state)
  serving --server URL [--json]
        serving-batcher state (GET /serving: canonical ladder + flush
        knobs, admission/shed/flush meters, per-world staged depth and
        staging-wait p99)
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np

VERSION = "0.3.0-tpu"

# Verdict code -> name (single copy for this CLI; .get-safe like
# observability/audit.py's map).
_VERDICT = {0: "Allow", 1: "Drop", 2: "Reject"}


def _load(state_dir: str):
    from .datapath import persist

    snap = persist.load_snapshot(state_dir)
    if snap is None:
        raise SystemExit(f"antctl: no readable snapshot in {state_dir}")
    return snap


def _fetch(server: str, path: str) -> str:
    from urllib.error import HTTPError, URLError
    from urllib.request import urlopen

    try:
        with urlopen(server.rstrip("/") + path, timeout=10) as r:
            return r.read().decode()
    except HTTPError as e:
        raise SystemExit(f"antctl: agent returned {e.code} for {path}")
    except (URLError, OSError) as e:
        raise SystemExit(f"antctl: cannot reach agent at {server}: {e}")


def _cmd_get(args) -> int:
    if getattr(args, "server", None):
        if args.kind == "services":
            raise SystemExit(
                "antctl: services is snapshot-only (--state); the live "
                "agent serves the installed frontends via ovsflows/cache"
            )
        # policystatus/controllerinfo are served by the CONTROLLER api
        # server (controller/apiserver.py) — same fetch path, the kind is
        # simply a controller route (realization phases per policy,
        # status_controller.go analog).
        print(json.dumps(json.loads(_fetch(args.server, "/" + args.kind)),
                         indent=2))
        return 0
    if args.state is None:
        raise SystemExit("antctl: get needs --state or --server")
    if args.kind in ("policystatus", "controllerinfo"):
        raise SystemExit(
            f"antctl: {args.kind} is only served live by the controller "
            "api server (--server)"
        )
    if args.kind not in (
        "networkpolicies", "addressgroups", "appliedtogroups", "services"
    ):
        raise SystemExit(f"antctl: {args.kind} is only served live (--server)")
    ps, services, gen = _load(args.state)
    if args.kind == "networkpolicies":
        rows = [
            {
                "uid": p.uid, "name": p.name, "namespace": p.namespace,
                "type": p.type.value, "tierPriority": p.tier_priority,
                "priority": p.priority, "rules": len(p.rules),
            }
            for p in ps.policies
        ]
    elif args.kind == "addressgroups":
        rows = [
            {"name": k, "members": len(g.members), "ipBlocks": len(g.ip_blocks)}
            for k, g in sorted(ps.address_groups.items())
        ]
    elif args.kind == "appliedtogroups":
        rows = [
            {"name": k, "members": len(g.members)}
            for k, g in sorted(ps.applied_to_groups.items())
        ]
    elif args.kind == "services":
        rows = [
            {
                "name": s.name or s.cluster_ip, "clusterIP": s.cluster_ip,
                "port": s.port, "protocol": s.protocol,
                "endpoints": len(s.endpoints), "nodePort": s.node_port,
                "externalIPs": list(s.external_ips),
            }
            for s in services
        ]
    else:  # pragma: no cover - argparse restricts choices
        raise SystemExit(f"unknown kind {args.kind}")
    print(json.dumps({"generation": gen, "items": rows}, indent=2))
    return 0


def _cmd_traceflow(args) -> int:
    from .datapath import OracleDatapath
    from .packet import PacketBatch
    from .utils import ip as iputil

    if args.live:
        # Live-traffic mode (the reference's liveTraffic Traceflow):
        # samples a REAL packet from the node's traffic, so it only works
        # against a live agent.  Unset ports/proto wildcard the filter.
        if not getattr(args, "server", None):
            raise SystemExit("antctl: traceflow --live needs --server "
                             "(live traffic is sampled on the agent)")
        qs = (f"/traceflow?live=1&src={args.src or ''}&dst={args.dst or ''}"
              f"&proto={args.proto if args.proto is not None else 0}"
              f"&sport={args.sport or 0}&dport={args.dport or 0}"
              f"&sampling={args.sampling}&wait={args.wait}"
              + ("&dropped_only=1" if args.dropped_only else ""))
        st = json.loads(_fetch(args.server, qs))
        print(json.dumps(st, indent=2, default=str))
        return 0 if st.get("phase") == "Succeeded" else 1
    if not args.src or not args.dst:
        raise SystemExit("antctl: traceflow needs --src and --dst")
    args.proto = 6 if args.proto is None else args.proto
    args.sport = 40000 if args.sport is None else args.sport
    args.dport = 80 if args.dport is None else args.dport
    if getattr(args, "server", None):
        qs = (f"/traceflow?src={args.src}&dst={args.dst}&proto={args.proto}"
              f"&sport={args.sport}&dport={args.dport}")
        obs = json.loads(_fetch(args.server, qs))
        obs["verdict"] = _VERDICT.get(obs["code"], "?")
        print(json.dumps(obs, indent=2, default=str))
        return 0
    if args.state is None:
        raise SystemExit("antctl: traceflow needs --state or --server")
    ps, services, _gen = _load(args.state)
    dp = OracleDatapath(ps, services, flow_slots=1 << 10, aff_slots=1 << 8)
    batch = PacketBatch(
        src_ip=np.array([iputil.ip_to_u32(args.src)], np.uint32),
        dst_ip=np.array([iputil.ip_to_u32(args.dst)], np.uint32),
        proto=np.array([args.proto], np.int32),
        src_port=np.array([args.sport], np.int32),
        dst_port=np.array([args.dport], np.int32),
    )
    obs = dp.trace(batch, now=0)[0]
    obs["verdict"] = _VERDICT.get(obs["code"], "?")
    obs["dnat_ip"] = iputil.u32_to_ip(obs["dnat_ip"])
    print(json.dumps(obs, indent=2, default=str))
    return 0


def _cmd_supportbundle(args) -> int:
    """Collect a support bundle from persisted state (the antctl
    supportbundle raw command, ref pkg/antctl/raw/supportbundle): rebuilds
    a datapath from the snapshot and tars its observable surfaces."""
    from .datapath import OracleDatapath
    from .observability.supportbundle import collect_bundle

    _load(args.state)  # fail fast with the CLI error on a bad state dir
    # Reconstruct THROUGH the persistence path so the bundle's meta.json
    # carries the snapshot's real generation (cookie round), not 0.
    dp = OracleDatapath(flow_slots=1 << 10, aff_slots=1 << 8,
                        persist_dir=args.state)
    members = collect_bundle(
        dp, args.out, node=args.node, now=0, persist_dir=args.state,
    )
    print(json.dumps({"bundle": args.out, "members": members}, indent=2))
    return 0


def _cmd_check(args) -> int:
    """Installation checkers (ref pkg/antctl/raw/check: post-install
    validation probes run as test pods; here: in-process self-diagnostics
    over the same surfaces).  Exit 0 iff every check passes."""
    import tempfile

    checks: list[tuple[str, str]] = []

    def run(name, fn):
        try:
            fn()
            checks.append((name, "ok"))
        except Exception as e:
            checks.append((name, f"FAIL: {type(e).__name__}: {e}"))

    def chk_native():
        from .native import ConfigStore, native_available

        with tempfile.TemporaryDirectory() as d:
            s = ConfigStore(d + "/c.db")
            s.set("k", b"v")
            s.commit()
            assert s.get("k") == b"v"
            # The check is named native-store: a silent Python-journal
            # fallback must FAIL it, not masquerade as healthy.
            assert native_available(), (
                "native backend unavailable (python fallback active)"
            )

    def chk_datapath_parity():
        import copy

        from .datapath import OracleDatapath, TpuflowDatapath
        from .packet import PacketBatch
        from .simulator import gen_cluster, gen_traffic

        cluster = gen_cluster(40, n_nodes=2, pods_per_node=4, seed=99)
        b = gen_traffic(cluster.pod_ips, 32, n_flows=16, seed=98)
        tpu = TpuflowDatapath(copy.deepcopy(cluster.ps), flow_slots=1 << 10,
                              aff_slots=1 << 8, miss_chunk=32)
        orc = OracleDatapath(copy.deepcopy(cluster.ps), flow_slots=1 << 10,
                             aff_slots=1 << 8)
        ra, rb = tpu.step(b, now=1), orc.step(b, now=1)
        assert ra.code.tolist() == rb.code.tolist()

    def chk_persistence():
        from .datapath import TpuflowDatapath

        with tempfile.TemporaryDirectory() as d:
            dp = TpuflowDatapath(flow_slots=1 << 10, aff_slots=1 << 8,
                                 miss_chunk=32, persist_dir=d)
            g = dp.install_bundle()
            dp2 = TpuflowDatapath(flow_slots=1 << 10, aff_slots=1 << 8,
                                  miss_chunk=32, persist_dir=d)
            assert dp2.generation >= g

    run("native-store", chk_native)
    run("datapath-parity", chk_datapath_parity)
    run("persistence-roundtrip", chk_persistence)
    for name, status in checks:
        print(f"{name}: {status}")
    return 0 if all(s == "ok" for _, s in checks) else 1


def _cmd_audit(args) -> int:
    """Continuous-revalidator status / forced full sweep over the live
    agent API (datapath/audit.py; route GET /audit on agent/apiserver)."""
    path = "/audit"
    if args.force:
        path += f"?force=1&now={args.now}"
    print(json.dumps(json.loads(_fetch(args.server, path)), indent=2))
    return 0


def _cmd_maintenance(args) -> int:
    """Unified maintenance-scheduler status / forced synchronous tick
    over the live agent API (datapath/maintenance.py; route
    GET /maintenance on agent/apiserver)."""
    path = "/maintenance"
    if not args.tick and (args.budget is not None or args.now):
        # A budget/now with no tick would be dropped on the floor while
        # the command prints plain status as if it took effect.
        print("antctl maintenance: --budget/--now require --tick",
              file=sys.stderr)
        return 2
    if args.tick:
        path += "?tick=1"
        if args.now:
            path += f"&now={args.now}"  # 0/unset: the scheduler clock advances itself
        if args.budget is not None:
            path += f"&budget={args.budget}"
    print(json.dumps(json.loads(_fetch(args.server, path)), indent=2))
    return 0


def _cmd_failover(args) -> int:
    """Replica-loss failover status / operator re-admission over the
    live agent API (parallel/failover.py; route GET /failover on
    agent/apiserver).  The body includes `tenants_pending_evacuation`
    — the tenant worlds still serving masked or latched behind the
    fleet topology.  --readmit triggers the certified re-admission: a
    pre-flip heal unmasks, an evacuated replica rejoins via the
    ordinary certified grow-resize — never a blind flip."""
    path = "/failover"
    if args.readmit:
        path += "?readmit=1"
    print(json.dumps(json.loads(_fetch(args.server, path)), indent=2))
    return 0


def _cmd_realization(args) -> int:
    """Realization span timelines over the live agent API
    (observability/tracing.py; route GET /realization)."""
    path = "/realization"
    if args.uid:
        from urllib.parse import quote
        path += f"?uid={quote(args.uid, safe='')}"
    body = json.loads(_fetch(args.server, path))
    if args.json:
        print(json.dumps(body, indent=2))
        return 0
    print(f"spans: pending={body['pending']} "
          f"awaiting_first_hit={body['awaiting_first_hit']} "
          f"closed={body['closed']} "
          f"dropped={body['spans_dropped_total']} "
          f"unstamped={body['unstamped_total']} "
          f"p99_s={body['p99_s']}")
    stages = body["stages"]
    hdr = ["UID", "GEN", "BUNDLE", "STATE", *[s.upper() for s in stages],
           "TOTAL_S"]
    rows = []
    for sp in body["spans"]:
        st = sp.get("stages_s") or {}
        rows.append([
            sp["uid"], str(sp["generation"]),
            str(sp.get("bundle_generation", "-")), sp["state"],
            *[f"{st[s]:.6f}" if s in st else "-" for s in stages],
            f"{sp['total_s']:.6f}" if "total_s" in sp else "-",
        ])
    _print_table(hdr, rows)
    return 0


def _cmd_flightrecorder(args) -> int:
    """Flight-recorder journal over the live agent API
    (observability/flightrec.py; route GET /flightrecorder)."""
    path = "/flightrecorder"
    q = []
    if args.tail is not None:
        q.append(f"tail={args.tail}")
    if args.kind:
        from urllib.parse import quote
        q.append(f"kind={quote(args.kind, safe='')}")
    if q:
        path += "?" + "&".join(q)
    body = json.loads(_fetch(args.server, path))
    if args.json:
        print(json.dumps(body, indent=2))
        return 0
    print(f"journal: seq={body['seq']} retained={body['retained']}/"
          f"{body['capacity']} dropped={body['dropped_total']}")
    rows = []
    for e in body["events"]:
        extra = {k: v for k, v in e.items()
                 if k not in ("seq", "ts", "kind")}
        rows.append([str(e["seq"]), str(e["ts"]), e["kind"],
                     " ".join(f"{k}={v}" for k, v in extra.items())])
    _print_table(["SEQ", "TS", "KIND", "FIELDS"], rows)
    return 0


def _cmd_telemetry(args) -> int:
    """Hot-path telemetry plane over the live agent API
    (observability/telemetry.py; route GET /telemetry)."""
    body = json.loads(_fetch(args.server, "/telemetry"))
    if args.json:
        print(json.dumps(body, indent=2))
        return 0
    print("counters: " + " ".join(
        f"{k}={v}" for k, v in body["counters"].items()))
    print(f"steps={body['steps_total']} sweeps={body['sweeps_total']} "
          f"regressions={body['regressions_total']}")
    rows = []
    for scope, regs in body["regimes"].items():
        for regime, row in regs.items():
            rows.append([scope, regime, str(row["count"]),
                         f"{row['p50_seconds']:.6f}",
                         f"{row['p99_seconds']:.6f}"])
    _print_table(["SCOPE", "REGIME", "STEPS", "P50-S", "P99-S"], rows)
    srows = [
        [regime, str(row["window_samples"]), str(row["baseline_samples"]),
         f"{row['baseline_p99_seconds']:.6f}"]
        for regime, row in body["sentinel"].items()
    ]
    _print_table(["REGIME", "WINDOW", "BASELINE", "BASE-P99-S"], srows)
    return 0


def _cmd_serving(args) -> int:
    """Serving-batcher state over the live agent API
    (serving/batcher.py; route GET /serving)."""
    body = json.loads(_fetch(args.server, "/serving"))
    if args.json:
        print(json.dumps(body, indent=2))
        return 0
    sizes = ",".join(str(s) for s in body["canonical_sizes"])
    print(f"ladder=[{sizes}] flush_depth={body['flush_depth']} "
          f"flush_deadline={body['flush_deadline']} "
          f"ring_slots={body['ring_slots']}")
    print(f"submitted={body['submitted_lanes']} shed={body['shed_lanes']} "
          f"flushed={body['flushed_lanes']} padded={body['padded_lanes']} "
          f"dispatches={body['dispatches']} "
          f"deadline_exceeded={body['deadline_exceeded']}")
    print("flushes: " + " ".join(
        f"{k}={v}" for k, v in sorted(body["flushes"].items())))
    rows = [
        [str(tid), str(row["staged_lanes"]), str(row["flushed_lanes"]),
         str(row["starved"]), f"{row['wait_p99_ticks']:.1f}"]
        for tid, row in body["worlds"].items()
    ]
    _print_table(["TENANT", "STAGED", "FLUSHED", "STARVED", "WAIT-P99-T"],
                 rows)
    return 0


def _print_table(header: list, rows: list) -> None:
    """Fixed-width column table (the reference antctl's output shape)."""
    widths = [len(h) for h in header]
    for r in rows:
        for i, cell in enumerate(r):
            widths[i] = max(widths[i], len(cell))
    for r in [header] + rows:
        print("  ".join(cell.ljust(w) for cell, w in zip(r, widths)).rstrip())


def _cmd_query_endpoint(args) -> int:
    """Snapshot-based endpoint query: membership sets computed by pod IP,
    then the shared policy scan (controller/endpoint_querier.scan_policies
    — the live-index variant is query_endpoint there)."""
    from .controller.endpoint_querier import scan_policies

    ps, _services, _gen = _load(args.state)
    applied_groups = {
        k for k, g in ps.applied_to_groups.items()
        if any(m.ip == args.ip for m in g.members)
    }
    peer_groups = {
        k for k, g in ps.address_groups.items()
        if any(m.ip == args.ip for m in g.members)
    }
    applied, ingress_from, egress_to = scan_policies(
        ps.policies, applied_groups, peer_groups
    )
    print(json.dumps({
        "endpoint": {"namespace": args.namespace, "pod": args.pod, "ip": args.ip},
        "appliedPolicies": [
            {"policy": uid, "rules": rules} for uid, rules in applied
        ],
        "ingressFrom": [{"policy": u, "rule": i} for u, i in ingress_from],
        "egressTo": [{"policy": u, "rule": i} for u, i in egress_to],
    }, indent=2))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="antctl")
    sub = ap.add_subparsers(dest="cmd", required=True)

    g = sub.add_parser("get", help="list objects (snapshot or live agent)")
    g.add_argument("kind", choices=[
        "networkpolicies", "addressgroups", "appliedtogroups", "services",
        "podinterfaces", "ovsflows", "memberlist", "featuregates",
        "agentinfo", "cache",
        # live-CONTROLLER kinds (--server points at a ControllerApiServer):
        "policystatus", "controllerinfo",
    ])
    g.add_argument("--state", help="datapath persist dir")
    g.add_argument("--server", help="live agent API base URL")
    g.set_defaults(fn=_cmd_get)

    m = sub.add_parser("metrics", help="Prometheus metrics from a live agent")
    m.add_argument("--server", required=True)
    m.set_defaults(fn=lambda a: (print(_fetch(a.server, "/metrics"), end=""), 0)[1])

    t = sub.add_parser(
        "traceflow",
        help="trace a crafted probe packet, or sample live traffic (--live)",
    )
    t.add_argument("--state")
    t.add_argument("--server", help="live agent API base URL")
    t.add_argument("--src", default="")
    t.add_argument("--dst", default="")
    # None = per-mode default: probe mode fills 6/40000/80 (a crafted
    # packet needs concrete fields), live mode wildcards unset fields.
    t.add_argument("--proto", type=int, default=None)
    t.add_argument("--sport", type=int, default=None)
    t.add_argument("--dport", type=int, default=None)
    t.add_argument("--live", action="store_true",
                   help="sample a real packet (liveTraffic mode)")
    t.add_argument("--dropped-only", action="store_true", dest="dropped_only",
                   help="live mode: only capture denied packets")
    t.add_argument("--sampling", type=int, default=1,
                   help="live mode: capture the Nth matching packet")
    t.add_argument("--wait", type=float, default=5.0,
                   help="live mode: seconds to wait for a match")
    t.set_defaults(fn=_cmd_traceflow)

    q = sub.add_parser("query", help="query subcommands")
    qsub = q.add_subparsers(dest="what", required=True)
    qe = qsub.add_parser("endpoint")
    qe.add_argument("--state", required=True)
    qe.add_argument("--namespace", default="default")
    qe.add_argument("--pod", default="")
    qe.add_argument("--ip", required=True)
    qe.set_defaults(fn=_cmd_query_endpoint)

    au = sub.add_parser(
        "audit", help="cache-revalidator status / forced full sweep"
    )
    au.add_argument("--server", required=True, help="live agent API base URL")
    au.add_argument("--force", action="store_true",
                    help="run a synchronous full-cache sweep first")
    au.add_argument("--now", type=int, default=0,
                    help="packet-clock seconds for the forced sweep")
    au.set_defaults(fn=_cmd_audit)

    mt = sub.add_parser(
        "maintenance",
        help="background-plane scheduler status / forced tick",
    )
    mt.add_argument("--server", required=True, help="live agent API base URL")
    mt.add_argument("--tick", action="store_true",
                    help="run one synchronous scheduler tick first")
    mt.add_argument("--now", type=int, default=0,
                    help="tick-clock seconds for the forced tick")
    mt.add_argument("--budget", type=int, default=None,
                    help="total budget units for the forced tick")
    mt.set_defaults(fn=_cmd_maintenance)

    fo = sub.add_parser(
        "failover",
        help="replica-loss failover status / certified re-admission",
    )
    fo.add_argument("--server", required=True, help="live agent API base URL")
    fo.add_argument("--readmit", action="store_true",
                    help="re-admit the quarantined replica (certified "
                         "grow-resize; pre-flip heal just unmasks)")
    fo.set_defaults(fn=_cmd_failover)

    rz = sub.add_parser(
        "realization",
        help="per-policy realization span timelines (tracing plane)",
    )
    rz.add_argument("--server", required=True, help="live agent API base URL")
    rz.add_argument("--uid", default="", help="filter to one policy uid")
    rz.add_argument("--json", action="store_true", help="raw JSON body")
    rz.set_defaults(fn=_cmd_realization)

    fr = sub.add_parser(
        "flightrecorder",
        help="post-mortem event journal (flight-recorder plane)",
    )
    fr.add_argument("--server", required=True, help="live agent API base URL")
    fr.add_argument("--tail", type=int, default=None,
                    help="keep only the last N events (after filtering)")
    fr.add_argument("--kind", default="",
                    help="filter by event kind (see EVENT_KINDS)")
    fr.add_argument("--json", action="store_true", help="raw JSON body")
    fr.set_defaults(fn=_cmd_flightrecorder)

    tl = sub.add_parser(
        "telemetry",
        help="hot-path telemetry counters / regime latencies / sentinel",
    )
    tl.add_argument("--server", required=True, help="live agent API base URL")
    tl.add_argument("--json", action="store_true", help="raw JSON body")
    tl.set_defaults(fn=_cmd_telemetry)

    sv = sub.add_parser(
        "serving",
        help="serving-batcher ladder / flush meters / per-world wait p99",
    )
    sv.add_argument("--server", required=True, help="live agent API base URL")
    sv.add_argument("--json", action="store_true", help="raw JSON body")
    sv.set_defaults(fn=_cmd_serving)

    c = sub.add_parser("check", help="installation self-diagnostics")
    c.set_defaults(fn=_cmd_check)

    sb = sub.add_parser("supportbundle", help="collect a diagnostic bundle")
    sb.add_argument("--state", required=True)
    sb.add_argument("--out", required=True, help="output .tar.gz path")
    sb.add_argument("--node", default="")
    sb.set_defaults(fn=_cmd_supportbundle)

    v = sub.add_parser("version")
    v.set_defaults(fn=lambda a: (print(VERSION), 0)[1])

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
