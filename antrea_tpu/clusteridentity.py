"""Cluster identity: a stable cluster UUID minted once, readable forever.

The analog of /root/reference/pkg/clusteridentity: the antrea-controller
creates the `antrea-cluster-identity` ConfigMap with a random UUID on first
boot and every component reads it thereafter (used by multicluster and
telemetry to name the cluster).  Here the identity lives in the native
transactional config store (the OVSDB external-IDs analog) so it survives
restarts.

Concurrency contract: like the reference — where a single controller
replica owns the create (K8s Create-if-absent serializes it) — minting
assumes ONE writer process; the store has no compare-and-swap, so two
processes racing the first boot could each mint a UUID with last-commit-
wins.  The commit-then-re-read below makes a process return the durably
stored value whenever the store can already see the winner, but true
multi-writer first-boot needs the K8s-side create, not this path."""

from __future__ import annotations

import uuid

_KEY = "cluster/identity"


def get_or_create_cluster_identity(store) -> str:
    """-> the cluster UUID string, minting it on first call."""
    raw = store.get(_KEY)
    if raw is not None:
        return raw.decode()
    ident = str(uuid.uuid4())
    store.set(_KEY, ident.encode())
    store.commit()
    # Return what is durably stored, not what we minted — if another
    # writer's commit landed in between, converge on it.
    raw = store.get(_KEY)
    return raw.decode() if raw is not None else ident
