"""Multicluster core: the leader/member resource-exchange pipeline.

The semantic slice of the reference's multicluster module
(/root/reference/multicluster/ — a separate controller-runtime module):

  * ClusterSet (leader + members; multicluster/controllers/multicluster/
    leader/clusterset_controller.go): membership and the exchange pipeline.
  * ServiceExport -> ResourceExport -> ResourceImport conversion
    (leader/resourceexport_controller.go): a member exports a Service; the
    leader merges all clusters' exports of the same namespaced name into
    ONE ResourceImport carrying the union of endpoints.
  * Service import (member/serviceimport): each member materializes the
    import as a local multi-cluster Service (`antrea-mc-<name>`) with a
    ClusterIP from its own MC service range; its endpoints are the OTHER
    clusters' exported endpoints (reaching them rides the cross-cluster
    Geneve tunnel in the reference — here the DNAT target is simply the
    remote pod IP, which the simulator's flat address space routes).
  * ACNP replication (member/acnp replication of leader-distributed
    policies): a ClusterSet-scoped ACNP applies to every member's policy
    controller.
  * LabelIdentity (leader label-identity export + pkg/controller/
    labelidentity): normalized label strings -> cluster-set-wide numeric
    IDs, allocated once per unique label string.

Everything is synchronous in-process calls, like the central NP
controller; the dissemination plane provides the async/wire boundary.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..apis.crd import AntreaNetworkPolicy
from ..apis.service import Endpoint, ServiceEntry

MC_SERVICE_PREFIX = "antrea-mc-"


@dataclass
class ResourceExport:
    """Leader-side record of one member's export (ref ResourceExport CRD)."""

    cluster: str
    namespace: str
    name: str
    service: ServiceEntry  # the exported service spec (incl. endpoints)


@dataclass
class ResourceImport:
    """Merged view the leader disseminates (ref ResourceImport CRD)."""

    namespace: str
    name: str
    port: int
    protocol: int
    # (cluster, Endpoint) pairs — unioned across exporting clusters.
    endpoints: list = field(default_factory=list)
    # Clusters whose export's port/protocol disagrees with the first
    # (cluster-id-ordered) exporter: surfaced, not merged (the reference
    # marks conflicting ResourceExports rather than guessing a winner).
    conflicts: list = field(default_factory=list)


class LabelIdentityIndex:
    """Normalized label string -> stable numeric id (ref
    pkg/controller/labelidentity + multicluster label-identity export).
    IDs are cluster-set-wide: both members resolving the same label string
    get the same id, which stretched policies can match on."""

    def __init__(self):
        self._ids: dict[str, int] = {}

    def id_of(self, namespace_labels: dict, pod_labels: dict) -> int:
        key = "ns:" + ",".join(
            f"{k}={v}" for k, v in sorted(namespace_labels.items())
        ) + ";pod:" + ",".join(f"{k}={v}" for k, v in sorted(pod_labels.items()))
        if key not in self._ids:
            self._ids[key] = len(self._ids) + 1  # 0 reserved (unknown)
        return self._ids[key]


class MemberCluster:
    """One member cluster's MC agent surface: exports local services,
    materializes imports as local Services, receives replicated ACNPs."""

    def __init__(self, cluster_id: str, mc_cidr_prefix: str = "10.96.200"):
        self.cluster_id = cluster_id
        self._mc_cidr_prefix = mc_cidr_prefix
        self._next_ip = 1
        self._free_ips: list[str] = []
        self.local_services: dict[tuple[str, str], ServiceEntry] = {}
        self.imported: dict[tuple[str, str], ServiceEntry] = {}
        self.replicated_policies: dict[str, AntreaNetworkPolicy] = {}
        self._import_ips: dict[tuple[str, str], str] = {}

    # -- member-side API ------------------------------------------------------

    def add_local_service(self, namespace: str, svc: ServiceEntry) -> None:
        self.local_services[(namespace, svc.name)] = svc

    def _alloc_mc_ip(self, key: tuple[str, str]) -> str:
        ip = self._import_ips.get(key)
        if ip is None:
            if self._free_ips:
                ip = self._free_ips.pop()  # retracted imports recycle IPs
            elif self._next_ip <= 254:
                ip = f"{self._mc_cidr_prefix}.{self._next_ip}"
                self._next_ip += 1
            else:  # /24 range: guard like other compile caps
                raise ValueError(
                    f"MC service range {self._mc_cidr_prefix}.0/24 exhausted "
                    f"(254 live imports); widen mc_cidr_prefix"
                )
            self._import_ips[key] = ip
        return ip

    def apply_import(self, ri: ResourceImport) -> Optional[ServiceEntry]:
        """Materialize a ResourceImport as the local antrea-mc-<name>
        Service.  Endpoints: every exporting cluster's endpoints EXCEPT
        this cluster's own (local traffic reaches local pods via the
        ordinary local Service; the MC service is the cross-cluster path,
        ref member/serviceimport controller)."""
        key = (ri.namespace, ri.name)
        eps = [ep for cl, ep in ri.endpoints if cl != self.cluster_id]
        svc = ServiceEntry(
            cluster_ip=self._alloc_mc_ip(key),
            port=ri.port,
            protocol=ri.protocol,
            endpoints=list(eps),
            name=f"{MC_SERVICE_PREFIX}{ri.name}",
            namespace=ri.namespace,
        )
        self.imported[key] = svc
        return svc

    def retract_import(self, namespace: str, name: str) -> None:
        self.imported.pop((namespace, name), None)
        ip = self._import_ips.pop((namespace, name), None)
        if ip is not None:
            self._free_ips.append(ip)  # the ClusterIP returns to the pool

    def apply_replicated_policy(self, anp: AntreaNetworkPolicy) -> None:
        self.replicated_policies[anp.uid] = anp

    def all_services(self) -> list[ServiceEntry]:
        """Local + imported services, the set this member's datapath
        compiles (compiler/services.py input)."""
        return list(self.local_services.values()) + sorted(
            self.imported.values(), key=lambda s: (s.namespace, s.name)
        )


class LeaderController:
    """Leader-side conversion pipeline: ResourceExports in, merged
    ResourceImports + replicated policies out to every member."""

    def __init__(self):
        self._exports: dict[tuple[str, str, str], ResourceExport] = {}
        self._members: dict[str, MemberCluster] = {}
        self._policies: dict[str, AntreaNetworkPolicy] = {}
        self.label_identities = LabelIdentityIndex()

    def join(self, member: MemberCluster) -> None:
        self._members[member.cluster_id] = member
        # Late joiners receive the current state (the reference's initial
        # ResourceImport list + ACNP resync).
        for ri in self._imports().values():
            member.apply_import(ri)
        for anp in self._policies.values():
            member.apply_replicated_policy(anp)

    def leave(self, cluster_id: str) -> None:
        self._members.pop(cluster_id, None)
        # A departed member's exports are stale: GC them (leader stale
        # controller, leader/stale_controller.go).
        gone = [k for k in self._exports if k[0] == cluster_id]
        touched = {(k[1], k[2]) for k in gone}
        for k in gone:
            del self._exports[k]
        self._reconcile(touched)

    # -- export intake --------------------------------------------------------

    def export_service(self, cluster_id: str, namespace: str,
                       svc: ServiceEntry) -> None:
        """A member's ServiceExport arrives (ref ServiceExport CRD ->
        ResourceExport conversion)."""
        self._exports[(cluster_id, namespace, svc.name)] = ResourceExport(
            cluster=cluster_id, namespace=namespace, name=svc.name, service=svc,
        )
        self._reconcile({(namespace, svc.name)})

    def retract_export(self, cluster_id: str, namespace: str, name: str) -> None:
        self._exports.pop((cluster_id, namespace, name), None)
        self._reconcile({(namespace, name)})

    # -- policy replication ---------------------------------------------------

    def replicate_policy(self, anp: AntreaNetworkPolicy) -> None:
        """Distribute a ClusterSet-scoped ACNP to every member."""
        self._policies[anp.uid] = anp
        for m in self._members.values():
            m.apply_replicated_policy(anp)

    # -- conversion -----------------------------------------------------------

    def _imports(self) -> dict[tuple[str, str], ResourceImport]:
        out: dict[tuple[str, str], ResourceImport] = {}
        # Deterministic merge order: cluster id, so the defining exporter
        # (whose port/protocol the import carries) never depends on dict
        # iteration or arrival order.
        for k in sorted(self._exports):
            ex = self._exports[k]
            key = (ex.namespace, ex.name)
            ri = out.get(key)
            if ri is None:
                ri = out[key] = ResourceImport(
                    namespace=ex.namespace, name=ex.name,
                    port=ex.service.port, protocol=ex.service.protocol,
                )
            elif (ex.service.port, ex.service.protocol) != (ri.port, ri.protocol):
                # Spec mismatch: exclude this cluster's endpoints and
                # surface the conflict instead of silently merging.
                ri.conflicts.append(ex.cluster)
                continue
            for ep in ex.service.endpoints:
                ri.endpoints.append((ex.cluster, ep))
        for ri in out.values():
            ri.endpoints.sort(key=lambda ce: (ce[0], ce[1].ip, ce[1].port))
            ri.conflicts.sort()
        return out

    def _reconcile(self, touched: set) -> None:
        imports = self._imports()
        for key in touched:
            ri = imports.get(key)
            for m in self._members.values():
                if ri is None:
                    m.retract_import(*key)
                else:
                    m.apply_import(ri)


@dataclass
class ClusterSet:
    """The ClusterSet wiring: one leader + joined members."""

    leader: LeaderController = field(default_factory=LeaderController)
    members: dict = field(default_factory=dict)

    def add_member(self, cluster_id: str) -> MemberCluster:
        m = MemberCluster(cluster_id)
        self.members[cluster_id] = m
        self.leader.join(m)
        return m

    def remove_member(self, cluster_id: str) -> None:
        """Full departure: leader GCs the member's exports AND the member
        drops its MC-materialized state (the member-side stale controller
        removes antrea-mc services / replicated policies on ClusterSet
        departure)."""
        m = self.members.pop(cluster_id, None)
        self.leader.leave(cluster_id)
        if m is not None:
            m.imported.clear()
            m.replicated_policies.clear()
