"""Multicluster: ClusterSet, service export/import, ACNP replication,
label identities (ref /root/reference/multicluster/)."""

from .core import (
    ClusterSet,
    LabelIdentityIndex,
    LeaderController,
    MemberCluster,
    ResourceExport,
    ResourceImport,
)

__all__ = [
    "ClusterSet",
    "LabelIdentityIndex",
    "LeaderController",
    "MemberCluster",
    "ResourceExport",
    "ResourceImport",
]
