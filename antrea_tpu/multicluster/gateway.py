"""Multicluster Gateway: election, ClusterInfo exchange, datapath routes.

The member-side Gateway path of the reference
(/root/reference/multicluster/controllers/multicluster/member/
gateway_controller.go:57,:80 — the Gateway CR is exported as a ClusterInfo
ResourceExport to the leader; clusterinfo imports of OTHER clusters come
back and pkg/agent/multicluster programs the routes):

  * each member cluster elects ONE gateway node among its agents
    (agent/memberlist consistent hash — the same failover machinery the
    Egress controller uses, so a dead gateway re-elects automatically);
  * the member exports {cluster id, gateway node+IP, pod CIDRs} as
    ClusterInfo; the leader fans every member's ClusterInfo out to every
    OTHER member (clusterinfo_controller.go semantics);
  * each member turns the imported remote ClusterInfos into datapath
    routes (mc_node_routes): on the GATEWAY node, remote-cluster pod
    CIDRs tunnel to the REMOTE gateway IP; on every other node they
    tunnel to the LOCAL gateway (the two-hop cross-cluster path,
    pkg/agent/multicluster/mc_route_controller.go).

Routes are ordinary compiler/topology.NodeRoute entries, so the existing
full-walk kernel forwards cross-cluster traffic (FWD_TUNNEL + peer ip)
with policy applied — no special MC tables.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..agent.memberlist import MemberlistCluster
from ..compiler.topology import NodeRoute


@dataclass(frozen=True)
class ClusterInfo:
    """The ClusterInfo ResourceExport payload (ref mcv1alpha1 ClusterInfo:
    clusterID, gateway infos, podCIDRs/serviceCIDR)."""

    cluster_id: str
    gateway_node: str
    gateway_ip: str  # the address peers tunnel to (GatewayIP)
    pod_cidrs: tuple = ()
    service_cidr: str = ""


class GatewayController:
    """Member-side gateway election + route computation for one cluster.

    Wraps a MemberlistCluster of this member's agent nodes: the consistent
    hash owner of the cluster-id key IS the gateway (deterministic across
    every node's independent computation, and it fails over with
    membership — gateway_controller.go's active-gateway semantics without
    a leader write).
    """

    GATEWAY_KEY = "mc-gateway"

    def __init__(self, cluster_id: str, node_ips: dict):
        """node_ips: {node name: node IP} of this member's agents."""
        self.cluster_id = cluster_id
        self._node_ips = dict(node_ips)
        self._ml = MemberlistCluster(next(iter(node_ips)))
        for n in list(node_ips)[1:]:
            self._ml.join(n)
        # Remote ClusterInfos by cluster id (the member's import cache).
        self._remote: dict[str, ClusterInfo] = {}

    # -- membership / election ----------------------------------------------

    def node_failed(self, node: str) -> None:
        self._ml.leave(node)

    def node_joined(self, node: str, node_ip: str) -> None:
        self._node_ips[node] = node_ip
        self._ml.join(node)

    @property
    def gateway_node(self) -> str:
        gw = self._ml.owner_of(self.GATEWAY_KEY)
        if gw is None:
            raise RuntimeError(
                f"cluster {self.cluster_id}: no live node to elect a gateway"
            )
        return gw

    def cluster_info(self, pod_cidrs, service_cidr: str = "") -> ClusterInfo:
        """This member's ClusterInfo export (the gateway_controller.go
        createResourceExport payload)."""
        gw = self.gateway_node
        return ClusterInfo(
            cluster_id=self.cluster_id,
            gateway_node=gw,
            gateway_ip=self._node_ips[gw],
            pod_cidrs=tuple(pod_cidrs),
            service_cidr=service_cidr,
        )

    # -- imports -> routes ----------------------------------------------------

    def apply_cluster_info(self, info: ClusterInfo) -> None:
        """Import a REMOTE cluster's ClusterInfo (leader fan-out)."""
        if info.cluster_id == self.cluster_id:
            return  # own export reflected back: ignore (ref skips self)
        self._remote[info.cluster_id] = info

    def retract_cluster_info(self, cluster_id: str) -> None:
        self._remote.pop(cluster_id, None)

    def mc_node_routes(self, node: str) -> list:
        """NodeRoute entries THIS node must install for cross-cluster
        reachability (merged into its Topology.remote_nodes by the caller,
        like any noderoute output):

          gateway node  -> remote pod CIDRs via the remote GATEWAY IP
          other nodes   -> remote pod CIDRs via the LOCAL gateway IP
        """
        gw = self.gateway_node
        local_gw_ip = self._node_ips[gw]
        out = []
        for info in sorted(self._remote.values(), key=lambda i: i.cluster_id):
            peer = info.gateway_ip if node == gw else local_gw_ip
            for i, cidr in enumerate(info.pod_cidrs):
                out.append(NodeRoute(
                    name=f"mc-{info.cluster_id}-{i}",
                    node_ip=peer,
                    pod_cidr=cidr,
                ))
        return out


@dataclass
class ClusterInfoExchange:
    """Leader-side ClusterInfo fan-out (ref leader clusterinfo import
    handling): members publish, every OTHER member receives."""

    _infos: dict = field(default_factory=dict)  # cluster id -> ClusterInfo
    _members: dict = field(default_factory=dict)  # cluster id -> GatewayController

    def register(self, gc: GatewayController) -> None:
        self._members[gc.cluster_id] = gc
        # Late joiner receives every existing remote info.
        for info in self._infos.values():
            gc.apply_cluster_info(info)

    def publish(self, info: ClusterInfo) -> None:
        self._infos[info.cluster_id] = info
        for cid, gc in self._members.items():
            if cid != info.cluster_id:
                gc.apply_cluster_info(info)

    def withdraw(self, cluster_id: str) -> None:
        self._infos.pop(cluster_id, None)
        for cid, gc in self._members.items():
            if cid != cluster_id:
                gc.retract_cluster_info(cluster_id)
