"""Serving admission plane — canonical-shape batching in front of the
jitted step dispatch.

`ServingBatcher` stages per-tenant lane submissions in bounded per-world
rings, packs them onto a small declared ladder of pow2 canonical batch
sizes (compile count bounded by rungs x ladder, never by traffic), and
flushes on a depth-OR-deadline policy driven by the maintenance
scheduler's tick clock.  Padded lanes ride the engines' `valid` mask so
padding is HLO-invisible and never mutates flow state.
"""

from .batcher import CANONICAL_SIZES, ServingBatcher

__all__ = ["CANONICAL_SIZES", "ServingBatcher"]
