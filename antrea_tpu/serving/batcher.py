"""Canonical-shape serving batcher (ROADMAP item 3, serving half).

The production gap this closes: `step_tenants` used to partition mixed
batches into per-(rung, traffic-shape) dispatches, so the XLA executable
count tracked whatever batch sizes tenants happened to send — unbounded
— and a one-lane trickle either burned a fresh compile or had no latency
story at all.  The batcher is a pure re-shaping layer in front of the
jitted step:

  * **Admission** — `submit()` appends lanes to a bounded per-world
    staging ring (O(lanes), no dispatch on the submitter's critical
    path).  A full ring sheds the tail, metered, never unbounded memory.
  * **Canonical ladder** — flushes pack staged lanes onto a small
    declared ladder of pow2 batch sizes; a partial chunk pads up to the
    smallest rung that fits and the padding lanes are masked through the
    engines' `valid` discipline (HLO-invisible: padded lanes behave
    exactly like spoof-dropped lanes — no state commit, no miss
    admission, no policy counters).  Compile count is therefore bounded
    by `occupied rungs x len(canonical_sizes)`, never by traffic.
  * **Flush policy** — depth-OR-deadline on the maintenance scheduler's
    tick clock (`FaultClock`-deterministic in tests): a ring flushes
    when it holds `flush_depth` lanes or its oldest lane has aged
    `flush_deadline` ticks.  The deadline knob is the per-tenant p99
    lever, observable on the telemetry plane's `batched` scope.
  * **Fairness** — deficit-round-robin over the staging rings with
    starvation aging (the maintenance-scheduler pattern): due rings bank
    a deficit credit per deferred tick, a ring deferred
    `STARVATION_TICKS` consecutive ticks jumps the queue, and depth-due
    rings always outrank deadline-due ones so a deadline storm cannot
    grow memory (depth-flush dominates).
  * **De-interleave** — results return lane-exactly to submitters
    (verdict fields scattered back per ticket, `n_miss` summed once per
    dispatch), so oracle parity holds regardless of how lanes were
    coalesced.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

import numpy as np

from ..config import ConfigError
from ..observability.flightrec import emit_into
from ..observability.metrics import Histogram
from ..observability.telemetry import classify_regime

# Default pow2 ladder; engines override via the `canonical_sizes` knob.
CANONICAL_SIZES = (16, 64, 256, 1024)

# Consecutive deferred-while-due ticks before a ring jumps the DRR queue
# (mirrors MaintenanceScheduler.STARVATION_TICKS).
STARVATION_TICKS = 8

# Deficit credits are capped so an idle-then-bursty world cannot bank an
# unbounded scheduling advantage.
DEFICIT_CAP = 64

# Tick-unit bounds for the per-world wait histogram (a lane's staging age
# at flush, in maintenance ticks — the p99 the deadline knob moves).
WAIT_TICK_BOUNDS = (0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0)


class _Ring:
    """Bounded staging ring for one world (default world = tenant 0)."""

    __slots__ = ("tid", "segs", "depth", "opened", "starved", "deficit")

    def __init__(self, tid: int) -> None:
        self.tid = tid
        self.segs: list = []  # (tickets ndarray, PacketBatch, submit tick)
        self.depth = 0
        self.opened = 0  # tick the oldest staged lane arrived
        self.starved = 0  # consecutive due-but-deferred ticks
        self.deficit = 0  # banked DRR credits


def _concat_batches(batches):
    """Lane-concatenate PacketBatches (optional columns preserved; a
    column is kept only when present on every segment — submissions to
    one ring share a schema in practice)."""
    if len(batches) == 1:
        return batches[0]
    kw = {}
    for f in dataclasses.fields(batches[0]):
        cols = [getattr(b, f.name) for b in batches]
        if any(c is None for c in cols):
            kw[f.name] = None
        else:
            kw[f.name] = np.concatenate([np.asarray(c) for c in cols])
    return type(batches[0])(**kw)


def _pad_batch(batch, lo: int, n: int, size: int):
    """Slice lanes [lo, lo+n) and pad up to `size` by repeating the last
    real lane (edge padding keeps every column in-domain — the pad lanes
    are masked out via `valid`, so their contents only need to trace)."""
    kw = {}
    for f in dataclasses.fields(batch):
        v = getattr(batch, f.name)
        if v is None:
            kw[f.name] = None
            continue
        v = np.asarray(v)[lo : lo + n]
        if n < size:
            pad = [(0, size - n)] + [(0, 0)] * (v.ndim - 1)
            v = np.pad(v, pad, mode="edge")
        kw[f.name] = v
    return type(batch)(**kw)


class ServingBatcher:
    """Batching admission plane in front of one datapath (see module
    docstring for the policy)."""

    def __init__(
        self,
        dp,
        *,
        canonical_sizes=None,
        flush_depth: Optional[int] = None,
        flush_deadline: int = 4,
        ring_slots: Optional[int] = None,
        results_slots: Optional[int] = None,
    ) -> None:
        sizes = tuple(
            int(s) for s in (CANONICAL_SIZES if canonical_sizes is None else canonical_sizes)
        )
        if not sizes:
            raise ConfigError("canonical_sizes must declare at least one batch size")
        for s in sizes:
            if s <= 0 or (s & (s - 1)) != 0:
                raise ConfigError(
                    f"canonical batch size {s} is not a positive power of two — "
                    "the compile-count bound holds only over a declared pow2 ladder"
                )
        if list(sizes) != sorted(set(sizes)):
            raise ConfigError("canonical_sizes must be strictly ascending")
        self.sizes = sizes
        self.flush_depth = int(flush_depth) if flush_depth is not None else sizes[-1]
        if self.flush_depth <= 0:
            raise ConfigError("flush_depth must be positive")
        self.flush_deadline = int(flush_deadline)
        if self.flush_deadline < 1:
            raise ConfigError("flush_deadline must be >= 1 maintenance tick")
        self.ring_slots = int(ring_slots) if ring_slots is not None else 4 * self.flush_depth
        if self.ring_slots < self.flush_depth:
            raise ConfigError(
                f"ring_slots ({self.ring_slots}) must hold at least one flush_depth "
                f"({self.flush_depth}) of staged lanes"
            )
        self.results_slots = (
            int(results_slots) if results_slots is not None else 4 * self.ring_slots
        )

        self._dp = dp
        self._rings: dict = {}
        self._completed: dict = {}  # ticket -> (StepResult, row) — insertion-ordered
        self._next_ticket = 0
        self._rr_cursor = 0

        # Meters (serving_stats; scraped as the serving metric families
        # registered in observability/metrics.py).
        self.submitted_total = 0
        self.shed_total = 0
        self.flushed_lanes_total = 0
        self.padded_total = 0
        self.dispatches_total = 0
        self.flushes_total = {"depth": 0, "deadline": 0, "forced": 0, "overflow": 0}
        self.deadline_exceeded_total = 0
        self.results_dropped_total = 0
        self._wait_hists: dict = {}  # tid -> Histogram (tick units)

    # -- clock / plumbing ----------------------------------------------------

    def _tick(self) -> int:
        sched = getattr(self._dp, "_maintenance", None)
        return 0 if sched is None else int(sched.clock())

    def _emit(self, kind: str, **fields) -> None:
        emit_into(self._dp, kind, **fields)

    def _ring(self, tid: int) -> _Ring:
        r = self._rings.get(tid)
        if r is None:
            r = self._rings[tid] = _Ring(tid)
        return r

    def _wait_hist(self, tid: int) -> Histogram:
        h = self._wait_hists.get(tid)
        if h is None:
            h = self._wait_hists[tid] = Histogram(bounds=WAIT_TICK_BOUNDS)
        return h

    # -- admission -----------------------------------------------------------

    def submit(self, batch, now: float, *, tenant: int = 0, shed: bool = True) -> np.ndarray:
        """Stage `batch`'s lanes into `tenant`'s ring; returns one ticket
        per lane (-1 = shed).  With shed=False a full ring force-flushes
        inline instead of shedding (the lossless synchronous path
        `step_tenants` uses); with shed=True lanes beyond the ring's
        capacity tail-drop, metered — the bounded-memory contract."""
        tid = int(tenant)
        if tid != 0:
            self._tenants.world(tid)  # raises KeyError on unknown tenants
        # Fold traffic time into the tick clock exactly like step() does:
        # staging ages and the deadline policy must live in the same clock
        # domain as the dispatches that will eventually observe this now.
        sched = getattr(self._dp, "_maintenance", None)
        if sched is not None:
            sched.observe(now)
        ring = self._ring(tid)
        n = batch.size
        tickets = np.full(n, -1, np.int64)
        lo = 0
        while lo < n:
            room = self.ring_slots - ring.depth
            if room <= 0:
                if not shed:
                    self._flush_ring(ring, now, "overflow")
                    continue
                self.shed_total += n - lo
                break
            take = min(room, n - lo)
            tk = np.arange(self._next_ticket, self._next_ticket + take, dtype=np.int64)
            self._next_ticket += take
            tickets[lo : lo + take] = tk
            t = self._tick()
            if ring.depth == 0:
                ring.opened = t
            seg = batch if (lo == 0 and take == n) else _sub(batch, lo, take)
            ring.segs.append((tk, seg, t))
            ring.depth += take
            self.submitted_total += take
            lo += take
        return tickets

    @property
    def _tenants(self):
        reg = getattr(self._dp, "_tenants", None)

        class _Default:
            @staticmethod
            def world(tid):
                raise KeyError(f"unknown tenant id {tid}")

        return reg if reg is not None else _Default

    # -- flush plane ---------------------------------------------------------

    def tick_flush(self, now: float, budget: int) -> int:
        """Maintenance-task body (`serving-flush`): flush due rings in
        DRR order, depth-due before deadline-due, starved rings boosted;
        returns dispatches spent (the scheduler's budget unit)."""
        t = self._tick()
        due = []
        for tid, ring in self._rings.items():
            if ring.depth <= 0:
                continue
            depth_due = ring.depth >= self.flush_depth
            deadline_due = (t - ring.opened) >= self.flush_deadline
            if depth_due or deadline_due:
                ring.deficit = min(ring.deficit + 1, DEFICIT_CAP)
                due.append((tid, ring, depth_due))
        if not due:
            return 0
        n_worlds = max(1, len(self._rings))
        due.sort(
            key=lambda e: (
                0 if e[2] else 1,  # depth-due dominates (memory bound)
                0 if e[1].starved >= STARVATION_TICKS else 1,
                -e[1].deficit,
                (e[0] - self._rr_cursor) % (2 * n_worlds + 1),
            )
        )
        spent = 0
        cap = max(1, int(budget))
        for tid, ring, depth_due in due:
            if spent >= cap:
                ring.starved += 1
                continue
            spent += self._flush_ring(ring, now, "depth" if depth_due else "deadline")
            ring.starved = 0
            ring.deficit = 0
            self._rr_cursor = tid + 1
        return spent

    def flush_all(self, now: float) -> int:
        """Force-flush every non-empty ring (the synchronous
        `step_tenants` path); returns dispatches spent."""
        spent = 0
        for ring in self._rings.values():
            if ring.depth > 0:
                spent += self._flush_ring(ring, now, "forced")
        return spent

    def _flush_ring(self, ring: _Ring, now: float, reason: str) -> int:
        t = self._tick()
        age = t - ring.opened
        segs, ring.segs, ring.depth = ring.segs, [], 0
        tickets = np.concatenate([s[0] for s in segs])
        waits = np.concatenate(
            [np.full(s[0].size, t - s[2], np.int64) for s in segs]
        )
        batch = _concat_batches([s[1] for s in segs])
        tid = ring.tid
        n = int(tickets.size)

        dispatches = 0
        padded = 0
        lo = 0
        while lo < n:
            left = n - lo
            if left >= self.sizes[-1]:
                take, size = self.sizes[-1], self.sizes[-1]
            else:
                size = next(s for s in self.sizes if s >= left)
                take = left
            pb = _pad_batch(batch, lo, take, size)
            vmask = np.zeros(size, bool)
            vmask[:take] = True
            t0 = time.perf_counter()
            if tid == 0:
                res = self._dp.step(pb, now, valid=vmask)
            else:
                res = self._dp.tenant_step(tid, pb, now, valid=vmask)
            dt = time.perf_counter() - t0
            tp = getattr(self._dp, "_telemetry", None)
            if tp is not None:
                regime = classify_regime(take, int(res.n_miss))
                tp.observe_scoped("batched", regime, dt)
                if tid:
                    tp.observe_scoped(f"batched:tenant:{tid}", regime, dt)
            for i in range(take):
                self._complete(int(tickets[lo + i]), res, i)
            dispatches += 1
            padded += size - take
            lo += take

        hist = self._wait_hist(tid)
        for w in waits:
            hist.observe(float(w))
        self.flushed_lanes_total += n
        self.padded_total += padded
        self.dispatches_total += dispatches
        self.flushes_total[reason] = self.flushes_total.get(reason, 0) + 1
        self._emit(
            "batch-flush",
            tenant=tid,
            lanes=n,
            padded=padded,
            dispatches=dispatches,
            reason=reason,
            age_ticks=int(age),
        )
        if age > self.flush_deadline:
            self.deadline_exceeded_total += 1
            self._emit(
                "batch-deadline-exceeded",
                tenant=tid,
                age_ticks=int(age),
                deadline=self.flush_deadline,
            )
        return dispatches

    # -- result plane --------------------------------------------------------

    def _complete(self, ticket: int, res, row: int) -> None:
        self._completed[ticket] = (res, row)
        while len(self._completed) > self.results_slots:
            oldest = next(iter(self._completed))
            del self._completed[oldest]
            self.results_dropped_total += 1

    def poll(self, ticket: int):
        """Pop one lane's completed verdict as a field dict, or None if
        still staged (or shed / aged out of the bounded result store)."""
        pair = self._completed.pop(int(ticket), None)
        if pair is None:
            return None
        res, row = pair
        out = {}
        for f in dataclasses.fields(res):
            v = getattr(res, f.name)
            if f.name == "n_miss":
                out[f.name] = int(v)
            elif v is None:
                out[f.name] = None
            elif isinstance(v, list):
                out[f.name] = v[row]
            else:
                out[f.name] = np.asarray(v)[row]
        return out

    def collect(self, tickets) -> "object":
        """De-interleave completed lanes back into one StepResult in
        submission order — lane-exact: verdict columns scatter back per
        ticket, list columns move element-wise, `n_miss` sums once per
        underlying dispatch."""
        tickets = np.asarray(tickets, np.int64)
        pairs = []
        for tk in tickets:
            pair = self._completed.pop(int(tk), None)
            if pair is None:
                raise KeyError(
                    f"ticket {int(tk)} has no completed result "
                    "(still staged, shed, or aged out of the result store)"
                )
            pairs.append(pair)
        B = len(pairs)
        groups: dict = {}  # id(res) -> (res, [lane], [row])
        for lane, (res, row) in enumerate(pairs):
            g = groups.get(id(res))
            if g is None:
                g = groups[id(res)] = (res, [], [])
            g[1].append(lane)
            g[2].append(row)
        res0 = pairs[0][0]
        kw = {}
        for f in dataclasses.fields(res0):
            v0 = getattr(res0, f.name)
            if f.name == "n_miss":
                kw[f.name] = int(sum(int(g[0].n_miss) for g in groups.values()))
            elif v0 is None:
                kw[f.name] = None
            elif isinstance(v0, list):
                out = [None] * B
                for res, lanes, rows in groups.values():
                    col = getattr(res, f.name)
                    if col is None:
                        continue
                    for lane, row in zip(lanes, rows):
                        out[lane] = col[row]
                kw[f.name] = out
            else:
                a0 = np.asarray(v0)
                out = np.zeros((B,) + a0.shape[1:], a0.dtype)
                for res, lanes, rows in groups.values():
                    col = getattr(res, f.name)
                    if col is None:
                        continue
                    out[np.asarray(lanes)] = np.asarray(col)[np.asarray(rows)]
                kw[f.name] = out
        return type(res0)(**kw)

    # -- observability -------------------------------------------------------

    def staged_lanes(self) -> int:
        return sum(r.depth for r in self._rings.values())

    def wait_p99_ticks(self, tenant: int = 0) -> float:
        """p99 staging wait in ticks for one world, from the bucketed
        histogram (upper-bound estimate) — the lever `flush_deadline`
        moves."""
        h = self._wait_hists.get(int(tenant))
        if h is None or h.count == 0:
            return 0.0
        target = 0.99 * h.count
        acc = 0
        for bound, c in zip(h.bounds, h._counts):
            acc += c
            if acc >= target:
                return float(bound)
        return float(h.bounds[-1])

    def stats(self) -> dict:
        per_world = {}
        for tid, ring in sorted(self._rings.items()):
            h = self._wait_hists.get(tid)
            per_world[tid] = {
                "staged_lanes": ring.depth,
                "starved": ring.starved,
                "flushed_lanes": 0 if h is None else h.count,
                "wait_p99_ticks": self.wait_p99_ticks(tid),
            }
        return {
            "canonical_sizes": list(self.sizes),
            "flush_depth": self.flush_depth,
            "flush_deadline": self.flush_deadline,
            "ring_slots": self.ring_slots,
            "submitted_lanes": self.submitted_total,
            "shed_lanes": self.shed_total,
            "flushed_lanes": self.flushed_lanes_total,
            "padded_lanes": self.padded_total,
            "dispatches": self.dispatches_total,
            "flushes": dict(self.flushes_total),
            "deadline_exceeded": self.deadline_exceeded_total,
            "results_dropped": self.results_dropped_total,
            "staged_lanes": self.staged_lanes(),
            "worlds": per_world,
        }

    def hist_rows(self, node: str) -> list:
        """(family, labels, Histogram) rows for the metrics renderer."""
        return [
            (
                "antrea_tpu_serving_wait_ticks",
                {"tenant": str(tid), "node": node},
                h,
            )
            for tid, h in sorted(self._wait_hists.items())
        ]


def _sub(batch, lo: int, n: int):
    kw = {}
    for f in dataclasses.fields(batch):
        v = getattr(batch, f.name)
        kw[f.name] = None if v is None else np.asarray(v)[lo : lo + n]
    return type(batch)(**kw)
